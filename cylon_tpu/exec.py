"""Out-of-core execution: key-partitioned streaming passes for inputs
larger than one chip's (or one mesh's) HBM.

The reference scales past one node by adding MPI ranks
(docs/docs/arch.md:146-162 — each rank holds a partition, the shuffle
moves rows); the TPU analog is to split the KEY DOMAIN into P disjoint
parts and stream one part at a time through the same compiled program:

- every pass reuses ONE static-shape XLA program (chunk capacities are
  maxed over passes, so nothing recompiles);
- because parts partition the key domain, a join pass only needs that
  part's rows from BOTH sides — every join type is exact per pass;
- a group-by whose keys pin down the partitioning key is FINAL per pass
  (host concatenation replaces any cross-pass combine); otherwise each
  pass emits PARTIAL aggregate states (the same SUM/COUNT/SUMSQ
  decomposition the distributed two-phase group-by shuffles,
  reference groupby/groupby.cpp:23-73) and one small device group-by
  combines them at the end;
- the host holds the full inputs (numpy); each pass uploads ~1/P of the
  data, so device residency is bounded by the pass size, not the input.

Two partitioners cover the key-type surface (both host-side, numpy):
``range`` splits on sample quantiles of an order-preserving uint64
prefix of the first key column (ints/floats exactly; strings by their
first eight codepoints, one clamped byte each — collisions only affect
balance, never correctness, because equal keys always share a prefix);
``hash`` mixes every key column's FULL content through a splitmix64
finalizer, which is skew-proof for distinct keys.  ``auto`` starts with
``range`` and flips to ``hash`` when the planned passes come out
pathologically unbalanced or fan out less than the distinct keys allow.

This is the 1B-row ladder of BASELINE.md: the single-chip rung runs the
fused kernel pipeline per pass; handing a distributed context shards
every pass over the mesh with the public distributed operators instead.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import column as colmod
from . import durable
from . import resilience
from . import config
from .obs import fleet as obs_fleet
from .obs import metrics as obs_metrics
from .obs import spans as obs_spans
from .config import JoinConfig, JoinType
from .ops import groupby as groupby_mod
from .ops import join as join_mod
from .ops.groupby import AggOp
from .status import Code, CylonError, Status
from .utils import pow2ceil


# ---------------------------------------------------------------------------
# host frames
# ---------------------------------------------------------------------------

def _as_host_frame(obj) -> Tuple[List[str], Dict[str, np.ndarray]]:
    """Normalize a pandas DataFrame / dict-of-arrays / Table to
    (ordered names, dict of host numpy columns)."""
    if isinstance(obj, dict):
        # stringify KEYS AND NAMES together — a names list of raw int
        # keys against a str-keyed dict would crash every lookup
        return ([str(k) for k in obj],
                {str(k): np.asarray(v) for k, v in obj.items()})
    if hasattr(obj, "columns") and hasattr(obj, "to_numpy") \
            and hasattr(obj, "names"):          # cylon_tpu Table
        return list(obj.names), obj.to_numpy()
    try:
        import pandas as pd
    except ImportError:
        # only a MISSING pandas disables DataFrame support; a broken
        # install must surface, not silently reject every DataFrame
        pd = None
    if pd is not None and isinstance(obj, pd.DataFrame):
        return ([str(c) for c in obj.columns],
                {str(c): obj[c].to_numpy() for c in obj.columns})
    raise CylonError(Code.Invalid,
                     f"expected DataFrame/dict/Table, got {type(obj)}")


#: public name (PR 19): the streaming layer's ``StreamTable.append``
#: accepts exactly the inputs the chunked engine does, through the same
#: normalizer — the two can never disagree on what a "frame" is
as_host_frame = _as_host_frame


_U63 = np.uint64(1) << np.uint64(63)


def _key_prefix_u64(a: np.ndarray) -> np.ndarray:
    """Order-preserving uint64 planning prefix: equal keys ALWAYS map to
    equal prefixes (the partition-correctness invariant); distinct keys
    may collide (strings beyond eight codepoints), which only affects
    pass balance.  Nulls/NaNs collapse to one prefix each, matching the
    device kernels' null-equality grouping."""
    a = np.asarray(a)
    if a.dtype.kind in ("U", "S", "O"):
        cp = _codepoints(a, 8)       # str() coercion: None -> "None", fine
        if cp is None:
            return np.zeros(0, np.uint64)
        # one byte per leading codepoint (clamped at 255: clamping can
        # only merge prefixes, never split equal keys)
        b = np.minimum(cp, 255).astype(np.uint64)
        out = np.zeros(len(a), np.uint64)
        for i in range(8):
            out = (out << np.uint64(8)) | b[:, i]
        return out
    if a.dtype.kind == "M":
        a = a.astype("datetime64[us]").astype(np.int64)
    if a.dtype.kind == "f":
        b = a.astype(np.float64)
        b = np.where(b == 0, 0.0, b)            # -0.0 groups with +0.0
        b = np.where(np.isnan(b), np.nan, b)    # one NaN payload
        bits = b.view(np.uint64)
        neg = (bits >> np.uint64(63)) == 1
        return np.where(neg, ~bits, bits | _U63)
    if a.dtype.kind == "b":
        return a.astype(np.uint64)
    if a.dtype.kind == "u":
        return a.astype(np.uint64)
    return a.astype(np.int64).view(np.uint64) ^ _U63  # signed bias


def _codepoints(a: np.ndarray, width: Optional[int] = None):
    """[n, width] uint32 codepoint matrix of a string-ish array (None for
    empty input)."""
    if len(a) == 0:
        return None
    u = a.astype("U" if width is None else f"U{width}")
    w = max(u.dtype.itemsize // 4, 1)
    return np.ascontiguousarray(u).view(np.uint32).reshape(len(a), w)


def _mix_u64(h: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (uint64 wraparound arithmetic)."""
    h = np.asarray(h, np.uint64)
    h = (h ^ (h >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return h ^ (h >> np.uint64(31))


def _row_hash_u64(a: np.ndarray) -> np.ndarray:
    """Full-content hash of one key column: unlike the planning prefix,
    DISTINCT string keys sharing a long prefix hash apart, so hash-mode
    passes fan out even when range-mode prefixes collapse.

    NUL codepoints are SKIPPED, not mixed: the codepoint matrix is padded
    to the array's max string length, so mixing the padding would make the
    same string hash differently on sides with different max lengths
    (equal keys would land in different passes and matches would silently
    drop).  Skipping keys the hash to the non-NUL codepoint sequence only
    — a deterministic function of the string value on every side."""
    a = np.asarray(a)
    if a.dtype.kind in ("U", "S", "O"):
        cp = _codepoints(a)
        if cp is None:
            return np.zeros(0, np.uint64)
        h = np.zeros(len(a), np.uint64)
        for i in range(cp.shape[1]):
            c = cp[:, i].astype(np.uint64)
            h = np.where(c == 0, h, _mix_u64(h ^ c))
        return h
    return _mix_u64(_key_prefix_u64(a))


def _hash_u64_cols(key_cols: Sequence[np.ndarray]) -> np.ndarray:
    """Combined full-content uint64 hash of a key-column tuple — the raw
    value behind hash-mode pass ids, also used by `_RefinablePlan` to
    subdivide passes (h % 2P refines h % P)."""
    h = _row_hash_u64(key_cols[0])
    for col in key_cols[1:]:
        h = _mix_u64(h ^ _row_hash_u64(col))
    return h


def _hash_pass_ids(key_cols: Sequence[np.ndarray], passes: int) -> np.ndarray:
    return (_hash_u64_cols(key_cols) % np.uint64(passes)).astype(np.int64)


_PLAN_SAMPLE = 1 << 20


def _plan_pass_ids(keys_l: Sequence[np.ndarray], keys_r: Sequence[np.ndarray],
                   passes: int, mode: str):
    """-> (pass_id_l, pass_id_r, n_passes, mode_used).

    range: sample-quantile edges over the FIRST key column's prefix, so
    passes inherit the reference's range-partition planning shape
    (arrow_partition_kernels.hpp:394-519 sample+histogram) on the host.
    hash: splitmix over all key columns' full content.  auto: range, then
    hash if the largest planned pass exceeds 3x its fair share OR the
    prefix edges fan out less than the (sampled) distinct keys allow —
    e.g. long-common-prefix strings, where range planning degenerates but
    full-content hashing still splits."""
    if mode not in ("range", "hash", "auto"):
        raise CylonError(Code.Invalid, f"bad chunk mode {mode!r}")
    n_l, n_r = len(keys_l[0]), len(keys_r[0])
    total = n_l + n_r
    passes = max(1, min(passes, max(total, 1)))
    if passes == 1 or total == 0:
        return (np.zeros(n_l, np.int32), np.zeros(n_r, np.int32), 1,
                "range" if mode == "auto" else mode)

    stride_l = max(1, (2 * n_l) // _PLAN_SAMPLE)
    stride_r = max(1, (2 * n_r) // _PLAN_SAMPLE)
    if mode in ("range", "auto"):
        pref_l0 = _key_prefix_u64(keys_l[0])
        pref_r0 = _key_prefix_u64(keys_r[0])
        # per-side strided samples (never a full-input concat: at 1B rows
        # that transient would cost gigabytes of host RAM)
        parts = [a[::st] for a, st in ((pref_l0, stride_l),
                                       (pref_r0, stride_r)) if len(a)]
        s = np.sort(np.concatenate(parts))
        pick = np.linspace(0, len(s) - 1, passes + 1)[1:-1].astype(np.int64)
        edges = np.unique(s[pick])
        edges = edges[edges > s[0]]  # an edge at the min would make an
        n_passes = len(edges) + 1    # unconditionally-empty first pass
        pid_l = np.searchsorted(edges, pref_l0, "right").astype(np.int32)
        pid_r = np.searchsorted(edges, pref_r0, "right").astype(np.int32)
        if mode == "range":
            return pid_l, pid_r, n_passes, "range"
        biggest = max(np.bincount(pid_l, minlength=n_passes).max(initial=0),
                      np.bincount(pid_r, minlength=n_passes).max(initial=0))
        fair = max(n_l, n_r) / n_passes
        # sampled distinct-key estimate bounds what any partitioner can do
        hs = [_hash_pass_ids([c[::st] for c in cols], 1 << 62)
              for cols, st in ((keys_l, stride_l), (keys_r, stride_r))
              if len(cols[0])]
        d_hash = len(np.unique(np.concatenate(hs))) if hs else 1
        if biggest <= 3 * fair + 64 and n_passes >= min(passes, d_hash):
            return pid_l, pid_r, n_passes, "range"
        passes = min(passes, max(d_hash, 1))
        if passes == 1:
            return pid_l, pid_r, n_passes, "range"
    return (_hash_pass_ids(keys_l, passes).astype(np.int32),
            _hash_pass_ids(keys_r, passes).astype(np.int32),
            passes, "hash")


# ---------------------------------------------------------------------------
# key/agg resolution helpers
# ---------------------------------------------------------------------------

def _resolve_keys(names, on, side_on, label):
    keys = side_on if side_on is not None else on
    if keys is None:
        raise CylonError(Code.Invalid, "join requires on= or left_on=/right_on=")
    if isinstance(keys, (str, int)):
        keys = [keys]
    out = []
    for k in keys:
        if isinstance(k, (int, np.integer)):
            if not 0 <= k < len(names):
                raise CylonError(Code.KeyError, f"no {label} column {k}")
            out.append(names[k])
        elif k in names:
            out.append(k)
        else:
            raise CylonError(Code.KeyError, f"no {label} column named {k!r}")
    return out


def _check_key_dtypes(arrs_l, lon, arrs_r, ron):
    from . import dtypes

    for ln, rn in zip(lon, ron):
        a, b = np.asarray(arrs_l[ln]), np.asarray(arrs_r[rn])
        kind = dtypes.join_key_mismatch(
            a.dtype.kind in "USO", b.dtype.kind in "USO",
            a.dtype == b.dtype, len(a) == 0 or len(b) == 0)
        if kind is not None:
            raise CylonError(
                Code.Invalid,
                f"join key type mismatch: {ln}:{a.dtype} vs {rn}:{b.dtype} "
                f"(cast the keys to a common type)")


def _joined_names(names_l, names_r, cfg: JoinConfig) -> List[str]:
    """left names ++ right names, prefixing collisions (reference:
    join_utils.cpp build_final_table naming; mirrors table._join_output_names)."""
    collisions = set(names_l) & set(names_r)
    out_l = [cfg.left_prefix + n if n in collisions else n for n in names_l]
    out_r = [cfg.right_prefix + n if n in collisions else n for n in names_r]
    return out_l + out_r


def _normalize_agg(agg, joined_names) -> List[Tuple[str, AggOp]]:
    """{col: op|[ops]} -> ordered [(joined column name, AggOp)]."""
    out = []
    for ref, ops in agg.items():
        if isinstance(ref, (int, np.integer)):
            ref = joined_names[ref]
        if ref not in joined_names:
            raise CylonError(Code.KeyError, f"no joined column named {ref!r}")
        if isinstance(ops, (str, AggOp)):
            ops = [ops]
        for op in ops:
            out.append((ref, AggOp.of(op)))
    return out


_PARTIAL_FILL = {AggOp.SUM: 0, AggOp.SUMSQ: 0, AggOp.COUNT: 0}


def _partials_for(aggs: List[Tuple[str, AggOp]]) -> List[Tuple[str, AggOp]]:
    """Distinct partial (column, op) pairs needed to reconstruct ``aggs``
    across passes; a COUNT partial is always carried per value column so
    the final combine can mask all-null groups."""
    seen: List[Tuple[str, AggOp]] = []
    for name, op in aggs:
        if op == AggOp.NUNIQUE:
            raise CylonError(
                Code.NotImplemented,
                "NUNIQUE across non-final chunk passes is unsupported: "
                "group by the partitioning key (or use passes=1)")
        for pop in groupby_mod.partial_ops(op):
            if (name, pop) not in seen:
                seen.append((name, pop))
        if (name, AggOp.COUNT) not in seen:
            seen.append((name, AggOp.COUNT))
    return seen


def _numeric_fill(arr: np.ndarray, pop: AggOp, src_dtype) -> np.ndarray:
    """Partial columns come back object-typed when a pass had all-null
    groups; refill with the combine identity so they re-upload numeric."""
    if arr.dtype != object:
        return arr
    mask = np.asarray([v is None for v in arr])
    if pop in (AggOp.MIN, AggOp.MAX):
        if np.issubdtype(src_dtype, np.floating):
            fill = np.inf if pop == AggOp.MIN else -np.inf
        elif np.issubdtype(src_dtype, np.integer):
            info = np.iinfo(src_dtype)
            fill = info.max if pop == AggOp.MIN else info.min
        else:
            raise CylonError(
                Code.NotImplemented,
                f"cross-pass {pop.name} combine over all-null groups of "
                f"dtype {src_dtype} — cast the value column to int/float "
                f"or group by the partitioning key")
        out = np.where(mask, fill, arr).astype(src_dtype)
    else:
        out = np.where(mask, _PARTIAL_FILL.get(pop, 0), arr)
        out = out.astype(np.float64 if pop in (AggOp.SUM, AggOp.SUMSQ)
                         else np.int64)
    return out


#: public name (PR 19): the streaming layer reloads persisted partial-
#: aggregate spills through the same identity-refill as the chunked
#: combine, so a stream state roundtrip and a cross-pass combine can
#: never disagree on what an all-null partial means
numeric_fill = _numeric_fill


# ---------------------------------------------------------------------------
# the chunked engine
# ---------------------------------------------------------------------------

def _passes_final(how: JoinType, mode: str, key_positions, nkeys: int) -> bool:
    """True when per-pass group-bys are final (no cross-pass combine):
    equal group tuples must imply equal pass ids.  ``key_positions`` maps
    key position -> set of copies ('l'/'r') present among group columns."""
    need = range(1) if mode == "range" else range(nkeys)
    for pos in need:
        copies = key_positions.get(pos, set())
        if how == JoinType.INNER:
            ok = bool(copies)          # both copies equal on inner rows
        elif how == JoinType.LEFT:
            ok = "l" in copies         # r-copy is null on unmatched rows
        elif how == JoinType.RIGHT:
            ok = "r" in copies
        else:                          # FULL: either copy may be null
            ok = copies == {"l", "r"}
        if not ok:
            return False
    return True


def _str_width(arr: np.ndarray) -> int:
    enc, _, _ = colmod._encode_strings(np.asarray(arr))
    return max(int(enc.dtype.itemsize), 1)


class _SideBuilder:
    """Builds one side's per-pass device columns with pass-invariant
    shapes (shared capacity, fixed string widths) so every pass hits the
    same compiled program."""

    def __init__(self, names, arrs, pass_ids, cap):
        self.names = names
        self.arrs = arrs
        self.pass_ids = pass_ids
        self.cap = cap
        self.widths = {n: _str_width(a) for n, a in arrs.items()
                       if np.asarray(a).dtype.kind in "USO"}
        # pre-group rows by pass id ONCE (stable order preserves each
        # pass's original row order): chunks become contiguous slices, so
        # total host scan work is O(n) per column instead of the mask
        # path's O(n * passes) — material for 16-pass 1B-row runs on one
        # host core.  Costs one sorted copy per column (the box has the
        # RAM; CYLON_TPU_CHUNK_PRESORT=0 reverts to masking).
        pid = np.asarray(pass_ids)
        self.presort = (config.knob("CYLON_TPU_CHUNK_PRESORT")
                        and int(pid.max(initial=0)) > 0)
        # single-pass plans skip the grouped copy: the identity argsort +
        # full-column gather would duplicate the whole table for nothing
        if self.presort:
            order = np.argsort(pid, kind="stable")
            counts = np.bincount(pid, minlength=int(pid.max(initial=0)) + 1)
            self._offsets = np.concatenate(
                [[0], np.cumsum(counts)]).astype(np.int64)
            self._grouped = {n: np.asarray(a)[order]
                             for n, a in arrs.items()}

    def chunk(self, p: int, only: Optional[Sequence[str]] = None):
        if self.presort:
            if p + 1 < len(self._offsets):
                lo, hi = int(self._offsets[p]), int(self._offsets[p + 1])
            else:
                lo = hi = 0  # pass beyond every planned id: empty chunk
            cols = [colmod.from_numpy(
                self._grouped[n][lo:hi], capacity=self.cap,
                string_width=self.widths.get(n, colmod.DEFAULT_STRING_WIDTH))
                for n in (only if only is not None else self.names)]
            return tuple(cols), jnp.asarray(hi - lo, jnp.int32)
        sel = self.pass_ids == p
        cols, n_sel = [], 0
        for n in (only if only is not None else self.names):
            a = np.asarray(self.arrs[n])[sel]
            n_sel = a.shape[0]
            cols.append(colmod.from_numpy(
                a, capacity=self.cap,
                string_width=self.widths.get(n, colmod.DEFAULT_STRING_WIDTH)))
        return tuple(cols), jnp.asarray(n_sel, jnp.int32)

    def empty_chunk(self, only: Optional[Sequence[str]] = None):
        """Zero-count chunk with the SAME shapes as every real chunk —
        compiles the pass program without re-paying a host compression
        pass over the largest chunk."""
        cols = []
        for n in (only if only is not None else self.names):
            a = np.asarray(self.arrs[n])[:0]
            cols.append(colmod.from_numpy(
                a, capacity=self.cap,
                string_width=self.widths.get(n, colmod.DEFAULT_STRING_WIDTH)))
        return tuple(cols), jnp.asarray(0, jnp.int32)


def _null_mask(a: np.ndarray):
    """Host null mask matching Column.from_numpy's validity inference
    (NaN floats, NaT datetimes, None/NaN objects), or None."""
    if a.dtype.kind == "f":
        return np.isnan(a)
    if a.dtype.kind in "Mm":
        return np.isnat(a)
    if a.dtype.kind == "O":
        try:
            import pandas as pd

            return np.asarray(pd.isna(a), bool)
        except ImportError:
            return np.asarray([x is None for x in a])
    return None


# Optional per-pass progress callback: (passes_done, n_passes,
# out_rows_so_far, run_seconds_so_far).  Set by measurement drivers (the
# TPU bench) so a tunnel drop or deadline mid-sweep still yields an
# honest partial throughput from the COMPLETED passes; None costs nothing.
PASS_PROGRESS_HOOK = None


def _notify_progress(done, n_passes, total, secs) -> None:
    """Invoke PASS_PROGRESS_HOOK non-fatally: a broken progress observer
    must never kill a 64-pass run — it is warned about once and disabled
    for the rest of the process."""
    global PASS_PROGRESS_HOOK
    hook = PASS_PROGRESS_HOOK
    if hook is None:
        return
    try:
        hook(done, n_passes, total, secs)
    except Exception as e:
        import warnings

        PASS_PROGRESS_HOOK = None
        warnings.warn(f"PASS_PROGRESS_HOOK raised {type(e).__name__}: {e}; "
                      f"progress reporting disabled", RuntimeWarning)


def _compose_guards(*guards):
    """One pass-boundary guard from several optional ones (elastic epoch
    checks, serve-layer cancellation/deadline) — None when all are None,
    the single guard unwrapped, else a caller running them in order."""
    gs = [g for g in guards if g is not None]
    if not gs:
        return None
    if len(gs) == 1:
        return gs[0]

    def guard():
        for g in gs:
            g()
    return guard


class _RefinablePlan:
    """Key-domain pass plan that can subdivide its REMAINING parts when a
    pass exceeds device memory.

    Level-``l`` pass ids are ``pid0 + P0 * (q % 2**l)`` over ``P0 * 2**l``
    parts, so part ``p`` at level ``l`` splits into ``{p, p + P0*2**l}``
    at level ``l+1`` — completed parts keep their frames, only unfinished
    key-domain parts re-run at the finer granularity.

    ``q`` (lazy — costs one host hash pass, paid only on the first OOM):
    hash plans use ``q = h // P0`` so the refined id equals ``h % (P0 *
    2**l)``, the splitmix64 partitioner's natural modulus refinement;
    range plans hash the first key column's order-preserving prefix, so
    the refined id stays a function of the FIRST key alone and
    `_passes_final`'s range-mode finality reasoning survives refinement.
    Either way equal keys share ``q`` on both sides, so refined parts
    still partition the key domain and every per-pass result stays exact.
    """

    def __init__(self, pid_l, pid_r, n_passes: int, mode_used: str,
                 keys_l, keys_r):
        self.pid0_l = np.asarray(pid_l)
        self.pid0_r = np.asarray(pid_r)
        self.p0 = int(n_passes)
        self.mode = mode_used
        self._keys_l = keys_l
        self._keys_r = keys_r
        self._q = None
        self._pid_cache = None  # (level, (pid_l, pid_r)) — one level only

    def _q_for(self, keys, pid0) -> np.ndarray:
        if not keys or len(keys[0]) == 0:
            return np.zeros(len(pid0), np.uint64)
        if self.mode == "hash":
            return _hash_u64_cols(keys) // np.uint64(self.p0)
        return _mix_u64(_key_prefix_u64(keys[0]))

    def part_count(self, level: int) -> int:
        return self.p0 << level

    def pids(self, level: int):
        """(pass_id_l, pass_id_r) int arrays at refinement ``level``.
        The last computed level is memoized: during one OOM recovery the
        redistribution checks and the rebuild all ask for the same level,
        and recomputing would materialize fresh full-table arrays at the
        exact moment the host is under memory pressure."""
        if level == 0:
            return self.pid0_l, self.pid0_r
        if self._pid_cache is not None and self._pid_cache[0] == level:
            return self._pid_cache[1]
        if self._q is None:
            self._q = (self._q_for(self._keys_l, self.pid0_l),
                       self._q_for(self._keys_r, self.pid0_r))
        mask = np.uint64((1 << level) - 1)
        ql, qr = self._q
        pid_l = (self.pid0_l.astype(np.int64)
                 + self.p0 * (ql & mask).astype(np.int64))
        pid_r = (self.pid0_r.astype(np.int64)
                 + self.p0 * (qr & mask).astype(np.int64))
        self._pid_cache = (level, (pid_l, pid_r))
        return pid_l, pid_r

    def split(self, parts: List[int], level: int) -> List[int]:
        """Subdivide each of ``parts`` (ids at ``level``) into its two
        children at ``level + 1``, keeping sibling adjacency."""
        c = self.part_count(level)
        return [s for p in parts for s in (p, p + c)]

    def max_part_rows(self, parts: List[int], level: int) -> Tuple[int, int]:
        """(max left rows, max right rows) over ``parts`` at ``level`` —
        the quantities that size a rebuild's chunk capacities."""
        if not parts:
            return 0, 0
        pid_l, pid_r = self.pids(level)
        c = self.part_count(level)
        sel = np.asarray(parts, np.int64)
        c_l = np.bincount(pid_l, minlength=c)[sel]
        c_r = np.bincount(pid_r, minlength=c)[sel]
        return int(c_l.max(initial=0)), int(c_r.max(initial=0))

    def parts_redistributing(self, parts: List[int], level: int):
        """Bool array aligned with ``parts``: True where splitting moves
        that part's rows between its two children on either side.  A
        False part is a key-domain atom (one hot key, or one shared
        8-byte prefix in range mode): its rows all land in one child of
        its old size, so no refinement depth can shrink it."""
        sel = np.asarray(parts, np.int64)
        out = np.zeros(len(sel), bool)
        if not parts:
            return out
        c0 = self.part_count(level)
        c1 = self.part_count(level + 1)
        for pid in self.pids(level + 1):
            if len(pid) == 0:
                continue
            cnt = np.bincount(pid, minlength=c1)
            out |= (cnt[sel] > 0) & (cnt[sel + c0] > 0)
        return out


def _stream_recoverable(make_exec, plan, t0, *, policy=None, stats=None,
                        prefetch=True, progress=True, journal=None,
                        parts=None, pass_guard=None):
    """The resilient streaming loop: checkpointed host frames + adaptive
    pass-splitting + bounded transient retry.

    ``make_exec(parts, level)`` builds one level's execution — builders
    and capacities sized over the REMAINING ``parts`` only, one compiled
    program — returning ``(chunk, prog, fetch)``.  Completed parts' host
    frames are kept across rebuilds, so recovery RESUMES the stream at
    the failed part instead of restarting it.

    With a ``journal`` (`durable.RunJournal`) the checkpoint outlives the
    process: every completed pass's frame spills to disk and is recorded
    in the run manifest, parts the journal already holds are LOADED
    instead of re-executed (``stats["passes_skipped"]``, metric
    ``durable.passes_skipped``) — a fresh process re-invoking the same
    fingerprinted run resumes mid-plan, surviving ``kill -9``.  A fully
    journaled run never even compiles.

    Failure handling, by classified code (`Status.from_exception`):
    - `Code.OutOfMemory` — every remaining part splits in two (``plan``)
      and the level's execution is rebuilt at roughly half the chunk
      capacity; bounded by ``CYLON_TPU_MAX_OOM_SPLITS``, after which a
      `CylonError(Code.OutOfMemory)` is raised.  ``plan=None`` (callers
      whose pass order is not refinable, e.g. the global sort) disables
      splitting and propagates the failure.
    - `Code.ExecutionError` / `Code.Timeout` (transient comm, or a pass
      deadline fired by ``durable.pass_deadline``) — the failing part
      retries in place under ``policy``'s exponential backoff.
    - anything else — propagates unchanged (a TypeError stays a bug).

    Elastic execution (PR 6): ``parts`` restricts the stream to a subset
    of the plan's level-0 part ids (this process's slice of an elastic
    gang; part ids stay GLOBAL so the shared journal is coherent across
    ranks and world sizes).  ``pass_guard`` is called before every pass;
    ANY exception it raises (elastic `EpochChanged`/`CoordinatorLost`,
    the serve layer's cancellation or request-budget Timeout) abandons
    the stream and propagates unchanged — guard raises never enter the
    retry/split/quarantine machinery, whatever their code.

    Poison-pass quarantine (``CYLON_TPU_QUARANTINE_AFTER`` = N > 0): a
    head part failing with the SAME classified code N consecutive times
    is dropped from the stream and reported in ``stats["quarantined"]``
    (and the journal) instead of wedging retries/refinement forever.
    Only recoverable codes qualify — an unknown code stays a bug.

    Returns ``(t_plan, t_run0, frames, total)`` like the old fixed loop.
    """
    policy = policy or resilience.RetryPolicy.from_env()
    stats = stats if stats is not None else {}
    max_splits = resilience.max_oom_splits() if plan is not None else 0
    n_parts0 = plan.part_count(0) if plan is not None else None
    prefetch = prefetch and config.knob("CYLON_TPU_PREFETCH")

    frames: List[Dict[str, np.ndarray]] = []
    total = 0
    if parts is not None and n_parts0 is not None:
        remaining = sorted(int(p) for p in parts if 0 <= int(p) < n_parts0)
    else:
        remaining = list(range(n_parts0)) if n_parts0 is not None else None
    level = 0
    part_retries = 0  # transient retries of the current head part
    atom_watch: set = set()  # child ids of a head atom already split once
    fail_key = None  # (code, level, head part): quarantine failure tracking
    fail_count = 0
    t_plan = None
    t_run0 = time.perf_counter()
    exec_cache: Dict[int, tuple] = {}
    if journal is not None:
        stats.setdefault("passes_skipped", 0)

    def consume_journaled(part: int, hit) -> None:
        """Append a journal-loaded pass frame in place of executing it.
        Serving a part IS completing it, so the head-part retry/failure
        state resets exactly as it would after an executed pass — the
        next part must start with its full budgets."""
        nonlocal total, part_retries, fail_key, fail_count
        frame, n = hit
        frames.append(frame)
        total += int(n)
        part_retries = 0
        fail_key, fail_count = None, 0
        stats["passes_skipped"] += 1
        obs_spans.instant("durable.pass_skipped", part=int(part),
                          level=level, rows=int(n))
        obs_metrics.counter_add("durable.passes_skipped")

    def quarantine_head(st: Status, msg: str) -> bool:
        """Isolate the head part into the run report (poison-pass
        quarantine); False when quarantine is off, nothing remains, or
        the code is not a recoverable kind (a TypeError stays a bug)."""
        nonlocal remaining, part_retries, fail_key, fail_count
        if durable.quarantine_after() <= 0 or not remaining:
            return False
        if not (st.code == Code.OutOfMemory
                or st.code in resilience.RETRYABLE_CODES):
            return False
        part = remaining[0]
        entry = {"part": int(part), "level": level, "code": st.code.name,
                 "failures": fail_count, "msg": msg}
        stats.setdefault("quarantined", []).append(entry)
        if journal is not None:
            journal.record_quarantine(level, part, st.code.name, msg)
        obs_spans.instant("exec.part_quarantined", part=int(part),
                          level=level, code=st.code.name)
        obs_metrics.counter_add("quarantine.parts")
        obs_fleet.flight_record("quarantine", part=int(part), level=level,
                                code=st.code.name, error=msg[:200])
        remaining = remaining[1:]
        part_retries = 0
        fail_key, fail_count = None, 0
        return True

    def fatal(code: Code, msg: str) -> CylonError:
        """A classified FATAL stream failure (OOM past the split budget,
        retries/deadline exhausted): dump the flight recorder before the
        raise so the post-mortem exists even when tracing was never
        armed."""
        obs_fleet.flight_record("pass_fatal", code=code.name, level=level,
                                part=int(remaining[0]) if remaining else None,
                                error=msg[:200])
        return CylonError(code, msg)

    def recover(e: Exception) -> None:
        """Adjust (remaining, level) for a recoverable failure or raise."""
        nonlocal remaining, level, part_retries, fail_key, fail_count
        st = Status.from_exception(e)
        if (journal is not None and remaining
                and (st.code == Code.OutOfMemory
                     or st.code in resilience.RETRYABLE_CODES)
                and journal.completed(level, remaining[0])):
            # the failing part's result is already durably journaled (a
            # deadline overrun classified AFTER its commit): the loop
            # re-enters and serves it from the journal — no retry budget,
            # no backoff, no quarantine, cannot be fatal.  Checked FIRST:
            # a part whose correct frame sits in the journal must never
            # be quarantined out of the output
            obs_spans.instant("exec.pass_served_from_journal",
                              part=int(remaining[0]), level=level,
                              code=st.code.name)
            return
        # the counter is keyed to the PART's identity, not just the code:
        # an OOM split advances the level (the head's first child keeps
        # its id one level up), so productive refinement starts a fresh
        # count instead of accumulating toward quarantine
        key = (st.code, level, remaining[0] if remaining else None)
        if key == fail_key:
            fail_count += 1
        else:
            fail_key, fail_count = key, 1
        # poison-pass quarantine fires EARLY once the head has failed the
        # same way N consecutive times, and LATE at any point a failure
        # would otherwise be fatal (retry/split budgets exhausted, atoms)
        # — so the knob works regardless of how it compares to the retry
        # budget, and a poisoned part never wedges or kills the stream
        qn = durable.quarantine_after()
        if qn > 0 and fail_count >= qn and quarantine_head(st, st.msg):
            return
        if st.code == Code.OutOfMemory and plan is not None:
            if level >= max_splits:
                msg = (f"pass still exceeds device memory after {level} "
                       f"pass-doublings (CYLON_TPU_MAX_OOM_SPLITS="
                       f"{max_splits}): {st.msg}")
                if quarantine_head(st, msg):
                    return
                raise fatal(Code.OutOfMemory, msg) from e
            # progress check: a split that moves no rows rebuilds an
            # identically-sized program that must OOM again — fail fast
            # instead of burning the whole split budget on no-ops
            moved = plan.parts_redistributing(remaining, level)
            if not moved.any():
                atom_l, atom_r = plan.max_part_rows(remaining, level)
                msg = (f"splitting cannot shrink the failing pass: the "
                       f"remaining parts (largest {atom_l}+{atom_r} rows) "
                       f"are key-domain atoms (single hot key or shared "
                       f"range prefix): {st.msg}")
                if quarantine_head(st, msg):
                    return
                raise fatal(Code.OutOfMemory, msg) from e
            # the FAILING head part may be an atom even when later parts
            # split: allow it ONE split (a smaller output capacity from
            # the other parts can heal an output-driven OOM), then stop.
            # The atom is tracked by id lineage — a part's first child
            # keeps its id, the second gets id + part_count — so an empty
            # sibling completing in between cannot hide the repeat OOM.
            if not moved[0]:
                head = remaining[0]
                if head in atom_watch:
                    atom_l, atom_r = plan.max_part_rows(remaining[:1],
                                                        level)
                    msg = (f"splitting cannot shrink the failing pass: "
                           f"its {atom_l}+{atom_r} rows are one "
                           f"key-domain atom (single hot key or shared "
                           f"range prefix): {st.msg}")
                    if quarantine_head(st, msg):
                        return
                    raise fatal(Code.OutOfMemory, msg) from e
                atom_watch.clear()
                atom_watch.update((head, head + plan.part_count(level)))
            else:
                atom_watch.clear()
            remaining = plan.split(remaining, level)
            level += 1
            part_retries = 0
            # levels are never revisited after a split: free the coarser
            # levels' builders (each holds presorted host copies of both
            # tables) instead of accumulating one copy per refinement
            # while recovering from memory pressure
            exec_cache.clear()
            stats["oom_splits"] = stats.get("oom_splits", 0) + 1
            obs_spans.instant("exec.oom_split", level=level,
                              remaining_parts=len(remaining))
            obs_metrics.counter_add("oom.refinements")
            return
        if st.code in resilience.RETRYABLE_CODES:
            if part_retries >= policy.max_retries:
                msg = (f"pass retries exhausted after {part_retries + 1} "
                       f"attempts: {st.msg}")
                if quarantine_head(st, msg):
                    return
                raise fatal(st.code, msg) from e
            d = policy.delay(part_retries)
            part_retries += 1
            stats["retries"] = stats.get("retries", 0) + 1
            obs_spans.instant("exec.pass_retry", attempt=part_retries,
                              code=st.code.name)
            obs_metrics.counter_add("retry.attempts")
            if d > 0:
                policy.sleep(d)
            return
        raise e

    while remaining is None or remaining:
        if journal is not None:
            if remaining is None and "passes" in stats:
                remaining = list(range(stats["passes"]))
            # consume the journaled prefix BEFORE building this level's
            # execution: execution is sequential, so a prior (crashed)
            # process's completions at this level always form a prefix —
            # and a fully journaled run must not compile at all
            while remaining:
                hit = journal.load_pass(level, remaining[0])
                if hit is None:
                    break
                consume_journaled(remaining[0], hit)
                remaining = remaining[1:]
            if not remaining:
                break
        try:
            ex = exec_cache.get(level)
            if ex is None:
                ex = make_exec(remaining, level)
                exec_cache[level] = ex
        except Exception as e:
            recover(e)
            continue
        chunk, prog, fetch = ex
        if remaining is None:  # plan-less callers stream positions 0..n-1
            remaining = list(range(stats["passes"]))
        if t_plan is None:
            t_plan = time.perf_counter() - t0
            t_run0 = time.perf_counter()
        cursor = 0
        cur = fut = nxt = None
        guard_exc = None
        try:
            nxt = chunk(remaining[0]) if prefetch else None
            while cursor < len(remaining):
                if pass_guard is not None:
                    # a guard raise (elastic EpochChanged/CoordinatorLost,
                    # serve cancellation or request-budget Timeout)
                    # ABANDONS the stream unconditionally — it never
                    # enters recover(), so a retryable-coded Timeout from
                    # a request budget cannot burn retries or quarantine
                    # healthy parts, and in-flight work is never retried
                    # into a changed world
                    try:
                        pass_guard()
                    except Exception as ge:
                        guard_exc = ge
                        raise
                part = remaining[cursor]
                if journal is not None:
                    hit = journal.load_pass(level, part)
                    if hit is not None:  # rejected-spill gaps re-ran; the
                        consume_journaled(part, hit)  # rest still skips
                        cursor += 1
                        nxt = None  # prefetched chunk was for this part
                        continue
                deadline = durable.pass_deadline()
                with obs_spans.span("exec.pass", part=part,
                                    level=level) as sp:
                    with deadline:
                        resilience.fault_point("pass_dispatch")
                        cur = nxt if nxt is not None else chunk(part)
                        fut = prog(*cur)               # async dispatch
                        nxt = (chunk(remaining[cursor + 1])
                               if prefetch and cursor + 1 < len(remaining)
                               else None)
                        resilience.fault_point("host_fetch")
                        frame, n = fetch(fut)  # blocks; device errors here
                    if obs_spans.events_enabled():
                        sp.set(rows=int(n), bytes=int(sum(
                            a.nbytes for a in frame.values())))
                        obs_metrics.record_hbm_watermark()
                    elif cursor == 0 and obs_spans.enabled():
                        # the watermark gauge is a metrics-side fact, so
                        # aggregate mode populates it too — but sampling
                        # scans every live jax array in the process, so
                        # the always-on default pays it once per level,
                        # not once per pass
                        obs_metrics.record_hbm_watermark()
                committed = False
                if journal is not None:
                    # spill + manifest-commit BEFORE the frame counts as
                    # done: a crash inside the journal write re-runs the
                    # pass on resume (at-least-once, never lost)
                    committed = journal.record_pass(level, part, frame,
                                                    int(n))
                if committed:
                    # a deadline overrun classifies AFTER the late frame
                    # is journaled: the Timeout retry serves the result
                    # from the journal instead of re-executing an
                    # identically-slow pass forever
                    deadline.raise_if_fired()
                else:
                    # no journal to serve a retry from: discarding the
                    # late-but-correct frame would condemn every
                    # consistently-slow pass to retry-until-fatal, so
                    # keep it and record the overrun
                    deadline.accept_late()
                total += n
                frames.append(frame)
                cursor += 1
                part_retries = 0
                fail_key, fail_count = None, 0
                stats["parts_run"] = stats.get("parts_run", 0) + 1
                obs_metrics.counter_add("exec.parts_run")
                cur = fut = None
                if progress:
                    _notify_progress(
                        len(frames), len(frames) + len(remaining) - cursor,
                        total, time.perf_counter() - t_run0)
            remaining = []
        except Exception as e:
            # drop the failed pass's device buffers BEFORE re-planning:
            # this frame stays alive through recover()/make_exec(), and a
            # rebuild warmed while the dead full-size buffers are still
            # resident would re-OOM and burn a split for nothing.  The
            # level's program/builder locals go too — their closures hold
            # full presorted host copies of both sides, and keeping them
            # referenced across make_exec would double host memory at the
            # exact moment we're recovering from pressure
            cur = fut = nxt = None
            chunk = prog = fetch = ex = None
            remaining = remaining[cursor:]  # completed frames are kept
            if guard_exc is e:
                raise
            recover(e)
    if t_plan is None:
        t_plan = time.perf_counter() - t0
    return t_plan, t_run0, frames, total


def _run_passes(prog, empty_chunk, chunk, n_passes, fetch, t0, *,
                policy=None, stats=None, journal=None, pass_guard=None):
    """Streaming loop over positional passes 0..n-1 with transient-retry
    resilience (no OOM splitting: callers on this entry — the global sort
    — emit passes in an order a hash subdivision would scramble).
    Compiles on a zero-count chunk (same shapes, no duplicate host pass
    over the largest chunk), then double-buffers — pass p dispatches
    async while pass p+1's host compression + upload overlap it
    (CYLON_TPU_PREFETCH=0 reverts to strictly serial)."""
    stats = stats if stats is not None else {}
    stats["passes"] = n_passes

    def make_exec(_parts, _level):
        warm = empty_chunk()
        jax.block_until_ready(prog(*warm))
        del warm
        return chunk, prog, fetch

    return _stream_recoverable(make_exec, None, t0, policy=policy,
                               stats=stats, journal=journal,
                               pass_guard=pass_guard)


def _concat_host(frames: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    if not frames:
        return {}
    out = {}
    for name in frames[0]:
        parts = [f[name] for f in frames]
        if any(p.dtype == object for p in parts):
            parts = [p.astype(object) for p in parts]
        out[name] = np.concatenate(parts)
    return out


def chunked_join(left, right, *, on=None, left_on=None, right_on=None,
                 how: str = "inner", passes: int = 4, algo: str = "sort",
                 mode: str = "auto", ctx=None, prefetch: bool = True,
                 left_prefix: str = "l_", right_prefix: str = "r_",
                 elastic=None, pass_guard=None):
    """Out-of-core join over host frames (pandas/dict/Table): the key
    domain is split into ``passes`` parts, each part joined on device by
    one shared compiled program, outputs concatenated on the host.  All
    four join types are exact because parts partition BOTH sides by key.

    ``pass_guard`` (serving layer): called before every pass; raising a
    non-retryable `CylonError` there (Cancelled, Timeout past a request
    budget) stops the stream at the next pass boundary — the in-flight
    pass finishes (and journals) first, so cancellation never loses
    completed work.

    Returns (dict of host columns keyed by joined names, stats)."""
    return _chunked_engine(left, right, on=on, left_on=left_on,
                           right_on=right_on, how=how, group_by=None,
                           agg=None, passes=passes, algo=algo, ddof=0,
                           mode=mode, ctx=ctx, prefetch=prefetch,
                           left_prefix=left_prefix,
                           right_prefix=right_prefix, elastic=elastic,
                           pass_guard=pass_guard)


def chunked_join_groupby_tables(left, right, *, on=None, left_on=None,
                                right_on=None, how: str = "inner",
                                group_by, agg: Dict, passes: int = 4,
                                algo: str = "sort", ddof: int = 0,
                                mode: str = "auto", ctx=None,
                                prefetch: bool = True, elastic=None,
                                pass_guard=None):
    """Out-of-core join + group-by over host frames.  ``group_by`` and
    ``agg`` use POST-JOIN column names (collisions prefixed l_/r_, as
    Table.join names them).  When the group keys pin down the
    partitioning key the per-pass group-bys are final; otherwise each
    pass emits partial aggregation states and one small device group-by
    combines them (the cross-pass analog of the distributed two-phase
    group-by, reference groupby/groupby.cpp:23-73).

    Returns (dict of host columns, stats)."""
    if agg is None or group_by is None:
        raise CylonError(Code.Invalid, "group_by and agg are required")
    return _chunked_engine(left, right, on=on, left_on=left_on,
                           right_on=right_on, how=how, group_by=group_by,
                           agg=agg, passes=passes, algo=algo, ddof=ddof,
                           mode=mode, ctx=ctx, prefetch=prefetch,
                           elastic=elastic, pass_guard=pass_guard)


def _chunked_engine(left, right, *, on, left_on, right_on, how, group_by,
                    agg, passes, algo, ddof, mode, ctx, prefetch,
                    left_prefix: str = "l_", right_prefix: str = "r_",
                    elastic=None, pass_guard=None):
    t_plan0 = time.perf_counter()
    names_l, arrs_l = _as_host_frame(left)
    names_r, arrs_r = _as_host_frame(right)
    lon = _resolve_keys(names_l, on, left_on, "left")
    ron = _resolve_keys(names_r, on, right_on, "right")
    if len(lon) != len(ron):
        raise CylonError(Code.Invalid, "left_on/right_on length mismatch")
    _check_key_dtypes(arrs_l, lon, arrs_r, ron)
    cfg = JoinConfig.of(how, algo, tuple(lon), tuple(ron),
                        left_prefix, right_prefix)
    jt = cfg.join_type
    joined = _joined_names(names_l, names_r, cfg)
    lidx = tuple(names_l.index(n) for n in lon)
    ridx = tuple(names_r.index(n) for n in ron)

    # -- plan passes over the key domain --------------------------------
    keys_l_arr = [np.asarray(arrs_l[n]) for n in lon]
    keys_r_arr = [np.asarray(arrs_r[n]) for n in ron]
    pid_l, pid_r, n_passes, mode_used = _plan_pass_ids(
        keys_l_arr, keys_r_arr, passes, mode)
    counts_l = np.bincount(pid_l, minlength=n_passes)
    counts_r = np.bincount(pid_r, minlength=n_passes)
    cap_l = pow2ceil(int(max(8, counts_l.max(initial=0))))
    cap_r = pow2ceil(int(max(8, counts_r.max(initial=0))))

    # -- group/agg resolution -------------------------------------------
    gb_names, aggs_req, final_per_pass, fuse_pipeline = None, None, True, False
    if group_by is not None:
        if isinstance(group_by, (str, int, np.integer)):
            group_by = [group_by]
        gb_names = []
        for g in group_by:
            if isinstance(g, (int, np.integer)):
                g = joined[g]
            if g not in joined:
                raise CylonError(Code.KeyError,
                                 f"no joined column named {g!r}")
            gb_names.append(g)
        aggs_req = _normalize_agg(agg, joined)
        # which join-key positions do the group columns pin down?
        key_positions: Dict[int, set] = {}
        n_l = len(names_l)
        for g in gb_names:
            gi = joined.index(g)
            if gi < n_l and gi in lidx:
                key_positions.setdefault(lidx.index(gi), set()).add("l")
            elif gi >= n_l and (gi - n_l) in ridx:
                key_positions.setdefault(ridx.index(gi - n_l), set()).add("r")
        final_per_pass = _passes_final(jt, mode_used, key_positions, len(lon))
        # key-grouped fusion: INNER join output is already adjacent on the
        # full key tuple, so group keys forming a PREFIX of the key tuple
        # need no second sort (pipeline group-by instead of hash group-by)
        every_gb_is_key = all(
            (joined.index(g) < n_l and joined.index(g) in lidx)
            or (joined.index(g) >= n_l and (joined.index(g) - n_l) in ridx)
            for g in gb_names)
        positions = sorted(key_positions)
        fuse_pipeline = (jt == JoinType.INNER and final_per_pass
                         and every_gb_is_key and len(positions) >= 1
                         and positions == list(range(len(positions))))

    world = 1 if ctx is None else ctx.GetWorldSize()
    if world > 1:
        if elastic is not None:
            raise CylonError(
                Code.Invalid,
                "elastic execution drives one local mesh per process "
                "(gang re-init on membership change); pass ctx=None — a "
                "live multi-device mesh cannot be reshaped under a run")
        return _chunked_distributed(
            arrs_l, names_l, arrs_r, names_r, lon, ron, cfg, joined,
            pid_l, pid_r, n_passes, counts_l, counts_r, gb_names, aggs_req,
            final_per_pass, agg, ddof, ctx, mode_used, t_plan0,
            pass_guard=pass_guard)

    # -- the one compiled per-pass program (per refinement level) --------
    nk = len(lon)
    kidx = tuple(range(nk))
    if gb_names is not None:
        gidx = tuple(joined.index(g) for g in gb_names)
        if final_per_pass:
            aggs_dev = tuple((joined.index(n), op) for n, op in aggs_req)
            out_names = list(gb_names) + [f"{op.name.lower()}_{n}"
                                          for n, op in aggs_req]
        else:
            partials = _partials_for(aggs_req)
            aggs_dev = tuple((joined.index(n), pop) for n, pop in partials)
            out_names = list(gb_names) + [f"{pop.name.lower()}_{n}"
                                          for n, pop in partials]

    def make_prog(out_cap: int):
        if gb_names is None:
            @jax.jit
            def prog(cl, cnt_l, cr, cnt_r):
                jcols, jm = join_mod.join_gather(cl, cnt_l, cr, cnt_r,
                                                 lidx, ridx, jt, out_cap,
                                                 algo)
                return jcols, jm

            def fetch(out):
                jcols, jm = out
                n = int(jm)
                return {name: colmod.to_numpy(c, n)
                        for name, c in zip(joined, jcols)}, n
        elif fuse_pipeline and final_per_pass:
            @jax.jit
            def prog(cl, cnt_l, cr, cnt_r):
                jcols, jm = join_mod.join_gather(
                    cl, cnt_l, cr, cnt_r, lidx, ridx, jt, out_cap, algo,
                    key_grouped=True)
                return groupby_mod.pipeline_groupby(jcols, jm, gidx,
                                                    aggs_dev, ddof)
        else:
            @jax.jit
            def prog(cl, cnt_l, cr, cnt_r):
                jcols, jm = join_mod.join_gather(
                    cl, cnt_l, cr, cnt_r, lidx, ridx, jt, out_cap, algo)
                return groupby_mod.hash_groupby(jcols, jm, gidx,
                                                aggs_dev, ddof)

        if gb_names is not None:
            def fetch(out):
                gcols, g = out
                n = int(g)
                return {name: colmod.to_numpy(c, n)
                        for name, c in zip(out_names, gcols)}, n
        return prog, fetch

    # -- resilient streaming: build one level's execution over the
    #    REMAINING parts only (capacities shrink as passes split), keep
    #    completed host frames, resume on recoverable failures ----------
    plan = _RefinablePlan(pid_l, pid_r, n_passes, mode_used,
                          keys_l_arr, keys_r_arr)
    policy = ctx.retry_policy() if ctx is not None \
        else resilience.RetryPolicy.from_env()
    stats = {"passes": n_passes, "mode": mode_used,
             "chunk_cap": max(cap_l, cap_r), "cap_l": cap_l, "cap_r": cap_r,
             "world": 1}
    journal = None
    if durable.enabled():
        # run identity: op shape x realized plan x sampled input content
        # x result-affecting knob config — a resumed process recomputes
        # the identical fingerprint and reopens the same journal
        op = "join" if gb_names is None else "join_groupby"
        fp = durable.run_fingerprint(
            op,
            (tuple(lon), tuple(ron), int(jt), int(cfg.algorithm),
             cfg.left_prefix, cfg.right_prefix,
             tuple(gb_names) if gb_names is not None else None,
             tuple((n, int(o)) for n, o in aggs_req)
             if aggs_req is not None else None,
             int(ddof), int(n_passes), mode_used, 1),
            ((names_l, arrs_l), (names_r, arrs_r)))
        # the fingerprint is world-INDEPENDENT by design: an elastic gang
        # at any membership (and a single-process re-invocation) shares
        # one journal; the slice's world/epoch ride the manifest as
        # per-pass provenance only
        journal = durable.open_run(
            fp, op,
            world=None if elastic is None else elastic.world,
            epoch=None if elastic is None else elastic.epoch)

    def make_exec(parts, level):
        pid_l_lvl, pid_r_lvl = plan.pids(level)
        max_l, max_r = plan.max_part_rows(parts, level)
        cap_l_lvl = pow2ceil(max(8, max_l))
        cap_r_lvl = pow2ceil(max(8, max_r))
        build_l = _SideBuilder(names_l, arrs_l, pid_l_lvl, cap_l_lvl)
        build_r = _SideBuilder(names_r, arrs_r, pid_r_lvl, cap_r_lvl)
        # exact output sizing over key columns only (the reference's
        # two-pass builder Reserve, join_utils.cpp), remaining parts only
        m_max = 0
        for p in parts:
            kc_l, cnt_l = build_l.chunk(p, only=lon)
            kc_r, cnt_r = build_r.chunk(p, only=ron)
            m = int(join_mod.join_row_count(kc_l, cnt_l, kc_r, cnt_r,
                                            kidx, kidx, jt, algo))
            m_max = max(m_max, m)
            del kc_l, kc_r
        out_cap = pow2ceil(max(8, m_max))
        stats.update(chunk_cap=max(cap_l_lvl, cap_r_lvl), cap_l=cap_l_lvl,
                     cap_r=cap_r_lvl, out_cap=out_cap)
        prog, fetch = make_prog(out_cap)

        def chunk(p):
            return build_l.chunk(p) + build_r.chunk(p)

        # compile + warm on the first remaining pass so run_seconds is
        # steady-state
        args0 = chunk(parts[0])
        jax.block_until_ready(prog(*args0))
        del args0
        return chunk, prog, fetch

    t_plan, t_run0, frames, total = _stream_recoverable(
        make_exec, plan, t_plan0, policy=policy, stats=stats,
        prefetch=prefetch, journal=journal,
        parts=None if elastic is None else elastic.parts,
        pass_guard=_compose_guards(
            None if elastic is None else elastic.guard, pass_guard))
    if journal is not None and not stats.get("quarantined"):
        # every pass the plan needed is journaled: the run is a complete
        # result-cache entry, and the cap GC may now reclaim older runs
        journal.record_done(len(frames), total)
        durable.gc_journal()
    result = _concat_host(frames)
    if gb_names is not None and not final_per_pass:
        result, total = _combine_partials(result, gb_names, aggs_req,
                                          arrs_l, arrs_r, names_l, names_r,
                                          joined, ddof, ctx)
    t_run = time.perf_counter() - t_run0
    stats["groups" if gb_names is not None else "rows"] = total
    stats["plan_seconds"] = t_plan
    stats["run_seconds"] = t_run
    # cold-run honesty (round-3 advice): the exact-sizing pass inside
    # plan_seconds re-reads the whole input, so a throughput from
    # run_seconds alone understates one-shot cost by ~one data pass
    stats["total_seconds"] = t_plan + t_run
    return result, stats


# ---------------------------------------------------------------------------
# cross-pass partial combine
# ---------------------------------------------------------------------------

def _combine_partials(partial_result, gb_names, aggs_req, arrs_l, arrs_r,
                      names_l, names_r, joined, ddof, ctx):
    """One small device group-by over the concatenated per-pass partial
    states, then host arithmetic derives the requested aggregates
    (MEAN/VAR/STDDEV from SUM/COUNT/SUMSQ — reference KernelTraits
    decomposition, compute/aggregate_kernels.hpp:38-200)."""
    from .context import default_context
    from .table import Table

    def src_dtype(joined_name):
        i = joined.index(joined_name)
        if i < len(names_l):
            return np.asarray(arrs_l[names_l[i]]).dtype
        return np.asarray(arrs_r[names_r[i - len(names_l)]]).dtype

    partials = _partials_for(aggs_req)
    filled = dict(partial_result)
    for name, pop in partials:
        col = f"{pop.name.lower()}_{name}"
        filled[col] = _numeric_fill(np.asarray(filled[col]), pop,
                                    src_dtype(name))
    t = Table.from_numpy(list(filled), list(filled.values()),
                         ctx=ctx or default_context())
    combine_agg = {f"{pop.name.lower()}_{name}":
                   [groupby_mod.combine_op(pop)] for name, pop in partials}
    out = t.groupby(gb_names, combine_agg).to_numpy()

    def comb(name, pop):
        c = groupby_mod.combine_op(pop)
        return np.asarray(
            out[f"{c.name.lower()}_{pop.name.lower()}_{name}"])

    result = {g: out[g] for g in gb_names}
    for name, op in aggs_req:
        n = comb(name, AggOp.COUNT).astype(np.float64)
        label = f"{op.name.lower()}_{name}"
        if op == AggOp.COUNT:
            result[label] = n.astype(np.int64)
            continue
        empty = n == 0
        with np.errstate(invalid="ignore", divide="ignore"):
            if op == AggOp.SUM:
                v = comb(name, AggOp.SUM)
                if np.issubdtype(src_dtype(name), np.integer):
                    v = np.where(empty, 0, v).astype(np.int64)
            elif op in (AggOp.MIN, AggOp.MAX):
                v = comb(name, op)
            elif op == AggOp.MEAN:
                v = comb(name, AggOp.SUM) / np.maximum(n, 1)
            elif op in (AggOp.VAR, AggOp.STDDEV):
                s, s2 = comb(name, AggOp.SUM), comb(name, AggOp.SUMSQ)
                nn = np.maximum(n, 1)
                v = np.maximum((s2 - s * s / nn) / np.maximum(nn - ddof, 1), 0)
                if op == AggOp.STDDEV:
                    v = np.sqrt(v)
                empty = empty | (n - ddof <= 0)
            else:
                raise CylonError(Code.NotImplemented, f"combine {op.name}")
        if empty.any():
            v = v.astype(object)
            v[empty] = None
        result[label] = v
    return result, len(next(iter(out.values())) if out else [])


# ---------------------------------------------------------------------------
# distributed per-pass execution (each pass sharded over the mesh)
# ---------------------------------------------------------------------------

def _chunked_distributed(arrs_l, names_l, arrs_r, names_r, lon, ron, cfg,
                         joined, pid_l, pid_r, n_passes, counts_l, counts_r,
                         gb_names, aggs_req, final_per_pass, agg, ddof, ctx,
                         mode_used, t_plan0, pass_guard=None):
    """Every key-domain pass sharded over ``ctx``'s mesh via the public
    distributed operators — total capacity is passes x mesh-HBM (the
    composition of the reference's rank scaling, docs/docs/arch.md:146-162,
    with range streaming)."""
    from .table import Table

    world = ctx.GetWorldSize()
    shard_cap = pow2ceil(int(max(
        8, -(-int(counts_l.max(initial=0)) // world),
        -(-int(counts_r.max(initial=0)) // world))))
    cap = shard_cap * world
    how = {JoinType.INNER: "inner", JoinType.LEFT: "left",
           JoinType.RIGHT: "right", JoinType.FULL_OUTER: "outer"}[cfg.join_type]

    if gb_names is not None:
        if final_per_pass:
            pass_agg = {}
            for name, op in aggs_req:
                pass_agg.setdefault(name, []).append(op)
        else:
            pass_agg = {}
            for name, pop in _partials_for(aggs_req):
                pass_agg.setdefault(name, []).append(pop)

    t_plan = time.perf_counter() - t_plan0
    t_run0 = time.perf_counter()
    frames = []
    total = 0
    # each pass is a fresh collective program over the mesh; retrying it
    # is only mesh-safe single-process (see collective_retry_policy)
    policy = ctx.collective_retry_policy()
    retries = 0

    def run_pass(p: int):
        resilience.fault_point("pass_dispatch")
        sel_l = pid_l == p
        sel_r = pid_r == p
        lt = Table.from_numpy(names_l, [np.asarray(arrs_l[n])[sel_l]
                                        for n in names_l], ctx=ctx,
                              capacity=cap)
        rt = Table.from_numpy(names_r, [np.asarray(arrs_r[n])[sel_r]
                                        for n in names_r], ctx=ctx,
                              capacity=cap)
        j = lt.distributed_join(rt, left_on=lon, right_on=ron, how=how,
                                algorithm=cfg.algorithm)
        if gb_names is None:
            return j.to_numpy(), j.row_count
        g = j.groupby(gb_names, pass_agg, ddof=ddof)
        return g.to_numpy(), g.row_count

    for p in range(n_passes):
        if pass_guard is not None:
            # serve-layer cancellation/deadline: stop at the next pass
            # boundary — completed frames were already fetched, nothing
            # in-flight is abandoned mid-collective
            pass_guard()
        # transient (comm/deadline) failures retry the PASS, not the whole
        # stream: completed frames are the checkpoint
        (frame, n), attempts = resilience.retry_call(
            lambda p=p: run_pass(p), policy=policy,
            site=f"distributed pass {p}/{n_passes}")
        retries += attempts - 1
        frames.append(frame)
        total += n
        _notify_progress(p + 1, n_passes, total,
                         time.perf_counter() - t_run0)
    result = _concat_host(frames)
    if gb_names is not None and not final_per_pass:
        result, total = _combine_partials(result, gb_names, aggs_req,
                                          arrs_l, arrs_r, names_l, names_r,
                                          joined, ddof, ctx)
    t_run = time.perf_counter() - t_run0
    from .parallel import plane as plane_mod

    # every mesh pass shuffles through parallel.ops; record which exchange
    # realization (packed plane vs per-buffer) the artifact was measured
    # under — the battery's A/B arms depend on this being in the ledger
    stats = {"passes": n_passes, "mode": mode_used, "world": world,
             "shard_cap": shard_cap, "retries": retries,
             "shuffle_pack": plane_mod.pack_enabled(),
             "groups" if gb_names is not None else "rows": total,
             "plan_seconds": t_plan, "run_seconds": t_run,
             "total_seconds": t_plan + t_run}
    return result, stats


# ---------------------------------------------------------------------------
# standalone out-of-core operators (no join): group-by and sort
# ---------------------------------------------------------------------------

def chunked_groupby(data, by, agg: Dict, *, passes: int = 4, ddof: int = 0,
                    mode: str = "auto", ctx=None, elastic=None,
                    pass_guard=None):
    """Out-of-core group-by over one host frame: the key domain is
    partitioned on the GROUP columns themselves, so every pass's
    group-by is final (a group never spans passes) and the results just
    concatenate — the single-frame analog of the distributed two-phase
    group-by's shuffle-on-keys (reference groupby/groupby.cpp:23-73).

    Returns (dict of host columns, stats)."""
    t0 = time.perf_counter()
    names, arrs = _as_host_frame(data)
    by_names = _resolve_keys(names, by, None, "group")
    aggs_req = _normalize_agg(agg, names)
    key_arrs = [np.asarray(arrs[n]) for n in by_names]
    empty = [np.zeros(0, a.dtype) for a in key_arrs]
    pid, _, n_passes, mode_used = _plan_pass_ids(key_arrs, empty, passes, mode)
    counts = np.bincount(pid, minlength=n_passes)
    cap = pow2ceil(int(max(8, counts.max(initial=0))))
    by_idx = tuple(names.index(n) for n in by_names)
    aggs_dev = tuple((names.index(n), op) for n, op in aggs_req)
    out_names = list(by_names) + [f"{op.name.lower()}_{n}"
                                  for n, op in aggs_req]

    world = 1 if ctx is None else ctx.GetWorldSize()
    if world > 1 and elastic is not None:
        raise CylonError(Code.Invalid,
                         "elastic execution drives one local mesh per "
                         "process; pass ctx=None")
    frames: List[Dict[str, np.ndarray]] = []
    total = 0
    if world > 1:
        from .table import Table

        shard_cap = pow2ceil(int(max(8, -(-int(counts.max(initial=0))
                                         // world))))
        pass_agg: Dict[str, list] = {}
        for n, op in aggs_req:
            pass_agg.setdefault(n, []).append(op)
        t_plan = time.perf_counter() - t0
        t_run0 = time.perf_counter()
        for p in range(n_passes):
            if pass_guard is not None:
                pass_guard()
            sel = pid == p
            t = Table.from_numpy(names, [np.asarray(arrs[n])[sel]
                                         for n in names], ctx=ctx,
                                 capacity=shard_cap * world)
            g = t.groupby(by_names, pass_agg, ddof=ddof)
            frames.append(g.to_numpy())
            total += g.row_count
    else:
        def fetch(out):
            gcols, g = out
            n = int(g)
            return {name: colmod.to_numpy(c, n)
                    for name, c in zip(out_names, gcols)}, n

        # the partition keys ARE the group keys, so hash-refining a part
        # never splits a group across passes: full OOM recovery applies
        plan = _RefinablePlan(pid, np.zeros(0, np.int32), n_passes,
                              mode_used, key_arrs, [])
        extra: Dict = {}
        journal = None
        if durable.enabled():
            fp = durable.run_fingerprint(
                "groupby",
                (tuple(by_names),
                 tuple((n, int(o)) for n, o in aggs_req),
                 int(ddof), int(n_passes), mode_used, 1),
                ((names, arrs),))
            journal = durable.open_run(
                fp, "groupby",
                world=None if elastic is None else elastic.world,
                epoch=None if elastic is None else elastic.epoch)

        def make_exec(parts, level):
            pid_lvl, _ = plan.pids(level)
            max_rows, _ = plan.max_part_rows(parts, level)
            cap_lvl = pow2ceil(max(8, max_rows))
            build = _SideBuilder(names, arrs, pid_lvl, cap_lvl)

            @jax.jit
            def prog(cols, cnt):
                return groupby_mod.hash_groupby(cols, cnt, by_idx, aggs_dev,
                                                ddof)

            warm = build.empty_chunk()
            jax.block_until_ready(prog(*warm))
            del warm
            return build.chunk, prog, fetch

        t_plan, t_run0, frames, total = _stream_recoverable(
            make_exec, plan, t0, stats=extra, journal=journal,
            parts=None if elastic is None else elastic.parts,
            pass_guard=_compose_guards(
                None if elastic is None else elastic.guard, pass_guard))
        if journal is not None and not extra.get("quarantined"):
            journal.record_done(len(frames), total)
            durable.gc_journal()
    result = _concat_host(frames)
    t_run = time.perf_counter() - t_run0
    stats = {"passes": n_passes, "mode": mode_used, "world": world,
             "groups": total, "plan_seconds": t_plan,
             "run_seconds": t_run, "total_seconds": t_plan + t_run}
    if world == 1:
        stats.update(extra)
    return result, stats


def chunked_repartition(data, keys, world: int, *, passes: int = 4,
                        out_dir: "str | None" = None, ctx=None):
    """Out-of-core hash repartition of one host frame into ``world`` hash
    shards, streamed through the device in ``passes`` passes — BASELINE
    config 3 ("1B-row hash shuffle / repartition") at beyond-HBM scale on
    one chip.  Each pass rides the SAME kernels as the distributed
    shuffle's local half (reference partition.cpp:24-87 + Split,
    arrow_kernels.hpp:60-96): Pallas murmur3 targets + the stable
    per-target split — so concatenating a target's per-pass slices yields
    exactly the shard the mesh shuffle would deliver to that rank (the
    device hasher is bit-identical to the native host hasher).

    Passes stripe the input by contiguous row blocks (target assignment
    is per-row, so any disjoint pass split is valid — striping keeps the
    host side at slice cost, no selection pass).

    With ``out_dir``, each (target, pass) slice lands in
    ``{out_dir}/shard_{t}/part_{p:04d}.parquet`` and only counts are kept
    in memory; otherwise per-target host columns are returned.

    With a distributed ``ctx`` each pass instead runs the REAL mesh
    shuffle; ``world`` must equal the context's world size (the mesh
    defines the shard count).  On a true multi-HOST mesh the return mode
    covers only this process's shards — use ``out_dir`` (each process
    writes its own shard files, gather-free) for the global result.

    Returns (list of ``world`` per-target host-column dicts | None when
    ``out_dir`` is given, stats)."""
    t0 = time.perf_counter()
    names, arrs = _as_host_frame(data)
    key_names = _resolve_keys(names, keys, None, "partition")
    key_idx = tuple(names.index(n) for n in key_names)
    if world < 1:
        raise CylonError(Code.Invalid, f"world must be >= 1, got {world}")
    n_rows = int(np.asarray(arrs[names[0]]).shape[0]) if names else 0
    n_passes = max(1, min(passes, max(1, n_rows)))
    block = -(-n_rows // n_passes)
    cap = pow2ceil(max(8, block))

    wctx = 1 if ctx is None else ctx.GetWorldSize()
    if out_dir is not None:
        os.makedirs(out_dir, exist_ok=True)
        # a reused out_dir must not mix this run's parts with a prior
        # run's (e.g. an earlier run with more passes): clear OUR layout
        # only — part files under shard_* dirs — never foreign files
        import glob as _glob

        for stale in _glob.glob(os.path.join(out_dir, "shard_*",
                                             "part_*.parquet")):
            os.remove(stale)

    widths = {n: _str_width(a) for n, a in arrs.items()
              if np.asarray(a).dtype.kind in "USO"}

    def slice_chunk(p: int):
        lo, hi = p * block, min((p + 1) * block, n_rows)
        cols = tuple(colmod.from_numpy(
            np.asarray(arrs[n])[lo:hi], capacity=cap,
            string_width=widths.get(n, colmod.DEFAULT_STRING_WIDTH))
            for n in names)
        return cols, jnp.asarray(hi - lo, jnp.int32)

    def empty_chunk():
        cols = tuple(colmod.from_numpy(
            np.asarray(arrs[n])[:0], capacity=cap,
            string_width=widths.get(n, colmod.DEFAULT_STRING_WIDTH))
            for n in names)
        return cols, jnp.asarray(0, jnp.int32)

    acc: "List[List[Dict[str, np.ndarray]]]" = [[] for _ in range(world)]
    per_target = np.zeros(world, np.int64)

    if wctx > 1:
        from .table import Table

        if world != wctx:
            raise CylonError(Code.Invalid,
                             f"world {world} != distributed context world "
                             f"{wctx}: with ctx the mesh defines the shard "
                             f"count")
        if out_dir is not None:
            for t in range(world):
                os.makedirs(os.path.join(out_dir, f"shard_{t}"),
                            exist_ok=True)
        t_plan = time.perf_counter() - t0
        t_run0 = time.perf_counter()
        total = 0
        for p in range(n_passes):
            lo, hi = p * block, min((p + 1) * block, n_rows)
            t = Table.from_numpy(names, [np.asarray(arrs[n])[lo:hi]
                                         for n in names], ctx=ctx,
                                 capacity=cap)
            s = t.shuffle(key_names)
            total += s.row_count
            if out_dir is not None:
                # same shard_{t}/part_{p}.parquet layout as single-chip
                s.to_parquet(os.path.join(out_dir, "shard_{shard}",
                                          f"part_{p:04d}.parquet"),
                             per_shard=True)
                from .table import _host_row_counts

                per_target[:] += np.asarray(_host_row_counts(s),
                                            np.int64)[:world]
            else:
                for sid, scols, cnt in s._addressable_host_shards():
                    frame = {name: colmod.to_numpy(c, cnt)
                             for name, c in zip(names, scols)}
                    per_target[sid] += cnt
                    acc[sid].append(frame)
        result = (None if out_dir is not None
                  else [_concat_host(fs) for fs in acc])
        t_run = time.perf_counter() - t_run0
        from .parallel import plane as plane_mod

        stats = {"passes": n_passes, "world": wctx, "rows": total,
                 "per_target": per_target.tolist(),
                 "shuffle_pack": plane_mod.pack_enabled(),
                 "plan_seconds": t_plan, "run_seconds": t_run,
                 "total_seconds": t_plan + t_run}
        return result, stats

    from .parallel import partition as partition_mod
    from .parallel import shuffle as shuffle_mod

    @jax.jit
    def prog(cols, cnt):
        t = partition_mod.hash_targets(cols, cnt, key_idx, world)
        perm_t = shuffle_mod._perm_by_target(t, world)
        counts = shuffle_mod.target_counts(t, world)
        grouped = tuple(c.take(perm_t) for c in cols)
        return grouped, counts

    def fetch_and_store(out, p: int) -> int:
        grouped, counts = out
        cnts = np.asarray(jax.device_get(counts))
        n = int(cnts.sum())
        frame = {name: colmod.to_numpy(c, n)
                 for name, c in zip(names, grouped)}
        offs = np.concatenate([[0], np.cumsum(cnts)]).astype(np.int64)
        for t in range(world):
            sl = {name: a[offs[t]:offs[t + 1]] for name, a in frame.items()}
            per_target[t] += offs[t + 1] - offs[t]
            if out_dir is not None:
                import pandas as pd

                d = os.path.join(out_dir, f"shard_{t}")
                os.makedirs(d, exist_ok=True)
                pd.DataFrame(sl).to_parquet(
                    os.path.join(d, f"part_{p:04d}.parquet"))
            else:
                acc[t].append(sl)
        return n

    warm = empty_chunk()
    jax.block_until_ready(prog(*warm))
    del warm
    t_plan = time.perf_counter() - t0
    prefetch = config.knob("CYLON_TPU_PREFETCH")
    t_run0 = time.perf_counter()
    total = 0
    nxt = slice_chunk(0) if prefetch else None
    for p in range(n_passes):
        cur = nxt if prefetch else slice_chunk(p)
        fut = prog(*cur)
        nxt = slice_chunk(p + 1) if prefetch and p + 1 < n_passes else None
        total += fetch_and_store(fut, p)
        del cur, fut
    del nxt
    t_run = time.perf_counter() - t_run0
    result = (None if out_dir is not None
              else [_concat_host(fs) for fs in acc])
    stats = {"passes": n_passes, "world": world, "rows": total,
             "per_target": per_target.tolist(),
             "plan_seconds": t_plan, "run_seconds": t_run,
             "total_seconds": t_plan + t_run}
    return result, stats


def chunked_unique(data, columns=None, *, passes: int = 4,
                   mode: str = "auto", ctx=None):
    """Out-of-core distinct rows over the given columns (default: all):
    a group-by with no aggregates — the key-domain partition makes every
    pass's distinct set globally disjoint (streamed analog of
    DistributedUnique's shuffle-then-local-unique, table.cpp:1031-1047).

    Returns (dict of host columns, stats with "rows")."""
    if columns is None:
        # names only — never materialize columns here; chunked_groupby
        # does the one full host conversion itself
        if isinstance(data, dict):
            columns = [str(k) for k in data]    # mirror _as_host_frame
        elif hasattr(data, "names"):            # cylon_tpu Table
            columns = list(data.names)
        else:                                   # pandas DataFrame
            columns = [str(c) for c in data.columns]
    result, stats = chunked_groupby(data, columns, {}, passes=passes,
                                    mode=mode, ctx=ctx)
    stats["rows"] = stats.pop("groups")
    return result, stats


def chunked_sort(data, by, *, ascending=True, nulls_first: bool = True,
                 passes: int = 4, ctx=None, pass_guard=None):
    """Out-of-core GLOBAL sort of one host frame: range-partition on the
    first sort column's order-preserving prefix (equal keys co-locate,
    ranges are contiguous in key order), sort each pass on device, and
    emit passes in key order — the streamed analog of DistributedSort's
    sample + range shuffle + local sort (reference table.cpp:313-356).
    Null first-key rows are routed to whichever pass is emitted first
    (``nulls_first``) or last, since the planning prefix cannot express
    the device kernels' null ordering.

    Returns (dict of host columns in global sort order, stats)."""
    t0 = time.perf_counter()
    names, arrs = _as_host_frame(data)
    by_names = _resolve_keys(names, by, None, "sort")
    if isinstance(ascending, bool):
        ascending = [ascending] * len(by_names)
    if len(ascending) != len(by_names):
        raise CylonError(Code.Invalid,
                         f"ascending length {len(ascending)} != "
                         f"{len(by_names)} sort columns")
    key0 = np.asarray(arrs[by_names[0]])
    empty = np.zeros(0, key0.dtype)
    pid, _, n_passes, _ = _plan_pass_ids([key0], [empty], passes, "range")
    emit_order = (list(range(n_passes)) if ascending[0]
                  else list(range(n_passes - 1, -1, -1)))
    nulls = _null_mask(key0)
    if nulls is not None and nulls.any():
        target = emit_order[0] if nulls_first else emit_order[-1]
        pid = np.where(nulls, target, pid)
    counts = np.bincount(pid, minlength=n_passes)
    cap = pow2ceil(int(max(8, counts.max(initial=0))))
    by_idx = tuple(names.index(n) for n in by_names)
    asc = tuple(bool(a) for a in ascending)

    world = 1 if ctx is None else ctx.GetWorldSize()
    frames: List[Dict[str, np.ndarray]] = []
    total = 0
    if world > 1:
        from .config import SortOptions
        from .table import Table

        t_plan = time.perf_counter() - t0
        t_run0 = time.perf_counter()
        for p in emit_order:
            if pass_guard is not None:
                pass_guard()
            sel = pid == p
            t = Table.from_numpy(names, [np.asarray(arrs[n])[sel]
                                         for n in names], ctx=ctx,
                                 capacity=cap)
            s = t.distributed_sort(
                by_names, options=SortOptions(nulls_first=nulls_first),
                ascending=list(asc))
            frames.append(s.to_numpy())
            total += s.row_count
    else:
        from .ops import sort as sort_mod

        build = _SideBuilder(names, arrs, pid, cap)

        @jax.jit
        def prog(cols, cnt):
            return sort_mod.sort_rows(cols, cnt, by_idx, asc, nulls_first)

        def fetch(out):
            scols, cnt = out
            n = int(cnt)
            return {name: colmod.to_numpy(c, n)
                    for name, c in zip(names, scols)}, n

        journal = None
        if durable.enabled():
            # positional passes (no refinement), keyed by emit position
            fp = durable.run_fingerprint(
                "sort",
                (tuple(by_names), tuple(asc), bool(nulls_first),
                 int(n_passes), 1),
                ((names, arrs),))
            journal = durable.open_run(fp, "sort")
        extra = {}
        t_plan, t_run0, frames, total = _run_passes(
            prog, build.empty_chunk, lambda p: build.chunk(emit_order[p]),
            n_passes, fetch, t0, stats=extra, journal=journal,
            pass_guard=pass_guard)
        if journal is not None and not extra.get("quarantined"):
            journal.record_done(len(frames), total)
            durable.gc_journal()
    result = _concat_host(frames)
    t_run = time.perf_counter() - t_run0
    stats = {"passes": n_passes, "mode": "range", "world": world,
             "rows": total, "plan_seconds": t_plan, "run_seconds": t_run,
             "total_seconds": t_plan + t_run}
    if world == 1:
        for k in ("passes_skipped", "quarantined", "retries", "parts_run"):
            if k in extra:
                stats[k] = extra[k]
    return result, stats


# ---------------------------------------------------------------------------
# legacy wrappers (the round-3 fixed-schema entry points, now thin)
# ---------------------------------------------------------------------------

def key_range_bounds(lo: int, hi: int, passes: int) -> List[Tuple[int, int]]:
    """Split [lo, hi) into ``passes`` near-equal [start, stop) intervals."""
    if passes < 1:
        raise ValueError(f"passes must be >= 1, got {passes}")
    span = hi - lo
    edges = [lo + (span * p) // passes for p in range(passes)] + [hi]
    return [(edges[p], edges[p + 1]) for p in range(passes)]


def chunked_join_groupby(lk: np.ndarray, lv: np.ndarray,
                         rk: np.ndarray, rv: np.ndarray,
                         passes: int, algo: str = "sort",
                         aggs: Tuple[Tuple[int, AggOp], ...] = (
                             (1, AggOp.SUM), (3, AggOp.MEAN))):
    """INNER join on int keys + group-by over key, in ``passes`` key-domain
    passes — the bench driver's fixed (k,v)x(k,v) shape, now a wrapper
    over the general engine.  Returns ({"key", "agg0", ...}, stats)."""
    joined = ["l_k", "a", "r_k", "b"]
    agg: Dict[str, list] = {}
    labels = []
    for idx, op in aggs:
        name = joined[idx]
        agg.setdefault(name, []).append(op)
        labels.append(f"{op.name.lower()}_{name}")
    result, stats = chunked_join_groupby_tables(
        {"k": lk, "a": lv}, {"k": rk, "b": rv}, on="k", how="inner",
        group_by="l_k", agg=agg, passes=passes, algo=algo, mode="auto")
    out = {"key": result["l_k"]}
    for i, label in enumerate(labels):
        out[f"agg{i}"] = result[label]
    return out, stats


def chunked_distributed_join_groupby(lk: np.ndarray, lv: np.ndarray,
                                     rk: np.ndarray, rv: np.ndarray,
                                     passes: int, ctx,
                                     agg: Optional[Dict] = None):
    """Multi-chip rung of the out-of-core ladder over the bench schema —
    now a wrapper over the general engine's distributed path.

    Returns (pandas-convertible dict of host arrays, stats)."""
    if agg is None:
        agg = {"a": ["sum"], "b": ["mean"]}
    return chunked_join_groupby_tables(
        {"k": lk, "a": lv}, {"k": rk, "b": rv}, on="k", how="inner",
        group_by="l_k", agg=agg, passes=passes, ctx=ctx, mode="auto")
