/* cylon_tpu C ABI — the foreign-language binding surface.
 *
 * This is the contract the reference exposes to Java over JNI
 * (java/src/main/native/src/Table.cpp calling table_api.hpp:38-195 and
 * arrow/arrow_builder.hpp:23-35): a string-id table registry plus a
 * raw-buffer column builder.  Any language with a C FFI (C, Java via
 * Panama/JNI, Go cgo, C#, ...) can host cylon_tpu tables through these
 * fifteen functions; the Python package itself consumes them via ctypes
 * (cylon_tpu/native/__init__.py), so this header IS the tested surface,
 * not a parallel one.
 *
 * Conventions: unless noted otherwise, int32_t returns are 0 on success
 * and negative on error (-1 unknown id / out-of-range, -2 row-count
 * mismatch).  Exceptions: ct_registry_contains returns 1 present /
 * 0 absent; ct_table_col_name and ct_registry_ids return the FULL
 * length of the requested string (like snprintf) — the caller's buffer
 * must hold length+1 bytes or the copy is NUL-truncated to cap-1.
 * Pointer returns are borrowed views owned by the registry — valid
 * until the table is removed or the registry cleared; never free()
 * them.  All functions are thread-safe (one internal mutex).
 *
 * dtype codes match cylon_tpu.dtypes.Type (dtypes.py): the builder
 * stores them opaquely, so a foreign host only needs agreement with the
 * reader on the other side.  width is bytes per row (strings: the padded
 * matrix row width); lengths[] carries per-row byte lengths for strings.
 */
#ifndef CYLON_TPU_C_H_
#define CYLON_TPU_C_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- builder: stage columns, then publish atomically ---- */
int32_t ct_builder_begin(const char* id);
int32_t ct_builder_add_column(const char* id, const char* name, int32_t dtype,
                              int32_t width, int64_t rows, const void* data,
                              const uint8_t* validity, const int32_t* lengths);
int32_t ct_builder_finish(const char* id);

/* ---- registry: string-id -> table, mirrors table_api.hpp ---- */
int32_t ct_registry_contains(const char* id);
int32_t ct_registry_remove(const char* id);
int64_t ct_registry_size(void);
void ct_registry_clear(void);
/* ids joined by '\n' into caller buffer (NUL-terminated, truncated to
 * cap-1 bytes); returns the full joined length — size the buffer as
 * ct_registry_ids(NULL, 0) + 1. */
int64_t ct_registry_ids(char* out, int64_t cap);

/* ---- readers: zero-copy borrowed views ---- */
int64_t ct_table_rows(const char* id);
int32_t ct_table_ncols(const char* id);
int32_t ct_table_col_name(const char* id, int32_t i, char* out, int32_t cap);
int32_t ct_table_col_info(const char* id, int32_t i, int32_t* dtype,
                          int32_t* width, int64_t* rows, int32_t* has_validity,
                          int32_t* has_lengths);
const void* ct_table_col_data(const char* id, int32_t i);
const uint8_t* ct_table_col_validity(const char* id, int32_t i);
const int32_t* ct_table_col_lengths(const char* id, int32_t i);

#ifdef __cplusplus
}
#endif

#endif /* CYLON_TPU_C_H_ */
