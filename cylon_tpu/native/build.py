"""Build libcylon_tpu.so from the C++ sources in ``src/``.

The native layer is compiled on first import (and cached next to the
sources), the same role as the reference's CMake build of libcylon
(cpp/CMakeLists.txt) — here a single g++ invocation because the library has
no external dependencies.
"""
from __future__ import annotations

import os
import subprocess
import sysconfig
from pathlib import Path

_SRC_DIR = Path(__file__).parent / "src"
_LIB_NAME = "libcylon_tpu.so"


def lib_path() -> Path:
    return Path(__file__).parent / _LIB_NAME


def _sources():
    return sorted(_SRC_DIR.glob("*.cpp"))


def needs_build(lib: Path) -> bool:
    if not lib.exists():
        return True
    mtime = lib.stat().st_mtime
    deps = list(_sources()) + list(_SRC_DIR.glob("*.hpp"))
    return any(s.stat().st_mtime > mtime for s in deps)


def build(verbose: bool = False) -> Path:
    lib = lib_path()
    if not needs_build(lib):
        return lib
    # NOT config.knob(): setup.py's wheel hook loads this file directly
    # (spec_from_file_location, no package context — pip's isolated build
    # env has no jax), so the registry is unreachable here by design
    cxx = os.environ.get("CXX", "g++")  # cylint: disable=CY102 -- standalone build hook, loaded outside the package where config.py cannot be imported
    # compile to a process-private temp then rename: concurrent importers
    # (multi-rank launches, pytest-xdist) must never dlopen a half-written .so
    tmp = lib.with_name(f"{lib.name}.tmp.{os.getpid()}")
    cmd = [cxx, "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
           "-o", str(tmp)] + [str(s) for s in _sources()]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        failed = proc.returncode != 0
        err = proc.stderr if failed else ""
    except OSError as e:  # read-only install dir / missing compiler
        failed, err = True, str(e)
    if failed:
        tmp.unlink(missing_ok=True)
        if lib.exists():
            # a shipped .so with sources that merely LOOK newer (wheel
            # mtime artifacts, read-only site-packages) beats no library —
            # but a real compile error against edited sources must not
            # vanish, so the fallback is always loud
            import sys

            print(f"[cylon_tpu.native] rebuild failed; using existing "
                  f"{lib.name}:\n{err}", file=sys.stderr)
            return lib
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}):\n{err}")
    os.replace(tmp, lib)
    if verbose:
        print(f"built {lib}")
    return lib


if __name__ == "__main__":
    build(verbose=True)
