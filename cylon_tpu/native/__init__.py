"""Native (C++) runtime layer, loaded over ctypes.

Host-side native equivalents of the reference's C++ runtime components:

- murmur3 + threaded multi-column row hashing / partition targets
  (reference: cpp/src/cylon/util/murmur3.cpp and
  arrow/arrow_partition_kernels.hpp:93-362)
- threaded CSV reader/writer producing Column-shaped flat buffers
  (reference: cpp/src/cylon/io/arrow_io.cpp:33-61, io/csv_read_config.hpp)
- tracking host memory pool (reference: ctx/memory_pool.hpp:25-66)
- raw-buffer column builder + string-id table registry — the foreign-binding
  surface (reference: arrow/arrow_builder.hpp:23-35, table_api.cpp:33-62)

Everything degrades gracefully: ``available()`` is False when no C++
toolchain exists, and callers (io layer, table_api) fall back to
pyarrow/pure-Python paths.  The TPU compute path (jit/pallas) never depends
on this module.
"""
from __future__ import annotations

import ctypes as ct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import config

# dtype codes shared with src/hashing.cpp / src/csv.cpp
CT_INT64 = 0
CT_FLOAT64 = 1
CT_BOOL = 2
CT_STRING = 3

_lock = threading.Lock()
_lib: Optional[ct.CDLL] = None
_load_error: Optional[str] = None


class _CtHashCol(ct.Structure):
    _fields_ = [("data", ct.c_void_p), ("lengths", ct.c_void_p),
                ("dtype", ct.c_int32), ("width", ct.c_int32)]


class _CtCsvOptions(ct.Structure):
    _fields_ = [("delimiter", ct.c_char), ("has_header", ct.c_int32),
                ("skip_rows", ct.c_int32), ("string_width", ct.c_int32),
                ("null_values", ct.c_char_p), ("use_quoting", ct.c_int32),
                ("quote_char", ct.c_char),
                ("strings_can_be_null", ct.c_int32)]


class _CtWriteCol(ct.Structure):
    _fields_ = [("name", ct.c_char_p), ("dtype", ct.c_int32),
                ("width", ct.c_int32), ("data", ct.c_void_p),
                ("validity", ct.c_void_p), ("lengths", ct.c_void_p)]


def _bind(lib: ct.CDLL) -> None:
    lib.ct_row_hash.argtypes = [ct.POINTER(_CtHashCol), ct.c_int32,
                                ct.c_int64, ct.POINTER(ct.c_uint32)]
    lib.ct_partition_targets.argtypes = [
        ct.POINTER(ct.c_uint32), ct.c_int64, ct.c_int32,
        ct.POINTER(ct.c_uint32), ct.POINTER(ct.c_int64)]
    lib.ct_murmur3_x86_32.restype = ct.c_uint32
    lib.ct_murmur3_x86_32.argtypes = [ct.c_void_p, ct.c_int32, ct.c_uint32]

    lib.ct_pool_create.restype = ct.c_void_p
    lib.ct_pool_destroy.argtypes = [ct.c_void_p]
    lib.ct_pool_alloc.restype = ct.c_void_p
    lib.ct_pool_alloc.argtypes = [ct.c_void_p, ct.c_int64]
    lib.ct_pool_free.argtypes = [ct.c_void_p, ct.c_void_p]
    for fn in ("ct_pool_bytes_allocated", "ct_pool_max_memory",
               "ct_pool_num_allocations"):
        f = getattr(lib, fn)
        f.restype = ct.c_int64
        f.argtypes = [ct.c_void_p]

    lib.ct_csv_read.restype = ct.c_void_p
    lib.ct_csv_read.argtypes = [ct.c_char_p, ct.POINTER(_CtCsvOptions),
                                ct.c_char_p, ct.c_int32]
    lib.ct_csv_free.argtypes = [ct.c_void_p]
    lib.ct_csv_rows.restype = ct.c_int64
    lib.ct_csv_rows.argtypes = [ct.c_void_p]
    lib.ct_csv_ncols.restype = ct.c_int32
    lib.ct_csv_ncols.argtypes = [ct.c_void_p]
    lib.ct_csv_col_name.restype = ct.c_int32
    lib.ct_csv_col_name.argtypes = [ct.c_void_p, ct.c_int32, ct.c_char_p,
                                    ct.c_int32]
    lib.ct_csv_col_info.restype = ct.c_int32
    lib.ct_csv_col_info.argtypes = [ct.c_void_p, ct.c_int32,
                                    ct.POINTER(ct.c_int32),
                                    ct.POINTER(ct.c_int32)]
    for fn in ("ct_csv_col_data", "ct_csv_col_validity",
               "ct_csv_col_lengths"):
        f = getattr(lib, fn)
        f.restype = ct.c_void_p
        f.argtypes = [ct.c_void_p, ct.c_int32]
    lib.ct_csv_write.restype = ct.c_int32
    lib.ct_csv_write.argtypes = [ct.c_char_p, ct.POINTER(_CtWriteCol),
                                 ct.c_int32, ct.c_int64, ct.c_char]

    lib.ct_builder_begin.restype = ct.c_int32
    lib.ct_builder_begin.argtypes = [ct.c_char_p]
    lib.ct_builder_add_column.restype = ct.c_int32
    lib.ct_builder_add_column.argtypes = [
        ct.c_char_p, ct.c_char_p, ct.c_int32, ct.c_int32, ct.c_int64,
        ct.c_void_p, ct.c_void_p, ct.c_void_p]
    lib.ct_builder_finish.restype = ct.c_int32
    lib.ct_builder_finish.argtypes = [ct.c_char_p]
    lib.ct_registry_contains.restype = ct.c_int32
    lib.ct_registry_contains.argtypes = [ct.c_char_p]
    lib.ct_registry_remove.restype = ct.c_int32
    lib.ct_registry_remove.argtypes = [ct.c_char_p]
    lib.ct_registry_size.restype = ct.c_int64
    lib.ct_registry_ids.restype = ct.c_int64
    lib.ct_registry_ids.argtypes = [ct.c_char_p, ct.c_int64]
    lib.ct_table_rows.restype = ct.c_int64
    lib.ct_table_rows.argtypes = [ct.c_char_p]
    lib.ct_table_ncols.restype = ct.c_int32
    lib.ct_table_ncols.argtypes = [ct.c_char_p]
    lib.ct_table_col_name.restype = ct.c_int32
    lib.ct_table_col_name.argtypes = [ct.c_char_p, ct.c_int32, ct.c_char_p,
                                      ct.c_int32]
    lib.ct_table_col_info.restype = ct.c_int32
    lib.ct_table_col_info.argtypes = [
        ct.c_char_p, ct.c_int32, ct.POINTER(ct.c_int32),
        ct.POINTER(ct.c_int32), ct.POINTER(ct.c_int64),
        ct.POINTER(ct.c_int32), ct.POINTER(ct.c_int32)]
    for fn in ("ct_table_col_data", "ct_table_col_validity",
               "ct_table_col_lengths"):
        f = getattr(lib, fn)
        f.restype = ct.c_void_p
        f.argtypes = [ct.c_char_p, ct.c_int32]


def _load() -> Optional[ct.CDLL]:
    global _lib, _load_error
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        if config.knob("CYLON_TPU_NO_NATIVE"):
            _load_error = "disabled by CYLON_TPU_NO_NATIVE"
            return None
        try:
            from . import build
            lib_file = build.build()
            lib = ct.CDLL(str(lib_file))
            _bind(lib)
            _lib = lib
        except Exception as e:  # toolchain missing / build failure
            _load_error = str(e)
        return _lib


def available() -> bool:
    return _load() is not None


def load_error() -> Optional[str]:
    _load()
    return _load_error


def _require() -> ct.CDLL:
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native unavailable: {_load_error}")
    return lib


def _read_buf(ptr, ctype, shape, np_dtype) -> np.ndarray:
    """Copy a C buffer into numpy; empty tables have no buffer to read."""
    if shape[0] == 0 or not ptr:
        return np.zeros(shape, dtype=np_dtype)
    return np.ctypeslib.as_array(ct.cast(ptr, ct.POINTER(ctype)), shape).copy()


def murmur3_32(data: bytes, seed: int = 0) -> int:
    lib = _require()
    buf = ct.create_string_buffer(data, len(data))
    return int(lib.ct_murmur3_x86_32(ct.cast(buf, ct.c_void_p), len(data),
                                     seed))


def _hash_cols_from_numpy(arrays, lengths_list) -> Tuple[List[_CtHashCol], list]:
    cols = []
    keepalive = []
    for arr, lengths in zip(arrays, lengths_list):
        arr = np.ascontiguousarray(arr)
        keepalive.append(arr)
        if arr.dtype == np.uint8 and arr.ndim == 2:
            dtype, width = CT_STRING, arr.shape[1]
            if lengths is not None:
                lengths = np.ascontiguousarray(lengths, dtype=np.int32)
                keepalive.append(lengths)
        else:
            if arr.ndim != 1:
                raise ValueError("fixed-width hash input must be 1-D")
            width = arr.dtype.itemsize
            dtype = CT_INT64 if arr.dtype.kind in "iub" else CT_FLOAT64
            lengths = None
        cols.append(_CtHashCol(
            arr.ctypes.data_as(ct.c_void_p),
            None if lengths is None else lengths.ctypes.data_as(ct.c_void_p),
            dtype, width))
    return cols, keepalive


def row_hash(arrays: Sequence[np.ndarray],
             lengths: Optional[Sequence[Optional[np.ndarray]]] = None
             ) -> np.ndarray:
    """Threaded composite row hash (reference:
    HashPartitionKernel::UpdateHash, arrow_partition_kernels.hpp:199-233)."""
    lib = _require()
    if lengths is None:
        lengths = [None] * len(arrays)
    rows = len(arrays[0])
    cols, keepalive = _hash_cols_from_numpy(arrays, lengths)
    out = np.empty(rows, dtype=np.uint32)
    arr_t = (_CtHashCol * len(cols))(*cols)
    lib.ct_row_hash(arr_t, len(cols), rows,
                    out.ctypes.data_as(ct.POINTER(ct.c_uint32)))
    return out


def partition_targets(hashes: np.ndarray, world: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """targets + histogram from row hashes (reference:
    arrow_partition_kernels.hpp:60-70 modulo/mask partitioner)."""
    lib = _require()
    hashes = np.ascontiguousarray(hashes, dtype=np.uint32)
    targets = np.empty(len(hashes), dtype=np.uint32)
    hist = np.zeros(world, dtype=np.int64)
    lib.ct_partition_targets(
        hashes.ctypes.data_as(ct.POINTER(ct.c_uint32)), len(hashes), world,
        targets.ctypes.data_as(ct.POINTER(ct.c_uint32)),
        hist.ctypes.data_as(ct.POINTER(ct.c_int64)))
    return targets, hist


class MemoryPool:
    """Tracking host allocator (reference: ctx/memory_pool.hpp:25-66)."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native unavailable: {_load_error}")
        self._lib = lib
        self._pool = lib.ct_pool_create()
        self._live = set()

    def allocate(self, size: int) -> int:
        ptr = self._lib.ct_pool_alloc(self._pool, size)
        if not ptr:
            raise MemoryError(f"pool allocation of {size} bytes failed")
        self._live.add(ptr)
        return ptr

    def free(self, ptr: int) -> None:
        self._live.discard(ptr)
        self._lib.ct_pool_free(self._pool, ptr)

    @property
    def bytes_allocated(self) -> int:
        return self._lib.ct_pool_bytes_allocated(self._pool)

    @property
    def max_memory(self) -> int:
        return self._lib.ct_pool_max_memory(self._pool)

    @property
    def num_allocations(self) -> int:
        return self._lib.ct_pool_num_allocations(self._pool)

    def close(self) -> None:
        if self._pool:
            for ptr in list(self._live):
                self.free(ptr)
            self._lib.ct_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # cylint: disable=CY105 -- __del__ runs during interpreter teardown; raising here aborts GC and no Status consumer exists
            pass


def csv_read(path, delimiter: str = ",", has_header: bool = True,
             skip_rows: int = 0, string_width: int = 0,
             null_values: Optional[Sequence[str]] = None,
             use_quoting: bool = True, quote_char: str = '"',
             strings_can_be_null: bool = False
             ) -> Tuple[List[str], List[Dict[str, np.ndarray]]]:
    """Read a CSV into Column-shaped numpy buffers.

    Returns (names, cols) where each col dict has ``data`` (1-D for
    fixed-width, 2-D uint8 for strings), ``validity`` (bool), and
    optionally ``lengths`` (int32).
    """
    lib = _require()
    opts = _CtCsvOptions(
        delimiter.encode()[:1], 1 if has_header else 0, skip_rows,
        string_width,
        None if null_values is None
        else "\n".join(null_values).encode("utf-8"),
        1 if use_quoting else 0, quote_char.encode()[:1],
        1 if strings_can_be_null else 0)
    err = ct.create_string_buffer(512)
    h = lib.ct_csv_read(str(path).encode("utf-8"), ct.byref(opts), err, 512)
    if not h:
        raise RuntimeError(f"native csv read failed: {err.value.decode()}")
    try:
        rows = lib.ct_csv_rows(h)
        ncols = lib.ct_csv_ncols(h)
        names, cols = [], []
        namebuf = ct.create_string_buffer(4096)
        for i in range(ncols):
            lib.ct_csv_col_name(h, i, namebuf, 4096)
            names.append(namebuf.value.decode("utf-8"))
            dtype = ct.c_int32()
            width = ct.c_int32()
            lib.ct_csv_col_info(h, i, ct.byref(dtype), ct.byref(width))
            dptr = lib.ct_csv_col_data(h, i)
            vptr = lib.ct_csv_col_validity(h, i)
            col: Dict[str, np.ndarray] = {}
            if dtype.value == CT_STRING:
                col["data"] = _read_buf(dptr, ct.c_uint8,
                                        (rows, width.value), np.uint8)
                lptr = lib.ct_csv_col_lengths(h, i)
                col["lengths"] = _read_buf(lptr, ct.c_int32, (rows,),
                                           np.int32)
            elif dtype.value == CT_INT64:
                col["data"] = _read_buf(dptr, ct.c_int64, (rows,), np.int64)
            elif dtype.value == CT_FLOAT64:
                col["data"] = _read_buf(dptr, ct.c_double, (rows,),
                                        np.float64)
            else:  # CT_BOOL
                col["data"] = _read_buf(dptr, ct.c_uint8, (rows,),
                                        np.uint8).astype(bool)
            col["validity"] = _read_buf(vptr, ct.c_uint8, (rows,),
                                        np.uint8).astype(bool)
            cols.append(col)
        return names, cols
    finally:
        lib.ct_csv_free(h)


def csv_write(path, names: Sequence[str], arrays: Sequence[np.ndarray],
              validities: Sequence[Optional[np.ndarray]],
              lengths_list: Sequence[Optional[np.ndarray]],
              delimiter: str = ",") -> None:
    lib = _require()
    rows = len(arrays[0]) if arrays else 0
    cols = []
    keepalive = []
    for name, arr, valid, lengths in zip(names, arrays, validities,
                                         lengths_list):
        arr = np.ascontiguousarray(arr)
        keepalive.append(arr)
        if arr.dtype == np.uint8 and arr.ndim == 2:
            dtype, width = CT_STRING, arr.shape[1]
        elif arr.dtype.kind == "b":
            arr = arr.astype(np.uint8)
            keepalive.append(arr)
            dtype, width = CT_BOOL, 1
        elif arr.dtype.kind in "iu":
            arr = arr.astype(np.int64)
            keepalive.append(arr)
            dtype, width = CT_INT64, 8
        else:
            arr = arr.astype(np.float64)
            keepalive.append(arr)
            dtype, width = CT_FLOAT64, 8
        vptr = None
        if valid is not None:
            valid = np.ascontiguousarray(valid, dtype=np.uint8)
            keepalive.append(valid)
            vptr = valid.ctypes.data_as(ct.c_void_p)
        lptr = None
        if lengths is not None:
            lengths = np.ascontiguousarray(lengths, dtype=np.int32)
            keepalive.append(lengths)
            lptr = lengths.ctypes.data_as(ct.c_void_p)
        nm = name.encode("utf-8")
        keepalive.append(nm)
        cols.append(_CtWriteCol(nm, dtype, width,
                                arr.ctypes.data_as(ct.c_void_p), vptr, lptr))
    arr_t = (_CtWriteCol * len(cols))(*cols)
    rc = lib.ct_csv_write(str(path).encode("utf-8"), arr_t, len(cols), rows,
                          delimiter.encode()[:1])
    if rc != 0:
        raise RuntimeError(f"native csv write failed: rc={rc}")


# --- registry / builder (foreign-binding surface) -----------------------

def builder_begin(table_id: str) -> None:
    lib = _require()
    if lib.ct_builder_begin(table_id.encode("utf-8")) != 0:
        raise RuntimeError(f"builder already open for id {table_id!r}")


def builder_add_column(table_id: str, name: str, data: np.ndarray,
                       validity: Optional[np.ndarray] = None,
                       lengths: Optional[np.ndarray] = None) -> None:
    lib = _require()
    data = np.ascontiguousarray(data)
    if data.dtype == np.uint8 and data.ndim == 2:
        dtype, width, rows = CT_STRING, data.shape[1], data.shape[0]
    elif data.dtype.kind == "b":
        data = data.astype(np.uint8)
        dtype, width, rows = CT_BOOL, 1, len(data)
    elif data.dtype.kind in "iu":
        data = data.astype(np.int64)
        dtype, width, rows = CT_INT64, 8, len(data)
    else:
        data = data.astype(np.float64)
        dtype, width, rows = CT_FLOAT64, 8, len(data)
    vptr = None
    if validity is not None:
        validity = np.ascontiguousarray(validity, dtype=np.uint8)
        vptr = validity.ctypes.data_as(ct.c_void_p)
    lptr = None
    if lengths is not None:
        lengths = np.ascontiguousarray(lengths, dtype=np.int32)
        lptr = lengths.ctypes.data_as(ct.c_void_p)
    rc = lib.ct_builder_add_column(
        table_id.encode("utf-8"), name.encode("utf-8"), dtype, width, rows,
        data.ctypes.data_as(ct.c_void_p), vptr, lptr)
    if rc != 0:
        raise RuntimeError(f"builder_add_column failed: rc={rc}")


def builder_finish(table_id: str) -> None:
    lib = _require()
    if lib.ct_builder_finish(table_id.encode("utf-8")) != 0:
        raise RuntimeError(f"no open builder for id {table_id!r}")


def registry_contains(table_id: str) -> bool:
    lib = _load()
    if lib is None:
        return False
    return bool(lib.ct_registry_contains(table_id.encode("utf-8")))


def registry_remove(table_id: str) -> bool:
    lib = _require()
    return lib.ct_registry_remove(table_id.encode("utf-8")) == 0


def registry_size() -> int:
    lib = _require()
    return int(lib.ct_registry_size())


def registry_ids() -> List[str]:
    lib = _require()
    n = lib.ct_registry_ids(None, 0)
    buf = ct.create_string_buffer(int(n) + 1)
    lib.ct_registry_ids(buf, n + 1)
    s = buf.value.decode("utf-8")
    return s.split("\n") if s else []


def registry_get(table_id: str
                 ) -> Tuple[List[str], List[Dict[str, np.ndarray]]]:
    """Zero-copy read-out of a registered table (copies into numpy on
    return so the registry entry can be dropped safely)."""
    lib = _require()
    tid = table_id.encode("utf-8")
    rows = lib.ct_table_rows(tid)
    if rows < 0:
        raise KeyError(table_id)
    ncols = lib.ct_table_ncols(tid)
    names, cols = [], []
    namebuf = ct.create_string_buffer(4096)
    for i in range(ncols):
        lib.ct_table_col_name(tid, i, namebuf, 4096)
        names.append(namebuf.value.decode("utf-8"))
        dtype = ct.c_int32()
        width = ct.c_int32()
        crows = ct.c_int64()
        has_v = ct.c_int32()
        has_l = ct.c_int32()
        lib.ct_table_col_info(tid, i, ct.byref(dtype), ct.byref(width),
                              ct.byref(crows), ct.byref(has_v),
                              ct.byref(has_l))
        dptr = lib.ct_table_col_data(tid, i)
        col: Dict[str, np.ndarray] = {}
        if dtype.value == CT_STRING:
            col["data"] = _read_buf(dptr, ct.c_uint8, (rows, width.value),
                                    np.uint8)
        elif dtype.value == CT_INT64:
            col["data"] = _read_buf(dptr, ct.c_int64, (rows,), np.int64)
        elif dtype.value == CT_FLOAT64:
            col["data"] = _read_buf(dptr, ct.c_double, (rows,), np.float64)
        else:
            col["data"] = _read_buf(dptr, ct.c_uint8, (rows,),
                                    np.uint8).astype(bool)
        if has_v.value:
            vptr = lib.ct_table_col_validity(tid, i)
            col["validity"] = _read_buf(vptr, ct.c_uint8, (rows,),
                                        np.uint8).astype(bool)
        if has_l.value:
            lptr = lib.ct_table_col_lengths(tid, i)
            col["lengths"] = _read_buf(lptr, ct.c_int32, (rows,), np.int32)
        cols.append(col)
    return names, cols
