// MurmurHash3 x86_32 — host-side hash used by the native row-hashing and
// partition paths.  Fresh implementation of the public-domain algorithm by
// Austin Appleby; fills the role of the reference's vendored
// util/murmur3.{hpp,cpp} (cpp/src/cylon/util/murmur3.cpp).
#pragma once

#include <cstdint>
#include <cstring>

namespace cylon_tpu {

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bU;
  h ^= h >> 13;
  h *= 0xc2b2ae35U;
  h ^= h >> 16;
  return h;
}

inline uint32_t murmur3_x86_32(const void* key, int len, uint32_t seed) {
  const uint8_t* data = static_cast<const uint8_t*>(key);
  const int nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51U;
  const uint32_t c2 = 0x1b873593U;

  for (int i = 0; i < nblocks; i++) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64U;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k1 ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= static_cast<uint32_t>(len);
  return fmix32(h1);
}

}  // namespace cylon_tpu
