// String-id table registry + raw-buffer column builder.
//
// Native analog of two reference components:
//  - table_api: the mutex-guarded global map<string, Table> that backs the
//    foreign-language (JNI) binding surface (cpp/src/cylon/table_api.cpp:
//    33-62, table_api.hpp:38-195);
//  - arrow_builder: building columns from raw (address, size) buffers
//    registered by id — the zero-copy ingest path used by the Java binding
//    (cpp/src/cylon/arrow/arrow_builder.hpp:23-35).
//
// A foreign host (or Python) registers column buffers by table id; the
// registry owns host copies; readers get zero-copy pointers back out.  The
// relational ops themselves run in the JAX/XLA compute path — this is the
// host-side hand-off surface.
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

// the published foreign-binding contract; including it here makes the
// compiler enforce header<->implementation prototype agreement
#include "../include/cylon_tpu_c.h"

namespace {

struct CtColumn {
  std::string name;
  int32_t dtype = 0;
  int32_t width = 0;  // bytes per row (strings: matrix row width)
  int64_t rows = 0;
  std::vector<uint8_t> data;
  std::vector<uint8_t> validity;  // 1 byte per row; empty = all valid
  std::vector<int32_t> lengths;   // strings only
};

struct CtTable {
  std::vector<CtColumn> cols;
  int64_t rows = 0;
};

std::mutex g_mutex;
std::map<std::string, std::shared_ptr<CtTable>> g_tables;
std::map<std::string, std::shared_ptr<CtTable>> g_building;

std::shared_ptr<CtTable> find_table(const char* id) {
  std::lock_guard<std::mutex> g(g_mutex);
  auto it = g_tables.find(id);
  return it == g_tables.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int32_t ct_builder_begin(const char* id) {
  std::lock_guard<std::mutex> g(g_mutex);
  if (g_building.count(id)) return -1;
  g_building[id] = std::make_shared<CtTable>();
  return 0;
}

int32_t ct_builder_add_column(const char* id, const char* name, int32_t dtype,
                              int32_t width, int64_t rows, const void* data,
                              const uint8_t* validity,
                              const int32_t* lengths) {
  std::shared_ptr<CtTable> t;
  {
    std::lock_guard<std::mutex> g(g_mutex);
    auto it = g_building.find(id);
    if (it == g_building.end()) return -1;
    t = it->second;
  }
  if (!t->cols.empty() && t->rows != rows) return -2;
  CtColumn col;
  col.name = name;
  col.dtype = dtype;
  col.width = width;
  col.rows = rows;
  int64_t nbytes = rows * static_cast<int64_t>(width);
  col.data.resize(nbytes);
  if (nbytes) std::memcpy(col.data.data(), data, nbytes);
  if (validity) {
    col.validity.resize(rows);
    std::memcpy(col.validity.data(), validity, rows);
  }
  if (lengths) {
    col.lengths.resize(rows);
    std::memcpy(col.lengths.data(), lengths, rows * sizeof(int32_t));
  }
  t->rows = rows;
  t->cols.push_back(std::move(col));
  return 0;
}

int32_t ct_builder_finish(const char* id) {
  std::lock_guard<std::mutex> g(g_mutex);
  auto it = g_building.find(id);
  if (it == g_building.end()) return -1;
  g_tables[id] = it->second;
  g_building.erase(it);
  return 0;
}

int32_t ct_registry_contains(const char* id) {
  std::lock_guard<std::mutex> g(g_mutex);
  return g_tables.count(id) ? 1 : 0;
}

int32_t ct_registry_remove(const char* id) {
  std::lock_guard<std::mutex> g(g_mutex);
  return g_tables.erase(id) ? 0 : -1;
}

int64_t ct_registry_size() {
  std::lock_guard<std::mutex> g(g_mutex);
  return static_cast<int64_t>(g_tables.size());
}

void ct_registry_clear() {
  std::lock_guard<std::mutex> g(g_mutex);
  g_tables.clear();
  g_building.clear();
}

// ids joined by '\n' into caller buffer; returns needed length.
int64_t ct_registry_ids(char* out, int64_t cap) {
  std::lock_guard<std::mutex> g(g_mutex);
  std::string joined;
  for (const auto& kv : g_tables) {
    if (!joined.empty()) joined += '\n';
    joined += kv.first;
  }
  if (out && cap > 0) {
    int64_t n = static_cast<int64_t>(joined.size()) < cap - 1
                    ? static_cast<int64_t>(joined.size())
                    : cap - 1;
    std::memcpy(out, joined.data(), n);
    out[n] = '\0';
  }
  return static_cast<int64_t>(joined.size());
}

int64_t ct_table_rows(const char* id) {
  auto t = find_table(id);
  return t ? t->rows : -1;
}

int32_t ct_table_ncols(const char* id) {
  auto t = find_table(id);
  return t ? static_cast<int32_t>(t->cols.size()) : -1;
}

int32_t ct_table_col_name(const char* id, int32_t i, char* out, int32_t cap) {
  auto t = find_table(id);
  if (!t || i < 0 || i >= static_cast<int32_t>(t->cols.size())) return -1;
  const std::string& name = t->cols[i].name;
  int32_t n = static_cast<int32_t>(name.size()) < cap - 1
                  ? static_cast<int32_t>(name.size())
                  : cap - 1;
  std::memcpy(out, name.data(), n);
  out[n] = '\0';
  return static_cast<int32_t>(name.size());
}

int32_t ct_table_col_info(const char* id, int32_t i, int32_t* dtype,
                          int32_t* width, int64_t* rows, int32_t* has_validity,
                          int32_t* has_lengths) {
  auto t = find_table(id);
  if (!t || i < 0 || i >= static_cast<int32_t>(t->cols.size())) return -1;
  const CtColumn& c = t->cols[i];
  *dtype = c.dtype;
  *width = c.width;
  *rows = c.rows;
  *has_validity = c.validity.empty() ? 0 : 1;
  *has_lengths = c.lengths.empty() ? 0 : 1;
  return 0;
}

const void* ct_table_col_data(const char* id, int32_t i) {
  auto t = find_table(id);
  if (!t || i < 0 || i >= static_cast<int32_t>(t->cols.size())) return nullptr;
  return t->cols[i].data.data();
}

const uint8_t* ct_table_col_validity(const char* id, int32_t i) {
  auto t = find_table(id);
  if (!t || i < 0 || i >= static_cast<int32_t>(t->cols.size())) return nullptr;
  return t->cols[i].validity.empty() ? nullptr : t->cols[i].validity.data();
}

const int32_t* ct_table_col_lengths(const char* id, int32_t i) {
  auto t = find_table(id);
  if (!t || i < 0 || i >= static_cast<int32_t>(t->cols.size())) return nullptr;
  return t->cols[i].lengths.empty() ? nullptr : t->cols[i].lengths.data();
}

}  // extern "C"
