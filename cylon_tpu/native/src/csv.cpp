// Native CSV reader/writer — the framework's data loader.
//
// Fills the role of the reference's IO layer (cpp/src/cylon/io/arrow_io.cpp:
// 33-61 read_csv over Arrow's memory-mapped multi-threaded CSV reader, with
// CSVReadOptions io/csv_read_config.hpp:27-130), built TPU-first: the
// output is flat fixed-width column buffers (data + validity byte-vector +
// string byte-matrix/lengths) shaped exactly like cylon_tpu.Column device
// buffers, so ingest is one memcpy/device_put per column with no
// offsets→padding conversion on the Python side.
//
// Three phases:
//   1. single scan for row boundaries (quote-aware) → row offsets
//   2. threaded field slicing  → (offset, len) per cell + per-column max len
//   3. type inference then threaded materialization into typed buffers
#include <algorithm>
#include <atomic>
#include <cctype>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <strings.h>
#include <string>
#include <thread>
#include <vector>

#include "parallel.hpp"

namespace {

constexpr int64_t kRowsPerThread = 1 << 14;

enum CtDType : int32_t {
  CT_INT64 = 0,
  CT_FLOAT64 = 1,
  CT_BOOL = 2,
  CT_STRING = 3,
};

struct Options {
  char delimiter = ',';
  bool has_header = true;
  int32_t skip_rows = 0;
  int32_t string_width = 0;  // 0 = auto
  std::vector<std::string> null_values = {"",    "NULL", "null", "NaN",
                                          "nan", "N/A",  "n/a",  "NA"};
  bool use_quoting = true;
  char quote_char = '"';
  bool strings_can_be_null = false;  // pyarrow ConvertOptions semantics
};

bool is_null_token(const Options& o, const char* p, int32_t n) {
  for (const std::string& s : o.null_values)
    if (static_cast<int32_t>(s.size()) == n &&
        std::memcmp(s.data(), p, n) == 0)
      return true;
  return false;
}

struct Cell {
  uint32_t off;
  int32_t len;  // unescaped length may differ; quoted cells re-scanned
  bool quoted;
};

struct OutCol {
  std::string name;
  int32_t dtype = CT_STRING;
  int32_t width = 0;
  std::vector<uint8_t> data;
  std::vector<uint8_t> validity;
  std::vector<int32_t> lengths;
};

struct CsvResult {
  int64_t rows = 0;
  std::vector<OutCol> cols;
};

using cylon_tpu::parallel_rows;

// Split one line [lo, hi) into cells.  Returns number of fields.
int split_line(const char* buf, uint32_t lo, uint32_t hi, const Options& o,
               std::vector<Cell>& out) {
  int n = 0;
  uint32_t i = lo;
  while (true) {
    Cell c{i, 0, false};
    if (o.use_quoting && i < hi && buf[i] == o.quote_char) {
      c.quoted = true;
      c.off = ++i;
      while (i < hi) {
        if (buf[i] == o.quote_char) {
          if (i + 1 < hi && buf[i + 1] == o.quote_char) {
            i += 2;  // escaped quote
            continue;
          }
          break;
        }
        i++;
      }
      c.len = static_cast<int32_t>(i - c.off);
      if (i < hi) i++;  // closing quote
    } else {
      while (i < hi && buf[i] != o.delimiter) i++;
      c.len = static_cast<int32_t>(i - c.off);
    }
    out.push_back(c);
    n++;
    if (i >= hi) break;
    if (buf[i] == o.delimiter) i++;
    if (i >= hi && buf[hi - 1] == o.delimiter) {  // trailing empty field
      out.push_back(Cell{hi, 0, false});
      n++;
      break;
    }
  }
  return n;
}

// A cell's bytes: a direct view into the file buffer for unquoted cells;
// quoted cells are unescaped (doubled quotes collapsed) into `scratch`.
// No length cap — scratch grows to the cell size.
struct CellView {
  const char* p;
  int32_t n;
};

CellView cell_view(const char* buf, const Cell& c, char q,
                   std::vector<char>& scratch) {
  if (!c.quoted) return {buf + c.off, c.len};
  if (static_cast<int32_t>(scratch.size()) < c.len) scratch.resize(c.len);
  int32_t n = 0;
  for (int32_t i = 0; i < c.len; i++) {
    char ch = buf[c.off + i];
    scratch[n++] = ch;
    if (ch == q && i + 1 < c.len && buf[c.off + i + 1] == q) i++;
  }
  return {scratch.data(), n};
}

bool parse_i64(const char* p, int32_t len, int64_t* out) {
  while (len > 0 && (*p == ' ' || *p == '\t')) p++, len--;
  while (len > 0 && (p[len - 1] == ' ' || p[len - 1] == '\t')) len--;
  if (len == 0) return false;
  auto [end, ec] = std::from_chars(p, p + len, *out);
  return ec == std::errc() && end == p + len;
}

bool parse_f64(const char* p, int32_t len, double* out) {
  while (len > 0 && (*p == ' ' || *p == '\t')) p++, len--;
  while (len > 0 && (p[len - 1] == ' ' || p[len - 1] == '\t')) len--;
  if (len == 0 || len > 63) return false;
  char tmp[64];
  std::memcpy(tmp, p, len);
  tmp[len] = '\0';
  char* end = nullptr;
  *out = std::strtod(tmp, &end);
  return end == tmp + len;
}

bool parse_bool(const char* p, int32_t len, bool* out) {
  if (len == 4 && strncasecmp(p, "true", 4) == 0) return *out = true, true;
  if (len == 5 && strncasecmp(p, "false", 5) == 0) return *out = false, true;
  return false;
}

struct Handle {
  CsvResult result;
  std::string error;
};

}  // namespace

extern "C" {

struct CtCsvOptions {
  char delimiter;
  int32_t has_header;
  int32_t skip_rows;
  int32_t string_width;
  const char* null_values;  // '\n'-joined; NULL = defaults
  int32_t use_quoting;
  char quote_char;
  int32_t strings_can_be_null;
};

void* ct_csv_read(const char* path, const CtCsvOptions* copts, char* err,
                  int32_t errcap) {
  auto fail = [&](const std::string& msg) -> void* {
    if (err && errcap > 0) {
      int32_t n = std::min<int32_t>(msg.size(), errcap - 1);
      std::memcpy(err, msg.data(), n);
      err[n] = '\0';
    }
    return nullptr;
  };

  Options o;
  if (copts) {
    o.delimiter = copts->delimiter ? copts->delimiter : ',';
    o.has_header = copts->has_header != 0;
    o.skip_rows = copts->skip_rows;
    o.string_width = copts->string_width;
    o.use_quoting = copts->use_quoting != 0;
    o.quote_char = copts->quote_char ? copts->quote_char : '"';
    o.strings_can_be_null = copts->strings_can_be_null != 0;
    if (copts->null_values) {
      o.null_values.clear();
      const char* p = copts->null_values;
      while (true) {
        const char* nl = std::strchr(p, '\n');
        o.null_values.emplace_back(p, nl ? nl - p : std::strlen(p));
        if (!nl) break;
        p = nl + 1;
      }
    }
  }

  FILE* f = std::fopen(path, "rb");
  if (!f) return fail(std::string("cannot open ") + path);
  std::fseek(f, 0, SEEK_END);
  long fsize = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  // cell/line offsets are uint32 — reject files they cannot address (the
  // Python layer falls back to the pyarrow reader)
  if (static_cast<uint64_t>(fsize) > UINT32_MAX - 1) {
    std::fclose(f);
    return fail("file exceeds native reader's 4GiB limit");
  }
  std::vector<char> buf(fsize);
  if (fsize && std::fread(buf.data(), 1, fsize, f) != (size_t)fsize) {
    std::fclose(f);
    return fail("short read");
  }
  std::fclose(f);

  // phase 1: quote-aware line boundaries
  std::vector<uint32_t> starts, ends;
  {
    bool in_quote = false;
    uint32_t line_start = 0;
    for (uint32_t i = 0; i < (uint32_t)fsize; i++) {
      char c = buf[i];
      if (o.use_quoting && c == o.quote_char) {
        in_quote = !in_quote;  // doubled quotes toggle twice: net zero
      } else if (c == '\n' && !in_quote) {
        uint32_t e = i;
        if (e > line_start && buf[e - 1] == '\r') e--;
        if (e > line_start) {
          starts.push_back(line_start);
          ends.push_back(e);
        }
        line_start = i + 1;
      }
    }
    if (line_start < (uint32_t)fsize) {
      uint32_t e = fsize;
      if (e > line_start && buf[e - 1] == '\r') e--;
      if (e > line_start) {
        starts.push_back(line_start);
        ends.push_back(e);
      }
    }
  }

  size_t first = o.skip_rows;
  auto h = std::make_unique<Handle>();
  CsvResult& res = h->result;

  std::vector<std::string> names;
  int ncols = 0;
  if (first < starts.size()) {
    std::vector<Cell> cells;
    ncols = split_line(buf.data(), starts[first], ends[first], o, cells);
    if (o.has_header) {
      std::vector<char> scratch;
      for (const Cell& c : cells) {
        CellView v = cell_view(buf.data(), c, o.quote_char, scratch);
        names.emplace_back(v.p, v.n);
      }
      first++;
    } else {
      for (int i = 0; i < ncols; i++) names.push_back("f" + std::to_string(i));
    }
  }
  int64_t rows = static_cast<int64_t>(starts.size()) - first;
  if (rows < 0) rows = 0;
  res.rows = rows;
  res.cols.resize(ncols);
  for (int c = 0; c < ncols; c++) res.cols[c].name = names[c];
  if (rows == 0 || ncols == 0) return h.release();

  // phase 2: threaded field slicing
  std::vector<Cell> cells(static_cast<size_t>(rows) * ncols);
  std::vector<int32_t> maxlen(ncols, 0);
  std::string bad_row;
  std::mutex m;
  parallel_rows(rows, kRowsPerThread, [&](int64_t lo, int64_t hi) {
    std::vector<Cell> line;
    std::vector<int32_t> local_max(ncols, 0);
    for (int64_t r = lo; r < hi; r++) {
      line.clear();
      int n = split_line(buf.data(), starts[first + r], ends[first + r], o,
                         line);
      if (n != ncols) {
        std::lock_guard<std::mutex> g(m);
        if (bad_row.empty())
          bad_row = "row " + std::to_string(r) + " has " + std::to_string(n) +
                    " fields, expected " + std::to_string(ncols);
        continue;
      }
      for (int c = 0; c < ncols; c++) {
        cells[r * ncols + c] = line[c];
        local_max[c] = std::max(local_max[c], line[c].len);
      }
    }
    std::lock_guard<std::mutex> g(m);
    for (int c = 0; c < ncols; c++) maxlen[c] = std::max(maxlen[c], local_max[c]);
  });
  if (!bad_row.empty()) return fail(bad_row);

  // phase 3a: threaded type inference (whole column; nulls don't break a
  // type).  Each thread scans a row range with local flags and stops once
  // every candidate type is ruled out for its range.
  for (int c = 0; c < ncols; c++) {
    std::atomic<bool> ok_i64{true}, ok_f64{true}, ok_bool{true}, any{false};
    parallel_rows(rows, kRowsPerThread, [&](int64_t lo, int64_t hi) {
      std::vector<char> scratch;
      bool li = true, lf = true, lb = true, la = false;
      for (int64_t r = lo; r < hi && (li || lf || lb); r++) {
        const Cell& cell = cells[r * ncols + c];
        CellView v = cell_view(buf.data(), cell, o.quote_char, scratch);
        if (!cell.quoted && is_null_token(o, v.p, v.n)) continue;
        la = true;
        int64_t iv;
        double dv;
        bool bv;
        if (li && !parse_i64(v.p, v.n, &iv)) li = false;
        if (lf && !parse_f64(v.p, v.n, &dv)) lf = false;
        if (lb && !parse_bool(v.p, v.n, &bv)) lb = false;
      }
      if (!li) ok_i64 = false;
      if (!lf) ok_f64 = false;
      if (!lb) ok_bool = false;
      if (la) any = true;
    });
    OutCol& col = res.cols[c];
    if (!any) col.dtype = CT_STRING;          // all-null → string
    else if (ok_i64) col.dtype = CT_INT64;
    else if (ok_f64) col.dtype = CT_FLOAT64;
    else if (ok_bool) col.dtype = CT_BOOL;
    else col.dtype = CT_STRING;
  }

  // phase 3b: threaded materialization
  for (int c = 0; c < ncols; c++) {
    OutCol& col = res.cols[c];
    switch (col.dtype) {
      case CT_INT64:
      case CT_FLOAT64: col.width = 8; break;
      case CT_BOOL: col.width = 1; break;
      case CT_STRING: {
        int32_t w = o.string_width > 0 ? o.string_width
                                       : std::max(1, maxlen[c]);
        col.width = (w + 7) & ~7;  // round to 8 for alignment
        col.lengths.assign(rows, 0);
        break;
      }
    }
    col.data.assign(static_cast<size_t>(rows) * col.width, 0);
    col.validity.assign(rows, 1);
  }
  parallel_rows(rows, kRowsPerThread, [&](int64_t lo, int64_t hi) {
    std::vector<char> scratch;
    for (int64_t r = lo; r < hi; r++) {
      for (int c = 0; c < ncols; c++) {
        OutCol& col = res.cols[c];
        const Cell& cell = cells[r * ncols + c];
        CellView v = cell_view(buf.data(), cell, o.quote_char, scratch);
        bool is_null = !cell.quoted && is_null_token(o, v.p, v.n) &&
                       (col.dtype != CT_STRING || o.strings_can_be_null);
        if (is_null) {
          col.validity[r] = 0;
          continue;
        }
        switch (col.dtype) {
          case CT_INT64: {
            int64_t val = 0;
            parse_i64(v.p, v.n, &val);
            std::memcpy(col.data.data() + r * 8, &val, 8);
            break;
          }
          case CT_FLOAT64: {
            double val = 0;
            parse_f64(v.p, v.n, &val);
            std::memcpy(col.data.data() + r * 8, &val, 8);
            break;
          }
          case CT_BOOL: {
            bool val = false;
            parse_bool(v.p, v.n, &val);
            col.data[r] = val ? 1 : 0;
            break;
          }
          case CT_STRING: {
            // truncation only when an explicit string_width option narrows
            // the column below the observed max length
            int32_t w = std::min(v.n, col.width);
            std::memcpy(col.data.data() + (int64_t)r * col.width, v.p, w);
            col.lengths[r] = w;
            break;
          }
        }
      }
    }
  });
  return h.release();
}

void ct_csv_free(void* handle) { delete static_cast<Handle*>(handle); }

int64_t ct_csv_rows(void* handle) {
  return static_cast<Handle*>(handle)->result.rows;
}

int32_t ct_csv_ncols(void* handle) {
  return static_cast<int32_t>(static_cast<Handle*>(handle)->result.cols.size());
}

int32_t ct_csv_col_name(void* handle, int32_t i, char* out, int32_t cap) {
  auto& cols = static_cast<Handle*>(handle)->result.cols;
  if (i < 0 || i >= (int32_t)cols.size()) return -1;
  const std::string& name = cols[i].name;
  int32_t n = std::min<int32_t>(name.size(), cap - 1);
  std::memcpy(out, name.data(), n);
  out[n] = '\0';
  return static_cast<int32_t>(name.size());
}

int32_t ct_csv_col_info(void* handle, int32_t i, int32_t* dtype,
                        int32_t* width) {
  auto& cols = static_cast<Handle*>(handle)->result.cols;
  if (i < 0 || i >= (int32_t)cols.size()) return -1;
  *dtype = cols[i].dtype;
  *width = cols[i].width;
  return 0;
}

const void* ct_csv_col_data(void* handle, int32_t i) {
  auto& cols = static_cast<Handle*>(handle)->result.cols;
  if (i < 0 || i >= (int32_t)cols.size()) return nullptr;
  return cols[i].data.data();
}

const uint8_t* ct_csv_col_validity(void* handle, int32_t i) {
  auto& cols = static_cast<Handle*>(handle)->result.cols;
  if (i < 0 || i >= (int32_t)cols.size()) return nullptr;
  return cols[i].validity.data();
}

const int32_t* ct_csv_col_lengths(void* handle, int32_t i) {
  auto& cols = static_cast<Handle*>(handle)->result.cols;
  if (i < 0 || i >= (int32_t)cols.size()) return nullptr;
  return cols[i].lengths.empty() ? nullptr : cols[i].lengths.data();
}

// --- writer ------------------------------------------------------------

struct CtWriteCol {
  const char* name;
  int32_t dtype;
  int32_t width;
  const void* data;
  const uint8_t* validity;  // may be NULL (all valid)
  const int32_t* lengths;   // strings only
};

int32_t ct_csv_write(const char* path, const CtWriteCol* cols, int32_t ncols,
                     int64_t rows, char delimiter) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  std::string out;
  out.reserve(1 << 20);
  for (int32_t c = 0; c < ncols; c++) {
    if (c) out += delimiter;
    out += cols[c].name;
  }
  out += '\n';
  char tmp[64];
  for (int64_t r = 0; r < rows; r++) {
    for (int32_t c = 0; c < ncols; c++) {
      if (c) out += delimiter;
      const CtWriteCol& col = cols[c];
      if (col.validity && !col.validity[r]) continue;  // empty = null
      const uint8_t* base = static_cast<const uint8_t*>(col.data);
      switch (col.dtype) {
        case CT_INT64: {
          int64_t v;
          std::memcpy(&v, base + r * 8, 8);
          out += std::to_string(v);
          break;
        }
        case CT_FLOAT64: {
          double v;
          std::memcpy(&v, base + r * 8, 8);
          std::snprintf(tmp, sizeof(tmp), "%.17g", v);
          out += tmp;
          break;
        }
        case CT_BOOL: out += base[r] ? "True" : "False"; break;  // pandas-style, round-trips both readers
        case CT_STRING: {
          int32_t n = col.lengths ? col.lengths[r] : col.width;
          const char* p =
              reinterpret_cast<const char*>(base + (int64_t)r * col.width);
          bool need_quote =
              std::memchr(p, delimiter, n) || std::memchr(p, '"', n) ||
              std::memchr(p, '\n', n);
          if (need_quote) {
            out += '"';
            for (int32_t i = 0; i < n; i++) {
              if (p[i] == '"') out += '"';
              out += p[i];
            }
            out += '"';
          } else {
            out.append(p, n);
          }
          break;
        }
      }
    }
    out += '\n';
    if (out.size() > (1 << 20)) {
      std::fwrite(out.data(), 1, out.size(), f);
      out.clear();
    }
  }
  std::fwrite(out.data(), 1, out.size(), f);
  std::fclose(f);
  return 0;
}

}  // extern "C"
