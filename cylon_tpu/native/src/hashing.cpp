// Host-side multi-column row hashing + partition-target kernels, threaded
// over row ranges.  Native analog of the reference's partition kernels
// (cpp/src/cylon/arrow/arrow_partition_kernels.hpp:93-362): the composite
// row hash is murmur3 of each value combined across columns as 31*h + x,
// and targets are hash % world (mask when world is a power of two).
//
// The TPU compute path does this on-device (cylon_tpu/ops/hashing.py /
// pallas); this native path serves host-resident data (CSV ingest,
// registry tables) without a device round-trip.
#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include "murmur3.hpp"
#include "parallel.hpp"

namespace cylon_tpu {
namespace {

constexpr uint32_t kSeed = 0;
constexpr int64_t kRowsPerThread = 1 << 16;  // >=64K rows per thread

}  // namespace
}  // namespace cylon_tpu

extern "C" {

// dtype codes shared with cylon_tpu/native/__init__.py
enum CtDType : int32_t {
  CT_INT64 = 0,
  CT_FLOAT64 = 1,
  CT_BOOL = 2,
  CT_STRING = 3,  // fixed-width byte matrix [rows, width] + int32 lengths
  CT_INT32 = 4,
  CT_FLOAT32 = 5,
};

// One column's buffers for hashing: fixed-width data, or byte matrix +
// lengths for strings (width = bytes per row).
struct CtHashCol {
  const void* data;
  const int32_t* lengths;  // strings only, else null
  int32_t dtype;
  int32_t width;  // bytes per row
};

// hashes[i] = combine over columns of murmur3(value_i) as 31*h + x
// (reference: HashPartitionKernel::UpdateHash,
// arrow_partition_kernels.hpp:199-233).
void ct_row_hash(const CtHashCol* cols, int32_t ncols, int64_t rows,
                 uint32_t* hashes) {
  cylon_tpu::parallel_rows(rows, cylon_tpu::kRowsPerThread, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) hashes[i] = 1;
    for (int32_t c = 0; c < ncols; c++) {
      const CtHashCol& col = cols[c];
      const uint8_t* base = static_cast<const uint8_t*>(col.data);
      for (int64_t i = lo; i < hi; i++) {
        int len = col.width;
        const uint8_t* p = base + i * static_cast<int64_t>(col.width);
        if (col.dtype == CT_STRING && col.lengths) len = col.lengths[i];
        uint32_t h = cylon_tpu::murmur3_x86_32(p, len, cylon_tpu::kSeed);
        hashes[i] = 31U * hashes[i] + h;
      }
    }
  });
}

// targets[i] = hashes[i] % world (mask when world is 2^k — reference:
// arrow_partition_kernels.hpp:60-70); also fills the per-target histogram.
void ct_partition_targets(const uint32_t* hashes, int64_t rows, int32_t world,
                          uint32_t* targets, int64_t* histogram) {
  std::memset(histogram, 0, sizeof(int64_t) * world);
  bool pow2 = (world & (world - 1)) == 0;
  uint32_t mask = static_cast<uint32_t>(world - 1);
  std::vector<std::vector<int64_t>> partials;
  std::mutex m;
  cylon_tpu::parallel_rows(rows, cylon_tpu::kRowsPerThread, [&](int64_t lo, int64_t hi) {
    std::vector<int64_t> hist(world, 0);
    if (pow2) {
      for (int64_t i = lo; i < hi; i++) {
        uint32_t t = hashes[i] & mask;
        targets[i] = t;
        hist[t]++;
      }
    } else {
      for (int64_t i = lo; i < hi; i++) {
        uint32_t t = hashes[i] % static_cast<uint32_t>(world);
        targets[i] = t;
        hist[t]++;
      }
    }
    std::lock_guard<std::mutex> g(m);
    partials.push_back(std::move(hist));
  });
  for (const auto& hist : partials)
    for (int32_t w = 0; w < world; w++) histogram[w] += hist[w];
}

uint32_t ct_murmur3_x86_32(const void* data, int32_t len, uint32_t seed) {
  return cylon_tpu::murmur3_x86_32(data, len, seed);
}

}  // extern "C"
