// Shared thread fan-out over row ranges (used by csv.cpp and hashing.cpp).
#ifndef CYLON_TPU_PARALLEL_HPP
#define CYLON_TPU_PARALLEL_HPP

#include <algorithm>
#include <cstdint>
#include <thread>
#include <vector>

namespace cylon_tpu {

inline int pick_threads(int64_t rows, int64_t rows_per_thread) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  int64_t by_work = rows / rows_per_thread;
  if (by_work < 1) by_work = 1;
  return static_cast<int>(by_work < hw ? by_work : hw);
}

template <typename F>
void parallel_rows(int64_t rows, int64_t rows_per_thread, F&& body) {
  int nthreads = pick_threads(rows, rows_per_thread);
  if (nthreads <= 1) {
    body(0, rows);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(nthreads);
  int64_t chunk = (rows + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; t++) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(lo + chunk, rows);
    if (lo >= hi) break;
    ts.emplace_back([&, lo, hi] { body(lo, hi); });
  }
  for (auto& t : ts) t.join();
}

}  // namespace cylon_tpu

#endif  // CYLON_TPU_PARALLEL_HPP
