// Tracking host memory pool — native analog of the reference's
// MemoryPool/ProxyMemoryPool abstraction (cpp/src/cylon/ctx/memory_pool.hpp:
// 25-66, ctx/arrow_memory_pool_utils.hpp): an allocator handle with
// bytes-allocated / max-memory accounting that the CSV reader and registry
// allocate through.  Device (HBM) memory is owned by XLA; this pool covers
// host staging buffers.
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <map>
#include <mutex>
#include <new>

extern "C" {

struct CtPool {
  std::atomic<int64_t> bytes{0};
  std::atomic<int64_t> peak{0};
  std::atomic<int64_t> allocations{0};
};

CtPool* ct_pool_create() { return new CtPool(); }

void ct_pool_destroy(CtPool* pool) { delete pool; }

void* ct_pool_alloc(CtPool* pool, int64_t size) {
  // size prefix so frees can be accounted without a side table
  void* raw = std::malloc(static_cast<size_t>(size) + 16);
  if (!raw) return nullptr;
  *static_cast<int64_t*>(raw) = size;
  if (pool) {
    int64_t now = pool->bytes.fetch_add(size) + size;
    pool->allocations.fetch_add(1);
    int64_t prev = pool->peak.load();
    while (now > prev && !pool->peak.compare_exchange_weak(prev, now)) {
    }
  }
  return static_cast<char*>(raw) + 16;
}

void ct_pool_free(CtPool* pool, void* ptr) {
  if (!ptr) return;
  void* raw = static_cast<char*>(ptr) - 16;
  int64_t size = *static_cast<int64_t*>(raw);
  if (pool) pool->bytes.fetch_sub(size);
  std::free(raw);
}

int64_t ct_pool_bytes_allocated(CtPool* pool) { return pool->bytes.load(); }
int64_t ct_pool_max_memory(CtPool* pool) { return pool->peak.load(); }
int64_t ct_pool_num_allocations(CtPool* pool) {
  return pool->allocations.load();
}

}  // extern "C"
