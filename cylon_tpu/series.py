"""Series: one named, typed column.

TPU-native analog of PyCylon's Series (reference:
python/pycylon/series.py:25-76 — a named Column wrapper with id/data/dtype/
shape accessors and scalar indexing).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from . import dtypes
from .column import Column, to_numpy as _col_to_numpy
from .status import Code, CylonError


class Series:
    """reference: series.py:25-76."""

    def __init__(self, series_id: Optional[str] = None, data=None,
                 data_type: Optional[dtypes.DataType] = None, *,
                 column: Optional[Column] = None, row_count: Optional[int] = None):
        from .column import from_numpy

        self._id = series_id or "s"
        if column is not None:
            if row_count is None:
                raise CylonError(
                    Code.Invalid,
                    "Series over a Column needs row_count (capacity includes "
                    "zeroed padding rows)")
            self._column = column
            self._count = int(row_count)
        else:
            arr = np.asarray(data)
            self._column = from_numpy(arr, dtype=data_type)
            self._count = len(arr)

    @property
    def id(self) -> str:
        return self._id

    @property
    def name(self) -> str:
        return self._id

    @property
    def data(self) -> Column:
        return self._column

    @property
    def dtype(self) -> dtypes.DataType:
        return self._column.dtype

    @property
    def shape(self):
        return (self._count,)

    def __len__(self) -> int:
        return self._count

    def to_numpy(self) -> np.ndarray:
        return _col_to_numpy(self._column, self._count)

    def to_pandas(self):
        import pandas as pd

        return pd.Series(self.to_numpy(), name=self._id)

    def __getitem__(self, item):
        vals = self.to_numpy()
        return vals[item]

    def __repr__(self) -> str:
        return (f"Series(id={self._id!r}, dtype={self.dtype}, "
                f"len={self._count})\n{self.to_numpy()!r}")
