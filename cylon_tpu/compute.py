"""Element-wise compute over Tables: comparison / math / logical ops,
null handling, membership.

TPU-native analog of PyCylon's compute layer (reference:
python/pycylon/data/compute.pyx:29-587 — table↔scalar/array comparison ops,
math ops with division guards, is_null/invert/neg, is_in, drop_na,
unique/nunique) and the Table method surface that consumes it
(python/pycylon/data/table.pyx:1170-1598 dunders, 1599-2146
fillna/where/isnull/dropna/isin).

All ops are shard-local element-wise programs: applied directly to the
sharded global column buffers, XLA keeps the sharding and runs them on each
device's shard — no collective traffic.  Padding rows are kept zeroed so
downstream kernels' invariants hold.
"""
from __future__ import annotations

import operator
from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes
from .column import Column
from .status import Code, CylonError

Scalar = Union[int, float, bool, str, np.generic]

_CMP_OPS = {
    "eq": operator.eq, "ne": operator.ne, "lt": operator.lt,
    "gt": operator.gt, "le": operator.le, "ge": operator.ge,
}
_MATH_OPS = {
    "add": operator.add, "sub": operator.sub, "mul": operator.mul,
    "truediv": operator.truediv,
}
_LOGICAL_OPS = {"or": operator.or_, "and": operator.and_, "xor": operator.xor}


def _table(columns, row_counts, names, ctx):
    from .table import Table

    return Table(tuple(columns), row_counts, tuple(names), ctx)


def _result_col(data: jax.Array, validity: jax.Array, dt: dtypes.DataType) -> Column:
    if data.dtype == jnp.bool_:
        data = data & validity
    else:
        data = jnp.where(validity, data, jnp.zeros((), data.dtype))
    return Column(data, validity, None, dt)


def _string_word_compare(col: Column, value: str, op_name: str) -> jax.Array:
    """Lexicographic compare of a string column against a scalar, on the
    packed big-endian word encoding (reference compares through arrow
    compute / object loops, compute.pyx:92-153; here it is vectorized)."""
    from .ops import keys as keys_mod

    words = keys_mod.pack_string_words(col.data)
    enc = value.encode("utf-8")
    width = col.data.shape[1]
    buf = np.zeros((max(width, len(enc)),), np.uint8)
    buf[:len(enc)] = np.frombuffer(enc, np.uint8)
    if len(enc) > width:
        # scalar longer than the column's padded width: equal-prefix rows
        # compare less-than
        pass
    padded = np.zeros(((len(buf) + 7) // 8 * 8,), np.uint8)
    padded[:len(buf)] = buf
    svals = padded.reshape(-1, 8).astype(np.uint64)
    shifts = np.array([56, 48, 40, 32, 24, 16, 8, 0], np.uint64)
    swords = (svals << shifts).sum(axis=1, dtype=np.uint64)

    lt = jnp.zeros(col.data.shape[:1], bool)
    gt = jnp.zeros(col.data.shape[:1], bool)
    nw = max(len(words), len(swords))
    for i in range(nw):
        w = words[i] if i < len(words) else jnp.zeros_like(words[0])
        s = jnp.uint64(swords[i]) if i < len(swords) else jnp.uint64(0)
        undecided = ~(lt | gt)
        lt = lt | (undecided & (w < s))
        gt = gt | (undecided & (w > s))
    eq = ~(lt | gt)
    return {"eq": eq, "ne": ~eq, "lt": lt, "gt": gt,
            "le": lt | eq, "ge": gt | eq}[op_name]


def _col_compare(col: Column, other, op_name: str, other_col: Optional[Column]) -> Column:
    op = _CMP_OPS[op_name]
    if other_col is not None:
        if col.is_string != other_col.is_string:
            raise CylonError(Code.Invalid, "cannot compare string and numeric")
        if col.is_string:
            raise CylonError(Code.Invalid,
                             "string column-vs-column compare not supported")
        data = op(col.data, other_col.data)
        validity = col.validity & other_col.validity
        return _result_col(data, validity, dtypes.bool_)
    if isinstance(other, str):
        if not col.is_string:
            raise CylonError(Code.Invalid, f"cannot compare {col.dtype} to str")
        data = _string_word_compare(col, other, op_name)
        return _result_col(data, col.validity, dtypes.bool_)
    if col.is_string:
        raise CylonError(Code.Invalid, "cannot compare string column to number")
    # rely on jnp weak-type promotion: int column vs 2.5 compares in float
    data = op(col.data, other)
    return _result_col(data, col.validity, dtypes.bool_)


def _col_math(col: Column, other, op_name: str, other_col: Optional[Column]) -> Column:
    if col.is_string or (other_col is not None and other_col.is_string):
        raise CylonError(Code.Invalid, "arithmetic on string columns")
    op = _MATH_OPS[op_name]
    if other_col is not None:
        validity = col.validity & other_col.validity
        a, b = col.data, other_col.data
        if op_name == "truediv":
            a = a.astype(jnp.result_type(a.dtype, jnp.float32))
            validity = validity & (b != 0)
            b = jnp.where(b == 0, jnp.ones((), b.dtype), b)
        data = op(a, b)
    else:
        # division guard (reference: compute.pyx:215-239 division_op raises
        # on a zero divisor)
        if op_name == "truediv" and not isinstance(other, jax.Array) and other == 0:
            raise CylonError(Code.Invalid, "division by zero")
        a = col.data
        if op_name == "truediv":
            a = a.astype(jnp.result_type(a.dtype, jnp.float32))
        # weak-type promotion: int column + 2.5 promotes to float
        data = op(a, other)
        validity = col.validity
    return _result_col(data, validity, dtypes.from_numpy_dtype(data.dtype))


def _broadcast_other(table, other):
    """Resolve ``other`` into per-column partners (None = scalar path)."""
    from .table import Table

    if isinstance(other, Table):
        if len(other.columns) != len(table.columns):
            raise CylonError(Code.Invalid, "column count mismatch")
        if other.capacity != table.capacity:
            raise CylonError(Code.Invalid, "row capacity mismatch")
        return other.columns
    return None


def _elementwise(table, other, op_name: str, kernel: Callable):
    others = _broadcast_other(table, other)
    cols = []
    for i, c in enumerate(table.columns):
        oc = others[i] if others is not None else None
        cols.append(kernel(c, other, op_name, oc))
    return _table(cols, table.row_counts, table.names, table.ctx)


# -- public op surface (reference: compute.pyx cpdef functions) -------------

def compare(table, other, op_name: str):
    return _elementwise(table, other, op_name, _col_compare)


def math_op(table, other, op_name: str):
    """reference: compute.pyx:240-274 math_op/add/subtract/multiply/divide."""
    return _elementwise(table, other, op_name, _col_math)


def add(table, value):
    return math_op(table, value, "add")


def subtract(table, value):
    return math_op(table, value, "sub")


def multiply(table, value):
    return math_op(table, value, "mul")


def divide(table, value):
    return math_op(table, value, "truediv")


def logical_op(table, other, op_name: str):
    """reference: table.pyx:1375-1442 __or__/__and__ (bool tables only)."""
    others = _broadcast_other(table, other)
    op = _LOGICAL_OPS[op_name]
    cols = []
    for i, c in enumerate(table.columns):
        if c.dtype.type != dtypes.Type.BOOL:
            raise CylonError(Code.Invalid,
                             f"logical op on non-bool column {table.names[i]}")
        if others is not None:
            oc = others[i]
            if oc.dtype.type != dtypes.Type.BOOL:
                raise CylonError(Code.Invalid, "logical op on non-bool column")
            data = op(c.data, oc.data)
            validity = c.validity & oc.validity
        else:
            data = op(c.data, bool(other))
            validity = c.validity
        cols.append(_result_col(data, validity, dtypes.bool_))
    return _table(cols, table.row_counts, table.names, table.ctx)


def invert(table):
    """reference: compute.pyx:174-193 (bool tables only)."""
    cols = []
    for i, c in enumerate(table.columns):
        if c.dtype.type != dtypes.Type.BOOL:
            raise CylonError(Code.Invalid,
                             f"invert on non-bool column {table.names[i]}")
        cols.append(_result_col(~c.data, c.validity, dtypes.bool_))
    return _table(cols, table.row_counts, table.names, table.ctx)


def neg(table):
    """reference: compute.pyx:194-214."""
    cols = []
    for c in table.columns:
        if c.is_string:
            raise CylonError(Code.Invalid, "neg on string column")
        cols.append(_result_col(-c.data, c.validity, c.dtype))
    return _table(cols, table.row_counts, table.names, table.ctx)


def is_null(table):
    """bool table: True where value is missing (reference: compute.pyx:158-173
    is_null, table.pyx:1736 isnull).  Padding rows read False."""
    cols = []
    for c in table.columns:
        live = _live(table, c)
        cols.append(Column((~c.validity) & live,
                           jnp.ones(c.validity.shape, bool), None, dtypes.bool_))
    return _table(cols, table.row_counts, table.names, table.ctx)


def fillna(table, fill_value: Scalar):
    """reference: table.pyx:1653-1684."""
    cols = []
    for c in table.columns:
        # only fill type-compatible columns; others pass through unchanged
        # (pandas fillna semantics)
        if c.is_string != isinstance(fill_value, str):
            cols.append(c)
            continue
        if c.is_string:
            enc = np.frombuffer(fill_value.encode("utf-8"), np.uint8)
            width = c.data.shape[1]
            if len(enc) > width:
                raise CylonError(Code.Invalid,
                                 f"fill string longer than column width {width}")
            row = np.zeros((width,), np.uint8)
            row[:len(enc)] = enc
            data = jnp.where(c.validity[:, None], c.data, jnp.asarray(row))
            lengths = jnp.where(c.validity, c.lengths, len(enc))
            cols.append(Column(data, jnp.ones(c.validity.shape, bool), lengths,
                               c.dtype))
        else:
            data = jnp.where(c.validity, c.data,
                             jnp.asarray(fill_value, c.data.dtype))
            cols.append(Column(data, jnp.ones(c.validity.shape, bool), None,
                               c.dtype))
    # padding rows of filled columns must stay zeroed/invalid for kernels
    return _mask_padding(_table(cols, table.row_counts, table.names, table.ctx))


def where(table, condition, other: Optional[Scalar] = None):
    """Keep values where ``condition`` holds, else ``other`` (null when
    ``other`` is None) — reference: table.pyx:1685-1735."""
    from .table import Table

    if not isinstance(condition, Table):
        raise CylonError(Code.Invalid, "where() condition must be a Table")
    masks = condition.columns
    if len(masks) != len(table.columns):
        raise CylonError(Code.Invalid, "condition column count mismatch")
    cols = []
    for c, m in zip(table.columns, masks):
        if m.dtype.type != dtypes.Type.BOOL:
            raise CylonError(Code.Invalid, "condition must be boolean")
        keep = m.data & m.validity
        if other is None:
            validity = c.validity & keep
            data = c.data
        else:
            if c.is_string:
                raise CylonError(Code.Invalid, "where(other=) on string column")
            # mask-False rows take `other` unconditionally, including rows
            # that were null (reference: table.pyx where(); pandas semantics)
            validity = c.validity | ~keep
            data = jnp.where(keep, c.data, jnp.asarray(other, c.data.dtype))
        cols.append(_result_col(data, validity, c.dtype) if not c.is_string
                    else Column(jnp.where(validity[:, None], c.data, 0),
                                validity, jnp.where(validity, c.lengths, 0),
                                c.dtype))
    # where(other=) marks mask-False rows valid — re-invalidate padding rows
    # so kernels that trust validity never see phantom `other` values
    return _mask_padding(_table(cols, table.row_counts, table.names, table.ctx))


def is_in(table, values: Sequence, skip_null: bool = True):
    """Membership test per element (reference: compute.pyx:489-511 is_in,
    table.pyx:2100-2146 isin)."""
    vals = list(values)
    null_in_vals = any(v is None for v in vals)
    cols = []
    for c in table.columns:
        live = _live(table, c)
        if c.is_string:
            svals = [v for v in vals if isinstance(v, str)]
            hit = jnp.zeros(c.data.shape[:1], bool)
            for s in svals:
                hit = hit | _string_word_compare(c, s, "eq")
        else:
            nums = [v for v in vals if not isinstance(v, str) and v is not None]
            if nums:
                # jnp.isin promotes, so 2.5 never falsely matches int 2
                hit = jnp.isin(c.data, jnp.asarray(np.asarray(nums)))
            else:
                hit = jnp.zeros(c.data.shape[:1], bool)
        hit = hit & c.validity
        if not skip_null and null_in_vals:
            hit = hit | (~c.validity)
        hit = hit & live
        cols.append(_result_col(hit, jnp.ones_like(c.validity), dtypes.bool_))
    return _table(cols, table.row_counts, table.names, table.ctx)


def drop_na(table, how: str = "any", axis: int = 0):
    """reference: compute.pyx:512-587 drop_na / table.pyx:2028-2099 dropna."""
    if axis == 1:
        counts = [(int(jnp.sum(~c.validity & _live(table, c))), i)
                  for i, c in enumerate(table.columns)]
        if how == "any":
            keep = [i for n, i in counts if n == 0]
        elif how == "all":
            live_total = table.row_count
            # a zero-row table has no all-null column (pandas keeps all)
            keep = [i for n, i in counts if live_total == 0 or n < live_total]
        else:
            raise CylonError(Code.Invalid, f"bad how={how!r}")
        return table.project(keep)
    if how not in ("any", "all"):
        raise CylonError(Code.Invalid, f"bad how={how!r}")

    names = table.names
    # stable predicate per (how, names) so the shard-map jit cache hits
    # (table.select keys on predicate identity)
    key = (how, names)
    predicate = _DROPNA_PREDICATES.get(key)
    if predicate is None:
        def predicate(env, names=names, how=how):
            ms = [env.validity(n) for n in names]
            acc = ms[0]
            for m in ms[1:]:
                acc = (acc & m) if how == "any" else (acc | m)
            return acc

        _DROPNA_PREDICATES[key] = predicate
    return table.select(predicate)


_DROPNA_PREDICATES: dict = {}


def _live(table, col: Column) -> jax.Array:
    cap = col.data.shape[0]
    if table.num_shards == 1:
        return jnp.arange(cap, dtype=jnp.int32) < table.row_counts[0]
    scap = cap // table.num_shards
    pos = jnp.arange(cap, dtype=jnp.int32) % scap
    return pos < jnp.repeat(table.row_counts, scap)


def _mask_padding(table):
    cols = []
    for c in table.columns:
        live = _live(table, c)
        validity = c.validity & live
        if c.is_string:
            data = jnp.where(validity[:, None], c.data, 0)
            lengths = jnp.where(validity, c.lengths, 0)
            cols.append(Column(data, validity, lengths, c.dtype))
        else:
            if c.data.dtype == jnp.bool_:
                data = c.data & validity
            else:
                data = jnp.where(validity, c.data, jnp.zeros((), c.data.dtype))
            cols.append(Column(data, validity, None, c.dtype))
    return _table(cols, table.row_counts, table.names, table.ctx)


def unique(table):
    """Row-distinct table (reference: compute.pyx:276-284)."""
    return table.unique()


def nunique(table) -> int:
    """Distinct row count (reference: compute.pyx:285-287)."""
    return table.unique().row_count
