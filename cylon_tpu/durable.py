"""Durable execution: journaled spill-to-disk checkpoints, cross-process
crash-resume, pass deadlines, and poison-pass quarantine.

PR 1 made device OOM a *recoverable* condition, but recovery only
survived inside one living process: a killed worker, a preempted TPU VM,
or a wedged collective still lost the whole out-of-core run.  The
reference survives failures by restarting the MPI job from source data;
the production-scale analog (ROADMAP north star) is elastic recovery —
the same spill/re-materialize-per-part shape as "Memory-efficient array
redistribution through portable collective communication" and the
bounded-retry/deadline discipline of "Scalable Distributed DNN Training
using TensorFlow and CUDA-Aware MPI" (PAPERS.md).  Three primitives:

- **run journal** (`RunJournal`) — every chunked run is fingerprinted
  (op spec x sampled input content x world/knob config,
  :func:`run_fingerprint`); each completed pass's host frame spills to
  an Arrow IPC file (``io.arrow_io.frame_to_ipc_bytes``) with a sha256
  checksum and an ATOMIC rename under ``CYLON_TPU_DURABLE_DIR``, and
  pass completion lands in an append-only ``MANIFEST.jsonl`` (fsync'd
  per line).  A fresh process re-invoking the same run loads completed
  parts from the spills and resumes mid-plan — a ``kill -9`` costs at
  most the in-flight pass.  A truncated/corrupt spill fails its
  checksum and is silently re-executed; a manifest whose recorded
  fingerprint disagrees with the run's is refused outright (stale
  spills never leak into a different run's output).

- **pass deadlines** (:func:`pass_deadline`) — a watchdog thread armed
  per pass fires ``deadline.fired`` (obs instant + metric) the moment
  ``CYLON_TPU_PASS_DEADLINE_S`` elapses, and the pass is classified
  `Code.Timeout` through the existing `Status` taxonomy when control
  returns, which the streaming loop retries like any transient.  The
  watchdog cannot preempt a wedged native call (nothing host-side can);
  it guarantees the hang is *visible* in the trace in real time and
  *classified* — never mistaken for a bug or an OOM.

- **poison-pass quarantine** — a part that fails the same way
  ``CYLON_TPU_QUARANTINE_AFTER`` consecutive times is isolated into the
  run report (``stats["quarantined"]`` + a manifest record) instead of
  wedging refinement forever; 0 (default) preserves the PR-1 fail-fast
  behavior.  Only classified-recoverable codes (OOM / transient /
  timeout) are quarantinable — a TypeError stays the bug it is.

Everything here is host-side (no jax import, no traced code), so the
jaxpr collective-budget goldens are untouched by construction and all
of it is deterministic-testable on CPU: the ``killhard`` fault kind
(``os._exit`` mid-journal) and ``journal_corrupt`` (truncates the last
committed spill) drive subprocess crash-resume tests, ``hang`` sleeps a
pass past its deadline (tests/test_durable.py).
"""
from __future__ import annotations

import contextlib
import errno
import hashlib
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import config
from . import durable_lease
from .obs import metrics as obs_metrics
from .obs import spans as obs_spans
from .obs import tracectx
from .status import Code, CylonError

log = logging.getLogger("cylon_tpu")

MANIFEST = "MANIFEST.jsonl"

#: marker file (PR 19) exempting a run dir from the size-cap LRU GC:
#: live stream state (a StreamTable's batch log, a standing query's
#: partial-aggregate spills) is consulted on EVERY refresh, and evicting
#: it between refreshes silently degrades each refresh to a full
#: recompute — so a pinned run is skipped by ``gc_journal`` even when it
#: is the LRU victim.  Honored UNDER the GC lease (re-checked per victim
#: immediately before eviction, like the freshen re-read), so a pin
#: racing a concurrent replica's sweep still protects the run.
PINNED = "PINNED"

#: advisory cross-process walker lease (journal root) — the ONE
#: implementation lives in `durable_lease` (stdlib-only so
#: tools/journal_fsck.py can load it by file path); re-exported here for
#: the PR-16 call sites and tests
GC_LOCK = durable_lease.GC_LOCK
_GC_LEASE_TTL_S = durable_lease.LEASE_TTL_S

#: minimum seconds between load-time manifest-mtime freshens (the LRU
#: clock a long replay must keep advancing under the shared journal)
_FRESHEN_MIN_S = 5.0


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def durable_dir() -> str:
    """Journal root (``CYLON_TPU_DURABLE_DIR``); empty disables."""
    return str(config.knob("CYLON_TPU_DURABLE_DIR"))


def enabled() -> bool:
    return bool(durable_dir())


def deadline_s() -> float:
    """Per-pass wall-clock budget (``CYLON_TPU_PASS_DEADLINE_S``);
    0 (default) disables the watchdog."""
    return max(0.0, float(config.knob("CYLON_TPU_PASS_DEADLINE_S")))


def quarantine_after() -> int:
    """Consecutive same-code failures before a part is quarantined
    (``CYLON_TPU_QUARANTINE_AFTER``); 0 (default) disables."""
    return max(0, int(config.knob("CYLON_TPU_QUARANTINE_AFTER")))


def cap_bytes() -> int:
    """Journal size cap (``CYLON_TPU_DURABLE_CAP_BYTES``); 0 (default)
    means unbounded — the pre-PR-7 grow-without-bound behavior."""
    return max(0, int(config.knob("CYLON_TPU_DURABLE_CAP_BYTES")))


def quota_bytes() -> int:
    """Hard disk budget for NEW spill writes
    (``CYLON_TPU_DURABLE_QUOTA_BYTES``); 0 (default) disables.  Unlike
    ``cap_bytes`` (which the GC enforces *after* the fact by evicting),
    the quota refuses the write up front — the run degrades to
    journal-off execution instead of filling a shared disk."""
    return max(0, int(config.knob("CYLON_TPU_DURABLE_QUOTA_BYTES")))


def replication_factor() -> int:
    """Target copies of every completed run across the fleet's journal
    roots (``CYLON_TPU_DURABLE_RF``, default 2).  1 disables anti-entropy
    replication entirely — byte-identical to the PR-19 single-root
    behavior (pinned by tests).  Only meaningful when replicas journal to
    DISTINCT roots; replicas sharing one filesystem root are one copy."""
    return max(1, int(config.knob("CYLON_TPU_DURABLE_RF")))


def scrub_interval_s() -> float:
    """Seconds between background integrity-scrub passes
    (``CYLON_TPU_SCRUB_S``); 0 (default) disables the scrubber thread —
    corruption is then detected lazily at load time, the pre-PR-20
    behavior.  ``durable_sync.scrub_once`` can always be called
    directly (tools/journal_fsck.py is the offline twin)."""
    return max(0.0, float(config.knob("CYLON_TPU_SCRUB_S")))


# ---------------------------------------------------------------------------
# run fingerprinting
# ---------------------------------------------------------------------------

_OBJ_SLAB = 1 << 20   # object-column elements decoded per hashing slab
_MIX_SLAB = 1 << 22   # u64 words mixed per vectorized slab (32 MB)


def _mix_u64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (uint64 wraparound arithmetic) — local twin
    of exec._mix_u64 (importing exec here would be a cycle)."""
    x = np.asarray(x, np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _update_spec(h, obj) -> None:
    """Feed a canonical encoding of a primitive/tuple spec into ``h`` —
    type-tagged so ("1",) and (1,) hash apart."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        h.update(f"<{type(obj).__name__}:{obj!r}>".encode())
        return
    if isinstance(obj, (tuple, list)):
        h.update(b"<seq[")
        for item in obj:
            _update_spec(h, item)
        h.update(b"]>")
        return
    raise CylonError(Code.Invalid,
                     f"unhashable fingerprint spec element {type(obj)}")


def _update_array(h, name: str, a: np.ndarray) -> None:
    """Fold one input column into the fingerprint with FULL content
    coverage — changing ANY element (at any index) changes the
    fingerprint, so a stale journal can never silently serve a modified
    run.  Fixed-width columns reduce through a position-mixed splitmix64
    xor-fold at memory bandwidth in bounded slabs (no big transients);
    object columns hash their decoded codepoints slab-wise (str()
    coercion is deterministic for the payloads frames carry: np scalars
    / str / bytes / None)."""
    a = np.asarray(a)
    h.update(f"|col:{name}:{a.dtype.str}:{a.shape}".encode())
    if a.size == 0:
        return
    flat = a.reshape(-1)
    if a.dtype.kind == "O":
        for lo in range(0, flat.size, _OBJ_SLAB):
            sl = flat[lo:lo + _OBJ_SLAB]
            # per-element kind tags disambiguate what str() coercion
            # conflates: None vs the literal string "None", and bytes
            # vs a str that happens to equal their repr
            tags = np.fromiter(
                (0 if x is None
                 else 1 if isinstance(x, (str, np.str_))
                 else 2 if isinstance(x, (bytes, np.bytes_))
                 else 3 for x in sl), np.uint8, count=len(sl))
            h.update(tags.tobytes())
            h.update(np.asarray(sl.astype("U")).tobytes())
        return
    b = np.ascontiguousarray(flat).view(np.uint8).reshape(-1)
    n_words = -(-b.size // 8)
    acc = np.uint64(0)
    for lo in range(0, n_words, _MIX_SLAB):
        hi = min(lo + _MIX_SLAB, n_words)
        chunk = b[lo * 8:min(hi * 8, b.size)]
        if len(chunk) < (hi - lo) * 8:  # zero-pad the final partial word
            chunk = np.concatenate(
                [chunk, np.zeros((hi - lo) * 8 - len(chunk), np.uint8)])
        words = np.ascontiguousarray(chunk).view(np.uint64)
        pos = np.arange(lo, hi, dtype=np.uint64)
        acc = acc ^ np.uint64(np.bitwise_xor.reduce(
            _mix_u64(words ^ _mix_u64(pos))))
    h.update(int(acc).to_bytes(8, "little"))


def run_fingerprint(op: str, spec, frames: Sequence[Tuple[Sequence[str],
                                                          Dict]]) -> str:
    """Hex fingerprint of one chunked run: op kind x plan/op spec x every
    input column's (sampled) content x the trace-knob configuration that
    can change results.  Two invocations share a journal exactly when
    this agrees."""
    h = hashlib.sha256()
    h.update(f"cylon_tpu.durable.v1|{op}".encode())
    # opaque salt (CYLON_TPU_FP_SALT): `bench.py --fresh` sets a
    # per-invocation value so a headline bench can never be served from
    # the journal result cache (the BENCH_r03–r05 stale cache echo);
    # empty keeps fingerprints stable across runs
    salt = config.knob("CYLON_TPU_FP_SALT")
    if salt:
        h.update(f"|salt:{salt}".encode())
    _update_spec(h, spec)
    # trace-scope knobs change the traced computation, hence the results
    # a resumed run must match; raw values, like the jit-plan cache keys
    _update_spec(h, [list(kv) for kv in config.trace_cache_token()])
    for names, arrs in frames:
        h.update(b"|frame")
        for name in names:
            _update_array(h, str(name), np.asarray(arrs[name]))
    return h.hexdigest()


# ---------------------------------------------------------------------------
# the run journal
# ---------------------------------------------------------------------------

# most recently opened journal — the handle the `journal_corrupt` fault
# kind corrupts (deterministic crash-resume tests, resilience.fault_point)
_LAST_JOURNAL: Optional["RunJournal"] = None


class RunJournal:
    """Append-only manifest + checksummed Arrow IPC spills for one
    fingerprinted run under ``<CYLON_TPU_DURABLE_DIR>/<fingerprint>/``.

    Crash-safety contract: a pass is *completed* iff its manifest line
    was fully written AND its spill file matches the recorded sha256.
    The spill is written first (tmp file + fsync + atomic ``os.replace``),
    the manifest line second (fsync'd append), so every crash point
    leaves either a resumable state or an orphan spill that is simply
    re-executed — never a manifest entry pointing at absent/garbage data
    that would silently corrupt a resumed run (garbage fails the
    checksum and is re-executed too)."""

    def __init__(self, root: str, fingerprint: str, op: str,
                 world: Optional[int] = None, epoch: Optional[int] = None):
        self.fingerprint = fingerprint
        self.op = op
        self.dir = os.path.join(root, fingerprint)
        # elastic provenance (PR 6): the membership world size and epoch
        # this PROCESS is journaling under.  Part ids are global
        # positions in the key-domain plan — world-INDEPENDENT — so the
        # fingerprint deliberately excludes world/epoch (a shard
        # journaled at world W must be consumed, not refused, at world
        # W-1); world/epoch ride the manifest as per-pass provenance so
        # the shrink history is auditable after the fact.
        self.world = world
        self.epoch = epoch
        self._passes: Dict[Tuple[int, int], dict] = {}
        self._quarantined: List[dict] = []
        self._last_committed: Optional[str] = None
        self._spill_disabled = False
        self._degraded = False
        self._done: Optional[dict] = None
        # lazy journal-root byte inventory for the quota guard (scanned
        # once per journal, then tracked incrementally for our own writes)
        self._root_seen_bytes: Optional[int] = None
        self._freshened_at = 0.0

    # -- open / manifest replay -----------------------------------------

    @classmethod
    def open_run(cls, fingerprint: str, op: str,
                 world: Optional[int] = None,
                 epoch: Optional[int] = None) -> Optional["RunJournal"]:
        """Open (creating if needed) the journal for ``fingerprint``, or
        None when durability is disabled — or when the journal root is
        unusable (unwritable, not a directory, IO errors): best-effort
        durability must never fail the run it exists to protect.  The
        foreign-fingerprint refusal is NOT best-effort and propagates.
        Replays the manifest so ``load_pass`` can serve completed
        parts."""
        global _LAST_JOURNAL
        root = durable_dir()
        if not root:
            return None
        j = cls(root, fingerprint, op, world=world, epoch=epoch)
        try:
            j._open()
        except OSError as e:
            obs_metrics.counter_add("durable.journal_errors")
            log.warning("durable: cannot open journal under %r (%s: %s); "
                        "journaling disabled for this run", root,
                        type(e).__name__, e)
            return None
        _LAST_JOURNAL = j
        return j

    def _open(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, MANIFEST)
        header = None
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as fh:
                for raw in fh:
                    try:
                        entry = json.loads(raw)
                    except ValueError:
                        # a torn tail line is the expected shape of a
                        # crash mid-append; everything before it stands
                        break
                    kind = entry.get("kind")
                    if kind == "run":
                        header = entry
                    elif kind == "pass":
                        self._passes[(int(entry["level"]),
                                      int(entry["part"]))] = entry
                    elif kind == "quarantine":
                        self._quarantined.append(entry)
                    elif kind == "done":
                        self._done = entry
        if header is not None and header.get("fingerprint") != self.fingerprint:
            # the dir is named by the fingerprint, so this means tampering
            # or a collision — stale spills must never serve another run
            raise CylonError(
                Code.Invalid,
                f"durable journal {self.dir} records fingerprint "
                f"{header.get('fingerprint')!r} != this run's "
                f"{self.fingerprint!r}: refusing stale spills")
        if header is None:
            entry = {"kind": "run", "fingerprint": self.fingerprint,
                     "op": self.op}
            if self.world is not None:
                entry["world"] = int(self.world)
            if self.epoch is not None:
                entry["epoch"] = int(self.epoch)
            try:
                self._append(entry)
            except OSError as e:
                # journaling is best-effort: an unwritable journal must
                # never fail the run it was meant to protect — loads (the
                # resume path) still work, new spills are skipped
                self._spill_disabled = True
                log.warning("durable: manifest header write failed (%s: "
                            "%s); journaling disabled for this run",
                            type(e).__name__, e)
        # LRU clock for the size-cap GC: every open (a fresh run, a
        # resume, a cache serve) freshens the manifest mtime, so eviction
        # order is least-recently-USED, not least-recently-written
        with contextlib.suppress(OSError):
            os.utime(path)
        if self._passes:
            log.info("durable: resuming run %s from %d journaled passes",
                     self.fingerprint[:12], len(self._passes))
            obs_spans.instant("durable.resume", op=self.op,
                              journaled_passes=len(self._passes))
            obs_metrics.counter_add("durable.resumes")

    def _append(self, entry: dict) -> None:
        with open(os.path.join(self.dir, MANIFEST), "a",
                  encoding="utf-8") as fh:
            fh.write(json.dumps(entry, sort_keys=True) + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    # -- pass completion --------------------------------------------------

    def completed_count(self) -> int:
        return len(self._passes)

    def completed(self, level: int, part: int) -> bool:
        """True when the pass has a manifest record (cheap — no spill
        read; the checksum is still verified at load time)."""
        return (int(level), int(part)) in self._passes

    def record_pass(self, level: int, part: int, frame: Dict[str, np.ndarray],
                    rows: int,
                    provenance: Optional[dict] = None) -> bool:
        """Spill one completed pass's host frame and commit it to the
        manifest; True iff the pass is now durably journaled.  Spill/
        serialize failures disable journaling for the rest of the run
        (counted, warned) — durability is best-effort and must never
        fail a pass that already computed.

        ``provenance`` (PR 19): an optional JSON-safe dict folded into
        the manifest pass entry — the streaming layer records each
        micro-batch's id, row count, content fingerprint and state
        schema version here, so a resumed process can audit WHAT a pass
        holds without decoding the spill (``pass_provenance``)."""
        if self._spill_disabled:
            return False
        from . import resilience
        from .io import arrow_io

        name = f"pass_L{level}_P{part}.arrow"
        path = os.path.join(self.dir, name)
        with obs_spans.span("durable.spill", level=level, part=part,
                            rows=rows):
            try:
                payload = arrow_io.frame_to_ipc_bytes(frame)
            except Exception as e:
                self._spill_failed("serialize", name, e)
                return False
            if self._quota_exceeded(len(payload)):
                self._degrade("quota", name,
                              f"CYLON_TPU_DURABLE_QUOTA_BYTES="
                              f"{quota_bytes()} would be exceeded by "
                              f"{len(payload)} more bytes")
                return False
            digest = hashlib.sha256(payload).hexdigest()
            tmp = path + f".tmp.{os.getpid()}"
            try:
                # the injected ENOSPC site (fault kind `disk_full`) sits
                # INSIDE the guarded region: a full disk — real or
                # seeded — degrades the run, it never fails the pass
                resilience.fault_point("journal_spill")
                with open(tmp, "wb") as fh:
                    fh.write(payload)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, path)
            except OSError as e:
                with contextlib.suppress(OSError):
                    os.remove(tmp)
                self._spill_failed("write", name, e)
                return False
            self._last_committed = path
            # the killhard crash window the subprocess tests aim at:
            # spill durable, completion not yet recorded -> the pass
            # re-runs on resume (at-least-once, never lost)
            resilience.fault_point("journal_commit")
            entry = {"kind": "pass", "level": int(level), "part": int(part),
                     "rows": int(rows), "file": name, "sha256": digest,
                     "bytes": len(payload)}
            if provenance:
                entry["provenance"] = dict(provenance)
            if self.world is not None:
                entry["world"] = int(self.world)
            if self.epoch is not None:
                entry["epoch"] = int(self.epoch)
            try:
                self._append(entry)
            except OSError as e:
                self._spill_failed("manifest commit", name, e)
                return False
            self._passes[(int(level), int(part))] = entry
        obs_metrics.counter_add("durable.passes_journaled")
        obs_metrics.counter_add("durable.spill_bytes", len(payload))
        return True

    def _spill_failed(self, stage: str, name: str, e: Exception) -> None:
        if getattr(e, "errno", None) == errno.ENOSPC:
            # a full shared disk is a fleet condition, not a bug: classify
            # it ResourceExhausted and degrade instead of counting it with
            # the anonymous spill errors an operator would page on
            self._degrade(stage, name, f"disk full (ENOSPC): {e}")
            return
        self._spill_disabled = True
        obs_metrics.counter_add("durable.spill_errors")
        log.warning("durable: %s of %s failed (%s: %s); journaling disabled "
                    "for the rest of this run", stage, name,
                    type(e).__name__, e)

    def _quota_exceeded(self, nbytes: int) -> bool:
        """True when writing ``nbytes`` more would push the journal root
        past ``CYLON_TPU_DURABLE_QUOTA_BYTES``.  The root inventory is
        scanned once per journal and then tracked incrementally for this
        writer's own spills — best-effort under concurrent writers, which
        is fine: the quota is a budget, ENOSPC is the backstop."""
        q = quota_bytes()
        if q <= 0:
            return False
        if self._root_seen_bytes is None:
            root = os.path.dirname(self.dir)
            self._root_seen_bytes = sum(
                r["bytes"] for r in scan_runs(root))
        if self._root_seen_bytes + nbytes > q:
            return True
        self._root_seen_bytes += nbytes
        return False

    def _degrade(self, stage: str, name: str, why: str) -> None:
        """Degraded mode: the shared cache is out of disk (ENOSPC or the
        quota) — stop journaling for this run and keep executing.  The
        answer is still served; only durability/cache-ability is lost.
        Classified `Code.ResourceExhausted` in the trace, counted under
        ``durable.degraded`` — distinct from ``durable.spill_errors``
        (unexpected IO bugs) so fleet dashboards can alert on disk
        pressure specifically."""
        self._spill_disabled = True
        if self._degraded:
            return
        self._degraded = True
        obs_metrics.counter_add("durable.degraded")
        obs_spans.instant("durable.degraded", stage=stage, spill=name,
                          code=Code.ResourceExhausted.name, reason=why)
        log.warning("durable: %s of %s hit the disk budget (%s); run "
                    "degrades to journal-off execution [%s]", stage, name,
                    why, Code.ResourceExhausted.name)

    def load_pass(self, level: int, part: int):
        """(frame, rows) for a journaled pass, or None when the pass is
        not recorded — or its spill is missing/truncated/corrupt (checksum
        mismatch) AND no peer holds a good copy, in which case the record
        is dropped so the pass simply re-executes.

        Read-repair (PR 20): a local checksum failure first degrades to
        fetching the spill from a peer replica's journal
        (`durable_sync.attempt_read_repair`) — the fetched bytes must
        match the SAME manifest sha256, are rewritten locally tmp+fsync+
        rename, and are served bit-identically.  A request never fails
        over corruption any replica can still repair; only when no peer
        holds a good copy does the pass fall back to re-execution."""
        entry = self._passes.get((int(level), int(part)))
        if entry is None:
            return None
        from .io import arrow_io

        # LRU clock, load-time half: `_open` freshens the manifest mtime
        # once, but under the SHARED fleet journal a long replay keeps
        # reading spills for minutes after its open — without periodic
        # re-freshening a concurrent replica's GC sees a stale clock and
        # evicts the hottest run first (throttled: one utime per
        # _FRESHEN_MIN_S, not per pass)
        self._freshen()
        path = os.path.join(self.dir, entry["file"])
        with obs_spans.span("durable.load", level=level, part=part):
            why = None
            try:
                with open(path, "rb") as fh:
                    payload = fh.read()
            except OSError as e:
                payload, why = None, f"unreadable spill: {e}"
            if (payload is not None
                    and hashlib.sha256(payload).hexdigest()
                    != entry["sha256"]):
                payload, why = None, "checksum mismatch (truncated/corrupt)"
            if payload is None:
                payload = self._read_repair(entry, why)
                if payload is None:
                    return self._reject(level, part, why)
            try:
                frame = arrow_io.frame_from_ipc_bytes(payload)
            except Exception as e:
                # a decode failure UNDER a passing checksum is a recorded
                # bad payload — a peer's copy would be the same bytes, so
                # repair cannot help; re-execute
                return self._reject(level, part,
                                    f"undecodable spill: "
                                    f"{type(e).__name__}: {e}")
        return frame, int(entry["rows"])

    def _read_repair(self, entry: dict, why: str) -> Optional[bytes]:
        """Fetch one bad spill's bytes from a peer journal (verified
        against OUR manifest sha256, rewritten locally) — None when no
        peer is registered or none holds a good copy.  Guarded: repair
        is an optimization over re-execution and must never raise."""
        try:
            from . import durable_sync
            return durable_sync.attempt_read_repair(
                self.dir, self.fingerprint, entry, why)
        except Exception as e:  # pragma: no cover - defensive
            log.warning("durable: read-repair attempt failed (%s: %s)",
                        type(e).__name__, e)
            return None

    def _freshen(self) -> None:
        now = time.monotonic()
        if now - self._freshened_at < _FRESHEN_MIN_S:
            return
        self._freshened_at = now
        with contextlib.suppress(OSError):
            os.utime(os.path.join(self.dir, MANIFEST))

    def _reject(self, level: int, part: int, why: str):
        self._passes.pop((int(level), int(part)), None)
        log.warning("durable: rejecting journaled pass L%d/P%d: %s "
                    "(the pass will re-execute)", level, part, why)
        obs_spans.instant("durable.spill_rejected", level=level, part=part,
                          reason=why)
        obs_metrics.counter_add("durable.spills_rejected")
        return None

    def pass_provenance(self, level: int, part: int) -> Optional[dict]:
        """The ``provenance`` dict a pass was recorded with, or None when
        the pass is absent or carried none.  Manifest-only (no spill
        read): the streaming layer's watermark replay and schema-version
        gate both decide from provenance before any decode."""
        entry = self._passes.get((int(level), int(part)))
        if entry is None:
            return None
        return entry.get("provenance")

    def parts_at_level(self, level: int) -> List[int]:
        """Sorted part ids journaled at ``level`` — the streaming
        layer's batch inventory (batch i == pass (0, i))."""
        return sorted(p for (lv, p) in self._passes if lv == int(level))

    # -- GC pinning (PR 19: live stream state) ----------------------------

    def pin(self) -> bool:
        """Exempt this run from ``gc_journal`` LRU eviction: write an
        fsync'd ``PINNED`` marker in the run dir.  Best-effort like
        every other journal write; True iff the marker is durable."""
        path = os.path.join(self.dir, PINNED)
        try:
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(json.dumps({"pid": os.getpid(),
                                     "fingerprint": self.fingerprint}) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
        except OSError as e:
            log.warning("durable: cannot pin run %s (%s: %s)",
                        self.fingerprint[:12], type(e).__name__, e)
            return False
        return True

    def unpin(self) -> None:
        """Re-admit this run to LRU eviction (stream closed/retired)."""
        with contextlib.suppress(OSError):
            os.remove(os.path.join(self.dir, PINNED))

    def pinned(self) -> bool:
        return os.path.exists(os.path.join(self.dir, PINNED))

    # -- quarantine record ------------------------------------------------

    def record_quarantine(self, level: int, part: int, code: str,
                          msg: str) -> None:
        entry = {"kind": "quarantine", "level": int(level),
                 "part": int(part), "code": code, "msg": msg}
        self._quarantined.append(entry)
        try:
            self._append(entry)
        except OSError as e:
            log.warning("durable: quarantine record failed: %s", e)

    # -- run completion (the result-cache contract) -----------------------

    def record_done(self, passes: int, rows: int) -> None:
        """Mark the run complete: every pass the plan needed is journaled
        (the streaming loop finished with nothing remaining and nothing
        quarantined).  A complete journal IS a result-cache entry — a
        repeated fingerprint replays entirely from spill.  Best-effort
        like every other write here."""
        if self._spill_disabled or self._done is not None:
            return
        entry = {"kind": "done", "passes": int(passes), "rows": int(rows)}
        try:
            self._append(entry)
        except OSError as e:
            log.warning("durable: done record failed: %s", e)
            return
        self._done = entry

    def is_complete(self) -> bool:
        """True when a prior invocation recorded the run done — the
        serving layer's cheap cache-hit probe (spill checksums are still
        verified pass-by-pass at load time)."""
        return self._done is not None


def open_run(fingerprint: str, op: str, world: Optional[int] = None,
             epoch: Optional[int] = None) -> Optional[RunJournal]:
    """Module-level convenience over :meth:`RunJournal.open_run`."""
    return RunJournal.open_run(fingerprint, op, world=world, epoch=epoch)


def scan_runs(root: Optional[str] = None) -> List[dict]:
    """Inventory of the journal root for GC/cache introspection: one dict
    per run dir — ``fingerprint``, ``bytes`` (all files), ``mtime`` (the
    manifest's, the LRU clock), ``complete`` (a ``done`` manifest record
    exists), ``pinned`` (a ``PINNED`` marker exempts the run from LRU
    eviction) — sorted least-recently-used first.  Pure filesystem walk;
    unreadable entries are skipped (a racing eviction is not an error)."""
    root = durable_dir() if root is None else root
    out: List[dict] = []
    if not root or not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        d = os.path.join(root, name)
        manifest = os.path.join(d, MANIFEST)
        if not os.path.isdir(d):
            continue
        total = 0
        complete = False
        try:
            for fn in os.listdir(d):
                with contextlib.suppress(OSError):
                    total += os.path.getsize(os.path.join(d, fn))
            mtime = os.path.getmtime(manifest) if os.path.exists(manifest) \
                else os.path.getmtime(d)
            if os.path.exists(manifest):
                with open(manifest, "r", encoding="utf-8") as fh:
                    for raw in fh:
                        try:
                            if json.loads(raw).get("kind") == "done":
                                complete = True
                        except ValueError:
                            break
        except OSError:
            continue
        out.append({"fingerprint": name, "dir": d, "bytes": total,
                    "mtime": mtime, "complete": complete,
                    "pinned": os.path.exists(os.path.join(d, PINNED))})
    out.sort(key=lambda r: (r["mtime"], r["fingerprint"]))
    return out


def read_manifest(d: str) -> Optional[dict]:
    """Structured, integrity-aware parse of one run dir's manifest (the
    scrubber's view — `RunJournal._open` keeps its own minimal replay):
    ``header`` / ``passes`` ({(level, part): entry}) / ``done`` /
    ``quarantined``, plus two corruption classifications the replay
    deliberately conflates:

    - ``torn_tail`` — the LAST line(s) fail to parse with nothing
      parseable after them: the expected shape of a crash mid-append,
      clean by contract (everything before the tear stands).
    - ``midline_corrupt`` — an unparseable line FOLLOWED by parseable
      lines: impossible under the fsync'd append-only discipline, so it
      is bitrot inside committed history; entries after the bad line
      cannot be trusted to be complete and the run must quarantine.

    None when the dir has no readable manifest at all."""
    path = os.path.join(d, MANIFEST)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            raw_lines = fh.read().splitlines()
    except OSError:
        return None
    out = {"header": None, "passes": {}, "done": None, "quarantined": [],
           "torn_tail": False, "midline_corrupt": False,
           "lines": len(raw_lines)}
    bad_seen = False
    for raw in raw_lines:
        try:
            entry = json.loads(raw)
            if not isinstance(entry, dict):
                raise ValueError("manifest line is not an object")
        except ValueError:
            bad_seen = True
            out["torn_tail"] = True
            continue
        if bad_seen:
            # a good line after a bad one: committed history was torn
            out["midline_corrupt"] = True
            out["torn_tail"] = False
            break
        kind = entry.get("kind")
        if kind == "run":
            out["header"] = entry
        elif kind == "pass":
            try:
                out["passes"][(int(entry["level"]),
                               int(entry["part"]))] = entry
            except (KeyError, TypeError, ValueError):
                out["midline_corrupt"] = True
                break
        elif kind == "quarantine":
            out["quarantined"].append(entry)
        elif kind == "done":
            out["done"] = entry
    return out


# run-digest cache: dir -> ((manifest mtime_ns, size), digest record).
# The digest is pure manifest content, so the (mtime, size) pair is a
# sound invalidation key under the fsync'd append-only discipline.
_DIGEST_CACHE: Dict[str, Tuple[Tuple[int, int], dict]] = {}
_DIGEST_CACHE_MAX = 4096


def run_digest(d: str) -> Optional[dict]:
    """Replication identity of one run dir, from the manifest ALONE (no
    spill reads — this runs on every heartbeat): ``digest`` folds the
    sorted (file, sha256) pass pairs plus the done flag, so two roots
    agree on a digest exactly when they hold the same committed content.
    Also carries ``complete`` / ``pinned`` / ``passes`` for the
    coordinator's placement math.  None for unreadable or header-less
    dirs (a mid-sync run not yet visible — by design)."""
    path = os.path.join(d, MANIFEST)
    try:
        st = os.stat(path)
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        return None
    cached = _DIGEST_CACHE.get(d)
    if cached is not None and cached[0] == key:
        rec = dict(cached[1])
        rec["pinned"] = os.path.exists(os.path.join(d, PINNED))
        return rec
    m = read_manifest(d)
    if m is None or m["header"] is None:
        return None
    h = hashlib.sha256()
    for (level, part), entry in sorted(m["passes"].items()):
        h.update(f"{level}:{part}:{entry.get('file')}:"
                 f"{entry.get('sha256')}\n".encode())
    h.update(b"done" if m["done"] is not None else b"open")
    rec = {"digest": h.hexdigest(),
           "complete": m["done"] is not None,
           "passes": len(m["passes"]),
           "bytes": sum(int(e.get("bytes", 0))
                        for e in m["passes"].values())}
    if len(_DIGEST_CACHE) >= _DIGEST_CACHE_MAX:
        _DIGEST_CACHE.clear()
    _DIGEST_CACHE[d] = (key, dict(rec))
    rec["pinned"] = os.path.exists(os.path.join(d, PINNED))
    return rec


def journal_digests(root: Optional[str] = None, cap: int = 512) -> Dict[str, dict]:
    """Per-run digests for heartbeat advertisement: fingerprint ->
    :func:`run_digest` record, most-recently-used runs first when the
    root holds more than ``cap`` (the hot runs are the ones worth
    replicating first; the rest ride later beats as the set churns)."""
    root = durable_dir() if root is None else root
    runs = scan_runs(root)
    out: Dict[str, dict] = {}
    for r in reversed(runs):  # scan_runs sorts LRU-first; advertise MRU
        if len(out) >= max(1, int(cap)):
            break
        rec = run_digest(r["dir"])
        if rec is not None:
            out[r["fingerprint"]] = rec
    return out


def _evict_run_dir(d: str) -> None:
    """Remove one run dir MANIFEST-LAST: spills go first, the manifest
    after them, the dir itself at the end.  A crash (or a concurrent
    reader) at any point sees either a manifest whose spills fail their
    checksums — so the affected passes simply re-execute — or no
    manifest at all; never a torn journal served as a result."""
    names = []
    with contextlib.suppress(OSError):
        names = os.listdir(d)
    for fn in sorted(names):
        if fn != MANIFEST:
            with contextlib.suppress(OSError):
                os.remove(os.path.join(d, fn))
    with contextlib.suppress(OSError):
        os.remove(os.path.join(d, MANIFEST))
    with contextlib.suppress(OSError):
        os.rmdir(d)


def _acquire_gc_lease(root: str) -> Optional[str]:
    """Advisory cross-process walker lease over ``root`` — delegated to
    the shared stdlib-only implementation in :mod:`durable_lease` (PR 20:
    GC, scrubber and fsck must exclude each other through ONE lease, not
    three drifting copies).  Returns the lease path, or None when another
    walker holds a lease younger than the TTL (counted
    ``durable.gc_lease_busy``)."""
    return durable_lease.acquire_lease(
        root, ttl_s=_GC_LEASE_TTL_S,
        on_busy=lambda: obs_metrics.counter_add("durable.gc_lease_busy"))


def _release_gc_lease(path: str) -> None:
    durable_lease.release_lease(path)


# fingerprint -> bool guard installed by the replication syncer (PR 20):
# True means the coordinator still counts OUR copy of this run toward
# CYLON_TPU_DURABLE_RF (holders <= RF), so LRU-evicting it here would
# silently drop the fleet below its replication target on a peer-less
# (or not-yet-caught-up) fleet.  None (default, and whenever no fleet
# syncer is attached) preserves the PR-16 behavior exactly.
_REPLICATION_GUARD = None


def set_gc_replication_guard(fn) -> None:
    """Install (or clear, with None) the fingerprint->bool guard
    ``gc_journal`` consults before evicting a run (see
    ``_REPLICATION_GUARD``).  Called by `durable_sync.JournalSyncer` from
    heartbeat replies; the guard must be cheap and non-raising."""
    global _REPLICATION_GUARD
    _REPLICATION_GUARD = fn


def gc_journal(root: Optional[str] = None,
               cap: Optional[int] = None) -> Tuple[int, int]:
    """Size-cap LRU eviction over the journal root: whole runs are
    evicted least-recently-used first until total bytes fit under
    ``CYLON_TPU_DURABLE_CAP_BYTES`` (or ``cap``).  Returns
    ``(runs_evicted, bytes_freed)``; (0, 0) when no cap is set, the root
    is unused, everything already fits, or another replica's GC holds
    the advisory lease.  The currently-open journal (an in-flight run)
    is never evicted from under its own writer.

    Fleet discipline (every replica GCs the SHARED root concurrently):
    destructive eviction runs only under the ``GC_LOCK`` lease, and each
    victim's manifest mtime is RE-READ immediately before eviction — the
    CoordLog ownership-re-read pattern — so a run that a third replica
    opened or replayed (freshening its LRU clock) after our scan is
    skipped this round instead of half-evicted under a reader.  A
    ``PINNED`` marker (live stream state, PR 19) is likewise re-checked
    per victim UNDER the lease: a pinned run is never evicted no matter
    how cold its LRU clock (``durable.gc_skipped_pinned``)."""
    root = durable_dir() if root is None else root
    cap = cap_bytes() if cap is None else max(0, int(cap))
    if not root or cap <= 0:
        return 0, 0
    runs = scan_runs(root)
    total = sum(r["bytes"] for r in runs)
    if total <= cap:
        return 0, 0
    lease = _acquire_gc_lease(root)
    if lease is None:
        return 0, 0
    live = _LAST_JOURNAL.dir if _LAST_JOURNAL is not None else None
    evicted = 0
    freed = 0
    try:
        for r in runs:
            if total - freed <= cap:
                break
            if r["dir"] == live:
                continue
            if os.path.exists(os.path.join(r["dir"], PINNED)):
                # re-checked under the lease, not trusted from the scan:
                # a stream that pinned its state after our inventory
                # must still survive this sweep
                obs_metrics.counter_add("durable.gc_skipped_pinned")
                continue
            guard = _REPLICATION_GUARD
            if guard is not None and guard(r["fingerprint"]):
                # the coordinator still counts our copy toward
                # CYLON_TPU_DURABLE_RF: evicting it would silently drop
                # the fleet below its replication target (PR 20)
                obs_metrics.counter_add("durable.gc_skipped_replication")
                continue
            manifest = os.path.join(r["dir"], MANIFEST)
            try:
                now_mtime = os.path.getmtime(manifest)
            except OSError:
                now_mtime = None  # already gone — nothing left to tear
            if now_mtime is not None and now_mtime > r["mtime"] + 1e-6:
                # freshened since our scan: a replica is using this run
                obs_metrics.counter_add("durable.gc_skipped_fresh")
                continue
            _evict_run_dir(r["dir"])
            evicted += 1
            freed += r["bytes"]
            obs_spans.instant("durable.gc_evict",
                              fingerprint=r["fingerprint"],
                              bytes=r["bytes"], complete=r["complete"])
    finally:
        _release_gc_lease(lease)
    if evicted:
        obs_metrics.counter_add("durable.gc_runs_evicted", evicted)
        obs_metrics.counter_add("durable.gc_bytes_freed", freed)
        log.info("durable: GC evicted %d run(s), %d bytes (cap %d)",
                 evicted, freed, cap)
    return evicted, freed


def _evict_last_run_spills() -> None:
    """Test hook behind the ``cache_evict_race`` fault kind: delete the
    most recently opened run's SPILL files while keeping its manifest —
    the exact window a concurrent GC eviction exposes to a reader that
    already replayed the manifest.  Every load then fails (missing
    spill) and the pass re-executes; the run must still complete."""
    j = _LAST_JOURNAL
    if j is None or not os.path.isdir(j.dir):
        return
    n = 0
    for fn in sorted(os.listdir(j.dir)):
        if fn != MANIFEST:
            with contextlib.suppress(OSError):
                os.remove(os.path.join(j.dir, fn))
                n += 1
    log.warning("durable: injected evict race removed %d spill(s) under %s",
                n, j.dir)


def _corrupt_last_spill() -> None:
    """Test hook behind the ``journal_corrupt`` fault kind: truncate the
    most recently committed spill to half its size, so its manifest
    checksum no longer matches — the corruption a resume must reject."""
    j = _LAST_JOURNAL
    path = j._last_committed if j is not None else None
    if path is None or not os.path.exists(path):
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size // 2)
    log.warning("durable: injected corruption truncated %s to %d bytes",
                path, size // 2)


def _bitrot_last_run(hit: int = 0) -> None:  # cylint: disable=CY117 -- deliberate fault injector: flips a spill byte to MANUFACTURE the bitrot CY117 guards against; verifying a checksum here would defeat the test hook
    """Test hook behind the ``bitrot`` fault kind (PR 20): XOR-flip ONE
    mid-file byte of a committed spill in the most recently opened run —
    the silent-decay failure the scrubber and read-repair exist to catch
    (vs ``journal_corrupt``'s blunt truncation).  The victim spill is
    chosen deterministically from the fault hit counter so subprocess
    chaos tests replay identically."""
    j = _LAST_JOURNAL
    if j is None or not os.path.isdir(j.dir):
        return
    spills = sorted(fn for fn in os.listdir(j.dir) if fn.endswith(".arrow"))
    if not spills:
        return
    victim = os.path.join(
        j.dir, spills[(int(hit) * 2654435761) % len(spills)])
    try:
        size = os.path.getsize(victim)
        if size == 0:
            return
        with open(victim, "r+b") as fh:
            fh.seek(size // 2)
            b = fh.read(1)
            fh.seek(size // 2)
            fh.write(bytes([b[0] ^ 0xFF]))
            fh.flush()
            os.fsync(fh.fileno())
    except OSError:
        return
    log.warning("durable: injected bitrot flipped byte %d of %s",
                size // 2, victim)


# ---------------------------------------------------------------------------
# pass deadlines
# ---------------------------------------------------------------------------

class _NullDeadline:
    __slots__ = ()

    def __enter__(self) -> "_NullDeadline":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def raise_if_fired(self) -> None:
        return None

    def accept_late(self) -> None:
        return None


_NULL_DEADLINE = _NullDeadline()


class PassDeadline:
    """Watchdog for one pass: a timer thread fires ``deadline.fired``
    (obs instant + metric) the moment ``seconds`` elapses — real-time
    visibility even while the main thread is wedged in a native call —
    and :meth:`raise_if_fired` classifies the overrun as `Code.Timeout`,
    which the streaming loop retries like any transient.

    The raise is deliberately NOT in ``__exit__``: the caller decides
    between :meth:`raise_if_fired` (after journaling the late-but-correct
    frame, so the Timeout retry serves it from the journal instead of
    re-executing an identically-slow pass forever) and
    :meth:`accept_late` (no journal to serve the retry from — keep the
    completed frame, record the overrun, and move on; discarding it
    would condemn every consistently-slow pass to retry-until-fatal).
    Either way a late result is never lost work.  An exception already
    in flight wins over the deadline (its own classification is more
    specific than "late")."""

    def __init__(self, seconds: float, site: str):
        self.seconds = seconds
        self.site = site
        self.fired = threading.Event()
        self._timer: Optional[threading.Timer] = None
        self._trace: Optional[tracectx.TraceContext] = None

    def _fire(self) -> None:
        self.fired.set()
        with tracectx.activate(self._trace):
            obs_spans.instant("deadline.fired", site=self.site,
                              deadline_s=self.seconds)
        obs_metrics.counter_add("deadline.fired")
        log.warning("durable: pass deadline %.3fs exceeded at %s "
                    "(CYLON_TPU_PASS_DEADLINE_S)", self.seconds, self.site)

    def __enter__(self) -> "PassDeadline":
        # the request trace active on the ARMING thread, captured at
        # __enter__ (serve constructs the deadline BEFORE activating the
        # ticket's context): the watchdog fires on its own timer thread
        # (fresh contextvar state), so without this capture the terminal
        # `deadline.fired` instant could never be joined to the request
        # whose budget it killed
        self._trace = tracectx.current()
        self._timer = threading.Timer(self.seconds, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._timer is not None:
            self._timer.cancel()
        return False

    def raise_if_fired(self) -> None:
        """Classify a recorded overrun as `Code.Timeout` (call after the
        block — and after journaling any completed frame)."""
        if self.fired.is_set():
            raise CylonError(
                Code.Timeout,
                f"pass exceeded CYLON_TPU_PASS_DEADLINE_S="
                f"{self.seconds:g}s at {self.site}")

    def accept_late(self) -> None:
        """Keep a late-but-complete result: record the overrun (instant +
        metric) without raising — the path for work that is NOT journaled
        and would otherwise be discarded just to re-run identically."""
        if self.fired.is_set():
            obs_spans.instant("deadline.accepted_late", site=self.site,
                              deadline_s=self.seconds)
            obs_metrics.counter_add("deadline.accepted_late")
            log.warning("durable: pass exceeded its %.3fs deadline but "
                        "completed and is not journaled; keeping the late "
                        "result at %s", self.seconds, self.site)


def pass_deadline(site: str = "exec.pass"):
    """Armed :class:`PassDeadline` when ``CYLON_TPU_PASS_DEADLINE_S`` is
    set, else a shared no-op context (zero allocation on the hot path)."""
    s = deadline_s()
    if s <= 0:
        return _NULL_DEADLINE
    return PassDeadline(s, site)
