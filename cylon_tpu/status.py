"""Status/error-code system.

TPU-native analog of the reference's ``cylon::Status`` / ``cylon::Code``
(reference: cpp/src/cylon/status.hpp, cpp/src/cylon/code.cpp).  The reference
models its codes after Arrow's; we keep the same code set so messages and
call-sites translate 1:1, but expose them Python-first (exceptions are the
idiomatic failure path in a JAX framework; ``Status`` objects remain available
for API parity with pycylon).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass


class Code(enum.IntEnum):
    """Error codes (reference: cpp/src/cylon/code.cpp)."""

    OK = 0
    OutOfMemory = 1
    KeyError = 2
    TypeError = 3
    Invalid = 4
    IOError = 5
    CapacityError = 6
    IndexError = 7
    UnknownError = 9
    NotImplemented = 10
    SerializationError = 11
    RError = 13
    CodeGenError = 40
    ExpressionValidationError = 41
    ExecutionError = 42
    AlreadyExists = 45


@dataclass(frozen=True)
class Status:
    """Operation status (reference: cpp/src/cylon/status.hpp).

    ``Status.OK()`` is success; anything else carries a code and message.
    """

    code: Code = Code.OK
    msg: str = ""

    @staticmethod
    def OK() -> "Status":
        return Status(Code.OK, "")

    def is_ok(self) -> bool:
        return self.code == Code.OK

    def get_code(self) -> Code:
        return self.code

    def get_msg(self) -> str:
        return self.msg

    def __bool__(self) -> bool:
        return self.is_ok()


class CylonError(Exception):
    """Exception raised by the Python-first API when an operation fails."""

    def __init__(self, code: Code, msg: str):
        super().__init__(f"[{code.name}] {msg}")
        self.code = code
        self.msg = msg


def raise_not_ok(status: Status) -> None:
    if not status.is_ok():
        raise CylonError(status.code, status.msg)
