"""Status/error-code system.

TPU-native analog of the reference's ``cylon::Status`` / ``cylon::Code``
(reference: cpp/src/cylon/status.hpp, cpp/src/cylon/code.cpp).  The reference
models its codes after Arrow's; we keep the same code set so messages and
call-sites translate 1:1, but expose them Python-first (exceptions are the
idiomatic failure path in a JAX framework; ``Status`` objects remain available
for API parity with pycylon).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass


class Code(enum.IntEnum):
    """Error codes (reference: cpp/src/cylon/code.cpp)."""

    OK = 0
    OutOfMemory = 1
    KeyError = 2
    TypeError = 3
    Invalid = 4
    IOError = 5
    CapacityError = 6
    IndexError = 7
    UnknownError = 9
    NotImplemented = 10
    SerializationError = 11
    RError = 13
    CodeGenError = 40
    ExpressionValidationError = 41
    ExecutionError = 42
    AlreadyExists = 45
    Timeout = 46
    # elastic-membership codes (PR 6; like Timeout, extensions past the
    # reference's table).  Neither is retryable: a lost coordinator has
    # no one to retry against, and re-running a pass into a changed
    # membership is the desync PR 1's no-retry-collectives rule bans —
    # the elastic loop re-PLANS at the new world instead.
    Unavailable = 47      # control plane gone / service draining or closed
    EpochMismatch = 48    # membership moved under in-flight work
    # serving codes (PR 7).  ResourceExhausted is the ADMISSION-layer
    # sibling of OutOfMemory: the request was never attempted because a
    # bounded queue / tenant budget had no room — deterministically
    # retryable by the CALLER (rejects carry a retry-after hint), but
    # never by the engine (nothing in-flight exists to retry).
    # Cancelled is a caller's own decision echoed back; retrying it
    # would countermand the cancel, so it is non-retryable too.
    ResourceExhausted = 49
    Cancelled = 50


# Failure-text classification tables (lowercase substrings).  PJRT raises
# one exception type (XlaRuntimeError) whose message carries the absl
# status code, so classification is textual by necessity; the patterns
# cover the RESOURCE_EXHAUSTED / allocator shapes TPU OOMs actually emit
# and the deadline/comm shapes a flaky tunnel emits.  resilience.py's
# injected faults reuse these exact message shapes.
_OOM_PATTERNS = (
    "resource_exhausted", "resource exhausted", "out of memory",
    "failed to allocate", "allocation failure", "exceeds hbm",
    "hbm capacity", "exceeds the memory",
)
_TRANSIENT_PATTERNS = (
    "deadline_exceeded", "deadline exceeded", "timed out", "timeout",
    "unavailable", "connection reset", "connection refused",
    "connection closed", "socket closed", "broken pipe", "aborted",
    "cancelled", "preempt", "network error",
)


@dataclass(frozen=True)
class Status:
    """Operation status (reference: cpp/src/cylon/status.hpp).

    ``Status.OK()`` is success; anything else carries a code and message.
    """

    code: Code = Code.OK
    msg: str = ""

    @staticmethod
    def OK() -> "Status":
        return Status(Code.OK, "")

    @staticmethod
    def from_exception(exc: BaseException) -> "Status":
        """Classify an exception into the `Code` taxonomy.

        `CylonError` keeps its own code; `MemoryError` and PJRT
        ``RESOURCE_EXHAUSTED``/allocator text map to `Code.OutOfMemory`;
        deadline/comm failure text maps to retryable `Code.ExecutionError`;
        anything unrecognized is `Code.UnknownError` (never retried, never
        split — a TypeError must surface as the bug it is)."""
        if isinstance(exc, CylonError):
            return Status(exc.code, exc.msg)
        msg = f"{type(exc).__name__}: {exc}"
        if isinstance(exc, MemoryError):
            return Status(Code.OutOfMemory, msg)
        if isinstance(exc, (TimeoutError, ConnectionError)):
            return Status(Code.ExecutionError, msg)
        # message-text matching is for PJRT/XLA failures, which surface as
        # RuntimeError (XlaRuntimeError's base); on any other type the
        # text is a bug's wording — e.g. ValueError("... timed out") —
        # and must stay unknown, never retried or split
        if isinstance(exc, RuntimeError):
            low = str(exc).lower()
            if any(p in low for p in _OOM_PATTERNS):
                return Status(Code.OutOfMemory, msg)
            if any(p in low for p in _TRANSIENT_PATTERNS):
                return Status(Code.ExecutionError, msg)
        return Status(Code.UnknownError, msg)

    def is_ok(self) -> bool:
        return self.code == Code.OK

    def get_code(self) -> Code:
        return self.code

    def get_msg(self) -> str:
        return self.msg

    def __bool__(self) -> bool:
        return self.is_ok()


class CylonError(Exception):
    """Exception raised by the Python-first API when an operation fails.

    ``retry_after_s`` (serving layer, PR 7): on admission rejects
    (`Code.ResourceExhausted` / `Code.Unavailable` sheds) it carries the
    service's estimate of when capacity returns — the classified
    alternative to an unbounded wait.  None everywhere else."""

    def __init__(self, code: Code, msg: str,
                 retry_after_s: "float | None" = None):
        super().__init__(f"[{code.name}] {msg}")
        self.code = code
        self.msg = msg
        self.retry_after_s = retry_after_s


def raise_not_ok(status: Status) -> None:
    if not status.is_ok():
        raise CylonError(status.code, status.msg)
