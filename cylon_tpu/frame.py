"""DataFrame: the pandas-like facade over Table.

TPU-native analog of PyCylon's DataFrame (reference:
python/pycylon/frame.py:33-961): construction from list/dict/pandas/numpy,
``[]`` get/set, comparison/logical/math dunders, drop/fillna/where/isnull/
notnull/rename/add_prefix/add_suffix — each delegating to the Table layer —
plus the relational verbs (merge/join/groupby/sort_values/drop_duplicates)
that the reference exposes through Table.

Context handling mirrors frame.py:56-61 _initialize_context: a local
context by default, the distributed mesh context when ``distributed=True``
(the reference initializes MPI there; here the mesh spans ``jax.devices()``).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .context import CylonContext, TPUConfig, default_context
from .index import ColumnIndex, Index, RangeIndex
from .series import Series
from .status import Code, CylonError
from .table import Table

_dist_ctx_cache: Dict[int, CylonContext] = {}


def _resolve_ctx(distributed: bool, ctx: Optional[CylonContext]) -> CylonContext:
    if ctx is not None:
        return ctx
    if not distributed:
        return default_context()
    import jax

    n = len(jax.devices())
    if n not in _dist_ctx_cache:
        _dist_ctx_cache[n] = CylonContext.InitDistributed(TPUConfig())
    return _dist_ctx_cache[n]


class DataFrame:
    """reference: frame.py:33-961."""

    def __init__(self, data=None, index=None, columns: Optional[Sequence[str]] = None,
                 dtype=None, copy: bool = False, distributed: bool = False,
                 ctx: Optional[CylonContext] = None):
        self._index: Index = RangeIndex()
        ctx = _resolve_ctx(distributed, ctx)
        self._table = self._initialize_dataframe(data, columns, dtype, ctx)
        self._index = RangeIndex(0, self._table.row_count)
        if index is not None:
            # constructor index= is ALWAYS row labels (pandas), even when
            # the labels coincide with column names — only set_index
            # prefers the column interpretation
            from .index import as_label_index

            self._table._index = as_label_index(index,
                                                self._table.row_count)
            self._index = self._table.index

    # -- construction (frame.py:63-146) ------------------------------------
    def _initialize_dataframe(self, data, columns, dtype, ctx) -> Table:
        if data is None:
            data = {}
        if isinstance(data, DataFrame):
            t = data._table
            if columns is not None:
                t = t.rename(list(columns))
            return t
        if isinstance(data, Table):
            return data if columns is None else data.rename(list(columns))
        if isinstance(data, dict):
            arrays = {str(k): np.asarray(v) for k, v in data.items()}
            if columns is not None:
                arrays = {str(c): arrays[str(c)] for c in columns}
            return Table.from_pydict(arrays, ctx=ctx)
        if isinstance(data, (list, tuple)):
            # each inner sequence is one column (reference frame.py:77-86)
            names = ([str(i) for i in range(len(data))] if columns is None
                     else [str(c) for c in columns])
            if len(names) != len(data):
                raise CylonError(Code.Invalid, "columns length mismatch")
            return Table.from_pydict(
                {n: np.asarray(c, dtype=dtype) for n, c in zip(names, data)},
                ctx=ctx)
        if isinstance(data, np.ndarray):
            if data.ndim == 1:
                data = data[:, None]
            names = ([str(i) for i in range(data.shape[1])] if columns is None
                     else [str(c) for c in columns])
            return Table.from_pydict(
                {n: np.ascontiguousarray(data[:, i]) for i, n in enumerate(names)},
                ctx=ctx)
        try:
            import pandas as pd

            if isinstance(data, pd.DataFrame):
                return Table.from_pandas(data, ctx=ctx)
            if isinstance(data, pd.Series):
                name = str(data.name) if data.name is not None else "0"
                return Table.from_pydict({name: data.to_numpy()}, ctx=ctx)
        except ImportError:
            pass
        try:
            import pyarrow as pa

            if isinstance(data, pa.Table):
                return Table.from_arrow(data, ctx=ctx)
        except ImportError:
            pass
        raise CylonError(Code.Invalid, f"cannot build DataFrame from {type(data)}")

    @staticmethod
    def _wrap(table: Table) -> "DataFrame":
        df = DataFrame.__new__(DataFrame)
        df._table = table
        df._index = RangeIndex(0, table.row_count)
        return df

    # -- identity / metadata (frame.py:45-158) ------------------------------
    @property
    def is_distributed(self) -> bool:
        return self._table.is_distributed()

    def distributed(self) -> "DataFrame":
        """Re-shard onto the full device mesh (reference frame.py:48-51 turns
        on distributed mode)."""
        if self.is_distributed:
            return self
        ctx = _resolve_ctx(True, None)
        return DataFrame(self.to_pandas(), distributed=True, ctx=ctx)

    @property
    def context(self) -> CylonContext:
        return self._table.ctx

    @property
    def index(self) -> Index:
        return self._index

    def set_index(self, key, drop: bool = True) -> "DataFrame":
        """Route loc lookups through ``key`` (a column name, list of
        names, Index, or row_count labels).  ``drop`` removes used index
        column(s) from the data and DEFAULTS TO TRUE like pandas — this
        facade mirrors pandas, while Table.set_index keeps the column."""
        self._table.set_index(key)
        self._index = self._table.index
        if drop:
            from .index import ColumnIndex

            if isinstance(self._index, ColumnIndex):
                keep = [n for n in self._table.names
                        if n not in self._index.names]
                dropped = self._table.project(keep)
                dropped._index = self._index
                self._table = dropped
        return self

    def reset_index(self) -> "DataFrame":
        self._table.reset_index()
        self._index = self._table.index
        return self

    @property
    def loc(self) -> "_FrameIndexer":
        """Label-based row access (working analog of the reference's
        stubbed _libs/index.pyx loc engine)."""
        return _FrameIndexer(self, "loc")

    @property
    def iloc(self) -> "_FrameIndexer":
        """Position-based row access."""
        return _FrameIndexer(self, "iloc")

    @property
    def shape(self):
        return (self._table.row_count, self._table.column_count)

    @property
    def columns(self) -> List[str]:
        return self._table.column_names

    def __len__(self) -> int:
        return self._table.row_count

    def __repr__(self) -> str:
        return "DataFrame\n" + repr(self.to_pandas())

    # -- exporters (frame.py:159-177) ---------------------------------------
    def to_pandas(self):
        return self._table.to_pandas()

    def to_numpy(self, order: str = "F", zero_copy_only: bool = True,
                 writable: bool = False) -> np.ndarray:
        d = self._table.to_numpy()
        return np.stack(list(d.values()), axis=1) if d else np.empty((0, 0))

    def to_arrow(self):
        return self._table.to_arrow()

    def to_dict(self) -> Dict:
        return self._table.to_pydict()

    def to_table(self) -> Table:
        return self._table

    def to_csv(self, path, csv_write_options=None) -> None:
        self._table.to_csv(path, csv_write_options)

    def to_parquet(self, path, options=None) -> None:
        self._table.to_parquet(path, options)

    # -- [] get/set (frame.py:179-281) --------------------------------------
    def __getitem__(self, key):
        if isinstance(key, DataFrame):
            return DataFrame._wrap(self._table.filter(key._table))
        if isinstance(key, (str, int, np.integer, list, tuple, slice)):
            return DataFrame._wrap(self._table[key])
        raise CylonError(Code.Invalid, f"bad DataFrame key {key!r}")

    def __setitem__(self, key: str, value) -> None:
        if isinstance(value, DataFrame):
            value = value._table
        self._table[key] = value
        self._index = RangeIndex(0, self._table.row_count)

    # -- dunders (frame.py:285-713) -----------------------------------------
    def _delegate(self, other, op):
        if isinstance(other, DataFrame):
            other = other._table
        return DataFrame._wrap(op(self._table, other))

    def __eq__(self, other):  # type: ignore[override]
        return self._delegate(other, lambda t, o: t == o)

    def __ne__(self, other):  # type: ignore[override]
        return self._delegate(other, lambda t, o: t != o)

    def __lt__(self, other):
        return self._delegate(other, lambda t, o: t < o)

    def __gt__(self, other):
        return self._delegate(other, lambda t, o: t > o)

    def __le__(self, other):
        return self._delegate(other, lambda t, o: t <= o)

    def __ge__(self, other):
        return self._delegate(other, lambda t, o: t >= o)

    __hash__ = object.__hash__

    def __or__(self, other):
        return self._delegate(other, lambda t, o: t | o)

    def __and__(self, other):
        return self._delegate(other, lambda t, o: t & o)

    def __invert__(self):
        return DataFrame._wrap(~self._table)

    def __neg__(self):
        return DataFrame._wrap(-self._table)

    def __add__(self, other):
        return self._delegate(other, lambda t, o: t + o)

    def __sub__(self, other):
        return self._delegate(other, lambda t, o: t - o)

    def __mul__(self, other):
        return self._delegate(other, lambda t, o: t * o)

    def __truediv__(self, other):
        return self._delegate(other, lambda t, o: t / o)

    # -- cleaning / selection (frame.py:714-961) -----------------------------
    def drop(self, column_names) -> "DataFrame":
        return DataFrame._wrap(self._table.drop(column_names))

    def fillna(self, fill_value) -> "DataFrame":
        return DataFrame._wrap(self._table.fillna(fill_value))

    def where(self, condition: "DataFrame" = None, other=None) -> "DataFrame":
        if condition is None:
            raise CylonError(Code.Invalid, "where() requires a condition")
        return DataFrame._wrap(self._table.where(condition._table, other))

    def isnull(self) -> "DataFrame":
        return DataFrame._wrap(self._table.isnull())

    isna = isnull

    def notnull(self) -> "DataFrame":
        return DataFrame._wrap(self._table.notnull())

    notna = notnull

    def dropna(self, axis: int = 0, how: str = "any") -> "DataFrame":
        return DataFrame._wrap(self._table.dropna(axis=axis, how=how))

    def isin(self, values) -> "DataFrame":
        return DataFrame._wrap(self._table.isin(values))

    def rename(self, column_names) -> "DataFrame":
        return DataFrame._wrap(self._table.rename(column_names))

    def add_prefix(self, prefix: str) -> "DataFrame":
        return DataFrame._wrap(self._table.add_prefix(prefix))

    def add_suffix(self, suffix: str) -> "DataFrame":
        return DataFrame._wrap(self._table.add_suffix(suffix))

    def applymap(self, fn) -> "DataFrame":
        return DataFrame._wrap(self._table.applymap(fn))

    # -- relational verbs (Table layer pass-throughs) ------------------------
    def merge(self, right: "DataFrame", on=None, left_on=None, right_on=None,
              how: str = "inner", algorithm: str = "sort") -> "DataFrame":
        t = self._table.distributed_join(
            right._table, on=on, left_on=left_on, right_on=right_on, how=how,
            algorithm=algorithm) if self.is_distributed else self._table.join(
            right._table, on=on, left_on=left_on, right_on=right_on, how=how,
            algorithm=algorithm)
        return DataFrame._wrap(t)

    join = merge

    def groupby(self, by, agg: Dict[str, Union[str, Sequence[str]]]) -> "DataFrame":
        return DataFrame._wrap(self._table.groupby(by, agg))

    def sort_values(self, by, ascending: bool = True) -> "DataFrame":
        t = (self._table.distributed_sort(by, ascending=ascending)
             if self.is_distributed else self._table.sort(by, ascending=ascending))
        return DataFrame._wrap(t)

    def drop_duplicates(self, subset=None, keep: str = "first") -> "DataFrame":
        t = (self._table.distributed_unique(subset, keep)
             if self.is_distributed else self._table.unique(subset, keep))
        return DataFrame._wrap(t)

    def __getattr__(self, name: str):
        # column access as attribute, pandas-style
        if name.startswith("_"):
            raise AttributeError(name)
        table = self.__dict__.get("_table")
        if table is not None and name in table.names:
            cols, total = table.project([name])._gathered_columns()
            return Series(name, column=cols[0], row_count=total)
        raise AttributeError(name)


class _FrameIndexer:
    """loc/iloc facade over the Table indexers, re-wrapping as DataFrame
    (reference intent: _libs/index.pyx LocIndexr — stubbed there, working
    here)."""

    def __init__(self, df: DataFrame, kind: str):
        self._df = df
        self._kind = kind

    def __getitem__(self, key) -> DataFrame:
        t = self._df._table
        out = t.loc[key] if self._kind == "loc" else t.iloc[key]
        wrapped = DataFrame._wrap(out)
        wrapped._index = out.index
        return wrapped
