"""plan.explain(): render the optimized tree without running anything.

Stdlib-only string assembly over the optimizer's annotations: every
elided shuffle, shared scan, fused stage and pruned column set is
spelled out, with the packed-plane word width a pruned scan would
actually exchange (the bytes the pruning rule saves)."""
from __future__ import annotations

from typing import List, Optional

from . import expr as expr_mod
from . import ir, optimizer


def explain(plan, optimized: Optional[bool] = None) -> str:
    from . import executor

    enabled = executor.planner_enabled() if optimized is None else bool(
        optimized)
    phys = optimizer.optimize(plan, enabled=enabled)
    lines: List[str] = [
        f"plan [world={phys.world} mode="
        f"{'optimized' if enabled else 'eager'} nodes={phys.nodes} "
        f"shuffles_elided={phys.shuffles_elided} "
        f"columns_pruned={phys.columns_pruned}]"
    ]
    _render(plan, phys.root, lines, 1)
    return "\n".join(lines)


def _shuffle_note(ann: tuple) -> str:
    if not ann or ann[0] == "local":
        return "local"
    if ann[0] == "elide":
        return f"ELIDED (already hash({','.join(ann[1])}))"
    return f"shuffle({','.join(ann[1])})"


def _render(plan, p: optimizer.Phys, lines: List[str], depth: int) -> None:
    n = p.node
    pad = "  " * depth
    if isinstance(n, ir.Scan):
        t = plan.inputs[n.idx]
        note = ""
        pruned = len(p.keep) < len(n.names)
        ann = optimizer.plane_annotation(t, p.keep)
        comp = ann.get("words_comp")
        if pruned or (comp is not None and comp < ann["words_pruned"]):
            # pruning and compression attribute separately: full->pruned
            # words are the planner's column elimination, pruned->comp
            # the payload encoder's bit-width/dictionary win
            words = f"plane {ann['words_full']}->{ann['words_pruned']}"
            if comp is not None:
                words += f"->{comp}"
            note = (f"  [pruned {len(n.names)}->{len(p.keep)} cols, "
                    f"{words} words/row"
                    + (" (compressed)" if comp is not None else "") + "]")
        lines.append(f"{pad}scan {n.label}: {', '.join(p.keep)}{note}")
        return
    if isinstance(n, ir.Project):
        lines.append(f"{pad}project [{', '.join(p.keep)}]")
    elif isinstance(n, ir.Filter):
        lines.append(f"{pad}filter {expr_mod.render(n.pred)}")
    elif isinstance(n, ir.Derive):
        dead = "  [DEAD: pruned]" if p.ann.get("dead") else ""
        lines.append(f"{pad}derive {n.name} = "
                     f"{expr_mod.render(n.value)}{dead}")
    elif isinstance(n, ir.Join):
        shared = "  [SHARED SCAN: one exchange feeds both sides]" \
            if p.ann.get("shared") else ""
        lines.append(
            f"{pad}join {n.how}/{n.algorithm} on "
            f"{','.join(n.left_on)} = {','.join(n.right_on)}  "
            f"[left: {_shuffle_note(p.ann.get('left', ()))}, "
            f"right: {_shuffle_note(p.ann.get('right', ()))}]{shared}")
    elif isinstance(n, ir.Aggregate):
        mode = p.ann.get("mode", "eager")
        if mode == "elided":
            note = (f"  [shuffle ELIDED: hash("
                    f"{','.join(p.ann.get('part_keys', ()))}) covers the "
                    f"group keys]")
        elif mode == "local":
            note = "  [local]"
        else:
            note = f"  [shuffle({','.join(n.by)})]"
        if p.ann.get("fuse"):
            note += "  [FUSED with join: one shard body]"
        aggs = ", ".join(f"{op.name.lower()}({c})" for c, op in n.aggs)
        lines.append(f"{pad}groupby [{', '.join(n.by)}] {aggs}{note}")
    elif isinstance(n, ir.Sort):
        keys = ", ".join(f"{k}{'^' if a else 'v'}"
                         for k, a in zip(n.by, n.ascending))
        lines.append(f"{pad}sort [{keys}]  [range shuffle]")
    elif isinstance(n, ir.Limit):
        lines.append(f"{pad}limit {n.n}  [gather]")
    else:
        lines.append(f"{pad}{n.kind}")
    for c in p.children:
        _render(plan, c, lines, depth + 1)
