"""plan.explain(): render the optimized tree — and, with
``analyze=True``, run it and annotate every node with actuals.

The plain mode is stdlib-only string assembly over the optimizer's
annotations: every elided shuffle, shared scan, fused stage and pruned
column set is spelled out, with the packed-plane word width a pruned
scan would actually exchange (the bytes the pruning rule saves).

``analyze=True`` is EXPLAIN ANALYZE: the plan executes once with the
profiler on (``plan/profile.py``) and each node line gains an
estimate→actual suffix — rows (the estimate is the persistent
statistics catalog's prior observation when one exists), self time,
exchange ``bytes_sent``/``bytes_saved``, jit-plan cache hits, and
per-shard row skew with the slowest shard named.  Nodes fused into a
parent's shard body (the join under a fused group-by chain) carry no
record of their own — their cost is the parent's, exactly as executed.
"""
from __future__ import annotations

from typing import List, Optional

from . import expr as expr_mod
from . import ir, optimizer


def explain(plan, optimized: Optional[bool] = None,
            analyze: bool = False) -> str:
    from . import executor

    if analyze:
        from . import profile as profile_mod

        prof = profile_mod.PlanProfile()
        executor.execute(plan, profile=prof)
        phys = prof.phys
        assert phys is not None
        lines = [_header(phys)]
        if prof.plan_cache_hit:
            lines[0] += "  [served from journal: plan.cache_hit]"
        lines.append(
            f"analyze: wall={prof.wall_ms():.1f}ms  "
            f"estimates={'catalog' if prof.estimates is not None else '-'}"
            + (f"  fingerprint={prof.fingerprint[:12]}"
               if prof.fingerprint else ""))
        if prof.fleet_skew:
            worst = max(prof.fleet_skew,
                        key=lambda c: c.get("skew_ns", 0) or 0)
            lines.append(
                f"fleet: {len(prof.fleet_skew)} recent collectives on "
                f"the coordinator ledger, worst skew "
                f"{(worst.get('skew_ns', 0) or 0) / 1e6:.3f}ms "
                f"(slowest r{worst.get('slowest_rank')})")
        _render(plan, phys.root, lines, 1, prof)
        return "\n".join(lines)

    enabled = executor.planner_enabled() if optimized is None else bool(
        optimized)
    phys = optimizer.optimize(plan, enabled=enabled)
    lines = [_header(phys)]
    _render(plan, phys.root, lines, 1, None)
    return "\n".join(lines)


def explain_refresh(info: dict) -> str:
    """Render a streaming refresh plan (PR 19) from its ``describe()``
    dict — a plain dict on purpose, so the plan package never imports
    the stream package.  States the incremental-vs-full decision and
    WHY, the same contract ``explain()`` has for shuffle elision."""
    mode = str(info.get("mode", "full")).upper()
    lines = [f"refresh [stream={info.get('stream')} "
             f"watermark={info.get('watermark')} mode={mode} "
             f"durable={'on' if info.get('durable') else 'off'}]",
             f"  {mode}: {info.get('reason', '-')}"]
    if info.get("kind") == "groupby":
        lines.append(
            f"  groupby [{', '.join(info.get('by', ()))}] "
            f"{', '.join(info.get('aggs', ()))}  "
            f"[{info.get('partials', 0)} persisted partial columns]")
        if mode == "INCREMENTAL":
            lines.append("  delta batches -> partial kernel -> one jitted "
                         "combine with persisted state -> finalize "
                         "(unchanged)")
        else:
            lines.append("  frozen batches 0..N-1 -> concat -> one local "
                         "group-by (no reusable partial state)")
    elif info.get("kind") == "join":
        lines.append(
            f"  join {info.get('how')} on {', '.join(info.get('on', ()))}  "
            f"[dim: {info.get('dim_rows')} rows, broadcast once]")
        lines.append("  delta fact batches probe the static dim; committed "
                     "probe outputs replay from the journal")
    return "\n".join(lines)


def _header(phys: optimizer.PhysPlan) -> str:
    # adaptive fields render ONLY when the adaptive planner ran — the
    # default header stays byte-identical to the PR-9 renderer
    adaptive = (f" adaptive=on broadcast_joins={phys.broadcast_joins} "
                f"keys_salted={phys.keys_salted}" if phys.adaptive else "")
    return (f"plan [world={phys.world} mode="
            f"{'optimized' if phys.enabled else 'eager'} "
            f"nodes={phys.nodes} "
            f"shuffles_elided={phys.shuffles_elided} "
            f"columns_pruned={phys.columns_pruned}{adaptive}]")


def _shuffle_note(ann: tuple) -> str:
    if not ann or ann[0] == "local":
        return "local"
    if ann[0] == "elide":
        return f"ELIDED (already hash({','.join(ann[1])}))"
    if ann[0] == "broadcast":
        return f"BROADCAST({','.join(ann[1])})"
    if ann[0] == "keep":
        return "kept in place"
    return f"shuffle({','.join(ann[1])})"


def _render(plan, p: optimizer.Phys, lines: List[str], depth: int,
            prof) -> None:
    n = p.node
    pad = "  " * depth
    suffix = prof.annotation(p.nid) if prof is not None else ""
    if isinstance(n, ir.Scan):
        t = plan.inputs[n.idx]
        note = ""
        pruned = len(p.keep) < len(n.names)
        ann = optimizer.plane_annotation(t, p.keep)
        comp = ann.get("words_comp")
        if pruned or (comp is not None and comp < ann["words_pruned"]):
            # pruning and compression attribute separately: full->pruned
            # words are the planner's column elimination, pruned->comp
            # the payload encoder's bit-width/dictionary win
            words = f"plane {ann['words_full']}->{ann['words_pruned']}"
            if comp is not None:
                words += f"->{comp}"
            note = (f"  [pruned {len(n.names)}->{len(p.keep)} cols, "
                    f"{words} words/row"
                    + (" (compressed)" if comp is not None else "") + "]")
        lines.append(f"{pad}scan {n.label}: "
                     f"{', '.join(p.keep)}{note}{suffix}")
        return
    if isinstance(n, ir.Project):
        lines.append(f"{pad}project [{', '.join(p.keep)}]{suffix}")
    elif isinstance(n, ir.Filter):
        lines.append(f"{pad}filter {expr_mod.render(n.pred)}{suffix}")
    elif isinstance(n, ir.Derive):
        dead = "  [DEAD: pruned]" if p.ann.get("dead") else ""
        lines.append(f"{pad}derive {n.name} = "
                     f"{expr_mod.render(n.value)}{dead}{suffix}")
    elif isinstance(n, ir.Join):
        shared = "  [SHARED SCAN: one exchange feeds both sides]" \
            if p.ann.get("shared") else ""
        bcast = ""
        b = p.ann.get("broadcast")
        if isinstance(b, dict):
            bcast = (f"  [ADAPTIVE: broadcast {b.get('side')} side, "
                     f"est {b.get('bytes')}B ({b.get('source')})]")
        lines.append(
            f"{pad}join {n.how}/{n.algorithm} on "
            f"{','.join(n.left_on)} = {','.join(n.right_on)}  "
            f"[left: {_shuffle_note(p.ann.get('left', ()))}, "
            f"right: {_shuffle_note(p.ann.get('right', ()))}]"
            f"{shared}{bcast}{suffix}")
    elif isinstance(n, ir.Aggregate):
        mode = p.ann.get("mode", "eager")
        if mode == "elided":
            note = (f"  [shuffle ELIDED: hash("
                    f"{','.join(p.ann.get('part_keys', ()))}) covers the "
                    f"group keys]")
        elif mode == "local":
            note = "  [local]"
        else:
            note = f"  [shuffle({','.join(n.by)})]"
        if p.ann.get("salt"):
            se = p.ann.get("salt_est") or {}
            note += (f"  [ADAPTIVE: salted x{p.ann['salt']}, observed "
                     f"skew {se.get('skew')} >= {se.get('factor')} "
                     f"({se.get('source')})]")
        if p.ann.get("fuse"):
            note += "  [FUSED with join: one shard body]"
        aggs = ", ".join(f"{op.name.lower()}({c})" for c, op in n.aggs)
        lines.append(f"{pad}groupby [{', '.join(n.by)}] "
                     f"{aggs}{note}{suffix}")
    elif isinstance(n, ir.Sort):
        keys = ", ".join(f"{k}{'^' if a else 'v'}"
                         for k, a in zip(n.by, n.ascending))
        lines.append(f"{pad}sort [{keys}]  [range shuffle]{suffix}")
    elif isinstance(n, ir.Limit):
        lines.append(f"{pad}limit {n.n}  [gather]{suffix}")
    else:
        lines.append(f"{pad}{n.kind}{suffix}")
    for c in p.children:
        _render(plan, c, lines, depth + 1, prof)
