"""Fingerprintable column expressions for logical plans.

Table.select() takes an opaque Python lambda — fine for eager execution,
useless for a *plan*: a lambda cannot be fingerprinted (the durable
journal and the serve result cache key runs by content), compared for
CSE, or asked which columns it reads (column pruning needs the exact
read set).  This module is the lazy twin: a tiny expression tree
(``col``/``lit`` + arithmetic/comparison/logical operators) whose

- ``spec()`` is a canonical primitive tuple (feeds
  :func:`cylon_tpu.durable.run_fingerprint` unchanged),
- ``columns()`` is the exact read set (drives the optimizer's pruning),
- ``evaluate(env)`` lowers onto the SAME kernels the eager compute layer
  uses (``cylon_tpu.compute._col_math`` / ``_col_compare``), so a
  planned filter/derive is bit-identical to its eager counterpart.

Null semantics follow the compute layer: arithmetic propagates validity
conjunction (division additionally invalidates zero divisors), and a
filter keeps a row only when the predicate is True AND valid — the
pandas behavior (NaN comparisons are False).
"""
from __future__ import annotations

from typing import Dict, Set, Tuple, Union

import numpy as np

from ..column import Column
from ..status import Code, CylonError

Scalar = Union[bool, int, float, str]

_CMP = ("eq", "ne", "lt", "gt", "le", "ge")
_MATH = ("add", "sub", "mul", "truediv")
_LOGICAL = ("and", "or")
_FLIP = {"lt": "gt", "gt": "lt", "le": "ge", "ge": "le",
         "eq": "eq", "ne": "ne"}


class Expr:
    """Base class: operator overloads build the tree."""

    # -- tree protocol --------------------------------------------------
    def spec(self) -> tuple:
        raise NotImplementedError

    def columns(self) -> Set[str]:
        raise NotImplementedError

    def evaluate(self, env: Dict[str, Column]) -> Column:
        raise NotImplementedError

    # -- operator surface ----------------------------------------------
    def _bin(self, op: str, other, flipped: bool = False) -> "Expr":
        other = _as_expr(other)
        left, right = (other, self) if flipped else (self, other)
        if isinstance(left, Lit) and isinstance(right, Lit):
            return _fold(op, left, right)  # constant-fold on the host
        return Bin(op, left, right)

    def __add__(self, o):
        return self._bin("add", o)

    def __radd__(self, o):
        return self._bin("add", o, flipped=True)

    def __sub__(self, o):
        return self._bin("sub", o)

    def __rsub__(self, o):
        return self._bin("sub", o, flipped=True)

    def __mul__(self, o):
        return self._bin("mul", o)

    def __rmul__(self, o):
        return self._bin("mul", o, flipped=True)

    def __truediv__(self, o):
        return self._bin("truediv", o)

    def __rtruediv__(self, o):
        return self._bin("truediv", o, flipped=True)

    def __eq__(self, o):  # type: ignore[override]
        return self._bin("eq", o)

    def __ne__(self, o):  # type: ignore[override]
        return self._bin("ne", o)

    def __lt__(self, o):
        return self._bin("lt", o)

    def __gt__(self, o):
        return self._bin("gt", o)

    def __le__(self, o):
        return self._bin("le", o)

    def __ge__(self, o):
        return self._bin("ge", o)

    def __and__(self, o):
        return self._bin("and", o)

    def __or__(self, o):
        return self._bin("or", o)

    def __invert__(self):
        return Not(self)

    def __neg__(self):
        return Neg(self)

    # == builds a comparison node, so identity must carry hashing
    __hash__ = object.__hash__

    def __bool__(self):
        raise CylonError(
            Code.Invalid,
            "a plan expression has no truth value; combine predicates "
            "with & / | / ~, not `and`/`or`/`not`")

    def __repr__(self) -> str:
        return f"Expr[{render(self)}]"


class Col(Expr):
    def __init__(self, name: str):
        self.name = str(name)

    def spec(self) -> tuple:
        return ("col", self.name)

    def columns(self) -> Set[str]:
        return {self.name}

    def evaluate(self, env: Dict[str, Column]) -> Column:
        if self.name not in env:
            raise CylonError(Code.KeyError,
                             f"expression references unknown column "
                             f"{self.name!r} (have {sorted(env)})")
        return env[self.name]


class Lit(Expr):
    def __init__(self, value: Scalar):
        if not isinstance(value, (bool, int, float, str, np.generic)):
            raise CylonError(Code.Invalid,
                             f"literal must be a scalar, got {type(value)}")
        self.value = value.item() if isinstance(value, np.generic) else value

    def spec(self) -> tuple:
        return ("lit", type(self.value).__name__, self.value)

    def columns(self) -> Set[str]:
        return set()

    def evaluate(self, env: Dict[str, Column]) -> Column:
        # a bare literal never evaluates standalone: Bin special-cases
        # literal operands into the compute layer's scalar paths
        raise CylonError(Code.Invalid,
                         "a bare literal is not a column expression")


class Bin(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        assert op in _CMP + _MATH + _LOGICAL, op
        self.op = op
        self.left = left
        self.right = right

    def spec(self) -> tuple:
        return ("bin", self.op, self.left.spec(), self.right.spec())

    def columns(self) -> Set[str]:
        return self.left.columns() | self.right.columns()

    def evaluate(self, env: Dict[str, Column]) -> Column:
        from .. import compute as compute_mod

        op = self.op
        lv, rv = self.left, self.right
        if isinstance(lv, Lit) and isinstance(rv, Lit):
            raise CylonError(Code.Invalid,
                             "literal-only expression; fold it on the host")
        # scalar fast paths mirror the eager compute layer exactly
        if isinstance(rv, Lit):
            lc = lv.evaluate(env)
            if op in _CMP:
                return compute_mod._col_compare(lc, rv.value, op, None)
            if op in _MATH:
                return compute_mod._col_math(lc, rv.value, op, None)
        if isinstance(lv, Lit):
            rc = rv.evaluate(env)
            if op in _CMP:  # flip: lit < col  ==  col > lit
                return compute_mod._col_compare(rc, lv.value, _FLIP[op], None)
            if op in ("add", "mul"):
                return compute_mod._col_math(rc, lv.value, op, None)
            if op == "sub":  # lit - col == (-col) + lit
                return compute_mod._col_math(_neg_col(rc), lv.value, "add",
                                             None)
            if op == "truediv":  # lit / col: materialize the literal
                lc = _lit_column(lv.value, rc)
                return compute_mod._col_math(lc, None, op, rc)
        if op in _LOGICAL and isinstance(rv, Lit):
            # a literal bool operand (often the residue of constant
            # folding, e.g. `pred & (lit(1) < lit(2))`): materialize it
            # against the evaluated side instead of crashing
            lc = lv.evaluate(env)
            rc = _lit_column(bool(rv.value), lc)
        elif op in _LOGICAL and isinstance(lv, Lit):
            rc = rv.evaluate(env)
            lc = _lit_column(bool(lv.value), rc)
        else:
            lc = lv.evaluate(env)
            rc = rv.evaluate(env)
        if op in _CMP:
            return compute_mod._col_compare(lc, None, op, rc)
        if op in _MATH:
            return compute_mod._col_math(lc, None, op, rc)
        # logical: both sides must be boolean columns
        import jax.numpy as jnp

        from .. import dtypes
        if lc.data.dtype != jnp.bool_ or rc.data.dtype != jnp.bool_:
            raise CylonError(Code.Invalid,
                             f"logical `{op}` needs boolean operands")
        data = (lc.data & rc.data) if op == "and" else (lc.data | rc.data)
        validity = lc.validity & rc.validity
        return compute_mod._result_col(data, validity, dtypes.bool_)


class Not(Expr):
    def __init__(self, e: Expr):
        self.e = e

    def spec(self) -> tuple:
        return ("not", self.e.spec())

    def columns(self) -> Set[str]:
        return self.e.columns()

    def evaluate(self, env: Dict[str, Column]) -> Column:
        import jax.numpy as jnp

        from .. import compute as compute_mod
        from .. import dtypes

        c = self.e.evaluate(env)
        if c.data.dtype != jnp.bool_:
            raise CylonError(Code.Invalid, "~ needs a boolean operand")
        return compute_mod._result_col(~c.data, c.validity, dtypes.bool_)


class Neg(Expr):
    def __init__(self, e: Expr):
        self.e = e

    def spec(self) -> tuple:
        return ("neg", self.e.spec())

    def columns(self) -> Set[str]:
        return self.e.columns()

    def evaluate(self, env: Dict[str, Column]) -> Column:
        return _neg_col(self.e.evaluate(env))


def _neg_col(c: Column) -> Column:
    import jax.numpy as jnp

    from .. import dtypes

    if c.is_string or c.data.dtype == jnp.bool_:
        raise CylonError(Code.Invalid, "negation needs a numeric column")
    data = jnp.where(c.validity, -c.data, jnp.zeros((), c.data.dtype))
    return Column(data, c.validity, None, c.dtype)


def _lit_column(value: Scalar, like: Column) -> Column:
    """Materialize a scalar as a full column with ``like``'s capacity —
    only for the rare non-flippable literal-first forms."""
    import jax.numpy as jnp

    from .. import dtypes

    if isinstance(value, str):
        raise CylonError(Code.Invalid, "string literals only compare")
    dt = (jnp.bool_ if isinstance(value, bool)
          else jnp.int32 if isinstance(value, int) else jnp.float32)
    cap = like.data.shape[0]
    data = jnp.full((cap,), value, dt)
    return Column(data, jnp.ones((cap,), bool), None,
                  dtypes.from_numpy_dtype(np.dtype(dt)))


def _fold(op: str, left: "Lit", right: "Lit") -> "Lit":
    """Host-side constant folding of literal-only subtrees (e.g.
    ``lit(1.0) - lit(0.1)`` inside a derive): a Bin over two literals
    could never evaluate against columns, so it folds at construction."""
    import operator as _op

    fns = {"add": _op.add, "sub": _op.sub, "mul": _op.mul,
           "truediv": _op.truediv, "eq": _op.eq, "ne": _op.ne,
           "lt": _op.lt, "gt": _op.gt, "le": _op.le, "ge": _op.ge,
           "and": lambda a, b: bool(a) and bool(b),
           "or": lambda a, b: bool(a) or bool(b)}
    try:
        return Lit(fns[op](left.value, right.value))
    except Exception as e:
        raise CylonError(Code.Invalid,
                         f"cannot fold literal expression "
                         f"({left.value!r} {op} {right.value!r}): {e}")


def _as_expr(v) -> Expr:
    if isinstance(v, Expr):
        return v
    return Lit(v)


def col(name: str) -> Col:
    """Reference a column by name."""
    return Col(name)


def lit(value: Scalar) -> Lit:
    """A scalar literal operand."""
    return Lit(value)


def render(e: Expr) -> str:
    """Human-readable one-line rendering (plan.explain)."""
    if isinstance(e, Col):
        return e.name
    if isinstance(e, Lit):
        return repr(e.value)
    if isinstance(e, Bin):
        sym = {"add": "+", "sub": "-", "mul": "*", "truediv": "/",
               "eq": "==", "ne": "!=", "lt": "<", "gt": ">", "le": "<=",
               "ge": ">=", "and": "&", "or": "|"}[e.op]
        return f"({render(e.left)} {sym} {render(e.right)})"
    if isinstance(e, Not):
        return f"~{render(e.e)}"
    if isinstance(e, Neg):
        return f"-{render(e.e)}"
    return repr(e)
