"""cylon_tpu.plan — the logical query planner.

``Table.plan()`` starts a lazy :class:`LogicalPlan`; builder methods
(``filter``/``project``/``with_column``/``join``/``groupby``/``sort``/
``limit``) append IR nodes; ``execute()`` runs the rule-optimized plan
(shuffle elision, column pruning, scan sharing, fused local kernels —
``CYLON_TPU_PLAN`` gates the optimizer) and ``explain()`` renders every
decision.  ``col``/``lit`` build the fingerprintable expressions plan
filters and derived columns require.
"""
from .executor import execute, planner_enabled, run_service
from .expr import Expr, col, lit
from .ir import LogicalPlan
from .profile import PlanProfile, profiler_enabled

__all__ = ["LogicalPlan", "Expr", "col", "lit", "execute",
           "planner_enabled", "run_service", "PlanProfile",
           "profiler_enabled"]
