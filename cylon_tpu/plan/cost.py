"""Calibrated shuffle cost model: the adaptive planner's arithmetic.

The PR-9 optimizer is rule-based and data-blind; this module gives it
numbers.  A :class:`CostModel` is built once per :func:`optimize` call
(adaptive mode only) from three feeds, in order of preference:

1. the **statistics catalog** (``obs/stats_catalog.py``) — per-node
   observed rows and shard-placement skew a prior profiled run of the
   SAME plan recorded under its base fingerprint;
2. **input metadata** — buffer bytes of the pruned scan columns (the
   same accounting as ``LogicalPlan.approx_input_bytes``), a
   capacity-level upper bound that needs no catalog and no device sync;
3. **observed collective costs** — the process-wide ratio of
   ``shuffle.bytes_sent`` to ``shuffle.collective_launches`` obs
   counters calibrates the per-launch byte-equivalent cost (how many
   payload bytes one extra collective launch is worth), with a
   conservative fallback when this process has not shuffled yet.

Everything here is host-side arithmetic over plan + metadata: nothing
is traced, nothing syncs a device, and a wrong estimate can only cost
performance, never correctness (both strategies are exact; tests pin
bit-identity).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from .. import config
from . import ir

#: byte-equivalent cost of ONE collective launch when the process has
#: no observed shuffle history to calibrate from.  Deliberately high
#: (64 KiB): with no evidence, prefer the plan shape PR-9 would build
#: unless the byte win is decisive.
DEFAULT_LAUNCH_BYTES = 64 * 1024

#: clamp band for the calibrated per-launch cost — one weird observed
#: ratio (empty exchanges, a single giant exchange) must not swing
#: planning by orders of magnitude.
_LAUNCH_BYTES_MIN = 4 * 1024
_LAUNCH_BYTES_MAX = 4 * 1024 * 1024


def broadcast_threshold_bytes() -> int:
    """``CYLON_TPU_PLAN_BROADCAST_BYTES``: largest estimated join-side
    payload the broadcast-hash rule may replicate."""
    return int(config.knob("CYLON_TPU_PLAN_BROADCAST_BYTES"))


def skew_salt_factor() -> float:
    """``CYLON_TPU_PLAN_SKEW_SALT``: max/mean shard-rows skew at which
    the salt rule fires."""
    return float(config.knob("CYLON_TPU_PLAN_SKEW_SALT"))


def calibrated_launch_bytes() -> int:
    """Per-collective launch cost in payload-byte equivalents,
    calibrated from this process's observed exchanges (mean bytes per
    launch), clamped; :data:`DEFAULT_LAUNCH_BYTES` when no exchange has
    run yet."""
    from ..obs import metrics

    launches = metrics.counter_value("shuffle.collective_launches")
    sent = metrics.counter_value("shuffle.bytes_sent")
    if launches <= 0 or sent <= 0:
        return DEFAULT_LAUNCH_BYTES
    mean = sent / launches
    return int(min(max(mean, _LAUNCH_BYTES_MIN), _LAUNCH_BYTES_MAX))


def _logical_nids(root: ir.Node) -> Dict[int, int]:
    """``id(logical node) -> stable preorder nid``.  The phys tree
    mirrors the logical tree 1:1 in child order, so this numbering
    matches ``optimizer._assign_nids`` — per-node catalog records are
    addressable DURING the bottom-up build, before nids are stamped."""
    out: Dict[int, int] = {}

    def walk(n: ir.Node, nxt: int) -> int:
        out[id(n)] = nxt
        nxt += 1
        for c in n.children:
            nxt = walk(c, nxt)
        return nxt

    walk(root, 0)
    return out


class CostModel:
    """Per-plan estimates for one :func:`optimizer.optimize` call.

    ``record`` is the catalog entry for this plan's BASE fingerprint
    (strategy-independent — the adaptive planner must read stats keyed
    by what the query IS, not by what it previously chose), or None
    when the catalog is disabled/cold; every estimate then degrades to
    the metadata bound."""

    def __init__(self, plan, world: int,
                 record: Optional[dict] = None):
        self.plan = plan
        self.world = int(world)
        self.record = record if isinstance(record, dict) else None
        self._nids = _logical_nids(plan.root)
        self.threshold = broadcast_threshold_bytes()
        self.salt_factor = skew_salt_factor()
        self.launch_bytes = calibrated_launch_bytes()

    # -- catalog access ---------------------------------------------------

    def node_record(self, node: ir.Node) -> Optional[dict]:
        """The prior run's per-node actuals for ``node`` (rows, self_ms,
        bytes_sent, skew), or None."""
        if self.record is None:
            return None
        nodes = self.record.get("nodes")
        if not isinstance(nodes, dict):
            return None
        rec = nodes.get(str(self._nids.get(id(node), -1)))
        return rec if isinstance(rec, dict) else None

    # -- size estimates ---------------------------------------------------

    def side_estimate(self, p) -> Tuple[int, str]:
        """Estimated payload bytes of physical subtree ``p``'s output,
        with its provenance: ``("catalog", ...)`` when a prior run
        observed this node's row count (metadata bytes scaled by
        observed-rows / capacity), else ``("metadata", ...)`` — the
        pruned scan buffer bytes of the subtree, a capacity upper
        bound."""
        meta_bytes, caps = self._subtree_meta(p)
        rec = self.node_record(p.node)
        rows = None
        if rec is not None:
            try:
                rows = int(rec.get("rows"))
            except (TypeError, ValueError):
                rows = None
        if rows is not None and rows >= 0 and caps > 0:
            return max(0, int(round(meta_bytes * rows / caps))), "catalog"
        return int(meta_bytes), "metadata"

    def _subtree_meta(self, p) -> Tuple[int, int]:
        """(kept scan buffer bytes, summed scan capacities) of ``p``'s
        subtree — the ``approx_input_bytes`` accounting, restricted to
        one side."""
        total = 0
        caps = 0
        stack = [p]
        while stack:
            cur = stack.pop()
            if isinstance(cur.node, ir.Scan):
                t = self.plan.inputs[cur.node.idx]
                caps += int(t.capacity)
                keep = set(cur.keep)
                for name, c in zip(t.names, t.columns):
                    if name in keep:
                        total += int(c.data.nbytes) + int(c.validity.nbytes)
                        if c.lengths is not None:
                            total += int(c.lengths.nbytes)
            stack.extend(cur.children)
        return total, caps

    # -- decisions ---------------------------------------------------------

    def broadcast_wins(self, small_bytes: int, big_bytes: int,
                       exchanges_saved: int) -> bool:
        """Broadcast-vs-shuffle cost comparison for one join.

        Broadcast replicates the small side to every rank (one gather,
        ``small x world`` wire bytes); shuffling moves each side's
        payload once but pays ``exchanges_saved`` packed exchanges, each
        two launches (counts gather + payload all_to_all).  The small
        side's own shuffle bytes count only when broadcasting actually
        removes that exchange (saved == 2)."""
        cost_b = small_bytes * self.world + self.launch_bytes
        cost_s = (big_bytes
                  + (small_bytes if exchanges_saved >= 2 else 0)
                  + exchanges_saved * 2 * self.launch_bytes)
        return cost_b < cost_s

    def skew_estimate(self, p) -> Tuple[float, str]:
        """Worst observed shard-placement skew (max/mean shard rows)
        over ``p``'s subtree from the catalog, with provenance; (1.0,
        "none") when the catalog never saw this plan — no evidence, no
        salt."""
        best = 0.0
        stack = [p]
        while stack:
            cur = stack.pop()
            rec = self.node_record(cur.node)
            if rec is not None:
                try:
                    best = max(best, float(rec.get("skew", 0.0)))
                except (TypeError, ValueError):
                    pass
            stack.extend(cur.children)
        if best > 0.0:
            return best, "catalog"
        return 1.0, "none"
