"""Per-plan-node execution profile: the EXPLAIN ANALYZE substrate.

PR 8 can say where a RANK's wall-clock went and PR 9 can say what the
optimizer decided — but nothing attributes cost to a *plan node*: which
join moved the bytes, which filter kept 2% of its input, which stage's
shards ran 5× skewed.  This module records exactly that, riding the
execution primitives that already exist (the DrJAX idiom from PAPERS.md:
measurement composes with the program, no side-channel):

- the executor wraps each physical node's ``_exec`` with two
  ``perf_counter_ns`` reads and a handful of ``obs.metrics`` counter
  reads (``shuffle.bytes_sent``/``bytes_saved``, launches, jit-plan
  cache traffic), so a node's ACTUALS are the deltas its subtree
  produced — exchange bytes land on the node that shuffled;
- row counts come from the node's materialized Table (per-shard counts
  when addressable, so per-node partition SKEW — max/mean shard rows
  and the slowest shard — falls out of data the engine already holds);
- :meth:`PlanProfile.finalize` turns subtree totals into SELF values by
  subtracting each node's nearest recorded descendants (the same
  flame-graph attribution ``tools/trace_report.py`` applies to spans).

The profile renders through ``explain(plan, analyze=True)`` as
estimate→actual annotations (estimates come from the persistent
statistics catalog when a prior run observed this plan), exports as a
JSON artifact ``tools/trace_report.py --plan`` summarizes, and distills
into the :mod:`cylon_tpu.obs.stats_catalog` record — observed per-scan
column cardinality, join-key selectivity, filter selectivity, per-node
skew — that ROADMAP item 1's cost model will consume.

Profiling is host-side by construction (counter reads, host timestamps,
row-count fetches of already-materialized tables): the traced programs,
their cache keys and the jaxpr budget goldens are untouched, and with
the profiler off (``CYLON_TPU_PROFILE`` unset, no ``analyze=True``)
the executor runs the exact pre-PR code path — zero new work.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import config
from ..obs import metrics as obs_metrics
from . import ir

log = logging.getLogger("cylon_tpu")

PROFILE_KIND = "cylon_tpu.plan_profile"

#: counters whose per-node deltas the profiler attributes (subtree
#: totals at record time, SELF deltas after finalize)
PROFILED_COUNTERS: Tuple[str, ...] = (
    "shuffle.exchanges", "shuffle.collective_launches",
    "shuffle.bytes_sent", "shuffle.bytes_saved", "shuffle.counts_gathers",
    "plan_cache.hit", "plan_cache.miss",
)


def profiler_enabled() -> bool:
    """``CYLON_TPU_PROFILE``: collect per-node actuals on every
    ``plan.execute`` (``explain(analyze=True)`` forces one profiled run
    regardless)."""
    return bool(config.knob("CYLON_TPU_PROFILE"))


def counters_now() -> Tuple[float, ...]:
    return tuple(obs_metrics.counter_value(n) for n in PROFILED_COUNTERS)


def describe(node: ir.Node) -> str:
    """One-line human label for a plan node (artifact + report tables)."""
    if isinstance(node, ir.Scan):
        return f"scan {node.label}"
    if isinstance(node, ir.Join):
        return (f"join {node.how}/{node.algorithm} on "
                f"{','.join(node.left_on)}={','.join(node.right_on)}")
    if isinstance(node, ir.Aggregate):
        return f"groupby [{', '.join(node.by)}]"
    if isinstance(node, ir.Filter):
        from . import expr as expr_mod

        return f"filter {expr_mod.render(node.pred)}"
    if isinstance(node, ir.Derive):
        return f"derive {node.name}"
    if isinstance(node, ir.Sort):
        return f"sort [{', '.join(node.by)}]"
    if isinstance(node, ir.Limit):
        return f"limit {node.n}"
    return node.kind


class PlanProfile:
    """Actuals of ONE executed plan, keyed by physical-node id (the
    optimizer's stable preorder numbering, so estimate lookups from a
    prior run's catalog record line up node-for-node)."""

    def __init__(self):
        self.nodes: Dict[int, dict] = {}
        self.phys = None                      # optimizer.PhysPlan
        self.world: int = 1
        self.plan_cache_hit = False
        self.wall_ns: int = 0
        self.fingerprint: Optional[str] = None
        self.estimates: Optional[dict] = None  # prior catalog record
        self.fleet_skew: Optional[List[dict]] = None  # PR-8 ledger rows
        self.artifact_path: Optional[str] = None
        self._finalized = False

    # -- recording (executor hot path) -----------------------------------

    def record_node(self, p, table, wall_ns: int,
                    before: Tuple[float, ...]) -> None:
        """Store one node's subtree actuals (called as ``_exec(p)``
        returns, so children recorded first)."""
        deltas = {n: obs_metrics.counter_value(n) - b
                  for n, b in zip(PROFILED_COUNTERS, before)}
        rec: Dict[str, object] = {
            "rows": int(table.row_count),
            "wall_ns": int(wall_ns),
            "metrics": {k: v for k, v in deltas.items() if v},
        }
        rc = table.row_counts
        if table.num_shards > 1 and getattr(rc, "is_fully_addressable",
                                            True):
            rec["shard_rows"] = [int(x) for x in np.asarray(rc)]
        self.nodes[int(p.nid)] = rec

    def record_fused_join(self, p, shard_counts) -> None:
        """Observed cardinality of a join fused into a parent's shard
        body: the exact count pass that sizes the fused program is the
        join's row count (per shard), even though the join intermediate
        never materializes.  Wall/bytes stay with the parent — only the
        rows are the join's own."""
        if not getattr(shard_counts, "is_fully_addressable", True):
            return
        sc = [int(x) for x in np.asarray(shard_counts).reshape(-1)]
        rec: Dict[str, object] = {"rows": int(sum(sc)), "wall_ns": 0,
                                  "metrics": {}, "fused": True}
        if len(sc) > 1:
            rec["shard_rows"] = sc
        self.nodes[int(p.nid)] = rec

    # -- finalize ---------------------------------------------------------

    def _recorded_children(self, p) -> List:
        """Nearest recorded descendants of ``p`` — a fused group-by's
        direct child chain has no records, but the scans underneath do,
        and their time/bytes must not double-count as the group-by's
        self cost."""
        out = []
        for c in p.children:
            if c.nid in self.nodes:
                out.append(c)
            else:
                out.extend(self._recorded_children(c))
        return out

    def _eff_wall(self, p) -> int:
        """Wall a subtree ACCOUNTS for toward its parent's self-time
        subtraction: the node's own measured wall when it was timed; a
        fused record (rows only, wall 0) or an unrecorded node passes
        its children's accounting through — the scans under a fused
        join still ran inside the parent's window."""
        rec = self.nodes.get(p.nid)
        if rec is not None and not rec.get("fused"):
            return int(rec["wall_ns"])
        return sum(self._eff_wall(c) for c in p.children)

    def _eff_metric(self, p, name: str) -> float:
        rec = self.nodes.get(p.nid)
        if rec is not None and not rec.get("fused"):
            return rec["metrics"].get(name, 0)
        return sum(self._eff_metric(c, name) for c in p.children)

    def finalize(self, phys, wall_ns: int) -> None:
        """Attach the physical plan, compute self times/deltas and skew."""
        self.phys = phys
        self.world = phys.world
        self.wall_ns = int(wall_ns)
        if self._finalized:
            return
        self._finalized = True

        def walk(p, depth: int) -> None:
            rec = self.nodes.get(p.nid)
            if rec is not None:
                rec["depth"] = depth
                rec["kind"] = p.node.kind
                rec["desc"] = describe(p.node)
                if rec.get("fused"):
                    # rows-only record: cost lives with the fusing parent
                    rec["self_ns"] = 0
                    rec["self_metrics"] = {}
                else:
                    kid_wall = sum(self._eff_wall(c) for c in p.children)
                    rec["self_ns"] = max(0, rec["wall_ns"] - kid_wall)
                    self_m: Dict[str, float] = {}
                    for name in PROFILED_COUNTERS:
                        v = rec["metrics"].get(name, 0) - sum(
                            self._eff_metric(c, name) for c in p.children)
                        if v > 0:
                            self_m[name] = v
                    rec["self_metrics"] = self_m
                sr = rec.get("shard_rows")
                if sr and sum(sr) > 0:
                    mean = sum(sr) / len(sr)
                    rec["skew"] = round(max(sr) / mean, 4) if mean else None
                    rec["slowest_shard"] = int(np.argmax(sr))
            for c in p.children:
                walk(c, depth + 1)

        walk(phys.root, 0)

    def attach_fleet_skew(self, ctx) -> None:
        """Pull the coordinator's recent per-collective skew ledger (the
        PR-8 slowest-participant attribution) into the profile when the
        context runs under an elastic agent — the fleet-level complement
        to the per-node shard-row skew.  Best-effort and read-only: no
        agent, an unreachable coordinator, or any error just leaves the
        ledger absent."""
        get = getattr(ctx, "elastic_agent", None)
        agent = get() if callable(get) else None
        if agent is None:
            return
        st = agent.status()
        if st:
            self.fleet_skew = list(st.get("collectives") or [])

    # -- the statistics-catalog record ------------------------------------

    def catalog_record(self, plan) -> dict:
        """Distill the profile into the persistent statistics record:
        per-scan column cardinalities (exact host nunique over the
        PRUNED columns — the same host gather the plan fingerprint
        already paid), join/filter selectivities from observed in/out
        rows, per-node rows and skew.  Called only when the catalog is
        enabled; the host gather is the documented profiling cost."""
        rec: dict = {"world": self.world, "wall_ms": self.wall_ms(),
                     "nodes": {}, "scans": {}, "joins": {}, "filters": {}}
        if self.phys is not None:
            from . import optimizer as optimizer_mod

            # which adaptive strategies produced these observations —
            # diagnostic provenance (the record itself is keyed by the
            # strategy-independent base fingerprint)
            strat = optimizer_mod.strategy_spec(self.phys)
            if strat:
                rec["strategies"] = [list(s) for s in strat]
        for nid, n in self.nodes.items():
            rec["nodes"][str(nid)] = {
                "kind": n.get("kind"), "rows": n["rows"],
                "self_ms": round(n.get("self_ns", 0) / 1e6, 3),
                "bytes_sent": n.get("self_metrics", {}).get(
                    "shuffle.bytes_sent", 0),
                **({"skew": n["skew"],
                    "slowest_shard": n["slowest_shard"]}
                   if n.get("skew") is not None else {}),
            }

        def walk(p) -> None:
            node = p.node
            me = self.nodes.get(p.nid)
            if isinstance(node, ir.Scan) and me is not None:
                cols: Dict[str, dict] = {}
                try:
                    t = plan.inputs[node.idx].project(list(p.keep))
                    frame = t.to_numpy()
                    for name, arr in frame.items():
                        cols[name] = {"nunique": int(len(np.unique(arr)))}
                except Exception as e:  # advisory: never fail the run
                    log.warning("profile: scan cardinality for %s failed "
                                "(%s: %s); omitting", node.label,
                                type(e).__name__, e)
                rec["scans"][str(p.nid)] = {
                    "label": node.label, "rows": me["rows"],
                    "columns": cols}
            if isinstance(node, ir.Join) and me is not None:
                kids = self._recorded_children(p)
                rows = None
                if len(kids) == 2:
                    rows = tuple(self.nodes[k.nid]["rows"] for k in kids)
                elif len(kids) == 1 and p.ann.get("shared"):
                    # shared-scan self-join: ONE chain fed both sides,
                    # so the single record IS both input cardinalities
                    one = self.nodes[kids[0].nid]["rows"]
                    rows = (one, one)
                if rows is not None:
                    l, r = rows
                    sel = (me["rows"] / (l * r)) if l and r else None
                    rec["joins"][str(p.nid)] = {
                        "left_rows": l, "right_rows": r,
                        "out_rows": me["rows"],
                        "selectivity": sel,
                        "keys": list(node.left_on)}
            if isinstance(node, ir.Filter) and me is not None:
                kids = self._recorded_children(p)
                if len(kids) == 1:
                    n_in = self.nodes[kids[0].nid]["rows"]
                    rec["filters"][str(p.nid)] = {
                        "in_rows": n_in, "out_rows": me["rows"],
                        "selectivity": (me["rows"] / n_in) if n_in
                        else None}
            for c in p.children:
                walk(c)

        if self.phys is not None:
            walk(self.phys.root)
        return rec

    # -- rendering / export ------------------------------------------------

    def wall_ms(self) -> float:
        return round(self.wall_ns / 1e6, 3)

    def est_rows(self, nid: int) -> Optional[int]:
        """Prior-run row estimate for a node (the catalog record the
        executor looked up before running), or None."""
        if not self.estimates:
            return None
        n = (self.estimates.get("nodes") or {}).get(str(nid))
        return None if n is None else int(n.get("rows", 0))

    def annotation(self, nid: int) -> str:
        """The estimate→actual suffix ``explain(analyze=True)`` appends
        to a node line; empty when the node has no record (fused into a
        parent, or served from cache)."""
        rec = self.nodes.get(nid)
        if rec is None:
            return ""
        est = self.est_rows(nid)
        rows = (f"rows={rec['rows']}" if est is None
                else f"rows est={est} actual={rec['rows']}")
        parts = [rows]
        if rec.get("fused"):
            parts.append("fused(count pass)")
        else:
            parts.append(f"self={rec.get('self_ns', 0) / 1e6:.1f}ms")
        sm = rec.get("self_metrics", {})
        if sm.get("shuffle.bytes_sent"):
            parts.append(f"bytes_sent={int(sm['shuffle.bytes_sent'])}")
        if sm.get("shuffle.bytes_saved"):
            parts.append(f"bytes_saved={int(sm['shuffle.bytes_saved'])}")
        if sm.get("plan_cache.hit"):
            parts.append(f"plan_cache_hits={int(sm['plan_cache.hit'])}")
        if rec.get("skew") is not None:
            parts.append(f"skew={rec['skew']:.2f}x"
                         f"@r{rec['slowest_shard']}")
        return "  <- [" + " ".join(parts) + "]"

    def as_dict(self) -> dict:
        nodes = []
        for nid in sorted(self.nodes):
            n = self.nodes[nid]
            nodes.append({
                "nid": nid, "depth": n.get("depth", 0),
                "kind": n.get("kind"), "desc": n.get("desc"),
                "rows": n["rows"], "est_rows": self.est_rows(nid),
                "wall_ms": round(n["wall_ns"] / 1e6, 3),
                "self_ms": round(n.get("self_ns", 0) / 1e6, 3),
                "metrics": n.get("self_metrics", {}),
                "shard_rows": n.get("shard_rows"),
                "skew": n.get("skew"),
                "slowest_shard": n.get("slowest_shard"),
            })
        return {"kind": PROFILE_KIND, "v": 1, "world": self.world,
                "wall_ms": self.wall_ms(),
                "plan_cache_hit": self.plan_cache_hit,
                "fingerprint": self.fingerprint,
                "had_estimates": self.estimates is not None,
                "fleet_skew": self.fleet_skew,
                "nodes": nodes}

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the profile artifact (``plan_profile[.run].rN.json``
        beside the trace exports) for ``tools/trace_report.py --plan``.
        Best-effort: a failed write is warned, never raised."""
        from ..obs import export as export_mod

        try:
            out = export_mod._artifact_path(path, "plan_profile", None)
            tmp = f"{out}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.as_dict(), fh, default=str)
            os.replace(tmp, out)
            self.artifact_path = out
            return out
        except OSError as e:
            log.warning("profile: artifact export failed (%s: %s)",
                        type(e).__name__, e)
            return None


def load_profile(path: str) -> dict:
    """Load and validate a plan-profile artifact."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("kind") != PROFILE_KIND:
        raise ValueError(f"{path}: not a plan profile "
                         f"(kind={doc.get('kind')!r})")
    if not isinstance(doc.get("nodes"), list):
        raise ValueError(f"{path}: nodes is not a list")
    return doc
