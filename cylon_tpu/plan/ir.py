"""The logical plan IR: scan → project/filter/derive → join → aggregate
→ sort/limit.

A ``LogicalPlan`` is the lazy twin of the eager ``Table`` method chain:
``Table.plan()`` starts one at a Scan node, each builder method appends
a node, and nothing touches the device until :meth:`execute`.  The tree
is the unit three consumers share:

- the **optimizer** (``plan/optimizer.py``) rewrites it — column
  pruning, shuffle elision from tracked partitioning, scan sharing,
  local fusion — into an annotated physical plan;
- the **executor** (``plan/executor.py``) lowers either the optimized
  plan or (``CYLON_TPU_PLAN=off``) the eager per-op chain;
- the **durable journal / serve result cache** fingerprint runs at PLAN
  granularity: :meth:`fingerprint` hashes the op spec chain × pruned
  input content × trace-knob config, so a repeated multi-op query is
  one cache entry, not N per-op entries.

Every node knows its output schema (names), computed with the same
naming rules the eager ops use (join collision prefixes, ``sum_col``
aggregate names), so a planned query and its eager per-op twin agree on
schema by construction.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..ops.groupby import AggOp
from ..status import Code, CylonError
from . import expr as expr_mod

ColumnRef = Union[int, str]


# ---------------------------------------------------------------------------
# nodes
# ---------------------------------------------------------------------------


class Node:
    """Base logical node; ``names`` is the output schema."""

    kind: str = "?"
    children: Tuple["Node", ...] = ()
    names: Tuple[str, ...] = ()

    def spec(self) -> tuple:
        raise NotImplementedError


class Scan(Node):
    kind = "scan"

    def __init__(self, idx: int, names: Tuple[str, ...],
                 dtype_tags: Tuple[str, ...], label: str = ""):
        self.idx = idx
        self.names = names
        self.dtype_tags = dtype_tags
        self.label = label or f"input{idx}"

    def spec(self) -> tuple:
        return ("scan", self.idx, tuple(self.names), tuple(self.dtype_tags))


class Project(Node):
    kind = "project"

    def __init__(self, child: Node, names: Tuple[str, ...]):
        missing = [n for n in names if n not in child.names]
        if missing:
            raise CylonError(Code.KeyError,
                             f"project of unknown column(s) {missing}")
        self.children = (child,)
        self.names = tuple(names)

    def spec(self) -> tuple:
        return ("project", tuple(self.names), self.children[0].spec())


class Filter(Node):
    kind = "filter"

    def __init__(self, child: Node, pred: expr_mod.Expr):
        unknown = sorted(pred.columns() - set(child.names))
        if unknown:
            raise CylonError(Code.KeyError,
                             f"filter reads unknown column(s) {unknown}")
        self.children = (child,)
        self.names = child.names
        self.pred = pred

    def spec(self) -> tuple:
        return ("filter", self.pred.spec(), self.children[0].spec())


class Derive(Node):
    kind = "derive"

    def __init__(self, child: Node, name: str, value: expr_mod.Expr):
        unknown = sorted(value.columns() - set(child.names))
        if unknown:
            raise CylonError(Code.KeyError,
                             f"derive reads unknown column(s) {unknown}")
        if name in child.names:
            raise CylonError(Code.Invalid,
                             f"derived column {name!r} already exists")
        self.children = (child,)
        self.names = child.names + (name,)
        self.name = name
        self.value = value

    def spec(self) -> tuple:
        return ("derive", self.name, self.value.spec(),
                self.children[0].spec())


class Join(Node):
    kind = "join"

    def __init__(self, left: Node, right: Node, left_on: Tuple[str, ...],
                 right_on: Tuple[str, ...], how: str, algorithm: str,
                 left_prefix: str = "l_", right_prefix: str = "r_"):
        if len(left_on) != len(right_on) or not left_on:
            raise CylonError(Code.Invalid,
                             "join needs equal-length non-empty key lists")
        for n in left_on:
            if n not in left.names:
                raise CylonError(Code.KeyError, f"left join key {n!r} missing")
        for n in right_on:
            if n not in right.names:
                raise CylonError(Code.KeyError,
                                 f"right join key {n!r} missing")
        if how not in ("inner", "left", "right", "outer", "full_outer",
                       "fullouter"):
            raise CylonError(Code.Invalid, f"bad join how {how!r}")
        if algorithm not in ("sort", "hash"):
            raise CylonError(Code.Invalid, f"bad join algorithm {algorithm!r}")
        self.children = (left, right)
        self.left_on = left_on
        self.right_on = right_on
        self.how = "outer" if how in ("full_outer", "fullouter") else how
        self.algorithm = algorithm
        self.left_prefix = left_prefix
        self.right_prefix = right_prefix
        self.names = join_names(left.names, right.names, left_prefix,
                                right_prefix)

    def out_name(self, side: str, name: str) -> str:
        """The output name of child column ``name`` from ``side`` —
        the same collision-prefix rule the eager join applies."""
        l, r = self.children[0].names, self.children[1].names
        collide = set(l) & set(r)
        if name not in collide:
            return name
        return (self.left_prefix if side == "left"
                else self.right_prefix) + name

    def spec(self) -> tuple:
        return ("join", tuple(self.left_on), tuple(self.right_on), self.how,
                self.algorithm, self.left_prefix, self.right_prefix,
                self.children[0].spec(), self.children[1].spec())


class Aggregate(Node):
    kind = "aggregate"

    def __init__(self, child: Node, by: Tuple[str, ...],
                 aggs: Tuple[Tuple[str, AggOp], ...], ddof: int):
        for n in by:
            if n not in child.names:
                raise CylonError(Code.KeyError, f"group key {n!r} missing")
        for n, _ in aggs:
            if n not in child.names:
                raise CylonError(Code.KeyError, f"agg column {n!r} missing")
        if not by or not aggs:
            raise CylonError(Code.Invalid, "groupby needs keys and aggs")
        self.children = (child,)
        self.by = by
        self.aggs = aggs
        self.ddof = int(ddof)
        self.names = tuple(by) + tuple(
            f"{op.name.lower()}_{n}" for n, op in aggs)

    def spec(self) -> tuple:
        return ("aggregate", tuple(self.by),
                tuple((n, op.name) for n, op in self.aggs), self.ddof,
                self.children[0].spec())


class Sort(Node):
    kind = "sort"

    def __init__(self, child: Node, by: Tuple[str, ...],
                 ascending: Tuple[bool, ...], nulls_first: bool):
        for n in by:
            if n not in child.names:
                raise CylonError(Code.KeyError, f"sort key {n!r} missing")
        if len(ascending) != len(by):
            raise CylonError(Code.Invalid, "ascending length mismatch")
        self.children = (child,)
        self.names = child.names
        self.by = by
        self.ascending = ascending
        self.nulls_first = bool(nulls_first)

    def spec(self) -> tuple:
        return ("sort", tuple(self.by), tuple(self.ascending),
                self.nulls_first, self.children[0].spec())


class Limit(Node):
    kind = "limit"

    def __init__(self, child: Node, n: int):
        if n < 0:
            raise CylonError(Code.Invalid, f"bad limit {n}")
        self.children = (child,)
        self.names = child.names
        self.n = int(n)

    def spec(self) -> tuple:
        return ("limit", self.n, self.children[0].spec())


def join_names(lnames: Sequence[str], rnames: Sequence[str],
               lp: str = "l_", rp: str = "r_") -> Tuple[str, ...]:
    """left ++ right with collision prefixes — the name-level twin of
    ``table._join_output_names`` (must stay in agreement)."""
    collide = set(lnames) & set(rnames)
    out_l = [lp + n if n in collide else n for n in lnames]
    out_r = [rp + n if n in collide else n for n in rnames]
    return tuple(out_l + out_r)


# ---------------------------------------------------------------------------
# the lazy builder
# ---------------------------------------------------------------------------


class LogicalPlan:
    """Immutable builder: every method returns a NEW plan sharing the
    input tables.  ``inputs[i]`` backs ``Scan(i)``."""

    def __init__(self, root: Node, inputs: List):
        self.root = root
        self.inputs = inputs

    # -- construction ----------------------------------------------------
    @staticmethod
    def scan(table, label: str = "") -> "LogicalPlan":
        tags = tuple(str(c.dtype) for c in table.columns)
        return LogicalPlan(Scan(0, tuple(table.names), tags, label), [table])

    @property
    def names(self) -> Tuple[str, ...]:
        return self.root.names

    def _wrap(self, node: Node) -> "LogicalPlan":
        return LogicalPlan(node, self.inputs)

    def project(self, refs) -> "LogicalPlan":
        names = self._resolve_many(refs)
        return self._wrap(Project(self.root, names))

    def filter(self, pred: expr_mod.Expr) -> "LogicalPlan":
        if not isinstance(pred, expr_mod.Expr):
            raise CylonError(
                Code.Invalid,
                "plan filters take a cylon_tpu.plan expression (col()/lit()"
                " combinators), not a lambda — plans must fingerprint")
        if isinstance(pred, expr_mod.Lit):
            raise CylonError(Code.Invalid,
                             "filter predicate is a constant "
                             f"({pred.value!r}); it reads no columns")
        return self._wrap(Filter(self.root, pred))

    select = filter

    def with_column(self, name: str, value: expr_mod.Expr) -> "LogicalPlan":
        if not isinstance(value, expr_mod.Expr):
            raise CylonError(Code.Invalid,
                             "with_column takes a plan expression")
        return self._wrap(Derive(self.root, str(name), value))

    def join(self, other, *, on=None, left_on=None, right_on=None,
             how: str = "inner", algorithm: str = "sort") -> "LogicalPlan":
        other_plan = _as_plan(other)
        if on is not None:
            left_on = right_on = on
        if left_on is None or right_on is None:
            raise CylonError(Code.Invalid,
                             "join requires on= or left_on=/right_on=")
        lo = self._resolve_many(left_on)
        if isinstance(right_on, (int, str)):
            right_on = [right_on]
        ro = tuple(_resolve_names(other_plan.root.names, right_on))
        # merge input lists, deduping shared tables by identity
        inputs = list(self.inputs)
        remap: Dict[int, int] = {}
        for i, t in enumerate(other_plan.inputs):
            for j, mine in enumerate(inputs):
                if mine is t:
                    remap[i] = j
                    break
            else:
                remap[i] = len(inputs)
                inputs.append(t)
        right_root = _remap_scans(other_plan.root, remap)
        node = Join(self.root, right_root, lo, ro, how, algorithm)
        return LogicalPlan(node, inputs)

    def groupby(self, by, agg: Dict[ColumnRef, Union[str, Sequence[str]]],
                ddof: int = 0) -> "LogicalPlan":
        by_n = self._resolve_many(by)
        aggs: List[Tuple[str, AggOp]] = []
        for ref, ops in agg.items():
            name = _resolve_names(self.root.names, [ref])[0]
            if isinstance(ops, (str, AggOp)):
                ops = [ops]
            for op in ops:
                aggs.append((name, AggOp.of(op)))
        return self._wrap(Aggregate(self.root, by_n, tuple(aggs), ddof))

    def sort(self, by, ascending: Union[bool, Sequence[bool]] = True,
             nulls_first: bool = True) -> "LogicalPlan":
        by_n = self._resolve_many(by)
        if isinstance(ascending, bool):
            asc = tuple([ascending] * len(by_n))
        else:
            asc = tuple(bool(a) for a in ascending)
        return self._wrap(Sort(self.root, by_n, asc, nulls_first))

    def limit(self, n: int) -> "LogicalPlan":
        return self._wrap(Limit(self.root, n))

    # -- execution surface ----------------------------------------------
    def execute(self, ctx=None):
        """Run the plan and return a Table (optimized when
        ``CYLON_TPU_PLAN`` allows, eager per-op otherwise)."""
        from . import executor

        return executor.execute(self, ctx=ctx)

    def explain(self, optimized: Optional[bool] = None,
                analyze: bool = False) -> str:
        """Pretty-print the (optimized) plan: stages, elided shuffles,
        pruned columns, plane widths.  Pure host-side — nothing runs —
        UNLESS ``analyze=True`` (EXPLAIN ANALYZE): the plan executes
        once with the profiler on and every node line gains an
        estimate→actual suffix (rows, self time, exchange bytes,
        per-shard skew; estimates from the statistics catalog when a
        prior run observed this plan)."""
        from . import explain as explain_mod

        return explain_mod.explain(self, optimized=optimized,
                                   analyze=analyze)

    def profile(self, ctx=None):
        """Execute once with the profiler on; returns ``(Table,
        PlanProfile)`` — the programmatic EXPLAIN ANALYZE surface
        (per-node rows/bytes/skew as data instead of rendered text)."""
        from . import executor
        from . import profile as profile_mod

        prof = profile_mod.PlanProfile()
        t = executor.execute(self, ctx=ctx, profile=prof)
        return t, prof

    def fingerprint(self) -> str:
        """Plan-granularity content fingerprint: op spec chain × world ×
        pruned input content × trace-knob config.  The durable journal
        and the serve result cache key planned runs by this — one entry
        per multi-op query.

        When the adaptive planner chose physical strategies (broadcast
        joins, salted repartitions), ``optimizer.strategy_spec`` is
        folded into the header — a stats-dependent choice the cache key
        omitted would serve the wrong program (the CY103/CY109 lesson;
        cylint CY112 machine-checks this fold).  With no strategies
        chosen the header is byte-identical to the pre-adaptive
        fingerprint, so existing journals stay valid."""
        from . import optimizer

        phys = optimizer.optimize(self, enabled=True)
        world = self._world()
        strat = optimizer.strategy_spec(phys)
        header = ((self.root.spec(), world) if not strat
                  else (self.root.spec(), world, ("adaptive", strat)))
        return self._content_fingerprint(phys, header)

    def base_fingerprint(self) -> str:
        """Strategy-INDEPENDENT content fingerprint: like
        :meth:`fingerprint` but optimized with ``adaptive=False``, so
        the header never carries strategy choices.  The statistics
        catalog keys observations by this — the cost model must read
        stats describing what the query IS regardless of what a prior
        planner chose, and the fingerprint→optimize→lookup recursion is
        structurally impossible (``adaptive=False`` never consults the
        catalog).  Equal to :meth:`fingerprint` whenever no adaptive
        strategy fired."""
        from . import optimizer

        phys = optimizer.optimize(self, enabled=True, adaptive=False)
        return self._content_fingerprint(
            phys, (self.root.spec(), self._world()))

    def _content_fingerprint(self, phys, header) -> str:
        from .. import durable
        from . import optimizer

        frames = []
        for scan, keep in optimizer.scan_prunes(phys):
            t = self.inputs[scan.idx].project(list(keep))
            frames.append((tuple(keep), t.to_numpy()))
        return durable.run_fingerprint("plan", header, frames)

    def approx_input_bytes(self) -> int:
        """Static HBM admission estimate (serve layer): buffer bytes of
        the pruned scan columns — array metadata only, no device sync.
        Strategy choices never change the pruned column sets, so the
        base (non-adaptive) optimization suffices and costs no catalog
        lookup."""
        from . import optimizer

        phys = optimizer.optimize(self, enabled=True, adaptive=False)
        total = 0
        for scan, keep in optimizer.scan_prunes(phys):
            t = self.inputs[scan.idx]
            for name, c in zip(t.names, t.columns):
                if name in keep:
                    total += int(c.data.nbytes) + int(c.validity.nbytes)
                    if c.lengths is not None:
                        total += int(c.lengths.nbytes)
        return total

    # -- helpers ---------------------------------------------------------
    def _world(self) -> int:
        worlds = {t.num_shards for t in self.inputs}
        if len(worlds) > 1:
            raise CylonError(Code.Invalid,
                             f"plan inputs span different worlds {worlds}")
        return worlds.pop() if worlds else 1

    def _ctx(self):
        return self.inputs[0].ctx if self.inputs else None

    def _resolve_many(self, refs) -> Tuple[str, ...]:
        if isinstance(refs, (int, str)):
            refs = [refs]
        return tuple(_resolve_names(self.root.names, refs))


def _resolve_names(names: Tuple[str, ...], refs) -> List[str]:
    out = []
    for r in refs:
        if isinstance(r, str):
            if r not in names:
                raise CylonError(Code.KeyError, f"no column named {r!r}")
            out.append(r)
        else:
            i = int(r)
            if not 0 <= i < len(names):
                raise CylonError(Code.IndexError,
                                 f"column index {i} out of range")
            out.append(names[i])
    return out


def _remap_scans(node: Node, remap: Dict[int, int]) -> Node:
    """Rewrite Scan input indices after an input-list merge (join of two
    plans).  Rebuilds only the spine that changes."""
    if isinstance(node, Scan):
        new_idx = remap.get(node.idx, node.idx)
        if new_idx == node.idx:
            return node
        label = (f"input{new_idx}" if node.label == f"input{node.idx}"
                 else node.label)
        return Scan(new_idx, node.names, node.dtype_tags, label)
    new_children = tuple(_remap_scans(c, remap) for c in node.children)
    if all(n is o for n, o in zip(new_children, node.children)):
        return node
    import copy

    clone = copy.copy(node)
    clone.children = new_children
    return clone


def _as_plan(other) -> LogicalPlan:
    if isinstance(other, LogicalPlan):
        return other
    # duck-typed Table (avoid the import cycle)
    if hasattr(other, "columns") and hasattr(other, "names"):
        return LogicalPlan.scan(other)
    raise CylonError(Code.Invalid,
                     f"cannot join a plan with {type(other).__name__}")
