"""Plan executor: lowers an (optimized) physical plan onto the engine.

Two lowering modes share one interpreter, so the A/B is exact:

- ``CYLON_TPU_PLAN`` off — the EAGER plan: no pruning, every
  distributed join/group-by pays its full shuffle, every intermediate
  materializes (bit-identical to the ``Table`` method chain by
  construction: the same ``_local_join`` / ``distributed_groupby`` /
  shuffle code paths run in the same order);
- on (default) — the optimized plan: pruned scans, elided/shared
  exchanges, and the fused join→aggregate shard body.

Bit-identity between the two modes is a hard invariant (asserted by
tests and the full-tree smoke): elision never changes which rows meet,
only where; the fused body runs the same kernels in the same order on
the same values; and an elided group-by's final combine folds exactly
one partial per group (co-location guarantees it), which is the
identity for every combine op.

Durable/serve integration is at PLAN granularity: one fingerprint for
the whole op chain (``LogicalPlan.fingerprint``), one journaled result
frame — a repeated plan replays from spill with zero compiles and zero
device passes (``plan.cache_hit``; serve op ``"plan"``).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import config, durable
from ..obs import fleet as obs_fleet
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..obs import stats_catalog
from ..status import Code, CylonError, Status
from . import ir, optimizer
from . import profile as profile_mod


def planner_enabled() -> bool:
    """Whether plan.execute() runs the optimizer (``CYLON_TPU_PLAN``;
    auto/on = optimize, off = eager per-op lowering).  A host-side
    plan-build choice like CYLON_TPU_SHUFFLE: each mode builds
    differently-keyed stage programs, so no cache-key participation."""
    return str(config.knob("CYLON_TPU_PLAN")) not in ("0", "off")


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def execute(plan: "ir.LogicalPlan", ctx=None, pass_guard=None,
            stats_out: Optional[dict] = None,
            profile: Optional["profile_mod.PlanProfile"] = None):
    """Run the plan, returning a Table.  With ``CYLON_TPU_DURABLE_DIR``
    set the run is journaled at plan granularity; a repeated fingerprint
    is served entirely from spill (a LOCAL 1-shard table — zero
    compiles, zero device passes).

    ``profile=`` (or the ``CYLON_TPU_PROFILE`` knob) collects per-node
    actuals into a :class:`~cylon_tpu.plan.profile.PlanProfile` — the
    EXPLAIN ANALYZE substrate — and, with ``CYLON_TPU_STATS_DIR`` set,
    persists the observed statistics to the catalog under the plan
    fingerprint.  All host-side: the traced programs and their cache
    keys are identical with the profiler on or off."""
    from ..table import Table

    ctx = ctx if ctx is not None else plan._ctx()
    if ctx is None:
        from ..context import default_context

        ctx = default_context()
    world = plan._world()
    enabled = planner_enabled()
    stats = stats_out if stats_out is not None else {}
    stats.update(passes=1, passes_skipped=0, parts_run=0)
    prof = profile
    if prof is None and profile_mod.profiler_enabled():
        prof = profile_mod.PlanProfile()

    fp: Optional[str] = None
    sfp: Optional[str] = None
    journal = None
    if durable.enabled() or (prof is not None and stats_catalog.enabled()):
        fp = plan.fingerprint()
    if prof is not None and fp is not None and stats_catalog.enabled():
        # the CATALOG is keyed by the strategy-independent base
        # fingerprint: observations must describe what the query IS, not
        # what the planner chose, or a strategy flip would orphan the
        # very statistics that justified it.  With the adaptive knob off
        # the full fingerprint IS the base one (no strategies to fold),
        # so the second content hash is skipped.
        sfp = (plan.base_fingerprint() if optimizer.planner_adaptive()
               else fp)
    if prof is not None:
        prof.fingerprint = fp
        if sfp is not None:
            prof.estimates = stats_catalog.lookup(sfp)
    if durable.enabled():
        journal = durable.open_run(fp, "plan", world=world)
        if journal is not None and journal.is_complete():
            got = journal.load_pass(0, 0)
            if got is not None:
                frame, rows = got
                obs_metrics.counter_add("plan.cache_hit")
                obs_spans.instant("plan.cache_hit", fingerprint=fp[:12],
                                  rows=rows)
                stats.update(passes_skipped=1, rows=rows, cache_hit=True)
                if prof is not None:
                    prof.plan_cache_hit = True
                    prof.finalize(optimizer.optimize(plan, enabled=enabled),
                                  0)
                    prof.export()
                from ..context import CylonContext

                return Table.from_numpy(list(frame), list(frame.values()),
                                        ctx=CylonContext.Init())

    t_run0 = time.perf_counter_ns()
    try:
        with obs_spans.span("plan.optimize", world=world, enabled=enabled):
            phys = optimizer.optimize(plan, enabled=enabled)
        if enabled:
            obs_metrics.counter_add("plan.shuffles_elided",
                                    phys.shuffles_elided)
            obs_metrics.counter_add("plan.columns_pruned",
                                    phys.columns_pruned)
        if phys.adaptive:
            obs_metrics.counter_add("plan.broadcast_joins",
                                    phys.broadcast_joins)
            obs_metrics.counter_add("plan.keys_salted",
                                    phys.keys_salted)
        with obs_spans.span("plan.execute", world=world, nodes=phys.nodes,
                            elided=phys.shuffles_elided,
                            pruned=phys.columns_pruned, optimized=enabled):
            result = _Executor(plan, phys, ctx, pass_guard, prof).run()
    except Exception as e:
        # planner-path terminal failure: dump the flight recorder like
        # exec/serve/elastic terminal events already do, so the
        # post-mortem exists even when tracing was never armed.  NOT
        # terminal: a pass_guard's EpochMismatch is an ordinary elastic
        # resume (elastic_run catches it and re-derives), and Cancelled
        # is a deliberate caller action — dumping "plan_fatal" for
        # those would litter every membership change / cancel with
        # misleading fatal post-mortems (exec.py's fatal() draws the
        # same line)
        st = Status.from_exception(e)
        if st.code not in (Code.EpochMismatch, Code.Cancelled):
            # terminal instant + flight dump, both stamped with the
            # active request trace (the instant via the ambient context,
            # the dump via flight_record's trace capture), so the
            # post-mortem joins to the request that died here
            obs_spans.instant("plan.fatal", code=st.code.name,
                              fingerprint=fp[:12] if fp else None,
                              world=world)
            obs_fleet.flight_record(
                "plan_fatal", code=st.code.name,
                fingerprint=fp[:12] if fp else None, world=world,
                error=f"{type(e).__name__}: {e}"[:200])
        raise
    stats.update(parts_run=1, rows=result.row_count, cache_hit=False)
    if prof is not None:
        prof.finalize(phys, time.perf_counter_ns() - t_run0)
        prof.attach_fleet_skew(ctx)
        if sfp is not None:
            stats_catalog.record(sfp, prof.catalog_record(plan))
        prof.export()

    if journal is not None:
        frame = result.to_numpy()
        journal.record_pass(0, 0, frame, int(stats["rows"]))
        journal.record_done(1, int(stats["rows"]))
        durable.gc_journal()
    if phys.root.part is not None:
        result._partitioning = phys.root.part
    return result


def run_service(plan: "ir.LogicalPlan", *, ctx=None, pass_guard=None,
                **_kw):
    """Serve-layer runner (op ``"plan"``): executes on the plan inputs'
    own mesh (the service ``ctx`` is accepted for signature parity) and
    returns ``(host frame, stats)`` with the journal-replay stats shape
    ``serve.cache.served_from_journal`` expects."""
    stats: dict = {}
    t = execute(plan, pass_guard=pass_guard, stats_out=stats)
    return t.to_numpy(), stats


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


class _Executor:
    def __init__(self, plan, phys: optimizer.PhysPlan, ctx, pass_guard,
                 profile: Optional["profile_mod.PlanProfile"] = None):
        self.plan = plan
        self.phys = phys
        self.ctx = ctx
        self.world = phys.world
        self.pass_guard = pass_guard
        self.profile = profile

    def run(self):
        return self._exec(self.phys.root)

    def _guard(self) -> None:
        if self.pass_guard is not None:
            self.pass_guard()

    # -- generic dispatch ------------------------------------------------
    def _exec(self, p: optimizer.Phys):
        prof = self.profile
        if prof is None:
            return self._exec_node(p)
        # profiled: two clock reads + a handful of counter reads around
        # the node, plus one row-count fetch of the ALREADY-materialized
        # result — the node's subtree deltas; finalize() subtracts
        # recorded descendants for self values.  Nothing traced changes.
        before = profile_mod.counters_now()
        t0 = time.perf_counter_ns()
        t = self._exec_node(p)
        prof.record_node(p, t, time.perf_counter_ns() - t0, before)
        return t

    def _exec_node(self, p: optimizer.Phys):
        n = p.node
        if isinstance(n, ir.Scan):
            return self._project_to(self.plan.inputs[n.idx], p.keep)
        if isinstance(n, ir.Project):
            return self._project_to(self._exec(p.children[0]), p.keep)
        if isinstance(n, ir.Filter):
            t = self._filter_table(self._exec(p.children[0]), n.pred)
            return self._project_to(t, p.keep)
        if isinstance(n, ir.Derive):
            t = self._exec(p.children[0])
            if not p.ann.get("dead"):
                t = self._derive_table(t, n.name, n.value)
            return self._project_to(t, p.keep)
        if isinstance(n, ir.Join):
            return self._project_to(self._exec_join(p), p.keep)
        if isinstance(n, ir.Aggregate):
            if p.ann.get("fuse"):
                return self._project_to(self._fused_join_agg(p), p.keep)
            return self._project_to(self._exec_agg(p), p.keep)
        if isinstance(n, ir.Sort):
            return self._project_to(self._exec_sort(p), p.keep)
        if isinstance(n, ir.Limit):
            return self._project_to(self._exec_limit(p), p.keep)
        raise CylonError(Code.Invalid, f"unknown node {n.kind!r}")

    @staticmethod
    def _project_to(t, keep: Tuple[str, ...]):
        if tuple(t.names) == tuple(keep):
            return t
        return t.project(list(keep))

    # -- scans / local row ops -------------------------------------------
    def _filter_table(self, t, pred):
        import jax.numpy as jnp

        from ..ops import compact as compact_mod
        from ..table import Table, _shard_wise

        names, ctx = t.names, t.ctx

        def fn(tt):
            cap = tt.columns[0].data.shape[0]
            env = dict(zip(names, tt.columns))
            c = pred.evaluate(env)
            keep = c.data & c.validity & compact_mod.live_mask(
                cap, tt.row_counts[0])
            perm, m = compact_mod.compact_indices(keep)
            live = compact_mod.live_mask(cap, m)
            cols = tuple(col.take(perm, valid_mask=live)
                         for col in tt.columns)
            return Table(cols, jnp.reshape(m, (1,)), names, ctx)

        return _shard_wise(ctx, fn, t, key=("plan_filter", names,
                                            pred.spec()))

    def _derive_table(self, t, name: str, value):
        from ..table import Table, _shard_wise

        names, ctx = t.names, t.ctx
        out_names = names + (name,)

        def fn(tt):
            env = dict(zip(names, tt.columns))
            c = value.evaluate(env)
            return Table(tt.columns + (c,), tt.row_counts, out_names, ctx)

        return _shard_wise(ctx, fn, t, key=("plan_derive", names, name,
                                            value.spec()))

    def _exec_chain(self, p: optimizer.Phys, keep: Tuple[str, ...]):
        """Execute a pure scan chain with an overridden column set (the
        shared-scan rule's union keep).  Profiled like ``_exec`` — a
        self-join CSE'd by the shared-scan rule must still feed scan
        cardinality and filter selectivity to the catalog (the chain
        runs ONCE for both sides, so records land on the LEFT child's
        subtree; the right twin stays unannotated)."""
        prof = self.profile
        if prof is None:
            return self._exec_chain_node(p, keep)
        before = profile_mod.counters_now()
        t0 = time.perf_counter_ns()
        t = self._exec_chain_node(p, keep)
        if p.nid not in prof.nodes:
            prof.record_node(p, t, time.perf_counter_ns() - t0, before)
        return t

    def _exec_chain_node(self, p: optimizer.Phys, keep: Tuple[str, ...]):
        n = p.node
        if isinstance(n, ir.Scan):
            t = self.plan.inputs[n.idx]
            want = set(keep)
            return t.project([c for c in t.names if c in want])
        child = p.children[0]
        if isinstance(n, ir.Project):
            return self._exec_chain(child, keep)
        if isinstance(n, ir.Filter):
            below = tuple(dict.fromkeys(tuple(keep)
                                        + tuple(sorted(n.pred.columns()))))
            t = self._exec_chain(child, below)
            t = self._filter_table(t, n.pred)
            return self._project_to(t, tuple(c for c in t.names
                                             if c in set(keep)))
        if isinstance(n, ir.Derive):
            below = tuple(dict.fromkeys(
                tuple(c for c in keep if c != n.name)
                + tuple(sorted(n.value.columns()))))
            t = self._exec_chain(child, below)
            if n.name in set(keep):
                t = self._derive_table(t, n.name, n.value)
            return self._project_to(t, tuple(c for c in t.names
                                             if c in set(keep)))
        raise AssertionError(n.kind)

    # -- shuffles ---------------------------------------------------------
    def _shuffle(self, t, keys: Tuple[str, ...], side: str):
        from ..parallel import ops as par_ops

        self._guard()
        idx = tuple(t.names.index(k) for k in keys)
        with obs_spans.span("plan.stage", kind="shuffle", side=side,
                            keys=len(idx), columns=len(t.names)):
            return par_ops.shuffle(t, idx)

    def _note_elided(self, side: str, keys: Tuple[str, ...]) -> None:
        obs_spans.instant("plan.shuffle_elided", side=side,
                          keys=",".join(keys))

    def _broadcast(self, t, side: str, p: optimizer.Phys):
        from ..parallel import ops as par_ops

        self._guard()
        est = p.ann.get("broadcast") or {}
        with obs_spans.span("plan.stage", kind="broadcast", side=side,
                            columns=len(t.names),
                            est_bytes=est.get("bytes"),
                            source=est.get("source")):
            return par_ops.broadcast_gather(t)

    def _join_inputs(self, p: optimizer.Phys):
        node: ir.Join = p.node  # type: ignore[assignment]
        lc, rc = p.children
        if p.ann.get("shared"):
            union = tuple(dict.fromkeys(tuple(lc.keep) + tuple(rc.keep)))
            base = self._exec_chain(lc, union)
            shuffled = self._shuffle(base, p.ann["left"][1], side="shared")
            self._note_elided("shared", p.ann["right"][1])
            lt = self._project_to(shuffled, lc.keep)
            rt = self._project_to(shuffled, rc.keep)
            return lt, rt
        lt = self._exec(lc)
        rt = self._exec(rc)
        la, ra = p.ann.get("left", ("local",)), p.ann.get("right",
                                                          ("local",))
        if la[0] == "shuffle":
            lt = self._shuffle(lt, la[1], side="left")
        elif la[0] == "elide":
            self._note_elided("left", la[1])
        elif la[0] == "broadcast":
            lt = self._broadcast(lt, "left", p)
        if ra[0] == "shuffle":
            rt = self._shuffle(rt, ra[1], side="right")
        elif ra[0] == "elide":
            self._note_elided("right", ra[1])
        elif ra[0] == "broadcast":
            rt = self._broadcast(rt, "right", p)
        # ("keep", keys): the broadcast join's probe side stays exactly
        # where it is — zero bytes moved
        return lt, rt

    def _join_cfg(self, node: ir.Join, lt, rt):
        from ..config import JoinConfig

        cfg = JoinConfig.of(node.how, node.algorithm,
                            tuple(lt.names.index(k) for k in node.left_on),
                            tuple(rt.names.index(k) for k in node.right_on),
                            node.left_prefix, node.right_prefix)
        from ..table import _check_join_keys

        return _check_join_keys(lt, rt, cfg)

    def _exec_join(self, p: optimizer.Phys):
        from ..table import _local_join

        node: ir.Join = p.node  # type: ignore[assignment]
        lc, rc = p.children
        lt, rt = self._join_inputs(p)
        cfg = self._join_cfg(node, lt, rt)
        self._guard()
        with obs_spans.span("plan.stage", kind="join", how=node.how,
                            algorithm=node.algorithm):
            joined = _local_join(lt, rt, cfg)
        # rename the pruned physical output to the LOGICAL names (the
        # collision set of the full schemas, not the pruned ones)
        logical = tuple(node.out_name("left", n) for n in lc.keep) \
            + tuple(node.out_name("right", n) for n in rc.keep)
        return joined.rename(list(logical))

    # -- aggregates -------------------------------------------------------
    def _agg_spec(self, node: ir.Aggregate, names: Tuple[str, ...]):
        by_idx = tuple(names.index(n) for n in node.by)
        aggs = tuple((names.index(n), op) for n, op in node.aggs)
        return by_idx, aggs

    def _exec_agg(self, p: optimizer.Phys):
        from ..parallel import ops as par_ops
        from ..table import _local_groupby

        node: ir.Aggregate = p.node  # type: ignore[assignment]
        t = self._exec(p.children[0])
        by_idx, aggs = self._agg_spec(node, tuple(t.names))
        mode = p.ann.get("mode", "eager")
        self._guard()
        with obs_spans.span("plan.stage", kind="aggregate", mode=mode,
                            keys=len(by_idx), aggs=len(aggs)):
            if mode == "local" or t.num_shards == 1:
                out = _local_groupby(t, by_idx, aggs, node.ddof)
            elif mode == "elided":
                self._note_elided("aggregate", node.by)
                out = par_ops.distributed_groupby(t, by_idx, aggs,
                                                  node.ddof,
                                                  pre_partitioned=True)
            else:
                out = par_ops.distributed_groupby(
                    t, by_idx, aggs, node.ddof,
                    salt=int(p.ann.get("salt", 0)))
        return out.rename(list(node.names))

    def _fused_join_agg(self, p: optimizer.Phys):
        """ONE jitted shard body: join probe + chained derives/filters +
        local aggregate — the join intermediate never materializes.  An
        exact count pass sizes the join output first (a too-small
        capacity would silently truncate INSIDE the fused program, so
        the planner never reuses a stale capacity here)."""
        import jax.numpy as jnp

        from ..config import JoinAlgorithm
        from ..ops import compact as compact_mod
        from ..ops import groupby as groupby_mod
        from ..ops import join as join_mod
        from ..parallel import ops as par_ops
        from ..table import Table, _cap_round, _shard_wise

        node: ir.Aggregate = p.node  # type: ignore[assignment]
        jphys: optimizer.Phys = p.ann["fuse_join"]  # type: ignore
        chain: List[optimizer.Phys] = p.ann["fuse_chain"]  # type: ignore
        jnode: ir.Join = jphys.node  # type: ignore[assignment]
        lc, rc = jphys.children

        lt, rt = self._join_inputs(jphys)
        cfg = self._join_cfg(jnode, lt, rt)
        jt, algo = cfg.join_type, (
            "hash" if cfg.algorithm == JoinAlgorithm.HASH else "sort")
        join_names = tuple(jnode.out_name("left", n) for n in lc.keep) \
            + tuple(jnode.out_name("right", n) for n in rc.keep)
        ctx = lt.ctx
        mode = p.ann.get("mode", "local")
        if mode == "elided":
            self._note_elided("aggregate", node.by)

        self._guard()
        stage_spec = ("plan_fused", jnode.spec()[:7], node.spec()[:4],
                      tuple(ph.node.spec()[:3] for ph in chain))

        def count_fn(a, b):
            c = join_mod.join_row_count(
                a.columns, a.row_counts[0], b.columns, b.row_counts[0],
                cfg.left_on, cfg.right_on, jt, algo)
            return jnp.reshape(c, (1,))

        with obs_spans.span("plan.stage", kind="join_count"):
            counts = _shard_wise(ctx, count_fn, lt, rt,
                                 key=("plan_join_count", stage_spec))
            out_cap = _cap_round(max(1, int(jnp.max(counts))))
        if self.profile is not None:
            # the fused join never materializes, but the exact count
            # pass that sizes it IS its observed cardinality — record
            # it so join selectivity reaches the statistics catalog
            self.profile.record_fused_join(jphys, counts)

        # the aggregate's partial/final split mirrors distributed_groupby
        # exactly (bit-identity with the eager path); 1-shard worlds run
        # the requested aggs directly, matching _local_groupby
        agg_names = tuple(node.names)
        by_names, aggs_by_name = node.by, node.aggs
        ddof = node.ddof
        split = mode == "elided"
        if split:
            partial_list, partial_index = par_ops.groupby_partial_plan(
                aggs_by_name)

        def fused_fn(a: Table, b: Table) -> Table:
            cols, m = join_mod.join_gather(
                a.columns, a.row_counts[0], b.columns, b.row_counts[0],
                cfg.left_on, cfg.right_on, jt, out_cap, algo)
            env = dict(zip(join_names, cols))
            count = m
            for ph in reversed(chain):
                cn = ph.node
                if isinstance(cn, ir.Derive):
                    if not ph.ann.get("dead"):
                        env[cn.name] = cn.value.evaluate(env)
                elif isinstance(cn, ir.Filter):
                    cap = next(iter(env.values())).data.shape[0]
                    c = cn.pred.evaluate(env)
                    keepm = c.data & c.validity & compact_mod.live_mask(
                        cap, count)
                    perm, count = compact_mod.compact_indices(keepm)
                    live = compact_mod.live_mask(cap, count)
                    env = {k: col.take(perm, valid_mask=live)
                           for k, col in env.items()}
                # Project: column selection is implicit in env-by-name
            in_names = tuple(by_names) + tuple(n for n, _ in aggs_by_name)
            in_names = tuple(dict.fromkeys(in_names))
            in_cols = tuple(env[n] for n in in_names)
            by_idx = tuple(in_names.index(n) for n in by_names)
            nkeys = len(by_idx)
            if not split:
                aggs_i = tuple((in_names.index(n), op)
                               for n, op in aggs_by_name)
                out_cols, g = groupby_mod.hash_groupby(
                    in_cols, count, by_idx, aggs_i, ddof)
                return Table(tuple(out_cols), jnp.reshape(g, (1,)),
                             agg_names, ctx)
            partial_i = tuple((in_names.index(n), pop)
                              for n, pop in partial_list)
            pcols, pm = groupby_mod.hash_groupby(in_cols, count, by_idx,
                                                 partial_i, ddof)
            key_range = tuple(range(nkeys))
            final_aggs = tuple(
                (nkeys + i, groupby_mod.combine_op(pop))
                for i, (_, pop) in enumerate(partial_list))
            fcols, fm = groupby_mod.hash_groupby(pcols, pm, key_range,
                                                 final_aggs, ddof)
            out_cols = par_ops.finalize_groupby_columns(
                fcols, nkeys, tuple((in_names.index(n), op)
                                    for n, op in aggs_by_name),
                {(in_names.index(n), pop): i
                 for i, (n, pop) in enumerate(partial_list)}, ddof)
            return Table(tuple(out_cols), jnp.reshape(fm, (1,)),
                         agg_names, ctx)

        with obs_spans.span("plan.stage", kind="fused_join_agg",
                            mode=mode, out_cap=out_cap):
            out = _shard_wise(ctx, fused_fn, lt, rt,
                              key=("plan_fused_exec", stage_spec, out_cap))
        return out

    # -- sort / limit -----------------------------------------------------
    def _exec_sort(self, p: optimizer.Phys):
        from ..config import SortOptions

        node: ir.Sort = p.node  # type: ignore[assignment]
        t = self._exec(p.children[0])
        self._guard()
        opts = SortOptions(ascending=node.ascending[0],
                           nulls_first=node.nulls_first)
        with obs_spans.span("plan.stage", kind="sort",
                            keys=len(node.by)):
            return t.distributed_sort(list(node.by), options=opts,
                                      ascending=list(node.ascending))

    def _exec_limit(self, p: optimizer.Phys):
        import jax.numpy as jnp

        from ..table import Table

        node: ir.Limit = p.node  # type: ignore[assignment]
        t = self._exec(p.children[0])
        self._guard()
        with obs_spans.span("plan.stage", kind="limit", n=node.n):
            cols, total = t._gathered_columns()
            local = Table(tuple(cols), jnp.asarray([total], jnp.int32),
                          t.names, t.ctx)
            n = min(node.n, int(total))
            return local.take_rows(np.arange(n, dtype=np.int64))
