"""Rule-based optimizer: logical plan -> annotated physical plan.

Four rules carry the win, in the order they run:

1. **column pruning** (``_rule_required_columns``) — the required-column
   set propagates top-down; every Scan keeps only what some ancestor
   actually reads, so dead columns are dropped BEFORE plane packing and
   ``parallel/plane.py``'s word layout (hence ``shuffle.bytes_sent``)
   shrinks with projected width.
2. **shuffle elision** (``_rule_shuffle_elision``) — partitioning is a
   tracked *property* of data (the arxiv 2112.01075 argument), not a
   side effect of each op: every node derives its output partitioning
   (``hash(keys) % world``, stamped by ``parallel/ops.shuffle``), and a
   join/group-by whose keys are already compatibly partitioned skips
   its partition→pack→all_to_all stage entirely.  Compatibility is
   positional-subset: data hash-partitioned on ``(a,)`` is co-located
   for a join on ``(a, b)`` (equal pairs have equal ``a``), and for a
   group-by whose key SET contains every partition key.
3. **scan sharing** (``_rule_share_scans``) — two join sides that are
   the same scan chain (table, filters) shuffled on the same source
   keys execute ONE exchange over the union of their columns (the
   self-join shape: 2x -> 1x packed exchange).
4. **local fusion** (``_rule_fuse_local``) — a group-by whose input
   chain is join → (derive/filter/project)* with no intervening
   exchange runs inside ONE jitted shard body (join probe + derives +
   local aggregate), never materializing the join intermediate.

Everything here is host-side static analysis over plan + input
metadata; nothing is traced, so the per-op jaxpr budget goldens are
untouched and ``explain()`` can render every decision without running.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import config
from ..parallel import plane as plane_mod
from ..status import Code, CylonError
from . import ir

#: partitioning property: ("hash", alternatives, world) where each
#: alternative is an ordered tuple of column names the rows were
#: hash-placed by — a join output is compatibly partitioned by EITHER
#: side's key names, hence alternatives.
Partitioning = Tuple[str, Tuple[Tuple[str, ...], ...], int]


@dataclass
class Phys:
    """One physical node: the logical node + pruning/shuffle/fusion
    annotations the executor and explain() consume.  ``nid`` is the
    stable preorder id :func:`optimize` assigns — the profiler
    (``plan/profile.py``) and the statistics catalog key per-node
    actuals by it, so estimate lookups from a prior run line up
    node-for-node (the numbering is a pure function of the plan tree
    and the enabled flag)."""

    node: ir.Node
    children: List["Phys"] = field(default_factory=list)
    keep: Tuple[str, ...] = ()
    part: Optional[Partitioning] = None
    ann: Dict[str, object] = field(default_factory=dict)
    nid: int = -1


@dataclass
class PhysPlan:
    root: Phys
    world: int
    enabled: bool
    shuffles_elided: int = 0
    columns_pruned: int = 0
    nodes: int = 0
    #: adaptive (statistics-driven) strategy selection was active for
    #: this optimization — False reproduces the PR-9 planner exactly.
    adaptive: bool = False
    broadcast_joins: int = 0
    keys_salted: int = 0
    #: the plan/cost.py CostModel the adaptive rules consulted (None
    #: when adaptive is off) — explain() renders its estimates.
    model: object = field(default=None, repr=False)


def hash_partitioning(names: Sequence[str], world: int) -> Partitioning:
    return ("hash", (tuple(names),), world)


def join_partition_alternatives(how: str, left_names: Sequence[str],
                                right_names: Sequence[str],
                                left_keys: Sequence[str],
                                right_keys: Sequence[str],
                                left_prefix: str = "l_",
                                right_prefix: str = "r_",
                                ) -> Tuple[Tuple[str, ...], ...]:
    """Output-name key alternatives a shuffled join's result is
    hash-placed by.  THE single source of the validity rule — the eager
    stamp (``table._stamp_join_partitioning``) and the planner's
    derived property (``_join_out_partitioning``) both call this, so
    they can never disagree: a side's key names are valid only when its
    unmatched rows still carry real key values (INNER both, LEFT left
    keys, RIGHT right keys, FULL_OUTER neither — either side's null
    keys break the placement property), with the eager join's
    collision-prefix naming applied."""
    collide = set(left_names) & set(right_names)

    def out(prefix: str, name: str) -> str:
        return prefix + name if name in collide else name

    alts: List[Tuple[str, ...]] = []
    if how in ("inner", "left"):
        alts.append(tuple(out(left_prefix, k) for k in left_keys))
    if how in ("inner", "right"):
        alts.append(tuple(out(right_prefix, k) for k in right_keys))
    return tuple(alts)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def planner_adaptive() -> bool:
    """Whether :func:`optimize` additionally runs the statistics-driven
    strategy rules (``CYLON_TPU_PLAN_ADAPTIVE``; 1/on = adaptive,
    auto/off = the PR-9 rule-only planner — auto stays off until the
    TPU calibration round).  Chosen strategies ride the plan
    fingerprint and distinctly-keyed stage programs, so no cache-key
    participation is needed."""
    return str(config.knob("CYLON_TPU_PLAN_ADAPTIVE")) in ("1", "on")


def optimize(plan: "ir.LogicalPlan", enabled: bool = True,
             adaptive: Optional[bool] = None) -> PhysPlan:
    """Annotate the plan.  ``enabled=False`` produces the EAGER physical
    plan: no pruning, every distributed join/group-by shuffles, no
    sharing, no fusion — the per-op baseline the A/B arms and the
    bit-identity gates compare against.

    ``adaptive`` layers the statistics-driven strategy rules (broadcast
    joins, skew salting) on top; None defers to the
    ``CYLON_TPU_PLAN_ADAPTIVE`` knob.  Adaptive mode NEVER changes the
    tree shape or the column-pruning/nid numbering — only per-node
    strategy annotations — so the base (``adaptive=False``) and
    adaptive plans stay node-for-node comparable."""
    world = plan._world()
    if adaptive is None:
        adaptive = planner_adaptive()
    adaptive = bool(adaptive) and enabled and world > 1
    out = PhysPlan(root=None, world=world, enabled=enabled,  # type: ignore
                   adaptive=adaptive)
    if adaptive:
        from . import cost

        out.model = cost.CostModel(plan, world, record=lookup_stats(plan))
    req = tuple(plan.root.names) if enabled else None
    out.root = _build(plan, plan.root, req, world, enabled, out)
    if enabled:
        _rule_fuse_local(out.root, world, out)
    out.nodes = _count(out.root)
    _assign_nids(out.root, 0)
    return out


def strategy_spec(phys: PhysPlan) -> tuple:
    """The adaptive strategy choices of an optimized plan as a sorted,
    hashable spec — ``()`` when no rule fired (or adaptive is off).
    ``LogicalPlan.fingerprint`` folds this into the plan fingerprint so
    a stats-dependent choice can never serve a cached program built for
    a different strategy (the CY103/CY109 lesson; cylint CY112
    machine-checks the fold)."""
    out: List[tuple] = []

    def walk(p: Phys) -> None:
        b = p.ann.get("broadcast")
        if isinstance(b, dict):
            out.append((p.nid, "broadcast_join", b.get("side")))
        s = p.ann.get("salt")
        if s:
            out.append((p.nid, "salted_groupby", int(s)))
        for c in p.children:
            walk(c)

    walk(phys.root)
    return tuple(sorted(out))


def _assign_nids(p: Phys, next_id: int) -> int:
    """Stable preorder node ids: the profiler/statistics-catalog key.
    Deterministic per (plan tree, enabled), so two optimizations of the
    same plan — this process's or a prior run's — number identically."""
    p.nid = next_id
    next_id += 1
    for c in p.children:
        next_id = _assign_nids(c, next_id)
    return next_id


def lookup_stats(plan) -> Optional[dict]:
    """Observed-statistics lookup for this exact plan: the persistent
    catalog record a prior profiled run left under the plan's BASE
    content fingerprint (per-scan column cardinality, join-key
    selectivity, per-node rows/skew), or None when the catalog is
    disabled or has never seen the plan.

    This is the adaptive planner's cost-model feed: :func:`optimize`
    consults it (adaptive mode) to size join sides and read observed
    skew.  Keyed by :meth:`LogicalPlan.base_fingerprint` — the
    strategy-INDEPENDENT fingerprint — so the lookup describes what the
    query is, not what a prior planner chose, and the
    fingerprint→optimize recursion is impossible (the base fingerprint
    optimizes with ``adaptive=False``, which never calls back here).
    Plans without adaptive mode remain bit-identical with the catalog
    present or absent (tests pin it).  Note the fingerprint hashes
    pruned input CONTENT, so the lookup costs one host gather of the
    scan columns — call it on planning/profiling paths, not per-row hot
    paths."""
    from ..obs import stats_catalog

    if not stats_catalog.enabled():
        return None
    return stats_catalog.lookup(plan.base_fingerprint())


def scan_prunes(phys: PhysPlan) -> List[Tuple[ir.Scan, Tuple[str, ...]]]:
    """Every (Scan node, kept columns) pair of the physical plan — the
    pruned inputs the fingerprint hashes and the admission estimator
    sizes."""
    out: List[Tuple[ir.Scan, Tuple[str, ...]]] = []

    def walk(p: Phys) -> None:
        if isinstance(p.node, ir.Scan):
            out.append((p.node, p.keep))
        for c in p.children:
            walk(c)

    walk(phys.root)
    return out


def _count(p: Phys) -> int:
    return 1 + sum(_count(c) for c in p.children)


# ---------------------------------------------------------------------------
# rule 1: required columns (top-down), interleaved with the bottom-up
# partitioning/elision pass — one recursion computes both
# ---------------------------------------------------------------------------


def _ordered(names: Sequence[str], want: Set[str]) -> Tuple[str, ...]:
    return tuple(n for n in names if n in want)


def _build(plan, node: ir.Node, req: Optional[Tuple[str, ...]], world: int,
           enabled: bool, out: PhysPlan) -> Phys:
    """req = ordered output columns an ancestor needs (None = keep all,
    the eager mode)."""
    keep_all = req is None
    req_set = set(node.names if keep_all else req)

    if isinstance(node, ir.Scan):
        keep = tuple(node.names) if keep_all else _ordered(node.names,
                                                           req_set)
        p = Phys(node, [], keep)
        stamp = getattr(plan.inputs[node.idx], "_partitioning", None)
        if (enabled and stamp and stamp[0] == "hash"
                and int(stamp[2]) == world and world > 1):
            alts = stamp[1] if isinstance(stamp[1][0], tuple) else (stamp[1],)
            p.part = ("hash", tuple(tuple(a) for a in alts), world)
        if enabled:
            out.columns_pruned += len(node.names) - len(keep)
            p.ann["pruned"] = len(node.names) - len(keep)
        return p

    if isinstance(node, ir.Project):
        child_req = None if keep_all else _rule_required_columns(
            node, req_set)
        c = _build(plan, node.children[0], child_req, world, enabled, out)
        keep = tuple(node.names) if keep_all else _ordered(node.names,
                                                           req_set)
        return Phys(node, [c], keep, _restrict_part(c.part, keep))

    if isinstance(node, ir.Filter):
        child_req = None if keep_all else _rule_required_columns(
            node, req_set)
        c = _build(plan, node.children[0], child_req, world, enabled, out)
        keep = tuple(node.names) if keep_all else _ordered(node.names,
                                                           req_set)
        return Phys(node, [c], keep, c.part)

    if isinstance(node, ir.Derive):
        alive = keep_all or node.name in req_set
        child_req = None if keep_all else _rule_required_columns(
            node, req_set)
        c = _build(plan, node.children[0], child_req, world, enabled, out)
        keep = tuple(node.names) if keep_all else _ordered(node.names,
                                                           req_set)
        p = Phys(node, [c], keep, c.part)
        p.ann["dead"] = not alive
        return p

    if isinstance(node, ir.Join):
        return _build_join(plan, node, req, world, enabled, out)

    if isinstance(node, ir.Aggregate):
        child_req = None if keep_all else _rule_required_columns(
            node, req_set)
        c = _build(plan, node.children[0], child_req, world, enabled, out)
        p = Phys(node, [c], tuple(node.names))
        _rule_shuffle_elision_agg(p, c, world, enabled, out)
        if out.model is not None:
            _rule_salt_agg(p, c, world, out)
        return p

    if isinstance(node, ir.Sort):
        child_req = None if keep_all else _rule_required_columns(
            node, req_set)
        c = _build(plan, node.children[0], child_req, world, enabled, out)
        keep = tuple(node.names) if keep_all else _ordered(node.names,
                                                           req_set)
        return Phys(node, [c], keep, None)  # range-partitioned, untracked

    if isinstance(node, ir.Limit):
        child_req = None if keep_all else tuple(req)
        c = _build(plan, node.children[0], child_req, world, enabled, out)
        keep = tuple(node.names) if keep_all else _ordered(node.names,
                                                           req_set)
        return Phys(node, [c], keep, None)

    raise CylonError(Code.Invalid, f"unknown plan node {node.kind!r}")


def _rule_required_columns(node: ir.Node,
                           req_set: Set[str]) -> Tuple[str, ...]:
    """The ordered column set ``node``'s child must produce for ``node``
    to emit ``req_set`` — the pruning rule's per-node transfer
    function."""
    child = node.children[0]
    if isinstance(node, ir.Project):
        return _ordered(child.names, req_set)
    if isinstance(node, ir.Filter):
        return _ordered(child.names, req_set | node.pred.columns())
    if isinstance(node, ir.Derive):
        want = set(req_set) - {node.name}
        if node.name in req_set:
            want |= node.value.columns()
        return _ordered(child.names, want)
    if isinstance(node, ir.Aggregate):
        want = set(node.by) | {n for n, _ in node.aggs}
        return _ordered(child.names, want)
    if isinstance(node, ir.Sort):
        return _ordered(child.names, req_set | set(node.by))
    raise AssertionError(node.kind)


def _restrict_part(part: Optional[Partitioning],
                   keep: Tuple[str, ...]) -> Optional[Partitioning]:
    """Partitioning survives a projection as a placement property even
    when key columns are projected away — but an alternative whose keys
    are gone is useless to every downstream compat check, so drop it."""
    if part is None:
        return None
    ks = set(keep)
    alts = tuple(a for a in part[1] if set(a) <= ks)
    return (part[0], alts, part[2]) if alts else None


# ---------------------------------------------------------------------------
# rules 2+3: shuffle elision & scan sharing (joins)
# ---------------------------------------------------------------------------


def _subset_positions(part_keys: Tuple[str, ...],
                      side_keys: Tuple[str, ...]) -> Optional[Tuple[int, ...]]:
    """Positions making ``part_keys`` an ordered positional subset of
    ``side_keys`` (data partitioned on the subset co-locates rows with
    equal full keys), or None."""
    pos: List[int] = []
    start = 0
    for pk in part_keys:
        for i in range(start, len(side_keys)):
            if side_keys[i] == pk:
                pos.append(i)
                start = i + 1
                break
        else:
            return None
    return tuple(pos)


def _compat_positions(part: Optional[Partitioning],
                      side_keys: Tuple[str, ...],
                      world: int) -> Optional[Tuple[int, ...]]:
    if part is None or part[0] != "hash" or part[2] != world:
        return None
    for alt in part[1]:
        pos = _subset_positions(alt, side_keys)
        if pos is not None:
            return pos
    return None


def _scan_chain(p: Phys):
    """(input_idx, op-spec tuple) when ``p`` is a pure scan chain
    (Scan under Project/Filter/Derive only), else None — the scan-
    sharing rule's identity key (projections excluded: column sets are
    unioned by the rule)."""
    specs: List[tuple] = []
    cur = p
    while True:
        n = cur.node
        if isinstance(n, ir.Scan):
            return n.idx, tuple(specs)
        if isinstance(n, ir.Filter):
            specs.append(("filter", n.pred.spec()))
        elif isinstance(n, ir.Derive):
            specs.append(("derive", n.name, n.value.spec()))
        elif not isinstance(n, ir.Project):
            return None
        cur = cur.children[0]


def _build_join(plan, node: ir.Join, req: Optional[Tuple[str, ...]],
                world: int, enabled: bool, out: PhysPlan) -> Phys:
    keep_all = req is None
    req_set = set(node.names if keep_all else req)
    left, right = node.children
    # map required output names back to child columns (+ join keys)
    want_l: Set[str] = set(node.left_on)
    want_r: Set[str] = set(node.right_on)
    for name in left.names:
        if node.out_name("left", name) in req_set:
            want_l.add(name)
    for name in right.names:
        if node.out_name("right", name) in req_set:
            want_r.add(name)
    lc = _build(plan, left, None if keep_all else _ordered(left.names,
                                                           want_l),
                world, enabled, out)
    rc = _build(plan, right, None if keep_all else _ordered(right.names,
                                                            want_r),
                world, enabled, out)
    keep = tuple(node.names) if keep_all else _ordered(node.names, req_set)
    p = Phys(node, [lc, rc], keep)
    _rule_shuffle_elision_join(p, lc, rc, world, enabled, out)
    if enabled:
        _rule_share_scans(p, lc, rc, world, out)
    if out.model is not None:
        _rule_broadcast_join(p, lc, rc, world, out)
    _join_out_partitioning(p, world)
    return p


def _rule_shuffle_elision_join(p: Phys, lc: Phys, rc: Phys, world: int,
                               enabled: bool, out: PhysPlan) -> None:
    node: ir.Join = p.node  # type: ignore[assignment]
    if world == 1:
        p.ann["left"] = p.ann["right"] = ("local",)
        return
    lo, ro = tuple(node.left_on), tuple(node.right_on)
    if not enabled:
        p.ann["left"] = ("shuffle", lo)
        p.ann["right"] = ("shuffle", ro)
        return
    lpos = _compat_positions(lc.part, lo, world)
    rpos = _compat_positions(rc.part, ro, world)
    if lpos is not None and rpos is not None and lpos == rpos:
        p.ann["left"] = ("elide", tuple(lo[i] for i in lpos))
        p.ann["right"] = ("elide", tuple(ro[i] for i in rpos))
        out.shuffles_elided += 2
    elif lpos is not None:
        p.ann["left"] = ("elide", tuple(lo[i] for i in lpos))
        p.ann["right"] = ("shuffle", tuple(ro[i] for i in lpos))
        out.shuffles_elided += 1
    elif rpos is not None:
        p.ann["left"] = ("shuffle", tuple(lo[i] for i in rpos))
        p.ann["right"] = ("elide", tuple(ro[i] for i in rpos))
        out.shuffles_elided += 1
    else:
        p.ann["left"] = ("shuffle", lo)
        p.ann["right"] = ("shuffle", ro)


def _rule_share_scans(p: Phys, lc: Phys, rc: Phys, world: int,
                      out: PhysPlan) -> None:
    """Self-join shape: both sides shuffle the SAME scan chain on the
    same source columns -> ONE exchange over the union of columns."""
    node: ir.Join = p.node  # type: ignore[assignment]
    if world == 1:
        return
    if p.ann.get("left", ())[:1] != ("shuffle",) \
            or p.ann.get("right", ())[:1] != ("shuffle",):
        return
    a, b = _scan_chain(lc), _scan_chain(rc)
    if a is None or b is None or a != b:
        return
    lkeys = p.ann["left"][1]
    rkeys = p.ann["right"][1]
    if lkeys != rkeys:  # same chain => same column namespace
        return
    p.ann["shared"] = True
    out.shuffles_elided += 1


def _rule_broadcast_join(p: Phys, lc: Phys, rc: Phys, world: int,
                         out: PhysPlan) -> None:
    """Adaptive rule: broadcast-hash join.  When the cost model says one
    side is dimension-sized (estimate at or under the broadcast
    threshold AND cheaper on the wire than shuffling), replicate that
    side to every rank with ONE all_gather and probe locally — the big
    side moves ZERO bytes.

    Validity mirrors :func:`join_partition_alternatives`' null-keys
    argument with sides swapped: the KEPT side's rows must each live on
    exactly one rank and be emitted there exactly once, so the
    broadcast side must never be null-extended (its unmatched rows are
    replicated on every rank) — broadcast left only for inner/right
    joins, broadcast right only for inner/left, never outer."""
    node: ir.Join = p.node  # type: ignore[assignment]
    model = out.model
    if model is None or p.ann.get("shared"):
        return
    la = p.ann.get("left", ())
    ra = p.ann.get("right", ())
    if not la or la[0] == "local":
        return
    # (side to broadcast, its child, the other side's current ann) —
    # profitable only when the OTHER side currently pays an exchange
    cands = []
    if node.how in ("inner", "right") and ra[:1] == ("shuffle",):
        cands.append(("left", lc))
    if node.how in ("inner", "left") and la[:1] == ("shuffle",):
        cands.append(("right", rc))
    best = None
    for side, child in cands:
        est, src = model.side_estimate(child)
        if est > model.threshold:
            continue
        if best is None or est < best[2]:
            best = (side, child, est, src)
    if best is None:
        return
    side, child, est, src = best
    own_ann = la if side == "left" else ra
    saved = 2 if own_ann[:1] == ("shuffle",) else 1
    big_child = rc if side == "left" else lc
    big_est, _ = model.side_estimate(big_child)
    if not model.broadcast_wins(est, big_est, saved):
        return
    lo, ro = tuple(node.left_on), tuple(node.right_on)
    if side == "left":
        p.ann["left"] = ("broadcast", lo)
        p.ann["right"] = ("keep", ro)
    else:
        p.ann["left"] = ("keep", lo)
        p.ann["right"] = ("broadcast", ro)
    p.ann["broadcast"] = {"side": side, "bytes": int(est), "source": src}
    out.broadcast_joins += 1


def _rule_salt_agg(p: Phys, c: Phys, world: int, out: PhysPlan) -> None:
    """Adaptive rule: skew-salted NUNIQUE repartition.  When the catalog
    observed the aggregate's input placing ``max/mean >= salt factor``
    rows on one rank (the zipfian-key shape), spread the exchange over
    value-hash salt buckets and COUNTSUM-combine the per-bucket partial
    distinct counts — exact by construction (buckets partition the
    value space, so per-(key, bucket) distinct counts sum to the
    per-key distinct count; integer combine).  Gated to the
    single-distinct-column all-NUNIQUE shape the salted physical path
    supports; no catalog evidence → no salt (conservative)."""
    node: ir.Aggregate = p.node  # type: ignore[assignment]
    from ..ops.groupby import AggOp

    model = out.model
    if model is None or p.ann.get("mode") != "eager":
        return
    if not node.aggs or any(op != AggOp.NUNIQUE for _, op in node.aggs):
        return
    if len({n for n, _ in node.aggs}) != 1:
        return
    # the estimate spans the aggregate's OWN record too: a plain
    # groupby-on-scan has a balanced (round-robin) input, so the only
    # observed placement skew lives on the aggregate node itself
    skew, src = model.skew_estimate(p)
    if skew < model.salt_factor:
        return
    p.ann["salt"] = world
    p.ann["salt_est"] = {"skew": skew, "source": src,
                         "factor": model.salt_factor}
    out.keys_salted += 1


def _join_out_partitioning(p: Phys, world: int) -> None:
    """Output partitioning of a join: rows land by hash of the keys the
    sides were exchanged (or already placed) on; which side's names are
    valid is :func:`join_partition_alternatives`' single-sourced
    rule."""
    node: ir.Join = p.node  # type: ignore[assignment]
    if world == 1:
        p.part = None
        return
    la = p.ann.get("left", ())
    ra = p.ann.get("right", ())
    if not la or la[0] == "local":
        p.part = None
        return
    if la[0] in ("broadcast", "keep"):
        # broadcast join: every output row derives from a KEPT-side row
        # in place (the broadcast side is the one replicated), so the
        # kept child's placement property survives, renamed through the
        # join's collision-prefix rule.  Kept rows are never
        # null-extended (the broadcast rule's validity gate), so their
        # key values stay real.
        kept_side = "left" if la[0] == "keep" else "right"
        kc = p.children[0] if kept_side == "left" else p.children[1]
        if kc.part is None or kc.part[0] != "hash" or kc.part[2] != world:
            p.part = None
            return
        keep_set = set(p.keep)
        alts = []
        for alt in kc.part[1]:
            mapped = tuple(node.out_name(kept_side, n) for n in alt)
            if set(mapped) <= keep_set:
                alts.append(mapped)
        p.part = ("hash", tuple(alts), world) if alts else None
        return
    lkeys = la[1] if len(la) > 1 else tuple(node.left_on)
    rkeys = ra[1] if len(ra) > 1 else tuple(node.right_on)
    alts = join_partition_alternatives(
        node.how, node.children[0].names, node.children[1].names,
        lkeys, rkeys, node.left_prefix, node.right_prefix)
    keep_set = set(p.keep)
    alts = tuple(a for a in alts if set(a) <= keep_set)
    p.part = ("hash", alts, world) if alts else None


def _rule_shuffle_elision_agg(p: Phys, c: Phys, world: int, enabled: bool,
                              out: PhysPlan) -> None:
    node: ir.Aggregate = p.node  # type: ignore[assignment]
    from ..ops.groupby import AggOp

    has_nunique = any(op == AggOp.NUNIQUE for _, op in node.aggs)
    if world == 1:
        p.ann["mode"] = "local"
        p.part = None
        return
    if enabled and not has_nunique and c.part is not None:
        by_set = set(node.by)
        for alt in c.part[1]:
            if c.part[0] == "hash" and c.part[2] == world \
                    and set(alt) <= by_set:
                p.ann["mode"] = "elided"
                p.ann["part_keys"] = alt
                p.part = ("hash", (alt,), world)
                out.shuffles_elided += 1
                return
    p.ann["mode"] = "eager"
    p.part = ("hash", (tuple(node.by),), world) if not has_nunique else None


# ---------------------------------------------------------------------------
# rule 4: local fusion
# ---------------------------------------------------------------------------


def _rule_fuse_local(p: Phys, world: int, out: PhysPlan) -> None:
    """Mark group-bys whose input chain is join → (derive/filter/
    project)* with no exchange in between: the post-shuffle local probe,
    the derived columns, the filters and the local aggregate run inside
    ONE jitted shard body instead of materializing each intermediate.
    Applies when the group-by itself needs no shuffle (elided, or a
    1-shard world) — the final combine then lives in the same body."""
    if isinstance(p.node, ir.Aggregate) \
            and p.ann.get("mode") in ("elided", "local"):
        chain: List[Phys] = []
        cur = p.children[0]
        while isinstance(cur.node, (ir.Derive, ir.Filter, ir.Project)):
            chain.append(cur)
            cur = cur.children[0]
        if isinstance(cur.node, ir.Join) and cur.node.algorithm in (
                "sort", "hash"):
            from ..ops.groupby import AggOp

            if not any(op == AggOp.NUNIQUE for _, op in
                       p.node.aggs):
                p.ann["fuse"] = True
                p.ann["fuse_chain"] = chain
                p.ann["fuse_join"] = cur
    for c in p.children:
        _rule_fuse_local(c, world, out)


# ---------------------------------------------------------------------------
# explain support: plane width of a pruned scan
# ---------------------------------------------------------------------------


def plane_annotation(table, keep: Tuple[str, ...]) -> Dict[str, int]:
    """Packed-plane word width of the full vs pruned column set — the
    explain() annotation making the pruning win concrete in bytes.
    Consults the trace-scope pack/compress knobs (the realization the
    exchange would actually use); the plan FINGERPRINT covers every
    trace knob via durable.run_fingerprint, which cylint CY108
    machine-checks.

    When compression is active, ``words_comp`` additionally reports the
    pruned set's width under the host-ESTIMATED compression spec
    (plane.estimate_spec over addressable buffers — advisory, like the
    rest of explain), so pruning and compression savings attribute
    separately: full -> pruned is the planner's win, pruned -> comp the
    payload encoder's."""
    cols = list(table.columns)
    kept = [c for n, c in zip(table.names, cols) if n in set(keep)]
    packed = plane_mod.pack_enabled()
    comp = packed and plane_mod.compress_enabled()
    ann = {
        "words_full": plane_mod.plane_words(cols) if cols else 0,
        "words_pruned": plane_mod.plane_words(kept) if kept else 0,
        "packed": int(packed),
        "compressed": int(comp),
    }
    # estimate_spec realizes buffers on the host (np.asarray) — fine for
    # an advisory explain() on a single-controller mesh, but an array
    # spanning non-addressable devices would raise, so the annotation is
    # simply omitted there (the REAL exchange derives its spec from the
    # replicated device stats pass, never from this estimate)
    if comp and kept and all(
            getattr(c.data, "is_fully_addressable", True) for c in kept):
        spec = plane_mod.estimate_spec(kept, world=table.num_shards,
                                       shard_cap=table.shard_capacity)
        ann["words_comp"] = plane_mod.plane_words(kept, spec)
    return ann
