"""Process-local metrics: counters, gauges and histograms.

The accounting half of the observability subsystem: where ``obs.spans``
answers "where did the wall-clock go", this module answers "how much
work actually ran" — collective launches and bytes moved per shuffle
exchange (``shuffle.collective_launches`` / ``shuffle.bytes_sent``),
out-of-core refinements (``oom.refinements``), transient retries
(``retry.attempts``), jit-plan cache traffic (``plan_cache.hit`` /
``plan_cache.miss``) and the host-visible HBM watermark
(``hbm.live_bytes`` via ``jax.live_arrays``).

Everything is plain dict arithmetic on the host — no jax dependency, no
locks on the hot counters (CPython's GIL makes the single add/assign
effectively atomic, the same contract the PR-0 timing registry relied
on).  ``snapshot()`` is deterministic: keys come out sorted, so two runs
recording the same work in any order serialize identically.
"""
from __future__ import annotations

import sys
from typing import Dict, Optional

_counters: Dict[str, float] = {}
_gauges: Dict[str, float] = {}
_hists: Dict[str, "_Hist"] = {}


class _Hist:
    """Fixed-shape histogram: count/sum/min/max plus power-of-two bucket
    counts (bucket i holds values in [2**i, 2**(i+1)); negatives and
    zeros land in bucket 0)."""

    __slots__ = ("count", "sum", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.buckets: Dict[int, int] = {}

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        b = max(0, int(v).bit_length() - 1) if v >= 1 else 0
        self.buckets[b] = self.buckets.get(b, 0) + 1

    def as_dict(self) -> Dict[str, object]:
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "buckets": {str(k): self.buckets[k]
                            for k in sorted(self.buckets)}}


def counter_add(name: str, value: float = 1) -> None:
    _counters[name] = _counters.get(name, 0) + value


def counter_value(name: str) -> float:
    return _counters.get(name, 0)


def gauge_set(name: str, value: float) -> None:
    _gauges[name] = float(value)


def gauge_max(name: str, value: float) -> None:
    """Watermark gauge: keeps the maximum ever set."""
    v = float(value)
    cur = _gauges.get(name)
    if cur is None or v > cur:
        _gauges[name] = v


def hist_observe(name: str, value: float) -> None:
    h = _hists.get(name)
    if h is None:
        h = _hists[name] = _Hist()
    h.observe(value)


def record_hbm_watermark() -> int:
    """Sum live device-array bytes (``jax.live_arrays``) into the
    ``hbm.live_bytes`` watermark gauge; returns the sampled total.
    Host-side and jax-optional: 0 when jax was never imported."""
    jax = sys.modules.get("jax")
    if jax is None or not hasattr(jax, "live_arrays"):
        return 0
    total = 0
    for a in jax.live_arrays():
        total += getattr(a, "nbytes", 0) or 0
    gauge_max("hbm.live_bytes", total)
    return total


def snapshot() -> Dict[str, object]:
    """Deterministic flat snapshot: {"counters": {...}, "gauges": {...},
    "histograms": {...}} with every key level sorted."""
    return {
        "counters": {k: _counters[k] for k in sorted(_counters)},
        "gauges": {k: _gauges[k] for k in sorted(_gauges)},
        "histograms": {k: _hists[k].as_dict() for k in sorted(_hists)},
    }


def reset() -> None:
    _counters.clear()
    _gauges.clear()
    _hists.clear()
