"""Process-local metrics: counters, gauges and histograms.

The accounting half of the observability subsystem: where ``obs.spans``
answers "where did the wall-clock go", this module answers "how much
work actually ran" — collective launches and bytes moved per shuffle
exchange (``shuffle.collective_launches`` / ``shuffle.bytes_sent``),
out-of-core refinements (``oom.refinements``), transient retries
(``retry.attempts``), jit-plan cache traffic (``plan_cache.hit`` /
``plan_cache.miss``) and the host-visible HBM watermark
(``hbm.live_bytes`` via ``jax.live_arrays``).

Everything is plain dict arithmetic on the host — no jax dependency, no
locks on the hot counters (CPython's GIL makes the single add/assign
effectively atomic, the same contract the PR-0 timing registry relied
on).  ``snapshot()`` is deterministic: keys come out sorted, so two runs
recording the same work in any order serialize identically.
"""
from __future__ import annotations

import bisect
import sys
from typing import Dict, Optional

_counters: Dict[str, float] = {}
_gauges: Dict[str, float] = {}
_hists: Dict[str, "_Hist"] = {}


#: fixed cumulative-bucket boundaries (OpenMetrics ``le`` semantics): a
#: 1-2.5-5 ladder through 1e6, decades beyond (the >1e6 range is byte
#: counts where decade resolution suffices) — wide enough to cover both
#: millisecond latencies and byte counts with ONE boundary set, and
#: FIXED so histograms recorded by different ranks (or different runs)
#: merge by plain per-key addition (``fleet.merge_hist``) and render as
#: Prometheus cumulative buckets without rebinning.  Changing this set
#: breaks merges against already-persisted snapshots (flight dumps,
#: heartbeat ledgers) — extend only with a version bump.
LE_BUCKETS: tuple = (1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                     5000, 10000, 25000, 50000, 100000, 250000, 500000,
                     1000000, 10000000, 100000000, 1000000000)


class _Hist:
    """Fixed-shape histogram: count/sum/min/max plus power-of-two bucket
    counts (bucket i holds values in [2**i, 2**(i+1)); negatives and
    zeros land in bucket 0).  ``as_dict`` additionally emits the fixed
    CUMULATIVE ``le`` buckets (``LE_BUCKETS`` + "+Inf") the OpenMetrics
    exposition needs — per-boundary counts are kept non-cumulative
    internally (one increment per observe) and accumulated at snapshot
    time."""

    __slots__ = ("count", "sum", "min", "max", "buckets", "le_counts")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.buckets: Dict[int, int] = {}
        # one slot per LE_BUCKETS boundary + the +Inf overflow slot
        self.le_counts = [0] * (len(LE_BUCKETS) + 1)

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        b = max(0, int(v).bit_length() - 1) if v >= 1 else 0
        self.buckets[b] = self.buckets.get(b, 0) + 1
        i = bisect.bisect_left(LE_BUCKETS, v)
        self.le_counts[i] += 1

    def le_dict(self) -> Dict[str, int]:
        """Cumulative {boundary: count of observations <= boundary},
        keys are decimal strings plus "+Inf" (== count)."""
        out: Dict[str, int] = {}
        acc = 0
        for bound, n in zip(LE_BUCKETS, self.le_counts):
            acc += n
            out[str(bound)] = acc
        out["+Inf"] = acc + self.le_counts[-1]
        return out

    def as_dict(self) -> Dict[str, object]:
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "buckets": {str(k): self.buckets[k]
                            for k in sorted(self.buckets)},
                "le": self.le_dict()}


def counter_add(name: str, value: float = 1) -> None:
    _counters[name] = _counters.get(name, 0) + value


def counter_value(name: str) -> float:
    return _counters.get(name, 0)


def gauge_set(name: str, value: float) -> None:
    _gauges[name] = float(value)


def gauge_max(name: str, value: float) -> None:
    """Watermark gauge: keeps the maximum ever set."""
    v = float(value)
    cur = _gauges.get(name)
    if cur is None or v > cur:
        _gauges[name] = v


def hist_observe(name: str, value: float) -> None:
    h = _hists.get(name)
    if h is None:
        h = _hists[name] = _Hist()
    h.observe(value)


def record_hbm_watermark() -> int:
    """Sum live device-array bytes (``jax.live_arrays``) into the
    ``hbm.live_bytes`` watermark gauge; returns the sampled total.
    Host-side and jax-optional: 0 when jax was never imported."""
    jax = sys.modules.get("jax")
    if jax is None or not hasattr(jax, "live_arrays"):
        return 0
    total = 0
    for a in jax.live_arrays():
        total += getattr(a, "nbytes", 0) or 0
    gauge_max("hbm.live_bytes", total)
    return total


def snapshot() -> Dict[str, object]:
    """Deterministic flat snapshot: {"counters": {...}, "gauges": {...},
    "histograms": {...}} with every key level sorted."""
    return {
        "counters": {k: _counters[k] for k in sorted(_counters)},
        "gauges": {k: _gauges[k] for k in sorted(_gauges)},
        "histograms": {k: _hists[k].as_dict() for k in sorted(_hists)},
    }


def reset() -> None:
    _counters.clear()
    _gauges.clear()
    _hists.clear()
