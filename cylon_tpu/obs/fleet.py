"""Fleet observability: process identity, clock alignment, and the
failure flight recorder.

PR 4's tracing is strictly process-local — every rank stamps events with
its own ``time.perf_counter_ns()``, whose zero point is arbitrary per
process, so two ranks' traces cannot be laid on one timeline.  This
module supplies the three cross-process pieces:

- **identity** — which rank this process is (set by the elastic agent at
  join, consulted by ``obs.export`` for artifact naming BEFORE the
  ``jax.process_index`` fallback, which reports 0 on every single-
  controller process and made two elastic agents clobber each other's
  ``trace.r0.json``) and which logical run it is part of (``run_id``,
  namespacing exports and flight dumps so back-to-back runs sharing one
  ``CYLON_TPU_TRACE_DIR`` never collide);

- **clock alignment** — an NTP-style offset/uncertainty handshake
  (:func:`measure_offset`) over the coordinator's one-shot JSON channel:
  each round trip stamps ``t0`` (send, local clock), ``t1``/``t2``
  (receive/reply, coordinator clock), ``t3`` (reply received, local);
  offset ≈ ((t1−t0)+(t2−t3))/2 with uncertainty bounded by half the
  round-trip residue — the classic symmetric-delay argument.  Best of N
  rounds wins (the shortest RTT has the least queueing asymmetry).  The
  resulting :class:`ClockInfo` rides every export's ``otherData`` so
  ``tools/trace_merge.py`` can map per-rank timestamps onto the
  coordinator clock — and refuse when the uncertainty is too coarse for
  the spans being merged;

- **flight recorder** — :func:`flight_record` dumps the always-on event
  ring (``obs.spans.ring_events``; the MOST RECENT events, kept even in
  aggregate mode) plus a full metrics snapshot to
  ``CYLON_TPU_TRACE_DIR/flight/<run_id>.r<rank>.json`` whenever a
  classified terminal event fires (poison-pass quarantine, serve shed or
  request failure, rank loss, straggler fencing, fatal pass failure).
  Post-mortems therefore never depend on the user having pre-armed
  ``CYLON_TPU_TRACE=1``.  The dump is written atomically (tmp + rename)
  and a dump failure is swallowed — the recorder must never kill the
  failing path it is recording.

Host-side stdlib only (no jax), like the rest of ``obs``.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .. import config
from . import metrics as metrics_mod
from . import spans as spans_mod
from . import tracectx

log = logging.getLogger("cylon_tpu")

_lock = threading.Lock()
_rank: Optional[object] = None       # int rank, or "coord" on a coordinator
_run_id: Optional[str] = None
_clock: Optional["ClockInfo"] = None
_incarnation: Optional[int] = None   # coordinator incarnation last seen
_reasons: List[Dict[str, object]] = []   # terminal events this process saw


# ---------------------------------------------------------------------------
# identity
# ---------------------------------------------------------------------------

def set_rank(rank, *, force: bool = False) -> None:
    """Register this process's fleet rank (the elastic agent calls this at
    join).  First registration wins unless ``force`` — a process hosts one
    agent in deployment, and in-process multi-agent tests must not have
    the last-constructed agent steal the export naming."""
    global _rank
    with _lock:
        if _rank is None or force:
            _rank = rank


def current_rank() -> Optional[object]:
    with _lock:
        return _rank


def set_run_id(run_id: Optional[str], *, force: bool = True) -> None:
    global _run_id
    with _lock:
        if _run_id is None or force:
            _run_id = run_id or None


def current_run_id() -> Optional[str]:
    """The explicitly registered run id, else the ``CYLON_TPU_RUN_ID``
    knob, else None (flat artifact naming)."""
    with _lock:
        if _run_id:
            return _run_id
    return str(config.knob("CYLON_TPU_RUN_ID")) or None


def set_incarnation(inc: Optional[int]) -> None:
    """Register the coordinator incarnation this process last observed
    (the elastic agent calls this on every absorbed view): flight dumps
    and the status tooling stamp it, so a post-mortem can tell which
    coordinator lifetime an event belongs to."""
    global _incarnation
    with _lock:
        _incarnation = None if inc is None else int(inc)


def current_incarnation() -> Optional[int]:
    with _lock:
        return _incarnation


def reset() -> None:
    """Clear identity, clock, and recorded terminal events (tests)."""
    global _rank, _run_id, _clock, _incarnation
    with _lock:
        _rank = None
        _run_id = None
        _clock = None
        _incarnation = None
        _reasons.clear()
        _last_write.clear()


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ClockInfo:
    """One measured mapping from this process's ``perf_counter_ns`` onto
    a reference clock: ``t_ref ≈ t_local + offset_ns``, wrong by at most
    about ``uncertainty_ns`` (half the round-trip residue)."""

    offset_ns: int
    uncertainty_ns: int
    rtt_ns: int
    ref: str                 # who the offset is against (host:port)
    measured_unix: float     # wall-clock stamp, labeling only
    measured_mono: float     # local monotonic stamp, for aging

    def as_dict(self) -> Dict[str, object]:
        return {"offset_ns": int(self.offset_ns),
                "uncertainty_ns": int(self.uncertainty_ns),
                "rtt_ns": int(self.rtt_ns), "ref": self.ref,
                "measured_unix": self.measured_unix}


def measure_offset(request_fn: Callable[[Dict], Dict], *, ref: str = "",
                   rank: Optional[int] = None,
                   rounds: int = 8) -> ClockInfo:
    """NTP-style offset handshake: ``rounds`` ``{"cmd": "clock"}`` round
    trips through ``request_fn`` (the agent's coordinator RPC), keeping
    the round with the smallest uncertainty.  Raises whatever
    ``request_fn`` raises (``OSError`` on a dead peer) and ``ValueError``
    on a malformed reply."""
    best: Optional[ClockInfo] = None
    for _ in range(max(1, int(rounds))):
        t0 = time.perf_counter_ns()
        resp = request_fn({"cmd": "clock", "rank": rank, "t0": t0})
        t3 = time.perf_counter_ns()
        if not resp.get("ok") or "t_recv" not in resp or "t_send" not in resp:
            raise ValueError(f"malformed clock reply: {resp}")
        t1, t2 = int(resp["t_recv"]), int(resp["t_send"])
        rtt = (t3 - t0) - (t2 - t1)
        offset = ((t1 - t0) + (t2 - t3)) // 2
        unc = max(rtt // 2, 1)
        if best is None or unc < best.uncertainty_ns:
            best = ClockInfo(offset, unc, rtt, ref,
                             time.time(), time.monotonic())
    assert best is not None
    return best


def set_clock(info: Optional[ClockInfo]) -> None:
    global _clock
    with _lock:
        _clock = info


def clock() -> Optional[ClockInfo]:
    with _lock:
        return _clock


def clock_dict() -> Optional[Dict[str, object]]:
    c = clock()
    return None if c is None else c.as_dict()


def merge_hist(a: Optional[Dict], b: Optional[Dict]) -> Optional[Dict]:
    """Merge two ``obs.metrics`` histogram dicts (count/sum/min/max +
    power-of-two buckets) — the coordinator aggregates per-rank serve
    telemetry with this."""
    if a is None:
        return None if b is None else dict(b)
    if b is None:
        return dict(a)
    mins = [v for v in (a.get("min"), b.get("min")) if v is not None]
    maxs = [v for v in (a.get("max"), b.get("max")) if v is not None]
    buckets: Dict[str, int] = dict(a.get("buckets") or {})
    for k, v in (b.get("buckets") or {}).items():
        buckets[k] = buckets.get(k, 0) + int(v)
    # the fixed cumulative le buckets sum per boundary (both sides share
    # the metrics.LE_BUCKETS boundary set, so cumulative counts add)
    le: Dict[str, int] = dict(a.get("le") or {})
    for k, v in (b.get("le") or {}).items():
        le[k] = le.get(k, 0) + int(v)
    out = {"count": int(a.get("count", 0)) + int(b.get("count", 0)),
           "sum": float(a.get("sum", 0.0)) + float(b.get("sum", 0.0)),
           "min": min(mins) if mins else None,
           "max": max(maxs) if maxs else None,
           "buckets": {k: buckets[k] for k in sorted(buckets, key=int)}}
    if le:
        out["le"] = {k: le[k] for k in sorted(
            le, key=lambda s: float("inf") if s == "+Inf" else float(s))}
    return out


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

FLIGHT_KIND = "cylon_tpu.flight"


def flight_enabled() -> bool:
    """The recorder rides the ring: ``CYLON_TPU_FLIGHT_RING_CAP`` > 0."""
    return spans_mod.ring_cap() > 0


def flight_dir() -> str:
    return os.path.join(
        str(config.knob("CYLON_TPU_TRACE_DIR")) or "traces", "flight")


def _safe_component(s: str) -> str:
    return "".join(c if (c.isalnum() or c in "._-") else "_" for c in s)


#: minimum spacing between REWRITES of one dump file for an IDENTICAL
#: repeating event (same reason, same attrs — e.g. one tenant's sheds
#: hammering a full queue): some call sites fire from hot paths, so an
#: event flood must not cost a file write apiece.  A DISTINCT terminal
#: event (different reason or attrs — a second rank lost, a different
#: tenant shed) always writes: the contract is that every classified
#: terminal event reaches disk, and only exact repeats coalesce into
#: the ledger the next write carries.
FLIGHT_REWRITE_MIN_S = 0.25

_last_write: Dict[str, Tuple[float, str]] = {}  # path -> (mono, event fp)


def flight_record(reason: str, *, rank=None, run_id: Optional[str] = None,
                  **attrs) -> Optional[str]:
    """Dump the flight ring + metrics snapshot for a classified terminal
    event.  Returns the dump path, or None when disabled, throttled, or
    the write failed (a recorder failure must never mask the event it
    records).

    Repeated terminal events in one process rewrite the same
    ``<run_id>.r<rank>.json`` file (an IDENTICAL event repeating within
    ``FLIGHT_REWRITE_MIN_S`` coalesces into the next write; distinct
    events always write); every dump
    carries the cumulative ``terminal_events`` list, so the latest file
    tells the whole story.  The write is atomic (tmp + rename) but NOT
    fsynced — this is a best-effort post-mortem, and several call sites
    hold hot locks; a synchronous disk flush there would stall the very
    control paths being recorded.
    """
    if not flight_enabled():
        return None
    try:
        entry = {"reason": reason, "ts_unix": time.time(),
                 "attrs": {k: v for k, v in attrs.items()}}
        with _lock:
            _reasons.append(entry)
            del _reasons[:-64]
            reasons = list(_reasons)
        r = rank if rank is not None else current_rank()
        if r is None:
            r = 0
        rid = run_id or current_run_id() or f"run-{os.getpid()}"
        d = flight_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"{_safe_component(str(rid))}.r{_safe_component(str(r))}.json")
        now = time.monotonic()
        fp = f"{reason}|{sorted(entry['attrs'].items())!r}"
        with _lock:
            last = _last_write.get(path)
            if (last is not None and last[1] == fp
                    and now - last[0] < FLIGHT_REWRITE_MIN_S):
                return None  # exact repeat coalesced; the ledger kept it
            _last_write[path] = (now, fp)
        from . import export as export_mod  # no cycle at call time

        pid = r if isinstance(r, int) else 0
        # the active (or explicitly attributed) request trace: a flight
        # dump can then be JOINED to the request trace that died — the
        # post-mortem's missing causal edge before PR 13
        tctx = tracectx.current()
        trace_id = entry["attrs"].get("trace_id") or (
            tctx.trace_id if tctx is not None else None)
        doc = {
            "kind": FLIGHT_KIND,
            "run_id": str(rid),
            "rank": r,
            "reason": reason,
            "trace_id": trace_id,
            "attrs": entry["attrs"],
            "terminal_events": reasons,
            "clock": clock_dict(),
            "incarnation": current_incarnation(),
            "traceEvents": [export_mod._event_json(e, pid)
                            for e in spans_mod.ring_events()],
            "ring_cap": spans_mod.ring_cap(),
            "dropped_events": spans_mod.dropped(),
            "metrics": metrics_mod.snapshot(),
            "aggregates": {k: [t, c] for k, (t, c)
                           in sorted(spans_mod.aggregate_report().items())},
            "ts_unix": entry["ts_unix"],
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, default=str)
        os.replace(tmp, path)
        metrics_mod.counter_add("flight.dumps")
        spans_mod.instant("flight.dump", reason=reason)
        return path
    except Exception as e:
        log.warning("flight recorder dump failed (%s): %s: %s",
                    reason, type(e).__name__, e)
        return None


def load_flight(path: str) -> Dict[str, object]:
    """Load and validate a flight-recorder dump."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("kind") != FLIGHT_KIND:
        raise ValueError(f"{path}: not a flight-recorder dump "
                         f"(kind={doc.get('kind')!r})")
    for k in ("run_id", "rank", "reason", "traceEvents", "metrics"):
        if k not in doc:
            raise ValueError(f"{path}: flight dump missing {k!r}")
    if not isinstance(doc["traceEvents"], list):
        raise ValueError(f"{path}: traceEvents is not a list")
    return doc
