"""Persistent statistics catalog: what queries OBSERVED, for the next
optimization.

ROADMAP item 1 (broadcast joins, skew salting, cost-based shuffle
choice) is blocked on a statistics substrate: the optimizer needs
observed cardinalities, selectivities and per-rank skew — not just the
one-shot ``column_stats`` pre-pass a compressed shuffle happens to run.
This module is that substrate's storage half: the query profiler
(``plan/profile.py``) distills each profiled run into a compact record
— per-scan/per-column cardinality, per-join key selectivity, per-node
row counts and partition skew — and persists it here, keyed by the
plan's content FINGERPRINT (``LogicalPlan.fingerprint()``: op chain ×
world × pruned input content × trace knobs), so a stat can never be
consumed against data it was not observed on.

Storage discipline is ``durable.py``'s: one append-only fsync'd
``STATS.jsonl`` under ``CYLON_TPU_STATS_DIR``, one JSON object per
line, torn tail tolerated (a crash mid-append costs that record, never
the file), atomic tmp+fsync+rename compaction once the distinct-key
count passes ``CYLON_TPU_STATS_CAP`` (most-recently-written entries
survive — the write-recency LRU, matching the journal GC's clock).  A
fresh process reloads the catalog by reading the file; there is no
in-memory daemon to lose.

Consumption is ``optimizer.lookup_stats()`` — ADVISORY-ONLY this PR:
the optimizer's decisions are unchanged whether the catalog is present
or absent (bit-identical plans, asserted by tests); ``explain
(analyze=True)`` renders the looked-up record as per-node estimates
next to the fresh actuals.  The cost model that will actually steer on
these numbers is ROADMAP item 1's.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
from typing import Dict, List, Optional

from .. import config

log = logging.getLogger("cylon_tpu")

STATS_FILE = "STATS.jsonl"
VERSION = 1


def stats_dir() -> str:
    """Catalog root (``CYLON_TPU_STATS_DIR``); empty disables."""
    return str(config.knob("CYLON_TPU_STATS_DIR"))


def enabled() -> bool:
    return bool(stats_dir())


def stats_cap() -> int:
    """Distinct fingerprints kept (``CYLON_TPU_STATS_CAP``): past it the
    file compacts to the most recently written entries."""
    return max(1, int(config.knob("CYLON_TPU_STATS_CAP")))


class StatsCatalog:
    """One loaded view of ``<root>/STATS.jsonl``: a fingerprint ->
    record dict in write order (later writes win)."""

    def __init__(self, root: str):
        self.root = root
        self.path = os.path.join(root, STATS_FILE)
        self.entries: Dict[str, dict] = {}
        self.torn = False

    @classmethod
    def open(cls, root: Optional[str] = None) -> Optional["StatsCatalog"]:
        """Load the catalog (None when disabled or the root is
        unusable — the catalog is advisory and must never fail the
        query it profiles)."""
        root = stats_dir() if root is None else root
        if not root:
            return None
        cat = cls(root)
        try:
            cat._load()
        except OSError as e:
            log.warning("stats_catalog: cannot read %r (%s: %s); catalog "
                        "disabled for this operation", root,
                        type(e).__name__, e)
            return None
        return cat

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "r", encoding="utf-8") as fh:
            for raw in fh:
                if not raw.strip():
                    continue
                try:
                    entry = json.loads(raw)
                except ValueError:
                    # a torn line is the expected shape of a crash
                    # mid-append.  Unlike the run journal, the catalog
                    # OUTLIVES the crash — a later process repairs the
                    # newline and keeps appending — so a bad line is
                    # skipped, not a stop: records after it are real
                    self.torn = True
                    continue
                key = entry.get("key")
                if not isinstance(key, str):
                    continue
                # re-insert so iteration order is write-recency order
                self.entries.pop(key, None)
                self.entries[key] = entry.get("stats") or {}

    def lookup(self, fingerprint: str) -> Optional[dict]:
        return self.entries.get(fingerprint)

    def record(self, fingerprint: str, stats: dict) -> None:
        """Append one fsync'd record; compacts past the cap.  IO
        failures are warned and swallowed — persisting statistics is
        best-effort by contract."""
        entry = {"v": VERSION, "key": fingerprint, "stats": stats}
        line = json.dumps(entry, sort_keys=True, default=_js) + "\n"
        try:
            os.makedirs(self.root, exist_ok=True)
            with open(self.path, "a+", encoding="utf-8") as fh:
                # repair a predecessor's torn tail: an append must start
                # on its own line or it merges into the torn record and
                # both are lost to every future reader
                fh.seek(0, os.SEEK_END)
                if fh.tell() > 0:
                    fh.seek(fh.tell() - 1)
                    if fh.read(1) != "\n":
                        fh.write("\n")
                fh.write(line)
                fh.flush()
                os.fsync(fh.fileno())
        except OSError as e:
            log.warning("stats_catalog: record failed (%s: %s); dropping",
                        type(e).__name__, e)
            return
        self.entries.pop(fingerprint, None)
        self.entries[fingerprint] = stats
        if len(self.entries) > stats_cap():
            self._compact()

    def _compact(self) -> None:
        """Rewrite the file with the ``stats_cap()`` most recently
        written entries (atomic tmp + fsync + rename, the durable.py
        discipline: a crash at any point leaves either the old complete
        file or the new complete file).

        Re-reads the file FIRST (the CoordLog ownership-re-read
        discipline): this catalog's in-memory view may predate another
        process's fsync'd appends, and a destructive rewrite from a
        stale view would erase them.  A write landing between the
        re-read and the rename can still lose (last-writer-wins on the
        whole file) — acceptable for advisory statistics, documented
        here rather than papered over with cross-process locks."""
        fresh = StatsCatalog(self.root)
        try:
            fresh._load()
        except OSError:
            return  # can't see the ground truth: don't rewrite over it
        self.entries = fresh.entries
        keep_keys = list(self.entries)[-stats_cap():]
        keep = {k: self.entries[k] for k in keep_keys}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                for k in keep_keys:
                    fh.write(json.dumps(
                        {"v": VERSION, "key": k, "stats": keep[k]},
                        sort_keys=True, default=_js) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError as e:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            log.warning("stats_catalog: compaction failed (%s: %s); the "
                        "append-only file keeps growing until the next "
                        "attempt", type(e).__name__, e)
            return
        self.entries = keep


def _js(o):
    """JSON default: numpy scalars and other numerics label themselves
    instead of crashing the record."""
    try:
        return float(o)
    except (TypeError, ValueError):
        return str(o)


# ---------------------------------------------------------------------------
# module-level convenience (fresh view per call: the file is small and a
# concurrent writer's appends must be visible to this process's lookups)
# ---------------------------------------------------------------------------


def lookup(fingerprint: str) -> Optional[dict]:
    cat = StatsCatalog.open()
    return None if cat is None else cat.lookup(fingerprint)


def record(fingerprint: str, stats: dict) -> None:
    cat = StatsCatalog.open()
    if cat is not None:
        cat.record(fingerprint, stats)


def keys() -> List[str]:
    cat = StatsCatalog.open()
    return [] if cat is None else list(cat.entries)
