"""OpenMetrics / Prometheus text exposition of the metrics registry.

The ``obs.metrics`` registry was only visible as ad-hoc JSON snapshots
(``export_metrics``, flight dumps, the coordinator ``status`` verb) —
fine for post-mortems, useless for a fleet that claims production scale:
every real scrape pipeline (Prometheus, Grafana agent, OpenTelemetry
collectors) speaks the text exposition format, not our JSON.  This
module renders the snapshot in that format and serves it:

- :func:`render` — counters (``_total`` suffix), gauges, and cumulative
  ``le``-bucket histograms (``_bucket``/``_sum``/``_count``) from one
  process's snapshot, names mangled ``shuffle.bytes_sent`` →
  ``cylon_tpu_shuffle_bytes_sent_total`` and bracketed tenant keys
  (``serve.run_ms[t]``) lifted into a ``tenant`` label;
- :func:`render_fleet` — the same over per-rank snapshots (the
  coordinator ``metrics`` verb), every sample labeled ``rank="N"`` so
  Prometheus can aggregate across the gang server-side;
- :func:`start_server` / :func:`ensure_server` — a tiny stdlib
  ``http.server`` listener on ``CYLON_TPU_METRICS_PORT`` answering
  ``GET /metrics`` with a fresh render per scrape (snapshots are a dict
  copy; no device work, no locks beyond the GIL);
- :func:`parse` — a small validating parser of the exposition text
  (``# TYPE`` tracking, sample shape, cumulative-bucket monotonicity)
  used by tests and the full-tree smoke to prove a scrape is
  well-formed without depending on a prometheus client library.

Everything is host-side stdlib, like the rest of ``obs``: the profiler/
exporter contract (budget goldens byte-identical, zero new device work)
holds by construction.
"""
from __future__ import annotations

import logging
import re
import threading
from typing import Dict, List, Optional, Tuple

from .. import config
from . import metrics as metrics_mod

log = logging.getLogger("cylon_tpu")

PREFIX = "cylon_tpu_"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def metrics_port() -> int:
    """``CYLON_TPU_METRICS_PORT``: the per-process scrape port;
    0 (default) disables the listener."""
    return int(config.knob("CYLON_TPU_METRICS_PORT"))


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


#: the label-pair bracket grammar: ``name=value`` pairs, names are
#: exposition-legal identifiers, values exclude the reserved ``, =``
#: (writers remap them — router/service.py `_safe_label`)
_LABEL_PAIRS = re.compile(
    r"^[a-zA-Z_][a-zA-Z0-9_]*=[^,=]*(?:,[a-zA-Z_][a-zA-Z0-9_]*=[^,=]*)*$")


def _split_label(key: str) -> Tuple[str, List[Tuple[str, str]]]:
    """Lift the bracketed labels out of a registry key.  Two grammars:

    - the PR-7/8 tenant form ``serve.run_ms[acme]`` -> one ``tenant``
      label (the bracket body is the tenant id, arbitrary bytes);
    - the PR-14 pair form ``router.requests_routed[tenant=a,replica=1]``
      -> explicit labels, accepted ONLY for ``router.``-prefixed keys
      (a serve tenant literally named ``x=y`` must keep rendering as a
      tenant, not sprout an ``x`` label)."""
    if key.endswith("]") and "[" in key:
        base, _, rest = key.partition("[")
        body = rest[:-1]
        if base.startswith("router.") and _LABEL_PAIRS.match(body):
            return base, [tuple(p.split("=", 1))  # type: ignore[misc]
                          for p in body.split(",")]
        return base, [("tenant", body)]
    return key, []


def metric_name(key: str, *, counter: bool = False) -> str:
    """Registry key -> exposition metric name: ``cylon_tpu_`` prefix,
    dots and every other illegal character to ``_``, counters get the
    conventional ``_total`` suffix."""
    name = PREFIX + _SANITIZE.sub("_", key)
    if counter and not name.endswith("_total"):
        name += "_total"
    assert _NAME_OK.match(name), name
    return name


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_esc(str(v))}"' for k, v in pairs) + "}"


def _num(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _render_into(lines: List[str], snapshot: Dict,
                 extra_labels: List[Tuple[str, str]],
                 typed: Dict[str, str]) -> None:
    """Append one snapshot's samples, emitting each metric's ``# TYPE``
    header exactly once across the whole document (``typed`` is the
    name -> kind memo shared between ranks of a fleet render)."""

    def head(name: str, kind: str) -> None:
        if name not in typed:
            typed[name] = kind
            lines.append(f"# TYPE {name} {kind}")

    for key in sorted(snapshot.get("counters") or {}):
        base, pairs = _split_label(key)
        name = metric_name(base, counter=True)
        head(name, "counter")
        lab = list(extra_labels) + pairs
        lines.append(f"{name}{_labels(lab)} "
                     f"{_num((snapshot['counters'])[key])}")
    for key in sorted(snapshot.get("gauges") or {}):
        base, pairs = _split_label(key)
        name = metric_name(base)
        head(name, "gauge")
        lab = list(extra_labels) + pairs
        lines.append(f"{name}{_labels(lab)} "
                     f"{_num((snapshot['gauges'])[key])}")
    for key in sorted(snapshot.get("histograms") or {}):
        h = (snapshot["histograms"])[key]
        base, pairs = _split_label(key)
        name = metric_name(base)
        head(name, "histogram")
        lab = list(extra_labels) + pairs
        le = h.get("le") or {}
        count = int(h.get("count", 0))
        if "+Inf" not in le:
            # a histogram recorded before the le buckets existed (an old
            # flight dump, a foreign snapshot): one +Inf bucket == count
            # keeps the exposition well-formed
            le = dict(le, **{"+Inf": count})
        for bound, n in sorted(
                le.items(),
                key=lambda kv: (float("inf") if kv[0] == "+Inf"
                                else float(kv[0]))):
            lines.append(f"{name}_bucket"
                         f"{_labels(lab + [('le', bound)])} {int(n)}")
        lines.append(f"{name}_sum{_labels(lab)} "
                     f"{_num(float(h.get('sum', 0.0)))}")
        lines.append(f"{name}_count{_labels(lab)} {count}")


def _pkg_version() -> str:
    """The package version for the build-info gauge, resolved lazily so
    this module never imports the (heavy) package root."""
    import sys as _sys

    v = getattr(_sys.modules.get("cylon_tpu"), "__version__", None)
    return str(v) if v else "unknown"


def _append_build_info(lines: List[str], typed: Dict[str, str],
                       extra_labels: List[Tuple[str, str]]) -> None:
    """The ``cylon_tpu_build_info`` info-style gauge (value always 1;
    identity rides the labels): version, rank and the last-observed
    coordinator incarnation — so a scrape pipeline can tell WHICH build
    and WHICH coordinator lifetime every other sample belongs to."""
    from . import export as export_mod
    from . import fleet as fleet_mod

    name = PREFIX + "build_info"
    if name not in typed:
        typed[name] = "gauge"
        lines.append(f"# TYPE {name} gauge")
    inc = fleet_mod.current_incarnation()
    lab = list(extra_labels) + [
        ("version", _pkg_version()),
        ("rank", str(fleet_mod.current_rank()
                     if fleet_mod.current_rank() is not None
                     else export_mod.default_rank())),
        ("incarnation", str(inc if inc is not None else -1)),
    ]
    lines.append(f"{name}{_labels(lab)} 1")


#: counters a scrape must ALWAYS see, zero-valued before first increment:
#: the tail-retention pair — a dashboard alerting on retention behavior
#: must be able to distinguish "no requests closed yet" (both zero) from
#: "the counters don't exist" (a broken deploy) — and the streaming
#: ingest pair (PR 19), for the same reason: an idle stream scrapes as
#: zeros, a process without the stream subsystem is a broken deploy
_ALWAYS_COUNTERS = ("trace.tail_kept", "trace.tail_dropped",
                    "stream.batches_appended", "stream.rows_delta")


def _with_always_counters(snap: Dict) -> Dict:
    counters = dict(snap.get("counters") or {})
    if all(k in counters for k in _ALWAYS_COUNTERS):
        return snap
    return {**snap,
            "counters": {**{k: 0 for k in _ALWAYS_COUNTERS}, **counters}}


def render(snapshot: Optional[Dict] = None) -> str:
    """One process's metrics snapshot as exposition text (terminated by
    the OpenMetrics ``# EOF`` marker, which Prometheus' text parser
    treats as a comment).  Always carries the ``cylon_tpu_build_info``
    identity gauge and the ``trace.tail_kept``/``trace.tail_dropped``
    retention pair, even over an empty registry."""
    snap = _with_always_counters(
        metrics_mod.snapshot() if snapshot is None else snapshot)
    lines: List[str] = []
    typed: Dict[str, str] = {}
    _append_build_info(lines, typed, [])
    _render_into(lines, snap, [], typed)
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_fleet(snapshots: Dict[str, Dict]) -> str:
    """Per-rank snapshots (the coordinator's heartbeat-shipped ledger)
    as ONE exposition document, every sample labeled ``rank``.  Ranks
    render in sorted order; each metric's ``# TYPE`` appears once.
    Carries the same always-on surface as :func:`render`: the rendering
    process's ``build_info`` identity gauge (per-rank versions are not
    shipped over heartbeats — the coordinator's identity stands in) and
    the zero-valued retention counter pair PER RANK, so the fleet
    scrape distinguishes "no requests closed on rank N" from a broken
    deploy exactly like the per-process one."""
    lines: List[str] = []
    typed: Dict[str, str] = {}
    _append_build_info(lines, typed, [])
    for rank in sorted(snapshots, key=str):
        _render_into(lines, _with_always_counters(snapshots[rank] or {}),
                     [("rank", str(rank))], typed)
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# validating parser (tests + the full-tree smoke)
# ---------------------------------------------------------------------------

# label values are QUOTED strings that may legally contain '}' and
# escaped quotes (tenant ids are arbitrary) — the label block must be
# matched as a sequence of quoted pairs, never as "anything up to the
# first '}'" (which broke render->parse roundtrip on a tenant "a}b")
_LABEL_PAIR = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{(?:" + _LABEL_PAIR + r")?(?:," + _LABEL_PAIR + r")*\})?"
    r"\s+(?P<value>\S+)$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESC = re.compile(r"\\(.)")


def _unescape(v: str) -> str:
    return _UNESC.sub(lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), v)


def parse(text: str) -> Dict[str, Dict]:
    """Validate exposition text and return
    ``{metric name: {"type": kind, "samples": [(labels dict, value)]}}``
    (bucket/sum/count samples attach to their histogram's base name).
    Raises ``ValueError`` on malformed lines, samples preceding their
    ``# TYPE``, a missing ``# EOF``, or non-monotone cumulative
    buckets."""
    out: Dict[str, Dict] = {}
    saw_eof = False
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if saw_eof:
            raise ValueError(f"line {ln}: content after # EOF")
        if line == "# EOF":
            saw_eof = True
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                name, kind = parts[2], parts[3]
                if kind not in ("counter", "gauge", "histogram"):
                    raise ValueError(f"line {ln}: unknown type {kind!r}")
                if name in out:
                    raise ValueError(f"line {ln}: duplicate TYPE for {name}")
                out[name] = {"type": kind, "samples": []}
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"line {ln}: malformed sample {line!r}")
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in out \
                    and out[name[: -len(suffix)]]["type"] == "histogram":
                base = name[: -len(suffix)]
                break
        if base not in out:
            raise ValueError(f"line {ln}: sample {name!r} precedes its "
                             f"# TYPE header")
        labels = {k: _unescape(v)
                  for k, v in _LABEL.findall(m.group("labels") or "")}
        try:
            value = float(m.group("value"))
        except ValueError as e:
            raise ValueError(f"line {ln}: bad value {m.group('value')!r}"
                             ) from e
        out[base]["samples"].append((name, labels, value))
    if not saw_eof:
        raise ValueError("missing # EOF terminator")
    for name, rec in out.items():
        if rec["type"] != "histogram":
            continue
        # cumulative-bucket monotonicity per label set (minus `le`)
        series: Dict[tuple, List[Tuple[float, float]]] = {}
        for sname, labels, value in rec["samples"]:
            if not sname.endswith("_bucket"):
                continue
            le = labels.get("le")
            if le is None:
                raise ValueError(f"{sname}: bucket sample without le")
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            bound = float("inf") if le == "+Inf" else float(le)
            series.setdefault(key, []).append((bound, value))
        for key, pts in series.items():
            pts.sort()
            vals = [v for _, v in pts]
            if vals != sorted(vals):
                raise ValueError(f"{name}{dict(key)}: non-monotone "
                                 f"cumulative buckets {vals}")
            if pts and pts[-1][0] != float("inf"):
                raise ValueError(f"{name}{dict(key)}: missing +Inf bucket")
    return out


# ---------------------------------------------------------------------------
# the scrape listener
# ---------------------------------------------------------------------------

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Tiny stdlib HTTP listener answering ``GET /metrics`` (and ``/``)
    with a fresh :func:`render` per scrape.  Daemon-threaded; binding
    port 0 takes an ephemeral port (``.port`` reports it)."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib handler API)
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                log.debug("openmetrics: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"cylon-openmetrics-{self.port}")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


_lock = threading.Lock()
_server: Optional[MetricsServer] = None


def start_server(port: int, host: str = "127.0.0.1") -> MetricsServer:
    """Start a listener on an explicit port (0 = ephemeral).  The caller
    owns the returned server (tests, scripts); :func:`ensure_server` is
    the knob-driven singleton path."""
    return MetricsServer(port, host)


def ensure_server() -> Optional[MetricsServer]:
    """Start (once per process) the knob-driven scrape listener when
    ``CYLON_TPU_METRICS_PORT`` > 0; None when disabled or the bind
    failed (an occupied port must never fail the context bringing the
    listener up — scraping is an observability extra, warned and
    skipped)."""
    global _server
    port = metrics_port()
    if port <= 0:
        return None
    with _lock:
        if _server is not None:
            return _server
        try:
            _server = start_server(port)
        except OSError as e:
            log.warning("openmetrics: cannot bind scrape port %d (%s: %s); "
                        "metrics listener disabled for this process",
                        port, type(e).__name__, e)
            return None
        log.info("openmetrics: serving /metrics on %s:%d",
                 _server.host, _server.port)
        return _server


def stop_server() -> None:
    """Stop the singleton listener (tests)."""
    global _server
    with _lock:
        if _server is not None:
            _server.close()
            _server = None
