"""cylon_tpu.obs — structured tracing, metrics, and Perfetto export.

The fourth leg after robustness (PR 1), perf (PR 2) and static analysis
(PR 3): PR 3's budget gates prove what a plan WOULD launch; this
subsystem records what actually ran — nested wall-clock spans over every
hot path (``obs.spans``), counters/gauges/histograms for collective
launches, bytes moved, retries, OOM refinements and plan-cache traffic
(``obs.metrics``), and Chrome-trace/Perfetto + flat-JSON artifacts with
per-rank naming (``obs.export``).  Zero hard dependencies (jax is
consulted through ``sys.modules`` only), host-side by construction.

Knobs (all runtime scope, registered in ``config.KNOBS``):
``CYLON_TPU_TRACE`` (auto: aggregate stopwatch only; 1: event buffer for
export; 0: alloc-free no-op), ``CYLON_TPU_TRACE_SYNC`` (device fence at
span boundaries), ``CYLON_TPU_TRACE_DIR``, ``CYLON_TPU_TRACE_BUFFER_CAP``.
"""
from __future__ import annotations

from . import export  # noqa: F401
from . import fleet  # noqa: F401
from . import metrics  # noqa: F401
from . import spans  # noqa: F401
from . import tracectx  # noqa: F401
from .spans import instant, span  # noqa: F401
