"""Causal request tracing: W3C-traceparent-style context propagation.

The fleet plane (obs.fleet + tools/trace_merge.py) lays every rank's
spans on ONE aligned clock, but nothing connects *one request* to the
engine passes, collectives and remote ranks it caused — the merged
Perfetto view is concurrent spans with no causal edges.  This module is
the missing identity layer:

- a :class:`TraceContext` (trace_id, span_id, parent_span_id, sampled)
  carried in a ``contextvars.ContextVar`` — host-side annotation ONLY,
  in the composable-primitives discipline of DrJAX (arXiv 2403.07128):
  traced jax programs, plan cache keys and jaxpr budget goldens are
  byte-identical with or without an active trace;
- the W3C ``traceparent`` wire form
  (``00-<32 hex trace>-<16 hex span>-<2 hex flags>``) so clients of the
  serve layer can supply their own context and control verbs can carry
  it across the coordinator wire (net/control.py attaches/activates it
  on every one-shot request);
- every ``obs.spans`` span entered while a context is active becomes a
  CHILD span (fresh span_id, parent = enclosing span) and its buffered
  event carries the (trace_id, span_id, parent_span_id) triple — the
  causal edges ``tools/critical_path.py`` walks;
- **tail-based retention** makes request tracing affordable always-on:
  when ``CYLON_TPU_TRACE_TAIL_MS`` > 0, a closing request KEEPS its
  buffered events only if it was slow (latency above the knob, or above
  a rolling p99 estimate), failed, or head-sampled
  (``CYLON_TPU_TRACE_SAMPLE_N`` = 1-in-N); fast-and-healthy requests
  keep only the aggregate stopwatch — their events are discarded from
  the buffer at close (``trace.tail_dropped``), bounded throughout by
  the existing buffer-cap/drop-counter machinery.

Host-side stdlib only (no jax), like the rest of ``obs``.
"""
from __future__ import annotations

import contextlib
import os
import re
import threading
from contextvars import ContextVar
from typing import Dict, NamedTuple, Optional, Tuple

from .. import config
from . import metrics as metrics_mod


# ---------------------------------------------------------------------------
# knob accessors (registry rows in config.py::KNOBS)
# ---------------------------------------------------------------------------

def tail_threshold_ms() -> float:
    """``CYLON_TPU_TRACE_TAIL_MS``: latency above which a request's
    buffered events are kept; 0 disables tail retention (keep all)."""
    return max(0.0, float(config.knob("CYLON_TPU_TRACE_TAIL_MS")))


def head_sample_n() -> int:
    """``CYLON_TPU_TRACE_SAMPLE_N``: 1-in-N head sampling; 0 disables."""
    return max(0, int(config.knob("CYLON_TPU_TRACE_SAMPLE_N")))


# ---------------------------------------------------------------------------
# the context
# ---------------------------------------------------------------------------

class TraceContext(NamedTuple):
    """One causal position: which request (``trace_id``), which span
    within it (``span_id``), and which span caused it
    (``parent_span_id``).  ``sampled`` marks a head-sampled trace that
    survives tail retention regardless of latency."""

    trace_id: str                    # 32 lowercase hex chars
    span_id: str                     # 16 lowercase hex chars
    parent_span_id: Optional[str] = None
    sampled: bool = False

    def child(self) -> "TraceContext":
        """A fresh span under this one (same trace, new span_id)."""
        return TraceContext(self.trace_id, _new_span_id(), self.span_id,
                            self.sampled)

    def traceparent(self) -> str:
        """The W3C wire form."""
        return (f"00-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    def triple(self) -> Tuple[str, str, Optional[str]]:
        return (self.trace_id, self.span_id, self.parent_span_id)


_TRACEPARENT = re.compile(
    r"^(?P<ver>[0-9a-f]{2})-(?P<trace>[0-9a-f]{32})-"
    r"(?P<span>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$")


def parse_traceparent(s: str) -> TraceContext:
    """Strict W3C ``traceparent`` parse.  Raises ``ValueError`` on any
    malformation (wrong field widths, uppercase hex, version ``ff``,
    all-zero trace or span id, trailing garbage) — a garbled header must
    be REJECTED, never silently adopted as somebody's trace."""
    if not isinstance(s, str):
        raise ValueError(f"traceparent must be a string, got {type(s)}")
    m = _TRACEPARENT.match(s)
    if m is None:
        raise ValueError(f"malformed traceparent {s!r} (want "
                         f"00-<32 hex>-<16 hex>-<2 hex>, lowercase)")
    if m.group("ver") == "ff":
        raise ValueError(f"traceparent {s!r}: version ff is forbidden")
    if m.group("trace") == "0" * 32:
        raise ValueError(f"traceparent {s!r}: all-zero trace id")
    if m.group("span") == "0" * 16:
        raise ValueError(f"traceparent {s!r}: all-zero span id")
    return TraceContext(m.group("trace"), m.group("span"), None,
                        bool(int(m.group("flags"), 16) & 1))


def parse_or_none(s) -> Optional[TraceContext]:
    """Lenient parse for wire paths where a bad header means "no trace",
    not an error (a control verb must never fail on a garbled label)."""
    if not isinstance(s, str) or not s:
        return None
    try:
        return parse_traceparent(s)
    except ValueError:
        return None


def _new_span_id() -> str:
    return os.urandom(8).hex()


_mint_lock = threading.Lock()
_minted = 0


def new_trace(sampled: Optional[bool] = None) -> TraceContext:
    """Mint a root context for one request.  ``sampled`` defaults to the
    1-in-N head-sampling decision (``CYLON_TPU_TRACE_SAMPLE_N``)."""
    if sampled is None:
        n = head_sample_n()
        if n > 0:
            global _minted
            with _mint_lock:
                sampled = _minted % n == 0
                _minted += 1
        else:
            sampled = False
    return TraceContext(os.urandom(16).hex(), _new_span_id(), None,
                        bool(sampled))


# ---------------------------------------------------------------------------
# the ambient context
# ---------------------------------------------------------------------------

_current: "ContextVar[Optional[TraceContext]]" = ContextVar(
    "cylon_tpu_trace", default=None)

# CYLON_TPU_TRACEPARENT fallback, cached per raw value: the knob roots a
# whole process in a caller's trace (deployment/CI hook) and is read on
# the span hot path, so the parse must not repeat per span
_ambient_cache: Tuple[Optional[str], Optional[TraceContext]] = (None, None)


def _ambient() -> Optional[TraceContext]:
    global _ambient_cache
    raw = str(config.knob("CYLON_TPU_TRACEPARENT"))
    if not raw:
        return None
    cached_raw, cached = _ambient_cache
    if cached_raw != raw:
        cached = parse_or_none(raw)
        _ambient_cache = (raw, cached)
    return cached


def current() -> Optional[TraceContext]:
    """The active context: the contextvar when set, else the
    ``CYLON_TPU_TRACEPARENT`` ambient root, else None."""
    ctx = _current.get()
    return ctx if ctx is not None else _ambient()


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
    """Make ``ctx`` the active context for the dynamic extent (a no-op
    passthrough when ``ctx`` is None, so call sites need no branching)."""
    if ctx is None:
        yield None
        return
    tok = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(tok)


def push_span():
    """Enter a child span of the active context (obs.spans calls this on
    span entry).  Returns ``(child_ctx, reset_token)`` or None when no
    context is active — the common case, kept to one contextvar read."""
    cur = current()
    if cur is None:
        return None
    child = cur.child()
    return child, _current.set(child)


def pop_span(token) -> None:
    _current.reset(token)


# ---------------------------------------------------------------------------
# tail-based retention
# ---------------------------------------------------------------------------

#: minimum closed-request observations before the rolling p99 estimate
#: may keep a request on its own (before that every request would read
#: as "above p99" and retention would keep everything)
P99_MIN_SAMPLES = 32

_tail_lock = threading.Lock()
_p99_ms: Optional[float] = None
_lat_samples = 0


def _observe_latency(ms: float) -> None:
    """Asymmetric EWMA approximating a rolling upper-tail latency: rises
    quickly toward outliers, decays slowly — a cheap stand-in for p99
    that needs no reservoir."""
    global _p99_ms, _lat_samples
    with _tail_lock:
        _lat_samples += 1
        if _p99_ms is None:
            _p99_ms = ms
        elif ms > _p99_ms:
            _p99_ms += 0.5 * (ms - _p99_ms)
        else:
            _p99_ms -= 0.01 * (_p99_ms - ms)


def p99_estimate_ms() -> Optional[float]:
    with _tail_lock:
        return _p99_ms


def tail_keep(ctx: TraceContext, duration_ms: float, *,
              failed: bool = False) -> bool:
    """The retention decision for one closing request.  Retention off
    (``CYLON_TPU_TRACE_TAIL_MS`` = 0) keeps everything — the pre-PR-13
    behavior; on, keep only slow / failed / head-sampled requests."""
    thr = tail_threshold_ms()
    if thr <= 0:
        return True
    with _tail_lock:
        p99, samples = _p99_ms, _lat_samples
    keep = (failed or ctx.sampled or duration_ms >= thr
            or (p99 is not None and samples >= P99_MIN_SAMPLES
                and duration_ms > p99))
    # only HEALTHY closes feed the estimator: sheds close at ~0 ms and a
    # shed storm would decay the p99 toward zero, after which every fast
    # request reads as "slow" and retention keeps everything — the exact
    # buffer flood the feature exists to prevent
    if not failed:
        _observe_latency(duration_ms)
    return keep


def finish_request(ctx: Optional[TraceContext], duration_ms: float, *,
                   failed: bool = False) -> bool:
    """Close one request's trace: decide retention, discard the trace's
    buffered events when it loses, and count the outcome
    (``trace.tail_kept`` / ``trace.tail_dropped`` — the scrapeable
    retention behavior).  Returns whether the events were kept.  Every
    terminal serve path calls this exactly once — completed, failed,
    cancelled, and shed requests all close their trace.  With retention
    OFF (the default) this is a pure no-op: the kept/dropped counters
    describe RETENTION decisions, so they stay zero until the knob is
    set ("no requests closed yet" and "retention disabled" both read as
    zeros; a missing counter is a broken deploy)."""
    if ctx is None or tail_threshold_ms() <= 0:
        return True
    if tail_keep(ctx, duration_ms, failed=failed):
        metrics_mod.counter_add("trace.tail_kept")
        return True
    from . import spans as spans_mod  # no cycle at call time

    discarded = spans_mod.discard_trace(ctx.trace_id)
    metrics_mod.counter_add("trace.tail_dropped")
    if discarded:
        metrics_mod.counter_add("trace.tail_events_discarded", discarded)
    return False


def reset() -> None:
    """Clear the retention estimator and sampling counter (tests)."""
    global _p99_ms, _lat_samples, _minted, _ambient_cache
    with _tail_lock:
        _p99_ms = None
        _lat_samples = 0
    with _mint_lock:
        _minted = 0
    _ambient_cache = (None, None)


# ---------------------------------------------------------------------------
# wire helpers (control-plane verbs)
# ---------------------------------------------------------------------------

def attach_wire(obj: Dict) -> Dict:
    """Return ``obj`` with the active context's ``traceparent`` attached
    (a copy; the original is never mutated).  No-op when no context is
    active or the caller already set one."""
    ctx = current()
    if ctx is None or "traceparent" in obj:
        return obj
    return dict(obj, traceparent=ctx.traceparent())
