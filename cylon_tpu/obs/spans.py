"""Structured tracing spans: the event substrate behind Perfetto export.

Replaces the PR-0 stopwatch (``utils/timing.py``, now a thin shim over
this module) with a process-local EVENT BUFFER — every span records
(monotonic ns start, duration, thread id, nesting depth, attributes) so
``obs.export`` can emit a Chrome-trace/Perfetto JSON showing exactly
where wall-clock went, not just per-name totals.

Three operating modes, selected by the ``CYLON_TPU_TRACE`` registry knob
(read live on every ``span()`` call, so ``config.knob_env`` works):

- ``auto`` (default) — the always-on aggregate stopwatch only: each span
  costs two ``perf_counter_ns`` reads and two dict updates (the PR-0
  ``utils.timing`` behavior; benchmarks read phase breakdowns via
  ``aggregate_report()``).  No event is buffered.
- ``1`` / ``on`` — aggregates PLUS the bounded event buffer
  (``CYLON_TPU_TRACE_BUFFER_CAP`` events; past it events are dropped and
  counted, never grown) for export.
- ``0`` / ``off`` — a true no-op: ``span()`` returns a process-wide
  singleton null context manager and touches nothing (the alloc-free
  fast path tests/test_obs.py pins).

Host-side only, by construction: a span measures host wall-clock between
``__enter__`` and ``__exit__`` and never reads a device value, so spans
are legal inside jit/shard_map bodies (they then measure TRACE time and
appear as children of the enclosing plan-build span — cylint CY101 stays
green because no tracer is read).  Device execution is asynchronous, so
by default device time lands in whichever span performed the blocking
fetch; ``CYLON_TPU_TRACE_SYNC=1`` fences (``block_until_ready`` on a
trivial dispatch, which on in-order backends drains prior launches) at
span boundaries to attribute device time to the span that launched it —
off by default because the fence serializes the pipeline.
"""
from __future__ import annotations

import logging
import sys
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, NamedTuple, Optional, Tuple

from .. import config
from . import tracectx

log = logging.getLogger("cylon_tpu")

OFF = "off"
AGGREGATE = "aggregate"
EVENTS = "events"

_MODE_OF = {"0": OFF, "off": OFF, "auto": AGGREGATE,
            "1": EVENTS, "on": EVENTS}


class Event(NamedTuple):
    """One buffered trace event.  ``ts``/``dur`` are monotonic
    nanoseconds (``time.perf_counter_ns``); ``ph`` is the Chrome-trace
    phase — "X" complete span, "i" instant.  ``trace`` is the causal
    identity triple ``(trace_id, span_id, parent_span_id)`` when a
    request context (obs.tracectx) was active, else None."""

    name: str
    ts: int
    dur: int
    tid: int
    depth: int
    ph: str
    attrs: Optional[Dict[str, object]]
    trace: Optional[Tuple[str, str, Optional[str]]] = None


_events: List[Event] = []
_dropped = 0
# guards buffer membership (record vs retention discard): only taken
# when event buffering is ON — the aggregate-only default never touches
# it.  Readers (events(), exports) stay lock-free: tuple(_events) is one
# GIL-atomic C call and the list is only ever appended or rebuilt whole.
_buf_lock = threading.Lock()
_totals: Dict[str, float] = {}
_counts: Dict[str, int] = {}
_tls = threading.local()

# flight-recorder ring: the most recent events, kept in EVERY enabled
# mode (aggregate included) so a terminal-event dump (obs.fleet) has
# context even when the user never armed CYLON_TPU_TRACE=1.  Unlike the
# export buffer it overwrites oldest-first — a post-mortem wants the
# events LEADING UP to the failure, not the run's first N.
_ring: "deque[Event]" = deque(maxlen=512)

# CYLON_TPU_DEBUG log-on-exit (the PR-0 utils.timing behavior, preserved
# through the shim): initialized from the knob, flipped by enable_log()
_log_enabled = bool(config.knob("CYLON_TPU_DEBUG"))


def mode() -> str:
    """The live tracing mode: "off" | "aggregate" | "events"
    (``CYLON_TPU_TRACE``, read per call so knob_env overrides apply)."""
    return _MODE_OF.get(str(config.knob("CYLON_TPU_TRACE")), AGGREGATE)


def enabled() -> bool:
    return mode() != OFF


def events_enabled() -> bool:
    return mode() == EVENTS


def sync_enabled() -> bool:
    return bool(config.knob("CYLON_TPU_TRACE_SYNC"))


def buffer_cap() -> int:
    return max(1, int(config.knob("CYLON_TPU_TRACE_BUFFER_CAP")))


def ring_cap() -> int:
    """``CYLON_TPU_FLIGHT_RING_CAP``: flight-recorder ring size (0 off)."""
    return max(0, int(config.knob("CYLON_TPU_FLIGHT_RING_CAP")))


def _ring_record(ev: Event) -> None:
    global _ring
    cap = ring_cap()
    if cap <= 0:
        return
    if _ring.maxlen != cap:
        _ring = deque(_ring, maxlen=cap)
    _ring.append(ev)


def ring_events() -> Tuple[Event, ...]:
    """Snapshot of the flight-recorder ring, oldest first."""
    return tuple(_ring)


def enable_log(on: bool = True) -> None:
    """Flip the per-span INFO log (the old ``utils.timing.enable``)."""
    global _log_enabled
    _log_enabled = on


def log_enabled() -> bool:
    return _log_enabled


def _depth() -> int:
    return getattr(_tls, "depth", 0)


def _fence() -> None:
    """Drain prior device launches: block on a trivial dispatch (in-order
    execution on TPU/CPU backends means it completes after everything
    launched before it).  No-op when jax was never imported — obs itself
    stays importable without jax."""
    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        jax.block_until_ready(jax.numpy.add(jax.numpy.int32(0),
                                            jax.numpy.int32(0)))
    except Exception as e:  # a failed fence must never kill the op it wraps
        log.debug("trace sync fence failed: %s: %s", type(e).__name__, e)


def _record(ev: Event) -> None:
    global _dropped
    with _buf_lock:
        if len(_events) >= buffer_cap():
            _dropped += 1
            return
        _events.append(ev)


class _NullSpan:
    """The disabled-mode singleton: every method is a no-op and ``span()``
    hands out the same instance, so fully-disabled tracing allocates
    nothing per call site."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "_t0", "_d", "_buffer", "_sync", "_ring",
                 "_trace")

    def __init__(self, name: str, attrs: Optional[Dict[str, object]],
                 buffer: bool, sync: bool, ring: bool):
        self.name = name
        self.attrs = attrs
        self._buffer = buffer
        self._sync = sync
        self._ring = ring
        self._trace = None

    def set(self, **attrs) -> "_Span":
        """Attach/refresh attributes after entry (e.g. a row count known
        only once the pass fetched)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "_Span":
        if self._sync:
            _fence()
        if self._buffer or self._ring:
            # causal identity: become a child span of the active request
            # context (None — the common case — costs one contextvar read)
            self._trace = tracectx.push_span()
        self._d = _depth()
        _tls.depth = self._d + 1
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        if self._sync:
            _fence()
        t1 = time.perf_counter_ns()
        _tls.depth = self._d
        dur = t1 - self._t0
        _totals[self.name] = _totals.get(self.name, 0.0) + dur * 1e-9
        _counts[self.name] = _counts.get(self.name, 0) + 1
        if self._buffer or self._ring:
            tr = None
            if self._trace is not None:
                ctx, tok = self._trace
                tracectx.pop_span(tok)
                tr = ctx.triple()
            ev = Event(self.name, self._t0, dur,
                       threading.get_ident(), self._d, "X", self.attrs, tr)
            if self._buffer:
                _record(ev)
            if self._ring:
                _ring_record(ev)
        if _log_enabled:
            log.info("%s took %.3f ms", self.name, dur * 1e-6)
        return False


def span(name: str, **attrs):
    """Context manager timing one named phase.

    Aggregate totals always accumulate (unless tracing is fully off);
    under ``CYLON_TPU_TRACE=1`` the span also lands in the event buffer
    with its attributes.  Use ``as s`` + ``s.set(...)`` for attributes
    known only at exit."""
    m = mode()
    if m == OFF:
        return _NULL
    # the sync/ring knobs resolve ONCE per span, not per boundary, so
    # enter/exit stay at two perf_counter reads and two dict updates
    return _Span(name, attrs or None, m == EVENTS, sync_enabled(),
                 ring_cap() > 0)


def instant(name: str, **attrs) -> None:
    """Record a zero-duration instant event (retry, injected fault, OOM
    refinement).  Counted in the aggregates; buffered only under
    ``CYLON_TPU_TRACE=1``."""
    m = mode()
    if m == OFF:
        return
    _counts[name] = _counts.get(name, 0) + 1
    _totals.setdefault(name, 0.0)
    if m == EVENTS or ring_cap() > 0:
        c = tracectx.current()
        ev = Event(name, time.perf_counter_ns(), 0,
                   threading.get_ident(), _depth(), "i", attrs or None,
                   None if c is None else c.triple())
        if m == EVENTS:
            _record(ev)
        _ring_record(ev)


def events() -> Tuple[Event, ...]:
    """Snapshot of the buffered events, in record order."""
    return tuple(_events)


def discard_trace(trace_id: str) -> int:
    """Tail-based retention's discard half: remove buffered events
    stamped with ``trace_id`` (a fast-and-healthy request closing), and
    return how many were removed.  The flight ring is deliberately
    untouched (a post-mortem wants the most recent events whoever owned
    them) and the drop counter is MONOTONE — retention discards are
    accounted separately (``trace.tail_dropped``), never by un-counting
    overflow drops.  One O(buffer) rebuild under the record lock, so a
    concurrent request's append can never be lost mid-rebuild; the cost
    is bounded by the buffer cap and paid only on a losing close."""
    with _buf_lock:
        before = len(_events)
        _events[:] = [e for e in _events
                      if e.trace is None or e.trace[0] != trace_id]
        return before - len(_events)


def dropped() -> int:
    """Events discarded because the buffer was at capacity."""
    return _dropped


def aggregate_report() -> Dict[str, Tuple[float, int]]:
    """{span name: (total seconds, call count)} — the PR-0
    ``utils.timing.report`` surface."""
    return {k: (_totals[k], _counts.get(k, 0)) for k in _totals}


def reset_aggregates() -> None:
    """Clear the aggregate stopwatch totals ONLY — buffered events and
    the drop counter survive, so a benchmark clearing phase totals
    between phases (the historical ``utils.timing.reset``) cannot
    truncate a pending Perfetto export."""
    _totals.clear()
    _counts.clear()


def reset() -> None:
    """Clear the event buffer, the flight ring, the drop counter and the
    aggregates."""
    global _dropped
    _events.clear()
    _ring.clear()
    _dropped = 0
    reset_aggregates()
