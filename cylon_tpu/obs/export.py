"""Chrome-trace/Perfetto export of the span buffer + flat metrics JSON.

Artifacts load directly in ``ui.perfetto.dev`` / ``chrome://tracing``:
the trace file is the Chrome Trace Event JSON object form
(``{"traceEvents": [...]}``) with "X" complete events (``ts``/``dur`` in
microseconds) and "i" instant events, one ``pid`` per mesh rank and the
recording thread id as ``tid``.  File names carry the rank
(``trace.r{rank}.json``) so every process of a multi-host mesh exports
beside the others without clobbering; the directory comes from the
``CYLON_TPU_TRACE_DIR`` knob.

``load_trace`` round-trips an export (the schema check
tests/test_obs.py pins); ``tools/trace_report.py`` builds its top-K
self-time table on top of these two functions.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional, Tuple

from .. import config
from . import fleet as fleet_mod
from . import metrics as metrics_mod
from . import spans as spans_mod


def trace_dir() -> str:
    """Artifact directory (``CYLON_TPU_TRACE_DIR``, default ``traces``)."""
    return str(config.knob("CYLON_TPU_TRACE_DIR")) or "traces"


def default_rank() -> int:
    """This process's mesh rank for artifact naming.

    The fleet identity (``obs.fleet.set_rank``, installed by the elastic
    agent at join) wins: every single-controller process reports
    ``jax.process_index() == 0``, so two elastic agents consulting jax
    alone would BOTH export ``trace.r0.json`` and clobber each other.
    Then ``jax.process_index`` (genuine multi-host meshes), then 0."""
    r = fleet_mod.current_rank()
    if isinstance(r, int):
        return r
    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        return int(jax.process_index())
    except Exception as e:  # backend not initialized yet: single-process
        import logging

        logging.getLogger("cylon_tpu").debug(
            "process_index unavailable (%s); exporting as rank 0", e)
        return 0


def _artifact_path(path: Optional[str], prefix: str,
                   rank: Optional[int]) -> str:
    if path is not None:
        return path
    r = default_rank() if rank is None else int(rank)
    d = trace_dir()
    os.makedirs(d, exist_ok=True)
    rid = fleet_mod.current_run_id()
    if rid:
        # run-id namespacing: back-to-back runs sharing one trace dir
        # (or two elastic runs on one host) never clobber
        return os.path.join(
            d, f"{prefix}.{fleet_mod._safe_component(rid)}.r{r}.json")
    return os.path.join(d, f"{prefix}.r{r}.json")


def _event_json(ev: spans_mod.Event, pid: int) -> Dict[str, object]:
    out: Dict[str, object] = {
        "name": ev.name, "cat": "cylon_tpu", "ph": ev.ph,
        "ts": ev.ts / 1e3, "pid": pid, "tid": ev.tid,
    }
    if ev.ph == "X":
        out["dur"] = ev.dur / 1e3
    else:
        out["s"] = "t"  # thread-scoped instant
    args: Dict[str, object] = {"depth": ev.depth}
    if ev.attrs:
        args.update(ev.attrs)
    if ev.trace is not None:
        # the causal identity triple (obs.tracectx): the edges
        # tools/critical_path.py walks and Perfetto queries can group on
        args["trace_id"], args["span_id"] = ev.trace[0], ev.trace[1]
        if ev.trace[2]:
            args["parent_span_id"] = ev.trace[2]
    out["args"] = args
    return out


def export_trace(path: Optional[str] = None, *, rank: Optional[int] = None,
                 prefix: str = "trace") -> str:
    """Write the buffered span events as Chrome-trace JSON; returns the
    file path (``{dir}/{prefix}.r{rank}.json`` unless ``path`` given)."""
    out_path = _artifact_path(path, prefix, rank)
    pid = default_rank() if rank is None else int(rank)
    doc = {
        "traceEvents": [_event_json(e, pid) for e in spans_mod.events()],
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "cylon_tpu.obs",
            "rank": pid,
            "dropped_events": spans_mod.dropped(),
            # clock alignment (obs.fleet): lets tools/trace_merge.py lay
            # this rank's monotonic timestamps onto the coordinator clock
            "run_id": fleet_mod.current_run_id(),
            "clock": fleet_mod.clock_dict(),
        },
    }
    with open(out_path, "w", encoding="utf-8") as fh:
        # default=str: attrs may carry dtypes/enums; a label beats a crash
        json.dump(doc, fh, default=str)
    return out_path


def export_metrics(path: Optional[str] = None, *, rank: Optional[int] = None,
                   prefix: str = "metrics") -> str:
    """Write the flat metrics snapshot (+ rank and span-drop counter) as
    JSON; returns the file path."""
    out_path = _artifact_path(path, prefix, rank)
    doc = dict(metrics_mod.snapshot())
    doc["rank"] = default_rank() if rank is None else int(rank)
    doc["dropped_events"] = spans_mod.dropped()
    doc["run_id"] = fleet_mod.current_run_id()
    doc["clock"] = fleet_mod.clock_dict()
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, default=str, sort_keys=True)
    return out_path


def export_all(*, rank: Optional[int] = None,
               prefix: str = "trace") -> Tuple[str, str]:
    """Trace + metrics side by side: ``{prefix}.r{rank}.json`` and
    ``{prefix}.metrics.r{rank}.json``."""
    return (export_trace(rank=rank, prefix=prefix),
            export_metrics(rank=rank, prefix=f"{prefix}.metrics"))


def load_trace(path: str) -> Dict[str, object]:
    """Load and validate an exported trace: the object form with a
    ``traceEvents`` list whose members carry name/ph/ts/pid/tid (and
    ``dur`` on "X" events)."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        raise ValueError(f"{path}: not a Chrome-trace export "
                         f"(missing traceEvents list)")
    for ev in evs:
        for k in ("name", "ph", "ts", "pid", "tid"):
            if k not in ev:
                raise ValueError(f"{path}: event missing {k!r}: {ev}")
        if ev["ph"] == "X" and "dur" not in ev:
            raise ValueError(f"{path}: complete event missing dur: {ev}")
    return doc


def load_metrics(path: str) -> Dict[str, object]:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)
