"""IO layer: CSV/Parquet ingest + egress (reference: cpp/src/cylon/io/)."""
from .arrow_io import read_csv, read_parquet, write_csv, write_parquet
from .csv_config import CSVReadOptions, CSVWriteOptions, ParquetOptions

__all__ = [
    "read_csv", "read_parquet", "write_csv", "write_parquet",
    "CSVReadOptions", "CSVWriteOptions", "ParquetOptions",
]
