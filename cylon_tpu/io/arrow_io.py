"""CSV / Parquet ingest and egress.

TPU-native analog of the reference's IO layer (reference:
cpp/src/cylon/io/arrow_io.cpp:33-116 read_csv/ReadParquet/WriteParquet and
the Table factory paths cpp/src/cylon/table.cpp:803-855 FromCSV /
:1049-1132 FromParquet/WriteParquet):

- parsing is pyarrow (the reference wraps Arrow's CSV/Parquet readers the
  same way), producing host Arrow tables;
- device placement pads columns to static capacities and lays shard i of a
  distributed table on mesh position i (cylon_tpu.table internals);
- multi-file reads fan out over a thread pool when
  ``options.ConcurrentFileReads`` (reference: table.cpp:824-844 spawns a
  std::thread + promise/future per file).

Distribution semantics:
- one path + distributed ctx  -> rows split contiguously across shards
- list of paths (len == world) -> file i becomes shard i, read concurrently
"""
from __future__ import annotations

import concurrent.futures as _futures
from typing import List, Optional, Sequence, Union

from ..status import Code, CylonError
from .csv_config import CSVReadOptions, CSVWriteOptions, ParquetOptions

PathLike = Union[str, "os.PathLike[str]"]


# pyarrow ConvertOptions default null sentinels, passed to the native
# parser so both paths agree on null semantics
_DEFAULT_NULLS = ["", "#N/A", "#N/A N/A", "#NA", "-1.#IND", "-1.#QNAN",
                  "-NaN", "-nan", "1.#IND", "1.#QNAN", "N/A", "NA", "NULL",
                  "NaN", "n/a", "nan", "null"]


def _read_csv_arrow(path: PathLike, options: CSVReadOptions):
    import pyarrow.csv as pc

    read, parse, convert = options.to_pyarrow()
    return pc.read_csv(str(path), read_options=read, parse_options=parse,
                       convert_options=convert)


def _native_csv_compatible(options: CSVReadOptions) -> bool:
    """The native parser handles the common-case option envelope; anything
    else falls back to the pyarrow reader (same outputs either way)."""
    from .. import config

    if config.knob("CYLON_TPU_NO_NATIVE_IO"):
        return False
    from .. import native

    return (not options.column_types
            and options.include_columns is None
            and options.true_values is None
            and options.false_values is None
            and not options.use_escaping
            and options.double_quote
            and len(options.delimiter) == 1
            and native.available())


def _read_csv_native(path: PathLike, options: CSVReadOptions):
    """Read over the native (C++) threaded parser into Column-shaped
    buffers (cylon_tpu/native/src/csv.cpp)."""
    from .. import native

    has_header = not (options.autogenerate_column_names
                      or options.column_names is not None)
    names, cols = native.csv_read(
        str(path), delimiter=options.delimiter, has_header=has_header,
        skip_rows=options.skip_rows,
        string_width=options.string_width or 0,
        null_values=(options.null_values if options.null_values is not None
                     else _DEFAULT_NULLS),
        use_quoting=options.use_quoting, quote_char=options.quote_char,
        strings_can_be_null=options.strings_can_be_null)
    if options.column_names is not None:
        if len(options.column_names) != len(names):
            from ..status import Code, CylonError

            raise CylonError(Code.Invalid,
                             f"{len(options.column_names)} column names for "
                             f"{len(names)} columns")
        names = list(options.column_names)
    return names, cols


# ---------------------------------------------------------------------------
# durable-execution frame spills (cylon_tpu.durable)
# ---------------------------------------------------------------------------
#
# A chunked-run pass frame is a dict of host numpy columns exactly as
# ``column.to_numpy`` produced them: plain fixed-width arrays, or object
# arrays of str/bytes/np-scalars with ``None`` under nulls.  The spill
# must round-trip BIT-IDENTICALLY (dtype included) or a resumed run's
# concatenated output would differ from an uninterrupted run's — so each
# Arrow field carries the exact numpy dtype (and, for object columns,
# the element kind) in its metadata, and fixed-width object columns are
# restored straight from the Arrow buffers (NaN payloads preserved)
# rather than through Python scalars.

_META_DTYPE = b"cylon_numpy_dtype"
_META_KIND = b"cylon_value_kind"     # object columns: str|bytes|fixed|null
_META_VDT = b"cylon_value_dtype"     # object 'fixed' columns: element dtype


def _obj_column_to_arrow(a, meta):
    import numpy as np
    import pyarrow as pa

    isnull = np.fromiter((x is None for x in a), bool, count=len(a))
    vals = a[~isnull]
    if vals.size == 0:
        meta[_META_KIND] = b"null"
        return pa.array([None] * len(a), type=pa.null())
    if all(isinstance(x, (str, np.str_)) for x in vals):
        meta[_META_KIND] = b"str"
        return pa.array([None if m else str(x) for x, m in zip(a, isnull)],
                        type=pa.string())
    if all(isinstance(x, (bytes, np.bytes_)) for x in vals):
        meta[_META_KIND] = b"bytes"
        return pa.array([None if m else bytes(x) for x, m in zip(a, isnull)],
                        type=pa.binary())
    # uniform numeric/temporal scalars under the nulls (the
    # ``vals.astype(object)`` shape to_numpy emits).  Uniformity is
    # CHECKED, not assumed: numpy assignment would silently cast a
    # mixed column (f64 after f32 rounds, i64 after i32 wraps) and the
    # checksum would bless the corrupted payload — raising here routes
    # the column through the journal's skip-this-spill path instead
    vdt = np.asarray(vals[0]).dtype
    for x in vals:
        if np.asarray(x).dtype != vdt:
            raise CylonError(
                Code.SerializationError,
                f"mixed object-column element dtypes ({vdt} vs "
                f"{np.asarray(x).dtype}): frame spill would not "
                f"round-trip bit-exactly")
    values = np.zeros(len(a), vdt)
    values[~isnull] = vals
    meta[_META_KIND] = b"fixed"
    meta[_META_VDT] = vdt.str.encode()
    return pa.array(values, mask=isnull)


def frame_to_ipc_bytes(frame) -> bytes:
    """Serialize one pass frame (dict of host numpy columns) to Arrow IPC
    file bytes, tagging every field with the numpy dtype needed for an
    exact restore."""
    import numpy as np
    import pyarrow as pa

    arrays, fields = [], []
    for name, arr in frame.items():
        a = np.asarray(arr)
        meta = {_META_DTYPE: a.dtype.str.encode()}
        if a.dtype.kind == "O":
            pa_arr = _obj_column_to_arrow(a, meta)
        elif a.dtype.kind == "U":
            pa_arr = pa.array(a.astype(object), type=pa.string())
        elif a.dtype.kind == "S":
            pa_arr = pa.array([bytes(x) for x in a], type=pa.binary())
        else:
            pa_arr = pa.array(a)
        arrays.append(pa_arr)
        fields.append(pa.field(str(name), pa_arr.type, metadata=meta))
    schema = pa.schema(fields)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_file(sink, schema) as writer:
        writer.write_batch(pa.record_batch(arrays, schema=schema))
    return sink.getvalue().to_pybytes()


def _bitmap_to_bool(buf, n, offset):
    import numpy as np

    if buf is None:
        return np.ones(n, bool)
    bits = np.unpackbits(np.frombuffer(buf, np.uint8), bitorder="little")
    return bits[offset:offset + n].astype(bool)


def _obj_column_from_arrow(arr, meta):
    import numpy as np

    kind = meta.get(_META_KIND, b"").decode()
    n = len(arr)
    if kind in ("str", "bytes", "null"):
        out = np.empty(n, object)
        out[:] = arr.to_pylist()
        return out
    if kind == "fixed":
        vdt = np.dtype(meta[_META_VDT].decode())
        if arr.offset == 0 and vdt.kind not in "b":
            vals = np.frombuffer(arr.buffers()[1], dtype=vdt)[:n]
            valid = _bitmap_to_bool(arr.buffers()[0], n, 0)
        else:  # sliced or bit-packed layouts take the scalar path
            valid = np.asarray([v.is_valid for v in arr], bool)
            vals = np.zeros(n, vdt)
            lst = arr.to_pylist()
            for i in np.nonzero(valid)[0]:
                vals[i] = lst[i]
        out = vals.astype(object)
        out[~valid] = None
        return out
    raise CylonError(Code.SerializationError,
                     f"unknown object-column kind {kind!r} in frame spill")


def frame_from_ipc_bytes(payload: bytes):
    """Inverse of :func:`frame_to_ipc_bytes`: Arrow IPC file bytes back to
    the exact dict of numpy columns that was spilled."""
    import numpy as np
    import pyarrow as pa

    table = pa.ipc.open_file(pa.BufferReader(payload)).read_all()
    out = {}
    for field in table.schema:
        arr = table.column(field.name).combine_chunks()
        meta = dict(field.metadata or {})
        dt = np.dtype(meta[_META_DTYPE].decode())
        if dt.kind == "O":
            out[field.name] = _obj_column_from_arrow(arr, meta)
        elif dt.kind in "US":
            out[field.name] = np.array(arr.to_pylist(), dtype=dt)
        else:
            out[field.name] = arr.to_numpy(zero_copy_only=False) \
                .astype(dt, copy=False)
    return out


def _read_parquet_arrow(path: PathLike):
    import pyarrow.parquet as pq

    return pq.read_table(str(path))


def _read_many(paths: Sequence[PathLike], reader, concurrent: bool):
    """Concurrent multi-file read (reference: table.cpp:824-844)."""
    if not paths:
        raise CylonError(Code.Invalid, "no input files")
    if not concurrent or len(paths) == 1:
        return [reader(p) for p in paths]
    with _futures.ThreadPoolExecutor(max_workers=len(paths)) as pool:
        return list(pool.map(reader, paths))


def read_csv(paths: Union[PathLike, Sequence[PathLike]],
             options: Optional[CSVReadOptions] = None, ctx=None,
             capacity: Optional[int] = None):
    """Read CSV file(s) into a (possibly distributed) Table
    (reference: io::read_csv, io/arrow_io.cpp:33-61 + Table::FromCSV)."""
    from ..context import default_context
    from ..table import _table_from_arrow_tables

    options = options or CSVReadOptions()
    ctx = ctx or default_context()
    if _native_csv_compatible(options):
        from ..table import _table_from_native_tables

        reader = lambda p: _read_csv_native(p, options)  # noqa: E731
        if isinstance(paths, (list, tuple)):
            ntables = _read_many(paths, reader,
                                 options.concurrent_file_reads)
            return _table_from_native_tables(
                ntables, ctx, capacity, per_shard=True,
                string_width=options.string_width)
        return _table_from_native_tables(
            [reader(paths)], ctx, capacity, per_shard=False,
            string_width=options.string_width)
    if isinstance(paths, (list, tuple)):
        atables = _read_many(paths, lambda p: _read_csv_arrow(p, options),
                             options.concurrent_file_reads)
        return _table_from_arrow_tables(atables, ctx, capacity,
                                        per_shard=True,
                                        string_width=options.string_width)
    atable = _read_csv_arrow(paths, options)
    return _table_from_arrow_tables([atable], ctx, capacity, per_shard=False,
                                    string_width=options.string_width)


def read_parquet(paths: Union[PathLike, Sequence[PathLike]],
                 options: Optional[ParquetOptions] = None, ctx=None,
                 capacity: Optional[int] = None):
    """reference: io::ReadParquet (io/arrow_io.cpp:65-91), Table::FromParquet
    (table.cpp:1049-1116)."""
    from ..context import default_context
    from ..table import _table_from_arrow_tables

    options = options or ParquetOptions()
    ctx = ctx or default_context()
    if isinstance(paths, (list, tuple)):
        atables = _read_many(paths, _read_parquet_arrow,
                             options.concurrent_file_reads)
        return _table_from_arrow_tables(atables, ctx, capacity,
                                        per_shard=True,
                                        string_width=options.string_width)
    atable = _read_parquet_arrow(paths)
    return _table_from_arrow_tables([atable], ctx, capacity, per_shard=False,
                                    string_width=options.string_width)


def _shard_path(path: PathLike, shard: int) -> str:
    p = str(path)
    if "{shard}" not in p:
        raise CylonError(Code.Invalid,
                         "per_shard write needs a '{shard}' placeholder in "
                         f"the path, got {p!r}")
    # token replacement, not str.format: other braces in the path (legal on
    # POSIX) must pass through literally, not raise KeyError mid-write
    return p.replace("{shard}", str(shard))


def _write_csv_columns(cols, total: int, names, path: str,
                       options: CSVWriteOptions) -> None:
    """One local column set -> one CSV file (native writer when possible)."""
    from .. import column as column_mod
    from .. import config
    from .. import dtypes, native

    # temporal columns need logical formatting (datetime strings, not raw
    # int64 micros) — only the pandas path renders those
    temporal = any(c.dtype.type in (dtypes.Type.TIMESTAMP, dtypes.Type.DATE32,
                                    dtypes.Type.DATE64, dtypes.Type.TIME32,
                                    dtypes.Type.TIME64)
                   for c in cols)
    if (native.available() and not temporal
            and not config.knob("CYLON_TPU_NO_NATIVE_IO")):
        import numpy as np

        arrays, validities, lengths_list = [], [], []
        for c in cols:
            arrays.append(np.asarray(c.data[:total]))
            validities.append(np.asarray(c.validity[:total]))
            lengths_list.append(
                None if c.lengths is None else np.asarray(c.lengths[:total]))
        native.csv_write(path, names, arrays, validities, lengths_list,
                         delimiter=options.delimiter)
        return
    import pyarrow as pa

    df = pa.table([column_mod.to_arrow(c, total) for c in cols],
                  names=names).to_pandas()
    df.to_csv(path, sep=options.delimiter, index=False)


def _out_names(table, options) -> list:
    names = list(table.column_names)
    if getattr(options, "column_names", None) is not None:
        if len(options.column_names) != len(names):
            raise CylonError(Code.Invalid, "column_names length mismatch")
        names = list(options.column_names)
    return names


def write_csv(table, path: PathLike, options: Optional[CSVWriteOptions] = None,
              per_shard: bool = False) -> None:
    """CSV write (reference: Table::WriteCSV, table.cpp:243-256 — each MPI
    rank writes ITS OWN partition).

    per_shard=False gathers the whole distributed table to this host (fine
    for small exports, a dead end at scale); per_shard=True is the
    reference-faithful scalable path: one file per process-local shard,
    ``path`` carries a ``{shard}`` placeholder, and the file list round-trips
    through the list-of-paths reader (file i -> shard i)."""
    options = options or CSVWriteOptions()
    names = _out_names(table, options)
    if per_shard:
        for sid, cols, count in table._addressable_host_shards():
            _write_csv_columns(cols, count, names, _shard_path(path, sid),
                               options)
        return
    cols, total = table._gathered_columns()
    _write_csv_columns(cols, total, names, str(path), options)


def write_parquet(table, path: PathLike,
                  options: Optional[ParquetOptions] = None,
                  per_shard: bool = False) -> None:
    """reference: io::WriteParquet (io/arrow_io.cpp:94-116,
    table.cpp:1118-1131); ``per_shard`` as in :func:`write_csv`."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from .. import column as column_mod

    options = options or ParquetOptions()
    if per_shard:
        names = list(table.column_names)
        for sid, cols, count in table._addressable_host_shards():
            pq.write_table(
                pa.table([column_mod.to_arrow(c, count) for c in cols],
                         names=names),
                _shard_path(path, sid), row_group_size=options.chunk_size)
        return
    pq.write_table(table.to_arrow(), str(path),
                   row_group_size=options.chunk_size)
