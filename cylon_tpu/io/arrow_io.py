"""CSV / Parquet ingest and egress.

TPU-native analog of the reference's IO layer (reference:
cpp/src/cylon/io/arrow_io.cpp:33-116 read_csv/ReadParquet/WriteParquet and
the Table factory paths cpp/src/cylon/table.cpp:803-855 FromCSV /
:1049-1132 FromParquet/WriteParquet):

- parsing is pyarrow (the reference wraps Arrow's CSV/Parquet readers the
  same way), producing host Arrow tables;
- device placement pads columns to static capacities and lays shard i of a
  distributed table on mesh position i (cylon_tpu.table internals);
- multi-file reads fan out over a thread pool when
  ``options.ConcurrentFileReads`` (reference: table.cpp:824-844 spawns a
  std::thread + promise/future per file).

Distribution semantics:
- one path + distributed ctx  -> rows split contiguously across shards
- list of paths (len == world) -> file i becomes shard i, read concurrently
"""
from __future__ import annotations

import concurrent.futures as _futures
from typing import List, Optional, Sequence, Union

from ..status import Code, CylonError
from .csv_config import CSVReadOptions, CSVWriteOptions, ParquetOptions

PathLike = Union[str, "os.PathLike[str]"]


def _read_csv_arrow(path: PathLike, options: CSVReadOptions):
    import pyarrow.csv as pc

    read, parse, convert = options.to_pyarrow()
    return pc.read_csv(str(path), read_options=read, parse_options=parse,
                       convert_options=convert)


def _read_parquet_arrow(path: PathLike):
    import pyarrow.parquet as pq

    return pq.read_table(str(path))


def _read_many(paths: Sequence[PathLike], reader, concurrent: bool):
    """Concurrent multi-file read (reference: table.cpp:824-844)."""
    if not paths:
        raise CylonError(Code.Invalid, "no input files")
    if not concurrent or len(paths) == 1:
        return [reader(p) for p in paths]
    with _futures.ThreadPoolExecutor(max_workers=len(paths)) as pool:
        return list(pool.map(reader, paths))


def read_csv(paths: Union[PathLike, Sequence[PathLike]],
             options: Optional[CSVReadOptions] = None, ctx=None,
             capacity: Optional[int] = None):
    """Read CSV file(s) into a (possibly distributed) Table
    (reference: io::read_csv, io/arrow_io.cpp:33-61 + Table::FromCSV)."""
    from ..context import default_context
    from ..table import _table_from_arrow_tables

    options = options or CSVReadOptions()
    ctx = ctx or default_context()
    if isinstance(paths, (list, tuple)):
        atables = _read_many(paths, lambda p: _read_csv_arrow(p, options),
                             options.concurrent_file_reads)
        return _table_from_arrow_tables(atables, ctx, capacity,
                                        per_shard=True,
                                        string_width=options.string_width)
    atable = _read_csv_arrow(paths, options)
    return _table_from_arrow_tables([atable], ctx, capacity, per_shard=False,
                                    string_width=options.string_width)


def read_parquet(paths: Union[PathLike, Sequence[PathLike]],
                 options: Optional[ParquetOptions] = None, ctx=None,
                 capacity: Optional[int] = None):
    """reference: io::ReadParquet (io/arrow_io.cpp:65-91), Table::FromParquet
    (table.cpp:1049-1116)."""
    from ..context import default_context
    from ..table import _table_from_arrow_tables

    options = options or ParquetOptions()
    ctx = ctx or default_context()
    if isinstance(paths, (list, tuple)):
        atables = _read_many(paths, _read_parquet_arrow,
                             options.concurrent_file_reads)
        return _table_from_arrow_tables(atables, ctx, capacity,
                                        per_shard=True,
                                        string_width=options.string_width)
    atable = _read_parquet_arrow(paths)
    return _table_from_arrow_tables([atable], ctx, capacity, per_shard=False,
                                    string_width=options.string_width)


def write_csv(table, path: PathLike,
              options: Optional[CSVWriteOptions] = None) -> None:
    """Gathered CSV write (reference: Table::WriteCSV, table.cpp:243-256)."""
    options = options or CSVWriteOptions()
    df = table.to_pandas()
    if options.column_names is not None:
        if len(options.column_names) != len(df.columns):
            raise CylonError(Code.Invalid, "column_names length mismatch")
        df.columns = options.column_names
    df.to_csv(str(path), sep=options.delimiter, index=False)


def write_parquet(table, path: PathLike,
                  options: Optional[ParquetOptions] = None) -> None:
    """reference: io::WriteParquet (io/arrow_io.cpp:94-116,
    table.cpp:1118-1131)."""
    import pyarrow.parquet as pq

    options = options or ParquetOptions()
    pq.write_table(table.to_arrow(), str(path),
                   row_group_size=options.chunk_size)
