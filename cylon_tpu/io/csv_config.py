"""CSV read/write option builders.

TPU-native analog of the reference's CSV config surface
(reference: cpp/src/cylon/io/csv_read_config.hpp:27-146 — a fluent builder
multiple-inheriting Arrow Read/Parse/ConvertOptions via CSVConfigHolder,
io/csv_read_config_holder.hpp:28-36 — and io/csv_write_config.hpp:24-39).
Here the holder maps onto ``pyarrow.csv`` option objects at read time.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence


class CSVReadOptions:
    """Fluent CSV read options (reference: io/csv_read_config.hpp:35-146).

    Every method returns ``self`` so options chain like the reference's
    builder: ``CSVReadOptions().UseThreads(True).WithDelimiter('|')``.
    """

    def __init__(self):
        self.concurrent_file_reads: bool = True
        self.use_threads: bool = True
        self.delimiter: str = ","
        self.ignore_emptylines: bool = True
        self.autogenerate_column_names: bool = False
        self.column_names: Optional[List[str]] = None
        self.block_size: int = 1 << 20
        self.use_quoting: bool = True   # Arrow's ParseOptions default
        self.quote_char: str = '"'
        self.double_quote: bool = True
        self.use_escaping: bool = False
        self.escape_char: str = "\\"
        self.newlines_in_values: bool = False
        self.skip_rows: int = 0
        self.column_types: Dict[str, object] = {}
        self.null_values: Optional[List[str]] = None
        self.true_values: Optional[List[str]] = None
        self.false_values: Optional[List[str]] = None
        self.strings_can_be_null: bool = False
        self.include_columns: Optional[List[str]] = None
        self.include_missing_columns: bool = False
        self.string_width: Optional[int] = None  # TPU extension: pad width

    # -- builder methods (names mirror csv_read_config.hpp) -----------------
    def ConcurrentFileReads(self, v: bool) -> "CSVReadOptions":
        self.concurrent_file_reads = v
        return self

    def IsConcurrentFileReads(self) -> bool:
        return self.concurrent_file_reads

    def UseThreads(self, v: bool) -> "CSVReadOptions":
        self.use_threads = v
        return self

    def WithDelimiter(self, d: str) -> "CSVReadOptions":
        self.delimiter = d
        return self

    def IgnoreEmptyLines(self) -> "CSVReadOptions":
        self.ignore_emptylines = True
        return self

    def AutoGenerateColumnNames(self) -> "CSVReadOptions":
        self.autogenerate_column_names = True
        return self

    def ColumnNames(self, names: Sequence[str]) -> "CSVReadOptions":
        self.column_names = list(names)
        return self

    def BlockSize(self, n: int) -> "CSVReadOptions":
        self.block_size = int(n)
        return self

    def UseQuoting(self, v: bool = True) -> "CSVReadOptions":
        self.use_quoting = v
        return self

    def WithQuoteChar(self, c: str) -> "CSVReadOptions":
        self.quote_char = c
        self.use_quoting = True
        return self

    def DoubleQuote(self) -> "CSVReadOptions":
        self.double_quote = True
        return self

    def UseEscaping(self) -> "CSVReadOptions":
        self.use_escaping = True
        return self

    def EscapingCharacter(self, c: str) -> "CSVReadOptions":
        self.escape_char = c
        self.use_escaping = True
        return self

    def HasNewLinesInValues(self) -> "CSVReadOptions":
        self.newlines_in_values = True
        return self

    def SkipRows(self, n: int) -> "CSVReadOptions":
        self.skip_rows = int(n)
        return self

    def WithColumnTypes(self, types: Dict[str, object]) -> "CSVReadOptions":
        self.column_types = dict(types)
        return self

    def NullValues(self, vals: Sequence[str]) -> "CSVReadOptions":
        self.null_values = list(vals)
        return self

    def TrueValues(self, vals: Sequence[str]) -> "CSVReadOptions":
        self.true_values = list(vals)
        return self

    def FalseValues(self, vals: Sequence[str]) -> "CSVReadOptions":
        self.false_values = list(vals)
        return self

    def StringsCanBeNull(self) -> "CSVReadOptions":
        self.strings_can_be_null = True
        return self

    def IncludeColumns(self, cols: Sequence[str]) -> "CSVReadOptions":
        self.include_columns = list(cols)
        return self

    def IncludeMissingColumns(self) -> "CSVReadOptions":
        self.include_missing_columns = True
        return self

    def StringWidth(self, width: int) -> "CSVReadOptions":
        """TPU extension: fixed byte width used to pad string columns on
        device (see cylon_tpu.column docstring)."""
        self.string_width = int(width)
        return self

    # -- pyarrow holders (the CSVConfigHolder role) -------------------------
    def to_pyarrow(self):
        import pyarrow.csv as pc

        read = pc.ReadOptions(
            use_threads=self.use_threads,
            block_size=self.block_size,
            skip_rows=self.skip_rows,
            column_names=self.column_names,
            autogenerate_column_names=self.autogenerate_column_names,
        )
        parse = pc.ParseOptions(
            delimiter=self.delimiter,
            quote_char=self.quote_char if self.use_quoting else False,
            double_quote=self.double_quote,
            escape_char=self.escape_char if self.use_escaping else False,
            newlines_in_values=self.newlines_in_values,
            ignore_empty_lines=self.ignore_emptylines,
        )
        ctypes = None
        if self.column_types:
            import pyarrow as pa

            from .. import dtypes as dt

            ctypes = {}
            for name, t in self.column_types.items():
                if isinstance(t, dt.DataType):
                    ctypes[name] = dt.to_arrow_type(t)
                elif isinstance(t, pa.DataType):
                    ctypes[name] = t
                else:
                    ctypes[name] = pa.from_numpy_dtype(t)
        convert = pc.ConvertOptions(
            column_types=ctypes,
            null_values=self.null_values,
            true_values=self.true_values,
            false_values=self.false_values,
            strings_can_be_null=self.strings_can_be_null,
            include_columns=self.include_columns,
            include_missing_columns=self.include_missing_columns,
        )
        return read, parse, convert


class CSVWriteOptions:
    """reference: io/csv_write_config.hpp:24-39."""

    def __init__(self):
        self.delimiter: str = ","
        self.column_names: Optional[List[str]] = None

    def WithDelimiter(self, d: str) -> "CSVWriteOptions":
        self.delimiter = d
        return self

    def ColumnNames(self, names: Sequence[str]) -> "CSVWriteOptions":
        self.column_names = list(names)
        return self

    def GetDelimiter(self) -> str:
        return self.delimiter


class ParquetOptions:
    """reference: io/parquet_config.{hpp,cpp} (BUILD_CYLON_PARQUET path)."""

    def __init__(self):
        self.concurrent_file_reads: bool = True
        self.chunk_size: int = 1 << 20
        self.string_width: Optional[int] = None

    def ConcurrentFileReads(self, v: bool) -> "ParquetOptions":
        self.concurrent_file_reads = v
        return self

    def IsConcurrentFileReads(self) -> bool:
        return self.concurrent_file_reads

    def ChunkSize(self, n: int) -> "ParquetOptions":
        self.chunk_size = int(n)
        return self

    def StringWidth(self, width: int) -> "ParquetOptions":
        self.string_width = int(width)
        return self
