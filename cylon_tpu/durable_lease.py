"""Shared advisory lease over a journal root — THE one implementation
(PR 20) behind the GC sweep (`durable.gc_journal`), the integrity
scrubber (`durable_sync.scrub_once`) and the offline checker
(`tools/journal_fsck.py`).

PR 16 introduced the lease inside `gc_journal`; PR 20 adds two more
destructive walkers (scrub quarantine, fsck repair) that must exclude
each other AND the GC, so the acquire/release pair moves here rather
than growing three copies whose TTL/stale-break semantics could drift.

Deliberately **stdlib-only** (no numpy, no obs, no package siblings):
`cylon_tpu/__init__.py` imports jax, so `tools/journal_fsck.py` — which
must run on a box with nothing but CPython — loads this module BY FILE
PATH (the `tools/trace_report.py` idiom) instead of importing the
package.  Keep it that way; callers that want counters pass ``on_busy``.

Semantics (unchanged from PR 16): O_CREAT|O_EXCL on ``<root>/GC_LOCK``
with pid + wall-clock inside for operators; a holder younger than the
TTL excludes us; a stale lease (crashed holder) is broken by an atomic
rewrite.  Two breakers racing the rewrite is acceptable for an ADVISORY
lease — the per-victim manifest-mtime re-read under the lease is what
protects correctness, the lease only serializes the common case.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Callable, Iterator, Optional

log = logging.getLogger("cylon_tpu")

#: advisory cross-process lease file name (journal root)
GC_LOCK = "GC_LOCK"

#: a holder younger than this excludes every other walker
LEASE_TTL_S = 30.0


def acquire_lease(root: str, ttl_s: float = LEASE_TTL_S,
                  on_busy: Optional[Callable[[], None]] = None,
                  ) -> Optional[str]:
    """Acquire the advisory walker lease on ``root``; returns the lease
    path, or None when another walker holds a lease younger than
    ``ttl_s`` (``on_busy`` is invoked exactly then — the hook where
    durable.py counts ``durable.gc_lease_busy``)."""
    path = os.path.join(root, GC_LOCK)
    payload = json.dumps({"pid": os.getpid(), "ts": time.time()}) + "\n"
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        try:
            age = time.time() - os.path.getmtime(path)
        except OSError:
            return None  # holder released between exists and stat
        if age < ttl_s:
            if on_busy is not None:
                on_busy()
            return None
        tmp = path + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(payload)
            os.replace(tmp, path)
        except OSError:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            return None
        log.warning("durable: broke stale GC lease at %s (age %.1fs)",
                    path, age)
        return path
    except OSError:
        return None
    try:
        os.write(fd, payload.encode())
    finally:
        os.close(fd)
    return path


def release_lease(path: str) -> None:
    with contextlib.suppress(OSError):
        os.remove(path)


@contextlib.contextmanager
def lease(root: str, ttl_s: float = LEASE_TTL_S,
          on_busy: Optional[Callable[[], None]] = None) -> Iterator[Optional[str]]:
    """Context manager form: yields the lease path (held for the body)
    or None when busy — the body must check and bail without touching
    the root destructively."""
    path = acquire_lease(root, ttl_s=ttl_s, on_busy=on_busy)
    try:
        yield path
    finally:
        if path is not None:
            release_lease(path)
