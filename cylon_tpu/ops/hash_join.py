"""Hash-join kernel: open-addressing build + probe.

A real HASH algorithm family distinct from the sort-merge kernel
(reference: ``do_hash_join`` cpp/src/cylon/join/join.cpp:448-513 and
``HashJoinKernel`` arrow/arrow_hash_kernels.hpp:33-215 — multimap build on
one side, probe from the other), shaped for XLA instead of pointers:

- the hash table is an ``int32[slots]`` array of build-row ids (open
  addressing, TRIANGULAR-NUMBER quadratic probing — offset p(p+1)/2,
  which visits every slot of a power-of-2 table exactly once per cycle
  while avoiding linear probing's primary clustering; fewer probe
  rounds is what matters here, because each round is a full-array pass
  and the while_loop runs until the LAST row settles) built by a
  ``lax.while_loop`` whose body is a vectorized claim round: every
  unplaced build row tries to claim its probe slot with one
  ``scatter-min`` (lowest row id wins a contended empty slot —
  deterministic), duplicates chain to the winning owner by key
  equality, losers advance their probe offset.  Expected rounds are
  O(1) at 0.5 load factor; total-duplicate inputs finish in 2 rounds
  (one claim, one chain).
- probe is the same loop shape per probe row: gather the slot, stop on
  empty (no match) or key-equal owner (match), else step.
- multiplicity (a probe row matching k build rows) reuses the sort path's
  histogram expansion: build rows are counting-sorted by their owner id,
  so a probe row's matches are one contiguous range — only the (smaller)
  build side is ever sorted, the probe side never is.  This is the classic
  hash-join asymmetry; the sort-merge kernel lexsorts both sides.

Key equality runs over the same encoded operands the sort kernel orders
by (ops/keys.column_operands), so null semantics (null == null) and
string packing agree bit-for-bit across both algorithms.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from ..config import JoinType
from ..utils import pow2ceil
from . import common, hashing, keys

# empty-slot sentinel; also the gid sort key that exiles padding rows to
# the back (both want "larger than any real row id", so one constant)
_EMPTY = jnp.iinfo(jnp.int32).max


def _step_offset(p: jax.Array) -> jax.Array:
    """Triangular-number probe offset p(p+1)/2 as uint32: covers every
    slot of a power-of-2 table exactly once per cycle (classic quadratic
    probing) without linear probing's primary clustering.  SHARED by
    build and probe — they must walk identical slot sequences or probe
    rows would stop on an empty slot before reaching their chain head."""
    pu = p.astype(jnp.uint32)
    return (pu * (pu + 1)) >> jnp.uint32(1)


def _row_eq(ops: Sequence[jax.Array], i_idx: jax.Array,
            j_idx: jax.Array) -> jax.Array:
    """Vectorized row equality over encoded key operands."""
    eq = jnp.ones(i_idx.shape, bool)
    for o in ops:
        a = jnp.take(o, i_idx, mode="clip")
        b = jnp.take(o, j_idx, mode="clip")
        eq &= a == b
    return eq


def _combined_key_ops(cols_l, cols_r, left_on, right_on):
    """Concatenated (cap_l + cap_r) operand arrays comparable across
    tables, plus the composite row hash of the concatenation.  Operands
    are bit-packed (keys.pack_operands) so each equality check in the
    build/probe loops costs one gather+compare per packed word instead of
    one per field."""
    combined_cols = []
    ops: List[jax.Array] = []
    for ia, ib in zip(left_on, right_on):
        c = common.concat_columns(cols_l[ia], cols_r[ib])
        combined_cols.append(c)
        ops.extend(keys.column_operands(c))
    h = hashing.hash_columns(combined_cols)
    return keys.pack_operands(ops), h


def _build(h_r: jax.Array, live_r: jax.Array, ops, cap_l: int, cap_r: int,
           slots: int):
    """Insert live build rows; returns (table, owner[cap_r]) where owner is
    each build row's representative (itself, or the first-inserted row with
    an equal key — the multimap chain head)."""
    mask = jnp.uint32(slots - 1)
    rid = jnp.arange(cap_r, dtype=jnp.int32)
    grid = cap_l + rid  # global operand index of build rows

    def cond(st):
        _, _, done, _, it = st
        return (~jnp.all(done)) & (it < slots + 2)

    def body(st):
        tab, p, done, owner, it = st
        cand = ((h_r + _step_offset(p)) & mask).astype(jnp.int32)
        occ = jnp.take(tab, cand)
        want = ~done
        empty = occ == _EMPTY
        # claim round: contended empty slots go to the lowest row id
        idx = jnp.where(want & empty, cand, slots)
        tab = tab.at[idx].min(rid, mode="drop")
        won = want & empty & (jnp.take(tab, cand) == rid)
        # occupied slots: equal key -> chain to owner, else advance
        dup = want & ~empty & _row_eq(ops, grid,
                                      cap_l + jnp.clip(occ, 0, cap_r - 1))
        owner = jnp.where(won, rid, jnp.where(dup, occ, owner))
        done = done | won | dup
        p = jnp.where(want & ~empty & ~dup, p + 1, p)
        return tab, p, done, owner, it + 1

    tab0 = jnp.full((slots,), _EMPTY, jnp.int32)
    st = (tab0, jnp.zeros((cap_r,), jnp.int32), ~live_r,
          jnp.full((cap_r,), _EMPTY, jnp.int32), jnp.zeros((), jnp.int32))
    tab, _, _, owner, _ = jax.lax.while_loop(cond, body, st)
    return tab, owner


def _probe(h_l: jax.Array, live_l: jax.Array, tab: jax.Array, ops,
           cap_l: int, cap_r: int, slots: int):
    """Walk each probe row's chain; returns rep[cap_l] — the matching build
    chain head's row id, or -1 for no match."""
    mask = jnp.uint32(slots - 1)
    lid = jnp.arange(cap_l, dtype=jnp.int32)

    def cond(st):
        _, done, _, it = st
        return (~jnp.all(done)) & (it < slots + 2)

    def body(st):
        p, done, rep, it = st
        cand = ((h_l + _step_offset(p)) & mask).astype(jnp.int32)
        occ = jnp.take(tab, cand)
        want = ~done
        empty = occ == _EMPTY
        hit = want & ~empty & _row_eq(ops, lid,
                                      cap_l + jnp.clip(occ, 0, cap_r - 1))
        rep = jnp.where(hit, occ, rep)
        done = done | (want & empty) | hit
        p = jnp.where(want & ~empty & ~hit, p + 1, p)
        return p, done, rep, it + 1

    st = (jnp.zeros((cap_l,), jnp.int32), ~live_l,
          jnp.full((cap_l,), -1, jnp.int32), jnp.zeros((), jnp.int32))
    _, _, rep, _ = jax.lax.while_loop(cond, body, st)
    return rep


def match_ranges_hash(cols_l: Tuple[Column, ...], count_l,
                      cols_r: Tuple[Column, ...], count_r,
                      left_on: Tuple[int, ...], right_on: Tuple[int, ...],
                      join_type: JoinType):
    """Hash-algorithm drop-in for join._match_ranges: same
    (lo, matches, perm_r, live_l, unmatched_right) contract, built from a
    hash table instead of a combined lexsort."""
    cap_l = cols_l[0].data.shape[0]
    cap_r = cols_r[0].data.shape[0]
    slots = pow2ceil(2 * cap_r)

    ops, h = _combined_key_ops(cols_l, cols_r, left_on, right_on)
    h_l, h_r = h[:cap_l], h[cap_l:]
    live_l = jnp.arange(cap_l, dtype=jnp.int32) < count_l
    live_r = jnp.arange(cap_r, dtype=jnp.int32) < count_r

    tab, owner = _build(h_r, live_r, ops, cap_l, cap_r, slots)
    rep = _probe(h_l, live_l, tab, ops, cap_l, cap_r, slots)

    # histogram of build rows per chain head -> contiguous match ranges in
    # the owner-sorted order (the counting sort of the build side ONLY)
    n_gid = cap_r + 1
    gid_r = jnp.where(live_r, jnp.clip(owner, 0, cap_r - 1), cap_r)
    counts_r = jnp.zeros((n_gid,), jnp.int32).at[gid_r].add(
        live_r.astype(jnp.int32))
    csum_r = jnp.cumsum(counts_r, dtype=jnp.int32)
    rstart = jnp.concatenate([jnp.zeros((1,), jnp.int32), csum_r[:-1]])

    gid_l = jnp.where(live_l & (rep >= 0), rep, cap_r)
    lo = jnp.take(rstart, gid_l)
    matches = jnp.where(live_l & (rep >= 0), jnp.take(counts_r, gid_l), 0)

    rkey = jnp.where(live_r, gid_r, _EMPTY)
    iota_r = jnp.arange(cap_r, dtype=jnp.int32)
    _, perm_r = jax.lax.sort((rkey, iota_r), num_keys=1, is_stable=True)

    if join_type in (JoinType.RIGHT, JoinType.FULL_OUTER):
        counts_l = jnp.zeros((n_gid,), jnp.int32).at[gid_l].add(
            live_l.astype(jnp.int32))
        unmatched_r = live_r & (jnp.take(counts_l, gid_r) == 0)
    else:
        unmatched_r = jnp.zeros((cap_r,), bool)
    return lo, matches, perm_r, live_l, unmatched_r
