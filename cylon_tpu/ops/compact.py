"""Mask -> front-packed compaction.

The reference materializes filtered results via ``arrow::compute::Filter``
over boolean masks (e.g. groupby index columns, hash_groupby.cpp:135-192;
Select, table.cpp:491-520).  The static-shape XLA equivalent: a stable sort
on the inverted mask yields a permutation that packs kept rows to the front
in original order; the new dynamic row count is the mask popcount.  One fused
sort+gather instead of a dynamically-sized filter.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def compact_indices(mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(perm, new_count): perm is a full-capacity permutation placing rows
    where ``mask`` is True at the front, preserving order; new_count is the
    number of kept rows (int32 scalar)."""
    cap = mask.shape[0]
    key = (~mask).astype(jnp.uint8)
    iota = jnp.arange(cap, dtype=jnp.int32)
    _, perm = jax.lax.sort((key, iota), num_keys=1, is_stable=True)
    new_count = jnp.sum(mask, dtype=jnp.int32)
    return perm, new_count


def live_mask(capacity: int, row_count) -> jax.Array:
    """bool[capacity]: True for rows below the dynamic row count."""
    return jnp.arange(capacity, dtype=jnp.int32) < row_count
