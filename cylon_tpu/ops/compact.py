"""Mask -> front-packed compaction.

The reference materializes filtered results via ``arrow::compute::Filter``
over boolean masks (e.g. groupby index columns, hash_groupby.cpp:135-192;
Select, table.cpp:491-520).  The static-shape XLA equivalent: a stable sort
on the inverted mask yields a permutation that packs kept rows to the front
in original order; the new dynamic row count is the mask popcount.  One fused
sort+gather instead of a dynamically-sized filter.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def compact_indices(mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(idx, new_count): the first ``new_count`` entries of ``idx`` are the
    row indices where ``mask`` is True, in order (a cumsum-scatter — one
    scan, no sort); entries past new_count are in-bounds filler that
    callers must mask.  new_count is an int32 scalar."""
    cap = mask.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    pos = jnp.cumsum(mask, dtype=jnp.int32) - 1
    idx = jnp.zeros((cap,), jnp.int32).at[
        jnp.where(mask, pos, cap)].set(iota, mode="drop")
    new_count = jnp.sum(mask, dtype=jnp.int32)
    return idx, new_count


def partition_indices(mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(perm, true_count): a full stable partition permutation — mask-True
    row indices first (in order), then every mask-False index (in order).
    Unlike ``compact_indices`` the tail is the real False rows, so ``perm``
    is a permutation of [0, n) usable wherever each row must appear exactly
    once (e.g. reordering a table without dropping rows)."""
    cap = mask.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    nt = jnp.sum(mask, dtype=jnp.int32)
    ct = jnp.cumsum(mask, dtype=jnp.int32)
    cf = iota + 1 - ct  # cumsum of ~mask without a second scan
    dest = jnp.where(mask, ct - 1, nt + cf - 1)
    perm = jnp.zeros((cap,), jnp.int32).at[dest].set(iota)
    return perm, nt


def live_mask(capacity: int, row_count) -> jax.Array:
    """bool[capacity]: True for rows below the dynamic row count."""
    return jnp.arange(capacity, dtype=jnp.int32) < row_count
