"""Mask -> front-packed compaction.

The reference materializes filtered results via ``arrow::compute::Filter``
over boolean masks (e.g. groupby index columns, hash_groupby.cpp:135-192;
Select, table.cpp:491-520).  The static-shape XLA equivalent: a stable sort
on the inverted mask yields a permutation that packs kept rows to the front
in original order; the new dynamic row count is the mask popcount.  One fused
sort+gather instead of a dynamically-sized filter.

Two interchangeable realizations, selected by :func:`permute_mode`:

- ``scatter``: cumsum destinations + one permuting scatter (one linear
  pass — optimal where scatter is cheap, e.g. XLA:CPU).
- ``sort``: pack (mask bit above row index) into ONE u32 word and
  ``lax.sort`` it — on TPU a full 64M-word sort measures ~4x FASTER than
  a same-size scatter (round-4 hardware profile: 213 ms sort vs ~900 ms
  per scatter pass at 2^26 rows/side), so sort-realized permutations are
  the TPU default.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .. import config


def permute_mode() -> str:
    """How permutations/compactions are materialized: "scatter" | "sort".

    CYLON_TPU_PERMUTE overrides; "auto" (default) picks "sort" on
    TPU-family backends (where XLA's sort is bandwidth-bound but its
    scatter serializes) and "scatter" elsewhere.  Read at trace time."""
    mode = config.knob("CYLON_TPU_PERMUTE")
    if mode in ("scatter", "sort"):
        return mode
    return "sort" if jax.default_backend() in ("tpu", "axon") else "scatter"


def index_bits(cap: int) -> int:
    """Bits needed to carry a row index in [0, cap) inside a packed sort
    word (shared with keys.lexsort_indices — the packing-width formula
    must stay single-sourced)."""
    return max(1, (cap - 1).bit_length()) if cap > 1 else 1


def _mask_sort_perm(mask: jax.Array) -> jax.Array:
    """Stable partition permutation via ONE single-word unstable sort:
    ``(~mask) << idx_bits | row`` — all words unique, ascending row bits
    make the unstable sort stable per mask value.  Arrays longer than
    2^31 rows can arise internally (e.g. the join expansion's merge of
    csum + out_capacity slots), where flag+index no longer fit u32; those
    fall back to a two-operand stable sort."""
    cap = mask.shape[0]
    bits = index_bits(cap)
    if bits + 1 > 32:
        # >=2^31 rows: int32 positions would wrap negative — exactly the
        # case this branch exists for — so carry the permutation in int64
        # (x64 is enabled package-wide; round-4 advice finding 1)
        iota = jnp.arange(cap, dtype=jnp.int64)
        _, perm = jax.lax.sort(
            (jnp.where(mask, jnp.uint32(0), jnp.uint32(1)), iota),
            num_keys=1, is_stable=True)
        return perm
    iota = jnp.arange(cap, dtype=jnp.uint32)
    word = (jnp.where(mask, jnp.uint32(0), jnp.uint32(1))
            << jnp.uint32(bits)) | iota
    s = jax.lax.sort(word, is_stable=False)
    return (s & jnp.uint32((1 << bits) - 1)).astype(jnp.int32)


def _idx_dtype(cap: int):
    """Row-index dtype wide enough for ``cap`` rows: positions at or past
    2^31 wrap negative in int32, so the >31-bit regime (reachable
    internally, e.g. count_leq_dense's merged csum + out_capacity array)
    carries indices in int64 (round-4 advice finding 1)."""
    return jnp.int64 if cap > (1 << 31) - 1 else jnp.int32


def compact_indices(mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(idx, new_count): the first ``new_count`` entries of ``idx`` are the
    row indices where ``mask`` is True, in order; entries past new_count
    are in-bounds filler that callers must mask.  new_count is a scalar
    (int32 below 2^31 rows, int64 past it)."""
    cap = mask.shape[0]
    it = _idx_dtype(cap)
    new_count = jnp.sum(mask, dtype=it)
    if permute_mode() == "sort":
        return _mask_sort_perm(mask), new_count
    iota = jnp.arange(cap, dtype=it)
    pos = jnp.cumsum(mask, dtype=it) - 1
    idx = jnp.zeros((cap,), it).at[
        jnp.where(mask, pos, cap)].set(iota, mode="drop")
    return idx, new_count


def partition_indices(mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(perm, true_count): a full stable partition permutation — mask-True
    row indices first (in order), then every mask-False index (in order).
    Unlike ``compact_indices`` the tail is the real False rows, so ``perm``
    is a permutation of [0, n) usable wherever each row must appear exactly
    once (e.g. reordering a table without dropping rows)."""
    cap = mask.shape[0]
    it = _idx_dtype(cap)
    nt = jnp.sum(mask, dtype=it)
    if permute_mode() == "sort":
        return _mask_sort_perm(mask), nt
    iota = jnp.arange(cap, dtype=it)
    ct = jnp.cumsum(mask, dtype=it)
    cf = iota + 1 - ct  # cumsum of ~mask without a second scan
    dest = jnp.where(mask, ct - 1, nt + cf - 1)
    perm = jnp.zeros((cap,), it).at[dest].set(iota)
    return perm, nt


def count_leq_dense(sorted_vals: jax.Array, num_queries: int) -> jax.Array:
    """``out[k] = #{i : sorted_vals[i] <= k}`` for k in [0, num_queries) —
    ``searchsorted(sorted_vals, arange(num_queries), side='right')`` for a
    monotone int array — via one merged u32 sort plus one packed
    compaction (both bandwidth-bound on TPU, unlike a scatter/histogram).

    Packing: word = value << 1 | tag (tag 1 = query).  A value v sorts
    before query k exactly when v <= k, and queries keep their ascending
    order, so query k's merged position p satisfies p = #{v <= k} + k.
    Values are clipped to num_queries (entries beyond every query count
    toward no query, preserving searchsorted semantics for the dense
    query range)."""
    vals = jnp.clip(sorted_vals, 0, num_queries).astype(jnp.uint32) << 1
    queries = (jnp.arange(num_queries, dtype=jnp.uint32) << 1) | 1
    merged = jax.lax.sort(jnp.concatenate([vals, queries]), is_stable=False)
    p, _ = compact_indices((merged & 1) == 1)
    return p[:num_queries] - jnp.arange(num_queries, dtype=jnp.int32)


def invperm_mode() -> str:
    """Sub-realization of sort-mode ``inverse_permute``: ``"sort"``
    (default — one multi-operand sort carries every field) or
    ``"gather"`` (one 2-operand sort builds the inverse index once, then
    one bandwidth-linear ``take`` per field).  The trade: a k-field
    multi-operand sort moves (k+1) operands through every sort pass,
    while the gather realization pays the sort passes once on 8 B/row
    and k linear gathers — the crossover is a hardware question
    (microbench + profiler A/B arms; CYLON_TPU_INVPERM overrides).
    Only meaningful when permute_mode() == "sort"."""
    return config.knob("CYLON_TPU_INVPERM")


def inverse_permute(perm: jax.Array, *fields: jax.Array) -> Tuple[jax.Array, ...]:
    """``out[perm[i]] = fields[..][i]`` for each field — the inverse-
    permutation apply (``perm`` must be a permutation of [0, n)).

    scatter mode: one scatter per field.  sort mode: ONE multi-operand
    ``lax.sort`` keyed on ``perm`` (unique keys, unstable OK) carries all
    fields to their destinations in a single fused pass — or, under
    ``invperm_mode() == "gather"``, one 2-operand sort computes
    ``inv = argsort(perm)`` and each field is one linear gather
    ``take(f, inv)`` (equivalent because out[j] = f[inv[j]])."""
    if permute_mode() == "sort":
        if invperm_mode() == "gather":
            cap = perm.shape[0]
            # index dtype must widen with cap like _mask_sort_perm's
            # fallback: an int32 iota (and a u32 key cast) silently wraps
            # for cap >= 2^31, scrambling the inverse permutation
            it = _idx_dtype(cap)
            iota = jnp.arange(cap, dtype=it)  # payload: no cast back
            key = (perm.astype(jnp.uint32) if it == jnp.int32
                   else perm.astype(jnp.int64))
            _, inv = jax.lax.sort((key, iota), num_keys=1, is_stable=False)
            # inv is an argsort of a permutation — provably in bounds and
            # unique; the default fill mode would add a clamp+select per
            # element inside the very A/B this realization exists to win
            return tuple(f.at[inv].get(mode="promise_in_bounds",
                                       unique_indices=True)
                         for f in fields)
        sorted_ops = jax.lax.sort((perm.astype(jnp.uint32),) + tuple(fields),
                                  num_keys=1, is_stable=False)
        return tuple(sorted_ops[1:])
    return tuple(jnp.zeros_like(f).at[perm].set(
        f, unique_indices=True, mode="promise_in_bounds") for f in fields)


def live_mask(capacity: int, row_count) -> jax.Array:
    """bool[capacity]: True for rows below the dynamic row count."""
    return jnp.arange(capacity, dtype=jnp.int32) < row_count
