"""Scalar column aggregates.

TPU-native replacement for the reference's compute layer
(cpp/src/cylon/compute/aggregates.cpp:30-156 — local arrow::compute reduction
then an MPI_Allreduce over the scalar, compute/aggregate_utils.hpp:124-144).
The local reduction is a masked jnp reduce; the distributed combine happens
in cylon_tpu.parallel via psum/pmin/pmax (see parallel/collectives.py) —
the direct analog of mpi::AllReduce (net/mpi/mpi_operations.cpp:18-78).
"""
from __future__ import annotations

import enum
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from .. import precision
from ..column import Column
from . import compact


class ReduceOp(enum.IntEnum):
    """reference: net/comm_operations.hpp:26-30."""

    SUM = 0
    MIN = 1
    MAX = 2
    PROD = 3
    COUNT = 4


@partial(jax.jit, static_argnames=("op",))
def scalar_agg(col: Column, count, op: ReduceOp):
    """(value, valid_count) for one column's live, non-null rows."""
    cap = col.data.shape[0]
    if col.is_string and op not in (ReduceOp.COUNT,):
        raise TypeError("scalar aggregation unsupported on string columns")
    mask = col.validity & compact.live_mask(cap, count)
    n = jnp.sum(mask, dtype=precision.count_acc())
    n = n if precision.narrow() else n.astype(jnp.int64)
    if op == ReduceOp.COUNT:
        return n, n
    data = col.data
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int32)
    if op in (ReduceOp.SUM, ReduceOp.PROD):
        acc = data.astype(precision.float_acc()
                          if jnp.issubdtype(data.dtype, jnp.floating)
                          else precision.int_acc())
        if op == ReduceOp.SUM:
            return jnp.sum(jnp.where(mask, acc, 0)), n
        return jnp.prod(jnp.where(mask, acc, 1)), n
    if jnp.issubdtype(data.dtype, jnp.floating):
        lo, hi = -jnp.inf, jnp.inf
    else:
        info = jnp.iinfo(data.dtype)
        lo, hi = info.min, info.max
    if op == ReduceOp.MIN:
        return jnp.min(jnp.where(mask, data, jnp.asarray(hi, data.dtype))), n
    if op == ReduceOp.MAX:
        return jnp.max(jnp.where(mask, data, jnp.asarray(lo, data.dtype))), n
    raise ValueError(op)
