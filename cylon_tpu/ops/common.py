"""Shared helpers for multi-table kernels."""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from . import keys


def widen_strings(a: Column, b: Column) -> Tuple[Column, Column]:
    """Pad two string columns' byte matrices to a common width so they can be
    concatenated / compared (zero padding preserves order)."""
    if not a.is_string:
        return a, b
    wa, wb = a.string_width, b.string_width
    w = max(wa, wb)

    def pad(c: Column) -> Column:
        if c.string_width == w:
            return c
        extra = jnp.zeros((c.data.shape[0], w - c.string_width), jnp.uint8)
        return Column(jnp.concatenate([c.data, extra], axis=1), c.validity,
                      c.lengths, c.dtype)

    return pad(a), pad(b)


def concat_columns(a: Column, b: Column) -> Column:
    """Stack two columns' buffers (paddings and all) into one column of
    capacity cap_a + cap_b."""
    a, b = widen_strings(a, b)
    data = jnp.concatenate([a.data, b.data], axis=0)
    validity = jnp.concatenate([a.validity, b.validity])
    lengths = None
    if a.lengths is not None:
        lengths = jnp.concatenate([a.lengths, b.lengths])
    return Column(data, validity, lengths, a.dtype)


def two_table_padding(cap_a: int, count_a, cap_b: int, count_b) -> jax.Array:
    """Padding-flag operand (bool — one packed bit) for a concatenated pair
    of tables."""
    idx = jnp.arange(cap_a + cap_b, dtype=jnp.int32)
    in_a = idx < cap_a
    pad_a = idx >= count_a
    pad_b = (idx - cap_a) >= count_b
    return jnp.where(in_a, pad_a, pad_b)


def combined_sorted_runs(cols_a: Sequence[Column], count_a,
                         cols_b: Sequence[Column], count_b,
                         key_a: Sequence[int], key_b: Sequence[int]):
    """Lexsort the union of two tables' key rows and mark the key runs.

    This is the TPU replacement for the reference's hash-table row matching
    (HashJoinKernel build/probe, arrow/arrow_hash_kernels.hpp:33-215, and the
    RowComparator hash-sets of the set ops, table.cpp:522-734): after one
    fused multi-key sort of all rows from both tables, rows with equal keys
    are one contiguous run, turning every equality problem downstream into
    prefix arithmetic over the sorted order (segments.run_extents) — no
    group-id arrays, no scatters.

    Returns (perm, sorted_ops, new_group, is_run_end, live_sorted) over the
    cap_a + cap_b sorted positions; ``perm[p] < cap_a`` identifies table-A
    rows, and padding rows from either table sort last (the padding flag is
    the primary sort operand), so ``live_sorted`` is a prefix mask.
    """
    cap_a = cols_a[0].data.shape[0]
    cap_b = cols_b[0].data.shape[0]
    n = cap_a + cap_b
    operands: List[jax.Array] = [two_table_padding(cap_a, count_a, cap_b, count_b)]
    for ia, ib in zip(key_a, key_b):
        combined = concat_columns(cols_a[ia], cols_b[ib])
        operands.extend(keys.column_operands(combined))
    perm, sorted_ops = keys.lexsort_indices(operands, n)
    new_group = ~keys.rows_equal_adjacent(sorted_ops)
    is_run_end = jnp.concatenate([new_group[1:], jnp.ones((1,), bool)])
    pos = jnp.arange(n, dtype=jnp.int32)
    live_sorted = pos < (count_a + count_b)
    return perm, sorted_ops, new_group, is_run_end, live_sorted
