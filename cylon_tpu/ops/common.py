"""Shared helpers for multi-table kernels."""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from . import keys


def widen_strings(a: Column, b: Column) -> Tuple[Column, Column]:
    """Pad two string columns' byte matrices to a common width so they can be
    concatenated / compared (zero padding preserves order)."""
    if not a.is_string:
        return a, b
    wa, wb = a.string_width, b.string_width
    w = max(wa, wb)

    def pad(c: Column) -> Column:
        if c.string_width == w:
            return c
        extra = jnp.zeros((c.data.shape[0], w - c.string_width), jnp.uint8)
        return Column(jnp.concatenate([c.data, extra], axis=1), c.validity,
                      c.lengths, c.dtype)

    return pad(a), pad(b)


def concat_columns(a: Column, b: Column) -> Column:
    """Stack two columns' buffers (paddings and all) into one column of
    capacity cap_a + cap_b."""
    a, b = widen_strings(a, b)
    data = jnp.concatenate([a.data, b.data], axis=0)
    validity = jnp.concatenate([a.validity, b.validity])
    lengths = None
    if a.lengths is not None:
        lengths = jnp.concatenate([a.lengths, b.lengths])
    return Column(data, validity, lengths, a.dtype)


def two_table_padding(cap_a: int, count_a, cap_b: int, count_b) -> jax.Array:
    """Padding-flag operand for a concatenated pair of tables."""
    idx = jnp.arange(cap_a + cap_b, dtype=jnp.int32)
    in_a = idx < cap_a
    pad_a = idx >= count_a
    pad_b = (idx - cap_a) >= count_b
    return jnp.where(in_a, pad_a, pad_b).astype(jnp.uint8)


def combined_group_ids(cols_a: Sequence[Column], count_a,
                       cols_b: Sequence[Column], count_b,
                       key_a: Sequence[int], key_b: Sequence[int]):
    """Lexsort the union of two tables' key rows and assign dense group ids.

    This is the TPU replacement for the reference's hash-table row matching
    (HashJoinKernel build/probe, arrow/arrow_hash_kernels.hpp:33-215, and the
    RowComparator hash-sets of the set ops, table.cpp:522-734): after one
    fused multi-key sort of all rows from both tables, rows with equal keys
    share a dense int32 id, turning every equality problem downstream into
    integer comparisons.

    Returns (gid_a[cap_a], gid_b[cap_b], perm, sorted_ops, num_all_groups).
    Padding rows from either table share the final (largest) group id.
    """
    cap_a = cols_a[0].data.shape[0]
    cap_b = cols_b[0].data.shape[0]
    n = cap_a + cap_b
    operands: List[jax.Array] = [two_table_padding(cap_a, count_a, cap_b, count_b)]
    for ia, ib in zip(key_a, key_b):
        combined = concat_columns(cols_a[ia], cols_b[ib])
        operands.extend(keys.column_operands(combined))
    perm, sorted_ops = keys.lexsort_indices(operands, n)
    gid_sorted, num_groups = keys.dense_group_ids(sorted_ops)
    gid = jnp.zeros((n,), jnp.int32).at[perm].set(gid_sorted)
    return gid[:cap_a], gid[cap_a:], perm, sorted_ops, num_groups
