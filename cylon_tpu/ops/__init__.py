"""Local relational kernels (jit/XLA programs).

TPU-native replacement for the reference's C++ kernel layer L2
(cpp/src/cylon/join, groupby, compute, arrow/ kernels): sort-based joins,
segment-reduce group-bys, set ops, unique, aggregates — all static-shape XLA
programs over padded column buffers.
"""
