"""LSD radix sort over packed key words — the bandwidth-bound alternative
to ``lax.sort`` for the ≤64-bit packed fast path in :mod:`.keys`.

Why: XLA lowers a TPU ``lax.sort`` to a comparator network whose depth
grows ~log²(n); at the bench shape (2^27 combined rows) that network is
the pipeline's dominant cost (PERF.md: the 84 B/row HBM peak is
sort-region-dominated, replacing the reference's hot sort loops
join/join.cpp:78-257 and util/sort.hpp).  A least-significant-digit radix
sort is O(n) passes over the data: per significant key bit, one stable
1-bit counting split (a cumsum plus one permuting scatter).  The packed
fast-path encoding makes the digit count SMALL: only the significant key
bits (e.g. padding + validity + 32-bit key = 34) are processed — the
embedded row-index bits that make keys unique are skipped entirely,
because counting splits are stable and therefore preserve the index
order that ``lax.sort`` would have established by comparing them.

The inclusive scan inside each split is itself a log-depth network if
left to XLA, so ``_cumsum_i32`` reshapes to [blocks, B] and rides the
MXU: an inclusive within-block scan is one f32 matmul against an
upper-triangular ones matrix (counts ≤ B « 2^24 stay exact in f32), and
the cross-block offset is a tiny host-size scan.  Total per-pass traffic
is a handful of linear sweeps, so the whole sort is ~34 linear passes
instead of ~400 comparator stages.

Env knobs (A/B'd by the TPU battery):
- CYLON_TPU_SORT=radix     switch lexsort's packed fast path to this sort
- CYLON_TPU_RADIX_BITS=d   digits wider than 1 bit (2^d cumsums per pass
                           via the counting scan, so scan traffic grows
                           as (2^d/d)·bits while scatter passes shrink as
                           bits/d; default 1 — the scan-optimal point)
- CYLON_TPU_RADIX_SCAN=xla use jnp.cumsum instead of the matmul scan
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .. import config

_BLOCK = 256  # matmul-scan block edge: one MXU tile, counts ≤ 256 exact in f32


def sort_mode() -> str:
    """Which packed-fast-path sort to use ("cmp" = lax.sort, "radix")."""
    return config.knob("CYLON_TPU_SORT")


def radix_bits() -> int:
    return max(1, min(int(config.knob("CYLON_TPU_RADIX_BITS")), 8))


def _cumsum_i32(m: jax.Array) -> jax.Array:
    """Inclusive cumsum of a bool/int mask as int32, O(n) HBM traffic.

    Two-level: per-block inclusive scan via one [B,B] upper-triangular f32
    matmul (MXU), plus an exclusive scan of the per-block sums (tiny).
    Falls back to jnp.cumsum under CYLON_TPU_RADIX_SCAN=xla for A/B."""
    if config.knob("CYLON_TPU_RADIX_SCAN") == "xla":
        return jnp.cumsum(m.astype(jnp.int32))
    n = m.shape[0]
    if n < _BLOCK * 4 or n % _BLOCK:
        return jnp.cumsum(m.astype(jnp.int32))
    x = m.astype(jnp.float32).reshape(n // _BLOCK, _BLOCK)
    tri = jnp.triu(jnp.ones((_BLOCK, _BLOCK), jnp.float32))  # k<=j upper incl.
    within = jax.lax.dot_general(
        x, tri, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [nb, B] inclusive scans
    block_sums = within[:, -1].astype(jnp.int32)     # [nb]
    offsets = jnp.cumsum(block_sums) - block_sums    # exclusive, tiny
    return (within.astype(jnp.int32) + offsets[:, None]).reshape(n)


def _extract_digit(hi: jax.Array, lo: jax.Array, shift: int,
                   width: int) -> jax.Array:
    """Bits [shift, shift+width) of the logical 64-bit (hi:lo) value, as
    uint32.  All shift arithmetic is static (trace-time)."""
    mask = jnp.uint32((1 << width) - 1)
    if shift >= 32:
        return (hi >> jnp.uint32(shift - 32)) & mask
    if shift + width <= 32:
        return (lo >> jnp.uint32(shift)) & mask
    low_part = lo >> jnp.uint32(shift)          # top (32-shift) bits of lo
    hi_bits = shift + width - 32                # bits taken from hi
    high_part = (hi & jnp.uint32((1 << hi_bits) - 1)) << jnp.uint32(32 - shift)
    return (high_part | low_part) & mask


def _split_destinations(digit: jax.Array, width: int) -> jax.Array:
    """Stable counting-sort destinations for one radix digit.

    width == 1 uses the single-cumsum split (rank among set bits is
    position minus rank among clear bits); wider digits run the counting
    scan (one cumsum per digit value, unrolled at trace time — the same
    shape as shuffle's _perm_by_target, whose alphabet is the mesh)."""
    n = digit.shape[0]
    if width == 1:
        zero = digit == 0
        c = _cumsum_i32(zero)                   # rank+1 among zeros
        total_zero = c[-1]
        iota = jnp.arange(n, dtype=jnp.int32)
        return jnp.where(zero, c - 1, total_zero + (iota - c))
    dest = jnp.zeros((n,), jnp.int32)
    base = jnp.zeros((), jnp.int32)
    for v in range(1 << width):
        sel = digit == v
        c = _cumsum_i32(sel)
        dest = jnp.where(sel, base + c - 1, dest)
        base = base + c[-1]
    return dest


def _permute(dest: jax.Array, *arrays: jax.Array) -> Tuple[jax.Array, ...]:
    """Apply the destination map as one scatter per array (dest is a
    permutation — unique, in-bounds by construction)."""
    out = []
    for a in arrays:
        out.append(jnp.zeros_like(a).at[dest].set(
            a, unique_indices=True, indices_are_sorted=False,
            mode="promise_in_bounds"))
    return tuple(out)


def radix_sort_packed(hi: jax.Array | None, lo: jax.Array,
                      sig_lo: int, sig_hi: int) -> Tuple[jax.Array | None, jax.Array]:
    """Stable LSD radix sort of the logical 64-bit values (hi:lo) — or
    32-bit values when ``hi is None`` — by bits [sig_lo, sig_hi).

    Bits below ``sig_lo`` (the embedded row index) are carried, not
    sorted: pass stability preserves their pre-existing order, which is
    exactly what sorting them would produce since they are unique and
    initially ascending.  Returns the reordered (hi, lo)."""
    d = radix_bits()
    shift = sig_lo
    while shift < sig_hi:
        width = min(d, sig_hi - shift)
        if hi is None:
            digit = (lo >> jnp.uint32(shift)) & jnp.uint32((1 << width) - 1)
        else:
            digit = _extract_digit(hi, lo, shift, width)
        dest = _split_destinations(digit, width)
        if hi is None:
            (lo,) = _permute(dest, lo)
        else:
            hi, lo = _permute(dest, hi, lo)
        shift += width
    return hi, lo
