"""Local multi-column sort.

Replaces the reference's index-sort kernels (cpp/src/cylon/arrow/
arrow_kernels.hpp:180-314 NumericIndexSortKernel / SortIndicesInPlace,
util/arrow_utils.cpp SortTable) with one fused ``jax.lax.sort`` over
lexicographic key operands + a gather.  Padding rows always sort last, so
the dynamic row count is unchanged.
"""
from __future__ import annotations

from typing import Sequence, Tuple

from ..column import Column
from . import keys


def sort_rows(cols: Tuple[Column, ...], count, by: Sequence[int],
              ascending: Sequence[bool] | None = None,
              nulls_first: bool = True) -> Tuple[Tuple[Column, ...], object]:
    """Sort all columns by the key columns ``by``; returns (columns, count)."""
    cap = cols[0].data.shape[0]
    if ascending is None:
        ascending = [True] * len(by)
    operands = keys.build_operands([cols[i] for i in by], count, cap,
                                   ascending=ascending, nulls_first=nulls_first)
    perm, _ = keys.lexsort_indices(operands, cap)
    return tuple(c.take(perm) for c in cols), count
