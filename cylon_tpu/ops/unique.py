"""Local unique / drop-duplicates.

TPU-native replacement for the reference's hash-set unique
(cpp/src/cylon/table.cpp:966-1029 — bytell hash-set insert per row building
a keep-filter, with 'first'/'last' keep semantics).  Here: lexsort the key
columns; the sort is stable (or embeds the row index in the key word), so
rows inside a key run sit in original row order and each run's first/last
position IS the group's first/last occurrence — one scatter along the
permutation marks the kept rows, then a compaction restores original
order like the reference's filter does.  No segment min/max needed.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from . import compact, keys


@partial(jax.jit, static_argnames=("key_idx", "keep"))
def unique(cols: Tuple[Column, ...], count, key_idx: Tuple[int, ...],
           keep: str = "first"):
    """Returns (columns, new_count): rows with a duplicate key removed,
    keeping the first or last occurrence, original order preserved."""
    if keep not in ("first", "last"):
        raise ValueError(f"keep must be 'first' or 'last', got {keep!r}")
    cap = cols[0].data.shape[0]
    key_cols = [cols[i] for i in key_idx]
    operands = keys.build_operands(key_cols, count, cap)
    perm, sorted_ops = keys.lexsort_indices(operands, cap)
    live_sorted = jnp.arange(cap, dtype=jnp.int32) < count

    new_group = ~keys.rows_equal_adjacent(sorted_ops)
    if keep == "first":
        rep_pos = new_group  # run start = smallest original index in the run
    else:  # run end = largest original index in the run
        rep_pos = jnp.concatenate([new_group[1:], jnp.ones((1,), bool)])
    leader = rep_pos & live_sorted  # padding runs sort last -> excluded

    # leader flags travel back to original row order along the (full)
    # sort permutation — fused key-sort on TPU, scatter elsewhere
    keep_mask = compact.inverse_permute(
        perm, leader.astype(jnp.int32))[0] == 1

    perm_keep, m = compact.compact_indices(keep_mask)
    out = tuple(c.take(perm_keep, valid_mask=compact.live_mask(cap, m)) for c in cols)
    return out, m
