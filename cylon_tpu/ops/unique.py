"""Local unique / drop-duplicates.

TPU-native replacement for the reference's hash-set unique
(cpp/src/cylon/table.cpp:966-1029 — bytell hash-set insert per row building
a keep-filter, with 'first'/'last' keep semantics).  Here: lexsort the key
columns, dense group ids, pick each group's first (or last) occurrence *in
original row order* via a segment min/max over original indices, then
compact — output preserves the input's row order like the reference's
filter does.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from . import compact, keys


@partial(jax.jit, static_argnames=("key_idx", "keep"))
def unique(cols: Tuple[Column, ...], count, key_idx: Tuple[int, ...],
           keep: str = "first"):
    """Returns (columns, new_count): rows with a duplicate key removed,
    keeping the first or last occurrence, original order preserved."""
    cap = cols[0].data.shape[0]
    key_cols = [cols[i] for i in key_idx]
    operands = keys.build_operands(key_cols, count, cap)
    perm, sorted_ops = keys.lexsort_indices(operands, cap)
    gid, _ = keys.dense_group_ids(sorted_ops)
    live_sorted = jnp.arange(cap, dtype=jnp.int32) < count

    orig = perm  # original row index of each sorted position
    if keep == "first":
        rep = jax.ops.segment_min(jnp.where(live_sorted, orig, cap), gid, cap)
    elif keep == "last":
        rep = jax.ops.segment_max(jnp.where(live_sorted, orig, -1), gid, cap)
    else:
        raise ValueError(f"keep must be 'first' or 'last', got {keep!r}")

    valid_rep = (rep >= 0) & (rep < cap)
    keep_mask = jnp.zeros((cap,), jnp.bool_).at[jnp.clip(rep, 0, cap - 1)].max(
        valid_rep)
    keep_mask = keep_mask & compact.live_mask(cap, count)

    perm_keep, m = compact.compact_indices(keep_mask)
    out = tuple(c.take(perm_keep, valid_mask=compact.live_mask(cap, m)) for c in cols)
    return out, m
