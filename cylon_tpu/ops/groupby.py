"""Local group-by: sort + segment reduce.

TPU-native replacement for the reference's hash group-by
(cpp/src/cylon/groupby/hash_groupby.cpp:86-295 — ska::bytell_hash_map row→
group-id assignment + per-group State streaming) and pipeline group-by
(groupby/pipeline_groupby.cpp:29-115 — boundary scan over a pre-sorted key
column).  A hash table is the wrong shape for a vector machine; instead:

1. lexsort rows by the key columns (one fused ``lax.sort``),
2. dense group ids via adjacent equality + prefix sum,
3. each aggregation is a masked ``jax.ops.segment_*`` keyed by group id.

The aggregation op set and their state decompositions mirror the reference's
KernelTraits (compute/aggregate_kernels.hpp:38-200: SUM/MIN/MAX/COUNT/MEAN
(sum,count)/VAR (sumsq,sum,count)/STDDEV/NUNIQUE), including the
partial/final split used by the distributed two-phase group-by
(groupby/groupby.cpp:23-73): ``partial_ops`` names the partial columns a
pre-aggregation emits and ``final_of_partial`` how they recombine.
"""
from __future__ import annotations

import enum
from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import dtypes, precision
from ..column import Column
from . import compact, keys, segments


class AggOp(enum.IntEnum):
    """reference: compute/aggregate_kernels.hpp AggregationOpId."""

    SUM = 0
    MIN = 1
    MAX = 2
    COUNT = 3
    MEAN = 4
    VAR = 5
    STDDEV = 6
    NUNIQUE = 7
    SUMSQ = 8  # internal: sum of squares partial for VAR/STDDEV two-phase
    COUNTSUM = 9  # internal: sum of partial counts — i32 scatter in narrow

    @staticmethod
    def of(name: "str | AggOp") -> "AggOp":
        if isinstance(name, AggOp):
            return name
        m = {"sum": AggOp.SUM, "min": AggOp.MIN, "max": AggOp.MAX,
             "count": AggOp.COUNT, "mean": AggOp.MEAN, "avg": AggOp.MEAN,
             "var": AggOp.VAR, "std": AggOp.STDDEV, "stddev": AggOp.STDDEV,
             "nunique": AggOp.NUNIQUE}
        return m[name.lower()]


# -- two-phase decomposition (reference: groupby/groupby.cpp:47-62 runs
#    local partial agg, shuffles, then a final agg over partial columns) ----

def partial_ops(op: AggOp) -> Tuple[AggOp, ...]:
    """Partial aggregations whose columns must be shuffled for ``op``."""
    return {
        AggOp.SUM: (AggOp.SUM,),
        AggOp.MIN: (AggOp.MIN,),
        AggOp.MAX: (AggOp.MAX,),
        AggOp.COUNT: (AggOp.COUNT,),
        AggOp.MEAN: (AggOp.SUM, AggOp.COUNT),
        AggOp.VAR: (AggOp.SUM, AggOp.COUNT, AggOp.SUMSQ),
        AggOp.STDDEV: (AggOp.SUM, AggOp.COUNT, AggOp.SUMSQ),
        # the internal partial states are their own partials, so a caller
        # holding partial columns (the out-of-core cross-pass combine) can
        # push them through the distributed two-phase group-by unchanged
        AggOp.SUMSQ: (AggOp.SUMSQ,),
        AggOp.COUNTSUM: (AggOp.COUNTSUM,),
    }[op]


def combine_op(partial: AggOp) -> AggOp:
    """How a partial column recombines in the final phase."""
    if partial == AggOp.COUNT:
        # counts are bounded by rows, so the combine keeps the count
        # accumulator (i32 in narrow mode) instead of the int-SUM i64 path
        return AggOp.COUNTSUM
    if partial in (AggOp.SUM, AggOp.SUMSQ):
        return AggOp.SUM
    return partial  # MIN of mins, MAX of maxes


def _agg_out_dtype(op: AggOp, dt: dtypes.DataType):
    nar = precision.narrow()
    if op in (AggOp.COUNT, AggOp.NUNIQUE, AggOp.COUNTSUM):
        # declared int64 even in narrow mode: the device buffer stays i32
        # (cheap scatter) and widens at the host/arrow column boundary
        return dtypes.int64
    if op in (AggOp.MEAN, AggOp.VAR, AggOp.STDDEV, AggOp.SUMSQ):
        return dtypes.float_ if nar else dtypes.double
    if op == AggOp.SUM:
        if dtypes.is_floating(dt):
            if dt.type == dtypes.Type.DOUBLE and not nar:
                return dtypes.double
            return dtypes.float_
        return dtypes.int64
    return dt  # MIN/MAX keep the input type


def _segment_aggregate(op: AggOp, data, valid, gid, num_segments: int,
                       ddof: int, spans=None, boundaries=None):
    """One masked segment reduction; returns (values, validity_counts).

    Reductions are ``jax.ops.segment_*`` scatters with 32-bit operands
    wherever the semantics allow (counts accumulate i32 and widen after;
    f32 sums stay f32, matching the reference's KernelTraits accumulator of
    the input type) — 64-bit scatters profile ~8x slower on TPU, and the
    prefix-sum alternative (cumsum + boundary gather) SIGSEGVs/hangs this
    XLA TPU backend whenever several 64-bit prefix programs share one
    multi-aggregation fusion.  Only ops whose semantics require double
    accumulation (MEAN/VAR/STDDEV/SUMSQ, f64/int64 SUM) pay the 64-bit
    scatter.

    ``spans``: optional (start, end) per-segment row spans when rows are
    already ordered by ``gid`` (always true here — gids come from a sort or
    key-adjacent input).  In narrow mode, validity counts then use an exact
    i32 cumsum + boundary gather instead of a scatter (the cumsum peaks at
    the shard's physical row count, always an i32-safe quantity).  Value
    sums — including COUNTSUM, whose partial counts can represent far more
    rows than the shard holds — keep the per-segment scatter-add: a global
    prefix sum would overflow i32 for int data and lose precision for
    f32.

    ``boundaries`` (the run-start mask over the gid-sorted rows) opts the
    float/min/max reductions into the scatter-free segmented scan
    (segments.segmented_reduce_sorted) when CYLON_TPU_SEGSUM=prefix —
    rounding stays per-segment because the scan's combine resets at run
    starts.  Integer sums stay on the scatter in every mode: their i64
    accumulator would make the scan a 64-bit prefix program (the class
    that has crashed this XLA TPU backend)."""
    sorted_counts = spans is not None and precision.narrow()
    use_scan = (sorted_counts and boundaries is not None
                and segments.prefix_reductions_enabled())
    if sorted_counts:
        start, end = spans
        cnt32 = segments.segment_sum_sorted(valid.astype(jnp.int32), start,
                                            end, jnp.int32)
    else:
        cnt32 = jax.ops.segment_sum(valid.astype(jnp.int32), gid, num_segments)
    cnt = cnt32 if precision.narrow() else cnt32.astype(jnp.int64)

    def fsum(x):
        if use_scan:
            return segments.segmented_reduce_sorted(x, boundaries, end, "sum")
        return jax.ops.segment_sum(x, gid, num_segments)

    if op == AggOp.COUNT:
        return cnt, cnt
    if op == AggOp.COUNTSUM:
        x = jnp.where(valid, data, 0).astype(precision.count_acc())
        s = jax.ops.segment_sum(x, gid, num_segments)
        return (s if precision.narrow() else s.astype(jnp.int64)), cnt
    if op == AggOp.SUMSQ:
        x = jnp.where(valid, data, 0).astype(precision.float_acc())
        return fsum(x * x), cnt
    if op == AggOp.SUM:
        acc = jnp.where(valid, data, jnp.zeros((), data.dtype))
        if jnp.issubdtype(data.dtype, jnp.floating):
            acc = acc.astype(precision.float_acc_for(data.dtype))
            return fsum(acc), cnt
        acc = acc.astype(precision.int_acc())
        return jax.ops.segment_sum(acc, gid, num_segments), cnt
    if op == AggOp.MIN or op == AggOp.MAX:
        if jnp.issubdtype(data.dtype, jnp.floating):
            sentinel = jnp.inf if op == AggOp.MIN else -jnp.inf
        elif data.dtype == jnp.bool_:
            data = data.astype(jnp.uint8)
            sentinel = 1 if op == AggOp.MIN else 0
        else:
            info = jnp.iinfo(data.dtype)
            sentinel = info.max if op == AggOp.MIN else info.min
        masked = jnp.where(valid, data, jnp.asarray(sentinel, data.dtype))
        if use_scan and masked.dtype.itemsize <= 4:
            out = segments.segmented_reduce_sorted(
                masked, boundaries, end, "min" if op == AggOp.MIN else "max")
        else:
            f = jax.ops.segment_min if op == AggOp.MIN else jax.ops.segment_max
            out = f(masked, gid, num_segments)
        return jnp.where(cnt > 0, out, jnp.zeros((), out.dtype)), cnt
    if op in (AggOp.MEAN, AggOp.VAR, AggOp.STDDEV):
        facc = precision.float_acc()
        x = jnp.where(valid, data, 0).astype(facc)
        s = fsum(x)
        if op == AggOp.MEAN:
            return s / jnp.maximum(cnt, 1).astype(facc), cnt
        s2 = fsum(x * x)
        n = jnp.maximum(cnt, 1).astype(facc)
        var = (s2 - s * s / n) / jnp.maximum(n - ddof, 1.0)
        var = jnp.maximum(var, 0.0)
        if op == AggOp.STDDEV:
            var = jnp.sqrt(var)
        return var, jnp.where(cnt - ddof > 0, cnt, 0)
    if op == AggOp.NUNIQUE:
        # distinct (gid, value) pairs: sort values within segments and count
        # adjacency breaks — handled in hash_groupby via a secondary sort.
        raise NotImplementedError("NUNIQUE is computed in hash_groupby")
    raise ValueError(op)


@partial(jax.jit, static_argnames=("key_idx", "aggs", "ddof"))
def hash_groupby(cols: Tuple[Column, ...], count,
                 key_idx: Tuple[int, ...],
                 aggs: Tuple[Tuple[int, AggOp], ...],
                 ddof: int = 0):
    """Group rows by ``key_idx`` columns and aggregate.

    Output columns: the key columns (one row per distinct live key, in key
    order) followed by one column per (value column, op) pair.  Returns
    (columns, group_count).
    """
    cap = cols[0].data.shape[0]
    key_cols = [cols[i] for i in key_idx]
    operands = keys.build_operands(key_cols, count, cap)
    perm, sorted_ops = keys.lexsort_indices(operands, cap)
    new_group = ~keys.rows_equal_adjacent(sorted_ops)
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    start, end = segments.segment_spans(new_group)
    iota = jnp.arange(cap, dtype=jnp.int32)
    live = iota < count  # padding sorted last -> first `count` sorted rows live
    num_groups = jnp.where(
        count > 0, jnp.take(gid, jnp.clip(count - 1, 0, cap - 1)) + 1, 0)

    # group leader positions (first sorted row of each group)
    leader = jnp.clip(start, 0, cap - 1)
    group_live = iota[:cap] < num_groups

    out_cols = []
    leader_src = jnp.take(perm, leader)  # compose index gathers: one
    for kc in key_cols:                  # column gather instead of two
        out_cols.append(kc.take(leader_src, valid_mask=group_live))

    for col_idx, op in aggs:
        vcol = cols[col_idx].take(perm)
        vvalid = vcol.validity & live
        if op == AggOp.NUNIQUE:
            vals, cnts = _nunique(vcol, vvalid, gid, cap)
        else:
            if vcol.is_string:
                raise TypeError(f"aggregation {op.name} unsupported on strings")
            vals, cnts = _segment_aggregate(op, vcol.data, vvalid, gid,
                                            cap, ddof, spans=(start, end),
                                            boundaries=new_group)
        if op in (AggOp.COUNT, AggOp.COUNTSUM, AggOp.NUNIQUE):
            validity = group_live  # a count of zero values is a valid 0
        else:
            validity = group_live & (cnts > 0)
        vals = jnp.where(validity, vals, jnp.zeros((), vals.dtype))
        out_cols.append(Column(vals, validity, None,
                               _agg_out_dtype(op, cols[col_idx].dtype)))
    return tuple(out_cols), num_groups


def _nunique(vcol: Column, vvalid, gid, cap: int):
    """Distinct non-null values per group via a (gid, value) lexsort."""
    ops = [~vvalid, gid] + keys.column_operands(vcol, with_validity=False)
    perm, sorted_ops = keys.lexsort_indices(ops, cap)
    eq = keys.rows_equal_adjacent(sorted_ops)
    # sorted_ops are packed words: recover fields through the permutation
    svalid = jnp.take(vvalid, perm)
    gsorted = jnp.take(gid, perm)
    new_distinct = (~eq) & svalid
    if compact.permute_mode() == "sort":
        # valid rows sort first (primary operand ~vvalid), so the valid
        # prefix is gid-ascending: per-gid distinct counts are prefix-sum
        # differences at merged-searchsorted group bounds — no scatter
        gclean = jnp.where(svalid, gsorted, cap)
        ub = compact.count_leq_dense(gclean, cap)
        p0 = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(new_distinct.astype(jnp.int32))])
        e = jnp.take(p0, ub)  # distinct count up to each group's end
        cnt = e - jnp.concatenate([jnp.zeros((1,), jnp.int32), e[:-1]])
    else:
        # i32 scatter-add, widened after: 64-bit scatters are ~8x slower
        cnt = jax.ops.segment_sum(new_distinct.astype(jnp.int32), gsorted,
                                  cap)
    return (cnt if precision.narrow() else cnt.astype(jnp.int64)), cnt


@partial(jax.jit, static_argnames=("key_idx", "aggs", "ddof"))
def pipeline_groupby(cols: Tuple[Column, ...], count,
                     key_idx: Tuple[int, ...],
                     aggs: Tuple[Tuple[int, AggOp], ...],
                     ddof: int = 0):
    """Group-by for key-sorted input (reference: pipeline_groupby.cpp): group
    boundaries come from adjacent comparison in row order — no sort."""
    cap = cols[0].data.shape[0]
    key_cols = [cols[i] for i in key_idx]
    operands = [keys.padding_operand(cap, count)]
    for kc in key_cols:
        operands.extend(keys.column_operands(kc))
    new_group = ~keys.rows_equal_adjacent(keys.pack_operands(operands))
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    start, end = segments.segment_spans(new_group)
    iota = jnp.arange(cap, dtype=jnp.int32)
    live = iota < count
    num_groups = jnp.where(
        count > 0, jnp.take(gid, jnp.clip(count - 1, 0, cap - 1)) + 1, 0)
    leader = jnp.clip(start, 0, cap - 1)
    group_live = iota < num_groups

    out_cols = []
    for kc in key_cols:
        out_cols.append(kc.take(leader, valid_mask=group_live))
    for col_idx, op in aggs:
        vcol = cols[col_idx]
        vvalid = vcol.validity & live
        if op == AggOp.NUNIQUE:
            vals, cnts = _nunique(vcol, vvalid, gid, cap)
        else:
            if vcol.is_string:
                raise TypeError(f"aggregation {op.name} unsupported on strings")
            vals, cnts = _segment_aggregate(op, vcol.data, vvalid, gid,
                                            cap, ddof, spans=(start, end),
                                            boundaries=new_group)
        if op in (AggOp.COUNT, AggOp.COUNTSUM, AggOp.NUNIQUE):
            validity = group_live  # a count of zero values is a valid 0
        else:
            validity = group_live & (cnts > 0)
        vals = jnp.where(validity, vals, jnp.zeros((), vals.dtype))
        out_cols.append(Column(vals, validity, None,
                               _agg_out_dtype(op, cols[col_idx].dtype)))
    return tuple(out_cols), num_groups
