"""Vectorized row hashing.

The reference hashes rows with scalar MurmurHash3_x86_32 per value, combined
as ``31*h + x`` across columns (cpp/src/cylon/util/murmur3.cpp,
arrow/arrow_partition_kernels.hpp:93-362 ModuloPartitionKernel /
NumericHashPartitionKernel / BinaryHashPartitionKernel,
arrow/arrow_comparator.hpp TableRowIndexHash).  On TPU a scalar hash loop is
the wrong shape; we use the same finalizer mathematics (murmur3 fmix32 /
splitmix64-style avalanche) applied **vectorially** to whole columns: every
lane hashes one row, strings fold their packed 8-byte words in a
``lax.fori``-free unrolled loop over the static word count.

All hashes are uint32; multi-column combination is ``h = 31*h + col_hash``
matching the reference's semantics so partition placement logic translates
directly.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..column import Column
from . import keys as keys_mod


def _fmix32(h: jax.Array) -> jax.Array:
    """murmur3 x86_32 finalizer (reference: util/murmur3.cpp fmix32)."""
    h = h.astype(jnp.uint32)
    h ^= h >> 16
    h *= jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h *= jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h


def _mix64_to_32(x: jax.Array) -> jax.Array:
    """Avalanche a uint64 lane down to uint32 (splitmix64 finalizer then
    fold) — used for 8-byte values and packed string words."""
    x = x.astype(jnp.uint64)
    x ^= x >> 30
    x *= jnp.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> 27
    x *= jnp.uint64(0x94D049BB133111EB)
    x ^= x >> 31
    return (x ^ (x >> 32)).astype(jnp.uint32)


def hash_column(col: Column) -> jax.Array:
    """uint32[capacity] hash per row; nulls hash to a fixed sentinel."""
    if col.is_string:
        words = keys_mod.pack_string_words(col.data)
        h = jnp.full(col.data.shape[:1], jnp.uint32(0x9747B28C))
        for w in words:
            h = h * jnp.uint32(31) + _mix64_to_32(w)
        h = _fmix32(h)
    else:
        data = col.data
        if data.dtype == jnp.bool_:
            h = _fmix32(data.astype(jnp.uint32))
        elif data.dtype.itemsize <= 4:
            bits = jax.lax.bitcast_convert_type(
                data, {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[data.dtype.itemsize])
            h = _fmix32(bits.astype(jnp.uint32))
        else:
            bits = jax.lax.bitcast_convert_type(data, jnp.uint64)
            h = _mix64_to_32(bits)
            h = _fmix32(h)
    return jnp.where(col.validity, h, jnp.uint32(0x52ABD123))


def hash_columns(cols: Sequence[Column]) -> jax.Array:
    """Composite row hash across columns: ``h = 31*h + hash(col)`` —
    the reference's UpdateHash combiner (arrow_partition_kernels.hpp,
    partition/partition.cpp:145-160)."""
    h = jnp.zeros(cols[0].data.shape[:1], jnp.uint32)
    for col in cols:
        h = h * jnp.uint32(31) + hash_column(col)
    return h
