"""Pallas TPU kernels for the hot partition path.

The reference's hottest per-row loop is the partition hash: scalar
MurmurHash3_x86_32 per value, ``31*h + x`` across columns, modulo world
(cpp/src/cylon/arrow/arrow_partition_kernels.hpp:93-233 HashPartitionKernel
::UpdateHash/Partition, util/murmur3.cpp).  Here it is one fused VMEM-
resident Pallas kernel: every lane hashes one row through the murmur3 block
recurrence (unrolled over the static word count), combines columns, and
emits the target shard — one HBM read per word buffer, one write, zero
intermediates.

Bit-exactness: a row's device hash equals the native layer's
``ct_row_hash`` (cylon_tpu/native/src/hashing.cpp) for fixed-width
columns — both compute murmur3_x86_32 over the value's little-endian bytes
with seed 0, combined as ``h = 31*h + column_hash`` from ``h = 1`` — so
host-partitioned and device-partitioned rows land on the same shard.

The kernel runs natively on TPU; elsewhere ``pallas_call`` uses interpret
mode (tests) or callers fall back to the jnp path in ops/hashing.
"""
from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..column import Column

_LANES = 128
_MIN_ROWS = 8 * _LANES  # one (8, 128) uint32 tile
_BLOCK_ROWS = 256       # max row-tiles per grid block

C1 = 0xCC9E2D51
C2 = 0x1B873593


def _rotl(x: jax.Array, r: int) -> jax.Array:
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _murmur3_words(words: Sequence[jax.Array], seed: int = 0) -> jax.Array:
    """murmur3_x86_32 of the little-endian concatenation of 4-byte words,
    vectorized over lanes (reference: util/murmur3.cpp MurmurHash3_x86_32,
    whole-block path; no tail since input is word-aligned)."""
    h = jnp.full(words[0].shape, seed, jnp.uint32)
    for w in words:
        k = w.astype(jnp.uint32) * jnp.uint32(C1)
        k = _rotl(k, 15)
        k = k * jnp.uint32(C2)
        h = h ^ k
        h = _rotl(h, 13)
        h = h * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    h = h ^ jnp.uint32(4 * len(words))
    h ^= h >> 16
    h *= jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h *= jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h


def column_words(col: Column) -> List[jax.Array]:
    """uint32 word columns (little-endian order) for a fixed-width column;
    the unit the native hasher consumes byte-wise."""
    data = col.data
    if col.is_string:
        raise ValueError("string columns use the jnp hash path")
    if data.dtype == jnp.bool_:
        return [data.astype(jnp.uint32)]
    size = data.dtype.itemsize
    if size <= 4:
        bits = jax.lax.bitcast_convert_type(
            data, {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[size])
        return [bits.astype(jnp.uint32)]
    bits = jax.lax.bitcast_convert_type(data, jnp.uint64)
    lo = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (bits >> jnp.uint64(32)).astype(jnp.uint32)
    return [lo, hi]


def _hash_kernel(nwords: Tuple[int, ...], world: int, *refs):
    """Kernel body: refs = flattened word refs per column + (hash_out,
    target_out)."""
    word_refs, (h_out, t_out) = refs[:-2], refs[-2:]
    h = jnp.full(word_refs[0].shape, 1, jnp.uint32)  # native row_hash seed
    i = 0
    for n in nwords:
        col_words = [word_refs[i + k][:] for k in range(n)]
        i += n
        h = h * jnp.uint32(31) + _murmur3_words(col_words)
    h_out[:] = h
    if world & (world - 1) == 0:
        t_out[:] = (h & jnp.uint32(world - 1)).astype(jnp.int32)
    else:
        t_out[:] = (h % jnp.uint32(world)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("nwords", "world", "interpret"))
def _hash_partition_padded(flat_words, nwords: Tuple[int, ...], world: int,
                           interpret: bool):
    n = flat_words[0].shape[0]
    rows = n // _LANES
    block_rows = min(rows, _BLOCK_ROWS)
    if rows % block_rows:  # caller pads to a whole number of grid blocks
        raise ValueError(f"rows {rows} not a multiple of block {block_rows}")
    # the literal 0 must be typed: under jax_enable_x64 a bare Python 0
    # traces as i64 and Mosaic rejects the (i32, i64) index-map signature
    spec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, jnp.int32(0)))
    shaped = [w.reshape(rows, _LANES) for w in flat_words]
    h, t = pl.pallas_call(
        functools.partial(_hash_kernel, nwords, world),
        grid=(rows // block_rows,),
        in_specs=[spec] * len(shaped),
        out_specs=(spec, spec),
        out_shape=(jax.ShapeDtypeStruct((rows, _LANES), jnp.uint32),
                   jax.ShapeDtypeStruct((rows, _LANES), jnp.int32)),
        interpret=interpret,
    )(*shaped)
    return h.reshape(n), t.reshape(n)


def supported(cols: Sequence[Column]) -> bool:
    return all(not c.is_string for c in cols)


def hash_partition(cols: Sequence[Column], world: int,
                   interpret: bool | None = None
                   ) -> Tuple[jax.Array, jax.Array]:
    """(hashes uint32[cap], targets int32[cap]) for fixed-width key columns
    via the fused Pallas kernel; pads rows to a whole tile and slices back.
    Padding-row targets are whatever the hash of zero bytes lands on —
    callers mask them (partition.hash_targets does)."""
    if interpret is None:
        from .. import precision
        interpret = not precision.on_tpu()
    cap = cols[0].data.shape[0]
    if cap == 0:
        return jnp.zeros((0,), jnp.uint32), jnp.zeros((0,), jnp.int32)
    flat: List[jax.Array] = []
    nwords: List[int] = []
    for c in cols:
        ws = column_words(c)
        # null rows hash as zero bytes so equal-null rows collide onto one
        # shard (the jnp path uses a sentinel for the same purpose)
        ws = [jnp.where(c.validity, w, 0) for w in ws]
        nwords.append(len(ws))
        flat.extend(ws)
    # one eager pad up to a whole grid of full-size blocks: a floor-divided
    # grid would skip tail tiles and leave their hashes undefined, while
    # full blocks keep every grid step saturated (waste <= one block,
    # ~32K elements — negligible hash work)
    tiles = -(-cap // _MIN_ROWS) * 8          # whole (8,128) tile groups
    block = min(tiles, _BLOCK_ROWS)
    tiles = -(-tiles // block) * block        # whole grid blocks
    pad = tiles * _LANES - cap
    if pad:
        flat = [jnp.concatenate([w, jnp.zeros((pad,), jnp.uint32)])
                for w in flat]
    h, t = _hash_partition_padded(tuple(flat), tuple(nwords), world,
                                  interpret)
    return h[:cap], t[:cap]
