"""Local set operations: union / intersect / subtract (distinct semantics).

TPU-native replacement for the reference's hash-set set ops
(cpp/src/cylon/table.cpp:522-734 — ``std::unordered_set<pair<int8,int64>>``
of ⟨table_id, row⟩ with composite RowComparator hash/eq over **all**
columns).  Here: one fused lexsort of both tables' rows → dense group ids →
per-group membership counts via segment sums → leader selection + compaction.
Union keeps one representative of every distinct row; intersect keeps groups
present in both tables; subtract keeps groups of A absent from B.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from . import common, compact


@partial(jax.jit, static_argnames=("op", "out_capacity"))
def set_op(cols_a: Tuple[Column, ...], count_a,
           cols_b: Tuple[Column, ...], count_b,
           op: str, out_capacity: int):
    """op in {'union','intersect','subtract'}; schemas must match.

    Returns (columns, row_count) with capacity ``out_capacity``.
    """
    cap_a = cols_a[0].data.shape[0]
    cap_b = cols_b[0].data.shape[0]
    n = cap_a + cap_b
    ncols = len(cols_a)
    key = tuple(range(ncols))
    gid_a, gid_b, perm, sorted_ops, _ = common.combined_group_ids(
        cols_a, count_a, cols_b, count_b, key, key)

    live_sorted = jnp.take(
        common.two_table_padding(cap_a, count_a, cap_b, count_b), perm) == 0
    from_a_sorted = perm < cap_a
    gid_sorted = jnp.where(from_a_sorted,
                           jnp.take(gid_a, jnp.clip(perm, 0, cap_a - 1)),
                           jnp.take(gid_b, jnp.clip(perm - cap_a, 0, cap_b - 1)))

    cnt_a = jax.ops.segment_sum((live_sorted & from_a_sorted).astype(jnp.int32),
                                gid_sorted, n)
    cnt_b = jax.ops.segment_sum((live_sorted & ~from_a_sorted).astype(jnp.int32),
                                gid_sorted, n)

    leader = (~common_eq(sorted_ops)) & live_sorted
    ga = jnp.take(cnt_a, gid_sorted) > 0
    gb = jnp.take(cnt_b, gid_sorted) > 0
    if op == "union":
        keep = leader
    elif op == "intersect":
        keep = leader & ga & gb
    elif op == "subtract":
        keep = leader & ga & ~gb
    else:
        raise ValueError(op)

    perm_keep, m = compact.compact_indices(keep)
    combined = tuple(common.concat_columns(a, b) for a, b in zip(cols_a, cols_b))
    out_live = jnp.arange(out_capacity, dtype=jnp.int32) < m
    sel = jnp.take(perm, jnp.take(perm_keep, jnp.arange(out_capacity) % n))
    out = tuple(c.take(sel, valid_mask=None) for c in combined)
    # zero out rows beyond the result count for determinism
    out = tuple(
        Column(jnp.where(out_live if c.data.ndim == 1 else out_live[:, None],
                         c.data, jnp.zeros((), c.data.dtype)),
               c.validity & out_live,
               None if c.lengths is None else jnp.where(out_live, c.lengths, 0),
               c.dtype)
        for c in out)
    return out, m


def common_eq(sorted_ops):
    from . import keys

    return keys.rows_equal_adjacent(sorted_ops)
