"""Local set operations: union / intersect / subtract (distinct semantics).

TPU-native replacement for the reference's hash-set set ops
(cpp/src/cylon/table.cpp:522-734 — ``std::unordered_set<pair<int8,int64>>``
of ⟨table_id, row⟩ with composite RowComparator hash/eq over **all**
columns).  Here: one fused lexsort of both tables' rows, then everything
stays in the sorted domain — per-run membership counts are prefix
arithmetic (segments.run_extents), the leader is the run-start row, and
the kept leaders compact to the front.  No group-id arrays, no scatters
besides the final compaction.  Union keeps one representative of every
distinct row; intersect keeps rows present in both tables; subtract keeps
rows of A absent from B.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from . import common, compact, segments


@partial(jax.jit, static_argnames=("op", "out_capacity"))
def set_op(cols_a: Tuple[Column, ...], count_a,
           cols_b: Tuple[Column, ...], count_b,
           op: str, out_capacity: int):
    """op in {'union','intersect','subtract'}; schemas must match.

    Returns (columns, row_count) with capacity ``out_capacity``.
    """
    cap_a = cols_a[0].data.shape[0]
    cap_b = cols_b[0].data.shape[0]
    n = cap_a + cap_b
    key = tuple(range(len(cols_a)))
    perm, _, new_group, is_run_end, live_sorted = common.combined_sorted_runs(
        cols_a, count_a, cols_b, count_b, key, key)
    from_a_sorted = perm < cap_a

    _, a_in_run = segments.run_extents(live_sorted & from_a_sorted,
                                       new_group, is_run_end)
    _, b_in_run = segments.run_extents(live_sorted & ~from_a_sorted,
                                       new_group, is_run_end)

    leader = new_group & live_sorted
    if op == "union":
        keep = leader
    elif op == "intersect":
        keep = leader & (a_in_run > 0) & (b_in_run > 0)
    elif op == "subtract":
        keep = leader & (a_in_run > 0) & (b_in_run == 0)
    else:
        raise ValueError(op)

    perm_keep, m = compact.compact_indices(keep)
    combined = tuple(common.concat_columns(a, b) for a, b in zip(cols_a, cols_b))
    out_live = jnp.arange(out_capacity, dtype=jnp.int32) < m
    sel = jnp.take(perm, jnp.take(perm_keep, jnp.arange(out_capacity) % n))
    out = tuple(c.take(sel, valid_mask=None) for c in combined)
    # zero out rows beyond the result count for determinism
    out = tuple(
        Column(jnp.where(out_live if c.data.ndim == 1 else out_live[:, None],
                         c.data, jnp.zeros((), c.data.dtype)),
               c.validity & out_live,
               None if c.lengths is None else jnp.where(out_live, c.lengths, 0),
               c.dtype)
        for c in out)
    return out, m
