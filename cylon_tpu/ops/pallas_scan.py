"""Pallas TPU segmented-scan kernel — the two-sweep replacement for
``lax.associative_scan`` in segment reductions.

Why: round-4 hardware settled that XLA:TPU serializes scatters, so
segment reductions ride a segmented ``lax.associative_scan``
(segments.segmented_reduce_sorted).  But XLA lowers an associative scan
as ~log2(n) materialized full-array passes over (value, flag) pairs —
hundreds of bytes of HBM traffic per element at 2^26 rows.  This kernel
does the same inclusive segmented scan in TWO bandwidth-bound sweeps
(~24 B/element total):

1. View the n elements as a (128, m) array: sublane s owns the
   contiguous range [s*m, (s+1)*m).  Sweep 1 runs one grid along the
   lane axis; each (128, bm) block computes an in-block Hillis-Steele
   segmented scan (log2(bm) vectorized roll+combine steps, VMEM
   resident) and stitches blocks with a per-sublane carry held in VMEM
   scratch — TPU grids execute sequentially, so the carry flows left to
   right across the whole sweep.  The per-sublane totals and
   reset-presence flags come out as a tiny (128, 1) side output.
2. The host combines those 128 pairs with one (cheap) exclusive
   segmented scan — carry_in[s] = running value entering sublane s.
3. Sweep 2 folds carry_in into every element positioned before its
   sublane's first segment boundary (the inclusive cum-OR of reset
   flags, recomputed in-block the same way).

The combine matches segments.segmented_reduce_sorted:
``(va, fa) o (vb, fb) = (fb ? vb : fn(va, vb), fa | fb)``.  Like the
associative scan it replaces, float sums round in combine-tree order —
contained per segment, but not bit-identical to a sequential sum (and
the two implementations' trees differ, so float results agree to
tolerance, not bitwise; int and min/max are exact).

Reference counterpart: the aggregation kernels this feeds replace
cpp/src/cylon/groupby/hash_groupby.cpp's per-row hash-map updates
(SURVEY §3.2); the kernel itself has no reference twin — it exists
because the TPU memory model punishes both hash maps and scatters.

The kernel runs natively on TPU; elsewhere ``pallas_call`` uses
interpret mode (tests), where ``jnp.roll`` stands in for
``pltpu.roll``.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SUBLANES = 128       # rows of the scan view; one contiguous range each
_BLOCK_LANES = 1024   # lanes per grid block (128*1024*4B = 512 KB/ref)

_FNS = {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


def _neutral(dtype, op: str):
    if op == "sum":
        return jnp.zeros((), dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if op == "min" else info.min, dtype)


def _roll_right(v: jax.Array, d: int, interpret: bool) -> jax.Array:
    """Shift lanes right by d along axis 1 (circular; callers mask the
    wrap).  pltpu.roll is the Mosaic-native rotate; interpret mode has no
    lowering for it, so tests take jnp.roll."""
    if interpret:
        return jnp.roll(v, d, axis=1)
    return pltpu.roll(v, d, axis=1)


def _block_segscan(v: jax.Array, f: jax.Array, op: str, bm: int,
                   interpret: bool) -> Tuple[jax.Array, jax.Array]:
    """Inclusive segmented Hillis-Steele scan along the lane axis of one
    (128, bm) block.  f is uint32 0/1 reset flags; returns (values,
    inclusive cum-OR of f)."""
    fn = _FNS[op]
    lane = jax.lax.broadcasted_iota(jnp.int32, v.shape, 1)
    d = 1
    while d < bm:
        vs = _roll_right(v, d, interpret)
        fs = _roll_right(f, d, interpret)
        live = lane >= d
        # combine (vs, fs) o (v, f): restart at boundaries, OR the flags
        v = jnp.where(live & (f == 0), fn(vs, v), v)
        f = jnp.where(live, f | fs, f)
        d *= 2
    return v, f


def _sweep1_kernel(op: str, bm: int, interpret: bool, x_ref, r_ref, out_ref,
                   tot_ref, any_ref, carry, or_acc):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        carry[:] = jnp.full(carry.shape, _neutral(carry.dtype, op))
        or_acc[:] = jnp.zeros(or_acc.shape, jnp.uint32)

    v, f = _block_segscan(x_ref[:], r_ref[:], op, bm, interpret)
    # fold the running carry into lanes before the block's first reset
    v = jnp.where(f == 0, _FNS[op](carry[:], v), v)
    out_ref[:] = v
    carry[:] = v[:, -1:]
    or_acc[:] = or_acc[:] | f[:, -1:]
    tot_ref[:] = carry[:]
    any_ref[:] = or_acc[:]


def _block_orscan(f: jax.Array, bm: int, interpret: bool) -> jax.Array:
    """Inclusive cum-OR along the lane axis — the flags-only half of
    _block_segscan (sweep 2 needs just the mask, not the values)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, f.shape, 1)
    d = 1
    while d < bm:
        fs = _roll_right(f, d, interpret)
        f = jnp.where(lane >= d, f | fs, f)
        d *= 2
    return f


def _sweep2_kernel(op: str, bm: int, interpret: bool, x_ref, r_ref, cin_ref,
                   out_ref, or_acc):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        or_acc[:] = jnp.zeros(or_acc.shape, jnp.uint32)

    f = _block_orscan(r_ref[:], bm, interpret)
    seen = or_acc[:] | f  # any reset in this sublane up to and incl. here
    out_ref[:] = jnp.where(seen == 0, _FNS[op](cin_ref[:], x_ref[:]),
                           x_ref[:])
    or_acc[:] = or_acc[:] | f[:, -1:]


@functools.partial(jax.jit,
                   static_argnames=("op", "bm", "interpret"))
def _segmented_scan_padded(x2: jax.Array, r2: jax.Array, op: str, bm: int,
                           interpret: bool) -> jax.Array:
    """x2, r2: (128, m) with m a multiple of bm."""
    m = x2.shape[1]
    grid = (m // bm,)
    blk = pl.BlockSpec((_SUBLANES, bm), lambda i: (0, i))
    col = pl.BlockSpec((_SUBLANES, 1), lambda i: (0, 0))
    partial_scan, totals, anyreset = pl.pallas_call(
        functools.partial(_sweep1_kernel, op, bm, interpret),
        grid=grid,
        in_specs=[blk, blk],
        out_specs=(blk, col, col),
        out_shape=(jax.ShapeDtypeStruct(x2.shape, x2.dtype),
                   jax.ShapeDtypeStruct((_SUBLANES, 1), x2.dtype),
                   jax.ShapeDtypeStruct((_SUBLANES, 1), jnp.uint32)),
        scratch_shapes=[pltpu.VMEM((_SUBLANES, 1), x2.dtype),
                        pltpu.VMEM((_SUBLANES, 1), jnp.uint32)],
        interpret=interpret,
    )(x2, r2)

    # host stitch: exclusive segmented scan over the 128 sublane pairs
    fn = _FNS[op]

    def combine(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, fn(va, vb)), fa | fb

    tv, tf = jax.lax.associative_scan(
        combine, (totals[:, 0], anyreset[:, 0] != 0))
    neutral = _neutral(x2.dtype, op)
    carry_in = jnp.concatenate([jnp.full((1,), neutral, x2.dtype), tv[:-1]])
    carry_in = carry_in[:, None]

    return pl.pallas_call(
        functools.partial(_sweep2_kernel, op, bm, interpret),
        grid=grid,
        in_specs=[blk, blk, col],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        scratch_shapes=[pltpu.VMEM((_SUBLANES, 1), jnp.uint32)],
        interpret=interpret,
    )(partial_scan, r2, carry_in)


def _block_scan_plain(v: jax.Array, op: str, bm: int,
                      interpret: bool) -> jax.Array:
    """Inclusive (unsegmented) Hillis-Steele scan along the lane axis —
    the flags-free fast path for cumsum/cummax/cummin."""
    fn = _FNS[op]
    lane = jax.lax.broadcasted_iota(jnp.int32, v.shape, 1)
    d = 1
    while d < bm:
        vs = _roll_right(v, d, interpret)
        v = jnp.where(lane >= d, fn(vs, v), v)
        d *= 2
    return v


def _sweep1_plain_kernel(op: str, bm: int, interpret: bool, x_ref, out_ref,
                         tot_ref, carry):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        carry[:] = jnp.full(carry.shape, _neutral(carry.dtype, op))

    v = _FNS[op](carry[:], _block_scan_plain(x_ref[:], op, bm, interpret))
    out_ref[:] = v
    carry[:] = v[:, -1:]
    tot_ref[:] = carry[:]


@functools.partial(jax.jit, static_argnames=("op", "bm", "interpret"))
def _scan_padded(x2: jax.Array, op: str, bm: int, interpret: bool):
    m = x2.shape[1]
    grid = (m // bm,)
    blk = pl.BlockSpec((_SUBLANES, bm), lambda i: (0, i))
    col = pl.BlockSpec((_SUBLANES, 1), lambda i: (0, 0))
    partial_scan, totals = pl.pallas_call(
        functools.partial(_sweep1_plain_kernel, op, bm, interpret),
        grid=grid,
        in_specs=[blk],
        out_specs=(blk, col),
        out_shape=(jax.ShapeDtypeStruct(x2.shape, x2.dtype),
                   jax.ShapeDtypeStruct((_SUBLANES, 1), x2.dtype)),
        scratch_shapes=[pltpu.VMEM((_SUBLANES, 1), x2.dtype)],
        interpret=interpret,
    )(x2)
    # sweep 2 degenerates to one fused broadcast: carry_in[s] combines
    # into every element of sublane s (no segment boundaries to respect)
    fn = _FNS[op]
    tv = jax.lax.associative_scan(fn, totals[:, 0])
    neutral = _neutral(x2.dtype, op)
    carry_in = jnp.concatenate([jnp.full((1,), neutral, x2.dtype), tv[:-1]])
    return fn(carry_in[:, None], partial_scan)


def _layout_1d(x: jax.Array, op: str, interpret: "bool | None",
               block_lanes: "int | None"):
    """Shared entry layout: validate, resolve interpret, pad ``x`` with
    the op's neutral to a whole (128, m) grid of bm-lane blocks.
    Returns (x2, bm, interpret) — single-sourced so scan_1d and
    segmented_scan can never disagree on the view."""
    if x.ndim != 1 or x.dtype.itemsize != 4:
        raise ValueError("pallas scan: 1-D 32-bit input required")
    if interpret is None:
        from .. import precision
        interpret = not precision.on_tpu()
    bm = block_lanes or _BLOCK_LANES
    n = x.shape[0]
    m = -(-n // _SUBLANES)
    m = -(-m // bm) * bm
    pad = _SUBLANES * m - n
    neutral = _neutral(x.dtype, op)
    xp = jnp.concatenate([x, jnp.full((pad,), neutral, x.dtype)]) if pad else x
    return xp.reshape(_SUBLANES, m), bm, interpret, pad


def scan_1d(x: jax.Array, op: str, reverse: bool = False,
            interpret: bool | None = None,
            block_lanes: int | None = None) -> jax.Array:
    """Inclusive scan of 1-D 32-bit ``x`` (cumsum/cummax/cummin family) —
    the Pallas sweep plus one broadcast combine instead of the ~log2(n)
    passes XLA materializes for lax.cumsum/cummax/cummin on this
    backend.  ``reverse=True`` scans right-to-left (the cummin
    run_extents needs) via flips that XLA fuses into the pad/reshape."""
    n = x.shape[0]
    if n == 0:
        return x
    if reverse:
        x = jnp.flip(x)
    x2, bm, interpret, _pad = _layout_1d(x, op, interpret, block_lanes)
    out = _scan_padded(x2, op, bm, interpret).reshape(-1)[:n]
    return jnp.flip(out) if reverse else out


def segmented_scan(x: jax.Array, reset: jax.Array, op: str,
                   interpret: bool | None = None,
                   block_lanes: int | None = None) -> jax.Array:
    """Inclusive segmented scan of 1-D ``x`` (32-bit dtype) with boolean
    ``reset`` marking segment starts; drop-in for the
    ``lax.associative_scan`` inside segments.segmented_reduce_sorted.
    Padding appended by the layout (to 128*bm granularity) is neutral
    with no resets, so it never perturbs real prefixes."""
    n = x.shape[0]
    if n == 0:
        return x
    x2, bm, interpret, pad = _layout_1d(x, op, interpret, block_lanes)
    rp = reset.astype(jnp.uint32)
    if pad:
        rp = jnp.concatenate([rp, jnp.zeros((pad,), jnp.uint32)])
    out2 = _segmented_scan_padded(x2, rp.reshape(_SUBLANES, x2.shape[1]),
                                  op, bm, interpret)
    return out2.reshape(-1)[:n]
