"""Sorted-segment reductions without scatter.

``jax.ops.segment_*`` lowers to scatter-add, which XLA serializes on TPU —
profiled at ~0.8 s for a 4M-row float64/int64 scatter vs ~70 ms for a
float64 cumsum of the same length.  Every segment reduction in this
framework runs over rows *already sorted by group id* (group ids come from a
lexsort — ops/keys.dense_group_ids), so the TPU-native formulation is:

    sum over segment g  =  csum[end_g] - csum[start_g]

with segment spans recovered once per groupby from the group-boundary mask
via a cumsum-scatter compaction (ops/compact.compact_indices).  This is
the replacement for the reference's per-group accumulator State streaming
(cpp/src/cylon/groupby/hash_groupby.cpp:135-192 aggregate<op,T> and
compute/aggregate_kernels.hpp KernelTraits): the prefix sum *is* the
running state, evaluated for all groups at once.

MIN/MAX keep ``jax.ops.segment_min/max`` — their operands stay in the input
dtype (int32/float32 scatters profile ~8x faster than 64-bit ones) and have
no cancellation-safe prefix formulation.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .. import precision
from . import compact


def segment_spans(new_group: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-segment [start, end) positions from a group-boundary mask.

    ``new_group[i]`` is True where sorted row i starts a new segment
    (position 0 must be True for any nonempty input).  Returns
    (start[cap], end[cap]) where segment g spans rows [start[g], end[g]);
    ids >= the number of segments get empty spans at cap.
    """
    cap = new_group.shape[0]
    starts_perm, num = compact.compact_indices(new_group)
    iota = jnp.arange(cap, dtype=jnp.int32)
    start = jnp.where(iota < num, starts_perm, cap)
    end = jnp.concatenate([start[1:], jnp.full((1,), cap, jnp.int32)])
    return start, end


def run_extents(member: jax.Array, new_group: jax.Array,
                is_run_end: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per sorted position: (# True ``member`` rows before this position's
    run, # True ``member`` rows inside the run).  ``new_group`` marks run
    starts and ``is_run_end`` run ends over the same sorted order.  One
    cumsum + one cummax run-start broadcast + one suffix-cummin run-end
    broadcast — no scatters (the per-gid histogram scatter-add this
    replaces serializes on TPU).

    Precondition (as for segment_spans): ``new_group[0]`` must be True for
    nonempty input — otherwise ``start`` stays -1 across the first run.
    All callers satisfy it because rows_equal_adjacent forces row 0 to
    start a run.

    CYLON_TPU_SCAN=pallas routes the three scans through the two-sweep
    Pallas kernel (ops/pallas_scan.scan_1d) — same keep-or-kill A/B
    discipline as CYLON_TPU_SEGSUM=pallas; default stays XLA until the
    hardware verdict."""
    n = member.shape[0]
    if _pallas_plain_scan_selected():
        from . import pallas_scan

        incl = pallas_scan.scan_1d(member.astype(jnp.int32), "sum")
        excl = incl - member.astype(jnp.int32)
        start = pallas_scan.scan_1d(
            jnp.where(new_group, excl, jnp.int32(-1)), "max")
        end = pallas_scan.scan_1d(
            jnp.where(is_run_end, incl, jnp.int32(n + 1)), "min",
            reverse=True)
        return start, end - start
    incl = jnp.cumsum(member.astype(jnp.int32))
    excl = incl - member.astype(jnp.int32)
    start = jax.lax.cummax(jnp.where(new_group, excl, jnp.int32(-1)))
    end = jax.lax.cummin(jnp.where(is_run_end, incl, jnp.int32(n + 1)),
                         reverse=True)
    return start, end - start


_SCAN_MODE: "str | None" = None  # None = read CYLON_TPU_SCAN


def set_scan(mode: "str | None") -> None:
    """Force ``"pallas"`` or ``"xla"`` plain scans in run_extents (None =
    env).  Clears jit caches like set_segsum — the knob is read at trace
    time inside jitted pipelines, so an env flip alone would silently
    keep the cached path and poison any in-process A/B."""
    global _SCAN_MODE
    if mode not in (None, "pallas", "xla"):
        raise ValueError(f"scan mode must be pallas/xla, got {mode}")
    if mode != _SCAN_MODE:
        jax.clear_caches()
    _SCAN_MODE = mode


def plain_scan_mode() -> str:
    """The plain-scan path trace-time state selects: ``"pallas"`` |
    ``"xla"`` (public accessor — bench reporting keys on it, like
    effective_mode for segsum)."""
    return "pallas" if _pallas_plain_scan_selected() else "xla"


def _pallas_plain_scan_selected() -> bool:
    """Whether run_extents' cumsum/cummax/cummin ride the Pallas scan
    (CYLON_TPU_SCAN=pallas / set_scan).  Read at trace time."""
    if _SCAN_MODE is not None:
        return _SCAN_MODE == "pallas"
    from .. import config

    return config.knob("CYLON_TPU_SCAN") == "pallas"


def _span_take(csum0: jax.Array, pos: jax.Array) -> jax.Array:
    return jnp.take(csum0, pos, mode="clip")


def segment_sum_sorted(x: jax.Array, start: jax.Array, end: jax.Array,
                       acc_dtype=None) -> jax.Array:
    """Segment sums via prefix sum + boundary gather.  ``x`` must already be
    masked (padding/null rows zeroed).  ``acc_dtype`` defaults to the
    precision policy's accumulator (f64/i64 wide, f32/i64 narrow) — the
    prefix sum over the whole column needs the headroom even when
    per-segment sums are small."""
    if acc_dtype is None:
        if jnp.issubdtype(x.dtype, jnp.floating):
            acc_dtype = precision.float_acc()
        elif x.dtype == jnp.bool_:
            acc_dtype = jnp.int32
        else:
            acc_dtype = precision.int_acc()
    csum = jnp.cumsum(x.astype(acc_dtype))
    csum0 = jnp.concatenate([jnp.zeros((1,), acc_dtype), csum])
    return _span_take(csum0, end) - _span_take(csum0, start)


def segment_count_sorted(valid: jax.Array, start: jax.Array,
                         end: jax.Array) -> jax.Array:
    """Number of True rows per segment (int64, matching the reference's
    COUNT output type)."""
    return segment_sum_sorted(valid.astype(jnp.int32), start, end,
                              jnp.int32).astype(jnp.int64)


_SEGSUM_MODE: "str | None" = None  # None = read CYLON_TPU_SEGSUM


def set_segsum(mode: "str | None") -> None:
    """Force ``"prefix"``, ``"pallas"`` or ``"scatter"`` segment reductions
    (None = env).  ``pallas`` is prefix semantics through the two-sweep
    Pallas kernel (ops/pallas_scan.py) instead of lax.associative_scan.
    Clears jit caches like precision.set_accumulation — the knob is read
    at trace time, so cached kernels would otherwise keep the old path."""
    global _SEGSUM_MODE
    if mode not in (None, "prefix", "pallas", "scatter"):
        raise ValueError(
            f"segsum mode must be prefix/pallas/scatter, got {mode}")
    if mode != _SEGSUM_MODE:
        jax.clear_caches()
    _SEGSUM_MODE = mode


def prefix_reductions_enabled() -> bool:
    """Whether narrow-mode float/min/max segment reductions use the
    segmented scan below instead of scatter-adds.  CYLON_TPU_SEGSUM
    (or set_segsum) forces "prefix"/"scatter"; the default is
    backend-aware like compact.permute_mode — prefix on TPU-family
    backends (round-4 hardware: XLA:TPU serializes scatters; a same-size
    scan is log-depth and bandwidth-bound), scatter elsewhere (XLA:CPU
    scatter-adds are cheap and its associative_scan is not).  The
    64-bit carve-outs in groupby._segment_aggregate are mode-independent:
    integer sums and wide accumulators keep the scatter in every mode
    (64-bit prefix fusions have crashed this TPU backend).  Read at trace
    time: set it before the first jitted compute or use set_segsum,
    which clears the jit caches."""
    if _SEGSUM_MODE is not None:
        return _SEGSUM_MODE in ("prefix", "pallas")
    from .. import config

    mode = config.knob("CYLON_TPU_SEGSUM")
    if mode in ("prefix", "pallas", "scatter"):
        return mode != "scatter"
    return jax.default_backend() in ("tpu", "axon")


def effective_mode() -> str:
    """The segment-reduction path trace-time state selects:
    ``"pallas"`` | ``"prefix"`` | ``"scatter"`` (public accessor — bench
    reporting keys on it)."""
    if not prefix_reductions_enabled():
        return "scatter"
    return "pallas" if _pallas_scan_selected() else "prefix"


def _pallas_scan_selected() -> bool:
    """Whether the scan-free-of-associative_scan Pallas kernel backs
    segmented_reduce_sorted (CYLON_TPU_SEGSUM=pallas / set_segsum).  Not
    a default anywhere yet: the kernel's ~2-sweep HBM traffic vs the
    scan's ~log2(n) materialized passes is a theoretical win awaiting
    the hardware A/B (battery step; keep-or-kill like radix)."""
    if _SEGSUM_MODE is not None:
        return _SEGSUM_MODE == "pallas"
    from .. import config

    return config.knob("CYLON_TPU_SEGSUM") == "pallas"


def segmented_reduce_sorted(x: jax.Array, new_group: jax.Array,
                            end: jax.Array, op: str) -> jax.Array:
    """Per-segment reduction over rows already grouped into runs, with NO
    scatter: a segmented ``lax.associative_scan`` over (value, reset-flag)
    pairs carries each run's running reduction — the combine restarts at
    run boundaries, so rounding stays per-segment exactly like the
    scatter-add it replaces — and the per-run total is gathered at the
    run's last row.  ``x`` must already be masked (null/padding rows set
    to the op's neutral element).  ``op``: 'sum' | 'min' | 'max'.

    Returns values indexed by segment id (same contract as
    ``jax.ops.segment_*`` with ``num_segments = len(x)``); ids past the
    number of segments read the clipped last row (callers mask by group
    liveness, as they already do for the scatter path)."""
    if _pallas_scan_selected() and x.dtype.itemsize == 4:
        from . import pallas_scan

        run_val = pallas_scan.segmented_scan(x, new_group, op)
        return jnp.take(run_val, end - 1, mode="clip")

    fns = {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}
    fn = fns[op]

    def combine(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, fn(va, vb)), fa | fb

    run_val, _ = jax.lax.associative_scan(combine, (x, new_group))
    return jnp.take(run_val, end - 1, mode="clip")
