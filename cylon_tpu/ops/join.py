"""Local join kernel (sort-merge over dense key ids).

TPU-native replacement for the reference's local join layer
(cpp/src/cylon/join/join.cpp:31-763: type-dispatched sort-merge and
``std::unordered_multimap`` hash joins; arrow/arrow_hash_kernels.hpp
build/probe; join_utils.cpp build_final_table).  Design:

1. One fused multi-key ``lax.sort`` over the union of both tables' key rows
   — the kernel's ONLY sort — subsumes both the comparator machinery and
   the hash table, works for any column type mix, and has no
   data-dependent control flow.
2. Per-left-row match ranges [lo, lo+matches) into the key-ordered right
   side are prefix arithmetic over the sorted order (cumsum + segmented
   broadcasts); the key-ordered right permutation is a compaction of the
   combined sort's right entries — the merge step without a second sort.
3. The variable-size expansion (a left row with k matches emits k rows;
   outer variants emit null-filled singletons, the reference's -1 fills,
   join.cpp:179-235) is realized as a static-capacity gather: each emitting
   row scatters its index at its first output slot and a ``cummax`` forward
   fill maps every slot back to its (left row, match ordinal) — one scan,
   no sort.

Everything is a static-shape XLA program; the only dynamic quantity is the
returned row count.  ``join_row_count`` exposes the exact output size so the
host can pick (and cache) an output capacity before running ``join_gather``.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from ..config import JoinType
from . import common, compact, segments

_I32_MAX = jnp.iinfo(jnp.int32).max


def _match_ranges(cols_l, count_l, cols_r, count_r, left_on, right_on,
                  join_type: JoinType):
    """Compute per-left-row match ranges into a gid-ordered right table.

    One fused multi-key ``lax.sort`` over the union of both tables' key rows
    is the ONLY sort in the kernel (the reference's hash build/probe,
    join.cpp:448-513, and its comparator sorts, join.cpp:78-434, both
    collapse into it).  Everything else is prefix arithmetic over the
    sorted order:

    - a left row's match range [lo, lo+matches) = (# live right rows before
      its key run, # live right rows inside it) — cumsum + segmented
      broadcast (cummax of run-start values / suffix-cummin of run-end
      values), replacing per-gid histogram scatter-adds;
    - the gid-ordered right permutation falls out of the combined sort by
      compacting its right-side entries (cumsum-scatter) — no second sort;
    - per-original-row results come back through one scatter along the sort
      permutation.

    Returns (lo, matches, perm_r, live_l, unmatched_right_mask,
    left_key_order) where left_key_order lists left row ids in key order
    (used by key_grouped join output to avoid another sort).
    """
    cap_l = cols_l[0].data.shape[0]
    cap_r = cols_r[0].data.shape[0]
    perm, _, new_group, is_run_end, live_sorted = common.combined_sorted_runs(
        cols_l, count_l, cols_r, count_r, left_on, right_on)
    is_right = perm >= cap_l

    # live right rows before / inside each position's key run
    lo_sorted, matches_sorted = segments.run_extents(
        is_right & live_sorted, new_group, is_run_end)

    fields = [lo_sorted, matches_sorted]
    if join_type in (JoinType.RIGHT, JoinType.FULL_OUTER):
        _, left_in_run = segments.run_extents(
            (~is_right) & live_sorted, new_group, is_run_end)
        fields.append((left_in_run == 0).astype(jnp.int32))

    # map per-sorted-position results back to original rows: one fused
    # key-sort on TPU, one scatter per field elsewhere (compact.permute_mode)
    back = compact.inverse_permute(perm, *fields)

    live_l = jnp.arange(cap_l, dtype=jnp.int32) < count_l
    live_r = jnp.arange(cap_r, dtype=jnp.int32) < count_r
    lo = back[0][:cap_l]
    matches = jnp.where(live_l, back[1][:cap_l], 0)
    if join_type in (JoinType.RIGHT, JoinType.FULL_OUTER):
        unmatched_r = live_r & (back[2][cap_l:] == 1)
    else:
        unmatched_r = jnp.zeros((cap_r,), bool)

    # gid-ordered right permutation AND left key order from ONE stable
    # partition of the combined sort's entries: exactly cap_r of them are
    # right-side (perm is a full permutation), so the front cap_r slots
    # are the right rows in key order (the order ``lo`` indexes into) and
    # the tail cap_l slots are the left rows in key order (key_grouped
    # output) — half the compaction cost, which in sort mode is a full
    # combined-length sort per call
    part, _ = compact.partition_indices(is_right)
    perm_r = jnp.take(perm, part[:cap_r]) - cap_l
    left_key_order = jnp.take(perm, part[cap_r:])
    return lo, matches, perm_r, live_l, unmatched_r, left_key_order


def _emission(matches, live_l, join_type: JoinType):
    outer_left = join_type in (JoinType.LEFT, JoinType.FULL_OUTER)
    emit = jnp.where(live_l & (matches == 0), jnp.int32(1 if outer_left else 0), matches)
    csum = jnp.cumsum(emit, dtype=jnp.int32)
    total = csum[-1] if emit.shape[0] else jnp.zeros((), jnp.int32)
    return emit, csum, total


def _ranges(cols_l, count_l, cols_r, count_r, left_on, right_on, join_type,
            algorithm: str):
    if algorithm == "hash":
        from . import hash_join

        return hash_join.match_ranges_hash(
            cols_l, count_l, cols_r, count_r, left_on, right_on,
            join_type) + (None,)
    return _match_ranges(cols_l, count_l, cols_r, count_r, left_on, right_on,
                         join_type)


@partial(jax.jit, static_argnames=("left_on", "right_on", "join_type",
                                   "algorithm"))
def join_row_count(cols_l: Tuple[Column, ...], count_l,
                   cols_r: Tuple[Column, ...], count_r,
                   left_on: Tuple[int, ...], right_on: Tuple[int, ...],
                   join_type: JoinType, algorithm: str = "sort"):
    """Exact output row count of the join (device scalar)."""
    lo, matches, perm_r, live_l, unmatched_r, _ = _ranges(
        cols_l, count_l, cols_r, count_r, left_on, right_on, join_type,
        algorithm)
    _, _, total = _emission(matches, live_l, join_type)
    if join_type in (JoinType.RIGHT, JoinType.FULL_OUTER):
        total = total + jnp.sum(unmatched_r, dtype=jnp.int32)
    return total


@partial(jax.jit, static_argnames=("left_on", "right_on", "join_type",
                                   "out_capacity", "algorithm",
                                   "key_grouped", "project"))
def join_gather(cols_l: Tuple[Column, ...], count_l,
                cols_r: Tuple[Column, ...], count_r,
                left_on: Tuple[int, ...], right_on: Tuple[int, ...],
                join_type: JoinType, out_capacity: int,
                algorithm: str = "sort", key_grouped: bool = False,
                project: "Tuple[int, ...] | None" = None):
    """Produce gathered output columns (left columns ++ right columns) with
    capacity ``out_capacity`` and the dynamic output row count.

    ``key_grouped=True`` (INNER only): rows with equal join keys come out
    adjacent, so a downstream group-by on the key can use the boundary-scan
    pipeline kernel instead of re-sorting the whole output.  Grouping
    reorders left rows into key order — on the sort path that order falls
    out of the combined lexsort (left_key_order) and matched rows are
    front-packed with one stable partition (no extra sort); the hash path
    has no key-sorted order, so it sorts left rows by their match-range
    offset ``lo``, which uniquely identifies the key group for matched
    rows.  Either way the multi-operand lexsort of the (larger) join
    output downstream is saved."""
    lo, matches, perm_r, live_l, unmatched_r, left_key_order = _ranges(
        cols_l, count_l, cols_r, count_r, left_on, right_on, join_type,
        algorithm)
    perm_l = None
    if key_grouped:
        if join_type != JoinType.INNER:
            raise ValueError("key_grouped join output requires INNER")
        cap_l = lo.shape[0]
        if left_key_order is None:  # hash path: order by match-range offset
            order_key = jnp.where(live_l & (matches > 0), lo, _I32_MAX)
            iota_l = jnp.arange(cap_l, dtype=jnp.int32)
            _, perm_l = jax.lax.sort((order_key, iota_l), num_keys=1,
                                     is_stable=True)
        else:  # sort path: key order is known; partition matched to front
            lm = jnp.take(live_l & (matches > 0), left_key_order)
            part, _ = compact.partition_indices(lm)
            perm_l = jnp.take(left_key_order, part)
        lo = jnp.take(lo, perm_l)
        matches = jnp.take(matches, perm_l)
        live_l = jnp.take(live_l, perm_l)
    emit, csum, total = _emission(matches, live_l, join_type)

    k = jnp.arange(out_capacity, dtype=jnp.int32)
    cap_l = emit.shape[0]
    base_l = csum - emit
    if compact.permute_mode() == "sort":
        # slot -> left row is searchsorted(csum, k, 'right') — csum is
        # monotone, so slot k's emitter is the count of rows with
        # csum <= k.  Realized as a sort-merge (sorts beat scatters on
        # TPU; see compact.count_leq_dense).
        li = compact.count_leq_dense(csum, out_capacity)
    else:
        # scatter + cummax forward fill: each emitting row drops its index
        # at its first output slot (bases are distinct and ascending),
        # cummax fills the run — one scan, one scatter
        iota_l = jnp.arange(cap_l, dtype=jnp.int32)
        marker = jnp.full((out_capacity,), -1, jnp.int32)
        marker = marker.at[jnp.where(emit > 0, base_l, out_capacity)].max(
            iota_l, mode="drop")
        li = jax.lax.cummax(marker)
    li = jnp.clip(li, 0, cap_l - 1)
    base = jnp.take(base_l, li)
    within = k - base
    matched = jnp.take(matches, li) > 0
    r_sorted_pos = jnp.take(lo, li) + within
    ridx_inner = jnp.take(perm_r, jnp.clip(r_sorted_pos, 0, perm_r.shape[0] - 1))

    in_main = k < total
    lvalid = in_main
    rvalid = in_main & matched
    lidx = li if perm_l is None else jnp.take(perm_l, li)
    ridx = jnp.where(rvalid, ridx_inner, 0)

    out_count = total
    if join_type in (JoinType.RIGHT, JoinType.FULL_OUTER):
        perm_u, m = compact.compact_indices(unmatched_r)
        tail = k - total
        in_tail = (k >= total) & (tail < m)
        ridx_tail = jnp.take(perm_u, jnp.clip(tail, 0, perm_u.shape[0] - 1))
        ridx = jnp.where(in_tail, ridx_tail, ridx)
        rvalid = rvalid | in_tail
        lvalid = lvalid & ~in_tail
        out_count = total + m

    # projection pushdown: materialize ONLY the requested output columns
    # (indices into left ++ right), in the requested order — a pruned
    # column skips its whole out_capacity-sized gather+write (the
    # reference prunes after materializing, join_utils.cpp
    # build_final_table; here pruning happens inside the kernel)
    n_l = len(cols_l)
    n_out = n_l + len(cols_r)
    if project is None:
        project = tuple(range(n_out))
    bad = [j for j in project if not 0 <= j < n_out]
    if bad:
        raise ValueError(f"project indices {bad} out of range for "
                         f"{n_out} output columns (left {n_l} ++ right "
                         f"{n_out - n_l}; negatives not supported)")
    out = []
    for j in project:
        if j < n_l:
            out.append(cols_l[j].take(lidx, valid_mask=lvalid))
        else:
            out.append(cols_r[j - n_l].take(ridx, valid_mask=rvalid))
    return tuple(out), out_count
