"""Local join kernel (sort-merge over dense key ids).

TPU-native replacement for the reference's local join layer
(cpp/src/cylon/join/join.cpp:31-763: type-dispatched sort-merge and
``std::unordered_multimap`` hash joins; arrow/arrow_hash_kernels.hpp
build/probe; join_utils.cpp build_final_table).  Design:

1. One fused multi-key ``lax.sort`` over the union of both tables' key rows
   assigns a dense int32 group id per distinct key
   (ops/common.combined_group_ids) — this subsumes both the comparator
   machinery and the hash table, works for any column type mix, and has no
   data-dependent control flow.
2. Right rows are sorted by group id; per left row a vectorized
   ``searchsorted`` yields its match range [lo, hi) — the merge step.
3. The variable-size expansion (a left row with k matches emits k rows;
   outer variants emit null-filled singletons, the reference's -1 fills,
   join.cpp:179-235) is realized as a static-capacity gather: each emitting
   row scatters its index at its first output slot and a ``cummax`` forward
   fill maps every slot back to its (left row, match ordinal) — one scan,
   no sort.

Everything is a static-shape XLA program; the only dynamic quantity is the
returned row count.  ``join_row_count`` exposes the exact output size so the
host can pick (and cache) an output capacity before running ``join_gather``.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from ..config import JoinType
from . import common, compact

_I32_MAX = jnp.iinfo(jnp.int32).max


def _match_ranges(cols_l, count_l, cols_r, count_r, left_on, right_on,
                  join_type: JoinType):
    """Compute per-left-row match ranges into a gid-sorted right table.

    Both sides share dense group ids from one combined lexsort, so the match
    range of a left row is pure integer arithmetic: a per-gid histogram of
    live right rows (one int32 scatter-add — 64-bit scatters and
    searchsorted binary searches both profile ~10x slower on TPU) prefix-
    summed into start offsets.  Returns
    (lo, matches, perm_r, live_l, unmatched_right_mask).
    """
    cap_l = cols_l[0].data.shape[0]
    cap_r = cols_r[0].data.shape[0]
    gid_l, gid_r, *_ = common.combined_group_ids(
        cols_l, count_l, cols_r, count_r, left_on, right_on)

    live_l = jnp.arange(cap_l, dtype=jnp.int32) < count_l
    live_r = jnp.arange(cap_r, dtype=jnp.int32) < count_r
    n_gid = cap_l + cap_r

    # per-gid live right-row histogram -> start offsets in gid-sorted order
    ones_r = live_r.astype(jnp.int32)
    counts_r = jnp.zeros((n_gid,), jnp.int32).at[gid_r].add(ones_r)
    csum_r = jnp.cumsum(counts_r, dtype=jnp.int32)
    rstart = jnp.concatenate([jnp.zeros((1,), jnp.int32), csum_r[:-1]])
    lo = jnp.take(rstart, gid_l)
    matches = jnp.where(live_l, jnp.take(counts_r, gid_l), 0)

    # right rows ordered by gid, live rows first (padding exiled to +inf);
    # rstart[g] indexes into exactly this order
    rkey = jnp.where(live_r, gid_r, _I32_MAX)
    iota_r = jnp.arange(cap_r, dtype=jnp.int32)
    _, perm_r = jax.lax.sort((rkey, iota_r), num_keys=1, is_stable=True)

    # right rows with no left partner — only RIGHT/FULL_OUTER pay for it
    if join_type in (JoinType.RIGHT, JoinType.FULL_OUTER):
        counts_l = jnp.zeros((n_gid,), jnp.int32).at[gid_l].add(
            live_l.astype(jnp.int32))
        unmatched_r = live_r & (jnp.take(counts_l, gid_r) == 0)
    else:
        unmatched_r = jnp.zeros((cap_r,), bool)
    return lo, matches, perm_r, live_l, unmatched_r


def _emission(matches, live_l, join_type: JoinType):
    outer_left = join_type in (JoinType.LEFT, JoinType.FULL_OUTER)
    emit = jnp.where(live_l & (matches == 0), jnp.int32(1 if outer_left else 0), matches)
    csum = jnp.cumsum(emit, dtype=jnp.int32)
    total = csum[-1] if emit.shape[0] else jnp.zeros((), jnp.int32)
    return emit, csum, total


def _ranges(cols_l, count_l, cols_r, count_r, left_on, right_on, join_type,
            algorithm: str):
    if algorithm == "hash":
        from . import hash_join

        return hash_join.match_ranges_hash(
            cols_l, count_l, cols_r, count_r, left_on, right_on, join_type)
    return _match_ranges(cols_l, count_l, cols_r, count_r, left_on, right_on,
                         join_type)


@partial(jax.jit, static_argnames=("left_on", "right_on", "join_type",
                                   "algorithm"))
def join_row_count(cols_l: Tuple[Column, ...], count_l,
                   cols_r: Tuple[Column, ...], count_r,
                   left_on: Tuple[int, ...], right_on: Tuple[int, ...],
                   join_type: JoinType, algorithm: str = "sort"):
    """Exact output row count of the join (device scalar)."""
    lo, matches, perm_r, live_l, unmatched_r = _ranges(
        cols_l, count_l, cols_r, count_r, left_on, right_on, join_type,
        algorithm)
    _, _, total = _emission(matches, live_l, join_type)
    if join_type in (JoinType.RIGHT, JoinType.FULL_OUTER):
        total = total + jnp.sum(unmatched_r, dtype=jnp.int32)
    return total


@partial(jax.jit, static_argnames=("left_on", "right_on", "join_type",
                                   "out_capacity", "algorithm",
                                   "key_grouped"))
def join_gather(cols_l: Tuple[Column, ...], count_l,
                cols_r: Tuple[Column, ...], count_r,
                left_on: Tuple[int, ...], right_on: Tuple[int, ...],
                join_type: JoinType, out_capacity: int,
                algorithm: str = "sort", key_grouped: bool = False):
    """Produce gathered output columns (left columns ++ right columns) with
    capacity ``out_capacity`` and the dynamic output row count.

    ``key_grouped=True`` (INNER only): rows with equal join keys come out
    adjacent, so a downstream group-by on the key can use the boundary-scan
    pipeline kernel instead of re-sorting the whole output.  Grouping
    reorders left rows by their match-range offset ``lo`` — for matched
    rows ``lo`` uniquely identifies the key group under both algorithms
    (distinct keys with right rows occupy distinct ranges), and only
    matched rows emit in an inner join.  Costs one extra single-key int32
    sort of the left side; saves the multi-operand lexsort of the (larger)
    join output downstream."""
    lo, matches, perm_r, live_l, unmatched_r = _ranges(
        cols_l, count_l, cols_r, count_r, left_on, right_on, join_type,
        algorithm)
    perm_l = None
    if key_grouped:
        if join_type != JoinType.INNER:
            raise ValueError("key_grouped join output requires INNER")
        cap_l = lo.shape[0]
        order_key = jnp.where(live_l & (matches > 0), lo, _I32_MAX)
        iota_l = jnp.arange(cap_l, dtype=jnp.int32)
        _, perm_l = jax.lax.sort((order_key, iota_l), num_keys=1,
                                 is_stable=True)
        lo = jnp.take(lo, perm_l)
        matches = jnp.take(matches, perm_l)
        live_l = jnp.take(live_l, perm_l)
    emit, csum, total = _emission(matches, live_l, join_type)

    k = jnp.arange(out_capacity, dtype=jnp.int32)
    # slot -> left row via scatter + cummax forward fill: each emitting row
    # drops its index at its first output slot (bases are distinct and
    # ascending), cummax fills the run — one scan instead of the
    # searchsorted merge-sort over out_capacity + cap_l rows
    cap_l = emit.shape[0]
    iota_l = jnp.arange(cap_l, dtype=jnp.int32)
    base_l = csum - emit
    marker = jnp.full((out_capacity,), -1, jnp.int32)
    marker = marker.at[jnp.where(emit > 0, base_l, out_capacity)].max(
        iota_l, mode="drop")
    li = jax.lax.cummax(marker)
    li = jnp.clip(li, 0, cap_l - 1)
    base = jnp.take(base_l, li)
    within = k - base
    matched = jnp.take(matches, li) > 0
    r_sorted_pos = jnp.take(lo, li) + within
    ridx_inner = jnp.take(perm_r, jnp.clip(r_sorted_pos, 0, perm_r.shape[0] - 1))

    in_main = k < total
    lvalid = in_main
    rvalid = in_main & matched
    lidx = li if perm_l is None else jnp.take(perm_l, li)
    ridx = jnp.where(rvalid, ridx_inner, 0)

    out_count = total
    if join_type in (JoinType.RIGHT, JoinType.FULL_OUTER):
        perm_u, m = compact.compact_indices(unmatched_r)
        tail = k - total
        in_tail = (k >= total) & (tail < m)
        ridx_tail = jnp.take(perm_u, jnp.clip(tail, 0, perm_u.shape[0] - 1))
        ridx = jnp.where(in_tail, ridx_tail, ridx)
        rvalid = rvalid | in_tail
        lvalid = lvalid & ~in_tail
        out_count = total + m

    out_l = tuple(c.take(lidx, valid_mask=lvalid) for c in cols_l)
    out_r = tuple(c.take(ridx, valid_mask=rvalid) for c in cols_r)
    return out_l + out_r, out_count
