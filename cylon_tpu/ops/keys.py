"""Row-key encoding for sort/equality kernels.

The reference compares rows through virtual-dispatch comparator objects
(cpp/src/cylon/arrow/arrow_comparator.hpp:25-189) and sorts via index
quicksorts (arrow/arrow_kernels.hpp:180-314, util/sort.hpp).  On TPU the
idiomatic equivalent is ``jax.lax.sort`` with **multiple key operands**
(lexicographic, one fused XLA sort), so this module turns typed columns into
flat sortable operands:

- numeric column  -> [validity_key, data]  (nulls ordered first/last)
- string column   -> [validity_key, w0, w1, ...] where wi are big-endian
  uint64 words packed from the zero-padded byte matrix; zero padding keeps
  bytewise lexicographic order identical to string order.
- the row-padding flag is always the first operand so rows beyond the dynamic
  row count sort to the back of every permutation.

Row equality (multi-column, the job of TableRowComparator) becomes adjacent
comparison of these operands after a lexsort, which then yields dense group
ids via a prefix sum — the backbone of groupby/unique/set-ops/joins here.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from . import compact, radix


def pack_string_words(data: jax.Array) -> List[jax.Array]:
    """Pack a uint8[n, L] byte matrix into ceil(L/8) uint64[n] big-endian
    words; lexicographic order on the word tuple == bytewise order."""
    n, width = data.shape
    pad = (-width) % 8
    if pad:
        data = jnp.concatenate([data, jnp.zeros((n, pad), jnp.uint8)], axis=1)
    nwords = data.shape[1] // 8
    words = data.reshape(n, nwords, 8).astype(jnp.uint64)
    shifts = jnp.array([56, 48, 40, 32, 24, 16, 8, 0], jnp.uint64)
    packed = jnp.sum(words << shifts, axis=2, dtype=jnp.uint64)
    return [packed[:, i] for i in range(nwords)]


def column_operands(col: Column, *, nulls_first: bool = True,
                    with_validity: bool = True) -> List[jax.Array]:
    """Sortable operands for one column (most-significant first).  Boolean
    operands stay ``bool`` so the bit-packer can store them in 1 bit."""
    ops: List[jax.Array] = []
    if with_validity:
        if nulls_first:
            ops.append(col.validity)       # invalid(0) < valid(1)
        else:
            ops.append(~col.validity)      # valid(0) < invalid(1)
    if col.is_string:
        ops.extend(pack_string_words(col.data))
    else:
        ops.append(col.data)
    return ops


def padding_operand(capacity: int, row_count) -> jax.Array:
    """First sort operand: False for live rows, True for padding, so padding
    always lands at the back."""
    return jnp.arange(capacity, dtype=jnp.int32) >= row_count


def build_operands(cols: Sequence[Column], row_count, capacity: int,
                   *, ascending: Sequence[bool] | None = None,
                   nulls_first: bool = True) -> List[jax.Array]:
    """All sort operands for a multi-column key, padding flag first.

    Descending order per column is realized by bit-flipping that column's
    operands (works for the unsigned encodings; for signed/float data we
    negate via the order-preserving unsigned reinterpretation).
    """
    ops: List[jax.Array] = [padding_operand(capacity, row_count)]
    for i, col in enumerate(cols):
        col_ops = column_operands(col, nulls_first=nulls_first)
        if ascending is not None and not ascending[i]:
            # flip the DATA order only: null placement is governed by
            # nulls_first alone, independent of per-column direction
            # (pandas na_position semantics — inverting the validity
            # operand would silently send nulls to the other end on
            # descending columns)
            col_ops = [col_ops[0]] + [_invert_operand(o)
                                      for o in col_ops[1:]]
        ops.extend(col_ops)
    return ops


def _invert_operand(x: jax.Array) -> jax.Array:
    """Order-reversing transform for one operand."""
    if x.dtype == jnp.bool_:
        return ~x
    if jnp.issubdtype(x.dtype, jnp.unsignedinteger):
        return ~x
    if jnp.issubdtype(x.dtype, jnp.signedinteger):
        return -1 - x  # maps min->max order-reversed without overflow on wrap
    if jnp.issubdtype(x.dtype, jnp.floating):
        return -x
    return ~x.astype(jnp.uint8)


_UINT_OF = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def _ordered_unsigned(x: jax.Array) -> Tuple[jax.Array, int]:
    """(unsigned array, bit width) in an order-preserving encoding: signed
    ints bias by the sign bit, floats use the total-order bit trick (NaNs
    sort to the extremes, matching lax.sort's totalorder comparator)."""
    dt = x.dtype
    if dt == jnp.bool_:
        return x, 1  # 0/1 — one bit in the packed word
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        return x, dt.itemsize * 8
    w = dt.itemsize * 8
    u = _UINT_OF[dt.itemsize]
    if jnp.issubdtype(dt, jnp.floating):
        # canonicalize before the bitcast so equality matches value
        # semantics: -0.0 groups with +0.0, and every NaN payload collapses
        # to one key (pandas-style: NaNs form a single group)
        x = jnp.where(x == 0, jnp.zeros((), dt), x)
        x = jnp.where(jnp.isnan(x), jnp.full((), jnp.nan, dt), x)
        bits = jax.lax.bitcast_convert_type(x, u)
        top = jnp.asarray(1 << (w - 1), u)
        neg = (bits >> jnp.asarray(w - 1, u)) == 1
        return jnp.where(neg, ~bits, bits | top), w
    bits = jax.lax.bitcast_convert_type(x, u)
    top = jnp.asarray(1 << (w - 1), u)
    if jnp.issubdtype(dt, jnp.signedinteger):
        return bits ^ top, w
    raise TypeError(f"unsupported operand dtype {dt}")


def pack_operands(operands: Sequence[jax.Array]) -> List[jax.Array]:
    """Greedily bit-pack the operands' order-preserving unsigned encodings
    into uint32 words (fields MSB-first within a word): lexicographic
    order AND rowwise equality over the packed words equal those over the
    original operand list, while the sort carries fewer arrays and
    comparisons.  E.g. [pad bool, validity bool, i16 key] packs to one
    18-bit-in-u32 word, so the sort carries 1 operand instead of 3.  64-bit
    fields (i64/f64 data, packed string words) pass through as standalone
    u64 operands — the 32-bit word target keeps narrow-mode programs free
    of emulated 64-bit arrays for 32-bit data."""
    return _pack_encoded([_ordered_unsigned(op) for op in operands])


def _pack_encoded(enc: Sequence[Tuple[jax.Array, int]]) -> List[jax.Array]:
    out: List[jax.Array] = []
    cur = None
    used = 0

    def flush():
        nonlocal cur, used
        if cur is not None:
            out.append(cur)
        cur, used = None, 0

    for bits, w in enc:
        if w >= 64:
            flush()
            out.append(bits)
            continue
        b32 = bits.astype(jnp.uint32)
        if cur is None or used + w > 32:
            flush()
            cur, used = b32, w
        else:
            cur = (cur << jnp.uint32(w)) | b32
            used += w
    flush()
    return out


def lexsort_indices(operands: Sequence[jax.Array], capacity: int) -> Tuple[jax.Array, List[jax.Array]]:
    """Stable lexicographic argsort over bit-packed operands.  Returns
    (permutation, sorted PACKED operands) — the packed words support
    adjacency/equality tests (rows_equal_adjacent, dense_group_ids) but
    not per-field access; gather original fields through the permutation
    when field values are needed.

    Fast path: when every key field plus a row index fits 64 bits (e.g.
    padding + validity + a 32-bit key + up to 30 index bits — the
    hash-partitioned join/groupby shape), the sort runs over one or two
    u32 words with the index in the low bits: no payload operand, and
    uniqueness makes stability free.  The words stay 32-bit — narrow
    mode's zero-64-bit-arrays guarantee holds (64-bit ops are emulated on
    TPU)."""
    enc = [_ordered_unsigned(o) for o in operands]
    total_bits = sum(w for _, w in enc)
    idx_bits = compact.index_bits(capacity)
    if total_bits + idx_bits <= 64:
        # assemble the logical (total+idx)-bit value MSB-first across
        # (hi, lo) u32 words with static double-word shifts
        hi = jnp.zeros((capacity,), jnp.uint32)
        lo = jnp.zeros((capacity,), jnp.uint32)

        def append(bits_u32, w: int):
            nonlocal hi, lo
            if w == 32:
                hi, lo = lo, bits_u32
            else:
                hi = (hi << jnp.uint32(w)) | (lo >> jnp.uint32(32 - w))
                lo = (lo << jnp.uint32(w)) | bits_u32

        for bits, w in enc:
            append(bits.astype(jnp.uint32), w)
        append(jnp.arange(capacity, dtype=jnp.uint32), idx_bits)

        use_radix = radix.sort_mode() == "radix"
        if total_bits + idx_bits <= 32:  # everything landed in lo
            if use_radix:
                _, s_lo = radix.radix_sort_packed(
                    None, lo, idx_bits, idx_bits + total_bits)
            else:
                s_lo = jax.lax.sort(lo, is_stable=False)  # keys are unique
            perm = (s_lo & jnp.uint32((1 << idx_bits) - 1)).astype(jnp.int32)
            return perm, [s_lo >> jnp.uint32(idx_bits)]
        if use_radix:
            s_hi, s_lo = radix.radix_sort_packed(
                hi, lo, idx_bits, idx_bits + total_bits)
        else:
            s_hi, s_lo = jax.lax.sort((hi, lo), num_keys=2, is_stable=False)
        perm = (s_lo & jnp.uint32((1 << idx_bits) - 1)).astype(jnp.int32)
        return perm, [s_hi, s_lo >> jnp.uint32(idx_bits)]
    packed = _pack_encoded(enc)
    iota = jnp.arange(capacity, dtype=jnp.int32)
    sorted_all = jax.lax.sort(tuple(packed) + (iota,),
                              num_keys=len(packed), is_stable=True)
    perm = sorted_all[-1]
    return perm, list(sorted_all[:-1])


def rows_equal_adjacent(sorted_operands: Sequence[jax.Array]) -> jax.Array:
    """bool[n]: row i has identical key to row i-1 (row 0 -> False).

    Operand 0 is the padding flag, which participates: a padding row never
    equals a live row, while padding rows equal each other (harmless — they
    are masked out downstream)."""
    eq = None
    for op in sorted_operands:
        e = jnp.concatenate([jnp.zeros((1,), bool), op[1:] == op[:-1]])
        eq = e if eq is None else (eq & e)
    return eq


def dense_group_ids(sorted_operands: Sequence[jax.Array]) -> Tuple[jax.Array, jax.Array]:
    """Dense group ids over sorted rows: (group_id[n], num_groups_incl_padding).

    group_id is 0-based and nondecreasing along the sorted order; rows with
    equal keys share an id.  ``num_groups`` counts all distinct keys present
    including the single padding group when padding rows exist; callers mask
    with the live-row count."""
    eq = rows_equal_adjacent(sorted_operands)
    new_group = ~eq
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    num = gid[-1] + 1 if gid.shape[0] else jnp.zeros((), jnp.int32)
    return gid, num
