"""The ONE way to enable the persistent XLA compile cache.

Root cause of the long-standing "full-tree XLA:CPU segfault"
(tools/full_tree_cold.sh reproduced it 2026-07-31, faulthandler stack in
PERF.md): every driver pointed at a SINGLE shared ``.jax_cache`` dir, so
executables serialized by processes with one XLA CPU target config (the
axon/TPU-attached bench worker and watcher probe embed pseudo-features
like ``+prefer-no-scatter``) were deserialized by pure-CPU test
processes with another — ``backend.deserialize_executable`` SIGSEGVs on
the mismatch (the cpu_aot_loader "machine type doesn't match … could
lead to execution errors such as SIGILL" warning is the polite version).
The crash needed the whole tree because ``examples/util.default_ctx``
enabled the cache mid-run for every later test, unconditionally — which
is also why each crashing test passed in isolation.

Fix: cache dirs are PER BACKEND (``.jax_cache_cpu``, ``.jax_cache_axon``,
…), so no process ever deserializes an executable produced under a
different target config, and CYLON_TEST_NO_COMPILE_CACHE=1 is honored by
every enabler, not just the test conftest.
"""
from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def enable_persistent_compile_cache(min_compile_secs: float = 5,
                                    root: "str | None" = None) -> "str | None":
    """Point jax's persistent compile cache at ``<root>/.jax_cache_<backend>``
    and return the directory (None when disabled via
    CYLON_TEST_NO_COMPILE_CACHE=1 or when jax is unavailable).  Safe to
    call multiple times; the backend suffix comes from
    ``jax.default_backend()``, which initializes the backend — call it
    only in driver/harness code, never at library import time."""
    if os.environ.get("CYLON_TEST_NO_COMPILE_CACHE") == "1":
        return None
    try:
        import jax

        path = os.path.join(root or _REPO_ROOT,
                            f".jax_cache_{jax.default_backend()}")
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        # record the enablement in the observability snapshot so a trace
        # artifact says whether its compiles could have been cache hits
        # (imported here, not at module top: this enabler must stay usable
        # before the package imports)
        from cylon_tpu.obs import metrics as _obs_metrics

        _obs_metrics.gauge_set("compile_cache.enabled", 1)
        return path
    except Exception as e:
        # visible, not fatal: a silently absent cache costs ~30s/kernel
        # per tunnel window (smoke) and re-compiles everywhere else
        import sys

        print(f"[compile_cache] persistent cache unavailable: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
        return None
