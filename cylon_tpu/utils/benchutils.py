"""Benchmark helpers (reference: python/pycylon/util/benchutils.py —
``benchmark_with_repitions`` decorator used by the op micro-benchmarks in
python/examples/op_benchmark/)."""
from __future__ import annotations

import time
from typing import Callable


def time_conversion(t_ns: float, time_type: str = "ms") -> float:
    """Nanoseconds to the requested unit (reference keeps the same four)."""
    if time_type == "ms":
        return t_ns / 1e6
    if time_type == "us":
        return t_ns / 1e3
    if time_type == "s":
        return t_ns / 1e9
    if time_type == "ns":
        return t_ns
    raise ValueError(f"bad time_type {time_type!r}")


def benchmark_with_repetitions(repetitions: int = 10, time_type: str = "ms"):
    """Decorator: run ``repetitions`` times, return (avg_time, last_result).

    Keeps the reference decorator's contract (average over repetitions in
    the chosen unit); also blocks on JAX async dispatch so device work is
    actually measured.
    """
    def wrap(f: Callable):
        def wrapped(*args, **kwargs):
            import jax

            t0 = time.perf_counter_ns()
            result = None
            for _ in range(repetitions):
                result = f(*args, **kwargs)
            jax.block_until_ready(jax.tree.leaves(result) or 0)
            elapsed = (time.perf_counter_ns() - t0) / max(repetitions, 1)
            return time_conversion(elapsed, time_type), result

        return wrapped

    return wrap


# the reference spells it "repitions"; accept both
benchmark_with_repitions = benchmark_with_repetitions
