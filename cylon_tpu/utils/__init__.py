"""Utility subsystem (reference: cpp/src/cylon/util/ — uuid v4 uuid.cpp,
value printing to_string.hpp, sort/sample helpers arrow_utils.cpp — and
python/pycylon/util/benchutils.py)."""
from __future__ import annotations

import uuid as _uuid

from .benchutils import (benchmark_with_repetitions,  # noqa: F401
                         benchmark_with_repitions, time_conversion)
from .timing import enable as enable_timing  # noqa: F401
from .timing import report as timing_report  # noqa: F401
from .timing import reset as timing_reset  # noqa: F401
from .timing import span  # noqa: F401


def generate_uuid_v4() -> str:
    """reference: util/uuid.cpp generate_uuid_v4."""
    return str(_uuid.uuid4())


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``jax.shard_map``: newer jax exposes it at the top
    level with a ``check_vma`` kwarg; jax <= 0.4.x only has
    ``jax.experimental.shard_map.shard_map`` whose equivalent kwarg is
    ``check_rep``.  Every shard_map construction in the tree goes through
    here so a jax upgrade/downgrade can't silently kill the whole
    distributed test surface again."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def pow2ceil(n: int, min_size: int = 8) -> int:
    """Smallest power of two >= n (>=1), floored at ``min_size`` — the one
    capacity-rounding rule shared by every planner and kernel so shard
    capacities never disagree."""
    return max(min_size, 1 << (max(1, int(n)) - 1).bit_length())


def to_string(value, quote_strings: bool = False) -> str:
    """CSV-ish scalar rendering used by Table.print (reference:
    util/to_string.hpp): nulls print empty, strings optionally quoted."""
    if value is None:
        return ""
    if isinstance(value, float):
        return repr(value)
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (bytes, bytearray)):
        value = value.decode("utf-8", "replace")
    if isinstance(value, str) and quote_strings:
        return f'"{value}"'
    return str(value)
