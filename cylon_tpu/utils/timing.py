"""Back-compat shim over ``cylon_tpu.obs.spans`` — the one timing
substrate.

PR 0 grew this module as a standalone stopwatch registry (the
reference's manual ``std::chrono`` + glog pairs, e.g. join timers
join/join.cpp:89-253, split timing partition/partition.cpp:29-57); PR 4
replaced the duplicated stopwatch logic with the structured tracing
subsystem.  ``span`` IS ``obs.spans.span`` (aggregate totals always
accumulate; ``CYLON_TPU_TRACE=1`` additionally buffers events for
Perfetto export), and ``report()``/``reset()`` read/clear the same
aggregate registry benchmarks always consumed.  New code should import
from ``cylon_tpu.obs`` directly.
"""
from __future__ import annotations

from typing import Dict, Tuple

from ..obs import spans as _spans
from ..obs.spans import span  # noqa: F401  (the shimmed entry point)


def enable(on: bool = True) -> None:
    """Flip the per-span INFO log (historically CYLON_TPU_DEBUG)."""
    _spans.enable_log(on)


def enabled() -> bool:
    return _spans.log_enabled()


def report() -> Dict[str, Tuple[float, int]]:
    """{span name: (total seconds, call count)} snapshot."""
    return _spans.aggregate_report()


def reset() -> None:
    """Clear the aggregate registry only — buffered trace events pending
    export are NOT discarded (use obs.spans.reset for everything)."""
    _spans.reset_aggregates()
