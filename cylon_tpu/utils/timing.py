"""Phase timing spans — the observability the reference gets from manual
``std::chrono`` + glog pairs around every hot phase (e.g. join combine/
sort/final-build timers join/join.cpp:89-253, split timing
partition/partition.cpp:29-57, shuffle left/right timing table.cpp:163-175,
CYLON_DEBUG-gated phase timers in Unique, table.cpp:970-1026).

``span("name")`` measures wall time; enabled when the ``CYLON_TPU_DEBUG``
env var is set (the reference's CYLON_DEBUG build flag) or via
``enable()``.  Spans always accumulate into a process-local registry that
``report()`` snapshots, so benchmarks can read phase breakdowns without
log scraping.
"""
from __future__ import annotations

import logging
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Iterator, Tuple

from .. import config

log = logging.getLogger("cylon_tpu")

_enabled = bool(config.knob("CYLON_TPU_DEBUG"))
_totals: Dict[str, float] = defaultdict(float)
_counts: Dict[str, int] = defaultdict(int)


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


@contextmanager
def span(name: str) -> Iterator[None]:
    """Wall-time span; logs at INFO when debug timing is on and always
    accumulates into the registry."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _totals[name] += dt
        _counts[name] += 1
        if _enabled:
            log.info("%s took %.3f ms", name, dt * 1e3)


def report() -> Dict[str, Tuple[float, int]]:
    """{span name: (total seconds, call count)} snapshot."""
    return {k: (_totals[k], _counts[k]) for k in _totals}


def reset() -> None:
    _totals.clear()
    _counts.clear()
