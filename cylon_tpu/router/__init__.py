"""cylon_tpu.router — fleet query routing: many meshes behind one
front door, with a shared fleet-wide result cache.

The PR-6/11 elastic coordinator promoted into a query router
(:class:`QueryRouter`): N independent mesh groups register as serving
replicas (`ReplicaServer` wrapping a PR-7 `QueryService`, heartbeat
telemetry carrying serve address + capacity + live load), the ``route``
verb places requests by tenant affinity with a live-load tiebreak and
proxies them with classified fleet-scope shedding (never a hang), the
shared durable journal serves any replica's fingerprint from any
replica, and a dead replica's queued work is re-routed while in-flight
work is abandoned classified — the PR-6 contract, one level up.
"""
from .replica import ReplicaServer
from .service import (QueryRouter, RouteShed, RouterClient,
                      cache_affinity_enabled, poll_interval_s,
                      route_timeout_s, router_max_line, rpc_timeout_s)
from .wire import request_key

__all__ = [
    "QueryRouter", "RouterClient", "ReplicaServer", "RouteShed",
    "request_key", "cache_affinity_enabled", "poll_interval_s",
    "rpc_timeout_s", "route_timeout_s", "router_max_line",
]
