"""Data-plane wire codec for the fleet query router.

The control plane (net/control.py) speaks one JSON object per line; the
router's ``route`` verb and the router->replica ``submit``/``poll``
proxy additionally carry whole TABLES — the request's input frames on
the way in, the result frame on the way out.  This module maps engine
values onto that JSON line and back, bit-exactly:

- a **frame** (dict of host numpy columns — the chunked engine's native
  currency) rides as Arrow IPC bytes (io/arrow_io.py's exact round-trip
  encoding, the same one the durable journal spills) in base64 under a
  reserved marker key;
- a bare ``numpy`` array rides as a single-column frame;
- numpy scalars collapse to Python scalars; dicts/lists/tuples recurse;
  JSON-native scalars pass through.

Anything else is a classified `Code.SerializationError` — the router
serves the ops whose arguments are tables and scalars (join /
join_groupby / groupby / sort and registered custom ops of the same
shape); a `LogicalPlan` handle is process-local and must be submitted
to a replica's own `QueryService` directly.

:func:`request_key` hashes the canonical encoding into the router's
cache-affinity key: two submissions with identical op + arguments get
the same key, so a repeat is steered to the replica whose caches are
warm.  It deliberately covers CONTENT only (no tenant, no deadline, no
trace header) — the durable run fingerprint remains the correctness
key; this one only picks a replica.
"""
from __future__ import annotations

import base64
import hashlib
import json
from typing import Dict, Optional, Tuple

import numpy as np

from ..io import arrow_io
from ..status import Code, CylonError

#: reserved marker keys of the encoded forms; a user dict carrying one
#: of these is refused rather than silently mis-decoded on the far side
FRAME_KEY = "__cylon_frame__"
ARRAY_KEY = "__cylon_array__"
_MARKERS = (FRAME_KEY, ARRAY_KEY)


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def _ipc_b64(frame: Dict) -> str:
    """Frame -> base64 Arrow IPC, with pyarrow's refusals (2-D arrays,
    structured dtypes, ...) re-raised CLASSIFIED — nothing escapes this
    module unclassified, on either side of the wire."""
    try:
        return _b64(arrow_io.frame_to_ipc_bytes(frame))
    except CylonError:
        raise
    except Exception as e:
        raise CylonError(
            Code.SerializationError,
            f"cannot encode frame for the router wire: "
            f"{type(e).__name__}: {e} (columns must be 1-D numpy "
            f"arrays)") from e


def encode_value(v):
    """One engine value -> a JSON-safe tree (see module docstring)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return {ARRAY_KEY: _ipc_b64({"v": v})}
    if isinstance(v, dict):
        if any(k in v for k in _MARKERS):
            raise CylonError(
                Code.SerializationError,
                f"dict carries a reserved router wire marker key "
                f"({[k for k in _MARKERS if k in v]})")
        if v and all(isinstance(c, np.ndarray) for c in v.values()):
            return {FRAME_KEY: _ipc_b64(v)}
        return {str(k): encode_value(c) for k, c in v.items()}
    if isinstance(v, (list, tuple)):
        return [encode_value(c) for c in v]
    raise CylonError(
        Code.SerializationError,
        f"cannot ship a {type(v).__name__} over the router wire "
        f"(frames = dicts of numpy columns, arrays, and JSON scalars "
        f"only; plan handles are process-local — submit them to a "
        f"replica's QueryService directly)")


def _ipc_from_b64(data) -> Dict:  # cylint: disable=CY117 -- decodes live wire frames (request/result tables in flight), not persisted .arrow spills; TCP delivers the sender's bytes, there is no at-rest decay for a digest to catch here
    """base64 Arrow IPC -> frame, with decode-side refusals (corrupt
    base64, malformed IPC, a non-string where the marker promised one)
    re-raised CLASSIFIED — the decode side honours the same
    nothing-escapes-unclassified contract as :func:`_ipc_b64`."""
    try:
        return arrow_io.frame_from_ipc_bytes(base64.b64decode(data))
    except CylonError:
        raise
    except Exception as e:
        raise CylonError(
            Code.SerializationError,
            f"cannot decode frame from the router wire: "
            f"{type(e).__name__}: {e}") from e


def decode_value(v):
    """Inverse of :func:`encode_value`."""
    if isinstance(v, dict):
        if FRAME_KEY in v:
            return _ipc_from_b64(v[FRAME_KEY])
        if ARRAY_KEY in v:
            return _ipc_from_b64(v[ARRAY_KEY])["v"]
        return {k: decode_value(c) for k, c in v.items()}
    if isinstance(v, list):
        return [decode_value(c) for c in v]
    return v


def encode_payload(args, kwargs) -> Dict:
    """``(args, kwargs)`` of one submit call -> the wire payload."""
    return {"args": [encode_value(a) for a in args],
            "kwargs": {str(k): encode_value(v)
                       for k, v in sorted(kwargs.items())}}


def payload_nbytes(v) -> int:
    """JSON-encoded size of an encoded payload tree, without paying a
    second ``json.dumps`` of the dominant content.  The base64 frame
    strings under the marker keys are escape-free ASCII by construction,
    so their length IS their encoded length; everything else (user
    strings may be escape-heavy — ``ensure_ascii`` inflates non-ASCII
    6x — plus scalars and keys) is measured with a per-node ``dumps``,
    which is exact and only touches the small parts.  The result never
    materially underestimates the real line, so the client's wire-cap
    pre-check stays a deterministic classified refusal instead of a
    mid-send connection drop."""
    if isinstance(v, str):
        return len(json.dumps(v))
    if isinstance(v, dict):
        if any(k in v for k in _MARKERS):
            # {marker: base64}: count, don't re-dump megabytes
            return 2 + sum(len(str(k)) + len(c) + 6 for k, c in v.items())
        return 2 + sum(len(json.dumps(str(k))) + 2 + payload_nbytes(c)
                       for k, c in v.items())
    if isinstance(v, (list, tuple)):
        return 2 + sum(payload_nbytes(c) + 1 for c in v)
    return len(json.dumps(v))  # None/bool/int/float — exact


def decode_payload(payload: Dict) -> Tuple[list, Dict]:
    if not isinstance(payload, dict):
        raise CylonError(Code.SerializationError,
                         f"malformed route payload: {type(payload).__name__}")
    args = [decode_value(a) for a in payload.get("args", [])]
    kwargs = {k: decode_value(v)
              for k, v in (payload.get("kwargs") or {}).items()}
    return args, kwargs


def request_key(op: str, payload: Dict) -> str:
    """Cache-affinity key: sha256 over the canonical encoded request.
    Content-only by construction — the payload has no tenant, deadline,
    or trace fields (those are top-level route verb fields)."""
    doc = json.dumps({"op": str(op), "payload": payload},
                     sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(doc.encode()).hexdigest()[:32]


def jsonable(obj, *, _depth: int = 0):
    """Best-effort JSON sanitizer for stats dicts riding the wire: numpy
    scalars/arrays become Python scalars/lists, sets sort, unknown
    objects stringify.  Lossy on purpose (stats are reporting, not
    data) — results always ride :func:`encode_value` instead."""
    if _depth > 8:
        return str(obj)
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, dict):
        return {str(k): jsonable(v, _depth=_depth + 1)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v, _depth=_depth + 1) for v in obj]
    if isinstance(obj, (set, frozenset)):
        return sorted(str(v) for v in obj)
    return str(obj)


# ---------------------------------------------------------------------------
# checksum-verified blobs (PR 20: journal replication data plane)
# ---------------------------------------------------------------------------

def blob_b64(data: bytes) -> Dict:
    """Raw journal bytes (a spill file, a manifest) -> wire dict with an
    in-band sha256.  Unlike the frame markers above this does NOT decode
    the payload — replication ships spills byte-verbatim so the copy is
    bit-identical by construction; the digest rides along so the far
    side can refuse a damaged transfer without interpreting it."""
    if not isinstance(data, (bytes, bytearray)):
        raise CylonError(Code.SerializationError,
                         f"blob_b64 wants bytes, got {type(data).__name__}")
    data = bytes(data)
    return {"blob": _b64(data), "sha256": hashlib.sha256(data).hexdigest(),
            "bytes": len(data)}


def blob_from_b64(d: Dict, expect_sha: Optional[str] = None) -> bytes:
    """Inverse of :func:`blob_b64`, verifying the in-band digest AND (when
    given) the caller's independent expectation — read-repair passes the
    LOCAL manifest's sha256 here, so a peer serving consistent-but-
    different bytes (a diverged journal) is refused as loudly as a torn
    transfer.  Mismatches classify `Code.IOError`."""
    try:
        data = base64.b64decode(d["blob"])
    except Exception as e:
        raise CylonError(Code.SerializationError,
                         f"cannot decode journal blob from the wire: "
                         f"{type(e).__name__}: {e}") from e
    digest = hashlib.sha256(data).hexdigest()
    if digest != d.get("sha256"):
        raise CylonError(Code.IOError,
                         f"journal blob damaged in transfer: sha256 "
                         f"{digest[:12]} != advertised "
                         f"{str(d.get('sha256'))[:12]}")
    if expect_sha is not None and digest != expect_sha:
        raise CylonError(Code.IOError,
                         f"peer journal blob diverges from the local "
                         f"manifest: sha256 {digest[:12]} != expected "
                         f"{expect_sha[:12]}")
    return data


# ---------------------------------------------------------------------------
# classified errors over the wire
# ---------------------------------------------------------------------------

def classified(err: CylonError) -> Dict:
    """A `CylonError` as a wire dict the far side can re-raise."""
    return {"code": err.code.name, "msg": err.msg,
            "retry_after_s": err.retry_after_s}


def classified_error(d: Optional[Dict]) -> CylonError:
    """Wire dict -> `CylonError` (unknown code names classify as
    `Code.UnknownError` rather than failing the decode)."""
    d = d or {}
    try:
        code = Code[str(d.get("code"))]
    except KeyError:
        code = Code.UnknownError
    ra = d.get("retry_after_s")
    return CylonError(code, str(d.get("msg", "remote classified failure")),
                      retry_after_s=float(ra) if ra is not None else None)
