"""Fleet query router: many meshes behind one front door.

PR 7 serves concurrent tenants on ONE mesh through ONE scheduler
thread — throughput is capped at a single mesh group no matter how many
TPU slices exist.  This module promotes the PR-6/11 coordinator into a
**query router** fronting N independent mesh groups as serving
replicas:

- **registration rides the existing control plane** — each replica runs
  a PR-7 `QueryService` behind a :class:`~cylon_tpu.router.replica.
  ReplicaServer` and joins the router exactly like an elastic rank:
  ``hello`` + heartbeats, with the replica's serve address, capacity
  and live queue-depth/HBM telemetry carried on the PR-8 telemetry
  payload (`ReplicaServer.telemetry`).  ``Agent.beat_now()`` pushes the
  first full beat immediately, so a replica is placeable the moment it
  starts;
- **the `route` verb admits or sheds, never hangs** — a request is
  placed by tenant affinity with a live-load tiebreak (least queue
  depth, HBM-headroom guard) and proxied to the chosen replica's data
  plane (submit/poll, `cylon_tpu.router.wire` codec).  When every live
  replica sheds or reports saturation the router answers a classified
  `Code.ResourceExhausted` / `Code.Unavailable` with ``retry_after_s``
  — overload at fleet scope is exactly as classified as PR 7 made it
  at mesh scope.  `CYLON_TPU_ROUTER_TIMEOUT_S` bounds a request whose
  replica wedges mid-run with a classified `Code.Timeout`;
- **the shared journal is a fleet-wide result cache** — run
  fingerprints are world-independent (PR 6 proved W→W−1 consumption),
  so with one shared ``CYLON_TPU_DURABLE_DIR`` any replica replays any
  replica's journaled plan: a hot dashboard query compiles once
  fleet-wide.  ``CYLON_TPU_ROUTER_CACHE_AFFINITY`` additionally steers
  a repeated request fingerprint (`wire.request_key`, content-only) to
  the replica whose in-memory caches are warm — a latency optimization,
  never a correctness requirement;
- **replica death is handled by machinery that already exists** — the
  dead mesh is fenced by the PR-6/11 epoch/incarnation ledger (the
  router IS the coordinator), its queued-not-dispatched requests are
  re-routed to a survivor (``router.reroutes``; never silently lost),
  and in-flight work follows the PR-6 abandon-don't-retry contract:
  the client gets a classified retryable `Code.Unavailable` instead of
  a re-execution into who-knows-what.  The router itself restarts from
  `CoordLog` (PR 11) with the routing table rebuilt from the next
  heartbeat round — affinity pins are soft state by design;
- **causality flows through the hop** — the route verb runs under the
  caller's presented traceparent (net/control.py), every proxied
  submit/poll carries the active context, and the replica's serve
  request becomes a child span: one request, one causally-linked
  PR-13 trace across router and replicas.

Everything here is host-side stdlib + numpy (no jax): the jaxpr
collective-budget goldens are untouched by construction, and cylint
CY110 machine-checks that no blocking device call is reachable from the
route/placement/reroute control paths.
"""
from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Dict, List, Optional, Set, Tuple

from .. import config
from ..elastic import Coordinator
from ..net import control
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..status import Code, CylonError
from . import wire


# ---------------------------------------------------------------------------
# knob accessors (registry rows in config.py::KNOBS)
# ---------------------------------------------------------------------------

def cache_affinity_enabled() -> bool:
    """``CYLON_TPU_ROUTER_CACHE_AFFINITY``: steer repeated request
    fingerprints to the replica that last served them."""
    return bool(config.knob("CYLON_TPU_ROUTER_CACHE_AFFINITY"))


def poll_interval_s() -> float:
    """``CYLON_TPU_ROUTER_POLL_S``: router->replica poll cadence."""
    return max(0.005, float(config.knob("CYLON_TPU_ROUTER_POLL_S")))


def rpc_timeout_s() -> float:
    """``CYLON_TPU_ROUTER_RPC_TIMEOUT_S``: one proxy verb's socket
    timeout."""
    return max(0.05, float(config.knob("CYLON_TPU_ROUTER_RPC_TIMEOUT_S")))


def route_timeout_s() -> float:
    """``CYLON_TPU_ROUTER_TIMEOUT_S``: the absolute per-request bound
    when the caller supplied no deadline."""
    return max(0.1, float(config.knob("CYLON_TPU_ROUTER_TIMEOUT_S")))


def router_max_line() -> int:
    """``CYLON_TPU_ROUTER_MAX_LINE_BYTES``: wire cap for one data-plane
    message (route verb / submit / poll reply carrying whole tables)."""
    return max(1 << 16, int(config.knob("CYLON_TPU_ROUTER_MAX_LINE_BYTES")))


#: consecutive failed proxy verbs against a replica the membership
#: ledger still believes alive before the router treats it as dead
#: anyway (the detector will fence it one heartbeat-timeout later; a
#: routed request must not wait that long to make progress)
MAX_PROXY_FAILURES = 3

#: affinity maps are soft state: bounded, oldest pin evicted first
AFFINITY_CAP = 4096

#: the per-replica counter row, single-sourced: every increment site
#: and the status fallback share this shape
_PER_REPLICA_ZERO = {"served": 0, "shed": 0, "rerouted_away": 0}


def _safe_label(s: str) -> str:
    """A tenant/op id as spelled inside a labeled metric key: the
    bracket-pair grammar (``router.x[tenant=a,replica=1]``) reserves
    ``[ ] , =`` — remap them so an adversarial tenant id cannot corrupt
    the exposition (lossy on purpose, labels are reporting)."""
    return (s.replace("[", "(").replace("]", ")")
             .replace(",", ";").replace("=", ":"))


class RouteShed(CylonError):
    """A route-scope admission rejection: the whole fleet (not one
    replica) had no room — same classified contract as a PR-7 shed."""


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class QueryRouter(Coordinator):
    """The PR-6/11 coordinator, promoted: everything a `Coordinator`
    does (membership, heartbeats, fencing, durable `CoordLog`, the
    ``status``/``metrics`` verbs) plus the ``route`` verb placing and
    proxying query requests over the registered serving replicas.

    One process-level object; replicas connect with ordinary
    `elastic.Agent`\\ s whose telemetry carries a ``replica`` record
    (`ReplicaServer.telemetry`).  Ranks without a replica record are
    plain elastic members — a mixed gang routes only over the serving
    subset.
    """

    def __init__(self, world: int, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_timeout_s: Optional[float] = None,
                 log_dir: Optional[str] = None):
        # instance override BEFORE super().__init__ creates the server:
        # the route verb and its replies carry whole encoded tables, so
        # the router's JsonServer needs the data-plane line cap
        self.SERVER_MAX_LINE = router_max_line()
        self._router_lock = threading.Lock()
        self._tenant_affinity: Dict[str, int] = {}
        self._key_affinity: Dict[str, int] = {}
        self._inflight: Dict[int, int] = {}    # rank -> router-held count
        self._route_ewma_s: Optional[float] = None
        self._route_counts = {"routed": 0, "sheds": 0, "reroutes": 0,
                              "abandoned": 0}
        self._per_replica: Dict[int, Dict[str, int]] = {}
        super().__init__(world, host=host, port=port,
                         heartbeat_timeout_s=heartbeat_timeout_s,
                         log_dir=log_dir)

    # -- request handling --------------------------------------------------

    def _handle_inner(self, req: Dict) -> Dict:
        cmd = req.get("cmd")
        if cmd == "route":
            if self.stale:
                return {"ok": False, "status": "stale_coordinator",
                        "incarnation": self.incarnation,
                        "error": "superseded coordinator incarnation"}
            return self._handle_route(req)
        resp = super()._handle_inner(req)
        if cmd == "status" and resp.get("ok"):
            resp["router"] = self.router_status()
        return resp

    # -- placement (host-only decisions; cylint CY110) ---------------------

    def _replica_view(self) -> Dict[int, Dict]:
        """Snapshot the live serving replicas from heartbeat telemetry:
        rank -> {addr, capacity, reported_depth, headroom}.  One short
        membership-lock hold; the proxy loops never touch shared state
        while blocked on a socket."""
        with self._lock:
            tel = {r: self._telemetry.get(r) for r in self._last_hb}
        view: Dict[int, Dict] = {}
        for rank, t in sorted(tel.items()):
            if not isinstance(t, dict):
                continue
            rep = t.get("replica")
            if not isinstance(rep, dict) or not rep.get("addr"):
                continue  # a plain elastic member, not a serving replica
            try:
                host, port = str(rep["addr"][0]), int(rep["addr"][1])
            except (KeyError, IndexError, TypeError, ValueError):
                continue
            view[rank] = {
                "addr": (host, port),
                "capacity": max(1, int(rep.get("capacity", 1) or 1)),
                "reported_depth": int(t.get("queue_depth", 0) or 0),
                "headroom": rep.get("hbm_headroom_bytes"),
            }
        obs_metrics.gauge_set("router.replicas_live", len(view))
        return view

    def _retry_after(self, depth: int) -> float:
        with self._router_lock:
            per = self._route_ewma_s
        return max(0.05, (per if per is not None else 0.25)
                   * max(1, depth + 1))

    def _shed_route(self, tenant: str, code: Code, reason: str,
                    retry_after: Optional[float]) -> RouteShed:
        """Build (don't count) a fleet-scope shed: a rotation candidate
        may still be accepted elsewhere — only the shed actually
        RETURNED to the client is accounted (`_handle_route`)."""
        hint = "" if retry_after is None \
            else f"; retry after ~{retry_after:.2f}s"
        return RouteShed(code, f"request shed at the router for tenant "
                               f"{tenant!r}: {reason}{hint}",
                         retry_after_s=retry_after)

    def _place(self, tenant: str, key: str, est_bytes: int,
               exclude: Set[int]) -> Tuple[int, Tuple[str, int]]:
        """Choose AND reserve one replica, or raise a classified
        `RouteShed`.  Order: cache affinity (a warm replica, when the
        knob is on), then the tenant's pin, then least live load —
        affinity never overrides saturation or the HBM-headroom guard,
        it only breaks ties among replicas that can actually take the
        request.

        The live-load tiebreak adds the router-held in-flight count to
        the (heartbeat-lagged) reported depth, and the chosen replica's
        count is incremented under the SAME lock hold as the decision —
        a reservation, so a burst of concurrent routes spreads over the
        fleet instead of every placement reading the same stale zero
        and piling onto one replica.  The caller releases it
        (`_note_inflight(rank, -1)`) at terminal state or submit
        failure.  The fleet-saturation pre-check uses reported depth
        only (conservative): the replica's own admission control is the
        authority, and its shed rotates the router onward."""
        view = self._replica_view()
        cands = {r: v for r, v in view.items() if r not in exclude}
        if not cands:
            raise self._shed_route(
                tenant, Code.Unavailable,
                f"no live serving replicas "
                f"({len(view)} registered, {len(exclude)} excluded)",
                self.timeout)
        fits = {r: v for r, v in cands.items()
                if not (isinstance(v["headroom"], (int, float))
                        and est_bytes > 0 and v["headroom"] < est_bytes)}
        if not fits:
            raise self._shed_route(
                tenant, Code.ResourceExhausted,
                f"no replica reports {est_bytes} bytes of HBM headroom",
                self._retry_after(min(v["reported_depth"]
                                      for v in cands.values())))
        if all(v["reported_depth"] >= v["capacity"]
               for v in fits.values()):
            raise self._shed_route(
                tenant, Code.ResourceExhausted,
                f"every serving replica is saturated "
                f"({len(fits)} replicas at capacity)",
                self._retry_after(
                    min(v["reported_depth"] for v in fits.values())))
        with self._router_lock:
            order = sorted(
                fits, key=lambda r: (fits[r]["reported_depth"]
                                     + self._inflight.get(r, 0), r))
            pin = self._tenant_affinity.get(tenant)
            warm = self._key_affinity.get(key) \
                if cache_affinity_enabled() else None
            for preferred in (pin, warm):  # last to front wins: warm
                # the saturation gate counts the router's own in-flight
                # reservations too: a burst sharing a tenant within one
                # heartbeat period must not all read the same stale
                # reported-zero and pile onto the pinned replica
                if preferred in order \
                        and fits[preferred]["reported_depth"] \
                        + self._inflight.get(preferred, 0) \
                        < fits[preferred]["capacity"]:
                    order.remove(preferred)
                    order.insert(0, preferred)
            chosen = order[0]
            self._inflight[chosen] = self._inflight.get(chosen, 0) + 1
        return chosen, fits[chosen]["addr"]

    def _pin(self, table: Dict, key, rank: int) -> None:
        table.pop(key, None)
        table[key] = rank
        while len(table) > AFFINITY_CAP:
            table.pop(next(iter(table)))

    def _replica_dead(self, rank: int) -> bool:
        with self._lock:
            return rank in self._dead or rank not in self._last_hb

    # -- the route verb ----------------------------------------------------

    def _handle_route(self, req: Dict) -> Dict:
        tenant = str(req.get("tenant", "default"))
        op = str(req.get("op", ""))
        payload = req.get("payload")
        t0 = time.monotonic()
        try:
            if not op or not isinstance(payload, dict):
                raise CylonError(
                    Code.Invalid,
                    f"malformed route request (op={op!r}, payload is "
                    f"{type(payload).__name__})")
            with obs_spans.span("router.route", tenant=tenant, op=op):
                out = self._route(tenant, op, payload, req, t0)
        except CylonError as e:
            if isinstance(e, RouteShed):
                with self._router_lock:
                    self._route_counts["sheds"] += 1
                obs_metrics.counter_add("router.sheds")
                obs_metrics.counter_add(
                    f"router.sheds[tenant={_safe_label(tenant)}]")
                obs_spans.instant("router.shed", tenant=tenant,
                                  code=e.code.name, reason=e.msg[:200])
            return {"ok": False, "classified": wire.classified(e),
                    **self._ie()}
        dur = time.monotonic() - t0
        with self._router_lock:
            self._route_counts["routed"] += 1
            rank = out["replica"]
            self._per_locked(rank)["served"] += 1
            if not out.get("cache_hit"):
                self._route_ewma_s = dur if self._route_ewma_s is None \
                    else 0.7 * self._route_ewma_s + 0.3 * dur
        obs_metrics.counter_add("router.requests_routed")
        obs_metrics.counter_add(
            f"router.requests_routed[tenant={_safe_label(tenant)},"
            f"replica={out['replica']}]")
        return {"ok": True, **out, **self._ie()}

    def _ie(self) -> Dict:
        return {"incarnation": self.incarnation, "epoch": self._epoch}

    def _route(self, tenant: str, op: str, payload: Dict, req: Dict,
               t0: float) -> Dict:
        """Place + proxy one request to completion (or a classified
        failure) — never a hang: the caller's ``deadline_s`` (or the
        ``CYLON_TPU_ROUTER_TIMEOUT_S`` default) bounds the whole
        journey including re-routes."""
        caller_deadline = req.get("deadline_s")
        deadline_s = float(caller_deadline) \
            if caller_deadline is not None else route_timeout_s()
        deadline = t0 + max(0.05, deadline_s)
        key = wire.request_key(op, payload)
        est = max(0, int(req.get("est_bytes", 0) or 0))
        submit = {"cmd": "submit", "tenant": tenant, "op": op,
                  "payload": payload}
        if caller_deadline is not None:
            # only an EXPLICIT caller budget overrides the replica's
            # tenant deadline table; the router's default bound stays a
            # router-side watchdog, not a per-request budget rewrite
            submit["deadline_s"] = float(caller_deadline)
        exclude: Set[int] = set()
        reroutes = 0
        last_shed: Optional[CylonError] = None
        while True:
            if time.monotonic() >= deadline:
                raise last_shed or CylonError(
                    Code.Timeout,
                    f"route exceeded its {deadline_s:g}s bound before "
                    f"any replica accepted (tenant {tenant!r})")
            try:
                rank, addr = self._place(tenant, key, est, exclude)
            except RouteShed as e:
                # replicas excluded for SHEDDING make "nothing is left"
                # the fleet-saturation case: the last replica-level
                # classified shed (with its retry hint) explains it
                # better than the bare placement view
                raise last_shed or e
            # a fresh idempotency token per placement attempt: the
            # replica dedups control.request's transient-reset retry of
            # an ALREADY-ADMITTED submit (same bytes, same token) back
            # to the same ticket instead of admitting a duplicate
            submit["token"] = token = uuid.uuid4().hex
            try:
                resp = control.request(addr, submit,
                                       timeout=rpc_timeout_s(),
                                       max_line=self.SERVER_MAX_LINE)
            except OSError:
                # no reply — but the submit MAY have been admitted (a
                # reply lost for good, past the token-dedup'd retry).
                # Reap the possible orphan by token; trying the next
                # replica then stays placement, not a re-route.
                self._note_inflight(rank, -1)
                self._try_cancel(addr, None, token=token)
                exclude.add(rank)
                continue
            if not resp.get("ok"):
                self._note_inflight(rank, -1)
                c = resp.get("classified")
                if c is None and resp.get("error"):
                    c = {"msg": str(resp["error"])}
                err = wire.classified_error(c)
                if err.code in (Code.ResourceExhausted, Code.Unavailable):
                    # one replica's shed is not the fleet's: rotate to
                    # the next candidate (_place raises the fleet-wide
                    # classified shed once every replica is excluded)
                    with self._router_lock:
                        self._per_locked(rank)["shed"] += 1
                    last_shed = self._shed_route(
                        tenant, err.code,
                        f"replica {rank} shed: {err.msg}",
                        err.retry_after_s)
                    exclude.add(rank)
                    continue
                raise err  # deterministic (Invalid etc.): propagate
            req_id = str(resp.get("req_id"))
            if reroutes == 0:
                # pin at ACCEPT, not completion: the very next request
                # of this tenant (or of this fingerprint) should land
                # where the queue is forming
                with self._router_lock:
                    self._pin(self._tenant_affinity, tenant, rank)
                    self._pin(self._key_affinity, key, rank)
            try:
                done = self._proxy_poll(tenant, rank, addr, req_id,
                                        deadline)
            finally:
                self._note_inflight(rank, -1)
            if done is not None:
                with self._router_lock:
                    self._pin(self._key_affinity, key, rank)
                return {**done, "replica": rank, "reroutes": reroutes}
            # the replica died with the request queued-not-dispatched:
            # re-route it to a survivor — never silently lost
            reroutes += 1
            exclude.add(rank)
            with self._router_lock:
                self._route_counts["reroutes"] += 1
                self._per_locked(rank)["rerouted_away"] += 1
            obs_metrics.counter_add("router.reroutes")
            obs_metrics.counter_add(f"router.reroutes[replica={rank}]")
            obs_spans.instant("router.reroute", tenant=tenant, op=op,
                              dead_replica=rank)

    def _per_locked(self, rank: int) -> Dict[str, int]:
        """One replica's counter row; call holding ``_router_lock``."""
        return self._per_replica.setdefault(rank,
                                            dict(_PER_REPLICA_ZERO))

    def _note_inflight(self, rank: int, delta: int) -> None:
        with self._router_lock:
            n = self._inflight.get(rank, 0) + delta
            if n > 0:
                self._inflight[rank] = n
            else:
                self._inflight.pop(rank, None)

    def _proxy_poll(self, tenant: str, rank: int, addr: Tuple[str, int],
                    req_id: str, deadline: float) -> Optional[Dict]:
        """Poll one accepted ticket to a terminal state.  Returns the
        terminal dict, raises the replica's classified error, or returns
        None when the replica DIED while the ticket was still queued
        (the caller re-routes).  A death after the ticket was observed
        running is the PR-6 abandon-don't-retry contract: classified
        retryable `Code.Unavailable`, never a silent re-execution.

        Two contracts the wire imposes: (a) the queued-vs-running
        distinction is observed at POLLING granularity — a replica dying
        before any poll saw ``running`` re-routes, which is exact for
        the journaled built-in ops (the survivor consumes the dead
        replica's journaled passes bit-identically) and the reason
        ``register_op`` handlers must be idempotent; (b) a terminal
        reply read here is ACKNOWLEDGED back to the replica — the
        ticket survives a reply lost on the wire (the retried poll
        regenerates it) and drops only on the ack."""
        fails = 0
        observed_running = False
        poll = {"cmd": "poll", "req_id": req_id}
        while True:
            if self._replica_dead(rank):
                return self._on_replica_death(tenant, rank, addr, req_id,
                                              observed_running)
            if time.monotonic() >= deadline:
                self._try_cancel(addr, req_id)
                raise CylonError(
                    Code.Timeout,
                    f"routed request exceeded its deadline on replica "
                    f"{rank} (tenant {tenant!r}); proxied ticket "
                    f"cancelled at the next pass boundary")
            try:
                resp = control.request(addr, poll,
                                       timeout=rpc_timeout_s(),
                                       max_line=self.SERVER_MAX_LINE)
            except control.ProtocolError as e:
                # DETERMINISTIC, not a death: the reply exceeds the
                # data-plane line cap — every retry would fail the same
                # way, and counting it toward MAX_PROXY_FAILURES would
                # declare a healthy replica dead and re-route into the
                # same wall.  Same classification the request path
                # gives oversize, naming the knob; the terminal ticket
                # is acked away so the replica doesn't hold it forever.
                self._try_ack(addr, req_id)
                raise CylonError(
                    Code.SerializationError,
                    f"replica {rank}'s reply exceeds the "
                    f"{self.SERVER_MAX_LINE}-byte "
                    f"CYLON_TPU_ROUTER_MAX_LINE_BYTES wire cap (tenant "
                    f"{tenant!r}); raise the knob (router AND replicas) "
                    f"or ship less data per request") from e
            except OSError:
                fails += 1
                if fails >= MAX_PROXY_FAILURES \
                        or self._replica_dead(rank):
                    return self._on_replica_death(
                        tenant, rank, addr, req_id, observed_running)
                time.sleep(poll_interval_s())
                continue
            fails = 0
            state = resp.get("state")
            if not resp.get("ok"):
                if state == "unknown":
                    # the replica lost track of an ADMITTED ticket
                    # (TICKET_CAP eviction, a data-plane restart): the
                    # replica's failure, not the caller's — classified
                    # RETRYABLE, never the replica's unknown-req_id
                    # Code.Invalid (which would read as a caller bug)
                    raise CylonError(
                        Code.Unavailable,
                        f"replica {rank} lost track of an admitted "
                        f"request (ticket evicted or replica restarted; "
                        f"tenant {tenant!r}) — resubmit to replay "
                        f"journaled passes",
                        retry_after_s=self._retry_after(0))
                raise wire.classified_error(resp.get("classified"))
            if state == "done":
                self._try_ack(addr, req_id)
                return {"result": resp.get("result"),
                        "stats": resp.get("stats"),
                        "cache_hit": bool(resp.get("cache_hit"))}
            if state in ("failed", "cancelled", "shed"):
                self._try_ack(addr, req_id)
                raise wire.classified_error(resp.get("classified"))
            if state == "running":
                observed_running = True
            time.sleep(poll_interval_s())

    def _on_replica_death(self, tenant: str, rank: int,
                          addr: Tuple[str, int], req_id: str,
                          observed_running: bool) -> Optional[Dict]:
        if not observed_running:
            # queued-not-dispatched: the caller re-routes.  The replica
            # may be merely UNREACHABLE (3 failed RPCs, not yet fenced)
            # rather than dead — best-effort cancel the queued ticket
            # first, so a replica that recovers does not run work the
            # survivor is about to run too (swallowed if it really died)
            self._try_cancel(addr, req_id)
            return None
        # in-flight on a dead mesh: abandon, don't retry — re-running
        # half-finished device work into a fresh replica is the desync
        # the PR-6 contract bans; the CALLER retries with a fresh
        # classified hint (completed passes are journaled, so the retry
        # is cheap)
        self._try_cancel(addr, req_id)
        with self._router_lock:
            self._route_counts["abandoned"] += 1
        obs_metrics.counter_add("router.abandoned")
        obs_spans.instant("router.abandoned", tenant=tenant,
                          dead_replica=rank)
        raise CylonError(
            Code.Unavailable,
            f"replica {rank} died with this request in flight (tenant "
            f"{tenant!r}); in-flight work is abandoned, not retried — "
            f"resubmit to replay journaled passes",
            retry_after_s=self._retry_after(0))

    def _try_cancel(self, addr: Tuple[str, int],
                    req_id: Optional[str],
                    token: Optional[str] = None) -> None:
        """Best-effort cancel by ``req_id`` or by idempotency ``token``
        — the token form reaps an orphan whose submit accept reply was
        lost (the router never learned its req_id)."""
        obj: Dict = {"cmd": "cancel"}
        if req_id is not None:
            obj["req_id"] = req_id
        if token is not None:
            obj["token"] = token
        try:
            control.request(addr, obj, timeout=rpc_timeout_s(),
                            retries=0, max_line=self.SERVER_MAX_LINE)
        except OSError:
            pass  # the replica is gone; nothing to cancel

    def _try_ack(self, addr: Tuple[str, int], req_id: str) -> None:
        """Terminal reply read: tell the replica the ticket may drop.
        Best-effort — an unacked terminal ticket ages out past the
        replica's TICKET_CAP."""
        try:
            control.request(addr, {"cmd": "ack", "req_id": req_id},
                            timeout=rpc_timeout_s(), retries=0,
                            max_line=self.SERVER_MAX_LINE)
        except OSError:
            pass  # ack is insurance, not a contract

    # -- introspection -----------------------------------------------------

    def router_status(self) -> Dict:
        """The routing table the ``status`` verb ships and
        ``tools/fleet_status.py --replicas`` renders: per-replica
        capacity/depth/headroom plus served/shed/re-route counters and
        the current affinity pins."""
        view = self._replica_view()
        with self._router_lock:
            counts = dict(self._route_counts)
            per = {r: dict(c) for r, c in sorted(self._per_replica.items())}
            tenants = dict(self._tenant_affinity)
            keys = len(self._key_affinity)
            inflight = dict(self._inflight)
        replicas = {}
        for rank, v in sorted(view.items()):
            replicas[str(rank)] = {
                "addr": f"{v['addr'][0]}:{v['addr'][1]}",
                "capacity": v["capacity"],
                "queue_depth": v["reported_depth"],
                "router_inflight": inflight.get(rank, 0),
                "hbm_headroom_bytes": v["headroom"],
                **per.get(rank, _PER_REPLICA_ZERO),
                "tenants_pinned": sorted(
                    t for t, r in tenants.items() if r == rank),
            }
        return {"replicas": replicas, "replicas_live": len(view),
                "cache_affinity": cache_affinity_enabled(),
                "key_pins": keys, **counts}


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RouterClient:
    """Caller-side handle for the ``route`` verb: encodes the request
    onto the wire (`cylon_tpu.router.wire`), ships it, blocks for the
    reply, and re-raises classified failures as `CylonError` —
    callers see the same contract `QueryService.submit(...).result()`
    gives them locally, with the fleet behind it."""

    def __init__(self, address, timeout_s: Optional[float] = None):
        if isinstance(address, (tuple, list)):
            self._addr: Tuple[str, int] = (str(address[0]),
                                           int(address[1]))
        else:
            host, _, port = str(address).rpartition(":")
            if not host or not port:
                raise CylonError(Code.Invalid,
                                 f"bad router address {address!r} "
                                 f"(want host:port)")
            self._addr = (host, int(port))
        self._timeout = timeout_s

    def route(self, tenant: str, op: str, *args,
              deadline_s: Optional[float] = None,
              timeout_s: Optional[float] = None, **kwargs):
        """One routed request: returns ``(result, stats)`` with
        ``stats["router"]`` carrying the serving replica, re-route
        count, and cache-hit flag; raises the classified `CylonError`
        on shed/failure/timeout.  The active trace context rides the
        verb (net/control.py), so the routed run joins the caller's
        trace."""
        payload = wire.encode_payload(args, kwargs)
        obj: Dict = {"cmd": "route", "tenant": str(tenant),
                     "op": str(op)}
        if deadline_s is not None:
            obj["deadline_s"] = float(deadline_s)
        cap = router_max_line()
        # the base64 payload dominates the encoded line; estimating its
        # size skips a second json.dumps of the whole object on the hot
        # path (send_json performs the ONLY full serialization).  The
        # non-payload fields are measured EXACTLY — a pathological
        # tenant/op string must hit this classified refusal too, not a
        # server-side connection drop read as retryable
        nbytes = (wire.payload_nbytes(payload)
                  + len(json.dumps(obj, sort_keys=True)))
        obj["payload"] = payload
        if nbytes + 1024 > cap:
            raise CylonError(
                Code.SerializationError,
                f"encoded route request is ~{nbytes} bytes — past the "
                f"{cap}-byte CYLON_TPU_ROUTER_MAX_LINE_BYTES wire cap; "
                f"raise the knob (router AND replicas) or ship less "
                f"data per request")
        # ~2x input residency is the serve layer's admission estimate;
        # base64 already inflated the frames 4/3, so the encoded line
        # length is the right order of magnitude for the headroom guard
        obj["est_bytes"] = 2 * nbytes
        budget = deadline_s if deadline_s is not None \
            else route_timeout_s()
        timeout = timeout_s if timeout_s is not None \
            else (self._timeout if self._timeout is not None
                  else budget + 30.0)
        try:
            # retries=0 ON PURPOSE: the route verb blocks server-side
            # for the whole proxied run, so a transparent resend of the
            # line would start a SECOND placement while the first
            # handler thread may still be driving the original to
            # completion.  A dropped connection surfaces classified and
            # retryable instead — the caller's resubmit replays
            # journaled passes, it does not double device work.
            resp = control.request(self._addr, obj, timeout=timeout,
                                   retries=0, max_line=cap)
        except control.ProtocolError as e:
            # the REPLY outgrew this client's cap (the router's own cap
            # may be higher — knobs are read per process): deterministic,
            # a retry hits the same wall, so never classified retryable
            raise CylonError(
                Code.SerializationError,
                f"routed reply exceeds this client's {cap}-byte "
                f"CYLON_TPU_ROUTER_MAX_LINE_BYTES wire cap ({e}); raise "
                f"the knob (client, router AND replicas) or ship less "
                f"data per request") from e
        except OSError as e:
            raise CylonError(
                Code.Unavailable,
                f"query router at {self._addr[0]}:{self._addr[1]} "
                f"unreachable or dropped mid-route "
                f"({type(e).__name__}: {e}); the routed request may "
                f"still complete server-side — a resubmit replays "
                f"journaled passes, never re-executes them") from e
        if not resp.get("ok"):
            if resp.get("status") == "stale_coordinator":
                # PR-11 split-brain: a superseded router incarnation is
                # still bound — retryable, not a caller bug
                raise CylonError(
                    Code.Unavailable,
                    f"query router at {self._addr[0]}:{self._addr[1]} "
                    f"answered stale (superseded by incarnation "
                    f"{resp.get('incarnation')}); re-resolve the router "
                    f"address and retry", retry_after_s=1.0)
            if "classified" in resp:
                raise wire.classified_error(resp["classified"])
            raise CylonError(Code.UnknownError,
                             f"route failed: {resp.get('error', resp)}")
        result = wire.decode_value(resp.get("result"))
        stats = dict(resp.get("stats") or {})
        stats["router"] = {"replica": resp.get("replica"),
                           "reroutes": resp.get("reroutes", 0),
                           "cache_hit": bool(resp.get("cache_hit"))}
        return result, stats

    def status(self, timeout_s: float = 5.0) -> Dict:
        return control.request(self._addr, {"cmd": "status"},
                               timeout=timeout_s,
                               max_line=router_max_line())
