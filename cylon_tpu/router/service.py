"""Fleet query router: many meshes behind one front door.

PR 7 serves concurrent tenants on ONE mesh through ONE scheduler
thread — throughput is capped at a single mesh group no matter how many
TPU slices exist.  This module promotes the PR-6/11 coordinator into a
**query router** fronting N independent mesh groups as serving
replicas:

- **registration rides the existing control plane** — each replica runs
  a PR-7 `QueryService` behind a :class:`~cylon_tpu.router.replica.
  ReplicaServer` and joins the router exactly like an elastic rank:
  ``hello`` + heartbeats, with the replica's serve address, capacity
  and live queue-depth/HBM telemetry carried on the PR-8 telemetry
  payload (`ReplicaServer.telemetry`).  ``Agent.beat_now()`` pushes the
  first full beat immediately, so a replica is placeable the moment it
  starts;
- **the `route` verb admits or sheds, never hangs** — a request is
  placed by tenant affinity with a live-load tiebreak (least queue
  depth, HBM-headroom guard) and proxied to the chosen replica's data
  plane (submit/poll, `cylon_tpu.router.wire` codec).  When every live
  replica sheds or reports saturation the router answers a classified
  `Code.ResourceExhausted` / `Code.Unavailable` with ``retry_after_s``
  — overload at fleet scope is exactly as classified as PR 7 made it
  at mesh scope.  `CYLON_TPU_ROUTER_TIMEOUT_S` bounds a request whose
  replica wedges mid-run with a classified `Code.Timeout`;
- **the shared journal is a fleet-wide result cache** — run
  fingerprints are world-independent (PR 6 proved W→W−1 consumption),
  so with one shared ``CYLON_TPU_DURABLE_DIR`` any replica replays any
  replica's journaled plan: a hot dashboard query compiles once
  fleet-wide.  ``CYLON_TPU_ROUTER_CACHE_AFFINITY`` additionally steers
  a repeated request fingerprint (`wire.request_key`, content-only) to
  the replica whose in-memory caches are warm — a latency optimization,
  never a correctness requirement;
- **replica death is handled by machinery that already exists** — the
  dead mesh is fenced by the PR-6/11 epoch/incarnation ledger (the
  router IS the coordinator), its queued-not-dispatched requests are
  re-routed to a survivor (``router.reroutes``; never silently lost),
  and in-flight work follows the PR-6 abandon-don't-retry contract:
  the client gets a classified retryable `Code.Unavailable` instead of
  a re-execution into who-knows-what.  The router itself restarts from
  `CoordLog` (PR 11) with the routing table rebuilt from the next
  heartbeat round — affinity pins are soft state by design;
- **causality flows through the hop** — the route verb runs under the
  caller's presented traceparent (net/control.py), every proxied
  submit/poll carries the active context, and the replica's serve
  request becomes a child span: one request, one causally-linked
  PR-13 trace across router and replicas.

Everything here is host-side stdlib + numpy (no jax): the jaxpr
collective-budget goldens are untouched by construction, and cylint
CY110 machine-checks that no blocking device call is reachable from the
route/placement/reroute control paths.
"""
from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Dict, List, Optional, Set, Tuple

from .. import config
from ..elastic import Coordinator
from ..net import control
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..status import Code, CylonError
from . import wire


# ---------------------------------------------------------------------------
# knob accessors (registry rows in config.py::KNOBS)
# ---------------------------------------------------------------------------

def cache_affinity_enabled() -> bool:
    """``CYLON_TPU_ROUTER_CACHE_AFFINITY``: steer repeated request
    fingerprints to the replica that last served them."""
    return bool(config.knob("CYLON_TPU_ROUTER_CACHE_AFFINITY"))


def poll_interval_s() -> float:
    """``CYLON_TPU_ROUTER_POLL_S``: router->replica poll cadence."""
    return max(0.005, float(config.knob("CYLON_TPU_ROUTER_POLL_S")))


def rpc_timeout_s() -> float:
    """``CYLON_TPU_ROUTER_RPC_TIMEOUT_S``: one proxy verb's socket
    timeout."""
    return max(0.05, float(config.knob("CYLON_TPU_ROUTER_RPC_TIMEOUT_S")))


def route_timeout_s() -> float:
    """``CYLON_TPU_ROUTER_TIMEOUT_S``: the absolute per-request bound
    when the caller supplied no deadline."""
    return max(0.1, float(config.knob("CYLON_TPU_ROUTER_TIMEOUT_S")))


def router_max_line() -> int:
    """``CYLON_TPU_ROUTER_MAX_LINE_BYTES``: wire cap for one data-plane
    message (route verb / submit / poll reply carrying whole tables)."""
    return max(1 << 16, int(config.knob("CYLON_TPU_ROUTER_MAX_LINE_BYTES")))


def hedge_floor_ms() -> float:
    """``CYLON_TPU_ROUTER_HEDGE_MS``: floor (and cold-start value) for
    the per-fingerprint hedge delay; 0 (default) disables hedging."""
    return max(0.0, float(config.knob("CYLON_TPU_ROUTER_HEDGE_MS")))


def breaker_failures() -> int:
    """``CYLON_TPU_ROUTER_BREAKER_FAILURES``: consecutive classified
    failures (or sustained-slow observations) before a replica's health
    breaker OPENs; 0 disables the breakers entirely."""
    return max(0, int(config.knob("CYLON_TPU_ROUTER_BREAKER_FAILURES")))


def breaker_cooldown_s() -> float:
    """``CYLON_TPU_ROUTER_BREAKER_COOLDOWN_S``: seconds an OPEN breaker
    holds before HALF_OPEN admits one real probe request."""
    return max(0.05,
               float(config.knob("CYLON_TPU_ROUTER_BREAKER_COOLDOWN_S")))


#: consecutive failed proxy verbs against a replica the membership
#: ledger still believes alive before the router treats it as dead
#: anyway (the detector will fence it one heartbeat-timeout later; a
#: routed request must not wait that long to make progress)
MAX_PROXY_FAILURES = 3

#: affinity maps are soft state: bounded, oldest pin evicted first
AFFINITY_CAP = 4096

#: the per-replica counter row, single-sourced: every increment site
#: and the status fallback share this shape
_PER_REPLICA_ZERO = {"served": 0, "shed": 0, "rerouted_away": 0,
                     "hedged_away": 0}

#: the journaled built-in serve ops: fingerprint-idempotent and
#: bit-identical across replicas by the PR-6/14 journal contract, hence
#: always hedge-safe.  A literal twin of serve.service.OPS on purpose —
#: importing serve here would drag the jax engine into the router
#: process, and CY110's host-only guarantee with it.
HEDGE_SAFE_OPS = frozenset({"join", "join_groupby", "groupby", "sort",
                            "plan", "refresh"})

# breaker states — also the `router.breaker_state[replica=N]` gauge
# values (0 scrapes as healthy, higher is worse)
BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN = 0, 1, 2
_BREAKER_NAMES = {BREAKER_CLOSED: "closed",
                  BREAKER_HALF_OPEN: "half_open",
                  BREAKER_OPEN: "open"}

#: one replica's breaker row (under ``_router_lock``): transitions are
#: host-only dict flips — never an RPC or fsync under the lock (CY111)
_BREAKER_ZERO = {"state": BREAKER_CLOSED, "strikes": 0, "opened_at": 0.0,
                 "probing": False, "opens": 0, "probes": 0}

#: classified codes that count as a replica-health strike: transient /
#: infrastructure failures.  Deterministic codes (Invalid, a caller's
#: oversize payload) are the CALLER's problem and never open a breaker.
_STRIKE_CODES = (Code.Timeout, Code.Unavailable, Code.UnknownError)


class _Attempt:
    """One placed execution of a routed request (the primary, or its
    hedge): the replica it landed on, the admitted ticket, and the
    per-attempt poll/failure bookkeeping."""

    __slots__ = ("rank", "addr", "req_id", "token", "probe", "is_hedge",
                 "fails", "observed_running", "t_submit", "released")

    def __init__(self, rank: int, addr: Tuple[str, int], req_id: str,
                 token: str, probe: bool, is_hedge: bool):
        self.rank = rank
        self.addr = addr
        self.req_id = req_id
        self.token = token
        self.probe = probe
        self.is_hedge = is_hedge
        self.fails = 0
        self.observed_running = False
        self.t_submit = time.monotonic()
        self.released = False


def _safe_label(s: str) -> str:
    """A tenant/op id as spelled inside a labeled metric key: the
    bracket-pair grammar (``router.x[tenant=a,replica=1]``) reserves
    ``[ ] , =`` — remap them so an adversarial tenant id cannot corrupt
    the exposition (lossy on purpose, labels are reporting)."""
    return (s.replace("[", "(").replace("]", ")")
             .replace(",", ";").replace("=", ":"))


class RouteShed(CylonError):
    """A route-scope admission rejection: the whole fleet (not one
    replica) had no room — same classified contract as a PR-7 shed."""


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class QueryRouter(Coordinator):
    """The PR-6/11 coordinator, promoted: everything a `Coordinator`
    does (membership, heartbeats, fencing, durable `CoordLog`, the
    ``status``/``metrics`` verbs) plus the ``route`` verb placing and
    proxying query requests over the registered serving replicas.

    One process-level object; replicas connect with ordinary
    `elastic.Agent`\\ s whose telemetry carries a ``replica`` record
    (`ReplicaServer.telemetry`).  Ranks without a replica record are
    plain elastic members — a mixed gang routes only over the serving
    subset.
    """

    def __init__(self, world: int, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_timeout_s: Optional[float] = None,
                 log_dir: Optional[str] = None):
        # instance override BEFORE super().__init__ creates the server:
        # the route verb and its replies carry whole encoded tables, so
        # the router's JsonServer needs the data-plane line cap
        self.SERVER_MAX_LINE = router_max_line()
        self._router_lock = threading.Lock()
        self._tenant_affinity: Dict[str, int] = {}
        self._key_affinity: Dict[str, int] = {}
        self._inflight: Dict[int, int] = {}    # rank -> router-held count
        self._route_ewma_s: Optional[float] = None
        self._route_counts = {"routed": 0, "sheds": 0, "reroutes": 0,
                              "abandoned": 0, "hedges_fired": 0,
                              "hedges_won": 0, "hedges_lost_cancelled": 0}
        self._per_replica: Dict[int, Dict[str, int]] = {}
        self._breakers: Dict[int, Dict] = {}
        # per-fingerprint asymmetric-EWMA p99 of observed route latency
        # (rises fast toward outliers, decays slowly — the PR-13 tail
        # estimator), bounded like the affinity maps
        self._key_p99_s: Dict[str, float] = {}
        super().__init__(world, host=host, port=port,
                         heartbeat_timeout_s=heartbeat_timeout_s,
                         log_dir=log_dir)

    # -- request handling --------------------------------------------------

    def _handle_inner(self, req: Dict) -> Dict:
        cmd = req.get("cmd")
        if cmd == "route":
            if self.stale:
                return {"ok": False, "status": "stale_coordinator",
                        "incarnation": self.incarnation,
                        "error": "superseded coordinator incarnation"}
            return self._handle_route(req)
        resp = super()._handle_inner(req)
        if cmd == "status" and resp.get("ok"):
            resp["router"] = self.router_status()
        return resp

    # -- placement (host-only decisions; cylint CY110) ---------------------

    def _replica_view(self) -> Dict[int, Dict]:
        """Snapshot the live serving replicas from heartbeat telemetry:
        rank -> {addr, capacity, reported_depth, headroom}.  One short
        membership-lock hold; the proxy loops never touch shared state
        while blocked on a socket."""
        with self._lock:
            tel = {r: self._telemetry.get(r) for r in self._last_hb}
        view: Dict[int, Dict] = {}
        for rank, t in sorted(tel.items()):
            if not isinstance(t, dict):
                continue
            rep = t.get("replica")
            if not isinstance(rep, dict) or not rep.get("addr"):
                continue  # a plain elastic member, not a serving replica
            try:
                host, port = str(rep["addr"][0]), int(rep["addr"][1])
            except (KeyError, IndexError, TypeError, ValueError):
                continue
            view[rank] = {
                "addr": (host, port),
                "capacity": max(1, int(rep.get("capacity", 1) or 1)),
                "reported_depth": int(t.get("queue_depth", 0) or 0),
                "headroom": rep.get("hbm_headroom_bytes"),
                # custom ops the replica declared hedge-safe
                # (register_op(..., idempotent=True)), heartbeat-shipped
                "idempotent_ops": frozenset(
                    str(x) for x in (rep.get("idempotent_ops") or ())),
            }
        obs_metrics.gauge_set("router.replicas_live", len(view))
        return view

    def _retry_after(self, depth: int) -> float:
        with self._router_lock:
            per = self._route_ewma_s
        return max(0.05, (per if per is not None else 0.25)
                   * max(1, depth + 1))

    def _shed_route(self, tenant: str, code: Code, reason: str,
                    retry_after: Optional[float]) -> RouteShed:
        """Build (don't count) a fleet-scope shed: a rotation candidate
        may still be accepted elsewhere — only the shed actually
        RETURNED to the client is accounted (`_handle_route`)."""
        hint = "" if retry_after is None \
            else f"; retry after ~{retry_after:.2f}s"
        return RouteShed(code, f"request shed at the router for tenant "
                               f"{tenant!r}: {reason}{hint}",
                         retry_after_s=retry_after)

    def _place(self, tenant: str, key: str, est_bytes: int,
               exclude: Set[int]) -> Tuple[int, Tuple[str, int], bool]:
        """Choose AND reserve one replica, or raise a classified
        `RouteShed`; returns ``(rank, addr, probe)`` where ``probe``
        marks the request as a HALF_OPEN breaker's one live health
        probe.  Order: cache affinity (a warm replica, when the knob is
        on), then the tenant's pin, then least live load — affinity
        never overrides saturation or the HBM-headroom guard, it only
        breaks ties among replicas that can actually take the request.
        Health breakers COMPOSE with that order (they never override
        fencing, affinity or saturation): an OPEN replica is dropped
        from the candidate set exactly like a fenced one, a HALF_OPEN
        replica admits one probe request (preferred to the front, so
        recovery is not starved by a healthy pin), and when the breakers
        leave nothing the request sheds classified with the shortest
        remaining cooldown as its retry hint.

        The live-load tiebreak adds the router-held in-flight count to
        the (heartbeat-lagged) reported depth, and the chosen replica's
        count is incremented under the SAME lock hold as the decision —
        a reservation, so a burst of concurrent routes spreads over the
        fleet instead of every placement reading the same stale zero
        and piling onto one replica.  The caller releases it
        (`_note_inflight(rank, -1)`) at terminal state or submit
        failure.  The fleet-saturation pre-check uses reported depth
        only (conservative): the replica's own admission control is the
        authority, and its shed rotates the router onward."""
        view = self._replica_view()
        cands = {r: v for r, v in view.items() if r not in exclude}
        if not cands:
            raise self._shed_route(
                tenant, Code.Unavailable,
                f"no live serving replicas "
                f"({len(view)} registered, {len(exclude)} excluded)",
                self.timeout)
        fits = {r: v for r, v in cands.items()
                if not (isinstance(v["headroom"], (int, float))
                        and est_bytes > 0 and v["headroom"] < est_bytes)}
        if not fits:
            raise self._shed_route(
                tenant, Code.ResourceExhausted,
                f"no replica reports {est_bytes} bytes of HBM headroom",
                self._retry_after(min(v["reported_depth"]
                                      for v in cands.values())))
        if all(v["reported_depth"] >= v["capacity"]
               for v in fits.values()):
            raise self._shed_route(
                tenant, Code.ResourceExhausted,
                f"every serving replica is saturated "
                f"({len(fits)} replicas at capacity)",
                self._retry_after(
                    min(v["reported_depth"] for v in fits.values())))
        breakers_on = breaker_failures() > 0
        with self._router_lock:
            now = time.monotonic()
            admit: Dict[int, bool] = {}   # rank -> is-probe
            for r in fits:
                ok, as_probe = (self._breaker_admit_locked(r, now)
                                if breakers_on else (True, False))
                if ok:
                    admit[r] = as_probe
            if not admit:
                # every fit replica's breaker is open: classified shed
                # with the shortest remaining cooldown as the hint
                cd = breaker_cooldown_s()
                wait = min((max(0.05, cd - (now - self._breakers[r]
                                            ["opened_at"]))
                            for r in fits if r in self._breakers),
                           default=cd)
                raise self._shed_route(
                    tenant, Code.Unavailable,
                    f"every live replica's health breaker is open "
                    f"({len(fits)} replicas)", wait)
            order = sorted(
                admit, key=lambda r: (fits[r]["reported_depth"]
                                      + self._inflight.get(r, 0), r))
            pin = self._tenant_affinity.get(tenant)
            warm = self._key_affinity.get(key) \
                if cache_affinity_enabled() else None
            for preferred in (pin, warm):  # last to front wins: warm
                # the saturation gate counts the router's own in-flight
                # reservations too: a burst sharing a tenant within one
                # heartbeat period must not all read the same stale
                # reported-zero and pile onto the pinned replica
                if preferred in order \
                        and fits[preferred]["reported_depth"] \
                        + self._inflight.get(preferred, 0) \
                        < fits[preferred]["capacity"]:
                    order.remove(preferred)
                    order.insert(0, preferred)
            # a HALF_OPEN replica's probe outranks even the pin: the
            # fleet gets its capacity back only if one real request
            # actually lands there
            probe_rank = next((r for r in order if admit[r]), None)
            if probe_rank is not None:
                order.remove(probe_rank)
                order.insert(0, probe_rank)
            chosen = order[0]
            probe = admit[chosen]
            if probe:
                b = self._breaker_locked(chosen)
                b["probing"] = True
                b["probes"] += 1
            self._inflight[chosen] = self._inflight.get(chosen, 0) + 1
        return chosen, fits[chosen]["addr"], probe

    def _pin(self, table: Dict, key, rank: int) -> None:
        table.pop(key, None)
        table[key] = rank
        while len(table) > AFFINITY_CAP:
            table.pop(next(iter(table)))

    def _replica_dead(self, rank: int) -> bool:
        with self._lock:
            return rank in self._dead or rank not in self._last_hb

    # -- replica health breakers (host-only transitions; cylint CY111) -----

    def _breaker_locked(self, rank: int) -> Dict:
        """One replica's breaker row; call holding ``_router_lock``."""
        return self._breakers.setdefault(rank, dict(_BREAKER_ZERO))

    def _breaker_set_locked(self, b: Dict, rank: int, state: int) -> None:
        b["state"] = state
        obs_metrics.gauge_set(f"router.breaker_state[replica={rank}]",
                              state)

    def _breaker_admit_locked(self, rank: int, now: float
                              ) -> Tuple[bool, bool]:
        """``(admit, as_probe)`` for one placement candidate; call
        holding ``_router_lock``.  OPEN past its cooldown transitions to
        HALF_OPEN here (the timed probe window opens lazily, on the next
        placement that wants the replica)."""
        b = self._breakers.get(rank)
        if b is None or b["state"] == BREAKER_CLOSED:
            return True, False
        if b["state"] == BREAKER_OPEN:
            if now - b["opened_at"] < breaker_cooldown_s():
                return False, False
            self._breaker_set_locked(b, rank, BREAKER_HALF_OPEN)
            b["probing"] = False
        # HALF_OPEN: exactly one real request probes at a time
        if b["probing"]:
            return False, False
        return True, True

    def _breaker_outcome(self, rank: int, ok: bool, slow: bool = False,
                         probe: bool = False) -> None:
        """Feed one classified outcome into a replica's breaker.  A
        clean completion resets the strike streak (and re-closes a
        HALF_OPEN breaker when it was the probe); a failure or a
        sustained-slow observation strikes, and ``breaker_failures()``
        consecutive strikes — or any failed probe — OPEN the breaker."""
        if breaker_failures() <= 0:
            return
        now = time.monotonic()
        with self._router_lock:
            b = self._breaker_locked(rank)
            if probe:
                b["probing"] = False
            if ok and not slow:
                b["strikes"] = 0
                if b["state"] == BREAKER_HALF_OPEN and probe:
                    self._breaker_set_locked(b, rank, BREAKER_CLOSED)
                    opened = False
                else:
                    return
            else:
                b["strikes"] += 1
                opened = (b["state"] == BREAKER_HALF_OPEN or probe
                          or b["strikes"] >= breaker_failures())
                if opened:
                    b["strikes"] = 0
                    b["probing"] = False
                    b["opened_at"] = now
                    b["opens"] += 1
                    self._breaker_set_locked(b, rank, BREAKER_OPEN)
                else:
                    return
        # transitions only, outside the lock: one instant per flip
        obs_spans.instant("router.breaker",
                          replica=rank,
                          state=_BREAKER_NAMES[BREAKER_OPEN if opened
                                               else BREAKER_CLOSED])

    def _breaker_force_open(self, rank: int, why: str) -> None:
        """Fencing/breaker agreement: a replica the membership ledger
        fenced (or the proxy path declared unreachable) is OPEN by
        definition — the two subsystems must never disagree on a dead
        replica."""
        if breaker_failures() <= 0:
            return
        with self._router_lock:
            b = self._breaker_locked(rank)
            if b["state"] == BREAKER_OPEN:
                return
            b["strikes"] = 0
            b["probing"] = False
            b["opened_at"] = time.monotonic()
            b["opens"] += 1
            self._breaker_set_locked(b, rank, BREAKER_OPEN)
        obs_spans.instant("router.breaker", replica=rank, state="open",
                          reason=why)

    def _breaker_clear_probe(self, rank: int) -> None:
        """Release a probe slot without a health verdict (the probe
        request was shed at admission or never started) so the next
        request can probe instead of the window staying wedged."""
        with self._router_lock:
            b = self._breakers.get(rank)
            if b is not None:
                b["probing"] = False

    def _slow_threshold_locked(self) -> float:
        """Latency past which a completion counts as p99 inflation (a
        strike): well past the fleet's own route EWMA, with a floor so
        a cold fleet never strikes on its first compile."""
        per = self._route_ewma_s
        return max(0.25, 4.0 * (per if per is not None else 0.25))

    def _is_slow(self, dur: float) -> bool:
        with self._router_lock:
            return dur > self._slow_threshold_locked()

    # -- the route verb ----------------------------------------------------

    def _handle_route(self, req: Dict) -> Dict:
        tenant = str(req.get("tenant", "default"))
        op = str(req.get("op", ""))
        payload = req.get("payload")
        t0 = time.monotonic()
        try:
            if not op or not isinstance(payload, dict):
                raise CylonError(
                    Code.Invalid,
                    f"malformed route request (op={op!r}, payload is "
                    f"{type(payload).__name__})")
            with obs_spans.span("router.route", tenant=tenant, op=op):
                out = self._route(tenant, op, payload, req, t0)
        except CylonError as e:
            if isinstance(e, RouteShed):
                with self._router_lock:
                    self._route_counts["sheds"] += 1
                obs_metrics.counter_add("router.sheds")
                obs_metrics.counter_add(
                    f"router.sheds[tenant={_safe_label(tenant)}]")
                obs_spans.instant("router.shed", tenant=tenant,
                                  code=e.code.name, reason=e.msg[:200])
            return {"ok": False, "classified": wire.classified(e),
                    **self._ie()}
        dur = time.monotonic() - t0
        with self._router_lock:
            self._route_counts["routed"] += 1
            rank = out["replica"]
            self._per_locked(rank)["served"] += 1
            if not out.get("cache_hit"):
                self._route_ewma_s = dur if self._route_ewma_s is None \
                    else 0.7 * self._route_ewma_s + 0.3 * dur
        obs_metrics.counter_add("router.requests_routed")
        obs_metrics.counter_add(
            f"router.requests_routed[tenant={_safe_label(tenant)},"
            f"replica={out['replica']}]")
        return {"ok": True, **out, **self._ie()}

    def _ie(self) -> Dict:
        return {"incarnation": self.incarnation, "epoch": self._epoch}

    def _route(self, tenant: str, op: str, payload: Dict, req: Dict,
               t0: float) -> Dict:
        """Place + proxy one request to completion (or a classified
        failure) — never a hang: the caller's ``deadline_s`` (or the
        ``CYLON_TPU_ROUTER_TIMEOUT_S`` default) bounds the whole
        journey including re-routes."""
        caller_deadline = req.get("deadline_s")
        deadline_s = float(caller_deadline) \
            if caller_deadline is not None else route_timeout_s()
        deadline = t0 + max(0.05, deadline_s)
        key = wire.request_key(op, payload)
        est = max(0, int(req.get("est_bytes", 0) or 0))
        submit = {"cmd": "submit", "tenant": tenant, "op": op,
                  "payload": payload}
        if caller_deadline is not None:
            # only an EXPLICIT caller budget overrides the replica's
            # tenant deadline table; the router's default bound stays a
            # router-side watchdog, not a per-request budget rewrite
            submit["deadline_s"] = float(caller_deadline)
        exclude: Set[int] = set()
        reroutes = 0
        last_shed: Optional[CylonError] = None
        while True:
            if time.monotonic() >= deadline:
                raise last_shed or CylonError(
                    Code.Timeout,
                    f"route exceeded its {deadline_s:g}s bound before "
                    f"any replica accepted (tenant {tenant!r})")
            try:
                rank, addr, probe = self._place(tenant, key, est, exclude)
            except RouteShed as e:
                # replicas excluded for SHEDDING make "nothing is left"
                # the fleet-saturation case: the last replica-level
                # classified shed (with its retry hint) explains it
                # better than the bare placement view
                raise last_shed or e
            # a fresh idempotency token per placement attempt: the
            # replica dedups control.request's transient-reset retry of
            # an ALREADY-ADMITTED submit (same bytes, same token) back
            # to the same ticket instead of admitting a duplicate
            submit["token"] = token = uuid.uuid4().hex
            try:
                resp = control.request(addr, submit,
                                       timeout=rpc_timeout_s(),
                                       max_line=self.SERVER_MAX_LINE)
            except OSError:
                # no reply — but the submit MAY have been admitted (a
                # reply lost for good, past the token-dedup'd retry).
                # Reap the possible orphan by token; trying the next
                # replica then stays placement, not a re-route.
                self._note_inflight(rank, -1)
                self._breaker_outcome(rank, ok=False, probe=probe)
                self._try_cancel(addr, None, token=token)
                exclude.add(rank)
                continue
            if not resp.get("ok"):
                self._note_inflight(rank, -1)
                if probe:
                    # an admission shed says nothing about health —
                    # release the probe slot, don't judge
                    self._breaker_clear_probe(rank)
                c = resp.get("classified")
                if c is None and resp.get("error"):
                    c = {"msg": str(resp["error"])}
                err = wire.classified_error(c)
                if err.code in (Code.ResourceExhausted, Code.Unavailable):
                    # one replica's shed is not the fleet's: rotate to
                    # the next candidate (_place raises the fleet-wide
                    # classified shed once every replica is excluded)
                    with self._router_lock:
                        self._per_locked(rank)["shed"] += 1
                    last_shed = self._shed_route(
                        tenant, err.code,
                        f"replica {rank} shed: {err.msg}",
                        err.retry_after_s)
                    exclude.add(rank)
                    continue
                raise err  # deterministic (Invalid etc.): propagate
            req_id = str(resp.get("req_id"))
            if reroutes == 0:
                # pin at ACCEPT, not completion: the very next request
                # of this tenant (or of this fingerprint) should land
                # where the queue is forming
                with self._router_lock:
                    self._pin(self._tenant_affinity, tenant, rank)
                    self._pin(self._key_affinity, key, rank)
            primary = _Attempt(rank, addr, req_id, token, probe=probe,
                               is_hedge=False)
            done = self._drive(tenant, op, key, primary, deadline,
                               submit, est, exclude)
            if done is not None:
                with self._router_lock:
                    self._pin(self._key_affinity, key, done["replica"])
                return {**done, "reroutes": reroutes}
            # every attempt died with the request queued-not-dispatched:
            # re-route it to a survivor — never silently lost
            reroutes += 1
            exclude.add(rank)
            with self._router_lock:
                self._route_counts["reroutes"] += 1
                self._per_locked(rank)["rerouted_away"] += 1
            obs_metrics.counter_add("router.reroutes")
            obs_metrics.counter_add(f"router.reroutes[replica={rank}]")
            obs_spans.instant("router.reroute", tenant=tenant, op=op,
                              dead_replica=rank)

    def _per_locked(self, rank: int) -> Dict[str, int]:
        """One replica's counter row; call holding ``_router_lock``."""
        return self._per_replica.setdefault(rank,
                                            dict(_PER_REPLICA_ZERO))

    def _note_inflight(self, rank: int, delta: int) -> None:
        with self._router_lock:
            n = self._inflight.get(rank, 0) + delta
            if n > 0:
                self._inflight[rank] = n
            else:
                self._inflight.pop(rank, None)

    # -- the proxy drive loop (hedged requests live here) ------------------

    def _note_latency(self, key: str, dur: float) -> None:
        """Fold one observed route latency into the per-fingerprint
        asymmetric-EWMA p99 (rises fast toward outliers, decays slowly
        — the PR-13 tail estimator): the hedge delay for the NEXT
        request of this fingerprint."""
        with self._router_lock:
            est = self._key_p99_s.pop(key, None)
            if est is None:
                est = dur
            elif dur > est:
                est += 0.5 * (dur - est)
            else:
                est -= 0.01 * (est - dur)
            self._key_p99_s[key] = est
            while len(self._key_p99_s) > AFFINITY_CAP:
                self._key_p99_s.pop(next(iter(self._key_p99_s)))

    def _hedge_delay_s(self, op: str, key: str,
                       primary_rank: int) -> Optional[float]:
        """Seconds after the primary submit before a hedge may fire, or
        None when this request must never hedge: hedging off (the
        ``CYLON_TPU_ROUTER_HEDGE_MS`` floor is 0), or a custom op whose
        registration on the PRIMARY replica did not declare
        ``idempotent=True`` — a speculative duplicate of a handler with
        unknown side effects is exactly the bug the opt-in exists to
        prevent.  The built-in journaled ops are always safe (the
        PR-6/14 fingerprint-idempotency contract)."""
        floor = hedge_floor_ms()
        if floor <= 0:
            return None
        if op not in HEDGE_SAFE_OPS:
            v = self._replica_view().get(primary_rank)
            if v is None or op not in v["idempotent_ops"]:
                return None
        with self._router_lock:
            est = self._key_p99_s.get(key)
        return max(floor / 1000.0, est if est is not None else 0.0)

    def _release(self, a: _Attempt) -> None:
        if not a.released:
            a.released = True
            self._note_inflight(a.rank, -1)

    def _try_hedge(self, tenant: str, op: str, key: str, submit: Dict,
                   est: int, attempts: List[_Attempt], exclude: Set[int],
                   hedge_exclude: Set[int]) -> Optional[_Attempt]:
        """Speculatively place the request on a SECOND replica.  Returns
        the admitted hedge attempt, or None when no eligible replica
        could take it right now (the caller may try again next tick).
        Custom ops restrict the target set to replicas whose telemetry
        declares the op idempotent — a hedge lands only where the
        registration promised safety."""
        avoid = exclude | hedge_exclude | {a.rank for a in attempts}
        if op not in HEDGE_SAFE_OPS:
            view = self._replica_view()
            avoid |= {r for r, v in view.items()
                      if op not in v["idempotent_ops"]}
        try:
            rank, addr, probe = self._place(tenant, key, est, avoid)
        except RouteShed:
            return None
        sub = dict(submit)
        sub["token"] = token = uuid.uuid4().hex
        try:
            resp = control.request(addr, sub, timeout=rpc_timeout_s(),
                                   max_line=self.SERVER_MAX_LINE)
        except OSError:
            self._note_inflight(rank, -1)
            self._breaker_outcome(rank, ok=False, probe=probe)
            self._try_cancel(addr, None, token=token)
            hedge_exclude.add(rank)
            return None
        if not resp.get("ok"):
            # a shed (or any refusal) of the SPECULATIVE copy never
            # fails or sheds the request — the primary is still running
            self._note_inflight(rank, -1)
            if probe:
                self._breaker_clear_probe(rank)
            hedge_exclude.add(rank)
            return None
        return _Attempt(rank, addr, str(resp.get("req_id")), token,
                        probe=probe, is_hedge=True)

    def _drive(self, tenant: str, op: str, key: str, primary: _Attempt,
               deadline: float, submit: Dict, est: int,
               exclude: Set[int]) -> Optional[Dict]:
        """Drive one admitted request to a terminal state, hedging onto
        a second replica when the primary outlives the fingerprint's
        hedge delay.  Returns the winner's terminal dict (with
        ``replica``/``hedged``/``hedge_won``), raises the classified
        error, or returns None when EVERY attempt died with the request
        queued-not-dispatched (the caller re-routes).

        First terminal ticket wins; losers are proxy-cancelled (the
        serve layer stops them at a pass boundary) and their replicas
        take a sustained-slow breaker strike — losing your own request
        to a hedge IS the p99-inflation signal.  A death after a ticket
        was observed ``running`` abandons that ATTEMPT; the request
        itself survives as long as another attempt lives (the hedge
        exists only for idempotent ops, so the duplicate execution the
        abandon contract bans was already declared safe).

        Two contracts the wire imposes: (a) the queued-vs-running
        distinction is observed at POLLING granularity — exact for the
        journaled built-in ops (a survivor consumes the dead replica's
        journaled passes bit-identically); (b) a terminal reply read
        here is ACKNOWLEDGED back to the replica — the ticket survives
        a reply lost on the wire and drops only on the ack."""
        attempts: List[_Attempt] = [primary]
        hedge_exclude: Set[int] = set()
        hedge_fired = False
        hedge_tries = 0
        delay = self._hedge_delay_s(op, key, primary.rank)
        hedge_at = None if delay is None else primary.t_submit + delay
        try:
            while True:
                now = time.monotonic()
                if now >= deadline:
                    for a in attempts:
                        self._try_cancel(a.addr, a.req_id)
                        self._breaker_outcome(a.rank, ok=False,
                                              probe=a.probe)
                    raise CylonError(
                        Code.Timeout,
                        f"routed request exceeded its deadline on "
                        f"replica(s) "
                        f"{sorted(a.rank for a in attempts)} (tenant "
                        f"{tenant!r}); proxied ticket(s) cancelled at "
                        f"the next pass boundary")
                if hedge_at is not None and not hedge_fired \
                        and now >= hedge_at and len(attempts) == 1:
                    hedge_tries += 1
                    if hedge_tries > 3:
                        hedge_at = None  # stop shopping a hedge around
                    else:
                        a2 = self._try_hedge(tenant, op, key, submit,
                                             est, attempts, exclude,
                                             hedge_exclude)
                        if a2 is not None:
                            attempts.append(a2)
                            hedge_fired = True
                            with self._router_lock:
                                self._route_counts["hedges_fired"] += 1
                            obs_metrics.counter_add("router.hedges_fired")
                            obs_spans.instant(
                                "router.hedge_fired", tenant=tenant,
                                op=op, primary=primary.rank,
                                hedge=a2.rank, delay_s=round(delay, 4))
                for a in list(attempts):
                    kind, val = self._poll_attempt_once(tenant, a)
                    if kind == "done":
                        return self._settle(tenant, key, attempts, a,
                                            val, hedge_fired)
                    if kind == "error":
                        if isinstance(val, CylonError) \
                                and val.code in _STRIKE_CODES:
                            self._breaker_outcome(a.rank, ok=False,
                                                  probe=a.probe)
                        elif a.probe:
                            self._breaker_clear_probe(a.rank)
                        if len(attempts) > 1:
                            # the OTHER attempt may still win: a
                            # per-replica transient must not fail a
                            # request whose hedge is healthy
                            attempts.remove(a)
                            self._release(a)
                            exclude.add(a.rank)
                            continue
                        raise val
                    if kind == "dead":
                        self._breaker_force_open(
                            a.rank, "unreachable from the proxy path")
                        if len(attempts) > 1:
                            self._try_cancel(a.addr, a.req_id)
                            attempts.remove(a)
                            self._release(a)
                            exclude.add(a.rank)
                            continue
                        # sole attempt: the exact PR-14 death contract
                        # (None re-routes queued work; observed-running
                        # raises the abandon-don't-retry classified)
                        return self._on_replica_death(
                            tenant, a.rank, a.addr, a.req_id,
                            a.observed_running)
                time.sleep(poll_interval_s())
        finally:
            for a in attempts:
                self._release(a)

    def _settle(self, tenant: str, key: str, attempts: List[_Attempt],
                winner: _Attempt, done: Dict, hedge_fired: bool) -> Dict:
        """First terminal ticket wins: cancel every loser (the serve
        layer stops it at a pass boundary), strike its replica's breaker
        (losing to a hedge is the latency-inflation signal), and feed
        the winner's latency into the fingerprint's hedge clock."""
        dur = time.monotonic() - winner.t_submit
        self._note_latency(key, dur)
        for o in attempts:
            if o is winner:
                continue
            self._try_cancel(o.addr, o.req_id)
            self._release(o)
            self._breaker_outcome(o.rank, ok=False, slow=True,
                                  probe=o.probe)
            with self._router_lock:
                self._route_counts["hedges_lost_cancelled"] += 1
                self._per_locked(o.rank)["hedged_away"] += 1
            obs_metrics.counter_add("router.hedges_lost_cancelled")
            obs_spans.instant("router.hedge_lost", tenant=tenant,
                              replica=o.rank, winner=winner.rank)
        self._breaker_outcome(winner.rank, ok=True,
                              slow=self._is_slow(dur),
                              probe=winner.probe)
        if winner.is_hedge:
            with self._router_lock:
                self._route_counts["hedges_won"] += 1
            obs_metrics.counter_add("router.hedges_won")
        return {**done, "replica": winner.rank,
                "hedged": 1 if hedge_fired else 0,
                "hedge_won": winner.is_hedge}

    def _poll_attempt_once(self, tenant: str,
                           a: _Attempt) -> Tuple[str, Optional[object]]:
        """One poll round for one attempt: ``("pending", None)``,
        ``("done", terminal-dict)``, ``("error", CylonError)`` (already
        acked when terminal), or ``("dead", None)`` — the replica is
        fenced/unreachable and the caller decides what that means for
        the request (re-route, abandon, or drop-the-attempt)."""
        if self._replica_dead(a.rank):
            return "dead", None
        try:
            resp = control.request(a.addr,
                                   {"cmd": "poll", "req_id": a.req_id},
                                   timeout=rpc_timeout_s(),
                                   max_line=self.SERVER_MAX_LINE)
        except control.ProtocolError as e:
            # DETERMINISTIC, not a death: the reply exceeds the
            # data-plane line cap — every retry would fail the same
            # way, and counting it toward MAX_PROXY_FAILURES would
            # declare a healthy replica dead and re-route into the
            # same wall.  Same classification the request path gives
            # oversize, naming the knob; the terminal ticket is acked
            # away so the replica doesn't hold it forever.
            self._try_ack(a.addr, a.req_id)
            return "error", CylonError(
                Code.SerializationError,
                f"replica {a.rank}'s reply exceeds the "
                f"{self.SERVER_MAX_LINE}-byte "
                f"CYLON_TPU_ROUTER_MAX_LINE_BYTES wire cap (tenant "
                f"{tenant!r}); raise the knob (router AND replicas) "
                f"or ship less data per request")
        except OSError:
            a.fails += 1
            if a.fails >= MAX_PROXY_FAILURES or self._replica_dead(a.rank):
                return "dead", None
            return "pending", None
        a.fails = 0
        state = resp.get("state")
        if not resp.get("ok"):
            if state == "unknown":
                # the replica lost track of an ADMITTED ticket
                # (TICKET_CAP eviction, a data-plane restart): the
                # replica's failure, not the caller's — classified
                # RETRYABLE, never the replica's unknown-req_id
                # Code.Invalid (which would read as a caller bug)
                return "error", CylonError(
                    Code.Unavailable,
                    f"replica {a.rank} lost track of an admitted "
                    f"request (ticket evicted or replica restarted; "
                    f"tenant {tenant!r}) — resubmit to replay "
                    f"journaled passes",
                    retry_after_s=self._retry_after(0))
            return "error", wire.classified_error(resp.get("classified"))
        if state == "done":
            self._try_ack(a.addr, a.req_id)
            return "done", {"result": resp.get("result"),
                            "stats": resp.get("stats"),
                            "cache_hit": bool(resp.get("cache_hit"))}
        if state in ("failed", "cancelled", "shed"):
            self._try_ack(a.addr, a.req_id)
            return "error", wire.classified_error(resp.get("classified"))
        if state == "running":
            a.observed_running = True
        return "pending", None

    def _on_replica_death(self, tenant: str, rank: int,
                          addr: Tuple[str, int], req_id: str,
                          observed_running: bool) -> Optional[Dict]:
        if not observed_running:
            # queued-not-dispatched: the caller re-routes.  The replica
            # may be merely UNREACHABLE (3 failed RPCs, not yet fenced)
            # rather than dead — best-effort cancel the queued ticket
            # first, so a replica that recovers does not run work the
            # survivor is about to run too (swallowed if it really died)
            self._try_cancel(addr, req_id)
            return None
        # in-flight on a dead mesh: abandon, don't retry — re-running
        # half-finished device work into a fresh replica is the desync
        # the PR-6 contract bans; the CALLER retries with a fresh
        # classified hint (completed passes are journaled, so the retry
        # is cheap)
        self._try_cancel(addr, req_id)
        with self._router_lock:
            self._route_counts["abandoned"] += 1
        obs_metrics.counter_add("router.abandoned")
        obs_spans.instant("router.abandoned", tenant=tenant,
                          dead_replica=rank)
        raise CylonError(
            Code.Unavailable,
            f"replica {rank} died with this request in flight (tenant "
            f"{tenant!r}); in-flight work is abandoned, not retried — "
            f"resubmit to replay journaled passes",
            retry_after_s=self._retry_after(0))

    def _try_cancel(self, addr: Tuple[str, int],
                    req_id: Optional[str],
                    token: Optional[str] = None) -> None:
        """Best-effort cancel by ``req_id`` or by idempotency ``token``
        — the token form reaps an orphan whose submit accept reply was
        lost (the router never learned its req_id)."""
        obj: Dict = {"cmd": "cancel"}
        if req_id is not None:
            obj["req_id"] = req_id
        if token is not None:
            obj["token"] = token
        try:
            control.request(addr, obj, timeout=rpc_timeout_s(),
                            retries=0, max_line=self.SERVER_MAX_LINE)
        except OSError:
            pass  # the replica is gone; nothing to cancel

    def _try_ack(self, addr: Tuple[str, int], req_id: str) -> None:
        """Terminal reply read: tell the replica the ticket may drop.
        Best-effort — an unacked terminal ticket ages out past the
        replica's TICKET_CAP."""
        try:
            control.request(addr, {"cmd": "ack", "req_id": req_id},
                            timeout=rpc_timeout_s(), retries=0,
                            max_line=self.SERVER_MAX_LINE)
        except OSError:
            pass  # ack is insurance, not a contract

    # -- introspection -----------------------------------------------------

    def router_status(self) -> Dict:
        """The routing table the ``status`` verb ships and
        ``tools/fleet_status.py --replicas`` renders: per-replica
        capacity/depth/headroom plus served/shed/re-route/hedge
        counters, breaker state, and the current affinity pins.
        ``breakers`` lists EVERY known breaker (dead replicas included,
        forced open first — fencing and breaker state must never
        disagree on a dead replica), while ``replicas`` rows cover the
        live serving set."""
        view = self._replica_view()
        with self._lock:
            fenced = set(self._dead)
        for r in fenced:
            self._breaker_force_open(r, "fenced by the membership "
                                        "detector")
        with self._router_lock:
            counts = dict(self._route_counts)
            per = {r: dict(c) for r, c in sorted(self._per_replica.items())}
            tenants = dict(self._tenant_affinity)
            keys = len(self._key_affinity)
            inflight = dict(self._inflight)
            breakers = {r: dict(b) for r, b in sorted(self._breakers
                                                      .items())}
        replicas = {}
        for rank, v in sorted(view.items()):
            b = breakers.get(rank, _BREAKER_ZERO)
            replicas[str(rank)] = {
                "addr": f"{v['addr'][0]}:{v['addr'][1]}",
                "capacity": v["capacity"],
                "queue_depth": v["reported_depth"],
                "router_inflight": inflight.get(rank, 0),
                "hbm_headroom_bytes": v["headroom"],
                **per.get(rank, _PER_REPLICA_ZERO),
                "breaker": _BREAKER_NAMES[b["state"]],
                "breaker_opens": b["opens"],
                "breaker_probes": b["probes"],
                "tenants_pinned": sorted(
                    t for t, r in tenants.items() if r == rank),
            }
        return {"replicas": replicas, "replicas_live": len(view),
                "cache_affinity": cache_affinity_enabled(),
                "key_pins": keys,
                "hedging": hedge_floor_ms() > 0,
                "breakers": {str(r): _BREAKER_NAMES[b["state"]]
                             for r, b in breakers.items()},
                **counts}


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RouterClient:
    """Caller-side handle for the ``route`` verb: encodes the request
    onto the wire (`cylon_tpu.router.wire`), ships it, blocks for the
    reply, and re-raises classified failures as `CylonError` —
    callers see the same contract `QueryService.submit(...).result()`
    gives them locally, with the fleet behind it."""

    def __init__(self, address, timeout_s: Optional[float] = None):
        if isinstance(address, (tuple, list)):
            self._addr: Tuple[str, int] = (str(address[0]),
                                           int(address[1]))
        else:
            host, _, port = str(address).rpartition(":")
            if not host or not port:
                raise CylonError(Code.Invalid,
                                 f"bad router address {address!r} "
                                 f"(want host:port)")
            self._addr = (host, int(port))
        self._timeout = timeout_s

    def route(self, tenant: str, op: str, *args,
              deadline_s: Optional[float] = None,
              timeout_s: Optional[float] = None, **kwargs):
        """One routed request: returns ``(result, stats)`` with
        ``stats["router"]`` carrying the serving replica, re-route
        count, and cache-hit flag; raises the classified `CylonError`
        on shed/failure/timeout.  The active trace context rides the
        verb (net/control.py), so the routed run joins the caller's
        trace."""
        payload = wire.encode_payload(args, kwargs)
        obj: Dict = {"cmd": "route", "tenant": str(tenant),
                     "op": str(op)}
        if deadline_s is not None:
            obj["deadline_s"] = float(deadline_s)
        cap = router_max_line()
        # the base64 payload dominates the encoded line; estimating its
        # size skips a second json.dumps of the whole object on the hot
        # path (send_json performs the ONLY full serialization).  The
        # non-payload fields are measured EXACTLY — a pathological
        # tenant/op string must hit this classified refusal too, not a
        # server-side connection drop read as retryable
        nbytes = (wire.payload_nbytes(payload)
                  + len(json.dumps(obj, sort_keys=True)))
        obj["payload"] = payload
        if nbytes + 1024 > cap:
            raise CylonError(
                Code.SerializationError,
                f"encoded route request is ~{nbytes} bytes — past the "
                f"{cap}-byte CYLON_TPU_ROUTER_MAX_LINE_BYTES wire cap; "
                f"raise the knob (router AND replicas) or ship less "
                f"data per request")
        # ~2x input residency is the serve layer's admission estimate;
        # base64 already inflated the frames 4/3, so the encoded line
        # length is the right order of magnitude for the headroom guard
        obj["est_bytes"] = 2 * nbytes
        budget = deadline_s if deadline_s is not None \
            else route_timeout_s()
        timeout = timeout_s if timeout_s is not None \
            else (self._timeout if self._timeout is not None
                  else budget + 30.0)
        try:
            # retries=0 ON PURPOSE: the route verb blocks server-side
            # for the whole proxied run, so a transparent resend of the
            # line would start a SECOND placement while the first
            # handler thread may still be driving the original to
            # completion.  A dropped connection surfaces classified and
            # retryable instead — the caller's resubmit replays
            # journaled passes, it does not double device work.
            resp = control.request(self._addr, obj, timeout=timeout,
                                   retries=0, max_line=cap)
        except control.ProtocolError as e:
            # the REPLY outgrew this client's cap (the router's own cap
            # may be higher — knobs are read per process): deterministic,
            # a retry hits the same wall, so never classified retryable
            raise CylonError(
                Code.SerializationError,
                f"routed reply exceeds this client's {cap}-byte "
                f"CYLON_TPU_ROUTER_MAX_LINE_BYTES wire cap ({e}); raise "
                f"the knob (client, router AND replicas) or ship less "
                f"data per request") from e
        except OSError as e:
            raise CylonError(
                Code.Unavailable,
                f"query router at {self._addr[0]}:{self._addr[1]} "
                f"unreachable or dropped mid-route "
                f"({type(e).__name__}: {e}); the routed request may "
                f"still complete server-side — a resubmit replays "
                f"journaled passes, never re-executes them") from e
        if not resp.get("ok"):
            if resp.get("status") == "stale_coordinator":
                # PR-11 split-brain: a superseded router incarnation is
                # still bound — retryable, not a caller bug
                raise CylonError(
                    Code.Unavailable,
                    f"query router at {self._addr[0]}:{self._addr[1]} "
                    f"answered stale (superseded by incarnation "
                    f"{resp.get('incarnation')}); re-resolve the router "
                    f"address and retry", retry_after_s=1.0)
            if "classified" in resp:
                raise wire.classified_error(resp["classified"])
            raise CylonError(Code.UnknownError,
                             f"route failed: {resp.get('error', resp)}")
        result = wire.decode_value(resp.get("result"))
        stats = dict(resp.get("stats") or {})
        stats["router"] = {"replica": resp.get("replica"),
                           "reroutes": resp.get("reroutes", 0),
                           "cache_hit": bool(resp.get("cache_hit")),
                           "hedged": int(resp.get("hedged", 0) or 0),
                           "hedge_won": bool(resp.get("hedge_won"))}
        return result, stats

    def status(self, timeout_s: float = 5.0) -> Dict:
        return control.request(self._addr, {"cmd": "status"},
                               timeout=timeout_s,
                               max_line=router_max_line())
