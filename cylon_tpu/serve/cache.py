"""The durable journal exposed as a result cache.

durable.py already fingerprints every chunked run (op x full input
content x result-affecting knobs) and replays journaled passes instead
of executing them — so a REPEATED query is, mechanically, a cache hit:
the engine consumes the journal prefix before it would build a program,
and a complete journal means zero compiles and zero device passes.
This module is the serving-side view of that machinery:

- :func:`served_from_journal` — the post-run predicate the service uses
  to count ``serve.cache_hit`` (every pass loaded from spill, nothing
  executed);
- :func:`contents` — the cache inventory (fingerprint, bytes, LRU
  mtime, completeness) straight off the journal root;
- :func:`maybe_gc` — the ``CYLON_TPU_DURABLE_CAP_BYTES`` LRU eviction
  (durable.gc_journal), counted under ``serve.cache_evictions``.

Eviction is manifest-LAST (durable._evict_run_dir): a reader racing an
eviction sees spills that fail their checksums and re-executes those
passes — a slower answer, never a torn one.  The ``cache_evict_race``
fault kind drives that window deterministically in tests.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from .. import durable
from ..obs import metrics as obs_metrics


def served_from_journal(stats: dict) -> bool:
    """True when a run's stats show it was answered ENTIRELY from the
    journal: at least one pass replayed from spill and zero passes
    executed on device — the serving layer's definition of a result-
    cache hit."""
    return (stats.get("passes_skipped", 0) > 0
            and stats.get("parts_run", 0) == 0)


def contents(root: Optional[str] = None) -> List[dict]:
    """Cache inventory, least-recently-used first: one dict per journaled
    run (``fingerprint``, ``bytes``, ``mtime``, ``complete`` — complete
    runs are servable end-to-end; incomplete ones only shorten a
    re-execution)."""
    return durable.scan_runs(root)


def cache_bytes(root: Optional[str] = None) -> int:
    return sum(r["bytes"] for r in durable.scan_runs(root))


def maybe_gc(root: Optional[str] = None) -> Tuple[int, int]:
    """Run the size-cap LRU eviction when ``CYLON_TPU_DURABLE_CAP_BYTES``
    is set; ``(runs_evicted, bytes_freed)``.  Safe to call after every
    request — without a cap it is a single knob read."""
    evicted, freed = durable.gc_journal(root)
    if evicted:
        obs_metrics.counter_add("serve.cache_evictions", evicted)
    return evicted, freed
