"""Multi-tenant query service: admission control, per-tenant budgets,
load shedding, cancellation, and graceful drain over ONE mesh.

The ROADMAP's "millions of users" is many concurrent small-to-medium
queries sharing one TPU mesh, not one giant query — and before this
module the process had no overload story: a runaway caller could wedge
the device queue or OOM the whole process, and every other caller died
with it.  The reference has no serving layer at all (PAPER.md §5 — its
unit of deployment is one MPI job per query), so this is where the TPU
build overtakes it.  The design makes overload a *classified,
recoverable* condition:

- **admission control** — submissions pass host-side checks on the
  CALLER's thread and either enter a BOUNDED queue or are shed
  immediately with `Code.ResourceExhausted` / `Code.Unavailable` and a
  ``retry_after_s`` hint (`CylonError.retry_after_s`).  Nothing ever
  waits unboundedly: the queue cap (``CYLON_TPU_SERVE_QUEUE_CAP``), a
  per-tenant share of it (``CYLON_TPU_SERVE_TENANT_SHARE`` — one
  flooding tenant sheds alone while others keep admitting), and an
  optional per-tenant HBM admission estimate
  (``CYLON_TPU_SERVE_HBM_BUDGET_BYTES``, checked against the
  ``hbm.live_bytes`` watermark BEFORE any device allocation) all reject
  deterministically.

- **one scheduler, one mesh** — a single daemon thread pops admitted
  tickets and runs them serially through the chunked engine (exec.py),
  the only execution discipline XLA's in-order device queues actually
  honor.  Scheduling decisions (`_dispatch_next`) are device-free by
  contract — cylint CY107 machine-checks that no blocking device call
  is reachable from the admission/dispatch path, so a wedged device can
  delay RESULTS but never admission or shedding.

- **per-tenant budgets through the existing substrate** — deadlines arm
  the `Code.Timeout` watchdog (durable.PassDeadline) over the whole
  request and stop it at the next pass boundary; repeated failures
  quarantine the TENANT (``CYLON_TPU_SERVE_QUARANTINE_AFTER`` /
  ``_QUARANTINE_S``) the way the engine quarantines poison passes — a
  poison tenant is shed with `Code.Unavailable` + retry-after while
  everyone else keeps being served.

- **the journal as a result cache** — with ``CYLON_TPU_DURABLE_DIR``
  set, a repeated fingerprint (durable.py already keys op x input
  content x knobs) replays entirely from spill: zero compiles, zero
  device passes (``serve.cache_hit``; serve/cache.py).  The
  ``CYLON_TPU_DURABLE_CAP_BYTES`` LRU GC bounds it.

- **cancellation + graceful drain** — ``Ticket.cancel()`` removes
  queued work (`Code.Cancelled`) or stops a running request at the next
  pass boundary (completed passes stay journaled, so a re-submit
  resumes); ``drain()`` sheds the queue with `Code.Unavailable` and
  lets the in-flight request finish or journal.

Everything is host-side threading + the existing engine — no new traced
code, so the jaxpr collective-budget goldens are untouched by
construction.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from .. import config
from .. import durable
from .. import exec as exec_mod
from .. import resilience
from ..obs import fleet as obs_fleet
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..obs import tracectx
from ..status import Code, CylonError, Status
from . import cache as cache_mod


# ---------------------------------------------------------------------------
# knob accessors (registry rows in config.py::KNOBS)
# ---------------------------------------------------------------------------

def queue_cap() -> int:
    return max(1, int(config.knob("CYLON_TPU_SERVE_QUEUE_CAP")))


def tenant_share() -> float:
    return min(1.0, max(0.0, float(config.knob("CYLON_TPU_SERVE_TENANT_SHARE"))))


def hbm_budget_bytes() -> int:
    return max(0, int(config.knob("CYLON_TPU_SERVE_HBM_BUDGET_BYTES")))


def default_deadline_s() -> float:
    return max(0.0, float(config.knob("CYLON_TPU_SERVE_DEADLINE_S")))


def tenant_quarantine_after() -> int:
    return max(0, int(config.knob("CYLON_TPU_SERVE_QUARANTINE_AFTER")))


def tenant_quarantine_s() -> float:
    return max(0.0, float(config.knob("CYLON_TPU_SERVE_QUARANTINE_S")))


# the ctor's ``queue_cap=`` parameter shadows the accessor's name
_default_queue_cap = queue_cap


def _slo_tenant(tenant: str) -> str:
    """The tenant id as spelled inside an SLO histogram key: brackets
    are remapped because every parser of these keys (``telemetry``,
    tools/trace_report.py ``slo_rows``) splits on the first ``[`` and
    strips one trailing ``]`` — a raw ``t[1]`` would silently vanish
    from the SLO view."""
    return tenant.replace("[", "(").replace("]", ")")


def _slo_key(kind: str, tenant: str) -> str:
    """Metric key of one tenant's SLO latency histogram:
    ``serve.<kind>[<tenant>]`` — kind is ``queue_wait_ms`` (admission to
    dispatch) or ``run_ms`` (dispatch to terminal).  Consumers split on
    the first ``[``; tools/trace_report.py renders these as the
    per-tenant SLO table and the elastic coordinator aggregates them
    fleet-wide in its ``status`` verb."""
    return f"serve.{kind}[{_slo_tenant(tenant)}]"


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------

#: ops the service admits — each maps to a chunked-engine entry point
#: accepting ``ctx=`` and ``pass_guard=`` (the cancellation hook)
OPS = ("join", "join_groupby", "groupby", "sort", "plan", "refresh")


def _run_plan(plan, *, ctx=None, pass_guard=None, **kw):
    """Serve runner for whole logical plans (``submit(tenant, "plan",
    table.plan()...)``): executes through the plan optimizer/executor
    and journals at PLAN granularity — one fingerprint for the whole op
    chain, so a repeated multi-op query is one result-cache entry.
    Lazy import: the plan package pulls the optimizer stack, which a
    serve-only process may never need."""
    from .. import plan as plan_mod

    return plan_mod.run_service(plan, ctx=ctx, pass_guard=pass_guard, **kw)


def _run_refresh(query_or_spec, *args, ctx=None, pass_guard=None, **kw):
    """Serve runner for streaming refreshes (``submit(tenant, "refresh",
    query_or_spec)``): accepts a built stream query object or its JSON
    spec (a replica sharing the durable dir rebuilds the stream from the
    manifest, which is what makes the op router-routable).  Idempotent
    by construction — the result fingerprint folds the stream's high-
    watermark batch id, so a refresh with no new batches is a pure
    cache hit and a hedged duplicate lands on the same journal entry.
    Lazy import: a serve-only process that never streams should not pay
    for the stream package."""
    from .. import stream as stream_mod

    return stream_mod.run_refresh(query_or_spec, *args, ctx=ctx,
                                  pass_guard=pass_guard, **kw)


_RUNNERS = {
    "join": exec_mod.chunked_join,
    "join_groupby": exec_mod.chunked_join_groupby_tables,
    "groupby": exec_mod.chunked_groupby,
    "sort": exec_mod.chunked_sort,
    "plan": _run_plan,
    "refresh": _run_refresh,
}


#: custom ops whose registration declared ``idempotent=True`` — the
#: router's hedging safety gate (built-in OPS are fingerprint-idempotent
#: by the PR-6 journal contract and need no declaration)
_IDEMPOTENT_OPS: set = set()


def register_op(op: str, runner, *, idempotent: bool = False) -> None:
    """Register a custom serve op: ``runner(*args, ctx=, pass_guard=,
    **kwargs) -> (result, stats)``.  The runner executes on the
    scheduler thread under the request's trace context, with the same
    cancellation/deadline guard every built-in op gets — the extension
    point the cross-rank tracing smoke uses to drive an elastic gang
    from one serve request.

    ``idempotent=True`` declares that re-running the op with the same
    arguments is side-effect-safe and bit-identical — the opt-in that
    lets the fleet router HEDGE requests for this op onto a second
    replica (a hedge never fires for an undeclared custom op: the
    router cannot know a handler's side effects)."""
    op = str(op)
    _RUNNERS[op] = runner
    if idempotent:
        _IDEMPOTENT_OPS.add(op)
    else:
        _IDEMPOTENT_OPS.discard(op)

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"
SHED = "shed"


@dataclass(frozen=True)
class TenantBudget:
    """Per-tenant overrides of the service-wide budget knobs.  None
    inherits the knob default."""

    deadline_s: Optional[float] = None    # request wall-clock budget
    hbm_bytes: Optional[int] = None       # admission HBM estimate cap
    max_queued: Optional[int] = None      # queued-request cap (share
                                          # of the queue otherwise)


class Ticket:
    """One admitted request: a caller-side handle carrying the result
    event, the terminal state, the cancel signal, and the request's
    causal trace context (``trace.trace_id`` joins this request to its
    spans across every rank it touched)."""

    def __init__(self, service: "QueryService", tenant: str, op: str,
                 args, kwargs,
                 trace: Optional[tracectx.TraceContext] = None,
                 deadline_s: Optional[float] = None):
        self._service = service
        self.tenant = tenant
        self.op = op
        self.args = args
        self.kwargs = kwargs
        self.deadline_s = deadline_s  # per-REQUEST budget override
        self.state = QUEUED
        self.result_value = None
        self.stats: Optional[dict] = None
        self.error: Optional[CylonError] = None
        self.cache_hit = False
        self.duration_s: Optional[float] = None
        self.queue_wait_s: Optional[float] = None
        self.t_submit = time.perf_counter()
        self.trace = trace
        self._trace_closed = False
        self._event = threading.Event()
        self._cancel = threading.Event()

    @property
    def trace_id(self) -> Optional[str]:
        return self.trace.trace_id if self.trace is not None else None

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block for the outcome: ``(result, stats)`` on success, the
        classified `CylonError` re-raised on failure/cancel/shed.  A
        ``timeout`` miss raises `Code.Timeout` WITHOUT cancelling the
        request — call :meth:`cancel` for that."""
        if not self._event.wait(timeout):
            raise CylonError(Code.Timeout,
                             f"no result within {timeout}s (request "
                             f"{self.op} for tenant {self.tenant!r} is "
                             f"still {self.state})")
        if self.error is not None:
            raise self.error
        return self.result_value, self.stats

    def cancel(self) -> bool:
        """Cancel: a queued request is removed immediately; a running one
        stops at the next pass boundary (the in-flight pass finishes —
        and journals — first).  False when already finished."""
        return self._service._cancel_ticket(self)

    def _finish(self, state: str, *, result=None, stats=None,
                error: Optional[CylonError] = None) -> None:
        self.state = state
        self.result_value = result
        self.stats = stats
        self.error = error
        # EVERY terminal path — completed, failed, cancelled, shed —
        # closes the request's trace exactly once: the tail-retention
        # decision runs here (keep the buffered events, or discard them
        # and keep only the aggregate stopwatch).  Anything that did not
        # complete counts as "failed" for retention — a cancelled or
        # shed request's trace is precisely what the caller will ask
        # about.
        if self.trace is not None and not self._trace_closed:
            self._trace_closed = True
            dur = self.duration_s if self.duration_s is not None \
                else max(0.0, time.perf_counter() - self.t_submit)
            tracectx.finish_request(self.trace, dur * 1e3,
                                    failed=state != DONE)
        self._event.set()


# ---------------------------------------------------------------------------
# the service
# ---------------------------------------------------------------------------

def _estimate_request_bytes(args, kwargs) -> int:
    """Host-side HBM admission estimate: the input frames' byte size
    times a pack factor of 2 (power-of-two chunk capacities + the join
    output roughly double residency).  Positional AND keyword values are
    scanned, so ``submit(t, "join", left=l, right=r)`` cannot slip past
    the budget.  Advisory by design — the engine's OOM recovery remains
    the backstop; this check only keeps a request that PLAINLY cannot
    fit from ever touching the device."""
    total = 0
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, dict):
            for v in a.values():
                nb = getattr(np.asarray(v), "nbytes", 0)
                total += int(nb)
        elif hasattr(a, "approx_input_bytes"):
            # a LogicalPlan: pruned-scan buffer metadata, host-only
            total += int(a.approx_input_bytes())
        else:
            nbytes = getattr(a, "nbytes", None)
            if isinstance(nbytes, (int, np.integer)):
                total += int(nbytes)
    return 2 * total


class _TenantState:
    __slots__ = ("queued", "admitted", "served", "shed", "failed",
                 "cancelled", "cache_hits", "streak", "quarantined_until")

    def __init__(self):
        self.queued = 0
        self.admitted = 0
        self.served = 0
        self.shed = 0
        self.failed = 0
        self.cancelled = 0
        self.cache_hits = 0
        self.streak = 0              # consecutive classified failures
        self.quarantined_until = 0.0


class QueryService:
    """Single-process multi-tenant query service over one mesh (``ctx``
    = None for the local chip, or a distributed `CylonContext`).

    Usage::

        svc = QueryService()
        t = svc.submit("tenant-a", "join", left, right, on="k", passes=2)
        result, stats = t.result(timeout=60)
        svc.close()

    ``submit`` raises `CylonError` (`Code.ResourceExhausted` /
    `Code.Unavailable`, ``retry_after_s`` set) when the request is shed
    at admission; an admitted `Ticket` ALWAYS terminates — completed,
    failed classified, cancelled, or shed by a drain — never a hang.
    """

    def __init__(self, ctx=None, *, queue_cap: Optional[int] = None,
                 budgets: Optional[Dict[str, TenantBudget]] = None,
                 name: str = "serve"):
        self._ctx = ctx
        self._cap = int(queue_cap) if queue_cap is not None \
            else _default_queue_cap()
        self._budgets: Dict[str, TenantBudget] = dict(budgets or {})
        self.name = name
        self._lock = threading.Condition()
        self._queue: "deque[Ticket]" = deque()
        self._running: Optional[Ticket] = None
        self._tenants: Dict[str, _TenantState] = {}
        self._draining = False
        self._closed = False
        self._ewma_s: Optional[float] = None
        self._runners: Dict[str, object] = {}  # instance op overrides
        self._idempotent_ops: set = set()      # declared-hedgeable ops
        self._pending_flight: List[dict] = []  # staged shed dumps
        self._counts = {"admitted": 0, "shed": 0, "completed": 0,
                        "failed": 0, "cancelled": 0, "cache_hits": 0,
                        "tenants_quarantined": 0}
        self._thread = threading.Thread(target=self._scheduler_loop,
                                        name=f"cylon-{name}", daemon=True)
        self._thread.start()

    # -- context manager --------------------------------------------------

    def __enter__(self) -> "QueryService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- admission (caller threads; device-free — cylint CY107) -----------

    def set_budget(self, tenant: str, budget: TenantBudget) -> None:
        with self._lock:
            self._budgets[str(tenant)] = budget

    def _tenant(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            st = self._tenants[tenant] = _TenantState()
        return st

    def _retry_after(self, ahead: int) -> float:
        """When capacity plausibly returns: the request-duration EWMA
        times the work ahead of the caller.  A hint, not a promise."""
        per = self._ewma_s if self._ewma_s is not None else 0.25
        return max(0.05, per * max(1, ahead))

    def _shed(self, tenant: str, code: Code, reason: str,
              retry_after: Optional[float],
              trace: Optional[tracectx.TraceContext] = None) -> CylonError:
        st = self._tenant(tenant)
        st.shed += 1
        self._counts["shed"] += 1
        obs_metrics.counter_add("serve.shed")
        # the shed instant is stamped under the request's trace (the
        # caller's thread has no ambient context during submit — the
        # trace was only just minted), so a shed request's terminal
        # instant joins the trace the caller was handed
        with tracectx.activate(trace):
            obs_spans.instant("serve.shed", tenant=tenant, code=code.name,
                              reason=reason)
        # a shed is a classified terminal event for the caller: the
        # flight dump records the admission state that forced it —
        # STAGED here (every _shed call site holds the service lock) and
        # written by _flush_flight after release, so disk latency never
        # serializes admission under the exact overload being recorded
        self._pending_flight.append(dict(
            tenant=tenant, code=code.name, shed_reason=reason,
            queue_depth=len(self._queue),
            **({"trace_id": trace.trace_id} if trace is not None else {})))
        hint = "" if retry_after is None else f"; retry after ~{retry_after:.2f}s"
        return CylonError(code, f"request shed for tenant {tenant!r}: "
                                f"{reason}{hint}",
                          retry_after_s=retry_after)

    def _flush_flight(self) -> None:
        """Write the shed dumps `_shed` staged under the service lock,
        OUTSIDE it — host-side file IO only, never device work."""
        while True:
            with self._lock:
                if not self._pending_flight:
                    return
                kw = self._pending_flight.pop(0)
            obs_fleet.flight_record("shed", **kw)

    def submit(self, tenant: str, op: str, *args, **kwargs) -> Ticket:
        """Admit one table op (``op`` in :data:`OPS`; ``args``/``kwargs``
        forwarded to the chunked engine) or shed it NOW with a
        classified `CylonError` carrying ``retry_after_s``.  Runs
        entirely on the caller's thread and never blocks on the device
        or the queue."""
        try:
            return self._submit_inner(tenant, op, *args, **kwargs)
        finally:
            self._flush_flight()  # staged shed dumps, lock released

    def _submit_inner(self, tenant: str, op: str, *args,
                      **kwargs) -> Ticket:
        tenant = str(tenant)
        if op not in _RUNNERS and op not in self._runners:
            raise CylonError(Code.Invalid,
                             f"unknown op {op!r} (expected one of {OPS})")
        # mint the request's causal trace BEFORE any admission decision,
        # so even a shed request has an identity the caller can chase
        # through the merged timeline.  A client-supplied ``traceparent=``
        # (the W3C wire form) is adopted as the parent — the request
        # becomes a child span of the caller's own trace; a malformed
        # header is rejected leniently (fresh trace, never a failed
        # submit).
        parent = tracectx.parse_or_none(kwargs.pop("traceparent", None))
        trace = parent.child() if parent is not None \
            else tracectx.new_trace()
        # reserved kwarg: a per-REQUEST wall-clock budget that overrides
        # the tenant/knob default — the router forwards a client's
        # deadline through its extra hop with it, so the budget that
        # fires is the one the CALLER set, not whatever the replica's
        # tenant table happens to say
        deadline_override = kwargs.pop("deadline_s", None)
        if deadline_override is not None:
            deadline_override = max(0.0, float(deadline_override))

        def shed_now(err: CylonError) -> CylonError:
            # an admission shed has no Ticket to close the trace through:
            # close it here (duration = time spent in admission, ~0)
            tracectx.finish_request(trace, 0.0, failed=True)
            return err

        est = _estimate_request_bytes(args, kwargs)
        try:
            resilience.fault_point("serve.admit")
        except Exception as e:
            # an injected admission fault (`tenant_flood`) sheds exactly
            # like a real budget trip — same code, same hint
            with self._lock:
                err = self._shed(tenant, Code.ResourceExhausted,
                                 Status.from_exception(e).msg,
                                 self._retry_after(len(self._queue) + 1),
                                 trace)
            raise shed_now(err)
        with self._lock:
            if self._closed or self._draining:
                raise shed_now(self._shed(tenant, Code.Unavailable,
                                          "service is draining", None,
                                          trace))
            st = self._tenant(tenant)
            now = time.monotonic()
            if st.quarantined_until > now:
                raise shed_now(self._shed(
                    tenant, Code.Unavailable,
                    f"tenant quarantined after {st.streak} "
                    f"consecutive failures",
                    st.quarantined_until - now, trace))
            if st.quarantined_until:
                # cooldown elapsed: the tenant re-enters with a CLEAN
                # failure streak (the knob's contract) — otherwise one
                # transient post-cooldown failure would re-quarantine
                # instantly
                st.quarantined_until = 0.0
                st.streak = 0
            depth = len(self._queue) + (1 if self._running is not None else 0)
            if len(self._queue) >= self._cap:
                raise shed_now(self._shed(
                    tenant, Code.ResourceExhausted,
                    f"admission queue full "
                    f"({len(self._queue)}/{self._cap})",
                    self._retry_after(depth + 1), trace))
            budget = self._budgets.get(tenant)
            tcap = budget.max_queued if budget is not None \
                and budget.max_queued is not None \
                else max(1, int(-(-self._cap * tenant_share() // 1)))
            if st.queued >= tcap:
                raise shed_now(self._shed(
                    tenant, Code.ResourceExhausted,
                    f"tenant queue share full "
                    f"({st.queued}/{tcap} of {self._cap})",
                    self._retry_after(st.queued + 1), trace))
            hbm_cap = budget.hbm_bytes if budget is not None \
                and budget.hbm_bytes is not None else hbm_budget_bytes()
            if hbm_cap > 0:
                live = obs_metrics.record_hbm_watermark()
                if est + live > hbm_cap:
                    raise shed_now(self._shed(
                        tenant, Code.ResourceExhausted,
                        f"HBM admission estimate {est} + live {live} "
                        f"exceeds the {hbm_cap}-byte tenant budget",
                        self._retry_after(depth + 1), trace))
            ticket = Ticket(self, tenant, op, args, kwargs, trace=trace,
                            deadline_s=deadline_override)
            self._queue.append(ticket)
            st.queued += 1
            st.admitted += 1
            self._counts["admitted"] += 1
            obs_metrics.counter_add("serve.admitted")
            obs_metrics.gauge_set("serve.queue_depth", len(self._queue))
            self._lock.notify_all()
        return ticket

    def _cancel_ticket(self, ticket: Ticket) -> bool:
        with self._lock:
            if ticket.done:
                return False
            if ticket in self._queue:
                self._queue.remove(ticket)
                st = self._tenant(ticket.tenant)
                st.queued -= 1
                st.cancelled += 1
                self._counts["cancelled"] += 1
                obs_metrics.counter_add("serve.cancelled")
                obs_metrics.gauge_set("serve.queue_depth", len(self._queue))
                ticket._finish(CANCELLED, error=CylonError(
                    Code.Cancelled,
                    f"request cancelled while queued (tenant "
                    f"{ticket.tenant!r})"))
                return True
        # running (or about to): the pass_guard stops it at the next
        # pass boundary — completed passes stay journaled
        ticket._cancel.set()
        return not ticket.done

    # -- scheduling (the one worker thread) --------------------------------

    _STOP = object()

    def _dispatch_next(self):
        """Pick the next admitted ticket — scheduling decisions ONLY, no
        device work on this path (cylint CY107): a wedged device must
        never block shedding or drain.  Returns a ticket, None (nothing
        actionable this tick), or ``_STOP``."""
        try:
            return self._dispatch_inner()
        finally:
            self._flush_flight()

    def _dispatch_inner(self):
        with self._lock:
            while not self._queue:
                if self._closed:
                    return self._STOP
                self._lock.wait(0.05)
            ticket = self._queue.popleft()
            st = self._tenant(ticket.tenant)
            st.queued -= 1
            obs_metrics.gauge_set("serve.queue_depth", len(self._queue))
            self._running = ticket
        if ticket._cancel.is_set():
            self._finish_cancelled(ticket, "before dispatch")
            with self._lock:
                self._running = None
                self._lock.notify_all()
            return None
        try:
            resilience.fault_point("serve.dispatch")
        except Exception as e:
            with self._lock:
                err = self._shed(ticket.tenant, Code.Unavailable,
                                 Status.from_exception(e).msg,
                                 self._retry_after(1), ticket.trace)
                self._running = None
                self._lock.notify_all()
            ticket._finish(SHED, error=err)
            return None
        return ticket

    def _scheduler_loop(self) -> None:
        while True:
            ticket = self._dispatch_next()
            if ticket is self._STOP:
                return
            if ticket is None:
                continue
            try:
                self._run_ticket(ticket)
            finally:
                with self._lock:
                    self._running = None
                    self._lock.notify_all()

    def _finish_cancelled(self, ticket: Ticket, where: str) -> None:
        with self._lock:
            st = self._tenant(ticket.tenant)
            st.cancelled += 1
            self._counts["cancelled"] += 1
            obs_metrics.counter_add("serve.cancelled")
        ticket._finish(CANCELLED, error=CylonError(
            Code.Cancelled, f"request cancelled {where} (tenant "
                            f"{ticket.tenant!r})"))

    # -- execution (device work lives here and only here) ------------------

    def _request_deadline_s(self, tenant: str) -> float:
        b = self._budgets.get(tenant)
        if b is not None and b.deadline_s is not None:
            return max(0.0, float(b.deadline_s))
        return default_deadline_s()

    def register_op(self, op: str, runner, *,
                    idempotent: bool = False) -> "QueryService":
        """Instance-scoped op registration: like the module-level
        :func:`register_op` but visible only to THIS service — two
        replicas in one process (the router tests' rendering) can serve
        the same op name through different runners.  ``idempotent=True``
        declares the op hedge-safe (see the module-level docstring)."""
        op = str(op)
        with self._lock:
            self._runners[op] = runner
            if idempotent:
                self._idempotent_ops.add(op)
            else:
                self._idempotent_ops.discard(op)
        return self

    def idempotent_ops(self) -> List[str]:
        """Custom ops this service may be hedged on: every registration
        (module or instance scope) that declared ``idempotent=True``.
        Shipped to the router via replica telemetry — placement-time
        ground truth, so a hedge can never land on a replica whose
        registration made no safety promise."""
        with self._lock:
            return sorted(_IDEMPOTENT_OPS | self._idempotent_ops)

    def _run_ticket(self, ticket: Ticket) -> None:
        tenant = ticket.tenant
        deadline_s = ticket.deadline_s if ticket.deadline_s is not None \
            else self._request_deadline_s(tenant)
        dl = durable.PassDeadline(deadline_s, f"serve.request.{tenant}") \
            if deadline_s > 0 else None

        def guard():
            # the engine calls this before every pass: cancellation and
            # the request budget both stop the run at a pass BOUNDARY, so
            # completed (journaled) work is never abandoned mid-flight
            if ticket._cancel.is_set():
                raise CylonError(Code.Cancelled,
                                 f"request cancelled (tenant {tenant!r})")
            if dl is not None and dl.fired.is_set():
                raise CylonError(Code.Timeout,
                                 f"request exceeded its {deadline_s:g}s "
                                 f"budget (tenant {tenant!r})")

        ticket.state = RUNNING
        t0 = time.perf_counter()
        # the SLO split: how long the request sat admitted (queue wait)
        # vs how long it ran — recorded for every dispatched request,
        # succeed or fail, so the histograms describe the service's
        # latency, not just its successes
        ticket.queue_wait_s = max(0.0, t0 - ticket.t_submit)
        obs_metrics.hist_observe(_slo_key("queue_wait_ms", tenant),
                                 ticket.queue_wait_s * 1e3)
        runner = self._runners.get(ticket.op) or _RUNNERS[ticket.op]
        # the request's trace context is ACTIVE for the whole execution:
        # every span the engine records on this thread (plan passes,
        # exec passes, shuffle collectives) becomes a child span of this
        # request, and every control verb the run issues (barriers,
        # heartbeat-adjacent RPCs) carries its traceparent — which is
        # how one serve request comes to own a cross-rank trace
        with tracectx.activate(ticket.trace), \
                obs_spans.span("serve.request", tenant=tenant,
                               op=ticket.op) as sp:
            try:
                with (dl if dl is not None else contextlib.nullcontext()):
                    result, stats = runner(*ticket.args, ctx=self._ctx,
                                           pass_guard=guard,
                                           **ticket.kwargs)
            except Exception as e:
                # duration BEFORE _finish_failed closes the trace: the
                # tail-retention p99 estimator must see run time, never
                # queue wait + run (the except body runs ahead of the
                # finally that normally stamps it)
                ticket.duration_s = time.perf_counter() - t0
                self._finish_failed(ticket, e)
                return
            finally:
                dur = time.perf_counter() - t0
                ticket.duration_s = dur
                obs_metrics.hist_observe(_slo_key("run_ms", tenant),
                                         dur * 1e3)
                if obs_spans.events_enabled():
                    sp.set(seconds=round(dur, 6), state=ticket.state)
        hit = cache_mod.served_from_journal(stats)
        with self._lock:
            st = self._tenant(tenant)
            st.streak = 0
            st.served += 1
            self._counts["completed"] += 1
            if hit:
                st.cache_hits += 1
                self._counts["cache_hits"] += 1
            # request-duration EWMA drives the retry-after hints; cache
            # hits are excluded (they predict nothing about device cost)
            if not hit:
                d = ticket.duration_s
                self._ewma_s = d if self._ewma_s is None \
                    else 0.7 * self._ewma_s + 0.3 * d
        obs_metrics.counter_add("serve.completed")
        if hit:
            obs_metrics.counter_add("serve.cache_hit")
            obs_spans.instant("serve.cache_hit", tenant=tenant,
                              op=ticket.op)
        ticket.cache_hit = hit
        ticket._finish(DONE, result=result, stats=stats)
        # no GC here: the engine already runs the CYLON_TPU_DURABLE_CAP_
        # BYTES eviction when it records a journaled run complete;
        # cache.maybe_gc() stays available as a manual sweep

    def _finish_failed(self, ticket: Ticket, exc: Exception) -> None:
        st_code = Status.from_exception(exc)
        if st_code.code == Code.Cancelled:
            self._finish_cancelled(ticket, "at a pass boundary")
            return
        err = exc if isinstance(exc, CylonError) \
            else CylonError(st_code.code, st_code.msg)
        quarantined = False
        with self._lock:
            st = self._tenant(ticket.tenant)
            st.failed += 1
            st.streak += 1
            self._counts["failed"] += 1
            qn = tenant_quarantine_after()
            if qn > 0 and st.streak >= qn:
                st.quarantined_until = time.monotonic() + tenant_quarantine_s()
                self._counts["tenants_quarantined"] += 1
                quarantined = True
        obs_metrics.counter_add("serve.failed")
        if quarantined:
            obs_metrics.counter_add("serve.tenants_quarantined")
            obs_spans.instant("serve.tenant_quarantined",
                              tenant=ticket.tenant, streak=st.streak,
                              code=err.code.name)
        # classified terminal failure (deadline overruns included): the
        # flight dump carries the ring + metrics so the post-mortem does
        # not depend on the caller having pre-armed tracing
        obs_fleet.flight_record("request_failed", tenant=ticket.tenant,
                                op=ticket.op, code=err.code.name,
                                quarantined=quarantined,
                                error=err.msg[:200],
                                **({"trace_id": ticket.trace.trace_id}
                                   if ticket.trace is not None else {}))
        ticket._finish(FAILED, error=err)

    # -- drain / close ------------------------------------------------------

    def drain(self, timeout: Optional[float] = 60.0) -> List[Ticket]:
        """Graceful drain: stop admitting (subsequent submits shed with
        `Code.Unavailable`), shed everything QUEUED with the same code,
        and wait up to ``timeout`` for the in-flight request to finish
        or journal.  Returns the shed tickets.  Idempotent."""
        with self._lock:
            self._draining = True
            shed = list(self._queue)
            self._queue.clear()
            for t in shed:
                st = self._tenant(t.tenant)
                st.queued -= 1
                err = self._shed(t.tenant, Code.Unavailable,
                                 "service draining", None, t.trace)
                t._finish(SHED, error=err)
            obs_metrics.gauge_set("serve.queue_depth", 0)
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while self._running is not None:
                rem = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                if rem == 0.0:
                    break
                self._lock.wait(rem if rem is not None else 0.1)
        self._flush_flight()
        return shed

    def close(self, timeout: Optional[float] = 60.0) -> None:
        """Drain, then stop the scheduler thread."""
        self.drain(timeout)
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        self._thread.join(timeout=5.0)

    # -- fleet integration --------------------------------------------------

    def attach_to_agent(self, agent) -> "QueryService":
        """Wire this service's :meth:`telemetry` onto an elastic agent's
        heartbeats (``agent.attach_telemetry``).  One call is durable
        across coordinator restarts: the callable lives on the AGENT, and
        the agent's reconnect path pushes an immediate heartbeat after a
        successful re-join, so a restarted coordinator's ``status`` verb
        repopulates this service's queue depth and per-tenant SLO
        histograms without waiting out a heartbeat interval — no
        re-registration choreography on the serving side."""
        agent.attach_telemetry(self.telemetry)
        obs_spans.instant("serve.telemetry_attached", service=self.name,
                          rank=getattr(agent, "rank", None))
        return self

    # -- introspection ------------------------------------------------------

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    #: largest tenant set one telemetry payload carries — tenant ids are
    #: caller-supplied strings, and an unbounded set would bloat every
    #: heartbeat and eventually overflow the status reply; the busiest
    #: tenants win, the rest are counted in ``tenants_omitted``
    TELEMETRY_MAX_TENANTS = 64

    def telemetry(self) -> dict:
        """Control-plane telemetry for the fleet status endpoint: queue
        depth plus per-tenant counters and SLO latency histograms
        (queue-wait vs run split).  Attach to an elastic agent
        (``agent.attach_telemetry(svc.telemetry)``) and the coordinator
        aggregates it across ranks in its ``status`` verb.  Host-only —
        a snapshot of already-recorded metrics, never device work.

        Scoped to THIS service's tenants (the metrics registry is
        process-global, and a second QueryService in the process must
        not double-report the first one's histograms) and bounded to the
        ``TELEMETRY_MAX_TENANTS`` busiest tenants."""
        with self._lock:
            depth = len(self._queue)
            mine = {t: dict(served=s.served, shed=s.shed, failed=s.failed,
                            cache_hits=s.cache_hits)
                    for t, s in sorted(self._tenants.items())}
        omitted = 0
        if len(mine) > self.TELEMETRY_MAX_TENANTS:
            busiest = sorted(
                mine, key=lambda t: -(mine[t]["served"] + mine[t]["shed"]
                                      + mine[t]["failed"]))
            omitted = len(mine) - self.TELEMETRY_MAX_TENANTS
            mine = {t: mine[t]
                    for t in sorted(busiest[:self.TELEMETRY_MAX_TENANTS])}
        tenants: Dict[str, dict] = dict(mine)
        by_slo_name = {_slo_tenant(t): t for t in tenants}
        for key, h in obs_metrics.snapshot()["histograms"].items():
            if not key.startswith("serve.") or "[" not in key:
                continue
            kind, t = key[len("serve."):].split("[", 1)
            t = by_slo_name.get(t.rstrip("]"))
            if t is not None:
                tenants[t][kind] = h
        out = {"queue_depth": depth, "tenants": tenants}
        if omitted:
            out["tenants_omitted"] = omitted
        return out

    def stats(self) -> dict:
        """Deterministic service report: the artifact the serve smoke and
        the flood tests assert against."""
        with self._lock:
            per = {
                t: {"admitted": s.admitted, "served": s.served,
                    "shed": s.shed, "failed": s.failed,
                    "cancelled": s.cancelled, "cache_hits": s.cache_hits,
                    "quarantined": s.quarantined_until > time.monotonic()}
                for t, s in sorted(self._tenants.items())
            }
            return {**self._counts, "queue_depth": len(self._queue),
                    "queue_cap": self._cap, "draining": self._draining,
                    "tenants": per}
