"""cylon_tpu.serve — multi-tenant query serving over one mesh.

The layer above single-op execution (ROADMAP item 2): a bounded-queue
admission controller + scheduler that turns overload into a classified,
recoverable condition (`Code.ResourceExhausted`/`Code.Unavailable` with
retry-after hints, never a hang or an OOM), enforces per-tenant
deadline/memory/failure budgets through the PR-1/5 substrate, and
serves repeated queries from the durable journal as a result cache.
"""
from .cache import cache_bytes, contents, maybe_gc, served_from_journal
from .service import (OPS, QueryService, TenantBudget, Ticket,
                      default_deadline_s, hbm_budget_bytes, queue_cap,
                      tenant_quarantine_after, tenant_quarantine_s,
                      tenant_share)

__all__ = [
    "QueryService", "TenantBudget", "Ticket", "OPS",
    "queue_cap", "tenant_share", "hbm_budget_bytes", "default_deadline_s",
    "tenant_quarantine_after", "tenant_quarantine_s",
    "served_from_journal", "contents", "cache_bytes", "maybe_gc",
]
