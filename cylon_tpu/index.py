"""Index hierarchy for the DataFrame facade.

TPU-native analog of PyCylon's index classes (reference:
python/pycylon/index.py:22-221 — Index / NumericIndex / IntegerIndex /
RangeIndex / CategoricalIndex / ColumnIndex plus resolution helpers).
Row identity in a mesh-sharded table is positional; RangeIndex is the
default and a ColumnIndex records which column plays the index role.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class Index:
    """Base index (reference: index.py:22-33)."""

    def __init__(self, data=None):
        self._data = data

    def initialize(self) -> None:
        pass

    @property
    def index(self) -> "Index":
        return self

    @property
    def index_values(self):
        return self._data

    def __len__(self) -> int:
        v = self.index_values
        return 0 if v is None else len(v)


class NumericIndex(Index):
    """reference: index.py:36-56."""

    def __init__(self, data):
        super().__init__(np.asarray(data))

    @Index.index_values.getter
    def index_values(self):
        return self._data

    @index_values.setter
    def index_values(self, data):
        self._data = np.asarray(data)


class IntegerIndex(NumericIndex):
    """reference: index.py:59-66."""


class Int64Index(IntegerIndex):
    pass


class RangeIndex(Index):
    """Positional row index (reference: index.py:69-95)."""

    def __init__(self, start: int = 0, stop: int = 0, step: int = 1):
        super().__init__(None)
        if isinstance(start, range):
            rng = start
            start, stop, step = rng.start, rng.stop, rng.step
        self._start, self._stop, self._step = start, stop, step

    @property
    def start(self) -> int:
        return self._start

    @start.setter
    def start(self, v: int) -> None:
        self._start = v

    @property
    def stop(self) -> int:
        return self._stop

    @stop.setter
    def stop(self, v: int) -> None:
        self._stop = v

    @property
    def step(self) -> int:
        return self._step

    @step.setter
    def step(self, v: int) -> None:
        self._step = v

    @property
    def index_values(self):
        return np.arange(self._start, self._stop, self._step)

    def __len__(self) -> int:
        return len(range(self._start, self._stop, self._step))


class CategoricalIndex(Index):
    """reference: index.py:106-115."""

    def __init__(self, key):
        super().__init__(key)

    @property
    def index_values(self):
        return self._data


class ColumnIndex(Index):
    """A named column acting as the index (reference: index.py:117-124).

    Beyond the reference (whose ``_libs/index.pyx`` loc engine is an empty
    stub), this index carries the column's HOST values so label lookups
    resolve to row positions without touching the device."""

    def __init__(self, key, values=None):
        super().__init__(values)
        self.names = [key] if isinstance(key, str) else list(key)

    @property
    def key(self):
        return self.names[0] if len(self.names) == 1 else self.names

    @property
    def index_values(self):
        return self._data


def range_calculator(index: Index) -> int:
    """reference: index.py resolution helper."""
    return len(index)


def process_index_by_value(key, table) -> Index:
    """set_index routing (reference: table.pyx:1992-2022 ->
    process_index_by_value): an Index passes through; a column name (or
    list of names) becomes a ColumnIndex with that column's host values;
    an array-like of row_count labels becomes a CategoricalIndex."""
    names = list(table.names)
    if isinstance(key, ColumnIndex) and key.index_values is None:
        # a bare ColumnIndex("name") (the pre-round-4 API shape) carries
        # no values; materialize them so loc/take_rows actually work
        if all(n in names for n in key.names):
            key = key.names[0] if len(key.names) == 1 else list(key.names)
        else:
            raise KeyError(f"ColumnIndex names {key.names} not all in table")
    if isinstance(key, Index):
        return key
    if isinstance(key, str) and key in names:
        return ColumnIndex(key, table.project([key]).to_numpy()[key])
    if isinstance(key, (list, tuple, np.ndarray)):
        if len(key) and all(isinstance(k, str) for k in key) and \
                all(k in names for k in key):
            vals = table.project(list(key)).to_numpy()
            return ColumnIndex(list(key), [vals[k] for k in key])
        if len(key) == table.row_count:
            return CategoricalIndex(np.asarray(key, dtype=object))
    raise KeyError(f"cannot build an index from {key!r}")


def as_label_index(key, row_count: int) -> Index:
    """Force the ROW-LABEL interpretation of ``key`` (the DataFrame
    constructor's ``index=``): label values that happen to coincide with
    column names must still become row labels, exactly as pandas does."""
    if isinstance(key, Index):
        return key
    if isinstance(key, (list, tuple, np.ndarray, range)):
        if len(key) != row_count:
            raise KeyError(f"index length {len(key)} != row count {row_count}")
        return CategoricalIndex(np.asarray(key, dtype=object))
    raise KeyError(f"cannot build a label index from {key!r}")


# ---------------------------------------------------------------------------
# label/position resolution (the working analog of the reference's stubbed
# _libs/index.pyx LocIndexr.get_loc)
# ---------------------------------------------------------------------------

def _match_positions(values, label) -> np.ndarray:
    values = np.asarray(values)
    # object arrays compare elementwise in C too — no Python-level scan
    eq = values == label
    if not isinstance(eq, np.ndarray):  # exotic __eq__ returned a scalar
        eq = np.asarray([v == label for v in values])
    pos = np.flatnonzero(eq)
    if pos.size == 0:
        raise KeyError(f"label {label!r} not in index")
    return pos


def loc_positions(index: Index, key, row_count: int) -> np.ndarray:
    """Row positions selected by a pandas-style ``loc`` key over
    ``index``: a scalar label (all matching rows), a list of labels (in
    list order), an inclusive label slice (first occurrence of start to
    LAST occurrence of stop), or a boolean mask."""
    if isinstance(index, RangeIndex):
        return _range_loc(index, key, row_count)
    values = index.index_values
    if isinstance(index, ColumnIndex) and len(index.names) > 1:
        return _multi_loc(values, key, row_count)
    if values is None:
        raise KeyError("index has no values to resolve labels against")
    if isinstance(key, slice):
        if key.step is not None and key.step != 1:
            raise KeyError("label slices do not support a step")
        lo = 0 if key.start is None else int(_match_positions(values, key.start)[0])
        hi = (row_count - 1 if key.stop is None
              else int(_match_positions(values, key.stop)[-1]))
        return np.arange(lo, hi + 1, dtype=np.int64)
    if _is_bool_mask(key):
        return _bool_mask_positions(key, row_count)
    if isinstance(key, (list, tuple, np.ndarray)):
        return np.concatenate([_match_positions(values, k) for k in key]) \
            if len(key) else np.zeros(0, np.int64)
    return _match_positions(values, key)


def _multi_loc(values, key, row_count: int) -> np.ndarray:
    """Multi-column index: a label is a tuple matched across all columns."""
    if _is_bool_mask(key):
        return _bool_mask_positions(key, row_count)
    if isinstance(key, slice):
        raise KeyError("label slices are unsupported on a multi-column index")
    labels = key if isinstance(key, list) else [key]
    out = []
    for label in labels:
        if not isinstance(label, tuple) or len(label) != len(values):
            raise KeyError(f"multi-index label must be a "
                           f"{len(values)}-tuple, got {label!r}")
        mask = np.ones(row_count, bool)
        for col_vals, part in zip(values, label):
            col_vals = np.asarray(col_vals)
            if col_vals.dtype == object:
                mask &= np.asarray([v == part for v in col_vals])
            else:
                mask &= col_vals == part
        pos = np.flatnonzero(mask)
        if pos.size == 0:
            raise KeyError(f"label {label!r} not in index")
        out.append(pos)
    return np.concatenate(out)


def _range_loc(index: RangeIndex, key, row_count: int) -> np.ndarray:
    """RangeIndex labels ARE the range values: position arithmetic."""
    start, step = index.start, index.step

    def pos_of(label) -> int:
        off = label - start
        if step == 0 or off % step or not 0 <= off // step < row_count:
            raise KeyError(f"label {label!r} not in index")
        return off // step

    if isinstance(key, slice):
        if key.step is not None and key.step != 1:
            raise KeyError("label slices do not support a step")
        lo = 0 if key.start is None else pos_of(key.start)
        hi = row_count - 1 if key.stop is None else pos_of(key.stop)
        return np.arange(lo, hi + 1, dtype=np.int64)
    if _is_bool_mask(key):
        return _bool_mask_positions(key, row_count)
    if isinstance(key, (list, tuple, np.ndarray)):
        return np.asarray([pos_of(k) for k in key], np.int64)
    return np.asarray([pos_of(key)], np.int64)


def iloc_positions(key, row_count: int) -> np.ndarray:
    """Row positions for a pandas-style ``iloc`` key: int (negatives
    allowed), slice, int list/array, or boolean mask."""
    if isinstance(key, slice):
        return np.arange(*key.indices(row_count), dtype=np.int64)
    if _is_bool_mask(key):
        try:
            return _bool_mask_positions(key, row_count)
        except KeyError as e:          # iloc's error surface is IndexError
            raise IndexError(str(e))
    if isinstance(key, (list, tuple, np.ndarray)):
        idx = np.asarray(key, np.int64)
    else:
        idx = np.asarray([key], np.int64)
    idx = np.where(idx < 0, idx + row_count, idx)
    if idx.size and (idx.min() < 0 or idx.max() >= row_count):
        raise IndexError(f"position out of bounds for {row_count} rows")
    return idx


def _is_bool_mask(key) -> bool:
    if isinstance(key, np.ndarray) and key.dtype == bool:
        return True
    return (isinstance(key, (list, tuple)) and len(key) > 0
            and all(isinstance(k, (bool, np.bool_)) for k in key))


def _bool_mask_positions(key, row_count: int) -> np.ndarray:
    """Validated mask -> positions: a wrong-length mask must raise (as
    pandas does), never silently select clamped rows downstream."""
    mask = np.asarray(key, bool)
    if mask.shape != (row_count,):
        raise KeyError(f"boolean mask length {mask.shape} != row count "
                       f"{row_count}")
    return np.flatnonzero(mask)
