"""Index hierarchy for the DataFrame facade.

TPU-native analog of PyCylon's index classes (reference:
python/pycylon/index.py:22-221 — Index / NumericIndex / IntegerIndex /
RangeIndex / CategoricalIndex / ColumnIndex plus resolution helpers).
Row identity in a mesh-sharded table is positional; RangeIndex is the
default and a ColumnIndex records which column plays the index role.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class Index:
    """Base index (reference: index.py:22-33)."""

    def __init__(self, data=None):
        self._data = data

    def initialize(self) -> None:
        pass

    @property
    def index(self) -> "Index":
        return self

    @property
    def index_values(self):
        return self._data

    def __len__(self) -> int:
        v = self.index_values
        return 0 if v is None else len(v)


class NumericIndex(Index):
    """reference: index.py:36-56."""

    def __init__(self, data):
        super().__init__(np.asarray(data))

    @Index.index_values.getter
    def index_values(self):
        return self._data

    @index_values.setter
    def index_values(self, data):
        self._data = np.asarray(data)


class IntegerIndex(NumericIndex):
    """reference: index.py:59-66."""


class Int64Index(IntegerIndex):
    pass


class RangeIndex(Index):
    """Positional row index (reference: index.py:69-95)."""

    def __init__(self, start: int = 0, stop: int = 0, step: int = 1):
        super().__init__(None)
        if isinstance(start, range):
            rng = start
            start, stop, step = rng.start, rng.stop, rng.step
        self._start, self._stop, self._step = start, stop, step

    @property
    def start(self) -> int:
        return self._start

    @start.setter
    def start(self, v: int) -> None:
        self._start = v

    @property
    def stop(self) -> int:
        return self._stop

    @stop.setter
    def stop(self, v: int) -> None:
        self._stop = v

    @property
    def step(self) -> int:
        return self._step

    @step.setter
    def step(self, v: int) -> None:
        self._step = v

    @property
    def index_values(self):
        return np.arange(self._start, self._stop, self._step)

    def __len__(self) -> int:
        return len(range(self._start, self._stop, self._step))


class CategoricalIndex(Index):
    """reference: index.py:106-115."""

    def __init__(self, key):
        super().__init__(key)

    @property
    def index_values(self):
        return self._data


class ColumnIndex(Index):
    """A named column acting as the index (reference: index.py:117-124)."""

    def __init__(self, key):
        super().__init__(key)

    @property
    def index_values(self):
        return self._data


def range_calculator(index: Index) -> int:
    """reference: index.py resolution helper."""
    return len(index)
