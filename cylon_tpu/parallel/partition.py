"""Row -> target-shard assignment (the "sharding strategies").

TPU-native replacement for the reference's partition layer
(cpp/src/cylon/partition/partition.cpp, arrow/arrow_partition_kernels.hpp):

- ``hash_targets``: multi-column murmur-style row hash, modulo (or mask for
  power-of-two world sizes, arrow_partition_kernels.hpp:60-70) — the analog
  of PartitionByHashing + ModuloPartitionKernel/NumericHashPartitionKernel.
- ``range_targets``: the sampled-histogram range partitioner behind
  DistributedSort (arrow_partition_kernels.hpp:394-519 RangePartitionKernel):
  sample rows, AllReduce global min/max, build a global histogram with one
  psum (the mirror of the MPI_Allreduce at :469-480), prefix-sum it into
  monotone bin->partition cut points.

Both run *inside* shard_map: each shard computes targets for its own rows.
Padding rows get target ``world`` (a sentinel bucket nothing is sent to).

``column_stats`` rides the same pre-pass (the count-matrix program that
already touches every key): it observes each column's realized value
range / string extent / cardinality and reduces them to REPLICATED
scalars with allreduce collectives, so every process derives the same
compression spec (``plane.build_spec``) for the exchange that follows.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import precision
from ..column import Column
from ..ops import compact as compact_mod
from ..ops import hashing
from ..ops import pallas_kernels
from . import collectives
from . import plane as plane_mod


def hash_targets(cols: Sequence[Column], count, key_idx: Sequence[int],
                 world: int) -> jax.Array:
    """int32[cap] target shard per row (``world`` for padding rows).

    On TPU, fixed-width keys route through the fused Pallas murmur3 kernel
    (ops/pallas_kernels.hash_partition — bit-identical to the native host
    hasher, so host- and device-partitioned rows agree); string keys and
    CPU execution use the vectorized jnp hash."""
    cap = cols[0].data.shape[0]
    key_cols = [cols[i] for i in key_idx]
    if precision.on_tpu() and pallas_kernels.supported(key_cols):
        _, t = pallas_kernels.hash_partition(key_cols, world)
    else:
        h = hashing.hash_columns(key_cols)
        if world & (world - 1) == 0:
            t = (h & jnp.uint32(world - 1)).astype(jnp.int32)
        else:
            t = (h % jnp.uint32(world)).astype(jnp.int32)
    live = compact_mod.live_mask(cap, count)
    return jnp.where(live, t, jnp.int32(world))


def range_targets(col: Column, count, world: int, *, num_bins: int,
                  num_samples: int, ascending: bool = True,
                  nulls_first: bool = True) -> jax.Array:
    """Range-partition targets for one sort column, globally monotone:
    rows in shard t all order before rows in shard t+1.

    Strings go BEYOND the reference (its RangePartitionKernel is numeric
    only, arrow_partition_kernels.hpp:394-519): the leading 4 bytes pack
    big-endian into a uint32 whose numeric order equals bytewise
    lexicographic order, so the bin map stays monotone w.r.t. the true key
    order — prefix collisions can only merge bins (worse balance), never
    reorder them, and the post-shuffle local sort uses the full key.

    Collective footprint (identical in shape to the reference): pmin/pmax of
    the column extrema + one psum of the (num_bins,) sample histogram.
    """
    cap = col.data.shape[0]
    live = compact_mod.live_mask(cap, count) & col.validity
    if col.is_string:
        from ..ops import keys as keys_mod

        # first word packs big-endian into the high bytes of a uint64;
        # keep the top 32 bits (4 leading characters) as the bin key
        word0 = keys_mod.pack_string_words(col.data[:, :4])[0]
        data = (word0 >> jnp.uint64(32)).astype(jnp.uint32)
    else:
        data = col.data
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int32)
    # bin math precision only shapes load balance, never correctness: the
    # value->bin map stays monotone under any float rounding
    facc = precision.float_acc()
    fdata = data.astype(facc)

    big = jnp.asarray(jnp.finfo(facc).max, facc)
    gmin = collectives.allreduce_min(jnp.min(jnp.where(live, fdata, big)))
    gmax = collectives.allreduce_max(jnp.max(jnp.where(live, fdata, -big)))
    span = jnp.maximum(gmax - gmin, jnp.asarray(jnp.finfo(facc).tiny, facc))

    # deterministic stride sample of live rows (reference samples `num_samples`
    # values per worker, partition.cpp:181)
    n_live = jnp.sum(live, dtype=jnp.int32)
    pos = (jnp.arange(num_samples, dtype=facc)
           * jnp.maximum(n_live, 1).astype(facc) / num_samples)
    pos = jnp.clip(pos.astype(jnp.int32), 0, cap - 1)
    # live rows are not contiguous post-filter; sample from a compacted view
    perm, m = compact_mod.compact_indices(live)
    sample_idx = jnp.take(perm, jnp.clip(pos, 0, cap - 1))
    sample = jnp.take(fdata, sample_idx)
    sample_ok = pos < m

    sbin = jnp.clip(((sample - gmin) / span * num_bins).astype(jnp.int32),
                    0, num_bins - 1)
    if compact_mod.permute_mode() == "sort":
        # histogram as prefix-count differences (merged-sort searchsorted
        # — count_leq_dense takes any input order); dead samples park in
        # a clip-guaranteed in-range bin and are excluded by remapping
        # them past every query
        sbin_ok = jnp.where(sample_ok, sbin, num_bins)
        leq = compact_mod.count_leq_dense(sbin_ok, num_bins)
        hist = jnp.diff(leq, prepend=0).astype(jnp.int32)
    else:
        hist = jax.ops.segment_sum(sample_ok.astype(jnp.int32), sbin,
                                   num_bins)
    hist = collectives.allreduce_sum(hist)          # global histogram (psum)
    total = jnp.maximum(jnp.sum(hist), 1)

    # monotone bin -> partition map from the histogram mass midpoint
    cum = jnp.cumsum(hist)
    mid = cum.astype(facc) - hist.astype(facc) / 2
    bin_part = jnp.clip((mid * world / total).astype(jnp.int32), 0, world - 1)
    if not ascending:
        bin_part = (world - 1) - bin_part

    rbin = jnp.clip(((fdata - gmin) / span * num_bins).astype(jnp.int32),
                    0, num_bins - 1)
    t = jnp.take(bin_part, rbin)
    null_target = jnp.int32(0 if nulls_first else world - 1)
    t = jnp.where(col.validity, t, null_target)
    row_live = compact_mod.live_mask(cap, count)
    return jnp.where(row_live, t, jnp.int32(world))


# ---------------------------------------------------------------------------
# compression observation pass (PR 10)
# ---------------------------------------------------------------------------


def stats_arity(cols: Sequence[Column]) -> int:
    """How many replicated stat arrays column_stats returns — the host
    side sizes its out_specs / unpacking from the same layout walk."""
    lay = plane_mod.stats_layout(cols)
    return sum(2 if k == "int" else 3 if k == "str" else 0 for k in lay)


def column_stats(cols: Sequence[Column], count) -> Tuple[jax.Array, ...]:
    """Observed-value stats of every LIVE row, replicated across the mesh
    (runs inside shard_map; allreduce collectives make every shard — and
    every process — see identical values).  Flat tuple matching
    ``plane.stats_layout``: (min, max) per integer column; (nonzero byte
    extent, max length, max per-shard distinct count) per string column.

    Liveness is ``row < count``, NOT validity: null rows' raw payload
    bits travel through the exchange and must stay inside the observed
    range, while padding rows beyond the count are never sent and may
    fall outside it."""
    cap = cols[0].data.shape[0]
    live = compact_mod.live_mask(cap, count)
    out: List[jax.Array] = []
    for c, kind in zip(cols, plane_mod.stats_layout(cols)):
        if kind == "int":
            info = jnp.iinfo(c.data.dtype)
            big = jnp.asarray(info.max, c.data.dtype)
            small = jnp.asarray(info.min, c.data.dtype)
            mn = collectives.allreduce_min(
                jnp.min(jnp.where(live, c.data, big)))
            mx = collectives.allreduce_max(
                jnp.max(jnp.where(live, c.data, small)))
            out.append(jnp.reshape(mn, (1,)))
            out.append(jnp.reshape(mx, (1,)))
        elif kind == "str":
            w = c.string_width
            if w:
                nzcol = jnp.any((c.data != 0) & live[:, None], axis=0)
                extent = jnp.max(jnp.where(
                    nzcol, jnp.arange(1, w + 1, dtype=jnp.int32), 0))
            else:
                extent = jnp.int32(0)
            maxlen = jnp.max(jnp.where(live, c.lengths, 0))
            # distinct (bytes, length) count among live rows, over the
            # SAME key tuple the codec's local dictionary build walks
            # (plane.string_key_words — single-sourced, or lcap would
            # silently under-cover the dictionary); non-live rows
            # collapse into one sentinel group, so the observed count
            # stays a safe upper bound for the codec's dictionary
            # (padding rows are the zero row, present via its reserved
            # entry)
            sent = jnp.uint64(0xFFFFFFFFFFFFFFFF)
            kws = [jnp.where(live, wv, sent)
                   for wv in plane_mod.string_key_words(c)]
            _swv, flag = plane_mod.sorted_distinct_flags(kws)
            nun = jnp.sum(flag, dtype=jnp.int32)
            out.append(jnp.reshape(collectives.allreduce_max(extent), (1,)))
            out.append(jnp.reshape(collectives.allreduce_max(maxlen), (1,)))
            out.append(jnp.reshape(collectives.allreduce_max(nun), (1,)))
    return tuple(out)
