"""Distributed layer: mesh collectives, partitioning, shuffle.

TPU-native replacement for the reference's L1 communication stack
(cpp/src/cylon/net: Channel/Buffer/TxRequest/AllToAll state machines over
MPI_Isend/Irecv) and L3 partitioning (cpp/src/cylon/partition,
arrow/arrow_partition_kernels.hpp).  The entire nonblocking P2P machinery —
header-first protocol, per-peer state machines, fin handshakes, busy-wait
progress loops (net/mpi/mpi_channel.cpp:30-247, net/ops/all_to_all.cpp:
26-178) — collapses into XLA collectives on a 1-D device mesh: program
order replaces edge tags, a psum'd count matrix replaces length headers,
and ``lax.all_to_all`` over ICI/DCN replaces the channel fabric.
"""
