"""Distributed operators: shuffle, join support, sort, group-by, reductions.

TPU-native replacement for the reference's L4 distributed-operator recipes
(cpp/src/cylon/table.cpp:313-1047, groupby/groupby.cpp:23-114,
compute/aggregates.cpp:30-156).  Every operator keeps the reference's
*partition -> all-to-all -> local kernel* shape, but each phase is a jit
shard_map program and the communication is XLA collectives.
"""
from __future__ import annotations

import math
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes, precision
from ..column import Column
from ..config import SortOptions
from ..context import PARTITION_AXIS, CylonContext
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..ops import aggregates as agg_mod
from ..ops import groupby as groupby_mod
from ..ops import sort as sort_mod
from ..ops.groupby import AggOp
from ..status import Code, CylonError
from . import collectives
from . import partition as partition_mod
from . import plane as plane_mod
from . import shuffle as shuffle_mod



def _shard_map(ctx: CylonContext, fn, key: tuple, shapes_key: tuple,
               out_specs=None):
    from jax.sharding import PartitionSpec as P

    from .. import config
    from ..context import ctx_cache
    from ..utils import shard_map

    cache = ctx_cache(ctx, "_plan_cache")
    # every trace-scope knob participates in every plan key: flipping e.g.
    # CYLON_TPU_PERMUTE or CYLON_TPU_SHUFFLE_PACK must retrace, never serve
    # a program traced under the other realization (the PR 2 bug class,
    # generalized; cylint rule CY103 treats builders that append this token
    # as key-complete)
    cache_key = (key, shapes_key, config.trace_cache_token())
    entry = cache.get(cache_key)
    if entry is None:
        obs_metrics.counter_add("plan_cache.miss")
        spec = P(PARTITION_AXIS)
        entry = jax.jit(shard_map(
            fn, mesh=ctx.mesh, in_specs=spec,
            out_specs=spec if out_specs is None else out_specs,
            check_vma=False))
        cache[cache_key] = entry
    else:
        obs_metrics.counter_add("plan_cache.hit")
    return entry


def _shapes_key(t) -> tuple:
    # names are static metadata baked into shard-fn closures, so they must
    # key the cache alongside shapes/dtypes
    return (t.capacity, t.names,
            tuple((c.dtype, c.data.shape[1:]) for c in t.columns))


# ---------------------------------------------------------------------------
# shuffle (reference: Shuffle, table.cpp:951-964)
# ---------------------------------------------------------------------------

def _counts_for(t, key_idx: Tuple[int, ...], mode: str, opts: SortOptions | None):
    """[world, world] count matrix for a prospective shuffle, replicated on
    every process (multi-host planners need it host-side everywhere)."""
    from jax.sharding import PartitionSpec as P

    world = t.num_shards
    ctx = t.ctx

    def fn(tt):
        tgt = _targets(tt, key_idx, world, mode, opts)
        counts = shuffle_mod.target_counts(tgt, world)  # [world] per shard
        return collectives.allgather(counts, axis=0).reshape(world, world)

    return _shard_map(ctx, fn, ("counts", key_idx, mode, opts), _shapes_key(t),
                      out_specs=P())(t)


def _targets_and_counts(t, key_idx: Tuple[int, ...], mode: str,
                        opts: SortOptions | None):
    """One targets pass returning (sharded targets array, replicated
    [world, world] count matrix) — the exchange program reuses the targets
    instead of re-hashing, and every process can size the plan."""
    from jax.sharding import PartitionSpec as P

    world = t.num_shards
    ctx = t.ctx

    def fn(tt):
        tgt = _targets(tt, key_idx, world, mode, opts)
        counts = shuffle_mod.target_counts(tgt, world)
        return tgt, collectives.allgather(counts, axis=0).reshape(world, world)

    return _shard_map(ctx, fn, ("targets+counts", key_idx, mode, opts),
                      _shapes_key(t),
                      out_specs=(P(PARTITION_AXIS), P()))(t)


def _targets_counts_stats(t, key_idx: Tuple[int, ...], mode: str,
                          opts: SortOptions | None):
    """The compression pre-pass: ONE program returning (sharded targets,
    replicated count matrix, replicated per-column value stats).  The
    stats ride the pass that already touches every key (the count-matrix
    pass), reduced with allreduce collectives so every process derives
    the identical compression spec from them (plane.build_spec)."""
    from jax.sharding import PartitionSpec as P

    world = t.num_shards
    ctx = t.ctx
    n_stats = partition_mod.stats_arity(t.columns)

    def fn(tt):
        tgt = _targets(tt, key_idx, world, mode, opts)
        counts = shuffle_mod.target_counts(tgt, world)
        cm = collectives.allgather(counts, axis=0).reshape(world, world)
        stats = partition_mod.column_stats(tt.columns, tt.row_counts[0])
        return tgt, cm, stats

    return _shard_map(ctx, fn, ("targets+counts+stats", key_idx, mode, opts),
                      _shapes_key(t),
                      out_specs=(P(PARTITION_AXIS), P(),
                                 tuple(P() for _ in range(n_stats))))(t)


def _counts_stats_for(t, key_idx: Tuple[int, ...], mode: str,
                      opts: SortOptions | None):
    """Bucketed-path compression pre-pass: replicated (count matrix,
    stats) — _counts_for plus the observation, with NO sharded targets
    output (the bucketed exchange recomputes targets inside its own
    program, so materializing them here would be pure waste)."""
    from jax.sharding import PartitionSpec as P

    world = t.num_shards
    ctx = t.ctx
    n_stats = partition_mod.stats_arity(t.columns)

    def fn(tt):
        tgt = _targets(tt, key_idx, world, mode, opts)
        counts = shuffle_mod.target_counts(tgt, world)
        cm = collectives.allgather(counts, axis=0).reshape(world, world)
        return cm, partition_mod.column_stats(tt.columns, tt.row_counts[0])

    return _shard_map(ctx, fn, ("counts+stats", key_idx, mode, opts),
                      _shapes_key(t),
                      out_specs=(P(), tuple(P() for _ in range(n_stats))))(t)


def _targets(tt, key_idx, world, mode, opts: SortOptions | None):
    # the span fires at TRACE time (this runs under shard_map tracing):
    # it nests the partition phase under the enclosing plan/exchange span
    # on plan-cache misses and never reads a tracer (cylint CY101)
    with obs_spans.span("shuffle.partition", mode=mode, world=world):
        count = tt.row_counts[0]
        if mode == "hash":
            return partition_mod.hash_targets(tt.columns, count, key_idx,
                                              world)
        assert mode == "range"
        return partition_mod.range_targets(
            tt.columns[key_idx[0]], count, world,
            num_bins=opts.num_bins or 16 * world,
            num_samples=opts.num_samples or 4096,
            ascending=opts.ascending, nulls_first=opts.nulls_first)


def _probe_ragged(ctx) -> bool:
    """One tiny RaggedAllToAll program on the context's mesh: each rank
    sends one element to every rank.  Compile+run success means the
    backend implements the collective (XLA:CPU currently does not); any
    failure here is a capability miss, so real shuffle errors are never
    misclassified as fallback triggers."""
    from jax.sharding import PartitionSpec as P

    world = ctx.GetWorldSize()

    def fn(x):
        me = jax.lax.axis_index(PARTITION_AXIS)
        out = jnp.zeros((world,), jnp.int32)
        io = jnp.arange(world, dtype=jnp.int32)
        ones = jnp.ones((world,), jnp.int32)
        oo = jnp.full((world,), me, jnp.int32)
        return jax.lax.ragged_all_to_all(x, out, io, ones, oo, ones,
                                         axis_name=PARTITION_AXIS)

    from ..utils import shard_map

    try:
        f = jax.jit(shard_map(fn, mesh=ctx.mesh, in_specs=P(PARTITION_AXIS),
                              out_specs=P(PARTITION_AXIS), check_vma=False))
        jax.block_until_ready(f(jnp.zeros((world * world,), jnp.int32)))
        return True
    except Exception as e:
        import logging

        logging.getLogger(__name__).info(
            "ragged all_to_all unavailable on this backend (%s); "
            "using bucketed shuffle", type(e).__name__)
        return False


def _ragged_enabled(ctx) -> bool:
    """Capability check, cached PER CONTEXT: a process that touches a
    CPU-mesh context first (probe -> False) and later a TPU context must
    re-probe on the TPU mesh, not inherit the CPU verdict."""
    from .. import config
    from ..context import ctx_cache

    env = config.knob("CYLON_TPU_SHUFFLE")
    if env == "bucketed":
        return False
    cache = ctx_cache(ctx, "_ragged_probe")
    if "ragged" not in cache:
        cache["ragged"] = _probe_ragged(ctx)
    if env == "ragged" and not cache["ragged"]:
        raise RuntimeError(
            "CYLON_TPU_SHUFFLE=ragged requested but this backend does not "
            "implement RaggedAllToAll")
    return cache["ragged"]


def _row_bytes(cols, packed: bool, spec=None) -> int:
    """Exchanged bytes per row under either realization — plane words when
    packed (compressed plane words under ``spec``), data+validity+lengths
    buffer bytes per-buffer (all static shape/dtype metadata, host-side)."""
    if packed:
        return plane_mod.plane_words(cols, spec) * 4
    total = 0
    for c in cols:
        total += c.data.dtype.itemsize * int(
            math.prod(c.data.shape[1:])) + 1  # data row + 1 validity byte
        if c.lengths is not None:
            total += c.lengths.dtype.itemsize
    return total


def _record_exchange(cols, packed: bool, family: str,
                     rows_exchanged: int, spec=None) -> None:
    """Account one collective exchange that actually ran: data-collective
    launch count (1 packed vs one per buffer — the PR-3 budget goldens'
    1-vs-13 on the canonical 6-column frame), the counts all_gather, and
    global bytes moved.  Under a compression spec, ``shuffle.bytes_sent``
    records the bytes that really traveled; the uncompressed-minus-sent
    delta lands in ``shuffle.bytes_saved`` and the per-exchange ratio in
    the ``shuffle.compress_ratio`` gauge."""
    launches = 1 if packed else shuffle_mod.buffer_count(cols)
    bytes_sent = rows_exchanged * _row_bytes(cols, packed, spec)
    obs_metrics.counter_add("shuffle.exchanges")
    obs_metrics.counter_add("shuffle.collective_launches", launches)
    obs_metrics.counter_add("shuffle.counts_gathers")
    obs_metrics.counter_add("shuffle.bytes_sent", bytes_sent)
    if spec is not None:
        raw_bytes = rows_exchanged * _row_bytes(cols, packed)
        obs_metrics.counter_add("shuffle.bytes_saved",
                                max(0, raw_bytes - bytes_sent))
        if bytes_sent > 0:
            obs_metrics.gauge_set("shuffle.compress_ratio",
                                  raw_bytes / bytes_sent)
    # distribution, not just the total: one hot exchange in a hundred
    # small ones is invisible in the counter but not in the histogram
    obs_metrics.hist_observe("shuffle.bytes_per_exchange", bytes_sent)
    obs_spans.instant("shuffle.exchange_done", family=family, packed=packed,
                      compressed=spec is not None,
                      collective_launches=launches, rows=rows_exchanged)


def _record_broadcast(cols, packed: bool, world: int, rows_buf: int) -> None:
    """Account one broadcast replication (static shape metadata only, no
    device sync).  Deliberately NOT ``shuffle.exchanges`` — tests pin
    exchange counts per plan shape, and a broadcast is the strategy that
    AVOIDED an exchange; it gets its own counter."""
    launches = 1 if packed else 1 + shuffle_mod.buffer_count(cols)
    bytes_sent = rows_buf * world * _row_bytes(cols, packed)
    obs_metrics.counter_add("shuffle.broadcasts")
    obs_metrics.counter_add("shuffle.collective_launches", launches)
    obs_metrics.counter_add("shuffle.bytes_sent", bytes_sent)
    obs_metrics.hist_observe("shuffle.bytes_per_exchange", bytes_sent)
    obs_spans.instant("shuffle.broadcast_done", packed=packed,
                      collective_launches=launches,
                      rows=rows_buf * world)


def broadcast_gather(t):
    """Replicate a (small) distributed table onto every shard — the
    broadcast-hash join's build side.

    Packed path runs exactly ONE all_gather: the shard's rows pack into
    the bit-plane, one extra meta row carries the live-row count in
    word 0 (a counts all_gather would be a second launch — the budget
    goldens pin broadcast joins at 1 gather), and every shard unpacks
    the [world, cap+1, words] result, compacting live rows front-wise
    in source-rank order.  The per-buffer fallback (packing disabled)
    gathers counts plus each buffer.  No compression: the build side is
    dimension-sized by the cost model's admission, so spec estimation
    overhead cannot pay for itself.

    The result is replicated (same rows, same order, every shard) and
    feeds the collective-free local join probe; it never escapes the
    executor."""
    from .. import resilience
    from ..ops import compact as compact_mod
    from ..table import Table

    world = t.num_shards
    if world == 1:
        return t
    ctx = t.ctx
    names = t.names
    cap = t.shard_capacity
    out_cap = cap * world
    pack = plane_mod.pack_enabled()

    def gather():
        resilience.fault_point("broadcast")
        if pack:
            def bcfn(tt):
                plane = plane_mod.pack_plane(tt.columns)
                meta = jnp.zeros((1, plane.shape[1]), dtype=plane.dtype)
                meta = meta.at[0, 0].set(
                    tt.row_counts[0].astype(plane.dtype))
                g = collectives.allgather(
                    jnp.concatenate([plane, meta], axis=0), axis=0)
                counts = g[:, cap, 0].astype(jnp.int32)
                rows = g[:, :cap, :].reshape(world * cap, -1)
                live = (jnp.arange(cap, dtype=jnp.int32)[None, :]
                        < counts[:, None]).reshape(world * cap)
                perm, m = compact_mod.compact_indices(live)
                valid = jnp.arange(out_cap, dtype=jnp.int32) < m
                cols = plane_mod.unpack_plane(
                    jnp.take(rows, perm, axis=0, mode="clip"),
                    tt.columns, valid_mask=valid)
                return Table(cols, jnp.reshape(m, (1,)), names, ctx)
        else:
            def bcfn(tt):
                counts = collectives.allgather(
                    tt.row_counts, axis=0).reshape(world).astype(jnp.int32)
                live = (jnp.arange(cap, dtype=jnp.int32)[None, :]
                        < counts[:, None]).reshape(world * cap)
                perm, m = compact_mod.compact_indices(live)
                valid = jnp.arange(out_cap, dtype=jnp.int32) < m
                cols = []
                for c in tt.columns:
                    gd = collectives.allgather(c.data, axis=0).reshape(
                        (world * cap,) + c.data.shape[1:])
                    gv = collectives.allgather(c.validity, axis=0).reshape(
                        world * cap)
                    gl = None
                    if c.lengths is not None:
                        gl = collectives.allgather(
                            c.lengths, axis=0).reshape(world * cap)
                    cols.append(Column(gd, gv, gl, c.dtype).take(
                        perm, valid_mask=valid))
                return Table(tuple(cols), jnp.reshape(m, (1,)), names, ctx)

        with obs_spans.span("shuffle.broadcast", packed=pack, world=world):
            out = _shard_map(ctx, bcfn, ("bcast", pack, out_cap),
                             _shapes_key(t))(t)
        _record_broadcast(t.columns, pack, world, cap + 1 if pack else cap)
        return out

    out, _attempts = resilience.retry_call(
        gather, policy=ctx.collective_retry_policy(), site="broadcast")
    return out


def _shuffled(t, key_idx: Tuple[int, ...], mode: str = "hash",
              opts: SortOptions | None = None):
    """partition -> all-to-all -> compact; returns a new distributed Table.

    The exchange prefers the skew-proof RaggedAllToAll path (exact traffic,
    no bucket padding, targets computed once); if the active backend lacks
    the ragged collective the bucketed path is used and remembered.
    """
    from .. import resilience
    from ..table import Table

    world = t.num_shards
    ctx = t.ctx
    names = t.names

    def exchange():
        # the named injection site for the collective exchange; a real or
        # injected transient failure retries the WHOLE plan+exchange (the
        # input table is untouched, so the retry is exact)
        resilience.fault_point("shuffle")
        # phase timers mirror the reference's split/shuffle chrono spans
        # (partition/partition.cpp:29-57, table.cpp:163-175)
        # the packed-plane knob is read at trace time, so it must key the
        # plan cache — flipping CYLON_TPU_SHUFFLE_PACK can never serve a
        # program traced under the other realization
        pack = plane_mod.pack_enabled()
        # compression rides the packed plane: the pre-pass additionally
        # observes per-column value stats (replicated via allreduce) and
        # the host folds them into the static spec.  The spec is realized
        # -data-derived jit layout, so it rides the exchange plan cache
        # key below (cylint CY109) — a data change retraces, never
        # decodes under a stale layout.
        compress = pack and plane_mod.compress_enabled()
        if _ragged_enabled(ctx):
            with obs_spans.span("shuffle.plan", mode=mode, world=world,
                      family="ragged"):
                # sized here, inside the retried exchange — the task-graph
                # path also calls plan_shuffle, so the injection site
                # lives with the recovery wrapper, not the sizing math
                resilience.fault_point("shuffle_plan")
                spec = None
                if compress:
                    targets, counts, stats = _targets_counts_stats(
                        t, key_idx, mode, opts)
                    spec = plane_mod.build_spec(
                        t.columns, [np.asarray(s) for s in stats], world,
                        t.shard_capacity)
                else:
                    targets, counts = _targets_and_counts(t, key_idx, mode,
                                                          opts)
                cm = np.asarray(counts).reshape(world, world)
                _, out_cap = shuffle_mod.plan_shuffle(cm)

            def rfn(tt, tgt):
                cols, total = shuffle_mod.shuffle_shard_ragged(
                    tt.columns, tgt, world, out_cap, spec=spec)
                return Table(cols, jnp.reshape(total, (1,)), names, ctx)

            with obs_spans.span("shuffle.exchange", packed=pack, family="ragged",
                      world=world, compressed=spec is not None):
                out = _shard_map(ctx, rfn,
                                 ("shuffle-ragged", key_idx, out_cap, pack,
                                  spec),
                                 _shapes_key(t))(t, targets)
            # ragged moves exactly the rows that exist
            _record_exchange(t.columns, pack, "ragged", int(cm.sum()),
                             spec=spec)
            return out

        with obs_spans.span("shuffle.plan", mode=mode, world=world, family="bucketed"):
            resilience.fault_point("shuffle_plan")
            spec = None
            if compress:
                counts, stats = _counts_stats_for(t, key_idx, mode, opts)
                spec = plane_mod.build_spec(
                    t.columns, [np.asarray(s) for s in stats], world,
                    t.shard_capacity)
            else:
                counts = _counts_for(t, key_idx, mode, opts)
            bucket, out_cap = shuffle_mod.plan_shuffle(
                np.asarray(counts).reshape(world, world))

        # unique closure name: cylint resolves closures module-wide by
        # bare name, and CY109 must see THIS body's spec use, not some
        # other `fn`'s
        def bfn(tt):
            tgt = _targets(tt, key_idx, world, mode, opts)
            cols, total = shuffle_mod.shuffle_shard(
                tt.columns, tt.row_counts[0], tgt, world, bucket, out_cap,
                spec=spec)
            return Table(cols, jnp.reshape(total, (1,)), names, ctx)

        with obs_spans.span("shuffle.exchange", packed=pack, family="bucketed",
                  world=world, bucket=bucket, compressed=spec is not None):
            out = _shard_map(ctx, bfn,
                             ("shuffle", key_idx, mode, opts, bucket,
                              out_cap, pack, spec),
                             _shapes_key(t))(t)
        # every (src, dst) pair pads to the static bucket
        _record_exchange(t.columns, pack, "bucketed",
                         world * world * bucket, spec=spec)
        return out

    out, _attempts = resilience.retry_call(
        exchange, policy=ctx.collective_retry_policy(), site="shuffle")
    return out


def shuffle(t, key_idx: Tuple[int, ...]):
    """Hash-repartition rows so equal keys land on the same shard.

    The result is stamped with its partitioning property
    (``_partitioning = ("hash", ((key names,),), world)``) — the
    planner (cylon_tpu.plan) treats partitioning as tracked data
    state, so a downstream join/group-by on compatible keys can elide
    its own exchange entirely."""
    key_idx = tuple(key_idx)
    out = _shuffled(t, key_idx, "hash")
    out._partitioning = ("hash", (tuple(t.names[i] for i in key_idx),),
                         t.num_shards)
    return out


def hash_partition(t, key_idx: Tuple[int, ...], num_partitions: int):
    """Public HashPartition (reference: table.cpp:358-375): split rows into
    ``num_partitions`` tables by key hash.  Purely local like the reference
    (each rank/shard splits its own rows; no exchange): partition p's table
    holds, on every shard, that shard's rows hashing to p, front-packed.
    Returns ``{partition_id: Table}``."""
    from ..ops import compact as compact_mod
    from ..table import Table, _shard_wise

    ctx = t.ctx
    names = t.names
    key_idx = tuple(key_idx)

    from jax.sharding import PartitionSpec as P

    from ..utils import pow2ceil

    nshards = t.num_shards
    one_shard = nshards == 1
    if one_shard:
        targets = partition_mod.hash_targets(t.columns, t.row_counts[0],
                                             key_idx, num_partitions)
        counts = shuffle_mod.target_counts(targets, num_partitions)
    else:
        def cfn(tt):
            tgt = partition_mod.hash_targets(tt.columns, tt.row_counts[0],
                                             key_idx, num_partitions)
            cnts = shuffle_mod.target_counts(tgt, num_partitions)
            return tgt, collectives.allgather(cnts, axis=0).reshape(
                nshards, num_partitions)

        targets, counts = _shard_map(ctx, cfn,
                                     ("hp_counts", key_idx, num_partitions),
                                     _shapes_key(t),
                                     out_specs=(P(PARTITION_AXIS), P()))(t)
    cm = np.asarray(counts).reshape(nshards, num_partitions)
    caps = tuple(min(pow2ceil(c), t.shard_capacity) for c in cm.max(axis=0))

    # under the packed-exchange knob the per-partition compaction gathers
    # run once on the bit-packed plane (num_partitions gathers total)
    # instead of once per column per partition — same machinery as the
    # shuffle exchange, minus the collective (this op is purely local)
    pack = plane_mod.pack_enabled()

    def pfn(tt, tgt):
        packed = plane_mod.pack_plane(tt.columns) if pack else None
        outs = []
        for p in range(num_partitions):
            perm, m = compact_mod.compact_indices(tgt == p)
            idx = perm[: caps[p]]
            valid = jnp.arange(caps[p], dtype=jnp.int32) < m
            if pack:
                cols = plane_mod.unpack_plane(
                    jnp.take(packed, idx, axis=0, mode="clip"),
                    tt.columns, valid_mask=valid)
            else:
                cols = tuple(c.take(idx, valid_mask=valid)
                             for c in tt.columns)
            outs.append(Table(cols, jnp.reshape(m, (1,)), names, ctx))
        return tuple(outs)

    if one_shard:
        parts = pfn(t, targets)
    else:
        parts = _shard_map(ctx, pfn,
                           ("hash_partition", key_idx, num_partitions, caps,
                            pack),
                           _shapes_key(t))(t, targets)
    return {p: parts[p] for p in range(num_partitions)}


# ---------------------------------------------------------------------------
# distributed sort (reference: DistributedSort, table.cpp:313-356)
# ---------------------------------------------------------------------------

def distributed_sort(t, by_idx: Tuple[int, ...], opts: SortOptions,
                     asc: Tuple[bool, ...] | None = None):
    # string lead columns range-partition on their 4-byte prefix (beyond
    # the reference, whose RangePartitionKernel is numeric only)
    shuffled = _shuffled(t, tuple(by_idx), "range", opts)
    if asc is None:
        asc = tuple([opts.ascending] * len(by_idx))
    from ..table import Table

    names, ctx = t.names, t.ctx

    def fn(tt):
        cols, count = sort_mod.sort_rows(tt.columns, tt.row_counts[0],
                                         tuple(by_idx), asc, opts.nulls_first)
        return Table(cols, tt.row_counts, names, ctx)

    return _shard_map(ctx, fn, ("dsort", tuple(by_idx), asc, opts.nulls_first),
                      _shapes_key(shuffled))(shuffled)


# ---------------------------------------------------------------------------
# distributed group-by (reference: DistributedHashGroupBy,
# groupby/groupby.cpp:23-73 — partial agg, shuffle, final agg)
# ---------------------------------------------------------------------------

def groupby_partial_plan(aggs):
    """Expand requested aggs into the deduped partial-op list and its
    index: ``(partial_list, partial_index)`` where ``partial_list`` is
    ``[(src_col, partial_op), ...]`` and ``partial_index[(src, pop)]``
    is that partial's position.  ``aggs`` entries may name columns by
    index or by name — the caller's namespace is preserved.  Shared by
    the distributed two-phase group-by and the planner's fused
    join→aggregate shard body (plan/executor.py), so the two can never
    disagree on the partial layout."""
    partial_list: list = []
    partial_index: Dict[tuple, int] = {}
    for ci, op in aggs:
        for pop in groupby_mod.partial_ops(op):
            k = (ci, pop)
            if k not in partial_index:
                partial_index[k] = len(partial_list)
                partial_list.append(k)
    return partial_list, partial_index


def finalize_groupby_columns(fcols, nkeys: int, aggs, partial_index,
                             ddof: int):
    """Combine-phase outputs -> the requested agg columns: pass-through
    for SUM/MIN/MAX/COUNT, derived math for MEAN/VAR/STDDEV.  Pure jnp
    on the combined columns, so it runs identically on host-side global
    arrays (distributed_groupby step 5) and INSIDE a traced shard body
    (the planner's fused local aggregate) — bit-identity between the
    eager and fused paths rests on this being single-sourced."""
    out_cols = list(fcols[:nkeys])
    for ci, op in aggs:
        def pcol(pop, _ci=ci):
            return fcols[nkeys + partial_index[(_ci, pop)]]

        facc = precision.float_acc()
        fdt = dtypes.float_ if precision.narrow() else dtypes.double
        if op in (AggOp.SUM, AggOp.MIN, AggOp.MAX, AggOp.COUNT,
                  AggOp.SUMSQ, AggOp.COUNTSUM):
            out_cols.append(pcol(op))
        elif op == AggOp.MEAN:
            s, c = pcol(AggOp.SUM), pcol(AggOp.COUNT)
            cnt = jnp.maximum(c.data, 1).astype(facc)
            v = s.data.astype(facc) / cnt
            valid = s.validity & (c.data > 0)
            out_cols.append(Column(jnp.where(valid, v, 0.0), valid, None,
                                   fdt))
        elif op in (AggOp.VAR, AggOp.STDDEV):
            s, c, s2 = pcol(AggOp.SUM), pcol(AggOp.COUNT), pcol(AggOp.SUMSQ)
            n = jnp.maximum(c.data, 1).astype(facc)
            var = (s2.data - s.data.astype(facc) ** 2 / n) / jnp.maximum(
                n - ddof, 1.0)
            var = jnp.maximum(var, 0.0)
            if op == AggOp.STDDEV:
                var = jnp.sqrt(var)
            valid = s.validity & ((c.data - ddof) > 0)
            out_cols.append(Column(jnp.where(valid, var, 0.0), valid, None,
                                   fdt))
        else:
            raise NotImplementedError(op)
    return out_cols


def distributed_groupby(t, by_idx: Tuple[int, ...],
                        aggs: Tuple[Tuple[int, AggOp], ...], ddof: int,
                        pipeline: bool = False,
                        pre_partitioned: bool = False,
                        salt: int = 0):
    """Two-phase distributed group-by.

    ``pipeline=False`` — the reference's DistributedHashGroupBy
    (groupby/groupby.cpp:23-73): local partial aggregate, shuffle partials
    on the keys, final combine.
    ``pipeline=True`` — DistributedPipelineGroupBy (groupby/groupby.cpp:
    75-114): the local phases run the boundary-scan pipeline group-by over
    key-sorted rows; after the shuffle each shard sorts its received
    partials before the final pipeline pass (the reference's local Sort at
    groupby.cpp:103-107).

    ``pre_partitioned=True`` — the planner's shuffle elision: the caller
    proves the input is already hash-partitioned on a subset of the
    group keys (every group fully on one shard), so the partial shuffle
    is SKIPPED and the final combine folds each group's single partial
    locally — bit-identical to the shuffled path, because combining one
    partial is the identity for every combine op.

    ``salt > 1`` — the adaptive planner's skew-salted repartition,
    valid ONLY for the all-NUNIQUE single-distinct-column shape (it
    raises otherwise): instead of co-locating each group entirely on
    ``hash(keys)``'s rank (one zipfian-hot key = one overloaded rank),
    rows spread over ``hash(keys, value_bucket)`` where ``value_bucket
    = hash(value) % salt``.  Exact by construction: buckets PARTITION
    the value space, so every distinct (key, value) pair lands on
    exactly one rank, the per-rank local NUNIQUE counts disjoint value
    sets, and the integer COUNTSUM combine over a second (tiny,
    group-sized) exchange sums them — bit-identical to the unsalted
    plan, at the price of that extra small exchange.
    """
    from ..table import Table, _groupby_output_names, _local_groupby, _shard_wise

    names_out = _groupby_output_names(t, by_idx, aggs)
    ctx = t.ctx

    if pre_partitioned and any(op == AggOp.NUNIQUE for _, op in aggs):
        raise CylonError(Code.Invalid,
                         "pre_partitioned group-by cannot carry NUNIQUE "
                         "(no partial/combine decomposition)")
    salt = int(salt)
    if salt > 1 and (pre_partitioned
                     or any(op != AggOp.NUNIQUE for _, op in aggs)
                     or len({ci for ci, _ in aggs}) != 1):
        raise CylonError(Code.Invalid,
                         "salted group-by requires the all-NUNIQUE "
                         "single-distinct-column shape")
    if any(op == AggOp.NUNIQUE for _, op in aggs):
        # NUNIQUE does not decompose into partial+combine columns; instead
        # co-locate raw rows by key (shuffle) and run ONE local group-by —
        # exact, because groups are disjoint across shards after the
        # shuffle.  When every agg is NUNIQUE, traffic shrinks first via a
        # local distinct pass over the involved columns (duplicate
        # (key,value) rows cannot change a distinct count).
        from ..ops import unique as unique_mod

        involved = tuple(dict.fromkeys(
            tuple(by_idx) + tuple(ci for ci, _ in aggs)))
        work = t.project(involved)  # shuffle only the columns the aggs touch
        remap = {ci: i for i, ci in enumerate(involved)}
        by_p = tuple(remap[i] for i in by_idx)
        aggs_p = tuple((remap[ci], op) for ci, op in aggs)
        if all(op == AggOp.NUNIQUE for _, op in aggs):
            nn = work.names

            def dedup_fn(tt):
                cols, m = unique_mod.unique(
                    tt.columns, tt.row_counts[0],
                    tuple(range(len(involved))), "first")
                return Table(cols, jnp.reshape(m, (1,)), nn, ctx)

            work = _shard_wise(ctx, dedup_fn, work,
                               key=("nunique_dedup", involved))
        if salt > 1:
            from ..ops import hashing as hashing_mod

            vpos = aggs_p[0][0]
            nkeys = len(by_p)
            sn = work.names + ("__salt__",)

            def salt_fn(tt):
                bucket = (hashing_mod.hash_columns([tt.columns[vpos]])
                          % jnp.uint32(salt)).astype(jnp.int32)
                live = jnp.arange(bucket.shape[0],
                                  dtype=jnp.int32) < tt.row_counts[0]
                cols = tuple(tt.columns) + (
                    Column(bucket, live, None, dtypes.int32),)
                return Table(cols, tt.row_counts, sn, ctx)

            salted = _shard_wise(ctx, salt_fn, work,
                                 key=("nunique_salt", vpos, salt))
            spread = shuffle(salted, by_p + (len(involved),))
            part = _local_groupby(spread, by_p, aggs_p, ddof,
                                  pipeline=False)
            combined = shuffle(part, tuple(range(nkeys)))
            out = _local_groupby(
                combined, tuple(range(nkeys)),
                tuple((nkeys + i, AggOp.COUNTSUM)
                      for i in range(len(aggs_p))), ddof, pipeline=False)
            obs_spans.instant("shuffle.salted", buckets=salt, keys=nkeys)
            return out.rename(names_out)
        shuffled = shuffle(work, by_p)
        out = _local_groupby(shuffled, by_p, aggs_p, ddof, pipeline=False)
        return out.rename(names_out)

    # 1. expand requested aggs into partial ops, dedup
    partial_list, partial_index = groupby_partial_plan(aggs)

    nkeys = len(by_idx)

    # 2. local partial aggregate (per shard)
    local_partial = (groupby_mod.pipeline_groupby if pipeline
                     else groupby_mod.hash_groupby)

    def partial_fn(tt):
        cols, m = local_partial(
            tt.columns, tt.row_counts[0], tuple(by_idx), tuple(partial_list), ddof)
        pnames = tuple(f"k{i}" for i in range(nkeys)) + tuple(
            f"p{i}" for i in range(len(partial_list)))
        return Table(cols, jnp.reshape(m, (1,)), pnames, ctx)

    partial = _shard_map(ctx, partial_fn,
                         ("gb_partial", tuple(by_idx), tuple(partial_list),
                          ddof, pipeline),
                         _shapes_key(t))(t)

    # 3. shuffle partials on the key columns — unless the caller proved
    # the input pre-partitioned (every group's rows, hence its single
    # partial, already live on one shard)
    shuffled = partial if pre_partitioned else shuffle(
        partial, tuple(range(nkeys)))

    # 4. final combine: SUM of sums/counts/sumsqs, MIN of mins, MAX of maxes
    final_aggs = tuple((nkeys + i, groupby_mod.combine_op(pop))
                       for i, (_, pop) in enumerate(partial_list))
    key_range = tuple(range(nkeys))

    def final_fn(tt):
        cols, count = tt.columns, tt.row_counts[0]
        if pipeline:  # received partials arrive unsorted: sort, then scan
            cols, count = sort_mod.sort_rows(
                cols, count, key_range, tuple([True] * nkeys), True)
            cols, m = groupby_mod.pipeline_groupby(
                cols, count, key_range, final_aggs, ddof)
        else:
            cols, m = groupby_mod.hash_groupby(
                cols, count, key_range, final_aggs, ddof)
        return cols, jnp.reshape(m, (1,))

    fcols, fcounts = _shard_map(
        ctx, final_fn, ("gb_final", key_range, final_aggs, ddof, pipeline),
        _shapes_key(shuffled))(shuffled)

    # 5. finalize derived outputs (MEAN/VAR/STDDEV) from combined partials
    out_cols = finalize_groupby_columns(fcols, nkeys, aggs, partial_index,
                                        ddof)
    out = Table(tuple(out_cols), fcounts, names_out, ctx)
    if not pre_partitioned:
        # placed by the partial shuffle's hash of ALL group keys; a
        # pre-partitioned run is placed by the caller's key SUBSET
        # instead, which only the planner knows — it stamps its own
        out._partitioning = ("hash", (tuple(names_out[:nkeys]),),
                             t.num_shards)
    return out


# ---------------------------------------------------------------------------
# distributed scalar aggregates (reference: compute/aggregates.cpp DoAllReduce)
# ---------------------------------------------------------------------------

def distributed_scalar_agg(t, col_idx: int, op: agg_mod.ReduceOp):
    """Local masked reduce + ONE collective combine, all in a single program
    (the shape of the reference's arrow::compute + mpi::AllReduce,
    compute/aggregates.cpp:30-156).  Empty shards contribute the op's
    neutral element (scalar_agg's sentinels), so no host-side masking."""
    from . import collectives

    ctx = t.ctx

    def fn(tt):
        v, n = agg_mod.scalar_agg(tt.columns[col_idx], tt.row_counts[0], op)
        if op in (agg_mod.ReduceOp.SUM, agg_mod.ReduceOp.COUNT):
            r = collectives.allreduce_sum(v)
        elif op == agg_mod.ReduceOp.MIN:
            r = collectives.allreduce_min(v)
        elif op == agg_mod.ReduceOp.MAX:
            r = collectives.allreduce_max(v)
        elif op == agg_mod.ReduceOp.PROD:  # XLA has no pprod collective
            r = jnp.prod(collectives.allgather(jnp.reshape(v, (1,))))
        else:
            raise ValueError(op)
        return jnp.reshape(r, (1,))

    vals = _shard_map(ctx, fn, ("scalar", col_idx, op), _shapes_key(t))(t)
    return vals[0]
