"""The all-to-all table shuffle — the framework's central primitive.

TPU-native replacement for the reference's entire shuffle stack:
``PartitionByHashing -> Split -> ArrowAllToAll`` (reference:
cpp/src/cylon/partition/partition.cpp:24-114, arrow/arrow_all_to_all.cpp:
24-236, net/ops/all_to_all.cpp:26-178, table.cpp:67-152
all_to_all_arrow_tables).  Where the reference streams each buffer with 6-int
headers through per-peer MPI state machines and busy-waits on progress
loops, here the whole exchange is ONE jit program per shard:

1. group rows by target shard with a stable counting scan over the
   world-sized target alphabet (the Split kernel's per-row appends,
   arrow_kernels.hpp:60-96, become one cumsum per target + a gather),
2. per-target counts via segment-sum; an ``all_gather`` of the count row
   replaces the length-header handshake (the receiver "pre-allocation" is
   the static bucket size),
3. rows are laid into fixed-size per-target buckets and exchanged over
   ICI/DCN — by default on TPU as ONE tiled ``lax.all_to_all`` over a
   single bit-packed u32 plane carrying every column's data/validity/
   lengths (``parallel/plane.py``; ``CYLON_TPU_SHUFFLE_PACK`` gates it),
   otherwise one collective per buffer,
4. received buckets are compacted to the front with one searchsorted-gather
   (on the plane when packed — one gather total instead of one per buffer),
   yielding a front-packed shard + new row count.

Raggedness is the hard part on TPU (static shapes): bucket size is a static
parameter.  ``plan_shuffle`` computes the exact count matrix on-device and
lets the host pick the padded bucket size (rounded to a power of two so jit
caches stay warm); ``shuffle_shard`` is the fully static kernel usable
inside larger fused programs.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..column import Column
from ..obs import spans as obs_spans
from ..ops import compact as compact_mod
from . import collectives
from . import plane as plane_mod


# Alphabet width above which the per-target unroll (_perm_by_target) and
# the dense alphabet compare (target_counts) both switch to sort-based
# derivations; the two predicates must stay identical so count derivation
# and permutation grouping never desynchronize.
_WIDE_MESH_CUTOFF = 32


def buffer_count(cols: Sequence[Column]) -> int:
    """Exchanged buffers per row set under the per-buffer realization —
    data + validity (+ lengths for strings) per column.  The single
    source behind the per-buffer collective-launch count: the span
    ``launches`` attrs here and the ``shuffle.collective_launches``
    metric (parallel/ops.py) must never disagree with the budget
    goldens on what counts as a launch."""
    return sum(2 + (1 if c.lengths is not None else 0) for c in cols)


def target_counts(targets: jax.Array, world: int) -> jax.Array:
    """int32[world]: rows this shard sends to each target (padding rows carry
    target == world and fall off the end).

    sort permute mode, narrow mesh: a fused compare-and-reduce over the
    tiny target alphabet — one bandwidth-bound pass, no scatter-add
    (XLA:TPU serializes scatters; see compact.permute_mode).  Wide mesh
    (same ``world + 1 > 32`` predicate as _perm_by_target's unroll
    cutoff): the O(cap*world) broadcast intermediate would dwarf the rows
    themselves (world=256 at a 64M-row chunk is a 2^34 compare unless XLA
    fuses it — round-4 advice finding 2), so counts come from one sort +
    count_leq_dense instead: counts[t] = #{targets <= t} - #{targets <= t-1}."""
    if compact_mod.permute_mode() == "sort":
        if world + 1 <= _WIDE_MESH_CUTOFF:
            alphabet = jnp.arange(world, dtype=targets.dtype)
            return jnp.sum(targets[:, None] == alphabet[None, :], axis=0,
                           dtype=jnp.int32)
        # count_leq_dense clips negatives to 0, which would misroute them
        # into target 0's count — remap to padding first (it takes any
        # input order: the packed merge sorts internally)
        t = _remap_oob_targets(targets, world)
        leq = compact_mod.count_leq_dense(t, world)
        return jnp.diff(leq, prepend=0).astype(jnp.int32)
    ones = jnp.ones_like(targets, dtype=jnp.int32)
    return jax.ops.segment_sum(ones, targets, world + 1)[:world]


def _remap_oob_targets(targets: jax.Array, world: int) -> jax.Array:
    """Out-of-range targets — negative included — become PADDING (== world),
    so a producer bug drops rows into padding (visible as count loss
    downstream) instead of silently misrouting them to rank 0, a
    legitimate destination.  Single-sourced: target_counts and
    _perm_by_target must never disagree on this policy."""
    return jnp.where((targets < 0) | (targets > world), world, targets)


def _perm_by_target(targets: jax.Array, world: int) -> jax.Array:
    """Stable permutation grouping rows by target, padding (== world) last.

    The target alphabet is tiny (world + 1 values), so a counting scan —
    one cumsum per target value, unrolled at trace time — replaces the
    stable sort the Split kernel would otherwise pay
    (reference: arrow_kernels.hpp:60-96 appends per-target builders row by
    row; here each target's rows get destinations base_t + rank-in-target).
    Falls back to ``lax.sort`` for wide meshes where the unroll would bloat
    the program.

    Precondition: targets in [0, world] (world == padding).  Producers
    (hash_targets/range_targets) guarantee it; out-of-range values — negative
    included — are remapped to the PADDING bucket, so a producer bug drops
    rows into padding (visible as count loss downstream) instead of silently
    misrouting them to rank 0, a legitimate destination."""
    cap = targets.shape[0]
    targets = _remap_oob_targets(targets, world)
    iota = jnp.arange(cap, dtype=jnp.int32)
    if world + 1 > _WIDE_MESH_CUTOFF or compact_mod.permute_mode() == "sort":
        _, perm = jax.lax.sort((targets, iota), num_keys=1, is_stable=True)
        return perm
    dest = jnp.zeros((cap,), jnp.int32)
    base = jnp.zeros((), jnp.int32)
    for t in range(world + 1):
        m = targets == t
        c = jnp.cumsum(m.astype(jnp.int32))
        dest = jnp.where(m, base + c - 1, dest)
        base = base + c[-1]
    return jnp.zeros((cap,), jnp.int32).at[dest].set(iota)


def shuffle_shard(cols: Tuple[Column, ...], count, targets: jax.Array,
                  world: int, bucket: int, out_capacity: int, spec=None):
    """Shard-local body of the shuffle (run under shard_map).

    bucket: static per-(src,dst) bucket row capacity; rows beyond it would be
    dropped, so callers size it from the count matrix (plan_shuffle) or use a
    safe bound (shard capacity).
    Returns (columns, new_count) with per-shard capacity ``out_capacity``.

    Exchange realization (``plane.pack_enabled()``, read at trace time):
    packed — every column's data/validity/lengths bit-packed into one u32
    plane, ONE ``all_to_all`` total, bucket-lay/compaction gathers run once
    on the plane; per-buffer — one collective and one gather pair per
    buffer.  Both produce bit-identical shards (tests/test_shuffle_pack.py).

    ``spec`` (packed realization only): the observed compression spec the
    caller derived from the pre-pass stats — narrow/dictionary/truncated
    plane fields, bit-exact round trip, at most one extra dictionary
    all_gather (plane.PlaneCodec).  Data-dependent static layout: callers
    key their jit-plan caches on it (cylint CY109)."""
    cap = cols[0].data.shape[0]

    counts = target_counts(targets, world)
    # group rows by target: rows for shard t become contiguous, padding last
    perm_t = _perm_by_target(targets, world)
    start = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                             jnp.cumsum(counts, dtype=jnp.int32)[:-1]])

    # lay rows into W fixed-size buckets: send slot (t, k) <- sorted row start[t]+k
    o = jnp.arange(world * bucket, dtype=jnp.int32)
    t = o // bucket
    k = o % bucket
    src_sorted = jnp.take(start, t) + k
    send_valid = k < jnp.take(counts, t)
    src = jnp.take(perm_t, jnp.clip(src_sorted, 0, cap - 1))

    # count matrix row exchange replaces the length-header protocol.
    # The spans here (and below) fire at TRACE time — this body runs on
    # the host under shard_map tracing — so each plan build nests
    # counts-gather/pack/collective/unpack children under the enclosing
    # shuffle.exchange span; no tracer is ever read (cylint CY101).
    with obs_spans.span("shuffle.counts_gather", world=world):
        cm = collectives.allgather(counts, axis=0).reshape(world, world)
    me = collectives.my_rank()
    incoming = cm[:, me]
    csum = jnp.cumsum(incoming, dtype=jnp.int32)
    total = csum[-1]

    # front-pack the received buckets: slot o2 <- bucket s, offset within
    o2 = jnp.arange(out_capacity, dtype=jnp.int32)
    s = jnp.clip(jnp.searchsorted(csum, o2, side="right").astype(jnp.int32),
                 0, world - 1)
    within = o2 - (jnp.take(csum, s) - jnp.take(incoming, s))
    src2 = jnp.clip(s * bucket + within, 0, world * bucket - 1)
    valid2 = o2 < total

    if plane_mod.pack_enabled():
        # ONE collective for the whole table: pack at shard capacity,
        # bucket-lay the plane (single gather), exchange, compact (single
        # gather), decode with the tail mask.  The codec applies the
        # compression spec (identity when spec is None); dictionary
        # columns cost one extra small all_gather at codec build.
        codec = plane_mod.PlaneCodec(cols, spec)
        with obs_spans.span("shuffle.pack", columns=len(cols)) as sp:
            packed = codec.pack(cols)
            sp.set(words=int(packed.shape[1]), compressed=spec is not None)
            send_plane = jnp.where(send_valid[:, None],
                                   jnp.take(packed, src, axis=0), 0)
        with obs_spans.span("shuffle.collective", family="all_to_all",
                            packed=True, launches=1):
            recv_plane = collectives.all_to_all(send_plane)
        with obs_spans.span("shuffle.unpack", columns=len(cols)):
            out_plane = jnp.take(recv_plane, src2, axis=0)
            out = codec.unpack(out_plane, cols, valid_mask=valid2)
        return out, total

    # per-buffer exchange: one tiled all_to_all per buffer
    # (data/validity/lengths) — the whole ArrowAllToAll machinery, but
    # O(buffers x columns) collective launches
    with obs_spans.span("shuffle.pack", columns=len(cols), packed=False):
        send_cols = tuple(c.take(src, valid_mask=send_valid) for c in cols)
    with obs_spans.span("shuffle.collective", family="all_to_all",
                        packed=False, launches=buffer_count(cols)):
        recv_cols = tuple(
            Column(collectives.all_to_all(c.data),
                   collectives.all_to_all(c.validity),
                   None if c.lengths is None
                   else collectives.all_to_all(c.lengths),
                   c.dtype)
            for c in send_cols)
    with obs_spans.span("shuffle.unpack", columns=len(cols)):
        out_cols = tuple(c.take(src2, valid_mask=valid2) for c in recv_cols)
    return out_cols, total


def plan_shuffle(counts: jax.Array) -> Tuple[int, int]:
    """Host-side sizing from the [world, world] count matrix: (bucket,
    out_capacity), both rounded to powers of two to bound recompilation."""
    import numpy as np

    from ..utils import pow2ceil

    cm = np.asarray(counts)
    bucket = int(cm.max()) if cm.size else 0
    incoming = cm.sum(axis=0).max() if cm.size else 0
    return pow2ceil(bucket), pow2ceil(incoming)


def ragged_plan(cm, me):
    """Rank ``me``'s RaggedAllToAll sizing from the [world, world] count
    matrix (cm[src, dst] = rows src sends to dst): (recv_sizes,
    output_offsets, total).  ``output_offsets[t]`` is where my slice lands
    on receiver t — after every lower-ranked source's slice — so received
    rows arrive front-packed with no compaction pass.  Pure math shared by
    the device kernel and the host-side emulation tests."""
    world = cm.shape[0]
    recv_sizes = cm[:, me]
    src_rank = jnp.arange(world, dtype=jnp.int32)
    output_offsets = jnp.sum(
        jnp.where((src_rank < me)[:, None], cm, 0), axis=0).astype(jnp.int32)
    total = jnp.sum(recv_sizes, dtype=jnp.int32)
    return recv_sizes, output_offsets, total


def shuffle_shard_ragged(cols: Tuple[Column, ...], targets: jax.Array,
                         world: int, out_capacity: int, spec=None):
    """Skew-proof shard-local shuffle body over ``lax.ragged_all_to_all``.

    Where ``shuffle_shard`` pads every (src,dst) pair to one static bucket
    (traffic ``world x bucket`` rows per buffer — up to ~world x inflation
    when one shard is hot), this variant sends *exactly* the rows that
    exist: rows are stable-sorted by target so each destination's slice is
    contiguous, the all-gathered count matrix yields send/recv sizes and
    the packed output offsets, and XLA's RaggedAllToAll moves the slices.
    Received rows land front-packed, so no compaction gather is needed.

    ``targets`` is taken as an argument (not recomputed) so the caller can
    reuse the targets pass that sized ``out_capacity`` — the reference
    similarly partitions once and streams only what exists
    (cpp/src/cylon/arrow/arrow_all_to_all.cpp:24-236).

    Exchange realization (``plane.pack_enabled()``, read at trace time):
    packed — the whole table travels as one bit-packed u32 plane through
    ONE ``ragged_all_to_all`` (the target-sort gather also runs once, on
    the plane); per-buffer — one collective and one sort-gather per
    buffer.  Bit-identical outputs either way.
    """
    cap = cols[0].data.shape[0]

    counts = target_counts(targets, world)
    perm_t = _perm_by_target(targets, world)
    input_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)[:-1]])

    # on-device count-matrix exchange (the 6-int header protocol's job);
    # trace-time child spans, like shuffle_shard's (cylint CY101-clean)
    with obs_spans.span("shuffle.counts_gather", world=world):
        cm = collectives.allgather(counts, axis=0).reshape(world, world)
    me = collectives.my_rank()
    recv_sizes, output_offsets, total = ragged_plan(cm, me)

    if plane_mod.pack_enabled():
        codec = plane_mod.PlaneCodec(cols, spec)
        with obs_spans.span("shuffle.pack", columns=len(cols)) as sp:
            packed = codec.pack(cols)
            sp.set(words=int(packed.shape[1]), compressed=spec is not None)
            sorted_plane = jnp.take(packed, perm_t, axis=0)
        with obs_spans.span("shuffle.collective",
                            family="ragged_all_to_all", packed=True,
                            launches=1):
            out = jnp.zeros((out_capacity, packed.shape[1]), packed.dtype)
            got = collectives.ragged_all_to_all(
                sorted_plane, out, input_offsets, counts, output_offsets,
                recv_sizes)
        # NO validity mask on decode: the per-buffer path below moves raw
        # buffers (a null row's bytes pass through untouched), and the
        # plane must stay bit-identical to it; rows past ``total`` decode
        # from the zeros of ``out`` — validity False, zero data — exactly
        # like the unwritten tail of the per-buffer outputs.  Under a
        # compression spec zero fields no longer decode to zero VALUES
        # (offset / dictionary entry 0), so the tail is masked explicitly
        # — in-range null rows' raw payloads stay untouched.
        with obs_spans.span("shuffle.unpack", columns=len(cols)):
            tail = None
            if spec is not None:
                tail = jnp.arange(out_capacity, dtype=jnp.int32) < total
            out_cols = codec.unpack(got, cols, tail_mask=tail)
        return out_cols, total

    def exchange(buf):
        squeeze = buf.ndim == 1
        if squeeze:  # RaggedAllToAll wants a payload axis
            buf = buf[:, None]
        orig = buf.dtype
        if orig == jnp.bool_:
            buf = buf.astype(jnp.uint8)
        sorted_buf = jnp.take(buf, perm_t, axis=0)
        out = jnp.zeros((out_capacity,) + buf.shape[1:], buf.dtype)
        got = collectives.ragged_all_to_all(
            sorted_buf, out, input_offsets, counts, output_offsets,
            recv_sizes)
        if orig == jnp.bool_:
            got = got.astype(jnp.bool_)
        return got[:, 0] if squeeze else got

    with obs_spans.span("shuffle.collective", family="ragged_all_to_all",
                        packed=False, launches=buffer_count(cols)):
        out_cols = tuple(
            Column(exchange(c.data), exchange(c.validity),
                   None if c.lengths is None else exchange(c.lengths),
                   c.dtype)
            for c in cols)
    return out_cols, total
