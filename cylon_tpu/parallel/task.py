"""Task-multiplexed all-to-all: many logical tables, one collective pass.

TPU-native replacement for the reference's ArrowTaskAllToAll
(cpp/src/cylon/arrow/arrow_task_all_to_all.h:9-59, .cpp): there, a
``LogicalTaskPlan`` maps logical task ids onto workers so several logical
tables share one worker's MPI channels, with mutex-guarded inserts and a
``WaitForCompletion`` spin.  Here the multiplexing is data-level: every
logical table's rows are tagged with their task id, concatenated, and moved
in ONE fused shuffle (single ``lax.all_to_all`` pass over ICI) whose routing
function is the plan's task->worker lookup instead of a key hash.  The
mutexes and completion spins have no equivalent — SPMD program order is the
synchronization.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..status import CylonError, Code

TASK_COL = "__task__"


class LogicalTaskPlan:
    """task id -> worker (shard) assignment (reference:
    arrow_task_all_to_all.h:9-24 LogicalTaskPlan's task_source_of/
    worker_num_of maps)."""

    def __init__(self, task_to_worker: Dict[int, int], world_size: int):
        for task, worker in task_to_worker.items():
            if not 0 <= worker < world_size:
                raise CylonError(
                    Code.Invalid,
                    f"task {task} assigned to worker {worker} outside world "
                    f"of {world_size}")
        self._map = dict(task_to_worker)
        self.world_size = world_size

    def worker_for(self, task: int) -> int:
        return self._map[task]

    def tasks_of(self, worker: int) -> List[int]:
        return sorted(t for t, w in self._map.items() if w == worker)

    @property
    def tasks(self) -> List[int]:
        return sorted(self._map)

    def __repr__(self) -> str:
        return f"LogicalTaskPlan({self._map}, world={self.world_size})"


def task_shuffle(tables: Sequence, task_ids: Sequence[int],
                 plan: LogicalTaskPlan) -> List:
    """Move each logical table's rows to its task's worker, all tasks in one
    collective exchange.

    ``tables`` must share a schema.  Returns one table per input task; the
    rows of output i live entirely on shard ``plan.worker_for(task_ids[i])``
    (other shards hold zero rows of it), which is the reference's
    ArrowTaskAllToAll delivery contract.
    """
    if len(tables) != len(task_ids):
        raise CylonError(Code.Invalid, "one task id per table required")
    unplanned = sorted(set(task_ids) - set(plan.tasks))
    if unplanned:
        raise CylonError(Code.Invalid,
                         f"task ids not in plan: {unplanned}")
    if not tables:
        return []
    for t in tables[1:]:
        if t.names != tables[0].names:
            raise CylonError(Code.Invalid, "task tables must share a schema")

    # tag + concatenate: one combined table with a task-id routing column
    combined = None
    for t, task in zip(tables, task_ids):
        tagged = t.project(list(range(t.column_count)))  # shallow copy
        tagged[TASK_COL] = np.full((t.row_count,), task, np.int64)
        combined = tagged if combined is None else combined.merge(tagged)

    shuffled = _plan_shuffle(combined, plan)

    outs = []
    for task in task_ids:
        pred = _task_predicate(task)
        outs.append(shuffled.select(pred).drop([TASK_COL]))
    return outs


_PREDICATES: Dict[int, object] = {}


def _task_predicate(task: int):
    """Stable predicate objects so Table.select's jit cache keys hit."""
    pred = _PREDICATES.get(task)
    if pred is None:
        def pred(env, task=task):
            return env[TASK_COL] == task

        _PREDICATES[task] = pred
    return pred


def _plan_shuffle(t, plan: LogicalTaskPlan):
    """Shuffle with plan-lookup routing instead of key hashing (the analog
    of ArrowTaskAllToAll::insert routing through plan.worker_num_of)."""
    from ..table import Table
    from . import ops as par_ops
    from . import plane as plane_mod
    from . import shuffle as shuffle_mod

    world = t.num_shards
    ctx = t.ctx
    task_idx = t.names.index(TASK_COL)
    # dense lookup table task -> worker (tasks may be sparse ids)
    max_task = max(plan.tasks) if plan.tasks else 0
    lut = np.zeros((max_task + 2,), np.int32)
    for task, worker in plan._map.items():
        lut[task] = worker
    lut_key = tuple(int(x) for x in lut)

    def targets(tt):
        count = tt.row_counts[0]
        cap = tt.columns[0].data.shape[0]
        task_col = tt.columns[task_idx].data.astype(jnp.int32)
        tgt = jnp.take(jnp.asarray(np.asarray(lut_key, np.int32)),
                       jnp.clip(task_col, 0, len(lut_key) - 1))
        live = jnp.arange(cap, dtype=jnp.int32) < count
        return jnp.where(live, tgt, world)  # padding rows fall off the end

    def counts_fn(tt):
        return shuffle_mod.target_counts(targets(tt), world)

    counts = par_ops._shard_map(ctx, counts_fn, ("task_counts", lut_key),
                                par_ops._shapes_key(t))(t)
    bucket, out_cap = shuffle_mod.plan_shuffle(
        np.asarray(counts).reshape(world, world))
    names = t.names

    def fn(tt):
        tgt = targets(tt)
        cols, total = shuffle_mod.shuffle_shard(
            tt.columns, tt.row_counts[0], tgt, world, bucket, out_cap)
        return Table(cols, jnp.reshape(total, (1,)), names, ctx)

    # trace-time knob -> cache key (same discipline as parallel.ops._shuffled)
    pack = plane_mod.pack_enabled()
    out = par_ops._shard_map(ctx, fn,
                             ("task_shuffle", lut_key, bucket, out_cap, pack),
                             par_ops._shapes_key(t))(t)
    # the task exchange launches the same collectives as the key shuffle
    # (budget golden analysis/budgets/task_shuffle.json) — it must show
    # up in shuffle.collective_launches/bytes_sent like every exchange
    par_ops._record_exchange(t.columns, pack, "task-bucketed",
                             world * world * bucket)
    return out
