"""Column-packed single-collective exchange plane.

The shuffle is the framework's central primitive (reference:
cpp/src/cylon/arrow/arrow_all_to_all.cpp:24-236), yet the per-buffer
exchange launches one collective PER BUFFER PER COLUMN — data, validity,
and lengths each pay their own ``all_to_all`` / ``ragged_all_to_all``, so
a 10-column table fires ~30 collectives per exchange.  On XLA the launch
count and payload layout, not FLOPs, dominate collective cost ("Memory-
efficient array redistribution through portable collective communication",
arxiv 2112.01075; EQuARX, arxiv 2506.17615): few large transfers saturate
ICI/DCN where many small ones serialize on launch overhead.

This module bit-packs every column's data/validity/lengths buffers into
ONE contiguous ``uint32[rows, words]`` plane per shard — the same
packed-word discipline ``ops/keys.py::pack_operands`` proved for sort
operands, except the plane is a round-trip format (bit-exact decode), not
an order-preserving encoding — so the whole table moves in a single
collective and is unpacked on the receiver.  Field layout is a pure
function of static column metadata (dtypes, string widths), so sender and
receiver agree by construction inside one SPMD program:

- validity        -> 1 bit
- bool data       -> 1 bit
- 8/16-bit data   -> 8/16 bits (bitcast to unsigned)
- 32-bit data     -> one u32 word (bitcast)
- 64-bit data     -> two u32 words (bitcast)
- string data     -> ceil(width/4) u32 words (4 bytes big-endian each)
- string lengths  -> one u32 word

Words are assigned first-fit-decreasing, so every 32-bit field owns one
word and the sub-word fields (validity bits, bool/8/16-bit data) pack
densely into the remainder — a narrow 10-column i32 table is 11 words
(44 B/row) in ONE collective vs 50 B/row across 20 collectives unpacked.

Gated by ``CYLON_TPU_SHUFFLE_PACK`` (auto = on for TPU-family backends,
the ``ops/compact.py::permute_mode`` precedent); hardware A/B arms live
in tools/microbench.py, tools/profile_pipeline.py and tools/tpu_battery.sh.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import config
from ..column import Column

_UINT_OF = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def pack_enabled() -> bool:
    """Whether shuffle exchanges move one packed u32 plane instead of one
    collective per buffer per column.  CYLON_TPU_SHUFFLE_PACK=1/0
    overrides; "auto" (default) packs on TPU-family backends, where
    collective launch count dominates, and stays per-buffer elsewhere.
    Read at trace time — callers key their jit caches on it."""
    mode = config.knob("CYLON_TPU_SHUFFLE_PACK")
    if mode in ("1", "on", "packed"):
        return True
    if mode in ("0", "off", "perbuf"):
        return False
    return jax.default_backend() in ("tpu", "axon")


def _string_word_count(col: Column) -> int:
    return (col.string_width + 3) // 4


def _field_widths(cols: Sequence[Column]) -> List[int]:
    """Bit width of every plane field, in canonical column order.  Must
    stay the exact mirror of _field_values/_rebuild_columns — the three
    walk one shared field sequence."""
    ws: List[int] = []
    for c in cols:
        ws.append(1)                                  # validity
        if c.is_string:
            ws.extend([32] * _string_word_count(c))   # data words
            ws.append(32)                             # lengths
        elif c.data.dtype == jnp.bool_:
            ws.append(1)
        elif c.data.dtype.itemsize == 8:
            ws.extend([32, 32])
        else:
            ws.append(c.data.dtype.itemsize * 8)
    return ws


def _layout(widths: Sequence[int]) -> Tuple[List[Tuple[int, int, int]], int]:
    """First-fit-decreasing assignment of fields to u32 words.  Returns
    (slots, num_words): slots[i] = (word, shift, bits) for field i, MSB-
    aligned within each word.  Pure static math — both ends of the
    exchange derive the identical layout from column metadata."""
    order = sorted(range(len(widths)), key=lambda i: (-widths[i], i))
    slots: List[Optional[Tuple[int, int, int]]] = [None] * len(widths)
    word, used = -1, 32
    for i in order:
        w = widths[i]
        if used + w > 32:
            word += 1
            used = 0
        slots[i] = (word, 32 - used - w, w)
        used += w
    return slots, word + 1  # type: ignore[return-value]


def plane_words(cols: Sequence[Column]) -> int:
    """Static u32 word count of the packed plane for this schema."""
    return _layout(_field_widths(cols))[1]


def _pack_string_data(data: jax.Array) -> List[jax.Array]:
    """uint8[n, width] byte matrix -> ceil(width/4) u32[n] big-endian
    words (the 4-byte analog of keys.pack_string_words' 8-byte packing)."""
    n, width = data.shape
    pad = (-width) % 4
    if pad:
        data = jnp.concatenate([data, jnp.zeros((n, pad), jnp.uint8)], axis=1)
    nwords = data.shape[1] // 4
    if nwords == 0:
        return []
    w = data.reshape(n, nwords, 4).astype(jnp.uint32)
    shifts = jnp.array([24, 16, 8, 0], jnp.uint32)
    packed = jnp.sum(w << shifts, axis=2, dtype=jnp.uint32)
    return [packed[:, i] for i in range(nwords)]


def _unpack_string_data(words: Sequence[jax.Array], width: int) -> jax.Array:
    """Inverse of _pack_string_data: u32 words -> uint8[n, width].
    ``words`` must be non-empty (zero-width matrices never pack words;
    unpack_plane rebuilds their empty shape directly)."""
    n = words[0].shape[0]
    stacked = jnp.stack(words, axis=1)                    # [n, nwords]
    shifts = jnp.array([24, 16, 8, 0], jnp.uint32)
    bytes_ = ((stacked[:, :, None] >> shifts) & jnp.uint32(0xFF)).astype(
        jnp.uint8).reshape(n, -1)
    return bytes_[:, :width]


def _field_values(cols: Sequence[Column]) -> List[jax.Array]:
    """u32[n] value array per field (same order as _field_widths); every
    value already fits its declared bit width."""
    vals: List[jax.Array] = []
    for c in cols:
        vals.append(c.validity.astype(jnp.uint32))
        if c.is_string:
            vals.extend(_pack_string_data(c.data))
            vals.append(jax.lax.bitcast_convert_type(
                c.lengths.astype(jnp.int32), jnp.uint32))
        elif c.data.dtype == jnp.bool_:
            vals.append(c.data.astype(jnp.uint32))
        elif c.data.dtype.itemsize == 8:
            w32 = jax.lax.bitcast_convert_type(c.data, jnp.uint32)  # [n, 2]
            vals.append(w32[:, 0])
            vals.append(w32[:, 1])
        else:
            bits = jax.lax.bitcast_convert_type(
                c.data, _UINT_OF[c.data.dtype.itemsize])
            vals.append(bits.astype(jnp.uint32))
    return vals


def pack_plane(cols: Sequence[Column]) -> jax.Array:
    """Bit-pack the columns' buffers into one uint32[rows, words] plane.
    Bit-exact round trip with unpack_plane (floats travel as raw bits, so
    NaN payloads and -0.0 survive)."""
    widths = _field_widths(cols)
    slots, nwords = _layout(widths)
    n = cols[0].data.shape[0]
    words: List[Optional[jax.Array]] = [None] * nwords
    for (word, shift, _bits), v in zip(slots, _field_values(cols)):
        sh = v if shift == 0 else (v << jnp.uint32(shift))
        words[word] = sh if words[word] is None else (words[word] | sh)
    if nwords == 0:
        return jnp.zeros((n, 0), jnp.uint32)
    return jnp.stack([w for w in words], axis=1)


def unpack_plane(plane: jax.Array, like: Sequence[Column],
                 valid_mask: Optional[jax.Array] = None) -> Tuple[Column, ...]:
    """Decode a packed plane back into Columns with ``like``'s schema
    (dtypes, string widths).  ``valid_mask`` ANDs into every column's
    validity and zeroes masked rows' data/lengths — the exact masking
    Column.take applies, so packed and per-buffer exchanges produce
    bit-identical shards."""
    widths = _field_widths(like)
    slots, nwords = _layout(widths)
    assert plane.shape[1] == nwords, (plane.shape, nwords)
    it = iter(slots)

    def field() -> jax.Array:
        word, shift, bits = next(it)
        v = plane[:, word]
        if shift:
            v = v >> jnp.uint32(shift)
        if bits < 32:
            v = v & jnp.uint32((1 << bits) - 1)
        return v

    out: List[Column] = []
    for c in like:
        validity = field().astype(jnp.bool_)
        lengths = None
        if c.is_string:
            words = [field() for _ in range(_string_word_count(c))]
            data = (_unpack_string_data(words, c.string_width) if words
                    else jnp.zeros((plane.shape[0], c.string_width),
                                   jnp.uint8))
            lengths = jax.lax.bitcast_convert_type(field(), jnp.int32)
        elif c.data.dtype == jnp.bool_:
            data = field().astype(jnp.bool_)
        elif c.data.dtype.itemsize == 8:
            pair = jnp.stack([field(), field()], axis=1)        # [n, 2]
            data = jax.lax.bitcast_convert_type(
                jax.lax.bitcast_convert_type(pair, jnp.uint64), c.data.dtype)
        else:
            w = c.data.dtype.itemsize
            data = jax.lax.bitcast_convert_type(
                field().astype(_UINT_OF[w]), c.data.dtype)
        if valid_mask is not None:
            validity = validity & valid_mask
            zero = jnp.zeros((), data.dtype)
            data = jnp.where(validity[:, None] if data.ndim == 2 else validity,
                             data, zero)
            if lengths is not None:
                lengths = jnp.where(validity, lengths, 0)
        out.append(Column(data, validity, lengths, c.dtype))
    return tuple(out)
