"""Column-packed single-collective exchange plane.

The shuffle is the framework's central primitive (reference:
cpp/src/cylon/arrow/arrow_all_to_all.cpp:24-236), yet the per-buffer
exchange launches one collective PER BUFFER PER COLUMN — data, validity,
and lengths each pay their own ``all_to_all`` / ``ragged_all_to_all``, so
a 10-column table fires ~30 collectives per exchange.  On XLA the launch
count and payload layout, not FLOPs, dominate collective cost ("Memory-
efficient array redistribution through portable collective communication",
arxiv 2112.01075; EQuARX, arxiv 2506.17615): few large transfers saturate
ICI/DCN where many small ones serialize on launch overhead.

This module bit-packs every column's data/validity/lengths buffers into
ONE contiguous ``uint32[rows, words]`` plane per shard — the same
packed-word discipline ``ops/keys.py::pack_operands`` proved for sort
operands, except the plane is a round-trip format (bit-exact decode), not
an order-preserving encoding — so the whole table moves in a single
collective and is unpacked on the receiver.  Field layout is a pure
function of static column metadata (dtypes, string widths), so sender and
receiver agree by construction inside one SPMD program:

- validity        -> 1 bit
- bool data       -> 1 bit
- 8/16-bit data   -> 8/16 bits (bitcast to unsigned)
- 32-bit data     -> one u32 word (bitcast)
- 64-bit data     -> two u32 words (bitcast)
- string data     -> ceil(width/4) u32 words (4 bytes big-endian each)
- string lengths  -> one u32 word

Words are assigned first-fit-decreasing, so every 32-bit field owns one
word and the sub-word fields (validity bits, bool/8/16-bit data) pack
densely into the remainder — a narrow 10-column i32 table is 11 words
(44 B/row) in ONE collective vs 50 B/row across 20 collectives unpacked.

Gated by ``CYLON_TPU_SHUFFLE_PACK`` (auto = on for TPU-family backends,
the ``ops/compact.py::permute_mode`` precedent); hardware A/B arms live
in tools/microbench.py, tools/profile_pipeline.py and tools/tpu_battery.sh.

Compression (PR 10, ``CYLON_TPU_SHUFFLE_COMPRESS``): an optional stage
between pack and exchange that shrinks each field to the bits its
*realized* values need — exact by construction, unlike EQuARX's lossy
quantized collectives (arxiv 2506.17615), and living in the data layout
rather than a custom collective (arxiv 2112.01075):

- integer columns narrow to ``("narrow", offset, bits)``: the plane field
  carries ``value - offset`` in ``bits`` bits, where ``offset``/``bits``
  come from the observed min/max over the LIVE rows (null rows' raw
  payload bits included, so they round-trip exactly); a single-value
  column costs 0 bits;
- string columns truncate to ``("trunc", nbytes, len_bits)``: data words
  beyond the observed nonzero-byte extent are all-zero by observation and
  drop out, and the lengths field narrows to the observed maximum;
- low-cardinality string columns dictionary-encode to ``("dict", nbytes,
  lcap, gcap, code_bits)``: rows exchange a ``code_bits``-wide index into
  a global dictionary every shard derives identically from ONE small
  all-gather of per-shard local dictionaries (code 0 is reserved for the
  all-zero row so unwritten ragged tails decode to zeros).

The spec is data-dependent static layout, so it participates in every
jit-plan cache key that reaches a spec-shaped body (cylint rule CY109)
and in the durable/plan fingerprints via the input content they already
hash.  ``CYLON_TPU_SHUFFLE_COMPRESS=0`` is the exact PR-2 baseline:
identical programs, bit-identical shards.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import config
from ..column import Column

_UINT_OF = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}

#: per-column spec entry for the uncompressed (PR-2) field layout
RAW: Tuple = ("raw",)

#: sentinel key word for dictionary padding entries: sorts after every
#: real value (no real row can carry length 2^64-1, so the sentinel can
#: never collide with a live key tuple)
_SENT64 = jnp.uint64(0xFFFFFFFFFFFFFFFF)

#: largest global dictionary worth gathering: past this the per-exchange
#: all-gather stops being "small" relative to the payload it shrinks
_DICT_GCAP_MAX = 4096


def pack_enabled() -> bool:
    """Whether shuffle exchanges move one packed u32 plane instead of one
    collective per buffer per column.  CYLON_TPU_SHUFFLE_PACK=1/0
    overrides; "auto" (default) packs on TPU-family backends, where
    collective launch count dominates, and stays per-buffer elsewhere.
    Read at trace time — callers key their jit caches on it."""
    mode = config.knob("CYLON_TPU_SHUFFLE_PACK")
    if mode in ("1", "on", "packed"):
        return True
    if mode in ("0", "off", "perbuf"):
        return False
    return jax.default_backend() in ("tpu", "axon")


def compress_enabled() -> bool:
    """Whether shuffle exchanges may bit-width-reduce / dictionary-encode
    the packed plane (CYLON_TPU_SHUFFLE_COMPRESS; auto = on for
    TPU-family backends, where payload bits over ICI are the cost).
    Compression rides the packed plane, so callers additionally require
    ``pack_enabled()``.  Read at trace time — the knob is in the
    trace_cache_token, and the data-derived spec itself must ride every
    plan cache key (cylint CY109)."""
    mode = config.knob("CYLON_TPU_SHUFFLE_COMPRESS")
    if mode in ("1", "on"):
        return True
    if mode in ("0", "off"):
        return False
    return jax.default_backend() in ("tpu", "axon")


def _string_word_count(col: Column) -> int:
    return (col.string_width + 3) // 4


def _spec_of(cols: Sequence[Column], spec) -> Tuple[Tuple, ...]:
    return tuple(spec) if spec is not None else (RAW,) * len(cols)


def _field_widths(cols: Sequence[Column], spec=None) -> List[int]:
    """Bit width of every plane field, in canonical column order.  Must
    stay the exact mirror of _field_values/_rebuild_columns — the three
    walk one shared field sequence.  ``spec`` (see build_spec) swaps a
    column's raw fields for its compressed encoding's fields."""
    ws: List[int] = []
    for c, enc in zip(cols, _spec_of(cols, spec)):
        ws.append(1)                                  # validity
        if c.is_string:
            if enc[0] == "dict":
                ws.append(enc[4])                     # code field
            elif enc[0] == "trunc":
                ws.extend([32] * ((enc[1] + 3) // 4))  # truncated data
                ws.append(enc[2])                     # narrowed lengths
            else:
                ws.extend([32] * _string_word_count(c))   # data words
                ws.append(32)                             # lengths
        elif c.data.dtype == jnp.bool_:
            ws.append(1)
        elif enc[0] == "narrow":
            ws.append(enc[2])                         # offset-reduced data
        elif c.data.dtype.itemsize == 8:
            ws.extend([32, 32])
        else:
            ws.append(c.data.dtype.itemsize * 8)
    return ws


def _layout(widths: Sequence[int]) -> Tuple[List[Tuple[int, int, int]], int]:
    """First-fit-decreasing assignment of fields to u32 words.  Returns
    (slots, num_words): slots[i] = (word, shift, bits) for field i, MSB-
    aligned within each word.  Zero-bit fields (single-value narrowed
    columns) own no plane bits: their slot is (-1, 0, 0) and decode
    reconstructs them from the spec alone.  Pure static math — both ends
    of the exchange derive the identical layout from column metadata."""
    order = sorted(range(len(widths)), key=lambda i: (-widths[i], i))
    slots: List[Optional[Tuple[int, int, int]]] = [None] * len(widths)
    word, used = -1, 32
    for i in order:
        w = widths[i]
        if w == 0:
            slots[i] = (-1, 0, 0)
            continue
        if used + w > 32:
            word += 1
            used = 0
        slots[i] = (word, 32 - used - w, w)
        used += w
    return slots, word + 1  # type: ignore[return-value]


def plane_words(cols: Sequence[Column], spec=None) -> int:
    """Static u32 word count of the packed plane for this schema (under
    ``spec``'s compressed encodings when given)."""
    return _layout(_field_widths(cols, spec))[1]


def _pack_string_data(data: jax.Array) -> List[jax.Array]:
    """uint8[n, width] byte matrix -> ceil(width/4) u32[n] big-endian
    words (the 4-byte analog of keys.pack_string_words' 8-byte packing)."""
    n, width = data.shape
    pad = (-width) % 4
    if pad:
        data = jnp.concatenate([data, jnp.zeros((n, pad), jnp.uint8)], axis=1)
    nwords = data.shape[1] // 4
    if nwords == 0:
        return []
    w = data.reshape(n, nwords, 4).astype(jnp.uint32)
    shifts = jnp.array([24, 16, 8, 0], jnp.uint32)
    packed = jnp.sum(w << shifts, axis=2, dtype=jnp.uint32)
    return [packed[:, i] for i in range(nwords)]


def _unpack_string_data(words: Sequence[jax.Array], width: int) -> jax.Array:
    """Inverse of _pack_string_data: u32 words -> uint8[n, width].
    ``words`` must be non-empty (zero-width matrices never pack words;
    unpack_plane rebuilds their empty shape directly)."""
    n = words[0].shape[0]
    stacked = jnp.stack(words, axis=1)                    # [n, nwords]
    shifts = jnp.array([24, 16, 8, 0], jnp.uint32)
    bytes_ = ((stacked[:, :, None] >> shifts) & jnp.uint32(0xFF)).astype(
        jnp.uint8).reshape(n, -1)
    return bytes_[:, :width]


def _unpack_string_words64(words: Sequence[jax.Array],
                           width: int) -> jax.Array:
    """u64 big-endian words (keys.pack_string_words layout) ->
    uint8[n, width] — the decode half of the dictionary value store."""
    n = words[0].shape[0]
    stacked = jnp.stack(words, axis=1)                    # [n, nwords]
    shifts = jnp.array([56, 48, 40, 32, 24, 16, 8, 0], jnp.uint64)
    bytes_ = ((stacked[:, :, None] >> shifts) & jnp.uint64(0xFF)).astype(
        jnp.uint8).reshape(n, -1)
    return bytes_[:, :width]


def _narrow_encode(data: jax.Array, offset: int, bits: int) -> jax.Array:
    """value -> u32 field: (value - offset), exact because the observed
    range guarantees 0 <= value - offset < 2^bits for every live row.
    Rows outside the observed range (padding rows the exchange never
    sends) may wrap — their field bits are never decoded."""
    if bits == 0:
        return jnp.zeros(data.shape, jnp.uint32)
    if jnp.issubdtype(data.dtype, jnp.unsignedinteger) \
            and data.dtype.itemsize == 8:
        return (data - jnp.uint64(offset)).astype(jnp.uint32)
    return (data.astype(jnp.int64) - jnp.int64(offset)).astype(jnp.uint32)


def _narrow_decode(field: jax.Array, offset: int, dtype) -> jax.Array:
    """u32 field -> value: offset + field, computed 64-bit wide then cast
    back to the column dtype (exact: the value came from that dtype)."""
    if jnp.issubdtype(dtype, jnp.unsignedinteger) and dtype.itemsize == 8:
        return (jnp.uint64(offset) + field.astype(jnp.uint64)).astype(dtype)
    return (jnp.int64(offset) + field.astype(jnp.int64)).astype(dtype)


def _field_values(cols: Sequence[Column], spec=None,
                  codes: Optional[Dict[int, jax.Array]] = None
                  ) -> List[jax.Array]:
    """u32[n] value array per field (same order as _field_widths); every
    value already fits its declared bit width.  ``codes`` carries the
    per-row dictionary codes for spec "dict" columns (PlaneCodec computes
    them — they need the all-gathered global dictionary)."""
    vals: List[jax.Array] = []
    for i, (c, enc) in enumerate(zip(cols, _spec_of(cols, spec))):
        vals.append(c.validity.astype(jnp.uint32))
        if c.is_string:
            if enc[0] == "dict":
                vals.append((codes or {})[i])
            elif enc[0] == "trunc":
                vals.extend(_pack_string_data(c.data[:, :enc[1]]))
                vals.append(c.lengths.astype(jnp.uint32))
            else:
                vals.extend(_pack_string_data(c.data))
                vals.append(jax.lax.bitcast_convert_type(
                    c.lengths.astype(jnp.int32), jnp.uint32))
        elif c.data.dtype == jnp.bool_:
            vals.append(c.data.astype(jnp.uint32))
        elif enc[0] == "narrow":
            vals.append(_narrow_encode(c.data, enc[1], enc[2]))
        elif c.data.dtype.itemsize == 8:
            w32 = jax.lax.bitcast_convert_type(c.data, jnp.uint32)  # [n, 2]
            vals.append(w32[:, 0])
            vals.append(w32[:, 1])
        else:
            bits = jax.lax.bitcast_convert_type(
                c.data, _UINT_OF[c.data.dtype.itemsize])
            vals.append(bits.astype(jnp.uint32))
    return vals


def pack_plane(cols: Sequence[Column], spec=None,
               codes: Optional[Dict[int, jax.Array]] = None) -> jax.Array:
    """Bit-pack the columns' buffers into one uint32[rows, words] plane.
    Bit-exact round trip with unpack_plane (floats travel as raw bits, so
    NaN payloads and -0.0 survive).  With ``spec``, compressed fields are
    laid out instead of raw ones (dict columns need ``codes``)."""
    widths = _field_widths(cols, spec)
    slots, nwords = _layout(widths)
    n = cols[0].data.shape[0]
    words: List[Optional[jax.Array]] = [None] * nwords
    for (word, shift, bits), v in zip(slots, _field_values(cols, spec,
                                                           codes)):
        if bits == 0:
            continue
        sh = v if shift == 0 else (v << jnp.uint32(shift))
        words[word] = sh if words[word] is None else (words[word] | sh)
    if nwords == 0:
        return jnp.zeros((n, 0), jnp.uint32)
    return jnp.stack([w for w in words], axis=1)


def unpack_plane(plane: jax.Array, like: Sequence[Column],
                 valid_mask: Optional[jax.Array] = None, spec=None,
                 dicts: Optional[Dict[int, Tuple[jax.Array, ...]]] = None,
                 tail_mask: Optional[jax.Array] = None) -> Tuple[Column, ...]:
    """Decode a packed plane back into Columns with ``like``'s schema
    (dtypes, string widths).  ``valid_mask`` ANDs into every column's
    validity and zeroes masked rows' data/lengths — the exact masking
    Column.take applies, so packed and per-buffer exchanges produce
    bit-identical shards.  ``tail_mask`` (compressed ragged path) forces
    rows beyond it to all-zero buffers WITHOUT touching in-range null
    rows' raw payloads — the unwritten tail of a ragged output buffer
    would otherwise decode to ``offset``/dictionary-entry-0 values
    instead of the zeros the uncompressed realizations produce."""
    widths = _field_widths(like, spec)
    slots, nwords = _layout(widths)
    assert plane.shape[1] == nwords, (plane.shape, nwords)
    it = iter(slots)
    n = plane.shape[0]

    def field() -> jax.Array:
        word, shift, bits = next(it)
        if bits == 0:
            return jnp.zeros((n,), jnp.uint32)
        v = plane[:, word]
        if shift:
            v = v >> jnp.uint32(shift)
        if bits < 32:
            v = v & jnp.uint32((1 << bits) - 1)
        return v

    def _widen(mat: jax.Array, width: int) -> jax.Array:
        if mat.shape[1] == width:
            return mat
        pad = jnp.zeros((n, width - mat.shape[1]), jnp.uint8)
        return jnp.concatenate([mat, pad], axis=1)

    out: List[Column] = []
    for i, (c, enc) in enumerate(zip(like, _spec_of(like, spec))):
        validity = field().astype(jnp.bool_)
        lengths = None
        if c.is_string:
            if enc[0] == "dict":
                idx = field().astype(jnp.int32)
                gws = (dicts or {})[i]
                vals = [jnp.take(w, idx, mode="clip") for w in gws]
                lengths = vals[-1].astype(jnp.int32)
                nbytes = enc[1]
                mat = (_unpack_string_words64(vals[:-1], nbytes) if nbytes
                       else jnp.zeros((n, 0), jnp.uint8))
                data = _widen(mat, c.string_width)
            elif enc[0] == "trunc":
                nbytes = enc[1]
                words = [field() for _ in range((nbytes + 3) // 4)]
                mat = (_unpack_string_data(words, nbytes) if words
                       else jnp.zeros((n, 0), jnp.uint8))
                data = _widen(mat, c.string_width)
                lengths = field().astype(jnp.int32)
            else:
                words = [field() for _ in range(_string_word_count(c))]
                data = (_unpack_string_data(words, c.string_width) if words
                        else jnp.zeros((n, c.string_width), jnp.uint8))
                lengths = jax.lax.bitcast_convert_type(field(), jnp.int32)
        elif c.data.dtype == jnp.bool_:
            data = field().astype(jnp.bool_)
        elif enc[0] == "narrow":
            data = _narrow_decode(field(), enc[1], c.data.dtype)
        elif c.data.dtype.itemsize == 8:
            pair = jnp.stack([field(), field()], axis=1)        # [n, 2]
            data = jax.lax.bitcast_convert_type(
                jax.lax.bitcast_convert_type(pair, jnp.uint64), c.data.dtype)
        else:
            w = c.data.dtype.itemsize
            data = jax.lax.bitcast_convert_type(
                field().astype(_UINT_OF[w]), c.data.dtype)
        if tail_mask is not None:
            validity = validity & tail_mask
            zero = jnp.zeros((), data.dtype)
            data = jnp.where(tail_mask[:, None] if data.ndim == 2
                             else tail_mask, data, zero)
            if lengths is not None:
                lengths = jnp.where(tail_mask, lengths, 0)
        if valid_mask is not None:
            validity = validity & valid_mask
            zero = jnp.zeros((), data.dtype)
            data = jnp.where(validity[:, None] if data.ndim == 2 else validity,
                             data, zero)
            if lengths is not None:
                lengths = jnp.where(validity, lengths, 0)
        out.append(Column(data, validity, lengths, c.dtype))
    return tuple(out)


# ---------------------------------------------------------------------------
# compression spec: observed stats -> static field encodings
# ---------------------------------------------------------------------------


def stats_layout(cols: Sequence[Column]) -> Tuple[Optional[str], ...]:
    """Which observation each column needs: "int" (min/max), "str"
    (extent/maxlen/nunique), None (float/bool — raw always).  The shared
    walk order between partition.column_stats (device) and build_spec
    (host): the two must consume the same flat stats sequence."""
    lay: List[Optional[str]] = []
    for c in cols:
        if c.is_string:
            lay.append("str")
        elif c.data.dtype != jnp.bool_ and jnp.issubdtype(c.data.dtype,
                                                          jnp.integer):
            lay.append("int")
        else:
            lay.append(None)
    return tuple(lay)


def _pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def _round_bits(bits: int) -> int:
    """Field widths round up to multiples of 4 so small data drift keeps
    hitting the same traced program (the jit-cache-churn bound)."""
    return ((bits + 3) // 4) * 4


def build_spec(cols: Sequence[Column], stats: Sequence, world: int,
               shard_cap: int):
    """Observed per-column stats -> the static compression spec, or None
    when nothing compresses (the all-raw spec normalizes to None so the
    baseline jit programs are reused verbatim).

    ``stats`` is the flat host-side sequence matching stats_layout: two
    values (min, max) per "int" column, three (byte extent, max length,
    max per-shard distinct count) per "str" column.  All values are
    REPLICATED observations (device collectives or a single-controller
    host pass), so every process derives the identical spec — the SPMD
    requirement for a layout that shapes the traced program."""
    import numpy as np

    it = iter(stats)
    spec: List[Tuple] = []
    any_comp = False
    for c, kind in zip(cols, stats_layout(cols)):
        if kind == "int":
            mn = int(np.asarray(next(it)).reshape(-1)[0])
            mx = int(np.asarray(next(it)).reshape(-1)[0])
            raw_bits = c.data.dtype.itemsize * 8
            if mx < mn:                      # no live rows anywhere
                spec.append(("narrow", 0, 0))
                any_comp = True
                continue
            span = mx - mn                   # exact Python-int arithmetic
            bits = _round_bits(span.bit_length())
            if bits <= 32 and bits < raw_bits:
                spec.append(("narrow", mn, bits))
                any_comp = True
            else:
                spec.append(RAW)
        elif kind == "str":
            extent = int(np.asarray(next(it)).reshape(-1)[0])
            maxlen = int(np.asarray(next(it)).reshape(-1)[0])
            nun = int(np.asarray(next(it)).reshape(-1)[0])
            len_bits = _round_bits(maxlen.bit_length())
            raw_cost = 32 * _string_word_count(c) + 32
            trunc_cost = 32 * ((extent + 3) // 4) + len_bits
            lcap = min(_pow2(max(1, nun)), max(1, int(shard_cap)))
            gcap = 1 + world * lcap
            code_bits = _round_bits(max(1, (gcap - 1).bit_length()))
            if nun > 0 and gcap <= _DICT_GCAP_MAX \
                    and code_bits < min(trunc_cost, raw_cost):
                spec.append(("dict", extent, lcap, gcap, code_bits))
                any_comp = True
            elif trunc_cost < raw_cost:
                spec.append(("trunc", extent, len_bits))
                any_comp = True
            else:
                spec.append(RAW)
        else:
            spec.append(RAW)
    return tuple(spec) if any_comp else None


def estimate_spec(cols: Sequence[Column], world: int, shard_cap: int,
                  count=None):
    """Host-side spec from locally addressable buffers (np.asarray pulls
    them) — for ADVISORY consumers only: plan.explain annotations, the
    microbench A/B, and the budget tracer's direct ragged trace.  The
    real exchange derives its spec from the replicated device stats pass
    (partition.column_stats) so multi-controller processes can never
    disagree on the layout."""
    import numpy as np

    n = cols[0].data.shape[0] if cols else 0
    live_n = n if count is None else int(count)
    stats: List[int] = []
    for c, kind in zip(cols, stats_layout(cols)):
        if kind == "int":
            d = np.asarray(c.data)[:live_n]
            if d.size == 0:
                stats.extend([0, -1])
            else:
                stats.extend([int(d.min()), int(d.max())])
        elif kind == "str":
            mat = np.asarray(c.data)[:live_n]
            lens = np.asarray(c.lengths)[:live_n]
            if mat.shape[0] == 0:
                stats.extend([0, 0, 1])
                continue
            nz = np.nonzero(mat.any(axis=0))[0]
            extent = int(nz[-1]) + 1 if nz.size else 0
            maxlen = int(lens.max()) if lens.size else 0
            pad = (-mat.shape[1]) % 8
            if pad:
                mat = np.concatenate(
                    [mat, np.zeros((mat.shape[0], pad), np.uint8)], axis=1)
            rows = np.concatenate(
                [mat, lens.astype(np.int64).view(np.uint8).reshape(
                    len(lens), 8)], axis=1)
            nun = len(np.unique(rows.view(
                [("", np.uint8, rows.shape[1])])))
            stats.extend([extent, maxlen, nun])
    return build_spec(cols, stats, world, shard_cap)


# ---------------------------------------------------------------------------
# dictionary key machinery — SHARED by the observation pass
# (partition.column_stats sizes lcap from a distinct-count upper bound)
# and the codec (which builds the actual local dictionary): both must
# walk the identical key space or the dictionary silently overflows lcap
# ---------------------------------------------------------------------------


def string_key_words(c: Column, nbytes: Optional[int] = None
                     ) -> List[jax.Array]:
    """THE dictionary key tuple for one string column: big-endian u64
    data words (optionally truncated to ``nbytes`` — truncation can only
    merge keys, so a full-width distinct count stays an upper bound)
    plus the length word."""
    from ..ops import keys as keys_mod

    data = c.data if nbytes is None else c.data[:, :nbytes]
    kws = keys_mod.pack_string_words(data) if data.shape[1] else []
    return kws + [c.lengths.astype(jnp.uint64)]


def sorted_distinct_flags(kws: Sequence[jax.Array]
                          ) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """lex-sort the key tuple and flag the first row of every distinct
    group: (sorted words, bool flag).  ``sum(flag)`` is the distinct
    count; compacting the flagged rows yields the sorted dictionary."""
    swv = jax.lax.sort(tuple(kws), num_keys=len(kws), is_stable=False)
    if not isinstance(swv, (tuple, list)):
        swv = (swv,)
    neq = functools.reduce(
        lambda a, b: a | b, [w[1:] != w[:-1] for w in swv])
    flag = jnp.concatenate([jnp.ones((1,), jnp.bool_), neq])
    return tuple(swv), flag


# ---------------------------------------------------------------------------
# codec: the spec applied to one shard's columns (dictionary build is a
# collective, so codecs are constructed INSIDE the shard body)
# ---------------------------------------------------------------------------


class PlaneCodec:
    """pack/unpack under one compression spec.  ``spec=None`` is the
    exact PR-2 baseline (no extra ops traced).  Dictionary columns cost
    ONE all_gather total at construction — every shard derives the
    identical sorted global dictionary from the gathered per-shard local
    dictionaries, so sender codes decode on any receiver."""

    def __init__(self, cols: Sequence[Column], spec=None):
        self.spec = spec
        self.codes: Dict[int, jax.Array] = {}
        self.dicts: Dict[int, Tuple[jax.Array, ...]] = {}
        if spec is None:
            return
        dcols = [(i, e) for i, e in enumerate(spec) if e[0] == "dict"]
        if not dcols:
            return
        from ..obs import spans as obs_spans
        from ..ops import compact as compact_mod
        from . import collectives

        def _distinct_sorted(kws: Sequence[jax.Array], keep: int):
            """(sorted distinct prefix padded with sentinels, count)."""
            swv, flag = sorted_distinct_flags(kws)
            perm, m = compact_mod.compact_indices(flag)
            sel = perm[:keep]
            ok = jnp.arange(keep, dtype=jnp.int32) < m
            return [jnp.where(ok, jnp.take(w, sel, mode="clip"), _SENT64)
                    for w in swv], m

        with obs_spans.span("shuffle.dict_gather", columns=len(dcols)):
            locals_: List[Tuple[int, Tuple, List[jax.Array],
                                List[jax.Array]]] = []
            for i, e in dcols:
                _, nbytes, lcap, gcap, code_bits = e
                kws = string_key_words(cols[i], nbytes)
                loc, _m = _distinct_sorted(kws, lcap)
                locals_.append((i, e, kws, loc))
            # ONE gather for every dictionary column: pad to a common
            # word count and concatenate rows
            maxk = max(len(loc) for _, _, _, loc in locals_)
            blocks = []
            for _i, _e, _kws, loc in locals_:
                padded = loc + [jnp.full_like(loc[0], _SENT64)
                                ] * (maxk - len(loc))
                blocks.append(jnp.stack(padded, axis=1))   # [lcap, maxk]
            buf = jnp.concatenate(blocks, axis=0)
            # all_gather stacks a new leading mesh axis: [world, rows, k]
            g3 = collectives.allgather(buf, axis=0)
            world = g3.shape[0]
        off = 0
        for i, e, kws, loc in locals_:
            _, nbytes, lcap, gcap, code_bits = e
            k = len(loc)
            block = g3[:, off:off + lcap, :k].reshape(world * lcap, k)
            off += lcap
            # code 0 is the all-zero row by construction: prepend it so
            # unwritten ragged tails (zero codes) decode to zero buffers
            gl = [jnp.concatenate([jnp.zeros((1,), jnp.uint64),
                                   block[:, j]]) for j in range(k)]
            gd, _g = _distinct_sorted(gl, gcap)
            self.dicts[i] = tuple(gd)
            # per-row codes: merged sort of (dict entries, rows) with a
            # dict-first marker — a row's code is the index of its value
            # in the sorted distinct dictionary (cumsum of dict entries
            # seen), scattered back to row order
            cap = cols[i].data.shape[0]
            keys_m = [jnp.concatenate([gd[j], kws[j]]) for j in range(k)]
            marker = jnp.concatenate([jnp.zeros((gcap,), jnp.bool_),
                                      jnp.ones((cap,), jnp.bool_)])
            payload = jnp.concatenate([jnp.zeros((gcap,), jnp.int32),
                                       jnp.arange(cap, dtype=jnp.int32)])
            srt = jax.lax.sort(tuple(keys_m) + (marker, payload),
                               num_keys=k + 1, is_stable=True)
            marker_s, payload_s = srt[-2], srt[-1]
            dictpos = jnp.cumsum((~marker_s).astype(jnp.int32)) - 1
            target = jnp.where(marker_s, payload_s, cap)
            self.codes[i] = jnp.zeros((cap + 1,), jnp.uint32).at[
                target].set(dictpos.astype(jnp.uint32))[:cap]

    def pack(self, cols: Sequence[Column]) -> jax.Array:
        return pack_plane(cols, self.spec, self.codes)

    def unpack(self, plane: jax.Array, like: Sequence[Column],
               valid_mask: Optional[jax.Array] = None,
               tail_mask: Optional[jax.Array] = None) -> Tuple[Column, ...]:
        return unpack_plane(plane, like, valid_mask=valid_mask,
                            spec=self.spec, dicts=self.dicts,
                            tail_mask=tail_mask)
