"""Thin wrappers over XLA collectives.

Replaces the reference's typed MPI collective wrappers
(cpp/src/cylon/net/mpi/mpi_operations.cpp:18-78 mpi::AllReduce /
GetMPIOp / GetMPIDataType and net/comm_operations.hpp ReduceOp): inside a
``shard_map`` region psum/pmin/pmax over the mesh axis ARE the AllReduce;
there is no type dispatch because XLA handles element types natively.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..context import PARTITION_AXIS


def allreduce_sum(x):
    return jax.lax.psum(x, PARTITION_AXIS)


def allreduce_min(x):
    return jax.lax.pmin(x, PARTITION_AXIS)


def allreduce_max(x):
    return jax.lax.pmax(x, PARTITION_AXIS)


def allgather(x, axis: int = 0):
    return jax.lax.all_gather(x, PARTITION_AXIS, axis=axis)


def all_to_all(x, split_axis: int = 0, concat_axis: int = 0):
    return jax.lax.all_to_all(x, PARTITION_AXIS, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def ragged_all_to_all(operand, output, input_offsets, send_sizes,
                      output_offsets, recv_sizes):
    """``lax.ragged_all_to_all`` over the partition axis (exact-traffic
    exchange; not implemented by every backend — callers probe via
    parallel.ops._ragged_enabled).  Centralized so the packed-plane and
    per-buffer shuffle bodies share one launch site."""
    return jax.lax.ragged_all_to_all(
        operand, output, input_offsets, send_sizes, output_offsets,
        recv_sizes, axis_name=PARTITION_AXIS)


def my_rank():
    return jax.lax.axis_index(PARTITION_AXIS)
