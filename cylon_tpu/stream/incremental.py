"""Incremental refresh over a StreamTable's frozen micro-batch log.

Two query shapes, one exactness contract:

``GroupByQuery``
    The PR-9 partial/combine/finalize decomposition, turned incremental:
    each refresh computes the delta batches' partial aggregates
    (``groupby_partial_plan`` layout), combines them with the persisted
    partial state in ONE jitted pass, persists the new state as a
    checksummed Arrow IPC spill (part id = watermark), and finalizes —
    finalize is the unchanged ``finalize_groupby_columns``.  NUNIQUE has
    no partial/combine decomposition, so it refreshes in ``full`` mode
    (concatenate + one local group-by) — ``explain()`` says which and
    why.

``JoinQuery``
    Incremental join against a STATIC dimension table, riding the PR-17
    broadcast-hash rule: the small dim side is materialized once, and
    only delta fact batches probe it; per-batch probe outputs are
    journaled so a refresh replays committed probes from the spill
    instead of re-executing them.

The exactness oracle is non-negotiable: the refresh result at watermark
N is bit-identical to ``recompute_cold()`` — a from-scratch fold over
the frozen batches 0..N-1 with no journal in the loop.  Three design
rules carry that:

* stream kernels always run on a LOCAL world-1 context regardless of
  the ambient mesh, so worlds 1/2/4 execute the identical program;
* batch boundaries are part of the durable contract (StreamTable), so
  the floating-point combine order is pinned by the log, not by which
  process happens to fold it;
* every capacity in the fold (batch pad, state pad, regrowth) is a pure
  function of the data in the log, so a cold replay re-derives the
  exact same padded shapes — and identical shapes + identical op order
  = identical bits.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import column as colmod
from .. import config
from .. import durable
from .. import exec as exec_mod
from ..column import Column
from ..context import default_context
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..ops import groupby as groupby_mod
from ..ops.groupby import AggOp
from ..parallel import ops as par_ops
from ..status import Code, CylonError
from ..utils import pow2ceil
from . import state as state_mod

#: manifest level persisted aggregate state / probe outputs live at
STATE_LEVEL = 0


# ---------------------------------------------------------------------------
# knob accessors (config.py registry names these — CY103)
# ---------------------------------------------------------------------------

def batch_cap() -> int:
    """CYLON_TPU_STREAM_BATCH_CAP: fixed device capacity per micro-batch
    (0 = derive ``pow2ceil(rows)`` per batch)."""
    return int(config.knob("CYLON_TPU_STREAM_BATCH_CAP"))


def state_cap() -> int:
    """CYLON_TPU_STREAM_STATE_CAP: floor for the persisted-state group
    capacity (0 = derive from the first batch's group count; state
    regrows by the deterministic overflow-restart rule either way)."""
    return int(config.knob("CYLON_TPU_STREAM_STATE_CAP"))


# ---------------------------------------------------------------------------
# jit kernel cache — the "reused compiled plan" the acceptance criteria
# count: a second refresh over same-shaped deltas must be all hits
# ---------------------------------------------------------------------------

_KERNELS: Dict[tuple, object] = {}


def _cached_kernel(key: tuple, build):
    full = (key, config.trace_cache_token())
    fn = _KERNELS.get(full)
    if fn is None:
        obs_metrics.counter_add("plan_cache.miss")
        fn = build()
        _KERNELS[full] = fn
    else:
        obs_metrics.counter_add("plan_cache.hit")
    return fn


def _shapes_key(cols: Sequence[Column]) -> tuple:
    return tuple((tuple(c.data.shape), str(c.data.dtype),
                  c.lengths is not None, str(c.dtype)) for c in cols)


def _take_all(c: Column, perm):
    """Row-gather every buffer of a column (2-D string matrices too)."""
    data = c.data[perm] if c.data.ndim == 1 else c.data[perm, :]
    lengths = None if c.lengths is None else c.lengths[perm]
    return Column(data, c.validity[perm], lengths, c.dtype)


def _pad_rows(a, cap: int):
    n = a.shape[0]
    if n == cap:
        return a
    if n > cap:
        return a[:cap] if a.ndim == 1 else a[:cap, :]
    pad = [(0, cap - n)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad)


def _pad_col(c: Column, cap: int) -> Column:
    return Column(_pad_rows(c.data, cap), _pad_rows(c.validity, cap),
                  None if c.lengths is None else _pad_rows(c.lengths, cap),
                  c.dtype)


def _key_refill(arr: np.ndarray, src_dtype) -> np.ndarray:
    """Reloaded key columns with null groups come back object-typed;
    refill nulls with the SAME payload ``from_numpy`` validity inference
    produces on upload (canonical NaN / NaT), so the re-uploaded state's
    key operands are bit-identical to the device-native state's (key
    payloads are unmasked sort operands — a drifted null payload would
    split the null group)."""
    if arr.dtype != object:
        return arr
    if np.issubdtype(src_dtype, np.floating):
        mask = np.asarray([v is None for v in arr])
        return np.where(mask, np.nan, arr).astype(src_dtype)
    if np.issubdtype(src_dtype, np.datetime64):
        out = arr.copy()
        out[np.asarray([v is None for v in arr])] = np.datetime64("NaT")
        return out.astype(src_dtype)
    return arr  # strings: from_numpy's missing handling IS the convention


def _concat_cols(a: Column, b: Column) -> Column:
    """Concatenate two columns row-wise; string matrices zero-pad to the
    wider width first (zero pad bytes never change key comparisons)."""
    ad, bd = a.data, b.data
    if ad.ndim == 2:
        w = max(ad.shape[1], bd.shape[1])
        ad = jnp.pad(ad, ((0, 0), (0, w - ad.shape[1])))
        bd = jnp.pad(bd, ((0, 0), (0, w - bd.shape[1])))
    data = jnp.concatenate([ad, bd], axis=0)
    validity = jnp.concatenate([a.validity, b.validity])
    lengths = None
    if a.lengths is not None:
        lengths = jnp.concatenate([a.lengths, b.lengths])
    return Column(data, validity, lengths, a.dtype)


# ---------------------------------------------------------------------------
# incremental group-by
# ---------------------------------------------------------------------------

class GroupByQuery:
    """Incremental group-by over a StreamTable.

    ``refresh()`` returns ``(frame, stats)`` where ``frame`` is a host
    dict of numpy arrays (same naming convention as ``Table.groupby``)
    and ``stats`` carries the incrementality evidence: ``parts_run`` =
    delta batches folded on device, ``partial_rows`` = delta rows fed to
    partial kernels, ``passes_skipped`` = batches answered from
    persisted state or the result cache.
    """

    def __init__(self, stream, by, agg, ddof: int = 0):
        if stream.schema is None:
            raise CylonError(Code.Invalid,
                             "stream has no schema yet — append a batch "
                             "before building a refresh query")
        self.stream = stream
        self.ddof = int(ddof)
        names = list(stream.schema)
        by_list = [by] if isinstance(by, (str, int, np.integer)) else list(by)
        self.by: List[str] = []
        for b in by_list:
            name = names[b] if isinstance(b, (int, np.integer)) else str(b)
            if name not in names:
                raise CylonError(Code.KeyError,
                                 f"no stream column named {name!r}")
            self.by.append(name)
        self.agg_named = exec_mod._normalize_agg(agg, names)

        # projection fed to the kernels: keys, then distinct value cols
        self.val_cols: List[str] = []
        for c, _ in self.agg_named:
            if c not in self.val_cols:
                self.val_cols.append(c)
        self.proj: Tuple[str, ...] = tuple(self.by) + tuple(self.val_cols)
        self.nkeys = len(self.by)
        self.key_idx = tuple(range(self.nkeys))
        self.aggs_idx = tuple(
            (self.nkeys + self.val_cols.index(c), op)
            for c, op in self.agg_named)
        self.out_names = tuple(self.by) + tuple(
            f"{op.name.lower()}_{c}" for c, op in self.agg_named)

        #: NUNIQUE has no partial/combine decomposition — full recompute
        self.incremental = all(op != AggOp.NUNIQUE
                               for _, op in self.agg_named)

        if self.incremental:
            # PR-9 partial layout, plus an always-carried COUNT partial
            # per value column (exec._partials_for convention): with it,
            # the identity refill of a reloaded spill (numeric_fill) is
            # EXACTLY equivalent to device-native validity masking —
            # finalize derives all-null-group validity from count > 0.
            plist, pindex = par_ops.groupby_partial_plan(self.aggs_idx)
            for ci, _ in self.aggs_idx:
                if (ci, AggOp.COUNT) not in pindex:
                    pindex[(ci, AggOp.COUNT)] = len(plist)
                    plist.append((ci, AggOp.COUNT))
            self.partial_list = tuple(plist)
            self.partial_index = dict(pindex)
            self.final_aggs = tuple(
                (self.nkeys + i, groupby_mod.combine_op(pop))
                for i, (_, pop) in enumerate(self.partial_list))
            self._state_names = tuple(
                [f"k{i}" for i in range(self.nkeys)]
                + [f"p{i}" for i in range(len(self.partial_list))])
        else:
            self.partial_list = ()
            self.partial_index = {}
            self.final_aggs = ()
            self._state_names = ()

        self.spec = ("stream_groupby", self.stream.name, tuple(self.by),
                     tuple((c, op.name) for c, op in self.agg_named),
                     self.ddof)

        # persisted partial-aggregate state: its own pinned run journal
        self._state_journal = None
        if self.incremental:
            fp = durable.run_fingerprint("stream_state", self.spec, ())
            self._state_journal = durable.open_run(fp, "stream_state")
            if self._state_journal is not None:
                self._state_journal.pin()

    # -- kernels ----------------------------------------------------------

    def _upload_batch(self, arrs: Dict[str, np.ndarray], cap: int):
        return tuple(colmod.from_numpy(np.asarray(arrs[n]), capacity=cap)
                     for n in self.proj)

    def _partial(self, cols, rows: int):
        key = ("stream_partial", self.spec, _shapes_key(cols))

        def build():
            key_idx, aggs, ddof = self.key_idx, self.partial_list, self.ddof

            def fn(cs, count):
                return groupby_mod.hash_groupby(cs, count, key_idx, aggs,
                                                ddof)
            return jax.jit(fn)

        pcols, pm = _cached_kernel(key, build)(cols, jnp.int32(rows))
        return pcols, int(pm)

    def _combine(self, scols, gs: int, S: int, dcols, gd: int, B: int):
        """One jitted pass: compact live state+delta partial rows to the
        front (stable argsort keeps the combine order pinned to batch
        order), re-group on the keys with the combine ops, slice back to
        state capacity.  Returns (new state cols, new group count)."""
        key = ("stream_combine", self.spec, S, B, _shapes_key(scols),
               _shapes_key(dcols))

        def build():
            nkeys, final_aggs, ddof = self.nkeys, self.final_aggs, self.ddof

            def fn(st, gs_, dt, gd_):
                cat = tuple(_concat_cols(a, b) for a, b in zip(st, dt))
                live = jnp.concatenate(
                    [jnp.arange(S, dtype=jnp.int32) < gs_,
                     jnp.arange(B, dtype=jnp.int32) < gd_])
                # stable sort: live rows first, relative order preserved
                perm = jnp.argsort(jnp.where(live, 0, 1).astype(jnp.int32))
                packed = tuple(_take_all(c, perm) for c in cat)
                out_cols, ng = groupby_mod.hash_groupby(
                    packed, gs_ + gd_, tuple(range(nkeys)), final_aggs,
                    ddof)
                return tuple(_pad_col(c, S) for c in out_cols), ng
            return jax.jit(fn)

        ncols, nm = _cached_kernel(key, build)(scols, jnp.int32(gs), dcols,
                                               jnp.int32(gd))
        return ncols, int(nm)

    def _finalize(self, scols, m: int):
        key = ("stream_finalize", self.spec, _shapes_key(scols))

        def build():
            nkeys, aggs, pindex, ddof = (self.nkeys, self.aggs_idx,
                                         self.partial_index, self.ddof)

            def fn(st):
                outs = par_ops.finalize_groupby_columns(
                    list(st), nkeys, aggs, pindex, ddof)
                # pass-through aggs surface all-null groups as NULL via
                # the always-carried COUNT partial: device-native state
                # (validity False) and reloaded state (identity-refilled,
                # validity True) converge on the same output validity
                for pos, (ci, op) in enumerate(aggs):
                    if op in (AggOp.SUM, AggOp.MIN, AggOp.MAX, AggOp.SUMSQ):
                        cnt = st[nkeys + pindex[(ci, AggOp.COUNT)]]
                        c = outs[nkeys + pos]
                        outs[nkeys + pos] = Column(
                            c.data, c.validity & (cnt.data > 0), c.lengths,
                            c.dtype)
                return tuple(outs)
            return jax.jit(fn)

        out_cols = _cached_kernel(key, build)(scols)
        return {name: colmod.to_numpy(c, m)
                for name, c in zip(self.out_names, out_cols)}

    # -- the fold ---------------------------------------------------------

    def _fold(self, frames, state0, start: int, pass_guard):
        """Fold batches ``start..`` onto ``state0`` (or from scratch).

        Every capacity decision is a pure function of the log: batch cap
        = knob or pow2ceil(rows); state cap = knob floor or pow2ceil of
        the first partial's group count; on combine overflow the state
        regrows to pow2ceil(overflowed count) and the WHOLE fold
        restarts from batch 0 — so a cold replay re-derives the exact
        regrowth cascade and the final fold happens entirely at the
        final capacity in both paths.  Returns
        ``(cols, m, S, folded_batches, folded_rows)``."""
        bcap = batch_cap()
        floor = state_cap()
        while True:
            if state0 is not None:
                cols, m, S = state0
                i = start
            else:
                cols, m, S = None, 0, 0
                i = 0
            folded = 0
            frows = 0
            overflow = 0
            for j in range(i, len(frames)):
                if pass_guard is not None:
                    pass_guard()
                _names, arrs, rows = frames[j]
                B = bcap or pow2ceil(rows)
                if rows > B:
                    raise CylonError(
                        Code.Invalid,
                        f"batch {j} has {rows} rows > "
                        f"CYLON_TPU_STREAM_BATCH_CAP={B}")
                pcols, pm = self._partial(self._upload_batch(arrs, B), rows)
                folded += 1
                frows += rows
                if cols is None:
                    S = max(floor, pow2ceil(pm))
                    cols, m = tuple(_pad_col(c, S) for c in pcols), pm
                    continue
                ncols, nm = self._combine(cols, m, S, pcols, pm, B)
                if nm > S:
                    overflow = nm
                    break
                cols, m = ncols, nm
            if not overflow:
                return cols, m, S, folded, frows
            # deterministic regrowth: restart the fold from batch 0 at
            # the grown capacity (a cold replay hits the identical
            # overflow at the identical batch and regrows identically)
            obs_metrics.counter_add("stream.state_regrown")
            floor = max(floor, pow2ceil(overflow))
            state0, start = None, 0

    # -- persisted state --------------------------------------------------

    def _state_frame(self, cols, m: int) -> Dict[str, np.ndarray]:
        return {n: colmod.to_numpy(c, m)
                for n, c in zip(self._state_names, cols)}

    def _load_state(self, js, part: int):
        """Reload the persisted partial state at ``part`` (schema-version
        gated, CY116).  Returns ``(cols, m, S)`` or None."""
        try:
            prov = state_mod.require_state_version(
                js.pass_provenance(STATE_LEVEL, part))
        except CylonError:
            return None
        loaded = js.load_pass(STATE_LEVEL, part)
        if loaded is None:
            return None
        frame, m = loaded
        m = int(m)
        S = int(prov.get("cap", 0))
        if S <= 0 or m > S or tuple(frame.keys()) != self._state_names:
            return None
        cols = []
        for i, name in enumerate(self._state_names):
            arr = np.asarray(frame[name])
            if i >= self.nkeys:
                ci, pop = self.partial_list[i - self.nkeys]
                arr = exec_mod.numeric_fill(arr, pop, self._src_dtype(ci))
            else:
                arr = _key_refill(arr, self._src_dtype(i))
            cols.append(colmod.from_numpy(arr, capacity=S))
        return tuple(cols), m, S

    def _src_dtype(self, ci: int):
        """Numpy dtype of projection column ``ci`` (for the identity
        refill of all-null partials), from the first committed batch."""
        name = self.proj[ci]
        for _names, arrs, _rows in self.stream.frames():
            return np.asarray(arrs[name]).dtype
        raise CylonError(Code.Invalid, "stream has no batches")

    # -- refresh ----------------------------------------------------------

    def result_fingerprint(self, watermark: int) -> str:
        """The refresh result's journal fingerprint: folds the query
        spec AND the high watermark, so a refresh at an unchanged
        watermark is a pure cache hit and an append moves the key."""
        return durable.run_fingerprint(
            "stream_refresh", self.spec + (("watermark", int(watermark)),),
            ())

    def refresh(self, pass_guard=None):
        wm = self.stream.watermark
        if wm == 0:
            raise CylonError(Code.Invalid,
                             "refresh before the first committed batch")
        mode = "incremental" if self.incremental else "full"
        jr = durable.open_run(self.result_fingerprint(wm), "stream_refresh")
        with obs_spans.span("stream.refresh", stream=self.stream.name,
                            watermark=wm, op="groupby", mode=mode):
            if jr is not None and jr.is_complete():
                cached = self._load_result(jr)
                if cached is not None:
                    frame, rows = cached
                    obs_metrics.counter_add("stream.refresh_cached")
                    return frame, {
                        "parts_run": 0, "passes_skipped": 1,
                        "partial_rows": 0, "rows": int(rows),
                        "watermark": wm, "mode": mode,
                        "stream": self.stream.name}
            if self.incremental:
                frame, rows, stats = self._refresh_incremental(wm,
                                                               pass_guard)
            else:
                frame, rows, stats = self._refresh_full(wm, pass_guard)
            if jr is not None:
                jr.record_pass(
                    0, 0, frame, rows,
                    provenance=state_mod.state_provenance(watermark=wm))
                jr.record_done(1, rows)
            obs_metrics.counter_add("stream.refreshes")
            stats.update(watermark=wm, mode=mode, rows=int(rows),
                         stream=self.stream.name)
            return frame, stats

    def _load_result(self, jr):
        # CY116: version-gate the result spill before decoding it
        try:
            state_mod.require_state_version(jr.pass_provenance(0, 0))
        except CylonError:
            return None
        return jr.load_pass(0, 0)

    def _refresh_incremental(self, wm: int, pass_guard):
        frames = self.stream.frames()[:wm]
        js = self._state_journal
        state0, start = None, 0
        if js is not None:
            for p in sorted((p for p in js.parts_at_level(STATE_LEVEL)
                             if p <= wm), reverse=True):
                got = self._load_state(js, p)
                if got is not None:
                    state0, start = got, p
                    break
        cols, m, S, folded, frows = self._fold(frames, state0, start,
                                               pass_guard)
        if js is not None and (folded or state0 is None):
            js.record_pass(
                STATE_LEVEL, wm, self._state_frame(cols, m), m,
                provenance=state_mod.state_provenance(
                    watermark=wm, groups=m, cap=S))
        frame = self._finalize(cols, m)
        obs_metrics.counter_add("stream.rows_delta", frows)
        return frame, m, {"parts_run": folded,
                          "passes_skipped": max(0, wm - folded),
                          "partial_rows": frows, "state_groups": m,
                          "state_cap": S}

    def _refresh_full(self, wm: int, pass_guard):
        frames = self.stream.frames()[:wm]
        if pass_guard is not None:
            pass_guard()
        total = sum(r for _, _, r in frames)
        arrays = [np.concatenate([np.asarray(arrs[n]) for _, arrs, _ in
                                  frames]) for n in self.proj]
        from ..table import Table, _local_groupby

        t = Table.from_numpy(self.proj, arrays, ctx=default_context(),
                             capacity=pow2ceil(total))
        res = _local_groupby(t, self.key_idx, self.aggs_idx, self.ddof)
        frame = res.to_numpy()
        rows = len(next(iter(frame.values()))) if frame else 0
        obs_metrics.counter_add("stream.rows_delta", total)
        return frame, rows, {"parts_run": wm, "passes_skipped": 0,
                             "partial_rows": total}

    # -- oracle -----------------------------------------------------------

    def recompute_cold(self):
        """The exactness oracle: a from-scratch fold over the frozen
        concatenation of batches 0..watermark-1 with NO journal in the
        loop.  ``refresh()`` must be bit-identical to this — persisted
        state, crash-resume and the result cache may never drift."""
        wm = self.stream.watermark
        if wm == 0:
            raise CylonError(Code.Invalid, "stream has no batches")
        if not self.incremental:
            frame, _rows, _stats = self._refresh_full(wm, None)
            return frame
        cols, m, _S, _folded, _frows = self._fold(
            self.stream.frames()[:wm], None, 0, None)
        return self._finalize(cols, m)

    # -- introspection ----------------------------------------------------

    def describe(self) -> dict:
        reason = ("all aggregates decompose into partial+combine"
                  if self.incremental else
                  "NUNIQUE has no partial/combine decomposition")
        return {"kind": "groupby", "stream": self.stream.name,
                "watermark": self.stream.watermark,
                "mode": "incremental" if self.incremental else "full",
                "reason": reason, "by": list(self.by),
                "aggs": [f"{op.name.lower()}({c})"
                         for c, op in self.agg_named],
                "partials": len(self.partial_list),
                "durable": self._state_journal is not None}

    def explain(self) -> str:
        from ..plan import explain as explain_mod

        return explain_mod.explain_refresh(self.describe())

    def close(self, unpin: bool = False) -> None:
        if self._state_journal is not None and unpin:
            self._state_journal.unpin()

    def to_spec(self) -> dict:
        """JSON-safe round-trippable spec (serve/router submission)."""
        agg: Dict[str, list] = {}
        for c, op in self.agg_named:
            agg.setdefault(c, []).append(op.name.lower())
        return {"kind": "groupby", "stream": self.stream.name,
                "by": list(self.by), "agg": agg, "ddof": self.ddof}


# ---------------------------------------------------------------------------
# incremental join against a static dimension table
# ---------------------------------------------------------------------------

class JoinQuery:
    """Incremental fact-stream ⋈ static-dim join.

    The dim side is materialized ONCE (that is the broadcast of the
    PR-17 broadcast-hash rule — the small side replicates, the big side
    never moves); each delta batch probes it in a shard-local join at
    the batch's own capacity, and per-batch probe outputs are journaled
    (part id = batch id) so committed probes replay from the spill.
    The result is the concatenation of per-batch outputs in batch
    order."""

    def __init__(self, stream, dim, on=None, left_on=None, right_on=None,
                 how: str = "inner", algorithm: str = "hash"):
        if stream.schema is None:
            raise CylonError(Code.Invalid,
                             "stream has no schema yet — append a batch "
                             "before building a refresh query")
        self.stream = stream
        self.how = str(how)
        if self.how not in ("inner", "left"):
            # per-batch probes can't express dim-preserving joins: an
            # unmatched dim row would re-emit once per batch
            raise CylonError(Code.Invalid,
                             f"incremental join supports how='inner'/'left' "
                             f"(fact-side), not {self.how!r}")
        self.algorithm = str(algorithm)
        if on is not None:
            left_on = right_on = on
        if left_on is None or right_on is None:
            raise CylonError(Code.Invalid,
                             "join needs on= or left_on=/right_on=")
        as_list = (lambda v: [v] if isinstance(v, (str, int, np.integer))
                   else list(v))
        self.left_on = [str(c) for c in as_list(left_on)]
        self.right_on = [str(c) for c in as_list(right_on)]

        dim_names, dim_arrs = exec_mod.as_host_frame(dim)
        self._dim_names = tuple(str(n) for n in dim_names)
        self._dim_arrs = {str(k): np.asarray(v) for k, v in dim_arrs.items()}
        self._dim_rows = (len(self._dim_arrs[self._dim_names[0]])
                          if self._dim_names else 0)
        self._dim_table = None  # built lazily, once

        from .table import _content_fingerprint

        self.spec = ("stream_join", self.stream.name,
                     _content_fingerprint(self._dim_names, self._dim_arrs),
                     tuple(self.left_on), tuple(self.right_on), self.how,
                     self.algorithm)
        self.incremental = True

        fp = durable.run_fingerprint("stream_state", self.spec, ())
        self._state_journal = durable.open_run(fp, "stream_state")
        if self._state_journal is not None:
            self._state_journal.pin()

    def _dim(self):
        if self._dim_table is None:
            from ..table import Table

            self._dim_table = Table.from_numpy(
                self._dim_names,
                [self._dim_arrs[n] for n in self._dim_names],
                ctx=default_context(),
                capacity=pow2ceil(self._dim_rows))
        return self._dim_table

    def _probe_batch(self, arrs: Dict[str, np.ndarray], rows: int):
        """Join ONE fact batch against the broadcast dim table."""
        from ..table import Table

        bcap = batch_cap()
        B = bcap or pow2ceil(rows)
        if rows > B:
            raise CylonError(
                Code.Invalid,
                f"batch has {rows} rows > CYLON_TPU_STREAM_BATCH_CAP={B}")
        names = self.stream.schema
        lt = Table.from_numpy(names, [np.asarray(arrs[n]) for n in names],
                              ctx=default_context(), capacity=B)
        out = lt.join(self._dim(), left_on=self.left_on,
                      right_on=self.right_on, how=self.how,
                      algorithm=self.algorithm)
        return out.to_numpy()

    def _load_probe(self, js, part: int):
        """Reload one committed per-batch probe output (version-gated
        before decode, CY116)."""
        try:
            state_mod.require_state_version(
                js.pass_provenance(STATE_LEVEL, part))
        except CylonError:
            return None
        return js.load_pass(STATE_LEVEL, part)

    def result_fingerprint(self, watermark: int) -> str:
        return durable.run_fingerprint(
            "stream_refresh", self.spec + (("watermark", int(watermark)),),
            ())

    def refresh(self, pass_guard=None):
        wm = self.stream.watermark
        if wm == 0:
            raise CylonError(Code.Invalid,
                             "refresh before the first committed batch")
        jr = durable.open_run(self.result_fingerprint(wm), "stream_refresh")
        with obs_spans.span("stream.refresh", stream=self.stream.name,
                            watermark=wm, op="join", mode="incremental"):
            if jr is not None and jr.is_complete():
                try:
                    state_mod.require_state_version(jr.pass_provenance(0, 0))
                    cached = jr.load_pass(0, 0)
                except CylonError:
                    cached = None
                if cached is not None:
                    frame, rows = cached
                    obs_metrics.counter_add("stream.refresh_cached")
                    return frame, {
                        "parts_run": 0, "passes_skipped": 1,
                        "partial_rows": 0, "rows": int(rows),
                        "watermark": wm, "mode": "incremental",
                        "stream": self.stream.name}
            frames = self.stream.frames()[:wm]
            js = self._state_journal
            outputs: List[Tuple[Dict[str, np.ndarray], int]] = []
            probed = 0
            probed_rows = 0
            for b, (_names, arrs, rows) in enumerate(frames):
                loaded = None if js is None else self._load_probe(js, b)
                if loaded is not None:
                    outputs.append((loaded[0], int(loaded[1])))
                    continue
                if pass_guard is not None:
                    pass_guard()
                frame_b = self._probe_batch(arrs, rows)
                out_rows = (len(next(iter(frame_b.values())))
                            if frame_b else 0)
                probed += 1
                probed_rows += rows
                if js is not None:
                    js.record_pass(
                        STATE_LEVEL, b, frame_b, out_rows,
                        provenance=state_mod.state_provenance(
                            batch=b, rows=out_rows))
                outputs.append((frame_b, out_rows))
            frame = self._concat_outputs(outputs)
            rows = sum(r for _, r in outputs)
            if jr is not None:
                jr.record_pass(
                    0, 0, frame, rows,
                    provenance=state_mod.state_provenance(watermark=wm))
                jr.record_done(1, rows)
            obs_metrics.counter_add("stream.refreshes")
            obs_metrics.counter_add("stream.rows_delta", probed_rows)
            return frame, {"parts_run": probed,
                           "passes_skipped": wm - probed,
                           "partial_rows": probed_rows, "rows": int(rows),
                           "watermark": wm, "mode": "incremental",
                           "stream": self.stream.name}

    @staticmethod
    def _concat_outputs(outputs):
        if not outputs:
            return {}
        names = list(outputs[0][0].keys())
        return {n: np.concatenate([np.asarray(f[n]) for f, _ in outputs])
                for n in names}

    def recompute_cold(self):
        """Oracle: probe every frozen batch from scratch, no journal."""
        wm = self.stream.watermark
        if wm == 0:
            raise CylonError(Code.Invalid, "stream has no batches")
        outputs = []
        for _names, arrs, rows in self.stream.frames()[:wm]:
            frame_b = self._probe_batch(arrs, rows)
            out_rows = len(next(iter(frame_b.values()))) if frame_b else 0
            outputs.append((frame_b, out_rows))
        return self._concat_outputs(outputs)

    def describe(self) -> dict:
        return {"kind": "join", "stream": self.stream.name,
                "watermark": self.stream.watermark, "mode": "incremental",
                "reason": "static dim broadcasts once; only delta fact "
                          "rows probe (PR-17 broadcast-hash rule)",
                "on": [f"{l}={r}" for l, r in zip(self.left_on,
                                                  self.right_on)],
                "how": self.how, "dim_rows": self._dim_rows,
                "durable": self._state_journal is not None}

    def explain(self) -> str:
        from ..plan import explain as explain_mod

        return explain_mod.explain_refresh(self.describe())

    def close(self, unpin: bool = False) -> None:
        if self._state_journal is not None and unpin:
            self._state_journal.unpin()


# ---------------------------------------------------------------------------
# serve-layer entry point
# ---------------------------------------------------------------------------

def query_from_spec(spec: dict):
    """Rebuild a refresh query from its JSON spec: any replica sharing
    the durable dir replays the stream's batch log from the manifest and
    runs the identical refresh — this is what makes the serve op
    router-routable."""
    from .table import StreamTable

    if not isinstance(spec, dict) or "stream" not in spec:
        raise CylonError(Code.Invalid,
                         "refresh spec must be a dict with a 'stream' key")
    stream = StreamTable(str(spec["stream"]))
    if stream.watermark == 0:
        raise CylonError(Code.Invalid,
                         f"stream {spec['stream']!r} has no committed "
                         f"batches in the durable journal")
    kind = str(spec.get("kind", "groupby"))
    if kind == "groupby":
        return GroupByQuery(stream, spec.get("by", []),
                            dict(spec.get("agg", {})),
                            ddof=int(spec.get("ddof", 0)))
    if kind == "join":
        return JoinQuery(stream, dict(spec.get("dim", {})),
                         left_on=spec.get("left_on") or spec.get("on"),
                         right_on=spec.get("right_on") or spec.get("on"),
                         how=str(spec.get("how", "inner")),
                         algorithm=str(spec.get("algorithm", "hash")))
    raise CylonError(Code.Invalid, f"unknown refresh kind {kind!r}")


def run_refresh(query_or_spec, *args, ctx=None, pass_guard=None, **kwargs):
    """The serve layer's ``refresh`` op runner: accepts a built query
    object or its JSON spec.  Idempotent by construction (the result
    fingerprint folds the high-watermark batch id), hence hedge-safe and
    router-routable."""
    del ctx, args, kwargs  # streams always run on a local world-1 context
    q = query_or_spec
    if isinstance(q, dict):
        q = query_from_spec(q)
    return q.refresh(pass_guard=pass_guard)
