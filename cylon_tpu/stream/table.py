"""StreamTable: an append-only log of micro-batches, journaled durably.

Each ``append`` journals the batch's host frame as a new fsync'd pass in
the existing durable manifest (``durable.RunJournal``) with batch id,
row count and a content fingerprint in the pass provenance — so the
frozen batch log IS the manifest, and a ``kill -9`` mid-append costs at
most the in-flight batch.  Re-running the same append sequence after a
crash resumes bit-identically: appends whose content fingerprint matches
the already-committed batch at the replay cursor are idempotent no-ops,
and the first genuinely new batch lands at the high watermark.

The batch log never reshapes: the **watermark** is the count of
contiguous committed batches, batch ``i`` is pass ``(0, i)``, and the
concatenation of batches ``0..watermark-1`` in batch order is the frozen
table every refresh and every cold-recompute oracle agrees on — batch
boundaries are part of the durable contract, not an implementation
detail (floating-point combines are ordered by them).

The run dir is **pinned** (``durable.PINNED``) while the stream is open:
live stream state must never be evicted by the size-cap LRU GC between
refreshes, or every refresh silently degrades to a full recompute.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import durable
from .. import exec as exec_mod
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..status import Code, CylonError
from . import state as state_mod

#: manifest level all batch passes live at (part id == batch id)
BATCH_LEVEL = 0


def _content_fingerprint(names: Sequence[str],
                         arrs: Dict[str, np.ndarray]) -> str:
    """Content-only batch fingerprint: full coverage of every column
    (durable's position-mixed fold), deliberately EXCLUDING knobs and
    salts — the batch log is raw data, its identity must not move when
    a trace knob flips (results do; the refresh fingerprint folds knobs
    via ``durable.run_fingerprint``)."""
    h = hashlib.sha256()
    h.update(b"cylon_tpu.stream.batch.v1")
    for name in names:
        durable._update_array(h, str(name), np.asarray(arrs[name]))
    return h.hexdigest()


def _stream_fingerprint(name: str) -> str:
    """The append log's journal fingerprint: name-keyed and knob-blind
    (same reasoning as the content fingerprint — the LOG is identity,
    not computation)."""
    h = hashlib.sha256()
    h.update(f"cylon_tpu.stream.append.v1|{name}".encode())
    return h.hexdigest()


class StreamTable:
    """Append-only micro-batch log with a durable, crash-resumable
    journal.  ``append`` takes the same DataFrame / dict-of-arrays /
    Table inputs the chunked engine does (``exec.as_host_frame``)."""

    def __init__(self, name: str):
        self.name = str(name)
        self.fingerprint = _stream_fingerprint(self.name)
        #: committed batches, in batch order: (names, arrs, rows, fp)
        self._frames: List[Tuple[Tuple[str, ...], Dict[str, np.ndarray],
                                 int, str]] = []
        self._names: Optional[Tuple[str, ...]] = None
        #: idempotent-replay cursor: how many already-committed batches
        #: this process has re-appended (crash-resume re-runs)
        self._replay_cursor = 0
        self._journal = durable.open_run(self.fingerprint, "stream_append")
        if self._journal is not None:
            self._journal.pin()
            self._replay()

    # -- journal replay ---------------------------------------------------

    def _replay(self) -> None:
        """Rebuild the in-memory batch log from the manifest: contiguous
        committed batches from 0 up to the first gap (a torn tail from a
        crash mid-append is re-executed by the re-run, never guessed
        at).  Every spill decode is schema-version-gated."""
        j = self._journal
        assert j is not None
        for bid in j.parts_at_level(BATCH_LEVEL):
            if bid != len(self._frames):
                break  # gap: everything after a lost batch is dead tail
            prov = state_mod.require_state_version(
                j.pass_provenance(BATCH_LEVEL, bid))
            loaded = j.load_pass(BATCH_LEVEL, bid)
            if loaded is None:
                break  # corrupt/missing spill: the re-run re-appends it
            frame, rows = loaded
            names = tuple(frame.keys())
            if self._names is None:
                self._names = names
            self._frames.append((names, frame, int(rows),
                                 str(prov.get("content_fp", ""))))
        if self._frames:
            obs_spans.instant("stream.resume", stream=self.name,
                              batches=len(self._frames))

    # -- the append/watermark contract ------------------------------------

    @property
    def watermark(self) -> int:
        """High watermark: number of committed batches.  A refresh at an
        unchanged watermark is a pure cache hit (the refresh fingerprint
        folds this value)."""
        return len(self._frames)

    @property
    def schema(self) -> Optional[Tuple[str, ...]]:
        """Column names, known after the first batch (None before)."""
        return self._names

    def append(self, data) -> int:
        """Append one micro-batch; returns its batch id.

        Idempotent under crash-resume: re-appending a batch whose
        content fingerprint matches the already-committed batch at the
        replay cursor is a no-op (returns the existing id), so re-running
        the same driver script after a ``kill -9`` converges on the
        identical batch log."""
        names, arrs = exec_mod.as_host_frame(data)
        if not names:
            raise CylonError(Code.Invalid, "cannot append an empty frame "
                                           "(no columns)")
        rows = len(np.asarray(arrs[names[0]]))
        for k in names:
            if len(np.asarray(arrs[k])) != rows:
                raise CylonError(Code.Invalid,
                                 f"ragged batch: column {k!r} has "
                                 f"{len(np.asarray(arrs[k]))} rows != {rows}")
        names_t = tuple(str(n) for n in names)
        if self._names is not None and names_t != self._names:
            raise CylonError(
                Code.Invalid,
                f"batch schema {names_t} != stream schema {self._names} "
                f"(append-only streams never reshape)")
        arrs = {str(k): np.asarray(v) for k, v in arrs.items()}
        fp = _content_fingerprint(names_t, arrs)

        if self._replay_cursor < len(self._frames):
            committed = self._frames[self._replay_cursor]
            if committed[3] == fp:
                # crash-resume re-run replaying an already-durable batch
                self._replay_cursor += 1
                obs_spans.instant("stream.append_replayed",
                                  stream=self.name,
                                  batch=self._replay_cursor - 1)
                return self._replay_cursor - 1
            # divergence from the journal: this is genuinely new data —
            # stop replay-dedupe and append at the watermark
            self._replay_cursor = len(self._frames)

        bid = len(self._frames)
        with obs_spans.span("stream.append", stream=self.name, batch=bid,
                            rows=rows):
            if self._journal is not None:
                self._journal.record_pass(
                    BATCH_LEVEL, bid, arrs, rows,
                    provenance=state_mod.state_provenance(
                        batch=bid, rows=rows, content_fp=fp))
        if self._names is None:
            self._names = names_t
        self._frames.append((names_t, arrs, rows, fp))
        self._replay_cursor = len(self._frames)
        obs_metrics.counter_add("stream.batches_appended")
        obs_metrics.counter_add("stream.rows_appended", rows)
        return bid

    def frames(self) -> List[Tuple[Tuple[str, ...], Dict[str, np.ndarray],
                                   int]]:
        """The frozen batch log: [(names, host frame, rows)] in batch
        order — the concatenation every oracle recomputes over."""
        return [(n, f, r) for (n, f, r, _) in self._frames]

    def batch_rows(self) -> List[int]:
        return [r for (_, _, r, _) in self._frames]

    def close(self, unpin: bool = False) -> None:
        """Release the stream.  ``unpin=True`` re-admits the batch log
        to LRU GC (the stream is retired, not merely idle)."""
        if self._journal is not None and unpin:
            self._journal.unpin()

    def __repr__(self) -> str:
        return (f"StreamTable({self.name!r}, watermark={self.watermark}, "
                f"durable={self._journal is not None})")
