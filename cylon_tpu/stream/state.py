"""Persisted stream-state schema versioning.

Every spill the streaming layer journals — micro-batch frames and
partial-aggregate state alike — records a ``state_version`` field in its
manifest pass provenance.  Readers MUST validate it through
:func:`require_state_version` BEFORE decoding the spill (cylint CY116
enforces this lexically for every stream-package reader): the partial
layout (`groupby_partial_plan` column order, combine identities, the
validity-refill convention) is an on-disk contract, and a layout change
that silently misreads an old spill would corrupt a refresh without any
checksum noticing — the bytes are intact, the MEANING moved.
"""
from __future__ import annotations

from typing import Optional

from ..status import Code, CylonError

#: bump on ANY change to the persisted layout: partial column order,
#: identity-fill convention, watermark/provenance semantics
STATE_SCHEMA_VERSION = 1

#: the provenance field name (manifest JSON)
VERSION_FIELD = "state_version"


def state_provenance(**fields) -> dict:
    """Provenance dict for one stream spill: the schema version plus the
    caller's batch/watermark facts."""
    return {VERSION_FIELD: STATE_SCHEMA_VERSION, **fields}


def require_state_version(provenance: Optional[dict]) -> dict:
    """Validate a spill's recorded schema version before decoding it.

    Raises ``Code.Invalid`` when the provenance is absent (a spill
    journaled by something other than the stream layer, or a pre-stream
    journal) or records a different version (a combine-layout change).
    Returns the provenance dict so call sites can destructure it."""
    if not isinstance(provenance, dict) or VERSION_FIELD not in provenance:
        raise CylonError(
            Code.Invalid,
            "stream spill carries no state schema version — refusing to "
            "decode (not written by the stream layer, or written before "
            "versioning)")
    v = provenance[VERSION_FIELD]
    if int(v) != STATE_SCHEMA_VERSION:
        raise CylonError(
            Code.Invalid,
            f"stream state schema version {v} != supported "
            f"{STATE_SCHEMA_VERSION} — refusing to decode a spill whose "
            f"partial layout this build cannot interpret")
    return provenance
