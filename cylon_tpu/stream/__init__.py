"""Streaming ingestion: append-only micro-batches with durable,
incremental refresh (PR 19).

``StreamTable`` journals each appended micro-batch as an fsync'd pass
in the durable manifest; ``GroupByQuery``/``JoinQuery`` refresh
incrementally over the frozen batch log, persisting partial-aggregate
state between refreshes.  The refresh result at watermark N is
bit-identical to a cold full recompute over batches 0..N-1 — see
``recompute_cold()`` on either query class."""
from .incremental import (GroupByQuery, JoinQuery, batch_cap,  # noqa: F401
                          query_from_spec, run_refresh, state_cap)
from .state import (STATE_SCHEMA_VERSION, VERSION_FIELD,  # noqa: F401
                    require_state_version, state_provenance)
from .table import StreamTable  # noqa: F401

__all__ = [
    "StreamTable", "GroupByQuery", "JoinQuery", "run_refresh",
    "query_from_spec", "batch_cap", "state_cap",
    "STATE_SCHEMA_VERSION", "VERSION_FIELD", "require_state_version",
    "state_provenance",
]
