"""Accumulation-precision policy: wide (64-bit) vs narrow (32-bit) kernels.

The reference accumulates in the KernelTraits state type — double for
MEAN/VAR, the input type for SUM/MIN/MAX (compute/aggregate_kernels.hpp:
38-200).  On TPU, 64-bit tensors are a liability: f64 is software-emulated,
64-bit scatters profile ~8x slower than 32-bit ones, and some fused 64-bit
prefix programs have crashed this XLA TPU backend outright (see
ops/groupby.py notes).  So every kernel that needs a float accumulator or
derives float statistics consults this policy:

- ``wide``   — f64 accumulation/derivation, int64 counts.  The default on
  CPU meshes; bit-compatible with the reference goldens.
- ``narrow`` — f32 accumulation/derivation, int32 count scatters (widened
  to int64 only at column boundaries).  The default on TPU.  Integer SUM
  still accumulates int64 (a 100M-row int32 sum overflows i32); that is
  correctness-mandated, exactly like the reference's int64 sum state.

Resolution order: explicit ``set_accumulation()`` > ``CYLON_TPU_ACCUM``
env var > backend default (tpu -> narrow, else wide).  The mode is read at
trace time, so switch it before the first jitted compute of the process;
``set_accumulation`` clears jit caches to force retraces when switched
mid-process.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import config

_MODE: str | None = None  # None = auto-resolve


def set_accumulation(mode: str | None) -> None:
    """Force ``"wide"`` or ``"narrow"`` accumulation (None = auto)."""
    global _MODE
    if mode not in (None, "wide", "narrow"):
        raise ValueError(f"accumulation mode must be wide/narrow, got {mode}")
    if mode != _MODE:
        jax.clear_caches()  # jitted kernels read the mode at trace time
    _MODE = mode


def on_tpu() -> bool:
    """True when the default backend is a real TPU (the axon PJRT plugin
    tunnels one under its own platform name)."""
    return jax.default_backend() in ("tpu", "axon")


def accumulation_mode() -> str:
    if _MODE is not None:
        return _MODE
    env = config.knob("CYLON_TPU_ACCUM")
    if env in ("wide", "narrow"):
        return env
    return "narrow" if on_tpu() else "wide"


def narrow() -> bool:
    return accumulation_mode() == "narrow"


def float_acc():
    """Accumulator dtype for float prefix sums / derived statistics."""
    return jnp.float32 if narrow() else jnp.float64


def float_acc_for(data_dtype):
    """Float accumulator for a float SUM: input-width in wide mode (an f32
    sum stays f32, like the reference's input-typed sum state), f32 in
    narrow mode (f64 data trades precision for a native-width scatter)."""
    if narrow():
        return jnp.float32
    return jnp.float64 if data_dtype == jnp.float64 else jnp.float32


def int_acc():
    """Accumulator for integer sums — always wide; overflow is worse than
    an emulated 64-bit scatter."""
    return jnp.int64


def count_acc():
    """Count scatters always run i32 (cardinality < 2^31 per shard)."""
    return jnp.int32
