"""Self-healing durable journal: integrity scrubbing, read-repair,
anti-entropy replication, and disaster recovery (PR 20).

The journal (durable.py) has quietly become the system's backbone — the
fleet-wide result cache (PR 14), the streaming partial-aggregate state
store (PR 19's PINNED runs), the crash-resume substrate (PR 5/6) — yet
it was a single unreplicated filesystem root where corruption surfaced
only lazily, when `load_pass` happened to replay a bad spill.  This
module closes that gap with three cooperating mechanisms, all host-side
(no jax, no traced code — budget goldens untouched by construction):

- **scrubbing** (:func:`scrub_once`, :class:`Scrubber`) — a background
  walk over `durable.scan_runs` re-verifying every committed spill's
  sha256 and the manifest's structural integrity UNDER the shared
  walker lease (durable_lease — the same lease GC and
  tools/journal_fsck.py take, so destructive passes exclude each
  other).  Findings classify exactly three ways: *repairable* (a peer
  holds a good copy — fetched, verified, rewritten in place),
  *quarantined* (no good copy anywhere — the run is evicted
  manifest-LAST and simply re-executes), *torn* (the legal crash
  shapes: a torn manifest tail, an orphan spill dir from a sync killed
  mid-copy — clean by contract, reported not repaired).

- **read-repair** (:func:`attempt_read_repair`, called from
  `RunJournal.load_pass`) — a checksum failure on the serving path
  degrades to fetching the spill from a peer's journal over the
  checksum-verified blob verb, rewriting it locally tmp+fsync+rename,
  and serving bit-identically: never a failed (or re-executed) request
  while ANY replica holds a good copy.  The fetched bytes must match
  the LOCAL manifest's sha256 — a diverged peer is refused as loudly
  as a torn transfer (wire.blob_from_b64's two-digest contract).

- **anti-entropy replication** (:class:`JournalPeerServer`,
  :class:`JournalSyncer`, :func:`pull_run`) — each replica advertises
  per-run manifest digests on the EXISTING heartbeat telemetry
  (durable.journal_digests); the coordinator diffs them against
  ``CYLON_TPU_DURABLE_RF`` and hands under-replicated fingerprints
  back in heartbeat replies; the syncer pulls whole runs — every spill
  first (each verified against the peer manifest's sha256), the
  manifest LAST via atomic rename — so a sync killed at ANY point
  (fault kind ``sync_partial``) leaves no visible run, only an orphan
  spill dir the next pull overwrites.  PINNED stream-state runs sync
  at priority.  :func:`journal_restore` is the disaster-recovery
  composition: point it at peers and an EMPTY root rebuilds into a
  serving journal (cache hits, stream state and all).

Replication/repair never changes a fingerprint or a served byte: pulls
copy spills verbatim (digest-checked end to end) and repair only ever
installs bytes matching the local manifest's recorded sha256.  With
``CYLON_TPU_DURABLE_RF=1`` and the scrubber off, nothing here runs and
the journal behaves byte-identically to PR 19 (pinned by tests).
"""
from __future__ import annotations

import collections
import contextlib
import hashlib
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import durable
from . import durable_lease
from . import resilience
from .net import control
from .obs import fleet as obs_fleet
from .obs import metrics as obs_metrics
from .obs import spans as obs_spans
from .status import Code, CylonError

log = logging.getLogger("cylon_tpu")

#: per-file injection probe for the replication pull path: `sync_partial`
#: (os._exit mid-copy) armed here proves the manifest-LAST order makes a
#: half-pulled run invisible
SYNC_FAULT_SITE = "journal_sync_file"

#: verb timeout for peer journal fetches (data plane: whole spills)
_FETCH_TIMEOUT_S = 30.0


def _data_max_line() -> int:
    """Wire cap for one journal blob message — the router's data-plane
    cap (spills are the same frames the route verb carries)."""
    from .router.service import router_max_line

    return router_max_line()


def _wire():
    """Lazy wire-codec import: router/replica.py imports THIS module, so
    a module-level `from .router import wire` here would be a cycle."""
    from .router import wire

    return wire


# ---------------------------------------------------------------------------
# peer registry (read-repair's fetch targets)
# ---------------------------------------------------------------------------

_PEERS_LOCK = threading.Lock()
_PEERS: Tuple[Tuple[str, int], ...] = ()


def set_peers(addrs: Sequence[Sequence]) -> None:
    """Install the peer journal endpoints read-repair may fetch from
    (the syncer refreshes this from every heartbeat reply; () clears)."""
    global _PEERS
    cleaned = tuple((str(a[0]), int(a[1])) for a in addrs)
    with _PEERS_LOCK:
        _PEERS = cleaned


def peers() -> Tuple[Tuple[str, int], ...]:
    with _PEERS_LOCK:
        return _PEERS


# ---------------------------------------------------------------------------
# peer data-plane server (verbs over net/control.py framing)
# ---------------------------------------------------------------------------

def _safe_name(s) -> Optional[str]:
    """One path component, no traversal, no empties — the only names the
    peer verbs accept (fingerprints are hex, spill names are flat)."""
    s = str(s)
    if not s or s in (".", "..") or os.path.basename(s) != s:
        return None
    return s


class JournalPeerServer:
    """Read-only data-plane server over one journal root: peers (and the
    offline fsck's ``--repair-from``) fetch manifests and spill bytes by
    fingerprint.  Three verbs, one JSON line each (net/control framing,
    data-plane line cap):

    - ``journal_runs``                      -> per-run digest inventory
    - ``journal_manifest {fingerprint}``    -> manifest blob + file list
    - ``journal_fetch {fingerprint, file}`` -> one file's verified blob

    Read-ONLY by design: replication is pull-based (each replica owns
    its root's writes), so serving bytes can never corrupt the server's
    journal, and a malicious/confused peer can at worst read what the
    shared cache already shares."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        self.root = root
        self._server = control.JsonServer(self._handle, host=host,
                                          port=port,
                                          max_line=_data_max_line())
        self.address: Tuple[str, int] = self._server.address
        self._server.start()

    def close(self) -> None:
        self._server.close()

    # -- verb dispatch ----------------------------------------------------

    def _handle(self, req: Dict) -> Dict:
        wire = _wire()
        cmd = req.get("cmd")
        try:
            if cmd == "journal_runs":
                return {"ok": True,
                        "runs": durable.journal_digests(self.root)}
            if cmd == "journal_manifest":
                return self._manifest(req)
            if cmd == "journal_fetch":
                return self._fetch(req)
            raise CylonError(Code.Invalid,
                             f"unknown journal verb {cmd!r}")
        except CylonError as e:
            return {"ok": False, "error": wire.classified(e)}
        except OSError as e:
            return {"ok": False, "error": wire.classified(CylonError(
                Code.IOError, f"journal read failed: "
                              f"{type(e).__name__}: {e}"))}

    def _run_dir(self, req: Dict) -> str:
        fp = _safe_name(req.get("fingerprint"))
        if fp is None:
            raise CylonError(Code.Invalid,
                             f"bad fingerprint {req.get('fingerprint')!r}")
        d = os.path.join(self.root, fp)
        if not os.path.isdir(d):
            raise CylonError(Code.KeyError,
                             f"no journaled run {fp[:12]} on this peer")
        return d

    def _manifest(self, req: Dict) -> Dict:
        wire = _wire()
        d = self._run_dir(req)
        m = durable.read_manifest(d)
        if m is None:
            raise CylonError(Code.KeyError,
                             "run dir holds no readable manifest "
                             "(mid-sync orphan — not a run yet)")
        if m["midline_corrupt"]:
            # never replicate corruption: a manifest torn INSIDE its
            # committed history is this peer's problem, not a template
            raise CylonError(Code.IOError,
                             "manifest corrupt on this peer (mid-line); "
                             "refusing to serve it for replication")
        with open(os.path.join(d, durable.MANIFEST), "rb") as fh:
            raw = fh.read()
        files = [{"file": e["file"], "sha256": e["sha256"],
                  "bytes": int(e.get("bytes", 0))}
                 for e in m["passes"].values()]
        return {"ok": True, "manifest": wire.blob_b64(raw),
                "files": sorted(files, key=lambda f: f["file"]),
                "complete": m["done"] is not None,
                "pinned": os.path.exists(os.path.join(d, durable.PINNED))}

    def _fetch(self, req: Dict) -> Dict:
        wire = _wire()
        d = self._run_dir(req)
        name = _safe_name(req.get("file"))
        if name is None or name == durable_lease.GC_LOCK:
            raise CylonError(Code.Invalid, f"bad file {req.get('file')!r}")
        path = os.path.join(d, name)
        if not os.path.isfile(path):
            raise CylonError(Code.KeyError,
                             f"no spill {name!r} in run "
                             f"{req.get('fingerprint')!r:.14}")
        with open(path, "rb") as fh:
            data = fh.read()
        return {"ok": True, **wire.blob_b64(data)}


def _verb(addr, obj: Dict, timeout: float = _FETCH_TIMEOUT_S) -> Dict:
    """One peer-journal verb round trip; protocol-level failures re-raise
    classified."""
    wire = _wire()
    resp = control.request((str(addr[0]), int(addr[1])), obj,
                           timeout=timeout, retries=1,
                           max_line=_data_max_line())
    if not resp.get("ok"):
        err = resp.get("error")
        if isinstance(err, dict):
            raise wire.classified_error(err)
        raise CylonError(Code.Unavailable,
                         f"journal peer refused {obj.get('cmd')!r}: {err}")
    return resp


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + atomic rename — the journal's one write discipline,
    reused for every byte replication installs."""
    tmp = path + f".tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


# ---------------------------------------------------------------------------
# read-repair (the load_pass degradation path)
# ---------------------------------------------------------------------------

def fetch_spill(addr, fingerprint: str, file: str,
                expect_sha: Optional[str] = None) -> bytes:
    """One spill's bytes from a peer, digest-verified (transfer AND —
    when given — against the caller's own manifest expectation)."""
    resp = _verb(addr, {"cmd": "journal_fetch", "fingerprint": fingerprint,
                        "file": file})
    return _wire().blob_from_b64(resp, expect_sha=expect_sha)


def attempt_read_repair(run_dir: str, fingerprint: str, entry: Dict,
                        why: str) -> Optional[bytes]:
    """Heal one bad local spill from the first peer holding a good copy:
    fetch, verify against the LOCAL manifest's sha256, rewrite in place
    (tmp+fsync+rename), return the verified bytes for the caller to
    serve bit-identically.  None when no registered peer can help — the
    caller then drops the record and the pass re-executes (the pre-PR-20
    behavior).  Never raises: repair is an optimization."""
    targets = peers()
    if not targets:
        return None
    name, want = entry.get("file"), entry.get("sha256")
    obs_fleet.flight_record("journal.corruption", fingerprint=fingerprint,
                            file=name, why=why)
    with obs_spans.span("durable.read_repair", fingerprint=fingerprint[:12],
                        file=name):
        for addr in targets:
            try:
                data = fetch_spill(addr, fingerprint, name, expect_sha=want)
            except Exception as e:
                log.info("durable: read-repair fetch of %s/%s from %s "
                         "failed (%s: %s)", fingerprint[:12], name, addr,
                         type(e).__name__, e)
                continue
            try:
                _atomic_write(os.path.join(run_dir, name), data)
            except OSError as e:
                # the verified bytes still serve this request; only the
                # local heal failed (disk trouble — the scrubber retries)
                log.warning("durable: read-repair rewrite of %s failed "
                            "(%s: %s); serving fetched bytes unpersisted",
                            name, type(e).__name__, e)
            obs_metrics.counter_add("durable.read_repair")
            obs_spans.instant("durable.read_repair", file=name,
                              fingerprint=fingerprint[:12],
                              peer=f"{addr[0]}:{addr[1]}", why=why)
            log.warning("durable: read-repaired %s/%s from peer %s:%s (%s)",
                        fingerprint[:12], name, addr[0], addr[1], why)
            return data
    obs_metrics.counter_add("durable.read_repair_failed")
    return None


# ---------------------------------------------------------------------------
# anti-entropy pulls + disaster recovery
# ---------------------------------------------------------------------------

def pull_run(addr, root: str, fingerprint: str) -> bool:
    """Replicate one whole run from a peer into ``root``: every spill
    first (each digest-verified, atomically renamed), the ``PINNED``
    marker next when the peer pins it, the manifest LAST — so a pull
    killed at ANY point (``sync_partial``) leaves a manifest-less orphan
    dir that is not a run, serves nothing, and is simply overwritten by
    the next pull.  False when the run already exists locally (pulls
    never clobber a journal that has its own history).  Bytes land
    verbatim — the fingerprint, every spill and the manifest are
    bit-identical to the peer's by construction."""
    fp = _safe_name(fingerprint)
    if fp is None:
        raise CylonError(Code.Invalid, f"bad fingerprint {fingerprint!r}")
    dest = os.path.join(root, fp)
    if os.path.exists(os.path.join(dest, durable.MANIFEST)):
        return False
    with obs_spans.span("durable.sync_pull", fingerprint=fp[:12]):
        resp = _verb(addr, {"cmd": "journal_manifest", "fingerprint": fp})
        manifest_bytes = _wire().blob_from_b64(resp["manifest"])
        os.makedirs(dest, exist_ok=True)
        pulled_bytes = 0
        for f in resp.get("files", ()):
            resilience.fault_point(SYNC_FAULT_SITE)
            data = fetch_spill(addr, fp, f["file"], expect_sha=f["sha256"])
            _atomic_write(os.path.join(dest, str(f["file"])), data)
            pulled_bytes += len(data)
        if resp.get("pinned"):
            # pin BEFORE the manifest: the instant the run becomes
            # visible it is already exempt from LRU eviction
            _atomic_write(os.path.join(dest, durable.PINNED), b"{}\n")
        resilience.fault_point(SYNC_FAULT_SITE)
        _atomic_write(os.path.join(dest, durable.MANIFEST), manifest_bytes)
    obs_metrics.counter_add("durable.sync_runs_pulled")
    obs_metrics.counter_add("durable.sync_bytes_pulled",
                            pulled_bytes + len(manifest_bytes))
    log.info("durable: pulled run %s (%d bytes) from peer %s:%s",
             fp[:12], pulled_bytes, addr[0], addr[1])
    return True


def journal_restore(root: str, peer_addrs: Sequence[Sequence]) -> Dict:
    """Disaster recovery: rebuild ``root`` (typically empty — a lost
    disk, a fresh replica) from peer journals.  Pulls every complete or
    pinned run each peer advertises, pinned stream-state first; runs the
    root already holds are left untouched.  Composes with coordinator
    restart (PR 11): restore the root, start the replica, and the fleet
    cache serves hits again with ``plan_cache.miss == 0``."""
    os.makedirs(root, exist_ok=True)
    stats = {"pulled": 0, "bytes": 0, "skipped": 0, "failed": 0}
    for addr in peer_addrs:
        try:
            runs = _verb(addr, {"cmd": "journal_runs"}).get("runs", {})
        except Exception as e:
            log.warning("durable: restore cannot inventory peer %s "
                        "(%s: %s)", addr, type(e).__name__, e)
            stats["failed"] += 1
            continue
        order = sorted(runs.items(),
                       key=lambda kv: (not kv[1].get("pinned"),
                                       kv[0]))
        for fp, rec in order:
            if not (rec.get("complete") or rec.get("pinned")):
                continue
            try:
                if pull_run(addr, root, fp):
                    stats["pulled"] += 1
                    stats["bytes"] += int(rec.get("bytes", 0))
                else:
                    stats["skipped"] += 1
            except Exception as e:
                stats["failed"] += 1
                log.warning("durable: restore pull of %s from %s failed "
                            "(%s: %s)", fp[:12], addr,
                            type(e).__name__, e)
    obs_spans.instant("durable.restore", **stats)
    log.info("durable: journal_restore pulled %d run(s) into %r (%d "
             "skipped, %d failed)", stats["pulled"], root,
             stats["skipped"], stats["failed"])
    return stats


# ---------------------------------------------------------------------------
# the scrubber
# ---------------------------------------------------------------------------

def _verify_entry(run_dir: str, entry: Dict) -> Optional[str]:
    """None when the spill matches its manifest sha256, else a reason."""
    path = os.path.join(run_dir, str(entry.get("file")))
    h = hashlib.sha256()
    try:
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
    except OSError as e:
        return f"unreadable spill: {type(e).__name__}: {e}"
    if h.hexdigest() != entry.get("sha256"):
        return "checksum mismatch (bitrot/truncation)"
    return None


def scrub_once(root: Optional[str] = None, repair: bool = True) -> Dict:
    """One full integrity pass over the journal root, under the shared
    walker lease.  Re-verifies every committed spill's sha256 against
    its manifest and classifies every finding (module docstring); a
    busy lease skips the round cleanly (``skipped_busy`` — the GC or a
    peer's scrub is walking; corruption waits one interval).

    Classification per run:

    - manifest-less dir         -> ``orphans`` (a sync killed mid-copy;
      clean by contract, the next pull overwrites it)
    - torn manifest tail        -> ``torn`` (legal crash shape; entries
      before the tear are verified like any others)
    - mid-line manifest damage / foreign-fingerprint header
                                -> quarantine (committed history is
      untrustworthy; manifest-LAST eviction, the run re-executes)
    - bad spill, peer good copy -> repaired in place (bit-identical)
    - bad spill, no good copy   -> quarantine; for PINNED runs the run
      is left standing (never evict live stream state — the corrupt
      pass re-executes via load-time rejection) but counted corrupt

    Quarantine honors the PR-16 victim discipline: the manifest mtime
    is re-read UNDER the lease and a freshened run is skipped this
    round (a live reader/writer is on it)."""
    root = durable.durable_dir() if root is None else root
    stats = {"runs": 0, "checked": 0, "corrupt": 0, "repaired": 0,
             "quarantined": 0, "torn": 0, "orphans": 0,
             "skipped_busy": 0, "skipped_live": 0, "skipped_fresh": 0}
    if not root or not os.path.isdir(root):
        return stats
    lease = durable_lease.acquire_lease(
        root, on_busy=lambda: obs_metrics.counter_add(
            "durable.scrub_lease_busy"))
    if lease is None:
        stats["skipped_busy"] = 1
        return stats
    try:
        live = (durable._LAST_JOURNAL.dir
                if durable._LAST_JOURNAL is not None else None)
        for r in durable.scan_runs(root):
            if r["dir"] == live:
                # never scrub under our own writer: its uncommitted
                # tail looks exactly like damage
                stats["skipped_live"] += 1
                continue
            stats["runs"] += 1
            obs_metrics.counter_add("durable.scrub_runs")
            m = durable.read_manifest(r["dir"])
            if m is None:
                stats["orphans"] += 1
                continue
            header_fp = (m["header"] or {}).get("fingerprint")
            structural = None
            if m["midline_corrupt"]:
                structural = "manifest corrupt mid-line"
            elif m["header"] is not None \
                    and header_fp != r["fingerprint"]:
                structural = (f"manifest records foreign fingerprint "
                              f"{str(header_fp)[:12]!r}")
            if m["torn_tail"]:
                stats["torn"] += 1
            bad_entries = []
            if structural is None:
                for key in sorted(m["passes"]):
                    entry = m["passes"][key]
                    stats["checked"] += 1
                    why = _verify_entry(r["dir"], entry)
                    if why is not None:
                        bad_entries.append((entry, why))
            if structural is None and not bad_entries:
                continue
            stats["corrupt"] += 1
            obs_metrics.counter_add("durable.scrub_corrupt")
            obs_fleet.flight_record(
                "journal.scrub_corruption", fingerprint=r["fingerprint"],
                structural=structural,
                bad=[{"file": e.get("file"), "why": w}
                     for e, w in bad_entries[:8]])
            healed = 0
            if repair and structural is None and peers():
                for entry, why in bad_entries:
                    data = attempt_read_repair(
                        r["dir"], r["fingerprint"], entry,
                        f"scrub: {why}")
                    if data is not None:
                        healed += 1
            if structural is None and healed == len(bad_entries):
                stats["repaired"] += 1
                obs_metrics.counter_add("durable.scrub_repaired")
                continue
            # unrepairable -> quarantine (PINNED runs stand: live stream
            # state is never evicted; its bad passes re-execute at load)
            if os.path.exists(os.path.join(r["dir"], durable.PINNED)):
                log.warning("durable: scrub found unrepairable damage in "
                            "PINNED run %s (%s); leaving it for load-time "
                            "re-execution", r["fingerprint"][:12],
                            structural or f"{len(bad_entries)} bad spills")
                continue
            manifest = os.path.join(r["dir"], durable.MANIFEST)
            try:
                now_mtime = os.path.getmtime(manifest)
            except OSError:
                now_mtime = None
            if now_mtime is not None and now_mtime > r["mtime"] + 1e-6:
                # freshened since the scan: someone is replaying it;
                # their loads reject bad spills themselves — next round
                stats["skipped_fresh"] += 1
                continue
            durable._evict_run_dir(r["dir"])
            stats["quarantined"] += 1
            obs_metrics.counter_add("durable.scrub_quarantined")
            obs_spans.instant("durable.scrub_quarantine",
                              fingerprint=r["fingerprint"],
                              reason=structural
                              or f"{len(bad_entries)} unrepairable "
                                 f"spill(s)")
            log.warning("durable: scrub quarantined run %s (%s); it will "
                        "re-execute", r["fingerprint"][:12],
                        structural or f"{len(bad_entries)} bad spill(s)")
    finally:
        durable_lease.release_lease(lease)
    return stats


class Scrubber:
    """Background scrub thread: one :func:`scrub_once` every
    ``CYLON_TPU_SCRUB_S`` seconds (constructor override for tests).
    Guarded — a scrub failure is logged and the cadence continues; the
    scrubber must never take down the replica it protects."""

    def __init__(self, root: Optional[str] = None,
                 interval_s: Optional[float] = None):
        self.root = durable.durable_dir() if root is None else root
        self.interval_s = (durable.scrub_interval_s()
                           if interval_s is None else float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cylon-journal-scrub")

    def start(self) -> "Scrubber":
        if self.interval_s > 0:
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                scrub_once(self.root)
            except Exception as e:  # pragma: no cover - defensive
                log.warning("durable: scrub round failed (%s: %s)",
                            type(e).__name__, e)


# ---------------------------------------------------------------------------
# the per-replica syncer (heartbeat-driven)
# ---------------------------------------------------------------------------

class JournalSyncer:
    """Consumes the coordinator's journal fields from heartbeat replies
    (`Agent.attach_journal_sync`) and turns them into local state:

    - ``journal_peers``  -> the read-repair peer registry (set_peers)
    - ``journal_guard``  -> the GC replication guard (fingerprints whose
      local copy the coordinator still counts toward RF — `gc_journal`
      skips them, ``durable.gc_skipped_replication``)
    - ``journal_sync``   -> pull hints, executed on a dedicated worker
      thread (NEVER on the heartbeat thread — a slow pull must not
      starve the liveness signal), pinned stream-state first.
    """

    def __init__(self, root: Optional[str] = None):
        self.root = durable.durable_dir() if root is None else root
        self.root_id = os.path.realpath(self.root) if self.root else ""
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: "collections.OrderedDict[str, Tuple[bool, Tuple[str, int]]]" = \
            collections.OrderedDict()
        self._guard: frozenset = frozenset()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cylon-journal-sync")
        self._thread.start()
        durable.set_gc_replication_guard(self._guarded)

    def _guarded(self, fingerprint: str) -> bool:
        return fingerprint in self._guard

    # -- heartbeat callback (runs on the agent's beat thread: cheap) ------

    def on_heartbeat(self, doc: Dict) -> None:
        peers_map = doc.get("journal_peers")
        if isinstance(peers_map, dict):
            set_peers([a for a in peers_map.values()
                       if isinstance(a, (list, tuple)) and len(a) == 2])
        guard = doc.get("journal_guard")
        if isinstance(guard, (list, tuple)):
            self._guard = frozenset(str(f) for f in guard)
        hints = doc.get("journal_sync")
        if not isinstance(hints, (list, tuple)) or not hints:
            return
        with self._cond:
            for h in hints:
                try:
                    fp = str(h["fingerprint"])
                    addr = (str(h["from"][0]), int(h["from"][1]))
                    pinned = bool(h.get("pinned"))
                except (KeyError, IndexError, TypeError, ValueError):
                    continue
                if fp not in self._queue:
                    self._queue[fp] = (pinned, addr)
                    if pinned:
                        self._queue.move_to_end(fp, last=False)
            self._cond.notify()

    # -- worker -----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(timeout=0.5)
                if self._stop:
                    return
                fp, (pinned, addr) = self._queue.popitem(last=False)
            try:
                pull_run(addr, self.root, fp)
            except Exception as e:
                log.info("durable: anti-entropy pull of %s from %s failed "
                         "(%s: %s); the coordinator will re-hint",
                         fp[:12], addr, type(e).__name__, e)

    def telemetry(self) -> Dict:
        """The per-beat journal advertisement riding replica telemetry:
        this root's identity and per-run digests (manifest-only — no
        spill reads on the heartbeat path)."""
        return {"root": self.root_id,
                "digests": durable.journal_digests(self.root)}

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=2.0)
        durable.set_gc_replication_guard(None)
        set_peers(())
