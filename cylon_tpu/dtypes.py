"""Data type system.

TPU-native analog of the reference's stripped-down Arrow type system
(reference: cpp/src/cylon/data_types.hpp:25-120 and
cpp/src/cylon/arrow/arrow_types.{hpp,cpp}).  The reference wraps an enum
``Type::type`` plus conversion to/from arrow types and a schema validity
check; we do the same, mapping to JAX/numpy dtypes as the device
representation:

- fixed-width numerics / bools / temporal types -> the matching jnp dtype
  (temporal values travel as int64 on device, like Arrow's physical layout)
- STRING / BINARY -> fixed-width padded ``uint8[capacity, width]`` byte
  matrices plus an int32 length vector (TPU kernels need static shapes; this
  replaces Arrow's offsets+bytes representation on device, and round-trips
  through offsets+bytes at the host boundary).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = [
    "Type", "Layout", "DataType",
    "bool_", "uint8", "int8", "uint16", "int16", "uint32", "int32",
    "uint64", "int64", "half_float", "float_", "double",
    "string", "binary", "fixed_size_binary", "date32", "date64",
    "timestamp", "time32", "time64",
    "from_numpy_dtype", "to_numpy_dtype", "from_arrow_type", "to_arrow_type",
    "is_numeric", "is_string_like", "is_floating", "is_integer",
]


class Type(enum.IntEnum):
    """Logical types (reference: cpp/src/cylon/data_types.hpp:25-86)."""

    BOOL = 0
    UINT8 = 1
    INT8 = 2
    UINT16 = 3
    INT16 = 4
    UINT32 = 5
    INT32 = 6
    UINT64 = 7
    INT64 = 8
    HALF_FLOAT = 9
    FLOAT = 10
    DOUBLE = 11
    STRING = 12
    BINARY = 13
    FIXED_SIZE_BINARY = 14
    DATE32 = 15
    DATE64 = 16
    TIMESTAMP = 17
    TIME32 = 18
    TIME64 = 19
    DECIMAL = 20
    DURATION = 21
    INTERVAL = 22
    LIST = 23
    FIXED_SIZE_LIST = 24
    EXTENSION = 25
    MAX_ID = 26


class Layout(enum.IntEnum):
    """Physical layout (reference: data_types.hpp Layout)."""

    FIXED_WIDTH = 1
    VARIABLE_WIDTH = 2


_NUMPY_OF = {
    Type.BOOL: np.bool_,
    Type.UINT8: np.uint8,
    Type.INT8: np.int8,
    Type.UINT16: np.uint16,
    Type.INT16: np.int16,
    Type.UINT32: np.uint32,
    Type.INT32: np.int32,
    Type.UINT64: np.uint64,
    Type.INT64: np.int64,
    Type.HALF_FLOAT: np.float16,
    Type.FLOAT: np.float32,
    Type.DOUBLE: np.float64,
    # device representation of byte-strings is uint8 matrices
    Type.STRING: np.uint8,
    Type.BINARY: np.uint8,
    Type.FIXED_SIZE_BINARY: np.uint8,
    # temporal types travel as their Arrow physical integer widths
    Type.DATE32: np.int32,
    Type.DATE64: np.int64,
    Type.TIMESTAMP: np.int64,
    Type.TIME32: np.int32,
    Type.TIME64: np.int64,
    Type.DURATION: np.int64,
}

_TYPE_OF_NUMPY = {
    np.dtype(np.bool_): Type.BOOL,
    np.dtype(np.uint8): Type.UINT8,
    np.dtype(np.int8): Type.INT8,
    np.dtype(np.uint16): Type.UINT16,
    np.dtype(np.int16): Type.INT16,
    np.dtype(np.uint32): Type.UINT32,
    np.dtype(np.int32): Type.INT32,
    np.dtype(np.uint64): Type.UINT64,
    np.dtype(np.int64): Type.INT64,
    np.dtype(np.float16): Type.HALF_FLOAT,
    np.dtype(np.float32): Type.FLOAT,
    np.dtype(np.float64): Type.DOUBLE,
}


@dataclass(frozen=True)
class DataType:
    """A logical column type (reference: data_types.hpp DataType).

    ``byte_width`` is only meaningful for FIXED_SIZE_BINARY; ``unit`` for
    temporal types (one of 's','ms','us','ns').
    """

    type: Type
    byte_width: int = -1
    unit: Optional[str] = None

    @property
    def layout(self) -> Layout:
        if self.type in (Type.STRING, Type.BINARY):
            return Layout.VARIABLE_WIDTH
        return Layout.FIXED_WIDTH

    def numpy_dtype(self) -> np.dtype:
        try:
            return np.dtype(_NUMPY_OF[self.type])
        except KeyError:
            raise TypeError(f"type {self.type.name} has no device representation")

    def __repr__(self) -> str:
        if self.type == Type.FIXED_SIZE_BINARY:
            return f"fixed_size_binary[{self.byte_width}]"
        if self.unit:
            return f"{self.type.name.lower()}[{self.unit}]"
        return self.type.name.lower()


def _mk(t: Type) -> DataType:
    return DataType(t)


bool_ = _mk(Type.BOOL)
uint8 = _mk(Type.UINT8)
int8 = _mk(Type.INT8)
uint16 = _mk(Type.UINT16)
int16 = _mk(Type.INT16)
uint32 = _mk(Type.UINT32)
int32 = _mk(Type.INT32)
uint64 = _mk(Type.UINT64)
int64 = _mk(Type.INT64)
half_float = _mk(Type.HALF_FLOAT)
float_ = _mk(Type.FLOAT)
double = _mk(Type.DOUBLE)
string = _mk(Type.STRING)
binary = _mk(Type.BINARY)
date32 = _mk(Type.DATE32)
date64 = _mk(Type.DATE64)


def fixed_size_binary(width: int) -> DataType:
    return DataType(Type.FIXED_SIZE_BINARY, byte_width=width)


def timestamp(unit: str = "us") -> DataType:
    return DataType(Type.TIMESTAMP, unit=unit)


def time32(unit: str = "ms") -> DataType:
    return DataType(Type.TIME32, unit=unit)


def time64(unit: str = "us") -> DataType:
    return DataType(Type.TIME64, unit=unit)


def join_key_mismatch(a_is_string: bool, b_is_string: bool, same_type: bool,
                      either_empty: bool):
    """Shared join-key compatibility policy (used by both the Table API
    and the out-of-core engine so the two rungs can never drift):
    returns "structural" (string vs non-string — buffers aren't even the
    same rank, always fatal), "mismatch" (differing non-string types on
    non-empty sides: concat promotion silently corrupts the packed sort
    operands), or None (compatible; an empty side's inferred dtype is
    vacuous because output values gather from the original typed
    buffers)."""
    if a_is_string != b_is_string:
        return "structural"
    if not a_is_string and not same_type and not either_empty:
        return "mismatch"
    return None


def is_numeric(dt: DataType) -> bool:
    return Type.BOOL <= dt.type <= Type.DOUBLE


def is_string_like(dt: DataType) -> bool:
    return dt.type in (Type.STRING, Type.BINARY, Type.FIXED_SIZE_BINARY)


def is_floating(dt: DataType) -> bool:
    return dt.type in (Type.HALF_FLOAT, Type.FLOAT, Type.DOUBLE)


def is_integer(dt: DataType) -> bool:
    return Type.UINT8 <= dt.type <= Type.INT64


def from_numpy_dtype(dtype) -> DataType:
    dtype = np.dtype(dtype)
    if dtype.kind in ("U", "S", "O"):
        return string
    if dtype.kind == "M":
        return timestamp("us")
    try:
        return DataType(_TYPE_OF_NUMPY[dtype])
    except KeyError:
        raise TypeError(f"unsupported numpy dtype {dtype}")


def to_numpy_dtype(dt: DataType) -> np.dtype:
    return dt.numpy_dtype()


# ---------------------------------------------------------------------------
# Arrow interop (reference: cpp/src/cylon/arrow/arrow_types.cpp ToCylonType /
# convertToArrowType).  pyarrow is imported lazily so the device-side library
# has no hard host-IO dependency.
# ---------------------------------------------------------------------------

def from_arrow_type(at) -> DataType:
    import pyarrow as pa

    if pa.types.is_boolean(at):
        return bool_
    if pa.types.is_uint8(at):
        return uint8
    if pa.types.is_int8(at):
        return int8
    if pa.types.is_uint16(at):
        return uint16
    if pa.types.is_int16(at):
        return int16
    if pa.types.is_uint32(at):
        return uint32
    if pa.types.is_int32(at):
        return int32
    if pa.types.is_uint64(at):
        return uint64
    if pa.types.is_int64(at):
        return int64
    if pa.types.is_float16(at):
        return half_float
    if pa.types.is_float32(at):
        return float_
    if pa.types.is_float64(at):
        return double
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return string
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return binary
    if pa.types.is_fixed_size_binary(at):
        return fixed_size_binary(at.byte_width)
    if pa.types.is_date32(at):
        return date32
    if pa.types.is_date64(at):
        return date64
    if pa.types.is_timestamp(at):
        return timestamp(at.unit)
    if pa.types.is_time32(at):
        return time32(at.unit)
    if pa.types.is_time64(at):
        return time64(at.unit)
    raise TypeError(f"unsupported arrow type {at}")


def to_arrow_type(dt: DataType):
    import pyarrow as pa

    m = {
        Type.BOOL: pa.bool_(),
        Type.UINT8: pa.uint8(),
        Type.INT8: pa.int8(),
        Type.UINT16: pa.uint16(),
        Type.INT16: pa.int16(),
        Type.UINT32: pa.uint32(),
        Type.INT32: pa.int32(),
        Type.UINT64: pa.uint64(),
        Type.INT64: pa.int64(),
        Type.HALF_FLOAT: pa.float16(),
        Type.FLOAT: pa.float32(),
        Type.DOUBLE: pa.float64(),
        Type.STRING: pa.string(),
        Type.BINARY: pa.binary(),
        Type.DATE32: pa.date32(),
        Type.DATE64: pa.date64(),
    }
    if dt.type in m:
        return m[dt.type]
    if dt.type == Type.FIXED_SIZE_BINARY:
        return pa.binary(dt.byte_width)
    if dt.type == Type.TIMESTAMP:
        return pa.timestamp(dt.unit or "us")
    if dt.type == Type.TIME32:
        return pa.time32(dt.unit or "ms")
    if dt.type == Type.TIME64:
        return pa.time64(dt.unit or "us")
    raise TypeError(f"unsupported type {dt}")
